#include "netem.hpp"

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <thread>

#include "log.hpp"

namespace pcclt::net::netem {

namespace {

uint64_t mono_ns() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
           static_cast<uint64_t>(ts.tv_nsec);
}

uint64_t splitmix64(uint64_t &s) {
    uint64_t z = (s += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
}

// strip leading/trailing spaces (map values often come from shell strings)
std::string trim(const std::string &s) {
    size_t a = s.find_first_not_of(" \t");
    if (a == std::string::npos) return "";
    size_t b = s.find_last_not_of(" \t");
    return s.substr(a, b - a + 1);
}

}  // namespace

// ---------- Edge ----------

void Edge::configure(const EdgeParams &p) {
    ns_per_byte_.store(p.mbps > 0 ? 8000.0 / p.mbps : 0.0,
                       std::memory_order_relaxed);
    owd_ns_.store(p.rtt_ms > 0 ? static_cast<uint64_t>(p.rtt_ms * 0.5e6) : 0,
                  std::memory_order_relaxed);
    jitter_ns_.store(
        p.jitter_ms > 0 ? static_cast<uint64_t>(p.jitter_ms * 1e6) : 0,
        std::memory_order_relaxed);
    drop_.store(p.drop > 0 ? std::min(p.drop, 1.0) : 0.0,
                std::memory_order_relaxed);
}

EdgeParams Edge::params() const {
    EdgeParams p;
    double npb = ns_per_byte_.load(std::memory_order_relaxed);
    p.mbps = npb > 0 ? 8000.0 / npb : 0.0;
    p.rtt_ms = static_cast<double>(owd_ns_.load(std::memory_order_relaxed)) /
               0.5e6;
    p.jitter_ms =
        static_cast<double>(jitter_ns_.load(std::memory_order_relaxed)) / 1e6;
    p.drop = drop_.load(std::memory_order_relaxed);
    return p;
}

void Edge::pace(size_t bytes) {
    double npb = ns_per_byte_.load(std::memory_order_relaxed);
    if (npb <= 0) return;
    uint64_t end;
    {
        MutexLock lk(mu_);
        uint64_t now = mono_ns();
        // reserve the transmission slot [start, end) and sleep until the
        // frame has fully drained — a sender cannot complete a send faster
        // than the wire carries it (no burst credit: next never lags now)
        uint64_t start = std::max(next_ns_, now);
        end = start + static_cast<uint64_t>(static_cast<double>(bytes) * npb);
        next_ns_ = end;
    }
    // small frames (ctl, quant metadata) charge the bucket but may run a
    // bounded window ahead of the wire: a real qdisc interleaves a sub-MTU
    // packet ~one chunk behind the current queue, not the full depth. The
    // bound matters — traffic composed ENTIRELY of small frames must still
    // be throttled, so beyond the window small frames pace like the rest.
    if (bytes <= 4096) {
        constexpr uint64_t kAheadNs = 40'000'000;  // ~2 chunk-times @ 100 Mbit
        if (end <= mono_ns() + kAheadNs) return;
        end -= kAheadNs;
    }
    for (uint64_t now = mono_ns(); now < end; now = mono_ns()) {
        uint64_t gap = end - now;
        struct timespec ts{static_cast<time_t>(gap / 1000000000ull),
                           static_cast<long>(gap % 1000000000ull)};
        nanosleep(&ts, nullptr);
    }
}

uint64_t Edge::delivery_delay_ns() {
    uint64_t d = owd_ns_.load(std::memory_order_relaxed);
    uint64_t jit = jitter_ns_.load(std::memory_order_relaxed);
    double drop = drop_.load(std::memory_order_relaxed);
    if (jit == 0 && drop <= 0) return d;
    MutexLock lk(mu_);
    if (jit > 0) d += splitmix64(rng_) % jit;
    if (drop > 0 &&
        static_cast<double>(splitmix64(rng_) >> 11) * 0x1.0p-53 < drop) {
        // TCP never loses a frame; a "dropped" one arrives an RTO late
        uint64_t rto = std::max<uint64_t>(
            2 * owd_ns_.load(std::memory_order_relaxed), 200'000'000ull);
        d += rto;
    }
    return d;
}

// ---------- DelayLine ----------

DelayLine &DelayLine::inst() {
    // intentionally leaked: the detached timer thread blocks on mu_/cv_
    // forever, so a static-destruction teardown would be UB at exit
    static DelayLine *d = new DelayLine;
    return *d;
}

void DelayLine::deliver(uint64_t delay_ns, std::function<void()> fn) {
    uint64_t at = mono_ns() + delay_ns;
    {
        MutexLock lk(mu_);
        q_.emplace(at, std::move(fn));
        if (!running_) {
            running_ = true;
            std::thread([this] { timer_loop(); }).detach();
        }
    }
    cv_.notify_one();
}

void DelayLine::timer_loop() {
    while (true) {
        std::function<void()> fn;
        {
            MutexLock lk(mu_);
            if (q_.empty()) {
                cv_.wait_for(mu_, std::chrono::seconds(1));
                continue;
            }
            uint64_t at = q_.begin()->first;
            uint64_t now = mono_ns();
            if (now < at) {
                cv_.wait_for(mu_, std::chrono::nanoseconds(at - now));
                continue;
            }
            fn = std::move(q_.begin()->second);
            q_.erase(q_.begin());
        }
        fn();
    }
}

// ---------- map parsing ----------

std::map<std::string, double> parse_map(const char *spec, const char *name) {
    std::map<std::string, double> out;
    if (!spec) return out;
    std::string s(spec);
    size_t pos = 0;
    while (pos <= s.size()) {
        size_t comma = s.find(',', pos);
        std::string entry =
            trim(s.substr(pos, comma == std::string::npos ? std::string::npos
                                                          : comma - pos));
        pos = comma == std::string::npos ? s.size() + 1 : comma + 1;
        if (entry.empty()) continue;
        // split on the LAST '=': v6 keys like [::1]:7000 contain no '=',
        // but being defensive costs nothing
        size_t eq = entry.rfind('=');
        if (eq == std::string::npos || eq == 0 || eq + 1 >= entry.size()) {
            PLOG(kWarn) << name << ": skipping malformed entry '" << entry
                        << "' (want key=value)";
            continue;
        }
        std::string key = trim(entry.substr(0, eq));
        std::string val = trim(entry.substr(eq + 1));
        char *endp = nullptr;
        double v = strtod(val.c_str(), &endp);
        if (key.empty() || !endp || *endp != '\0' || !(v >= 0) ||
            !std::isfinite(v)) {
            PLOG(kWarn) << name << ": skipping malformed entry '" << entry
                        << "' (bad key or value)";
            continue;
        }
        out[key] = v;
    }
    return out;
}

// ---------- Registry ----------

Registry &Registry::inst() {
    static Registry *r = new Registry;  // leaked: edges outlive any conn
    return *r;
}

namespace {
double env_f(const char *name) {
    if (const char *e = std::getenv(name)) {
        double v = atof(e);
        if (v > 0) return v;
    }
    return 0;
}
}  // namespace

void Registry::refresh() {
    MutexLock lk(mu_);
    mbps_ = parse_map(std::getenv("PCCLT_WIRE_MBPS_MAP"),
                      "PCCLT_WIRE_MBPS_MAP");
    rtt_ = parse_map(std::getenv("PCCLT_WIRE_RTT_MS_MAP"),
                     "PCCLT_WIRE_RTT_MS_MAP");
    jitter_ = parse_map(std::getenv("PCCLT_WIRE_JITTER_MS_MAP"),
                        "PCCLT_WIRE_JITTER_MS_MAP");
    drop_ = parse_map(std::getenv("PCCLT_WIRE_DROP_MAP"),
                      "PCCLT_WIRE_DROP_MAP");
    global_.mbps = env_f("PCCLT_WIRE_MBPS");
    global_.rtt_ms = env_f("PCCLT_WIRE_RTT_MS");
    global_.jitter_ms = 0;
    global_.drop = 0;
    if (!default_) default_ = std::make_shared<Edge>();
    default_->configure(global_);
    // retune live edges in place: conns keep their shared_ptr (and their
    // shared bucket) across refreshes; keys that dropped out of the maps
    // fall back to the current global defaults field by field
    for (auto &[key, e] : edges_)
        e.edge->configure(params_for(e.exact_key, e.ip_key));
}

EdgeParams Registry::params_for(const std::string &exact_key,
                                const std::string &ip_key) const {
    auto field = [&](const std::map<std::string, double> &m,
                     double global) -> double {
        auto it = m.find(exact_key);
        if (it != m.end()) return it->second;
        it = m.find(ip_key);
        if (it != m.end()) return it->second;
        return global;
    };
    EdgeParams p;
    p.mbps = field(mbps_, global_.mbps);
    p.rtt_ms = field(rtt_, global_.rtt_ms);
    p.jitter_ms = field(jitter_, global_.jitter_ms);
    p.drop = field(drop_, global_.drop);
    return p;
}

std::shared_ptr<Edge> Registry::resolve(const Addr &peer) {
    std::string exact = peer.str();
    // bare-ip wildcard key: Addr::str() is "a.b.c.d:port" / "[v6]:port"
    std::string ip = exact.substr(0, exact.rfind(':'));
    MutexLock lk(mu_);
    // written out per key (not a helper lambda): a lambda body does not
    // inherit the caller's lock set under -Wthread-safety
    std::string match;
    if (mbps_.count(exact) || rtt_.count(exact) || jitter_.count(exact) ||
        drop_.count(exact)) {
        match = exact;  // per-endpoint bucket
    } else if (mbps_.count(ip) || rtt_.count(ip) || jitter_.count(ip) ||
               drop_.count(ip)) {
        match = ip;  // per-host bucket, shared by every port on that ip
    } else {
        return default_;  // globals: the one process-wide bucket (legacy)
    }
    auto it = edges_.find(match);
    if (it == edges_.end()) {
        Entry e;
        // wildcard-matched edges key their refresh lookups by the ip too:
        // the bucket is shared host-wide, so one endpoint's later exact
        // entry must not retune it
        e.exact_key = match == ip ? ip : exact;
        e.ip_key = ip;
        e.edge = std::make_shared<Edge>(params_for(e.exact_key, ip));
        it = edges_.emplace(match, std::move(e)).first;
    }
    return it->second.edge;
}

std::shared_ptr<Edge> Registry::default_edge() {
    MutexLock lk(mu_);
    return default_;
}

}  // namespace pcclt::net::netem
