#include "hash.hpp"

#include <array>
#include <vector>
#include <bit>
#include <cstdlib>
#include <cstring>
#include <string_view>

#include "log.hpp"

namespace pcclt::hash {

// hardware CRC (hash_clmul.cpp, its own -mpclmul TU; runtime-gated)
namespace clmul {
bool available();
uint32_t crc32(const void *data, size_t nbytes, uint32_t crc);
} // namespace clmul

uint64_t avalanche64(uint64_t x) {
    x ^= x >> 33;
    x *= 0xFF51AFD7ED558CCDull;
    x ^= x >> 33;
    x *= 0xC4CEB9FE1A85EC53ull;
    x ^= x >> 33;
    return x;
}

uint64_t simplehash(const void *data, size_t nbytes) {
    const auto *bytes = static_cast<const uint8_t *>(data);
    const size_t nwords = (nbytes + 3) / 4;

    std::array<uint64_t, kLanes> lane;
    lane.fill(kSeed);

    // words are DEFINED as little-endian (the Python twin uses "<u4");
    // byteswap on big-endian hosts so digests stay device-independent
    auto le_word = [](uint32_t w) {
        if constexpr (std::endian::native == std::endian::big)
            w = __builtin_bswap32(w);
        return w;
    };
    size_t full_words = nbytes / 4;
    for (size_t i = 0; i < full_words; ++i) {
        uint32_t w;
        memcpy(&w, bytes + i * 4, 4);
        size_t l = i % kLanes;
        lane[l] = lane[l] * kP + le_word(w);
    }
    if (full_words != nwords) { // zero-padded tail word
        uint32_t w = 0;
        memcpy(&w, bytes + full_words * 4, nbytes - full_words * 4);
        size_t l = full_words % kLanes;
        lane[l] = lane[l] * kP + le_word(w);
    }

    uint64_t acc = kSeed ^ (static_cast<uint64_t>(nbytes) * kQ);
    for (size_t l = 0; l < kLanes; ++l) acc = acc * kQ + lane[l];
    return avalanche64(acc);
}

namespace {

// slice-by-8 CRC32 tables, generated at first use
struct Crc32Tables {
    uint32_t t[8][256];
    Crc32Tables() {
        for (uint32_t i = 0; i < 256; ++i) {
            uint32_t c = i;
            for (int k = 0; k < 8; ++k) c = (c >> 1) ^ (0xEDB88320u & (~(c & 1) + 1));
            t[0][i] = c;
        }
        for (uint32_t i = 0; i < 256; ++i)
            for (int s = 1; s < 8; ++s)
                t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
    }
};

} // namespace

uint64_t simplehash_tpu(const void *data, size_t nbytes) {
    const auto *bytes = static_cast<const uint8_t *>(data);
    const size_t nwords = (nbytes + 3) / 4;
    const size_t full_rows = nwords / kTpuLanes;
    const size_t tail = nwords - full_rows * kTpuLanes;

    auto le_word = [](uint32_t w) {
        if constexpr (std::endian::native == std::endian::big)
            w = __builtin_bswap32(w);
        return w;
    };
    std::vector<uint32_t> la(kTpuLanes, kTpuSA), lb(kTpuLanes, kTpuSB);
    auto word_at = [&](size_t i) {
        uint32_t w = 0;
        size_t b = i * 4;
        memcpy(&w, bytes + b, b + 4 <= nbytes ? 4 : nbytes - b);
        return le_word(w);
    };
    for (size_t r = 0; r < full_rows; ++r) {
        const size_t base = r * kTpuLanes;
        // tail-safe: every word of a full row is 4 in-bounds bytes
        for (size_t l = 0; l < kTpuLanes; ++l) {
            uint32_t w;
            memcpy(&w, bytes + (base + l) * 4, 4);
            w = le_word(w);
            la[l] = la[l] * kTpuPA + w;
            lb[l] = lb[l] * kTpuPB + w;
        }
    }
    if (tail) {
        // the definition pads the last row to a FULL row of the lane grid
        // (the jax twin reshapes to [rows, 65536]); lanes >= tail fold a
        // zero word, i.e. just advance their Horner chains
        const size_t base = full_rows * kTpuLanes;
        for (size_t l = 0; l < tail; ++l) {
            uint32_t w = word_at(base + l);
            la[l] = la[l] * kTpuPA + w;
            lb[l] = lb[l] * kTpuPB + w;
        }
        for (size_t l = tail; l < kTpuLanes; ++l) {
            la[l] = la[l] * kTpuPA;
            lb[l] = lb[l] * kTpuPB;
        }
    }
    // murmur3-step fold: the combiner must be non-linear with rotations —
    // a linear fold of IDENTICAL halves (uniform content, e.g. zero-init
    // params) cancels structurally and made every constant array hash the
    // same (see ops/hashing.py:_mix2 for the derivation)
    auto rotl = [](uint32_t x, int r) {
        return (x << r) | (x >> (32 - r));
    };
    auto mix2 = [&](uint32_t h, uint32_t k) {
        k = rotl(k * 0xCC9E2D51u, 15) * 0x1B873593u;
        return rotl(h ^ k, 13) * 5u + 0xE6546B64u;
    };
    for (size_t half = kTpuLanes / 2; half >= 1; half /= 2) {
        for (size_t l = 0; l < half; ++l) {
            la[l] = mix2(la[l], la[l + half]);
            lb[l] = mix2(lb[l], lb[l + half]);
        }
        if (half == 1) break;
    }
    uint64_t d = (static_cast<uint64_t>(la[0]) << 32) | lb[0];
    return avalanche64(d ^ (static_cast<uint64_t>(nbytes) * kQ));
}

uint64_t content_hash(Type t, const void *data, size_t nbytes) {
    switch (t) {
    case Type::kCrc32: return crc32(data, nbytes);
    case Type::kSimpleTpu: return simplehash_tpu(data, nbytes);
    case Type::kSimple: break;
    }
    return simplehash(data, nbytes);
}

Type type_from_env() {
    const char *v = std::getenv("PCCLT_SS_HASH");
    if (!v || std::string_view(v) == "simple") return Type::kSimple;
    if (std::string_view(v) == "crc32") return Type::kCrc32;
    if (std::string_view(v) == "simple-tpu") return Type::kSimpleTpu;
    PLOG(kWarn) << "unknown PCCLT_SS_HASH value \"" << v
                << "\" (expected \"simple\", \"crc32\" or \"simple-tpu\"); "
                   "using simplehash";
    return Type::kSimple;
}

uint32_t crc32(const void *data, size_t nbytes, uint32_t crc) {
    // hardware path: PCLMUL folding (hash_clmul.cpp), ~10x the table CRC
    // on large shared-state buffers; bit parity enforced by selftest
    static const bool hw = clmul::available();
    if (hw && nbytes >= 64) return clmul::crc32(data, nbytes, crc);
    static const Crc32Tables tbl;
    const auto *p = static_cast<const uint8_t *>(data);
    crc = ~crc;
    while (nbytes >= 8) {
        uint32_t lo;
        memcpy(&lo, p, 4);
        lo ^= crc;
        uint32_t hi;
        memcpy(&hi, p + 4, 4);
        crc = tbl.t[7][lo & 0xFF] ^ tbl.t[6][(lo >> 8) & 0xFF] ^
              tbl.t[5][(lo >> 16) & 0xFF] ^ tbl.t[4][lo >> 24] ^
              tbl.t[3][hi & 0xFF] ^ tbl.t[2][(hi >> 8) & 0xFF] ^
              tbl.t[1][(hi >> 16) & 0xFF] ^ tbl.t[0][hi >> 24];
        p += 8;
        nbytes -= 8;
    }
    while (nbytes--) crc = (crc >> 8) ^ tbl.t[0][(crc ^ *p++) & 0xFF];
    return ~crc;
}

} // namespace pcclt::hash
