// Quantization kernels.
//
// Reference parity: the reference implements MinMax with templated SIMD
// kernels (/root/reference/ccoip/src/cpp/quantize_kernels.cpp:38-83) and
// delegates ZeroPointScale to piquant, with a fused dequantize+accumulate
// in reduce_kernels.cpp:361-427. Here both algorithms share one design:
// typed `#pragma omp simd` template kernels for the
// {f32, f64, bf16, f16} -> u8/u16/u32/i8 hot paths (bf16/f16 widen to f32
// in the lanes; the reference never had 16-bit float quantize sources at
// all — quantize_kernels.cpp is float/double only), with a generic scalar
// double fallback for the remaining combos.
// All peers run identical code, so cross-peer bit parity of the
// quantize -> dequantize round trip is preserved by construction.
#include "quantize.hpp"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <limits>

#include "kernels.hpp"
#include "wire.hpp"

namespace pcclt::quant {

using proto::DType;
using proto::QuantAlgo;

std::vector<uint8_t> Meta::encode() const {
    wire::Writer w;
    w.u8(static_cast<uint8_t>(algo));
    w.u8(static_cast<uint8_t>(src_dtype));
    w.u8(static_cast<uint8_t>(q_dtype));
    w.f64(lo);
    w.f64(hi);
    return w.take();
}

std::optional<Meta> Meta::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        Meta m;
        m.algo = static_cast<QuantAlgo>(r.u8());
        m.src_dtype = static_cast<DType>(r.u8());
        m.q_dtype = static_cast<DType>(r.u8());
        m.lo = r.f64();
        m.hi = r.f64();
        return m;
    } catch (...) { return std::nullopt; }
}

size_t quantized_bytes(DType q_dtype, size_t count) {
    return proto::dtype_size(q_dtype) * count;
}

namespace {

// ---------- generic scalar fallback (f16/bf16 + exotic combos) ----------

// read element i of a float-typed source as double
template <typename T> double get_as_double(const void *p, size_t i) {
    return static_cast<double>(static_cast<const T *>(p)[i]);
}

double load_elem(DType dt, const void *p, size_t i) {
    switch (dt) {
    case DType::kF32: return get_as_double<float>(p, i);
    case DType::kF64: return get_as_double<double>(p, i);
    case DType::kF16: return kernels::f16_to_f32(static_cast<const uint16_t *>(p)[i]);
    case DType::kBF16: return kernels::bf16_to_f32(static_cast<const uint16_t *>(p)[i]);
    default: return 0.0; // quantization only defined for float dtypes
    }
}

void store_elem(DType dt, void *p, size_t i, double v) {
    switch (dt) {
    case DType::kF32: static_cast<float *>(p)[i] = static_cast<float>(v); break;
    case DType::kF64: static_cast<double *>(p)[i] = v; break;
    case DType::kF16:
        static_cast<uint16_t *>(p)[i] = kernels::f32_to_f16(static_cast<float>(v));
        break;
    case DType::kBF16:
        static_cast<uint16_t *>(p)[i] = kernels::f32_to_bf16(static_cast<float>(v));
        break;
    default: break;
    }
}

double qmax_of(DType q) {
    switch (q) {
    case DType::kU8: return 255.0;
    case DType::kU16: return 65535.0;
    case DType::kU32: return 4294967295.0;
    case DType::kI8: return 255.0; // ZPS uses the full 256-step range
    default: return 255.0;
    }
}

template <typename Q> void store_q(void *q, size_t i, double v) {
    static_cast<Q *>(q)[i] = static_cast<Q>(v);
}

void store_quant(DType qd, void *q, size_t i, double v) {
    switch (qd) {
    case DType::kU8: store_q<uint8_t>(q, i, v); break;
    case DType::kU16: store_q<uint16_t>(q, i, v); break;
    case DType::kU32: store_q<uint32_t>(q, i, v); break;
    case DType::kI8: static_cast<int8_t *>(q)[i] = static_cast<int8_t>(v); break;
    default: break;
    }
}

double load_quant(DType qd, const void *q, size_t i) {
    switch (qd) {
    case DType::kU8: return static_cast<const uint8_t *>(q)[i];
    case DType::kU16: return static_cast<const uint16_t *>(q)[i];
    case DType::kU32: return static_cast<const uint32_t *>(q)[i];
    case DType::kI8: return static_cast<const int8_t *>(q)[i];
    default: return 0.0;
    }
}

// ---------- typed SIMD kernels (f32/f64/bf16/f16 sources) ----------

// T: storage type of the source buffer; SrcTraits<T>::S is the compute
// type the lanes run in. f32 computes in f32 (vectorizes 2x wider than
// double), f64 in double, and the 16-bit float formats widen to f32 in
// the lanes — bf16's converters are branch-free inline bit shifts that
// vectorize cleanly (the TPU gradient dtype must not fall to the scalar
// double path; see kernels_avx2.cpp for the same reasoning on reduction).

struct bf16_t {
    uint16_t bits;
};
struct f16_t {
    uint16_t bits;
};

template <typename T> struct SrcTraits {
    using S = T;
    static S load(const T *p, size_t i) { return p[i]; }
    static void store(T *p, size_t i, S v) { p[i] = v; }
};
template <> struct SrcTraits<bf16_t> {
    using S = float;
    static S load(const bf16_t *p, size_t i) { return kernels::bf16_to_f32(p[i].bits); }
    static void store(bf16_t *p, size_t i, S v) { p[i].bits = kernels::f32_to_bf16(v); }
};
template <> struct SrcTraits<f16_t> {
    using S = float;
    static S load(const f16_t *p, size_t i) { return kernels::f16_to_f32(p[i].bits); }
    static void store(f16_t *p, size_t i, S v) { p[i].bits = kernels::f32_to_f16(v); }
};

template <typename T, typename Q>
void k_quant_minmax(const T *src, Q *out, size_t n,
                    typename SrcTraits<T>::S lo, typename SrcTraits<T>::S inv,
                    typename SrcTraits<T>::S qmax) {
    using S = typename SrcTraits<T>::S;
#pragma omp simd
    for (size_t i = 0; i < n; ++i) {
        S v = (SrcTraits<T>::load(src, i) - lo) * inv;
        v = v < S(0) ? S(0) : (v > qmax ? qmax : v);
        out[i] = static_cast<Q>(v + S(0.5)); // v >= 0: floor(v+.5) == round
    }
}

template <typename T, typename Q>
void k_quant_zps(const T *src, Q *out, size_t n,
                 typename SrcTraits<T>::S inv_scale, typename SrcTraits<T>::S zp,
                 typename SrcTraits<T>::S qlo, typename SrcTraits<T>::S qhi) {
    using S = typename SrcTraits<T>::S;
#pragma omp simd
    for (size_t i = 0; i < n; ++i) {
        // shift into the non-negative domain so the +0.5 rounding trick holds
        S v = SrcTraits<T>::load(src, i) * inv_scale + zp - qlo;
        S span = qhi - qlo;
        v = v < S(0) ? S(0) : (v > span ? span : v);
        out[i] = static_cast<Q>(static_cast<S>(static_cast<int64_t>(v + S(0.5))) + qlo);
    }
}

template <typename T, typename Q>
void k_dq_set_minmax(const Q *q, T *dst, size_t n,
                     typename SrcTraits<T>::S lo, typename SrcTraits<T>::S step) {
    using S = typename SrcTraits<T>::S;
#pragma omp simd
    for (size_t i = 0; i < n; ++i)
        SrcTraits<T>::store(dst, i, lo + static_cast<S>(q[i]) * step);
}

template <typename T, typename Q>
void k_dq_set_zps(const Q *q, T *dst, size_t n,
                  typename SrcTraits<T>::S scale, typename SrcTraits<T>::S zp) {
    using S = typename SrcTraits<T>::S;
#pragma omp simd
    for (size_t i = 0; i < n; ++i)
        SrcTraits<T>::store(dst, i, (static_cast<S>(q[i]) - zp) * scale);
}

struct AddOp {
    template <typename S> S operator()(S a, S b) const { return a + b; }
};
struct MulOp {
    template <typename S> S operator()(S a, S b) const { return a * b; }
};
struct MaxOp {
    template <typename S> S operator()(S a, S b) const { return a > b ? a : b; }
};
struct MinOp {
    template <typename S> S operator()(S a, S b) const { return a < b ? a : b; }
};

template <typename T, typename Q, typename Op>
void k_dq_acc_minmax(const Q *q, T *dst, size_t n,
                     typename SrcTraits<T>::S lo, typename SrcTraits<T>::S step,
                     Op op) {
    using S = typename SrcTraits<T>::S;
#pragma omp simd
    for (size_t i = 0; i < n; ++i)
        SrcTraits<T>::store(
            dst, i, op(SrcTraits<T>::load(dst, i), lo + static_cast<S>(q[i]) * step));
}

template <typename T, typename Q, typename Op>
void k_dq_acc_zps(const Q *q, T *dst, size_t n,
                  typename SrcTraits<T>::S scale, typename SrcTraits<T>::S zp,
                  Op op) {
    using S = typename SrcTraits<T>::S;
#pragma omp simd
    for (size_t i = 0; i < n; ++i)
        SrcTraits<T>::store(
            dst, i, op(SrcTraits<T>::load(dst, i), (static_cast<S>(q[i]) - zp) * scale));
}

// min/max scan; omp simd reduction licenses the reassociation
template <typename T>
void k_minmax_scan(const T *src, size_t n,
                   typename SrcTraits<T>::S &lo_out, typename SrcTraits<T>::S &hi_out) {
    using S = typename SrcTraits<T>::S;
    S lo = SrcTraits<T>::load(src, 0), hi = lo;
#pragma omp simd reduction(min : lo) reduction(max : hi)
    for (size_t i = 0; i < n; ++i) {
        S v = SrcTraits<T>::load(src, i);
        lo = lo < v ? lo : v;
        hi = hi > v ? hi : v;
    }
    lo_out = lo;
    hi_out = hi;
}

// dispatch (src f32/f64/bf16/f16) x (q u8/u16/u32/i8) to fn(T{}, Q{});
// returns false when the combo has no typed kernel (caller uses the scalar
// fallback)
template <typename Fn> bool dispatch_typed(DType src, DType q, Fn &&fn) {
    auto with_q = [&](auto t_tag) {
        using T = decltype(t_tag);
        using S = typename SrcTraits<T>::S;
        switch (q) {
        case DType::kU8: fn(T{}, uint8_t{}); return true;
        case DType::kU16: fn(T{}, uint16_t{}); return true;
        case DType::kU32:
            // float cannot represent 2^32-1: the rounding trick would
            // overflow the cast — that combo takes the scalar double path
            if constexpr (std::is_same_v<S, float>) return false;
            else { fn(T{}, uint32_t{}); return true; }
        case DType::kI8: fn(T{}, int8_t{}); return true;
        default: return false;
        }
    };
    switch (src) {
    case DType::kF32: return with_q(float{});
    case DType::kF64: return with_q(double{});
    case DType::kBF16: return with_q(bf16_t{});
    case DType::kF16: return with_q(f16_t{});
    default: return false;
    }
}

} // namespace

Meta compute_meta(QuantAlgo algo, DType q_dtype, DType src_dtype, const void *src,
                  size_t count) {
    Meta m;
    m.algo = algo;
    m.src_dtype = src_dtype;
    m.q_dtype = q_dtype;
    if (algo == QuantAlgo::kNone || count == 0) return m;

    double lo, hi;
    if (src_dtype == DType::kF64) {
        k_minmax_scan(static_cast<const double *>(src), count, lo, hi);
    } else if (src_dtype == DType::kF32 || src_dtype == DType::kBF16 ||
               src_dtype == DType::kF16) {
        float l = 0, h = 0;
        if (src_dtype == DType::kF32)
            k_minmax_scan(static_cast<const float *>(src), count, l, h);
        else if (src_dtype == DType::kBF16)
            k_minmax_scan(static_cast<const bf16_t *>(src), count, l, h);
        else
            k_minmax_scan(static_cast<const f16_t *>(src), count, l, h);
        lo = l;
        hi = h;
    } else {
        lo = std::numeric_limits<double>::infinity();
        hi = -lo;
        for (size_t i = 0; i < count; ++i) {
            double v = load_elem(src_dtype, src, i);
            lo = std::min(lo, v);
            hi = std::max(hi, v);
        }
    }
    if (!std::isfinite(lo) || !std::isfinite(hi)) {
        lo = 0.0;
        hi = 0.0;
    }
    if (algo == QuantAlgo::kMinMax) {
        m.lo = lo;
        m.hi = hi;
    } else { // ZeroPointScale (asymmetric, piquant-style)
        double qmax = qmax_of(q_dtype);
        double scale = (hi - lo) / qmax;
        if (scale <= 0.0) scale = 1.0;
        double zp = std::round(-lo / scale) + (q_dtype == DType::kI8 ? -128.0 : 0.0);
        m.lo = scale;
        m.hi = zp;
    }
    return m;
}

void quantize(const Meta &m, const void *src, void *q_out, size_t count) {
    if (m.algo == QuantAlgo::kMinMax) {
        const double range = m.hi - m.lo;
        const double qmax = qmax_of(m.q_dtype);
        const double inv = range > 0 ? qmax / range : 0.0;
        bool done = dispatch_typed(m.src_dtype, m.q_dtype, [&](auto t_tag, auto q_tag) {
            using T = decltype(t_tag);
            using S = typename SrcTraits<T>::S;
            using Q = decltype(q_tag);
            k_quant_minmax<T, Q>(static_cast<const T *>(src), static_cast<Q *>(q_out),
                                 count, static_cast<S>(m.lo), static_cast<S>(inv),
                                 static_cast<S>(qmax));
        });
        if (done) return;
        for (size_t i = 0; i < count; ++i) {
            double v = load_elem(m.src_dtype, src, i);
            double q = std::round((v - m.lo) * inv);
            q = std::clamp(q, 0.0, qmax);
            store_quant(m.q_dtype, q_out, i, q);
        }
    } else { // ZPS: q = round(x/scale) + zp
        const double scale = m.lo, zp = m.hi;
        const double qlo = m.q_dtype == DType::kI8 ? -128.0 : 0.0;
        const double qhi = m.q_dtype == DType::kI8 ? 127.0 : qmax_of(m.q_dtype);
        bool done = dispatch_typed(m.src_dtype, m.q_dtype, [&](auto t_tag, auto q_tag) {
            using T = decltype(t_tag);
            using S = typename SrcTraits<T>::S;
            using Q = decltype(q_tag);
            k_quant_zps<T, Q>(static_cast<const T *>(src), static_cast<Q *>(q_out),
                              count, static_cast<S>(1.0 / scale), static_cast<S>(zp),
                              static_cast<S>(qlo), static_cast<S>(qhi));
        });
        if (done) return;
        for (size_t i = 0; i < count; ++i) {
            double v = load_elem(m.src_dtype, src, i);
            double q = std::clamp(std::round(v / scale) + zp, qlo, qhi);
            store_quant(m.q_dtype, q_out, i, q);
        }
    }
}

namespace {

double dequant_elem(const Meta &m, const void *q, size_t i) {
    double qv = load_quant(m.q_dtype, q, i);
    if (m.algo == QuantAlgo::kMinMax) {
        double range = m.hi - m.lo;
        double qmax = qmax_of(m.q_dtype);
        return m.lo + (range > 0 ? qv * range / qmax : 0.0);
    }
    return (qv - m.hi) * m.lo; // (q - zp) * scale
}

// step = range/qmax for MinMax (0 when the range collapses)
double minmax_step(const Meta &m) {
    double range = m.hi - m.lo;
    return range > 0 ? range / qmax_of(m.q_dtype) : 0.0;
}

} // namespace

void dequantize_set(const Meta &m, const void *q, void *dst, size_t count) {
    bool done = dispatch_typed(m.src_dtype, m.q_dtype, [&](auto t_tag, auto q_tag) {
        using T = decltype(t_tag);
        using S = typename SrcTraits<T>::S;
        using Q = decltype(q_tag);
        if (m.algo == QuantAlgo::kMinMax)
            k_dq_set_minmax<T, Q>(static_cast<const Q *>(q), static_cast<T *>(dst),
                                  count, static_cast<S>(m.lo),
                                  static_cast<S>(minmax_step(m)));
        else
            k_dq_set_zps<T, Q>(static_cast<const Q *>(q), static_cast<T *>(dst), count,
                               static_cast<S>(m.lo), static_cast<S>(m.hi));
    });
    if (done) return;
    for (size_t i = 0; i < count; ++i) store_elem(m.src_dtype, dst, i, dequant_elem(m, q, i));
}

void dequantize_accumulate(const Meta &m, proto::RedOp op, const void *q, void *dst,
                           size_t count) {
    bool done = dispatch_typed(m.src_dtype, m.q_dtype, [&](auto t_tag, auto q_tag) {
        using T = decltype(t_tag);
        using S = typename SrcTraits<T>::S;
        using Q = decltype(q_tag);
        auto *qs = static_cast<const Q *>(q);
        auto *ds = static_cast<T *>(dst);
        auto run = [&](auto red) {
            if (m.algo == QuantAlgo::kMinMax)
                k_dq_acc_minmax<T, Q>(qs, ds, count, static_cast<S>(m.lo),
                                      static_cast<S>(minmax_step(m)), red);
            else
                k_dq_acc_zps<T, Q>(qs, ds, count, static_cast<S>(m.lo),
                                   static_cast<S>(m.hi), red);
        };
        switch (op) {
        case proto::RedOp::kSum:
        case proto::RedOp::kAvg: run(AddOp{}); break;
        case proto::RedOp::kProd: run(MulOp{}); break;
        case proto::RedOp::kMax: run(MaxOp{}); break;
        case proto::RedOp::kMin: run(MinOp{}); break;
        default: run(AddOp{});
        }
    });
    if (done) return;
    for (size_t i = 0; i < count; ++i) {
        double v = dequant_elem(m, q, i);
        double d = load_elem(m.src_dtype, dst, i);
        double r;
        switch (op) {
        case proto::RedOp::kSum:
        case proto::RedOp::kAvg: r = d + v; break;
        case proto::RedOp::kProd: r = d * v; break;
        case proto::RedOp::kMax: r = std::max(d, v); break;
        case proto::RedOp::kMin: r = std::min(d, v); break;
        default: r = v;
        }
        store_elem(m.src_dtype, dst, i, r);
    }
}

void requantize_self(const Meta &m, void *data, size_t count) {
    if (m.algo == QuantAlgo::kNone) return;
    std::vector<uint8_t> q(quantized_bytes(m.q_dtype, count));
    quantize(m, data, q.data(), count);
    dequantize_set(m, q.data(), data, count);
}

} // namespace pcclt::quant
