#include "quantize.hpp"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <limits>

#include "kernels.hpp"
#include "wire.hpp"

namespace pcclt::quant {

using proto::DType;
using proto::QuantAlgo;

std::vector<uint8_t> Meta::encode() const {
    wire::Writer w;
    w.u8(static_cast<uint8_t>(algo));
    w.u8(static_cast<uint8_t>(src_dtype));
    w.u8(static_cast<uint8_t>(q_dtype));
    w.f64(lo);
    w.f64(hi);
    return w.take();
}

std::optional<Meta> Meta::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        Meta m;
        m.algo = static_cast<QuantAlgo>(r.u8());
        m.src_dtype = static_cast<DType>(r.u8());
        m.q_dtype = static_cast<DType>(r.u8());
        m.lo = r.f64();
        m.hi = r.f64();
        return m;
    } catch (...) { return std::nullopt; }
}

size_t quantized_bytes(DType q_dtype, size_t count) {
    return proto::dtype_size(q_dtype) * count;
}

namespace {

// read element i of a float-typed source as double
template <typename T> double get_as_double(const void *p, size_t i) {
    return static_cast<double>(static_cast<const T *>(p)[i]);
}

double load_elem(DType dt, const void *p, size_t i) {
    switch (dt) {
    case DType::kF32: return get_as_double<float>(p, i);
    case DType::kF64: return get_as_double<double>(p, i);
    case DType::kF16: return kernels::f16_to_f32(static_cast<const uint16_t *>(p)[i]);
    case DType::kBF16: return kernels::bf16_to_f32(static_cast<const uint16_t *>(p)[i]);
    default: return 0.0; // quantization only defined for float dtypes
    }
}

void store_elem(DType dt, void *p, size_t i, double v) {
    switch (dt) {
    case DType::kF32: static_cast<float *>(p)[i] = static_cast<float>(v); break;
    case DType::kF64: static_cast<double *>(p)[i] = v; break;
    case DType::kF16:
        static_cast<uint16_t *>(p)[i] = kernels::f32_to_f16(static_cast<float>(v));
        break;
    case DType::kBF16:
        static_cast<uint16_t *>(p)[i] = kernels::f32_to_bf16(static_cast<float>(v));
        break;
    default: break;
    }
}

double qmax_of(DType q) {
    switch (q) {
    case DType::kU8: return 255.0;
    case DType::kU16: return 65535.0;
    case DType::kU32: return 4294967295.0;
    case DType::kI8: return 255.0; // ZPS uses the full 256-step range
    default: return 255.0;
    }
}

template <typename Q> void store_q(void *q, size_t i, double v) {
    static_cast<Q *>(q)[i] = static_cast<Q>(v);
}

void store_quant(DType qd, void *q, size_t i, double v) {
    switch (qd) {
    case DType::kU8: store_q<uint8_t>(q, i, v); break;
    case DType::kU16: store_q<uint16_t>(q, i, v); break;
    case DType::kU32: store_q<uint32_t>(q, i, v); break;
    case DType::kI8: static_cast<int8_t *>(q)[i] = static_cast<int8_t>(v); break;
    default: break;
    }
}

double load_quant(DType qd, const void *q, size_t i) {
    switch (qd) {
    case DType::kU8: return static_cast<const uint8_t *>(q)[i];
    case DType::kU16: return static_cast<const uint16_t *>(q)[i];
    case DType::kU32: return static_cast<const uint32_t *>(q)[i];
    case DType::kI8: return static_cast<const int8_t *>(q)[i];
    default: return 0.0;
    }
}

} // namespace

Meta compute_meta(QuantAlgo algo, DType q_dtype, DType src_dtype, const void *src,
                  size_t count) {
    Meta m;
    m.algo = algo;
    m.src_dtype = src_dtype;
    m.q_dtype = q_dtype;
    if (algo == QuantAlgo::kNone || count == 0) return m;

    double lo = std::numeric_limits<double>::infinity();
    double hi = -std::numeric_limits<double>::infinity();
    for (size_t i = 0; i < count; ++i) {
        double v = load_elem(src_dtype, src, i);
        lo = std::min(lo, v);
        hi = std::max(hi, v);
    }
    if (!std::isfinite(lo) || !std::isfinite(hi)) {
        lo = 0.0;
        hi = 0.0;
    }
    if (algo == QuantAlgo::kMinMax) {
        m.lo = lo;
        m.hi = hi;
    } else { // ZeroPointScale (asymmetric, piquant-style)
        double qmax = qmax_of(q_dtype);
        double scale = (hi - lo) / qmax;
        if (scale <= 0.0) scale = 1.0;
        double zp = std::round(-lo / scale) + (q_dtype == DType::kI8 ? -128.0 : 0.0);
        m.lo = scale;
        m.hi = zp;
    }
    return m;
}

void quantize(const Meta &m, const void *src, void *q_out, size_t count) {
    if (m.algo == QuantAlgo::kMinMax) {
        double range = m.hi - m.lo;
        double qmax = qmax_of(m.q_dtype);
        double inv = range > 0 ? qmax / range : 0.0;
        for (size_t i = 0; i < count; ++i) {
            double v = load_elem(m.src_dtype, src, i);
            double q = std::round((v - m.lo) * inv);
            q = std::clamp(q, 0.0, qmax);
            store_quant(m.q_dtype, q_out, i, q);
        }
    } else { // ZPS: q = round(x/scale) + zp
        double scale = m.lo, zp = m.hi;
        double qlo = m.q_dtype == DType::kI8 ? -128.0 : 0.0;
        double qhi = m.q_dtype == DType::kI8 ? 127.0 : qmax_of(m.q_dtype);
        for (size_t i = 0; i < count; ++i) {
            double v = load_elem(m.src_dtype, src, i);
            double q = std::clamp(std::round(v / scale) + zp, qlo, qhi);
            store_quant(m.q_dtype, q_out, i, q);
        }
    }
}

namespace {

double dequant_elem(const Meta &m, const void *q, size_t i) {
    double qv = load_quant(m.q_dtype, q, i);
    if (m.algo == QuantAlgo::kMinMax) {
        double range = m.hi - m.lo;
        double qmax = qmax_of(m.q_dtype);
        return m.lo + (range > 0 ? qv * range / qmax : 0.0);
    }
    return (qv - m.hi) * m.lo; // (q - zp) * scale
}

} // namespace

void dequantize_set(const Meta &m, const void *q, void *dst, size_t count) {
    for (size_t i = 0; i < count; ++i) store_elem(m.src_dtype, dst, i, dequant_elem(m, q, i));
}

void dequantize_accumulate(const Meta &m, proto::RedOp op, const void *q, void *dst,
                           size_t count) {
    for (size_t i = 0; i < count; ++i) {
        double v = dequant_elem(m, q, i);
        double d = load_elem(m.src_dtype, dst, i);
        double r;
        switch (op) {
        case proto::RedOp::kSum:
        case proto::RedOp::kAvg: r = d + v; break;
        case proto::RedOp::kProd: r = d * v; break;
        case proto::RedOp::kMax: r = std::max(d, v); break;
        case proto::RedOp::kMin: r = std::min(d, v); break;
        default: r = v;
        }
        store_elem(m.src_dtype, dst, i, r);
    }
}

void requantize_self(const Meta &m, void *data, size_t count) {
    if (m.algo == QuantAlgo::kNone) return;
    std::vector<uint8_t> q(quantized_bytes(m.q_dtype, count));
    quantize(m, data, q.data(), count);
    dequantize_set(m, q.data(), data, count);
}

} // namespace pcclt::quant
