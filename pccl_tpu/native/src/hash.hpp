// Content hashing for shared-state drift detection.
//
// Reference parity: simplehash (CPU emulating the CUDA grid layout for
// bit-identical CPU/GPU digests — /root/reference/ccoip/src/cpp/simplehash/
// simplehash_cpu.cpp:7-58) and CRC32 (crc32_cpu.cpp).
//
// TPU-first re-design: instead of emulating an accelerator grid, the hash is
// a 256-lane polynomial hash whose lane structure vectorizes identically in
// C++ (Horner per lane) and numpy/JAX (matrix-times-power-vector) — the
// device-independent bit-parity invariant the reference achieves with its
// warp-shuffle emulation. See pccl_tpu/ops/hashing.py for the Python twin.
//
// Layout: bytes → little-endian u32 words (zero-padded tail), word i → lane
// (i % 256). Lane state: Horner with P = 0x100000001B3 over u64. Lanes are
// combined with a second Horner pass (Q = golden ratio), seeded with the
// byte length, then finalized with a murmur-style avalanche.
#pragma once

#include <cstddef>
#include <cstdint>

namespace pcclt::hash {

inline constexpr uint64_t kLanes = 256;
inline constexpr uint64_t kP = 0x100000001B3ull;           // FNV-1a prime
inline constexpr uint64_t kQ = 0x9E3779B97F4A7C15ull;      // 2^64 / phi
inline constexpr uint64_t kSeed = 0xCBF29CE484222325ull;   // FNV offset basis

uint64_t simplehash(const void *data, size_t nbytes);

// TPU-native hash (Type::kSimpleTpu): the digest a TPU can compute over
// HBM-RESIDENT bytes with pure u32 arithmetic (no u64 on the VPU), so a
// clean shared-state sync ships 8 bytes over the wire instead of staging
// the whole array to host (the reference hashes CUDA buffers on-GPU for
// exactly this reason: /root/reference/ccoip/src/cuda/simplehash_cuda.cu,
// dispatched at ccoip_client_handler.cpp:383-416). Definition: LE u32
// words, word i -> (row i / 65536, lane i % 65536); each of the 65536
// lanes runs two parallel u32 Horner chains (planes A/B with distinct
// primes/seeds) over its padded column; lanes combine by 16 levels of
// pairwise murmur3-step folding (non-linear rotate-multiply — a linear
// fold cancels on uniform content); the two u32 plane digests
// concatenate to 64 bits, mix with the byte length, and avalanche. The
// lane/fold structure is embarrassingly parallel on the VPU (the jax twin
// is a baked weighted-sum + fold, ops/hashing.py) and this CPU twin is
// bit-identical.
inline constexpr size_t kTpuLanes = 65536;
inline constexpr uint32_t kTpuPA = 0x01000193u;  // FNV-1a 32 prime
inline constexpr uint32_t kTpuSA = 0x811C9DC5u;  // FNV-1a 32 offset
inline constexpr uint32_t kTpuPB = 0x85EBCA6Bu;  // murmur3 fmix c1
inline constexpr uint32_t kTpuSB = 0x9E3779B9u;  // 2^32 / phi
uint64_t simplehash_tpu(const void *data, size_t nbytes);

// CRC32 (IEEE 802.3, reflected, poly 0xEDB88320) — matches zlib.crc32.
uint32_t crc32(const void *data, size_t nbytes, uint32_t crc = 0);

// Selectable shared-state hash (reference ccoip_hash_type_t,
// ccoip_types.hpp:27-30 — the reference also defaults to simplehash).
// All peers of a group must agree on the type; it is configured via the
// PCCLT_SS_HASH env var ("simple" | "crc32" | "simple-tpu"), mirroring the
// reference where the choice is internal rather than per-call.
enum class Type : uint8_t { kSimple = 0, kCrc32 = 1, kSimpleTpu = 2 };
uint64_t content_hash(Type t, const void *data, size_t nbytes);
Type type_from_env();

uint64_t avalanche64(uint64_t x); // exposed for the Python twin's tests

} // namespace pcclt::hash
