// Authoritative master state machine — pure logic, no IO.
//
// Reference parity: CCoIPMasterState + the consensus logic of
// CCoIPMasterHandler (/root/reference/ccoip/internal_include/
// ccoip_master_state.hpp, ccoip/src/cpp/ccoip_master_handler.cpp).
// Re-designed as an event-in → packets-out pure state machine: every
// client packet (or disconnect) is applied by one method which returns the
// set of packets to emit. A single dispatcher thread applies events, so the
// machine is deterministic by construction (the reference achieves the same
// via a single libuv loop thread).
//
// Orchestrated consensus rounds:
//  - topology update / peer accept (global vote, admits pending peers)
//  - collective ops (per peer-group, per tag: init votes -> commence,
//    complete votes -> exactly-one-abort + done)
//  - shared-state sync (per group: mask election by popularity, dirty keys,
//    one-increment revision rule, kicks)
//  - topology optimization (global: bandwidth probes -> ATSP ring)
#pragma once

#include <atomic>
#include <chrono>
#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <optional>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "annotations.hpp"
#include "bandwidth.hpp"
#include "journal.hpp"
#include "protocol.hpp"
#include "schedule.hpp"
#include "telemetry.hpp"

namespace pcclt::master {

using proto::Uuid;

struct Outbox {
    uint64_t conn_id;
    uint16_t type;
    std::vector<uint8_t> payload;
};

struct ClientInfo {
    Uuid uuid{};
    uint64_t conn_id = 0;
    uint32_t peer_group = 0;
    net::Addr ip{}; // observed or advertised (family-tagged; port unused)
    uint16_t p2p_port = 0, ss_port = 0, bench_port = 0;
    bool accepted = false; // admitted to the world vs pending join
    // telemetry-only control session (hello observer flag): may push
    // digests but never joins the world — excluded from admission rounds,
    // peer lists, and the journal. The fleet-scale digest bots (bench,
    // stress orchestrator --fleet-scale) and external monitoring agents
    // register this way so a thousand of them cannot wedge a topology
    // round that real peers are waiting on.
    bool observer = false;

    // votes (valid within their phase)
    bool vote_topology = false;
    // vote granted AT ADMISSION (the joiner is parked in its establish
    // loop and cannot re-vote): never declined as moot, only consumed by
    // a completed round — see check_topology / remove_client
    bool admission_vote = false;
    bool reported_establish = false;
    bool establish_ok = false;
    std::vector<Uuid> establish_failed;
    bool vote_optimize = false;
    bool optimize_work_done = false;
    std::optional<proto::SharedStateSyncC2M> sync_req;
    bool dist_done = false;
};

struct CollectiveOp {
    proto::CollectiveInit params;
    uint64_t seq = 0;
    bool commenced = false;
    bool abort_broadcast = false; // exactly-one-abort accounting
    bool any_aborted = false;
    std::set<Uuid> members; // group membership at commence
    std::set<Uuid> initiated;
    std::set<Uuid> completed;
};

// ---- fleet health model (observability plane, docs/09) ----
// Soft state folded from kC2MTelemetryDigest pushes. Lives behind its own
// mutex (NOT dispatcher-only like the consensus machine): the dedicated
// digest-ingest (fold) thread is the only WRITER, the metrics/health HTTP
// threads read it concurrently, and the dispatcher only ever enqueues
// work toward it (it takes health_mu_ solely as a render READER inside an
// incident manifest). Deliberately unjournaled: rates are meaningless
// across a restart — a restarted master rebuilds the picture from the
// next digests.

struct PeerHealth {
    std::string uuid;   // uuid_str form (label-friendly)
    uint32_t group = 0;
    uint64_t last_seq = 0;       // newest collective seq the peer completed
    uint64_t ring_dropped = 0;   // its flight-recorder events lost to wrap
    uint64_t ring_pushed = 0;    // events pushed into its recorder ring
    uint64_t ring_cap = 0;       // its ring capacity (saturation gauge)
    uint64_t collectives_ok = 0;
    uint64_t digests = 0;        // digests received from this peer
    uint64_t last_digest_ns = 0; // telemetry clock at the last digest
    bool departed = false;       // disconnected (entry kept for post-mortems)
    // comm-level phase latency histograms (cumulative; keyed by
    // telemetry::Phase wire value) — rendered as Prometheus histogram
    // series + quantile summary gauges
    std::map<uint8_t, telemetry::HistSnapshot> phase_hists;
};

struct EdgeHealth {
    std::string from_uuid;    // reporting peer
    std::string to_endpoint;  // canonical remote endpoint ("ip:port")
    std::string to_uuid;      // resolved target peer ("" = unknown endpoint)
    double tx_mbps = 0, rx_mbps = 0, stall_ratio = 0;  // peer EWMAs
    uint64_t tx_bytes = 0, rx_bytes = 0;               // cumulative
    double expected_mbps = 0;  // bandwidth-matrix entry (0 = unmeasured)
    bool straggler = false;    // measured below the straggler threshold
    // matrix entry captured when the flag went up: recovery is judged
    // against THIS, not the live matrix — the REOPT hook rewrites the
    // matrix with the degraded rate, which must not self-clear the flag
    double flag_baseline_mbps = 0;
    // the reporter's data-plane watchdog verdict for its OUTBOUND hop to
    // this endpoint (0 ok / 1 suspect / 2 confirmed); a CONFIRMED report
    // means the peer is already relaying around the edge in-collective
    uint32_t wd_state = 0;
    // this straggler flag came from a watchdog CONFIRM (outbound witness),
    // so recovery is judged by the watchdog clearing, not the rx rate
    bool wd_flagged = false;
    // per-edge latency distributions (cumulative, from the digest)
    telemetry::HistSnapshot stage_wire_hist, stall_hist;
};

struct GroupState {
    bool revision_initialized = false;
    uint64_t last_revision = 0;                 // last completed sync revision
    bool sync_in_flight = false;                // responses sent, awaiting dist-done
    uint64_t sync_revision = 0;                 // canonical revision of current round
    // chunk plane (docs/04): keys the in-flight round distributes as
    // chunk maps, and (uuid, key) promotions already broadcast — a
    // re-sent kC2MSyncKeyDone must not re-broadcast
    std::set<std::string> sync_chunked_keys;
    std::set<std::pair<Uuid, std::string>> sync_promoted;
    std::map<uint64_t, CollectiveOp> ops;       // by tag
    std::vector<Uuid> ring;                     // current ring order
    // synthesized collective schedule (docs/12): one entry per
    // (collective, size-class), costed against the measured bandwidth
    // matrix at optimize-topology time. Versioned so the commence stamp
    // can name which table it was drawn from; empty = ring-everything
    // (fresh group, no optimize round yet, or PCCLT_SCHEDULE=0).
    sched::Table schedule;
    uint64_t sched_version = 0;  // last version synthesized for this group
};

class MasterState {
public:
    // spawns the digest-ingest (fold) thread; joined by the destructor
    MasterState();
    ~MasterState();

    // --- HA: journal attachment + rehydration (call before any event) ---
    // Rehydrated clients enter LIMBO: known by UUID with their endpoint
    // info, awaiting kC2MSessionResume. While a group has limbo members,
    // its consensus rounds are frozen (a round completed without them
    // would treat a merely-disconnected peer as departed); limbo entries
    // expire after PCCLT_MASTER_LIMBO_MS (default 15000) and are then
    // treated exactly like a disconnect.
    void attach_journal(journal::Journal *j);
    uint64_t epoch() const { return epoch_; }
    size_t limbo_count() const { return limbo_.size(); }

    // --- event handlers: apply + return packets to send ---
    std::vector<Outbox> on_hello(uint64_t conn, const net::Addr &src_ip,
                                 const proto::HelloC2M &h);
    std::vector<Outbox> on_session_resume(uint64_t conn, const net::Addr &src_ip,
                                          const proto::SessionResumeC2M &s);
    // periodic housekeeping from the dispatcher (limbo expiry)
    std::vector<Outbox> on_tick();
    std::vector<Outbox> on_topology_update(uint64_t conn);
    std::vector<Outbox> on_peers_pending_query(uint64_t conn);
    std::vector<Outbox> on_p2p_established(uint64_t conn, uint64_t revision, bool ok,
                                           const std::vector<Uuid> &failed);
    std::vector<Outbox> on_collective_init(uint64_t conn, const proto::CollectiveInit &ci);
    std::vector<Outbox> on_collective_complete(uint64_t conn, uint64_t tag, bool aborted);
    std::vector<Outbox> on_shared_state_sync(uint64_t conn,
                                             const proto::SharedStateSyncC2M &req);
    std::vector<Outbox> on_dist_done(uint64_t conn);
    // chunk plane: an outdated peer completed (verified) one key mid-round
    // — promote it to seeder and broadcast kM2CSeederUpdate to the group.
    // Fire-and-forget: never answered, invalid/duplicate reports ignored.
    std::vector<Outbox> on_sync_key_done(uint64_t conn,
                                         const proto::SyncKeyDoneC2M &d);
    std::vector<Outbox> on_optimize(uint64_t conn);
    std::vector<Outbox> on_bandwidth_report(uint64_t conn, const Uuid &to, double mbps);
    std::vector<Outbox> on_optimize_work_done(uint64_t conn);
    // fire-and-forget telemetry digest: folds into the fleet health model,
    // runs the straggler detector (vs the bandwidth matrix), never replies
    std::vector<Outbox> on_telemetry_digest(uint64_t conn,
                                            const proto::TelemetryDigestC2M &d);
    std::vector<Outbox> on_disconnect(uint64_t conn);

    // --- fleet health egress (HTTP threads; the fold thread is the only
    // writer). Prometheus text-format gauges/counters, and the /health
    // JSON the C API (pccltMasterGetHealth) and MasterNode.health()
    // mirror. render_metrics serves from a short-lived cache
    // (PCCLT_METRICS_MAX_AGE_MS, default 1000; 0 disables) so N
    // concurrent scrapers share one build; include_history appends the
    // /health?history=1 snapshot ring.
    std::string render_metrics() const;
    std::string render_health_json(bool include_history = false) const;

    // --- test/bench hooks (see selftest + run_master_scale_bench) ---
    // digests fully folded into the fleet maps (NOT merely enqueued):
    // tests spin on this before asserting render output, since the
    // dispatcher returns from on_telemetry_digest before the fold runs
    uint64_t digests_folded() const {
        return digests_total_.load(std::memory_order_acquire);
    }
    uint64_t ingest_dropped() const {
        return ingest_dropped_.load(std::memory_order_relaxed);
    }
    size_t ingest_queue_depth() const {
        return ingest_depth_.load(std::memory_order_relaxed);
    }
    // regression hook: a test holds this while pumping digests through the
    // dispatcher path — enqueue-only ingest must not block (a deadlock
    // here means on_telemetry_digest re-grew a health_mu_ acquisition)
    Mutex &health_mutex_test_hook() { return health_mu_; }

    // conns the dispatcher should close (kicked); cleared on read
    std::vector<uint64_t> take_pending_closes();

    size_t num_clients() const { return clients_.size(); }
    size_t world_size() const;

private:
    ClientInfo *by_conn(uint64_t conn);
    ClientInfo *by_uuid(const Uuid &u);
    std::vector<ClientInfo *> accepted_clients();
    std::vector<ClientInfo *> group_members(uint32_t group);
    std::vector<Uuid> build_ring(uint32_t group);

    void kick(std::vector<Outbox> &out, ClientInfo &c, const std::string &reason);
    // shared tail of on_disconnect and limbo expiry: prune the departed
    // client's votes/ops, reset emptied groups, re-check every consensus
    void remove_client(std::vector<Outbox> &out, const ClientInfo &gone);
    // HA freeze gates: no round may complete while its members sit in limbo
    bool group_frozen(uint32_t group) const;
    void journal_client(const ClientInfo &c);

    // consensus checks — called after votes change AND after disconnects
    void check_topology(std::vector<Outbox> &out);
    // vote-vs-commence deadlock tie-break (see master_state.cpp)
    void defer_topology_voters(std::vector<Outbox> &out, uint32_t group);
    bool group_mid_round(const ClientInfo &c);
    void check_establish(std::vector<Outbox> &out);
    void check_collective(std::vector<Outbox> &out, uint32_t group, uint64_t tag);
    void check_shared_state(std::vector<Outbox> &out, uint32_t group);
    void check_optimize(std::vector<Outbox> &out);
    void abort_group_collectives(std::vector<Outbox> &out, uint32_t group);
    void recheck_all(std::vector<Outbox> &out);

    std::map<uint64_t, ClientInfo> clients_; // by conn_id
    std::map<uint32_t, GroupState> groups_;

    // HA: journal (owned by Master; null = disabled), this incarnation's
    // epoch, and rehydrated sessions awaiting resume
    journal::Journal *journal_ = nullptr;
    uint64_t epoch_ = 1;
    // completed-collective verdicts from the PREVIOUS incarnation, still
    // owed to members whose Done was lost in the crash: a re-init of the
    // (group, tag) from such a member replays Abort(verdict)+Done instead
    // of forming a ghost op its moved-on peers would never join (see
    // journal::OpDoneRec)
    std::map<std::pair<uint32_t, uint64_t>, journal::OpDoneRec> replay_ops_;
    struct LimboClient {
        ClientInfo info; // conn_id 0 (no connection yet)
        std::chrono::steady_clock::time_point deadline;
    };
    std::map<Uuid, LimboClient> limbo_;

    // topology / establishment round
    bool establish_in_flight_ = false;
    std::set<Uuid> round_members_;
    uint64_t topology_revision_ = 0;
    uint64_t next_seq_ = 1;
    // journaled upper bound on issued collective seqs (stride-batched so the
    // journal is not written per collective); a restarted master resumes
    // ABOVE every seq the previous incarnation could have issued — seq-scoped
    // tag ranges in client sink tables are never reused across an epoch
    uint64_t seq_bound_ = 0;

    // optimization round
    bool optimize_in_flight_ = false;
    bool optimize_work_phase_ = false;
    BandwidthStore bandwidth_;

    // fleet health (observability plane): the dispatcher ENQUEUES ingest
    // items (digests, membership deltas, bandwidth-mirror updates, world
    // counts, incident records); the dedicated fold thread drains them and
    // is the only writer of the health_mu_-guarded maps. HTTP threads read
    // under health_mu_ via the render methods.
    // publish_health_summary republishes the dispatcher-only world view
    // (counts) so readers never touch clients_/limbo_ themselves.
    void publish_health_summary();
    // ---- incident black box (docs/09) ----
    // When PCCLT_INCIDENT_DIR is set and an incident trigger fires
    // (collective abort, kick, watchdog CONFIRM, limbo expiry), broadcast
    // a fire-and-forget kM2CIncidentDump to every connected client under a
    // fresh shared incident id and write the master-side manifest
    // (trigger + fleet-health snapshot) under that id. Rate-limited PER
    // TRIGGER CLASS (the prefix before ':') by PCCLT_INCIDENT_MIN_MS
    // (default 30000) so a flapping kick storm cannot starve a later
    // watchdog_confirm bundle — suppressed triggers only bump the
    // per-class counter.
    void maybe_incident(std::vector<Outbox> &out, const std::string &trigger,
                        uint32_t group);
    struct IncidentRec {
        std::string id, trigger;
        uint64_t t_ns = 0; // telemetry clock at the trigger
    };
    // dispatcher-only: per-class rate limiter + id counter
    std::map<std::string, uint64_t> last_incident_ns_by_class_;
    uint64_t incident_seq_ = 0;
    // spawn a background ATSP improvement seeded from the current ring,
    // with the straggler's measured rate substituted into the cost matrix
    // (PCCLT_STRAGGLER_REOPT=1 hook; adopted at the next optimize round)
    void request_straggler_reopt(uint32_t gid);

    // ---- digest-ingest queue (dispatcher -> fold thread) ----
    // Bounded MPSC-style handoff: the dispatcher (and attach_journal, both
    // serialized) push IngestItems; the fold thread drains them in order.
    // Only kDigest items are droppable (cap PCCLT_DIGEST_QUEUE_CAP,
    // default 4096; overflow drops-and-counts so a digest flood can never
    // back-pressure admission/topology); membership/bandwidth deltas are
    // control items and always enqueue.
    struct IngestItem {
        enum Kind : uint8_t {
            kDigest,          // fold a telemetry digest
            kEndpointAdd,     // (endpoint -> peer) index entry add/update
            kEndpointRemove,  // index entry removal (disconnect/limbo drop)
            kDeparted,        // mark fleet peer departed (post-mortem keep)
            kBandwidth,       // bandwidth-matrix mirror: store(peer,to)
            kForget,          // bandwidth-matrix mirror: forget(peer)
            kSummary,         // world/clients/limbo counts republish
            kIncident,        // fired incident record for /health listing
            kSchedule,        // group's synthesized schedule table changed
        };
        Kind kind = kDigest;
        proto::TelemetryDigestC2M digest;    // kDigest
        std::string from_uuid;               // kDigest/kDeparted: label form
        Uuid peer{};                         // kDigest/kEndpointAdd/kBandwidth/kForget
        uint32_t group = 0;                  // kDigest/kEndpointAdd
        std::string endpoint;                // kEndpointAdd/kEndpointRemove
        Uuid to{};                           // kBandwidth
        double mbps = 0;                     // kBandwidth
        size_t world = 0, clients = 0, limbo = 0; // kSummary
        std::string inc_id, inc_trigger;     // kIncident
        uint64_t t_ns = 0;                   // kDigest/kIncident
        std::vector<uint8_t> sched;          // kSchedule: Table::encode()
    };
    // straggler transitions detected by the fold; drained by the
    // dispatcher on its next tick (<=100 ms) to run the parts that need
    // the consensus state: matrix rewrite + journal, REOPT kick-off, and
    // the incident broadcast
    struct StragglerAction {
        std::string endpoint;   // witnessed endpoint ("ip:port")
        std::string from_uuid;  // reporter (label form)
        Uuid from_raw{};        // reporter
        Uuid to_raw{};          // resolved target (valid iff has_to)
        bool has_to = false;
        uint32_t group = 0;
        double measured_mbps = 0, expected_mbps = 0;
        bool outbound_confirm = false; // watchdog CONFIRM on outbound hop
    };
    void enqueue(IngestItem &&it);
    void enqueue_endpoint_add(const ClientInfo &c);
    void fold_loop();
    void fold_item(IngestItem &it);
    void fold_digest(IngestItem &it);
    void fold_sweep(uint64_t now);
    void fold_sample_history(uint64_t now);
    std::string render_metrics_uncached() const;
    mutable Mutex ingest_mu_; // lock-rank: 33
    CondVar ingest_cv_;
    std::deque<IngestItem> ingest_q_ PCCLT_GUARDED_BY(ingest_mu_);
    std::vector<StragglerAction> pending_actions_ PCCLT_GUARDED_BY(ingest_mu_);
    std::atomic<size_t> ingest_depth_{0};     // kDigest items in queue
    std::atomic<uint64_t> ingest_dropped_{0}; // digests dropped at the cap
    std::thread fold_thread_;
    std::atomic<bool> fold_stop_{false};
    // fold-thread-private digest-resolution state (no lock: single owner).
    // The endpoint->peer index the dispatcher used to rebuild O(world) per
    // membership change ON the consensus thread is now maintained
    // incrementally here from kEndpointAdd/kEndpointRemove deltas; fold_bw_
    // mirrors the dispatcher-only BandwidthStore for expected-rate lookups.
    struct FoldPeer {
        Uuid raw{};
        std::string uuid_str;
        uint32_t group = 0;
    };
    std::map<std::string, FoldPeer> fold_endpoints_; // endpoint -> peer
    std::map<Uuid, std::map<Uuid, double>> fold_bw_;
    uint64_t fold_last_sweep_ns_ = 0;
    uint64_t fold_last_sample_ns_ = 0;
    // per-digest fold latency (enqueue->folded), rendered as a histogram +
    // p50/p99 gauges — the "is the ingest thread keeping up" signal
    telemetry::Hist fold_hist_;

    mutable Mutex health_mu_; // lock-rank: 36
    std::map<std::string, PeerHealth> fleet_peers_ PCCLT_GUARDED_BY(health_mu_);
    std::map<std::pair<std::string, std::string>, EdgeHealth> fleet_edges_
        PCCLT_GUARDED_BY(health_mu_);
    // monotone counters: atomics so the fold thread can publish (and
    // tests/bench can poll) without the readers taking health_mu_
    std::atomic<uint64_t> digests_total_{0};
    std::atomic<uint64_t> stragglers_flagged_{0};
    // incident plane: fired incidents (newest last, bounded) + trigger
    // totals incl. rate-limited suppressions, listed on /health
    std::deque<IncidentRec> recent_incidents_ PCCLT_GUARDED_BY(health_mu_);
    std::atomic<uint64_t> incidents_total_{0};
    std::atomic<uint64_t> incidents_suppressed_{0};
    std::map<std::string, uint64_t> incidents_suppressed_by_class_
        PCCLT_GUARDED_BY(health_mu_);
    size_t health_world_ PCCLT_GUARDED_BY(health_mu_) = 0;
    size_t health_clients_ PCCLT_GUARDED_BY(health_mu_) = 0;
    size_t health_limbo_ PCCLT_GUARDED_BY(health_mu_) = 0;
    // schedule plane (docs/12): per-group synthesized tables mirrored for
    // /metrics (pcclt_schedule_kind / pcclt_schedule_version)
    std::map<uint32_t, sched::Table> fleet_schedules_
        PCCLT_GUARDED_BY(health_mu_);
    // /health?history=1 ring: fleet snapshot every
    // PCCLT_HEALTH_HISTORY_MS (default 1000), last PCCLT_HEALTH_HISTORY
    // (default 120) kept — trend-over-time without external storage
    struct HealthSample {
        uint64_t t_ns = 0;
        size_t world = 0, clients = 0, limbo = 0, peers = 0, edges = 0;
        uint64_t digests = 0;   // cumulative at the sample
        double digest_rate = 0; // digests/s since the previous sample
        uint64_t stragglers = 0, incidents = 0, suppressed = 0;
        size_t queue_depth = 0;
        uint64_t queue_dropped = 0;
    };
    std::deque<HealthSample> health_history_ PCCLT_GUARDED_BY(health_mu_);
    // /metrics render cache (PCCLT_METRICS_MAX_AGE_MS): concurrent
    // scrapers serialize here and share one build instead of N copies
    // contending on health_mu_
    mutable Mutex metrics_cache_mu_; // lock-rank: 35
    mutable std::string metrics_cache_ PCCLT_GUARDED_BY(metrics_cache_mu_);
    mutable uint64_t metrics_cache_ns_ PCCLT_GUARDED_BY(metrics_cache_mu_) = 0;
    const uint64_t start_ns_ = telemetry::now_ns();

    // "moonshot" background ATSP improvement (reference: 30 s budget on a
    // thread pool, adopted on a LATER optimize round —
    // ccoip_master_handler.cpp:455-496). The worker thread writes its result
    // into a mutex-guarded slot; the single dispatcher thread adopts it on
    // the next optimize completion if membership is unchanged.
    struct Moonshot {
        std::set<Uuid> members;   // membership the result is valid for
        std::vector<Uuid> ring;
        double cost = 0;
    };
    void spawn_moonshot(uint32_t gid, std::vector<Uuid> uuids,
                        std::vector<double> cost, std::vector<int> tour);
    // the ONLY cross-thread state in this otherwise single-dispatcher
    // machine: the moonshot worker writes its result here, the dispatcher
    // adopts it on the next optimize round
    Mutex moon_mu_; // lock-rank: 34
    std::map<uint32_t, Moonshot> moon_ PCCLT_GUARDED_BY(moon_mu_);
    // one worker per group at a time; finished handles are joined before a
    // replacement is spawned, and moon_stop_ cancels workers on destruction
    std::map<uint32_t, std::thread> moon_threads_;
    std::map<uint32_t, std::shared_ptr<std::atomic<bool>>> moon_running_;
    std::atomic<bool> moon_stop_{false};

    std::vector<uint64_t> pending_closes_;
};

} // namespace pcclt::master
