#include "master.hpp"

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "log.hpp"
#include "telemetry.hpp"

namespace pcclt::master {

using proto::PacketType;

bool Master::launch() {
    // bind FIRST: a second master accidentally started on a live master's
    // port+journal must fail here, BEFORE Journal::open rename-clobbers the
    // running master's journal file out from under it
    if (!listener_.listen(port_)) {
        PLOG(kError) << "master: cannot bind port " << port_;
        return false;
    }
    if (!journal_path_.empty()) {
        // open (and rehydrate from) the journal before accept()ing clients
        // (connections queue in the TCP backlog until run_async below): the
        // first hello must already see the restored world + bumped epoch
        if (!journal_.open(journal_path_)) {
            PLOG(kError) << "master: cannot open journal " << journal_path_;
            listener_.stop();
            return false;
        }
        state_.attach_journal(&journal_);
    }
    port_ = listener_.port();
    // trace correlation: stamp this incarnation's epoch into every event
    // the (possibly in-process) recorder captures from here on
    telemetry::Recorder::inst().set_epoch(state_.epoch());
    running_ = true;
    dispatcher_ = std::thread([this] { dispatcher_loop(); });

    // observability plane egress: plain-HTTP /metrics + /health when
    // PCCLT_MASTER_METRICS_PORT is set ("0" = kernel-assigned ephemeral
    // port, reported by metrics_port(); unset/empty = disabled)
    if (const char *mp = std::getenv("PCCLT_MASTER_METRICS_PORT");
        mp && mp[0]) {
        int want = std::atoi(mp);
        if (want >= 0 && want <= 65535 &&
            metrics_listener_.listen(static_cast<uint16_t>(want), 1)) {
            metrics_port_ = metrics_listener_.port();
            metrics_listener_.run_async([this](net::Socket sock) {
                serve_metrics_conn(std::move(sock));
            });
            PLOG(kInfo) << "metrics/health endpoint on port " << metrics_port_;
        } else {
            PLOG(kWarn) << "metrics endpoint disabled: cannot bind port " << mp;
        }
    }

    listener_.run_async([this](net::Socket sock) {
        // the reader handle must be assigned BEFORE any event from this conn
        // can reach the dispatcher: a probe connection that connects and
        // instantly closes (health checks, MasterProc restart polls) lets
        // the reader push its disconnect while `conn->reader` is still
        // empty — the dispatcher then sees joinable()==false, skips the
        // join, and the last reference later destroys a joinable thread
        // (std::terminate). Assign under conns_mu_ and make the reader's
        // first action acquire the same mutex: its events now happen-after
        // the assignment for anyone who locked conns_mu_ in between.
        MutexLock lk(conns_mu_);
        uint64_t id = next_conn_id_++;
        auto conn = std::make_shared<Conn>();
        conn->src_ip = sock.peer_addr();
        // family-tagged observed address; zero the ephemeral source port so
        // Addr equality (which compares ports) can't silently mismatch this
        // against advertised addresses, which store port 0
        conn->src_ip.port = 0;
        conn->sock = std::move(sock);
        conn->sock.set_keepalive();
        conns_[id] = conn;
        conn->reader = std::thread([this, id, conn] {
            { MutexLock gate(conns_mu_); } // wait out the assignment
            while (running_.load()) {
                auto f = net::recv_frame(conn->sock);
                if (!f) break;
                push_event({Event::kPacket, id, std::move(*f)});
            }
            push_event({Event::kDisconnect, id, {}});
        });
    });
    PLOG(kInfo) << "master listening on port " << port_;
    return true;
}

void Master::serve_metrics_conn(net::Socket sock) {
    // Minimal HTTP/1.0-style exchange, served inline on the accept thread:
    // read the request head (bounded, 2 s), answer one GET, close. The
    // render methods read only the health_mu_-published snapshot, so a
    // scrape never touches (or waits on) the dispatcher's state machine.
    char req[2048];
    size_t got = 0;
    // overall wall-clock deadline, not just per-recv: a client trickling
    // one byte per recv timeout would otherwise hold the accept thread
    // (and Master::interrupt's listener join) for the whole head buffer
    const auto head_deadline =
        std::chrono::steady_clock::now() + std::chrono::seconds(3);
    while (got < sizeof req - 1 &&
           std::chrono::steady_clock::now() < head_deadline) {
        ssize_t n = sock.recv_some(req + got, sizeof req - 1 - got, 1000);
        if (n <= 0) break;
        got += static_cast<size_t>(n);
        req[got] = 0;
        if (strstr(req, "\r\n\r\n") || strstr(req, "\n\n")) break;
    }
    req[got] = 0;
    std::string path = "/";
    if (strncmp(req, "GET ", 4) == 0) {
        const char *p = req + 4;
        const char *e = strchr(p, ' ');
        if (e) path.assign(p, e);
    }
    // split off the query string: /health?history=1 asks for the ring of
    // recent fleet snapshots alongside the live view
    std::string query;
    if (auto q = path.find('?'); q != std::string::npos) {
        query = path.substr(q + 1);
        path.resize(q);
    }
    const bool want_history = query.find("history=1") != std::string::npos;
    std::string body;
    const char *ctype = "text/plain; charset=utf-8";
    const char *status = "200 OK";
    if (path == "/metrics") {
        // Prometheus text exposition format 0.0.4
        body = state_.render_metrics();
        ctype = "text/plain; version=0.0.4; charset=utf-8";
    } else if (path == "/health" || path == "/health.json") {
        body = state_.render_health_json(want_history);
        ctype = "application/json";
    } else if (path == "/") {
        body = "pcclt master: /metrics (prometheus), /health (json), "
               "/health?history=1 (json + recent fleet snapshots)\n";
    } else {
        status = "404 Not Found";
        body = "not found\n";
    }
    char head[256];
    int hn = snprintf(head, sizeof head,
                      "HTTP/1.1 %s\r\nContent-Type: %s\r\n"
                      "Content-Length: %zu\r\nConnection: close\r\n\r\n",
                      status, ctype, body.size());
    if (hn > 0 && sock.send_all(head, static_cast<size_t>(hn)))
        sock.send_all(body.data(), body.size());
    sock.close();
}

void Master::push_event(Event ev) {
    {
        MutexLock lk(ev_mu_);
        events_.push_back(std::move(ev));
    }
    ev_cv_.notify_one();
}

void Master::apply_outbox(const std::vector<Outbox> &out) {
    for (const auto &o : out) {
        std::shared_ptr<Conn> conn;
        {
            MutexLock lk(conns_mu_);
            auto it = conns_.find(o.conn_id);
            if (it == conns_.end()) continue;
            conn = it->second;
        }
        net::send_frame(conn->sock, conn->write_mu, o.type, o.payload);
    }
    for (uint64_t id : state_.take_pending_closes()) {
        std::shared_ptr<Conn> conn;
        {
            MutexLock lk(conns_mu_);
            auto it = conns_.find(id);
            if (it == conns_.end()) continue;
            conn = it->second;
        }
        conn->sock.shutdown(); // reader thread will emit the disconnect event
    }
}

void Master::dispatcher_loop() {
    // the state machine's single-thread invariant (see the class marker in
    // master.hpp) is enforced here at runtime: reference THREAD_GUARD
    // discipline
    PCCLT_THREAD_GUARD(state_guard_);
    // limbo expiry (HA) must run on a steady deadline, not only when the
    // queue drains: a busy group's event stream would otherwise starve the
    // tick and freeze rounds on a never-resuming session forever
    auto next_tick = std::chrono::steady_clock::now() + std::chrono::milliseconds(100);
    while (running_.load()) {
        Event ev;
        bool have_ev = false;
        {
            // manual wait (no predicate lambda: a lambda body does not
            // inherit the caller's lock set under -Wthread-safety); a
            // spurious wake just re-runs the tick check and loops
            MutexLock lk(ev_mu_);
            if (events_.empty() && running_.load())
                ev_cv_.wait_for(ev_mu_, std::chrono::milliseconds(100));
            if (!events_.empty()) {
                ev = std::move(events_.front());
                events_.pop_front();
                have_ev = true;
            }
        }
        if (auto now = std::chrono::steady_clock::now(); now >= next_tick) {
            apply_outbox(state_.on_tick());
            next_tick = now + std::chrono::milliseconds(100);
        }
        if (!have_ev) continue;

        std::vector<Outbox> out;
        if (ev.kind == Event::kDisconnect) {
            out = state_.on_disconnect(ev.conn_id);
            std::shared_ptr<Conn> conn;
            {
                MutexLock lk(conns_mu_);
                auto it = conns_.find(ev.conn_id);
                if (it != conns_.end()) {
                    conn = it->second;
                    conns_.erase(it);
                }
            }
            if (conn) {
                conn->sock.close();
                // join, never detach: the reader's last act was pushing this
                // very disconnect event, so it is instants from exiting — a
                // detached reader could still be inside push_event when the
                // Master is destroyed, racing the condvar's destruction
                if (conn->reader.joinable()) conn->reader.join();
            }
        } else {
            net::Addr src_ip{};
            {
                MutexLock lk(conns_mu_);
                auto it = conns_.find(ev.conn_id);
                if (it != conns_.end()) src_ip = it->second->src_ip;
            }
            const auto &p = ev.frame.payload;
            try {
                switch (ev.frame.type) {
                case PacketType::kC2MHello: {
                    auto h = proto::HelloC2M::decode(p);
                    if (h) out = state_.on_hello(ev.conn_id, src_ip, *h);
                    break;
                }
                case PacketType::kC2MSessionResume: {
                    auto s = proto::SessionResumeC2M::decode(p);
                    if (s) out = state_.on_session_resume(ev.conn_id, src_ip, *s);
                    break;
                }
                case PacketType::kC2MTopologyUpdate:
                    out = state_.on_topology_update(ev.conn_id);
                    break;
                case PacketType::kC2MPeersPendingQuery:
                    out = state_.on_peers_pending_query(ev.conn_id);
                    break;
                case PacketType::kC2MP2PEstablished: {
                    wire::Reader r(p);
                    uint64_t revision = r.u64();
                    bool ok = r.u8() != 0;
                    uint32_t n = r.u32();
                    std::vector<Uuid> failed;
                    for (uint32_t i = 0; i < n; ++i) failed.push_back(proto::get_uuid(r));
                    out = state_.on_p2p_established(ev.conn_id, revision, ok, failed);
                    break;
                }
                case PacketType::kC2MCollectiveInit: {
                    auto ci = proto::CollectiveInit::decode(p);
                    if (ci) out = state_.on_collective_init(ev.conn_id, *ci);
                    break;
                }
                case PacketType::kC2MCollectiveComplete: {
                    wire::Reader r(p);
                    uint64_t tag = r.u64();
                    bool aborted = r.u8() != 0;
                    out = state_.on_collective_complete(ev.conn_id, tag, aborted);
                    break;
                }
                case PacketType::kC2MSharedStateSync: {
                    auto s = proto::SharedStateSyncC2M::decode(p);
                    if (s) out = state_.on_shared_state_sync(ev.conn_id, *s);
                    break;
                }
                case PacketType::kC2MSharedStateDistDone:
                    out = state_.on_dist_done(ev.conn_id);
                    break;
                case PacketType::kC2MSyncKeyDone: {
                    auto d = proto::SyncKeyDoneC2M::decode(p);
                    if (d) out = state_.on_sync_key_done(ev.conn_id, *d);
                    break;
                }
                case PacketType::kC2MOptimizeTopology:
                    out = state_.on_optimize(ev.conn_id);
                    break;
                case PacketType::kC2MBandwidthReport: {
                    wire::Reader r(p);
                    Uuid to = proto::get_uuid(r);
                    double mbps = r.f64();
                    out = state_.on_bandwidth_report(ev.conn_id, to, mbps);
                    break;
                }
                case PacketType::kC2MOptimizeWorkDone:
                    out = state_.on_optimize_work_done(ev.conn_id);
                    break;
                case PacketType::kC2MTelemetryDigest: {
                    auto d = proto::TelemetryDigestC2M::decode(p);
                    if (d) out = state_.on_telemetry_digest(ev.conn_id, *d);
                    break;
                }
                default:
                    PLOG(kWarn) << "master: unknown packet type 0x" << std::hex
                                << ev.frame.type;
                }
            } catch (const std::exception &e) {
                PLOG(kError) << "master: malformed packet type 0x" << std::hex
                             << ev.frame.type << ": " << e.what();
            }
        }
        apply_outbox(out);
    }
}

void Master::interrupt() {
    if (!running_.exchange(false)) return;
    listener_.stop();
    metrics_listener_.stop();
    {
        MutexLock lk(conns_mu_);
        for (auto &[_, c] : conns_) c->sock.shutdown();
    }
    ev_cv_.notify_all();
}

void Master::join() {
    if (dispatcher_.joinable()) dispatcher_.join();
    std::map<uint64_t, std::shared_ptr<Conn>> conns;
    {
        MutexLock lk(conns_mu_);
        conns.swap(conns_);
    }
    for (auto &[_, c] : conns) {
        c->sock.shutdown();
        if (c->reader.joinable()) c->reader.join();
        c->sock.close();
    }
}

} // namespace pcclt::master
