#include "bandwidth.hpp"

namespace pcclt::master {

void BandwidthStore::store(const proto::Uuid &from, const proto::Uuid &to, double mbps) {
    mbps_[from][to] = mbps;
}

std::optional<double> BandwidthStore::get(const proto::Uuid &from,
                                          const proto::Uuid &to) const {
    auto it = mbps_.find(from);
    if (it == mbps_.end()) return std::nullopt;
    auto jt = it->second.find(to);
    if (jt == it->second.end()) return std::nullopt;
    return jt->second;
}

std::vector<std::pair<proto::Uuid, proto::Uuid>>
BandwidthStore::missing_edges(const std::vector<proto::Uuid> &peers) const {
    std::vector<std::pair<proto::Uuid, proto::Uuid>> out;
    for (const auto &a : peers)
        for (const auto &b : peers) {
            if (a == b) continue;
            if (!get(a, b)) out.emplace_back(a, b);
        }
    return out;
}

void BandwidthStore::forget(const proto::Uuid &peer) {
    mbps_.erase(peer);
    for (auto &[_, m] : mbps_) m.erase(peer);
}

} // namespace pcclt::master
