// net::Addr — the dual-family endpoint POD, split from sockets.hpp so the
// wire-format layer (protocol.hpp) can carry addresses without pulling in
// the whole socket/multiplex machinery.
#pragma once

#include <array>
#include <cstdint>
#include <optional>
#include <string>

namespace pcclt::net {

// Dual-family endpoint. Field order keeps v4 aggregate inits
// (`Addr{ip, port}`) working; v6 carries its 16 bytes network-order in
// `ip6` with `family == 6`. Reference parity: ccoip_inet.h:15-29 carries
// both families in its inet types; here they also ROUTE (connect, listen,
// peer_addr, and the PCCP/2 family-tagged wire all speak both).
struct Addr {
    uint32_t ip = 0; // v4, host byte order
    uint16_t port = 0;
    uint8_t family = 4; // 4 or 6
    std::array<uint8_t, 16> ip6{}; // v6, network byte order
    std::string str() const; // defined in sockets.cpp
    // accepts dotted v4, plain v6 ("::1"), or bracketed v6 ("[::1]")
    static std::optional<Addr> parse(const std::string &ip_str, uint16_t port);
    bool operator==(const Addr &o) const {
        return family == o.family && port == o.port &&
               (family == 6 ? ip6 == o.ip6 : ip == o.ip);
    }
};

} // namespace pcclt::net
