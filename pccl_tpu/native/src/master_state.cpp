#include "master_state.hpp"

#include <sys/stat.h>

#include <algorithm>
#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <ctime>

#include "atsp.hpp"
#include "log.hpp"
#include "sockets.hpp"
#include "telemetry.hpp"
#include "uring.hpp"
#include "version.hpp"

namespace pcclt::master {

using proto::PacketType;

namespace {
proto::PeerEndpoint endpoint_of(const ClientInfo &c) {
    return proto::PeerEndpoint{c.uuid, c.ip, c.p2p_port, c.bench_port, c.peer_group};
}

// ---- observability-plane tunables (docs/03, docs/09) ----

double straggler_fraction() {
    static const double v = [] {
        if (const char *e = std::getenv("PCCLT_STRAGGLER_FRACTION")) {
            double f = std::atof(e);
            if (f > 0 && f < 1) return f;
        }
        return 0.5;
    }();
    return v;
}

bool straggler_reopt_enabled() {
    static const bool v = [] {
        const char *e = std::getenv("PCCLT_STRAGGLER_REOPT");
        return e && e[0] == '1';
    }();
    return v;
}

// edges quieter than this carry no meaningful throughput sample — an idle
// edge must never read as "degraded"
constexpr double kMinActiveMbps = 0.05;

// the receiver must have spent at least this fraction of the interval
// BLOCKED on the edge for its throughput to count as a capacity sample:
// achieved rate only witnesses degradation when the wire (not compute or
// a light duty cycle) is pacing the run — without this gate any healthy
// link carrying sparse traffic would read as a straggler, and with
// PCCLT_STRAGGLER_REOPT=1 its load-limited rate would corrupt the matrix
constexpr double kMinStallRatio = 0.15;

// ingest-queue digest cap. Re-read per enqueue (a linear environ scan is
// noise next to a digest decode): tests flip it at runtime.
size_t digest_queue_cap() {
    if (const char *e = std::getenv("PCCLT_DIGEST_QUEUE_CAP")) {
        long v = std::atol(e);
        if (v > 0) return static_cast<size_t>(v);
    }
    return 4096;
}

} // namespace

ClientInfo *MasterState::by_conn(uint64_t conn) {
    auto it = clients_.find(conn);
    return it == clients_.end() ? nullptr : &it->second;
}

ClientInfo *MasterState::by_uuid(const Uuid &u) {
    for (auto &[_, c] : clients_)
        if (c.uuid == u) return &c;
    return nullptr;
}

std::vector<ClientInfo *> MasterState::accepted_clients() {
    std::vector<ClientInfo *> v;
    for (auto &[_, c] : clients_)
        if (c.accepted) v.push_back(&c);
    return v;
}

std::vector<ClientInfo *> MasterState::group_members(uint32_t group) {
    std::vector<ClientInfo *> v;
    for (auto &[_, c] : clients_)
        if (c.accepted && c.peer_group == group) v.push_back(&c);
    return v;
}

size_t MasterState::world_size() const {
    size_t n = 0;
    for (auto &[_, c] : clients_)
        if (c.accepted) ++n;
    return n;
}

std::vector<Uuid> MasterState::build_ring(uint32_t group) {
    // keep the existing (possibly ATSP-optimized) order for surviving members,
    // append newcomers in join order
    auto members = group_members(group);
    std::vector<Uuid> ring;
    for (const auto &u : groups_[group].ring) {
        for (auto *m : members)
            if (m->uuid == u) {
                ring.push_back(u);
                break;
            }
    }
    for (auto *m : members)
        if (std::find(ring.begin(), ring.end(), m->uuid) == ring.end())
            ring.push_back(m->uuid);
    groups_[group].ring = ring;
    return ring;
}

void MasterState::kick(std::vector<Outbox> &out, ClientInfo &c, const std::string &reason) {
    PLOG(kWarn) << "kicking client " << proto::uuid_str(c.uuid) << ": " << reason;
    if (telemetry::Recorder::inst().on())
        telemetry::Recorder::inst().instant("membership", "master_kick",
                                            "group", c.peer_group, nullptr, 0,
                                            telemetry::intern(reason));
    wire::Writer w;
    w.str(reason);
    out.push_back({c.conn_id, PacketType::kM2CKicked, w.take()});
    pending_closes_.push_back(c.conn_id);
    // a kick is the classic "it just stopped" incident (docs/09): order a
    // fleet black-box capture while the evidence is still in the rings
    maybe_incident(out, "kick:" + reason, c.peer_group);
    // removal + consensus re-checks happen when the dispatcher closes the
    // conn and feeds the disconnect event back in.
}

std::vector<uint64_t> MasterState::take_pending_closes() {
    auto v = std::move(pending_closes_);
    pending_closes_.clear();
    return v;
}

// ---------- HA: journal rehydration + session resume ----------

void MasterState::journal_client(const ClientInfo &c) {
    if (!journal_) return;
    journal::ClientRec rec;
    rec.uuid = c.uuid;
    rec.peer_group = c.peer_group;
    rec.ip = c.ip.str();
    rec.p2p_port = c.p2p_port;
    rec.ss_port = c.ss_port;
    rec.bench_port = c.bench_port;
    rec.accepted = c.accepted;
    journal_->record_client(rec);
}

bool MasterState::group_frozen(uint32_t group) const {
    for (const auto &[_, l] : limbo_)
        if (l.info.peer_group == group) return true;
    return false;
}

void MasterState::attach_journal(journal::Journal *j) {
    journal_ = j;
    if (!j) return;
    epoch_ = j->epoch();
    const auto &r = j->restored();
    topology_revision_ = r.topology_revision;
    next_seq_ = std::max<uint64_t>(1, r.next_seq);
    seq_bound_ = next_seq_;
    int limbo_ms = 15'000;
    if (const char *e = std::getenv("PCCLT_MASTER_LIMBO_MS")) {
        int v = std::atoi(e);
        if (v > 0) limbo_ms = v;
    }
    auto deadline =
        std::chrono::steady_clock::now() + std::chrono::milliseconds(limbo_ms);
    for (const auto &[u, rc] : r.clients) {
        ClientInfo c;
        c.uuid = rc.uuid;
        c.conn_id = 0;
        c.peer_group = rc.peer_group;
        if (auto a = net::Addr::parse(rc.ip, 0)) c.ip = *a;
        c.p2p_port = rc.p2p_port;
        c.ss_port = rc.ss_port;
        c.bench_port = rc.bench_port;
        c.accepted = rc.accepted;
        limbo_[u] = LimboClient{c, deadline};
    }
    for (const auto &[gid, gr] : r.groups) {
        auto &g = groups_[gid];
        g.last_revision = gr.last_revision;
        g.revision_initialized = gr.revision_initialized;
        g.ring = gr.ring;
        // schedule plane (docs/12): the synthesized table survives the
        // restart next to the ring it was costed against, so the first
        // post-restore commence stamps the same algorithm the fleet was
        // already running — no ring-everything regression window
        if (!gr.schedule.empty()) {
            if (auto t = sched::Table::decode(gr.schedule)) {
                g.schedule = std::move(*t);
                g.sched_version = g.schedule.version;
                IngestItem it;
                it.kind = IngestItem::kSchedule;
                it.group = gid;
                it.sched = gr.schedule;
                enqueue(std::move(it));
            }
        }
    }
    for (const auto &b : r.bandwidth) {
        bandwidth_.store(b.from, b.to, b.mbps);
        IngestItem it;
        it.kind = IngestItem::kBandwidth;
        it.peer = b.from;
        it.to = b.to;
        it.mbps = b.mbps;
        enqueue(std::move(it));
    }
    replay_ops_ = r.op_done;
    if (!limbo_.empty())
        PLOG(kInfo) << "journal restore: epoch " << epoch_ << ", "
                    << limbo_.size() << " sessions in limbo awaiting resume ("
                    << limbo_ms << " ms window)";
    telemetry::Recorder::inst().instant("membership", "master_restore", "epoch",
                                        epoch_, "limbo", limbo_.size());
}

std::vector<Outbox> MasterState::on_session_resume(uint64_t conn,
                                                   const net::Addr &src_ip,
                                                   const proto::SessionResumeC2M &s) {
    std::vector<Outbox> out;
    proto::SessionResumeAck ack;
    ack.epoch = epoch_;
    auto it = limbo_.find(s.uuid);
    if (it == limbo_.end()) {
        // not a rehydrated session: either this master has no journal, the
        // limbo window expired, or the uuid is already (re)bound — the
        // client must fall back to a fresh registration
        ack.ok = 0;
        ack.reason = by_uuid(s.uuid) ? "session already bound"
                                     : "unknown session (no journaled state)";
        out.push_back({conn, PacketType::kM2CSessionResumeAck, ack.encode()});
        return out;
    }
    ClientInfo c = it->second.info;
    limbo_.erase(it);
    c.conn_id = conn;
    // refresh the observed address + re-advertised ports: the client
    // process survived, but its NAT mapping may not have
    c.ip = src_ip;
    if (s.p2p_port) c.p2p_port = s.p2p_port;
    if (s.ss_port) c.ss_port = s.ss_port;
    if (s.bench_port) c.bench_port = s.bench_port;
    if (!s.adv_ip.empty())
        if (auto a = net::Addr::parse(s.adv_ip, 0)) c.ip = *a;
    auto &g = groups_[c.peer_group];
    if (s.last_revision > g.last_revision) {
        // the client witnessed a sync Done the journal missed (crash between
        // emitting Done and the append reaching disk): the client can only
        // have seen a Done this master emitted, so trust it — this restores
        // the one-increment invariant for the whole group
        g.last_revision = s.last_revision;
        g.revision_initialized = true;
        if (journal_)
            journal_->record_group(c.peer_group, g.last_revision, true);
    }
    ack.ok = 1;
    ack.last_revision = g.last_revision;
    clients_[conn] = c;
    enqueue_endpoint_add(c);
    journal_client(c);
    PLOG(kInfo) << "session resumed: " << proto::uuid_str(c.uuid) << " group "
                << c.peer_group << " (" << limbo_.size() << " still in limbo)";
    telemetry::Recorder::inst().instant("membership", "master_session_resume",
                                        "group", c.peer_group, "limbo",
                                        limbo_.size());
    out.push_back({c.conn_id, PacketType::kM2CSessionResumeAck, ack.encode()});
    // last limbo session back: unfreeze every consensus round
    if (limbo_.empty()) recheck_all(out);
    return out;
}

std::vector<Outbox> MasterState::on_tick() {
    std::vector<Outbox> out;
    // keep the published health summary fresh even while no digests flow
    // (membership changes between digests must show up in /health promptly)
    publish_health_summary();
    // straggler transitions the fold thread detected since the last tick:
    // the parts that need the consensus state — matrix rewrite + journal,
    // REOPT kick-off, incident broadcast — run here, within one tick
    // (<=100 ms) of the digest that witnessed the degradation
    std::vector<StragglerAction> acts;
    {
        MutexLock lk(ingest_mu_);
        acts.swap(pending_actions_);
    }
    for (const auto &a : acts) {
        if (straggler_reopt_enabled() && a.has_to) {
            // telemetry-refreshed matrix: the measured (degraded) rate
            // replaces the stale probe value — in the WITNESSED direction:
            // remote -> reporter for the rate detector, reporter -> remote
            // for a watchdog CONFIRM — so the background ATSP pass actually
            // routes around the slow hop; the next optimize round adopts
            // the improved ring (check_optimize moonshot path)
            const Uuid &from = a.outbound_confirm ? a.from_raw : a.to_raw;
            const Uuid &to = a.outbound_confirm ? a.to_raw : a.from_raw;
            bandwidth_.store(from, to, a.measured_mbps);
            if (journal_) journal_->record_bandwidth(from, to, a.measured_mbps);
            IngestItem bw;
            bw.kind = IngestItem::kBandwidth;
            bw.peer = from;
            bw.to = to;
            bw.mbps = a.measured_mbps;
            enqueue(std::move(bw));
            request_straggler_reopt(a.group);
        }
        // a watchdog CONFIRM means the data plane is already relaying
        // around a dead-slow hop mid-collective — exactly the evidence
        // that evaporates by the time anyone looks: capture it NOW
        if (a.outbound_confirm)
            maybe_incident(out, "watchdog_confirm:" + a.from_uuid + "->" +
                                    a.endpoint,
                           a.group);
    }
    if (limbo_.empty()) return out;
    auto now = std::chrono::steady_clock::now();
    std::vector<Uuid> expired;
    for (const auto &[u, l] : limbo_)
        if (now >= l.deadline) expired.push_back(u);
    for (const auto &u : expired) {
        ClientInfo gone = limbo_[u].info;
        limbo_.erase(u);
        if (journal_) journal_->record_client_remove(u);
        PLOG(kWarn) << "limbo session " << proto::uuid_str(u)
                    << " expired without resume; treating as departed";
        telemetry::Recorder::inst().instant("membership", "master_limbo_expired",
                                            "group", gone.peer_group, "world",
                                            world_size());
        maybe_incident(out, "limbo_expiry", gone.peer_group);
        remove_client(out, gone);
    }
    return out;
}

// ---------- join ----------

std::vector<Outbox> MasterState::on_hello(uint64_t conn, const net::Addr &src_ip,
                                          const proto::HelloC2M &h) {
    std::vector<Outbox> out;
    if (h.wire_rev != proto::kWireRev) {
        // mixed-version peer: reject with a diagnosable error instead of
        // letting it misparse every later packet (a rev-1 client's hello
        // has no rev byte, so this reads its peer-group high byte = 0)
        PLOG(kWarn) << "rejecting client on conn " << conn << ": wire rev "
                    << int(h.wire_rev) << " != PCCP/" << int(proto::kWireRev);
        wire::Writer w;
        w.u8(0);
        w.str("wire protocol revision mismatch (master speaks PCCP/" +
              std::to_string(int(proto::kWireRev)) + ")");
        out.push_back({conn, PacketType::kM2CWelcome, w.take()});
        return out;
    }
    ClientInfo c;
    c.uuid = proto::uuid_random();
    c.conn_id = conn;
    c.peer_group = h.peer_group;
    c.ip = src_ip;
    c.p2p_port = h.p2p_port;
    c.ss_port = h.ss_port;
    c.bench_port = h.bench_port;
    c.observer = h.observer != 0;
    if (!h.adv_ip.empty()) {
        if (auto a = net::Addr::parse(h.adv_ip, 0)) c.ip = *a;
    }
    clients_[conn] = c;
    enqueue_endpoint_add(c);
    if (c.observer) {
        // telemetry-only control session: never pending, never admitted,
        // never journaled — a thousand of these must not open (or wedge)
        // an admission round real peers are waiting on
        PLOG(kInfo) << "observer session " << proto::uuid_str(c.uuid)
                    << " attached (telemetry-only), sessions="
                    << clients_.size();
        wire::Writer w;
        w.u8(1);
        proto::put_uuid(w, c.uuid);
        w.str("welcome (observer)");
        w.u64(epoch_);
        out.push_back({conn, PacketType::kM2CWelcome, w.take()});
        return out;
    }
    PLOG(kInfo) << "client " << proto::uuid_str(c.uuid) << " joined (pending), group "
                << c.peer_group << ", world=" << world_size();
    telemetry::Recorder::inst().instant("membership", "master_join_pending",
                                        "group", c.peer_group, "world",
                                        world_size());

    wire::Writer w;
    w.u8(1);
    proto::put_uuid(w, c.uuid);
    w.str("welcome");
    w.u64(epoch_); // master epoch (HA); older clients simply don't read it
    out.push_back({conn, PacketType::kM2CWelcome, w.take()});
    check_topology(out);
    return out;
}

// ---------- topology update / peer accept round ----------

// Deadlock tie-break. A topology vote only completes when EVERY accepted
// client has voted, and a collective/sync round only commences when every
// group member has initiated. When peers race a joiner's admission (one
// sees are_peers_pending() before the join lands, the other after), one
// peer parks in the vote while another parks in the commence wait — a
// cross-wait neither side can resolve. The master breaks the tie in favor
// of the IN-FLIGHT round: voters in a group with outstanding initiates are
// sent kM2CTopologyDeferred (their update_topology returns no-op and the
// app's admit-pending loop re-votes after its next collective, when the
// whole group can reach the vote together).
void MasterState::defer_topology_voters(std::vector<Outbox> &out, uint32_t group) {
    for (auto *m : group_members(group))
        if (m->vote_topology) {
            m->vote_topology = false;
            out.push_back({m->conn_id, PacketType::kM2CTopologyDeferred, {}});
            PLOG(kDebug) << "topology vote of " << proto::uuid_str(m->uuid)
                         << " deferred: group " << group << " is mid-round";
        }
}

// true when `c`'s group has a round in flight that `c` is not part of yet —
// voting now would park `c` while the round waits for it (see above)
bool MasterState::group_mid_round(const ClientInfo &c) {
    auto git = groups_.find(c.peer_group);
    if (git == groups_.end()) return false;
    for (auto &[tag, op] : git->second.ops)
        if (!op.commenced && !op.initiated.empty() && !op.initiated.count(c.uuid))
            return true;
    if (!git->second.sync_in_flight && !c.sync_req)
        for (auto *m : group_members(c.peer_group))
            if (m->uuid != c.uuid && m->sync_req) return true;
    return false;
}

std::vector<Outbox> MasterState::on_topology_update(uint64_t conn) {
    std::vector<Outbox> out;
    auto *c = by_conn(conn);
    if (!c) return out;
    if (c->accepted && group_mid_round(*c)) {
        // the group is already committing to a collective/sync round this
        // voter is not part of: parking the vote would deadlock (cross-wait
        // with the commence) — decline, the caller re-votes next loop
        out.push_back({c->conn_id, PacketType::kM2CTopologyDeferred, {}});
        return out;
    }
    c->vote_topology = true;
    check_topology(out);
    return out;
}

std::vector<Outbox> MasterState::on_peers_pending_query(uint64_t conn) {
    std::vector<Outbox> out;
    bool pending = false;
    for (auto &[_, c] : clients_)
        if (!c.accepted && !c.observer) pending = true;
    wire::Writer w;
    w.u8(pending ? 1 : 0);
    out.push_back({conn, PacketType::kM2CPeersPendingReply, w.take()});
    return out;
}

void MasterState::check_topology(std::vector<Outbox> &out) {
    if (establish_in_flight_ || optimize_in_flight_) return;
    // HA freeze: sessions rehydrated from the journal have not re-attached
    // yet; a round run without them would drop their endpoints from every
    // peer list and tear the surviving mesh down (limbo resolves by resume
    // or expiry, both of which re-check)
    if (!limbo_.empty()) return;
    auto acc = accepted_clients();
    // observers are telemetry-only sessions: never pending, never admitted
    bool any_pending = false;
    for (auto &[_, c] : clients_)
        if (!c.accepted && !c.observer) any_pending = true;
    if (acc.empty() && !any_pending) return;
    // a round runs when every accepted client has voted (trivially true when
    // none are accepted yet — a pending-only world admits immediately)
    for (auto *a : acc)
        if (!a->vote_topology) return;
    for (auto &[_, c] : clients_)
        if (!c.accepted && !c.observer) {
            c.accepted = true;
            // An admitted joiner is by definition parked in its establish
            // loop awaiting this round's completion: give it a STANDING
            // vote so a round that fails (member crash mid-round,
            // unreachable-peer kick) immediately re-opens for it. Without
            // this, a failed admission round whose only voters departed
            // strands the joiner accepted-but-unconfirmed until its 120 s
            // conn-info timeout fails the whole connect() — found by the
            // pcclt-verify model checker (scenario collective_crash).
            // Safe: votes are only consulted between rounds, and no
            // collective/sync can be mid-commence while a round is in
            // flight (the all-accepted-must-vote gate plus the
            // group_mid_round deferral exclude it), so this vote can
            // never be deferred away while the joiner is parked.
            c.vote_topology = true;
            c.admission_vote = true;
            journal_client(c);
            PLOG(kInfo) << "admitted " << proto::uuid_str(c.uuid) << " to group "
                        << c.peer_group;
        }
    ++topology_revision_;
    if (journal_) journal_->record_topology_revision(topology_revision_);
    establish_in_flight_ = true;
    round_members_.clear();
    std::set<uint32_t> groups;
    for (auto &[_, c] : clients_) {
        if (c.observer) continue;
        round_members_.insert(c.uuid);
        c.reported_establish = false;
        c.establish_ok = false;
        c.establish_failed.clear();
        groups.insert(c.peer_group);
    }
    for (uint32_t g : groups) {
        build_ring(g);
        if (journal_) journal_->record_ring(g, groups_[g].ring);
    }

    for (auto &[_, c] : clients_) {
        if (c.observer) continue;
        proto::P2PConnInfo info;
        info.revision = topology_revision_;
        for (auto &[_, o] : clients_)
            if (!o.observer && o.uuid != c.uuid)
                info.peers.push_back(endpoint_of(o));
        info.ring = groups_[c.peer_group].ring;
        // trailing schedule table: a (re)joining peer adopts ring order and
        // schedule in one epoch-safe step (docs/12)
        if (!groups_[c.peer_group].schedule.empty())
            info.sched = groups_[c.peer_group].schedule.encode();
        out.push_back({c.conn_id, PacketType::kM2CP2PConnInfo, info.encode()});
    }
}

std::vector<Outbox> MasterState::on_p2p_established(uint64_t conn, uint64_t revision,
                                                    bool ok,
                                                    const std::vector<Uuid> &failed) {
    std::vector<Outbox> out;
    auto *c = by_conn(conn);
    if (!c) return out;
    if (revision != topology_revision_) return out; // stale-round report
    c->reported_establish = true;
    c->establish_ok = ok;
    c->establish_failed = failed;
    check_establish(out);
    return out;
}

void MasterState::check_establish(std::vector<Outbox> &out) {
    if (!establish_in_flight_) return;
    for (auto &[_, c] : clients_)
        if (c.accepted && !c.reported_establish) return;

    // a round member departed mid-round? force retry (newly-arrived pending
    // clients are NOT round members and do not disturb the round)
    size_t present = 0;
    for (auto &[_, c] : clients_)
        if (round_members_.count(c.uuid)) ++present;
    bool membership_stable = present == round_members_.size();

    // peers reported unreachable by anyone get kicked
    std::set<Uuid> unreachable;
    bool all_ok = true;
    for (auto &[_, c] : clients_) {
        if (!c.accepted) continue; // pending newcomers are not in the round
        if (!c.establish_ok) all_ok = false;
        for (const auto &f : c.establish_failed) unreachable.insert(f);
    }

    establish_in_flight_ = false;
    if (all_ok && membership_stable && unreachable.empty()) {
        for (auto &[_, c] : clients_) {
            if (!c.accepted) continue; // pending clients are not in this round
            c.vote_topology = false;
            c.admission_vote = false; // the round the joiner needed completed
            c.reported_establish = false;
            wire::Writer w;
            w.u64(topology_revision_);
            w.u8(1);
            const auto &ring = groups_[c.peer_group].ring;
            w.u32(static_cast<uint32_t>(ring.size()));
            for (const auto &u : ring) proto::put_uuid(w, u);
            out.push_back({c.conn_id, PacketType::kM2CP2PEstablishedResp, w.take()});
        }
        PLOG(kInfo) << "topology round " << topology_revision_ << " complete, world="
                    << world_size();
        telemetry::Recorder::inst().instant("membership",
                                            "master_topology_complete",
                                            "revision", topology_revision_,
                                            "world", world_size());
    } else {
        // kick unreachable peers; everyone else retries
        std::vector<ClientInfo *> to_kick;
        for (auto &[_, c] : clients_)
            if (unreachable.count(c.uuid)) to_kick.push_back(&c);
        for (auto *c : to_kick) kick(out, *c, "unreachable by peers");
        for (auto &[_, c] : clients_) {
            if (!c.accepted || unreachable.count(c.uuid)) continue;
            c.reported_establish = false;
            wire::Writer w;
            w.u64(topology_revision_);
            w.u8(0);
            w.u32(0);
            out.push_back({c.conn_id, PacketType::kM2CP2PEstablishedResp, w.take()});
        }
        PLOG(kWarn) << "topology round " << topology_revision_ << " failed; clients retry";
        // votes are still standing: immediately open the next round so joiners
        // that raced into the failed round get admitted now
        check_topology(out);
    }
}

// ---------- collectives ----------

std::vector<Outbox> MasterState::on_collective_init(uint64_t conn,
                                                    const proto::CollectiveInit &ci) {
    std::vector<Outbox> out;
    auto *c = by_conn(conn);
    if (!c || !c->accepted) return out;
    // Verdict replay (HA): this op COMPLETED under the previous master
    // incarnation, but this member's Done was lost in the crash, so it is
    // retrying. Its peers saw the Done and moved on — forming a fresh op
    // here would cross-wait the group forever (model-checker finding,
    // scenario restart_resume). Replay the journaled verdict instead: the
    // member's data plane already ran to completion back then. Gated on
    // ci.retry: tags are app-reused across steps, and replaying a stale
    // verdict into a member's NEXT op on the same tag would silently skip
    // that op with stale data (a member whose Done landed pre-crash is in
    // the owed set too — nothing acks Dones).
    auto rit = replay_ops_.find({c->peer_group, ci.tag});
    if (rit != replay_ops_.end() && rit->second.members.count(c->uuid) &&
        !(ci.retry && ci.retry_seq == rit->second.seq)) {
        // Any OTHER init of this (group, tag) from an owed member proves
        // it is past the recorded op: ops on one tag are serialized per
        // member, so a fresh init — or a retry of a DIFFERENT incarnation
        // (mismatched seq, including seq 0 = died pre-commence, where the
        // recorded completion cannot be its op) — means its Done landed or
        // its attempt post-dates the record. Consume the owed entry so the
        // stale-verdict window closes at the member's next op instead of
        // lingering across epochs (code-review catch).
        if (journal_)
            journal_->record_op_done_consumed(c->peer_group, ci.tag, c->uuid);
        rit->second.members.erase(c->uuid);
        if (rit->second.members.empty()) replay_ops_.erase(rit);
    }
    rit = replay_ops_.find({c->peer_group, ci.tag});
    if (ci.retry && rit != replay_ops_.end() &&
        rit->second.members.count(c->uuid) &&
        ci.retry_seq == rit->second.seq) {
        wire::Writer w;
        w.u64(ci.tag);
        w.u8(rit->second.any_aborted ? 1 : 0);
        // trailing world (op size at commence): only replayed verdicts
        // carry it; normal abort readers never look this far
        w.u32(rit->second.world);
        out.push_back({conn, PacketType::kM2CCollectiveAbort, w.take()});
        wire::Writer w2;
        w2.u64(ci.tag);
        out.push_back({conn, PacketType::kM2CCollectiveDone, w2.take()});
        // deliberately NOT consumed here: journaling consumption before the
        // packets actually reach the member would strand it if we die in
        // between, and replaying twice is harmless (idempotent verdict).
        // The owed entry is consumed by the member's next NON-matching init
        // above — which is the proof the replay landed (code-review catch).
        PLOG(kInfo) << "replayed pre-epoch collective verdict (tag " << ci.tag
                    << ") to " << proto::uuid_str(c->uuid);
        return out;
    }
    auto &g = groups_[c->peer_group];
    auto it = g.ops.find(ci.tag);
    if (it == g.ops.end()) {
        CollectiveOp op;
        op.params = ci;
        g.ops[ci.tag] = op;
        it = g.ops.find(ci.tag);
    } else if (it->second.params.count != ci.count ||
               it->second.params.dtype != ci.dtype || it->second.params.op != ci.op ||
               it->second.params.aux != ci.aux) {
        // aux is part of the matched-parameters contract (docs/12): a
        // broadcast where members disagree on the root slot must kick like
        // a count/dtype mismatch, not silently pick one member's root
        kick(out, *c, "collective op parameter mismatch");
        return out;
    }
    it->second.initiated.insert(c->uuid);
    check_collective(out, c->peer_group, ci.tag);
    // the op is waiting on members that may be parked in a topology vote —
    // release them or neither the vote nor the commence can ever complete
    if (!it->second.commenced) defer_topology_voters(out, c->peer_group);
    return out;
}

void MasterState::check_collective(std::vector<Outbox> &out, uint32_t group, uint64_t tag) {
    auto git = groups_.find(group);
    if (git == groups_.end()) return;
    auto oit = git->second.ops.find(tag);
    if (oit == git->second.ops.end()) return;
    auto &op = oit->second;
    auto members = group_members(group);

    if (!op.commenced) {
        // HA freeze: a group member is in limbo (master restarted, session
        // not yet resumed) — commencing without it would run the ring over a
        // membership the clients' rings disagree with
        if (group_frozen(group)) return;
        for (auto *m : members)
            if (!op.initiated.count(m->uuid)) return;
        op.commenced = true;
        op.seq = next_seq_++;
        if (journal_ && next_seq_ > seq_bound_) {
            // batched: journal a stride-ahead bound, not every seq
            seq_bound_ = next_seq_ + 1024;
            journal_->record_seq_bound(seq_bound_);
        }
        for (auto *m : members) op.members.insert(m->uuid);
        // ---- schedule stamp (docs/12): bind this op to ONE algorithm at
        // commence, so a racing kM2CScheduleUpdate can never split the
        // group. Trailing fields; pre-schedule clients stop after seq.
        const auto &gs = git->second;
        const uint32_t world = static_cast<uint32_t>(op.members.size());
        const sched::Coll coll = sched::coll_of(op.params.op);
        const uint64_t bytes =
            op.params.count * proto::dtype_size(op.params.dtype);
        sched::Algo algo = sched::Algo::kRing;
        uint32_t root = 0;
        if (coll == sched::Coll::kBroadcast && world > 0) {
            // aux carries the root SLOT (sorted-uuid order, the
            // user-visible rank space); the step programs address ring
            // indices — convert here, once, authoritatively. op.members is
            // an ordered set, i.e. already the sorted-uuid slot order.
            if (op.params.aux >= world)
                PLOG(kWarn) << "broadcast root slot " << op.params.aux
                            << " out of range for world " << world
                            << "; wrapping";
            auto sit = op.members.begin();
            std::advance(sit, static_cast<size_t>(op.params.aux % world));
            for (uint32_t i = 0; i < static_cast<uint32_t>(gs.ring.size()); ++i)
                if (gs.ring[i] == *sit) {
                    root = i;
                    break;
                }
        }
        if (sched::schedule_enabled()) {
            if (auto f = sched::forced_algo()) {
                // FORCE works at commence even before any optimize round
                // has synthesized a table (bench/test hook, docs/03)
                if (sched::algo_valid(coll, *f, world)) algo = *f;
            } else if (const sched::Entry *e =
                           gs.schedule.find(coll, sched::size_class(bytes))) {
                auto a = static_cast<sched::Algo>(e->algo);
                // re-validate against the COMMENCE world: membership may
                // have shifted since synthesis (butterfly needs a power of
                // two, relay roots must still be in range)
                if (sched::algo_valid(coll, a, world) &&
                    (a != sched::Algo::kRelayRing || e->root < world)) {
                    algo = a;
                    if (a == sched::Algo::kRelayRing) root = e->root;
                }
            }
        }
        // the only invalid DEFAULT: a2a's rotation tag grid caps at 64
        // ranks — stamp the mesh for bigger worlds (matches the
        // executor's deterministic fallback)
        if (!sched::algo_valid(coll, algo, world) &&
            coll == sched::Coll::kAllToAll)
            algo = sched::Algo::kMesh;
        for (auto *m : members) {
            wire::Writer w;
            w.u64(tag);
            w.u64(op.seq);
            w.u8(static_cast<uint8_t>(algo));
            w.u32(root);
            w.u64(gs.sched_version);
            out.push_back({m->conn_id, PacketType::kM2CCollectiveCommence, w.take()});
        }
        PLOG(kDebug) << "collective tag " << tag << " commenced, group " << group
                     << ", world " << op.members.size() << ", algo "
                     << sched::algo_name(algo);
        return;
    }

    // completion: all surviving members must have reported
    for (const auto &u : op.members) {
        auto *m = by_uuid(u);
        if (m && !op.completed.count(u)) return;
    }
    // WRITE-AHEAD completion record, before any verdict/Done packet is
    // handed to the dispatcher: if we die after a Done reaches some member
    // but not all, the next incarnation replays the verdict to the
    // stragglers instead of letting their retry cross-wait the group
    // (journal::OpDoneRec). One small fflush'd append per collective —
    // negligible next to the collective itself (the seq STRIDE batching
    // above stays; it covers the per-commence path).
    if (journal_) {
        journal::OpDoneRec rec;
        rec.group = group;
        rec.tag = tag;
        rec.seq = op.seq;
        rec.any_aborted = op.any_aborted;
        rec.world = static_cast<uint32_t>(op.members.size());
        rec.members = op.members;
        journal_->record_op_done(rec);
    }
    // exactly-one-abort accounting: if not broadcast early, deliver verdict now
    for (const auto &u : op.members) {
        auto *m = by_uuid(u);
        if (!m) continue;
        if (!op.abort_broadcast) {
            wire::Writer w;
            w.u64(tag);
            w.u8(op.any_aborted ? 1 : 0);
            out.push_back({m->conn_id, PacketType::kM2CCollectiveAbort, w.take()});
        }
        wire::Writer w2;
        w2.u64(tag);
        out.push_back({m->conn_id, PacketType::kM2CCollectiveDone, w2.take()});
    }
    git->second.ops.erase(oit);
}

std::vector<Outbox> MasterState::on_collective_complete(uint64_t conn, uint64_t tag,
                                                        bool aborted) {
    std::vector<Outbox> out;
    auto *c = by_conn(conn);
    if (!c) return out;
    auto &g = groups_[c->peer_group];
    auto it = g.ops.find(tag);
    if (it == g.ops.end()) return out;
    auto &op = it->second;
    op.completed.insert(c->uuid);
    if (aborted) {
        op.any_aborted = true;
        // a local failure must abort the whole op NOW — the other members are
        // blocked in the ring waiting for data that will never arrive
        // (reference: exactly-one-abort broadcast, ccoip_master_handler.cpp:887-905)
        if (op.commenced && !op.abort_broadcast) {
            op.abort_broadcast = true;
            for (const auto &u : op.members) {
                auto *m = by_uuid(u);
                if (!m) continue;
                wire::Writer w;
                w.u64(tag);
                w.u8(1);
                out.push_back({m->conn_id, PacketType::kM2CCollectiveAbort, w.take()});
            }
            PLOG(kWarn) << "collective tag " << tag << " aborted by peer failure report";
            maybe_incident(out, "collective_abort", c->peer_group);
        }
    }
    check_collective(out, c->peer_group, tag);
    return out;
}

void MasterState::abort_group_collectives(std::vector<Outbox> &out, uint32_t group) {
    auto git = groups_.find(group);
    if (git == groups_.end()) return;
    bool any_aborted = false;
    for (auto &[tag, op] : git->second.ops) {
        if (!op.commenced || op.abort_broadcast) continue;
        op.abort_broadcast = true;
        op.any_aborted = true;
        any_aborted = true;
        for (const auto &u : op.members) {
            auto *m = by_uuid(u);
            if (!m) continue;
            wire::Writer w;
            w.u64(tag);
            w.u8(1);
            out.push_back({m->conn_id, PacketType::kM2CCollectiveAbort, w.take()});
        }
        PLOG(kWarn) << "aborting collective tag " << tag << " in group " << group;
    }
    if (any_aborted) maybe_incident(out, "collective_abort", group);
}

// ---------- shared state ----------

std::vector<Outbox> MasterState::on_shared_state_sync(uint64_t conn,
                                                      const proto::SharedStateSyncC2M &req) {
    std::vector<Outbox> out;
    auto *c = by_conn(conn);
    if (!c || !c->accepted) return out;
    auto &g = groups_[c->peer_group];
    if (g.revision_initialized && req.revision > g.last_revision + 1) {
        kick(out, *c, "shared-state revision increment violation");
        return out;
    }
    c->sync_req = req;
    c->dist_done = false;
    check_shared_state(out, c->peer_group);
    // same cross-wait tie-break as collectives: members parked in a
    // topology vote can never offer their sync_req — release them
    if (!groups_[c->peer_group].sync_in_flight)
        defer_topology_voters(out, c->peer_group);
    return out;
}

void MasterState::check_shared_state(std::vector<Outbox> &out, uint32_t group) {
    if (groups_[group].sync_in_flight) return; // round already answered
    if (group_frozen(group)) return; // HA freeze (see check_collective)
    auto members = group_members(group);
    if (members.empty()) return;
    for (auto *m : members)
        if (!m->sync_req) return;
    auto &g = groups_[group];

    // Mask election with the reference's priority rules
    // (ccoip_master_state.cpp:1093-1184, ccoip_master_handler.cpp:632-727):
    //  - rx-only peers never put their content up for election
    //  - peers at the expected revision (match) beat revision-outdated peers
    //  - within the winning class, the most popular full entry list wins
    //  - key-set disagreement with the elected mask kicks the *disagreeing*
    //    peer; content-hash disagreement marks dirty keys for retransmission
    std::vector<ClientInfo *> candidates;
    for (auto *m : members)
        if (m->sync_req->strategy != proto::SyncStrategy::kRxOnly) candidates.push_back(m);
    if (candidates.empty()) {
        for (auto *m : members) kick(out, *m, "no tx-capable peer for shared-state sync");
        return;
    }

    // strategy mixing: enforce-popular is all-or-nothing; any peer declaring a
    // different strategy alongside an enforce-popular peer is kicked
    // (reference: ccoip_master_handler.cpp:703-731). NOTE for joiners
    // resuming from a checkpoint: this rule means an rx-only "adopt the
    // cohort" first sync is impossible against enforce-popular incumbents —
    // offer revision 0 WITH enforce-popular instead (never kickable: 0 is
    // always <= last+1, and a revision-mismatched member simply loses the
    // election and adopts; see examples/nanogpt_ddp/train_ddp.py).
    bool any_enforce = false, any_other = false;
    for (auto *m : members) {
        if (m->sync_req->strategy == proto::SyncStrategy::kEnforcePopular) any_enforce = true;
        else any_other = true;
    }
    if (any_enforce && any_other) {
        for (auto *m : members)
            if (m->sync_req->strategy != proto::SyncStrategy::kEnforcePopular)
                kick(out, *m, "shared-state sync strategy mixed with enforce-popular");
        return; // disconnect events re-run this check for the survivors
    }

    // expected revision: strict one-increment once initialized; on a fresh
    // master any revision bootstraps (logical resume), and the highest offer
    // among candidates sets the bar (reference: ccoip_master_state.cpp:1066-1090)
    const uint64_t expected =
        g.revision_initialized ? g.last_revision + 1 : [&] {
            uint64_t mx = 0;
            for (auto *m : candidates) mx = std::max(mx, m->sync_req->revision);
            return mx;
        }();

    std::vector<ClientInfo *> matched;
    for (auto *m : candidates)
        if (m->sync_req->revision == expected) matched.push_back(m);
    if (matched.empty()) {
        // nobody offers the expected revision (e.g. the only advancing peer was
        // just kicked for an increment violation, or the whole group re-offered
        // an old revision without incrementing): the round fails loudly instead
        // of silently re-syncing at the stale revision
        proto::SharedStateSyncResp resp;
        resp.failed = 1;
        resp.revision = expected;
        for (auto *m : members) {
            out.push_back({m->conn_id, PacketType::kM2CSharedStateSyncResp, resp.encode()});
            m->sync_req.reset();
            m->dist_done = false;
        }
        PLOG(kWarn) << "shared-state sync failed for group " << group
                    << ": no candidate at expected revision " << expected;
        return;
    }

    // popularity among matched candidates, keyed by the full entry list
    std::map<std::string, std::vector<ClientInfo *>> content_groups;
    for (auto *m : matched) {
        std::string key;
        for (const auto &e : m->sync_req->entries) {
            key += e.name;
            key += '\0';
            key += std::to_string(static_cast<int>(e.dtype)) + ":" + std::to_string(e.count) +
                   ":" + std::to_string(e.allow_content_inequality ? 1 : 0) + ":" +
                   std::to_string(e.allow_content_inequality ? 0 : e.hash) + ";";
        }
        content_groups[key].push_back(m);
    }
    std::vector<ClientInfo *> mask;
    size_t best = 0;
    for (auto &[_, v] : content_groups)
        if (v.size() > best) {
            best = v.size();
            mask = v;
        }
    ClientInfo *distributor = mask[0];
    const auto &mask_entries = distributor->sync_req->entries;

    // key-set agreement vs the elected mask: name/dtype/count/inequality-flag
    // disagreement kicks the minority peer (never the mask holders)
    for (auto *m : members) {
        const auto &e = m->sync_req->entries;
        bool mismatch = e.size() != mask_entries.size();
        if (!mismatch)
            for (size_t i = 0; i < e.size(); ++i)
                if (e[i].name != mask_entries[i].name || e[i].dtype != mask_entries[i].dtype ||
                    e[i].count != mask_entries[i].count ||
                    e[i].allow_content_inequality != mask_entries[i].allow_content_inequality)
                    mismatch = true;
        if (mismatch) {
            kick(out, *m, "shared-state key-set mismatch");
            return; // disconnect event will re-run this check
        }
    }

    // dirty keys come from content-hash comparison ONLY: a peer whose
    // revision lags but whose content matches the mask receives nothing
    // and just adopts the canonical revision (reference drag-along
    // semantics, test_shared_state_distribution.cpp:1147-1318)
    std::vector<std::vector<std::string>> dirty_per(members.size());
    std::vector<std::vector<uint64_t>> hashes_per(members.size());
    for (size_t k = 0; k < members.size(); ++k) {
        auto *m = members[k];
        for (size_t i = 0; i < mask_entries.size(); ++i) {
            if (mask_entries[i].allow_content_inequality) continue;
            if (m->sync_req->entries[i].hash != mask_entries[i].hash) {
                dirty_per[k].push_back(mask_entries[i].name);
                hashes_per[k].push_back(mask_entries[i].hash);
            }
        }
    }
    // ALL kick decisions happen before ANY response is emitted: a mid-loop
    // kick after queueing responses would hand survivors a stale resp that
    // their NEXT sync call consumes, desyncing the request/response protocol
    for (size_t k = 0; k < members.size(); ++k) {
        auto *m = members[k];
        // a tx-only peer that would be assigned to request state (content or
        // revision behind) is kicked: tx-only is only meaningful when the
        // declaring peer already holds the winning state
        if ((!dirty_per[k].empty() || m->sync_req->revision != expected) &&
            m->sync_req->strategy == proto::SyncStrategy::kTxOnly) {
            kick(out, *m, "tx-only peer has outdated shared state");
            return; // disconnect event re-runs this check
        }
    }
    // ---- chunk map (docs/04): seeder directory + per-key leaf hashes ----
    // A key's seeders are ALL members whose offered hash matches the mask
    // for that key — revision-lagging drag-along peers with identical
    // content included (matching hash == matching bytes). The directory is
    // shared by every response; fetchers drop themselves by uuid.
    const uint64_t chunk_bytes = distributor->sync_req->chunk_bytes;
    std::set<size_t> dirty_idx;  // mask-entry indices dirty for ANYONE
    for (size_t k = 0; k < members.size(); ++k)
        for (size_t i = 0; i < mask_entries.size(); ++i)
            if (!mask_entries[i].allow_content_inequality &&
                members[k]->sync_req->entries[i].hash != mask_entries[i].hash)
                dirty_idx.insert(i);
    std::vector<proto::SeederRec> seeders;
    std::map<Uuid, uint32_t> seeder_by_uuid;
    std::map<std::string, std::vector<uint32_t>> seeders_of_key;
    std::map<std::string, const proto::SharedStateEntryMeta *> mask_by_name;
    if (chunk_bytes) {
        for (size_t i : dirty_idx) {
            const auto &me = mask_entries[i];
            mask_by_name[me.name] = &me;
            for (auto *m : members) {
                if (m->sync_req->entries[i].hash != me.hash) continue;
                auto it = seeder_by_uuid.find(m->uuid);
                uint32_t idx;
                if (it == seeder_by_uuid.end()) {
                    idx = static_cast<uint32_t>(seeders.size());
                    // the chunk plane rides the pooled p2p mesh now: the
                    // seeder directory advertises data-plane endpoints
                    // only. The legacy ss-port field stays on the wire
                    // (decode-tolerant zero) for un-upgraded fetchers.
                    seeders.push_back({m->uuid, m->ip, 0, m->p2p_port});
                    seeder_by_uuid[m->uuid] = idx;
                } else {
                    idx = it->second;
                }
                seeders_of_key[me.name].push_back(idx);
            }
        }
    }
    g.sync_chunked_keys.clear();
    g.sync_promoted.clear();
    for (size_t k = 0; k < members.size(); ++k) {
        auto *m = members[k];
        proto::SharedStateSyncResp resp;
        resp.outdated = dirty_per[k].empty() ? 0 : 1;
        resp.dist_ip = distributor->ip;
        resp.dist_port = distributor->ss_port;
        resp.revision = expected;
        resp.outdated_keys = dirty_per[k];
        resp.expected_hashes = hashes_per[k];
        if (chunk_bytes) {
            resp.has_chunk_map = 1;
            resp.chunk_bytes = chunk_bytes;
            resp.dist_p2p_port = distributor->p2p_port;
            resp.seeders = seeders;
            for (const auto &name : dirty_per[k]) {
                const auto *me = mask_by_name.at(name);
                resp.key_leaves.push_back(me->chunk_leaves);
                resp.key_seeders.push_back(seeders_of_key[name]);
                if (!me->chunk_leaves.empty()) g.sync_chunked_keys.insert(name);
            }
        }
        out.push_back({m->conn_id, PacketType::kM2CSharedStateSyncResp, resp.encode()});
    }
    g.sync_in_flight = true;
    g.sync_revision = expected;
}

std::vector<Outbox> MasterState::on_sync_key_done(uint64_t conn,
                                                  const proto::SyncKeyDoneC2M &d) {
    std::vector<Outbox> out;
    auto *c = by_conn(conn);
    if (!c || !c->accepted) return out;
    auto &g = groups_[c->peer_group];
    // stale or bogus reports (previous round, unknown key, duplicate) are
    // silently ignored — the packet is fire-and-forget by design
    if (!g.sync_in_flight || d.revision != g.sync_revision) return out;
    if (!g.sync_chunked_keys.count(d.key)) return out;
    if (!g.sync_promoted.insert({c->uuid, d.key}).second) return out;
    proto::SeederUpdateM2C up;
    up.revision = d.revision;
    up.key = d.key;
    up.seeder = {c->uuid, c->ip, 0, c->p2p_port};  // p2p endpoint only
    auto payload = up.encode();
    for (auto *m : group_members(c->peer_group))
        if (m->conn_id != conn && m->sync_req)
            out.push_back({m->conn_id, PacketType::kM2CSeederUpdate, payload});
    telemetry::Recorder::inst().instant(
        "membership", "master_seeder_promoted", "group", c->peer_group,
        "revision", d.revision, telemetry::intern(d.key));
    return out;
}

std::vector<Outbox> MasterState::on_dist_done(uint64_t conn) {
    std::vector<Outbox> out;
    auto *c = by_conn(conn);
    if (!c) return out;
    c->dist_done = true;
    auto members = group_members(c->peer_group);
    for (auto *m : members)
        if (m->sync_req && !m->dist_done) return out;
    auto &g = groups_[c->peer_group];
    for (auto *m : members) {
        wire::Writer w;
        w.u64(g.sync_revision);
        out.push_back({m->conn_id, PacketType::kM2CSharedStateDone, w.take()});
        m->sync_req.reset();
        m->dist_done = false;
    }
    g.last_revision = g.sync_revision;
    g.revision_initialized = true;
    g.sync_in_flight = false;
    g.sync_chunked_keys.clear();
    g.sync_promoted.clear();
    if (journal_) journal_->record_group(c->peer_group, g.last_revision, true);
    PLOG(kDebug) << "shared-state sync complete, group " << c->peer_group << " revision "
                 << g.last_revision;
    telemetry::Recorder::inst().instant("membership", "master_sync_complete",
                                        "group", c->peer_group, "revision",
                                        g.last_revision);
    return out;
}

// ---------- topology optimization ----------

std::vector<Outbox> MasterState::on_optimize(uint64_t conn) {
    std::vector<Outbox> out;
    auto *c = by_conn(conn);
    if (!c || !c->accepted) return out;
    c->vote_optimize = true;
    check_optimize(out);
    return out;
}

void MasterState::check_optimize(std::vector<Outbox> &out) {
    if (!limbo_.empty()) return; // HA freeze (optimize rounds are global)
    auto acc = accepted_clients();
    if (acc.empty()) {
        // The world emptied mid-round: clear the in-flight latch. Leaving
        // it set wedges the master PERMANENTLY — check_topology() returns
        // early while optimize_in_flight_ holds, so no future client can
        // ever be admitted and only a master restart recovers. Found by
        // the pcclt-verify model checker (scenario optimize_crash: the
        // sole voter crashes after its optimize vote opened the round).
        optimize_in_flight_ = false;
        // clients that said hello while the latch held were turned away by
        // check_topology (which recheck_all runs BEFORE this): re-open the
        // admission round for them now that the latch is down
        check_topology(out);
        return;
    }
    if (!optimize_in_flight_) {
        for (auto *a : acc)
            if (!a->vote_optimize) return;
        optimize_in_flight_ = true;
    } else {
        for (auto *a : acc)
            if (!a->optimize_work_done) return;
    }

    std::vector<Uuid> uuids;
    for (auto *a : acc) uuids.push_back(a->uuid);
    auto missing = bandwidth_.missing_edges(uuids);

    if (!missing.empty()) {
        // hand each client its outgoing un-measured edges
        for (auto *a : acc) {
            proto::OptimizeResponse resp;
            resp.complete = 0;
            for (const auto &[from, to] : missing) {
                if (from != a->uuid) continue;
                auto *t = by_uuid(to);
                if (!t) continue;
                resp.requests.push_back({to, t->ip, t->bench_port});
            }
            a->optimize_work_done = false;
            out.push_back({a->conn_id, PacketType::kM2COptimizeResponse, resp.encode()});
        }
        return;
    }

    // all edges measured: solve ATSP per group, adopt new rings
    // (unreachable edges — epsilon-bandwidth reports — carry cost >= 5e5;
    // a tour crossing one falls back to reachability-aware backtracking)
    constexpr double kUnreachableCost = 5e5;
    std::set<uint32_t> groups;
    for (auto *a : acc) groups.insert(a->peer_group);
    for (uint32_t gid : groups) {
        auto members = group_members(gid);
        if (members.size() >= 2) {
            std::vector<Uuid> m_uuids;
            for (auto *m : members) m_uuids.push_back(m->uuid);
            size_t n = m_uuids.size();
            std::vector<double> cost(n * n, 0.0);
            for (size_t i = 0; i < n; ++i)
                for (size_t j = 0; j < n; ++j) {
                    if (i == j) continue;
                    auto bw = bandwidth_.get(m_uuids[i], m_uuids[j]);
                    cost[i * n + j] = bw && *bw > 0 ? 1000.0 / *bw : 1e9;
                }
            auto tour = atsp::solve(cost, n, /*budget_ms=*/1000);

            // adopt a finished moonshot result if it beats the quick solve
            // and the membership hasn't changed since it was computed
            {
                MutexLock lk(moon_mu_);
                auto it = moon_.find(gid);
                if (it != moon_.end()) {
                    std::set<Uuid> now(m_uuids.begin(), m_uuids.end());
                    if (it->second.members == now) {
                        std::map<Uuid, int> idx_of;
                        for (size_t i = 0; i < n; ++i) idx_of[m_uuids[i]] = static_cast<int>(i);
                        std::vector<int> mtour;
                        for (const auto &u : it->second.ring) mtour.push_back(idx_of[u]);
                        if (atsp::tour_cost(cost, n, mtour) <
                            atsp::tour_cost(cost, n, tour)) {
                            tour = mtour;
                            PLOG(kInfo) << "adopting moonshot ring for group " << gid;
                        }
                    }
                    moon_.erase(it);
                }
            }

            // reachability: avoid unreachable edges if a Hamiltonian cycle
            // over reachable edges exists (reference backtracking ring build)
            bool crosses_unreachable = false;
            for (size_t i = 0; i < n; ++i)
                if (cost[static_cast<size_t>(tour[i]) * n + tour[(i + 1) % n]] >=
                    kUnreachableCost)
                    crosses_unreachable = true;
            if (crosses_unreachable) {
                auto h = atsp::hamiltonian(cost, n, kUnreachableCost, 500);
                if (!h.empty()) {
                    // improve() has no edge limit and could reintroduce an
                    // unreachable edge; keep the feasible tour if it does
                    auto feasible = h;
                    atsp::improve(cost, n, h, 200);
                    for (size_t i = 0; i < n; ++i)
                        if (cost[static_cast<size_t>(h[i]) * n + h[(i + 1) % n]] >=
                            kUnreachableCost) {
                            h = feasible;
                            break;
                        }
                    PLOG(kInfo) << "group " << gid
                                << ": reachability-aware ring adopted (cost "
                                << atsp::tour_cost(cost, n, h) << ")";
                    tour = h;
                } else {
                    PLOG(kWarn) << "group " << gid
                                << ": no fully-reachable ring exists; keeping "
                                   "least-cost tour across unreachable edges";
                }
            }

            std::vector<Uuid> ring;
            for (int idx : tour) ring.push_back(m_uuids[idx]);
            groups_[gid].ring = ring;
            if (journal_) journal_->record_ring(gid, ring);
            spawn_moonshot(gid, m_uuids, cost, tour);

            // ---- schedule synthesis (docs/12): same measured matrix,
            // richer question. The planner's peer space is ring POSITIONS,
            // so build the mbps matrix in adopted-ring order; versioned,
            // journaled, and broadcast so /metrics and rejoiners see it —
            // the per-op binding truth stays the commence stamp.
            if (sched::schedule_enabled()) {
                auto &gs = groups_[gid];
                const size_t rn = gs.ring.size();
                sched::CostModel cm;
                cm.n = static_cast<uint32_t>(rn);
                cm.mbps.assign(rn * rn, 0.0);
                for (size_t i = 0; i < rn; ++i)
                    for (size_t j = 0; j < rn; ++j) {
                        if (i == j) continue;
                        auto bw = bandwidth_.get(gs.ring[i], gs.ring[j]);
                        cm.mbps[i * rn + j] = bw ? *bw : 0.0;
                    }
                std::vector<uint32_t> ring_idx(rn);
                for (size_t i = 0; i < rn; ++i)
                    ring_idx[i] = static_cast<uint32_t>(i);
                gs.schedule =
                    sched::synthesize(cm, ring_idx, ++gs.sched_version);
                auto enc = gs.schedule.encode();
                if (journal_) journal_->record_schedule(gid, enc);
                for (auto *m : members) {
                    proto::ScheduleUpdateM2C su;
                    su.group = gid;
                    su.table = enc;
                    out.push_back({m->conn_id, PacketType::kM2CScheduleUpdate,
                                   su.encode()});
                }
                IngestItem sit;
                sit.kind = IngestItem::kSchedule;
                sit.group = gid;
                sit.sched = std::move(enc);
                enqueue(std::move(sit));
                PLOG(kInfo) << "group " << gid << ": collective schedule v"
                            << gs.schedule.version << " synthesized ("
                            << gs.schedule.entries.size() << " entries)";
            }
        }
    }
    for (auto *a : acc) {
        a->vote_optimize = false;
        a->optimize_work_done = false;
        wire::Writer w;
        w.u8(1);
        const auto &ring = groups_[a->peer_group].ring;
        w.u32(static_cast<uint32_t>(ring.size()));
        for (const auto &u : ring) proto::put_uuid(w, u);
        out.push_back({a->conn_id, PacketType::kM2COptimizeComplete, w.take()});
    }
    optimize_in_flight_ = false;
    PLOG(kInfo) << "topology optimization complete";
    telemetry::Recorder::inst().instant("membership", "master_optimize_complete",
                                        "world", world_size());
}

MasterState::MasterState() {
    // the digest-ingest (fold) thread: drains the bounded queue the
    // dispatcher enqueues into and owns every health_mu_-guarded write
    fold_thread_ = std::thread([this] { fold_loop(); });
}

MasterState::~MasterState() {
    moon_stop_ = true; // improve() polls this, so joins return promptly
    fold_stop_.store(true, std::memory_order_release);
    ingest_cv_.notify_all();
    if (fold_thread_.joinable()) fold_thread_.join();
    for (auto &[_, t] : moon_threads_)
        if (t.joinable()) t.join();
}

void MasterState::spawn_moonshot(uint32_t gid, std::vector<Uuid> uuids,
                                 std::vector<double> cost, std::vector<int> tour) {
    if (uuids.size() < 3) return; // a 2-node ring has nothing to improve
    auto tit = moon_threads_.find(gid);
    if (tit != moon_threads_.end()) {
        auto rit = moon_running_.find(gid);
        if (rit != moon_running_.end() && rit->second->load())
            return; // previous worker still running: a stale result produced
                    // from an older cost matrix must not overwrite a newer one
        if (tit->second.joinable()) tit->second.join();
        moon_threads_.erase(tit);
    }
    int budget_ms = 10'000; // reference uses 30 s; env-tunable for tests
    if (const char *v = std::getenv("PCCLT_MOONSHOT_MS")) budget_ms = std::atoi(v);
    if (budget_ms <= 0) return;
    auto running = std::make_shared<std::atomic<bool>>(true);
    moon_running_[gid] = running;
    moon_threads_[gid] = std::thread([this, gid, uuids = std::move(uuids),
                                      cost = std::move(cost), tour = std::move(tour),
                                      budget_ms, running]() mutable {
        size_t n = uuids.size();
        double c = atsp::improve(cost, n, tour, budget_ms, &moon_stop_);
        Moonshot m;
        m.members.insert(uuids.begin(), uuids.end());
        for (int idx : tour) m.ring.push_back(uuids[idx]);
        m.cost = c;
        {
            MutexLock lk(moon_mu_);
            moon_[gid] = std::move(m);
        }
        running->store(false);
    });
}

std::vector<Outbox> MasterState::on_bandwidth_report(uint64_t conn, const Uuid &to,
                                                     double mbps) {
    std::vector<Outbox> out;
    auto *c = by_conn(conn);
    if (!c) return out;
    bandwidth_.store(c->uuid, to, mbps);
    if (journal_) journal_->record_bandwidth(c->uuid, to, mbps);
    IngestItem it;
    it.kind = IngestItem::kBandwidth;
    it.peer = c->uuid;
    it.to = to;
    it.mbps = mbps;
    enqueue(std::move(it));
    return out;
}

std::vector<Outbox> MasterState::on_optimize_work_done(uint64_t conn) {
    std::vector<Outbox> out;
    auto *c = by_conn(conn);
    if (!c) return out;
    c->optimize_work_done = true;
    check_optimize(out);
    return out;
}

// ---------- fleet health (observability plane, docs/09) ----------

void MasterState::enqueue(IngestItem &&it) {
    const bool droppable = it.kind == IngestItem::kDigest;
    if (droppable && ingest_depth_.load(std::memory_order_relaxed) >=
                         digest_queue_cap()) {
        // overflow drops-and-counts: a digest flood can never back-pressure
        // the dispatcher (admission/topology rounds) — only digests are
        // droppable, membership/bandwidth deltas always land
        ingest_dropped_.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    {
        MutexLock lk(ingest_mu_);
        if (droppable) ingest_depth_.fetch_add(1, std::memory_order_relaxed);
        ingest_q_.push_back(std::move(it));
    }
    ingest_cv_.notify_one();
}

void MasterState::enqueue_endpoint_add(const ClientInfo &c) {
    if (c.observer) return; // observers own no data-plane endpoint
    IngestItem it;
    it.kind = IngestItem::kEndpointAdd;
    net::Addr a = c.ip;
    a.port = c.p2p_port;
    it.endpoint = a.str();
    it.peer = c.uuid;
    it.group = c.peer_group;
    enqueue(std::move(it));
}

void MasterState::publish_health_summary() {
    IngestItem it;
    it.kind = IngestItem::kSummary;
    it.world = world_size();
    it.clients = clients_.size();
    it.limbo = limbo_.size();
    enqueue(std::move(it));
}

std::vector<Outbox> MasterState::on_telemetry_digest(
    uint64_t conn, const proto::TelemetryDigestC2M &d) {
    std::vector<Outbox> out; // fire-and-forget: never replies
    auto *c = by_conn(conn);
    if (!c) return out;
    // ENQUEUE-ONLY on the dispatcher: no health_mu_, no endpoint
    // resolution, no string builds — the fold thread owns all of it. The
    // only work here is one copy of the decoded digest (the dispatcher's
    // decode buffer is transient) and one bounded-queue push.
    IngestItem it;
    it.kind = IngestItem::kDigest;
    it.digest = d;
    it.peer = c->uuid;
    it.group = c->peer_group;
    it.t_ns = telemetry::now_ns();
    enqueue(std::move(it));
    return out;
}

void MasterState::fold_loop() {
    for (;;) {
        std::deque<IngestItem> batch;
        {
            MutexLock lk(ingest_mu_);
            if (ingest_q_.empty() &&
                !fold_stop_.load(std::memory_order_acquire))
                ingest_cv_.wait_for(ingest_mu_,
                                    std::chrono::milliseconds(100));
            if (ingest_q_.empty()) {
                if (fold_stop_.load(std::memory_order_acquire)) return;
            } else {
                batch.swap(ingest_q_);
            }
        }
        for (auto &it : batch) {
            if (it.kind == IngestItem::kDigest)
                ingest_depth_.fetch_sub(1, std::memory_order_relaxed);
            fold_item(it);
        }
        // periodic work rides the same thread (it used to ride dispatcher
        // ticks): departed-peer eviction + the /health history sampler
        const uint64_t now = telemetry::now_ns();
        fold_sweep(now);
        fold_sample_history(now);
    }
}

void MasterState::fold_item(IngestItem &it) {
    switch (it.kind) {
    case IngestItem::kDigest:
        fold_digest(it);
        break;
    case IngestItem::kEndpointAdd:
        fold_endpoints_[it.endpoint] =
            FoldPeer{it.peer, proto::uuid_str(it.peer), it.group};
        break;
    case IngestItem::kEndpointRemove: {
        // only drop the entry if it still belongs to the departing peer —
        // a relaunched peer may have re-bound the endpoint in between
        auto f = fold_endpoints_.find(it.endpoint);
        if (f != fold_endpoints_.end() && f->second.raw == it.peer)
            fold_endpoints_.erase(f);
        break;
    }
    case IngestItem::kDeparted: {
        // keep the record for post-mortems, mark it down (pcclt_peer_up 0;
        // the next digest after a session resume revives)
        MutexLock lk(health_mu_);
        auto fit = fleet_peers_.find(proto::uuid_str(it.peer));
        if (fit != fleet_peers_.end()) fit->second.departed = true;
        break;
    }
    case IngestItem::kBandwidth:
        fold_bw_[it.peer][it.to] = it.mbps;
        break;
    case IngestItem::kForget:
        fold_bw_.erase(it.peer);
        for (auto &[_, m] : fold_bw_) m.erase(it.peer);
        break;
    case IngestItem::kSummary: {
        MutexLock lk(health_mu_);
        health_world_ = it.world;
        health_clients_ = it.clients;
        health_limbo_ = it.limbo;
        break;
    }
    case IngestItem::kSchedule: {
        auto t = sched::Table::decode(it.sched);
        if (!t) break;
        MutexLock lk(health_mu_);
        fleet_schedules_[it.group] = std::move(*t);
        break;
    }
    case IngestItem::kIncident: {
        MutexLock lk(health_mu_);
        if (it.inc_id.empty()) {
            // suppressed trigger: only the per-class counter moves
            ++incidents_suppressed_by_class_[it.inc_trigger];
        } else {
            recent_incidents_.push_back({it.inc_id, it.inc_trigger, it.t_ns});
            while (recent_incidents_.size() > 8) recent_incidents_.pop_front();
        }
        break;
    }
    }
}

void MasterState::fold_sweep(uint64_t now) {
    // Retention: departed entries stay visible for post-mortems but must
    // not accumulate forever under peer churn (every relaunch is a fresh
    // uuid). Sweep every ~5 s; evict departed peers idle past the horizon
    // — or past a hard cap, oldest first — plus their edges. Used to ride
    // dispatcher ticks; now the fold thread owns it, so an O(peers) scan
    // can never pace a consensus round.
    constexpr uint64_t kSweepNs = 5'000'000'000ull;
    constexpr uint64_t kRetainNs = 10ull * 60 * 1'000'000'000;  // 10 min
    constexpr size_t kMaxPeers = 4096;
    if (now - fold_last_sweep_ns_ < kSweepNs) return;
    fold_last_sweep_ns_ = now;
    MutexLock lk(health_mu_);
    std::vector<std::string> evict;
    for (const auto &[uuid, p] : fleet_peers_)
        if (p.departed && now - p.last_digest_ns > kRetainNs)
            evict.push_back(uuid);
    if (fleet_peers_.size() - evict.size() > kMaxPeers) {
        std::vector<std::pair<uint64_t, std::string>> departed;
        for (const auto &[uuid, p] : fleet_peers_)
            if (p.departed && now - p.last_digest_ns <= kRetainNs)
                departed.emplace_back(p.last_digest_ns, uuid);
        std::sort(departed.begin(), departed.end());
        for (const auto &[_, uuid] : departed) {
            if (fleet_peers_.size() - evict.size() <= kMaxPeers) break;
            evict.push_back(uuid);
        }
    }
    for (const auto &uuid : evict) {
        fleet_peers_.erase(uuid);
        for (auto it = fleet_edges_.begin(); it != fleet_edges_.end();)
            it = it->first.first == uuid ? fleet_edges_.erase(it) : ++it;
    }
}

namespace {

// /health history ring tunables (docs/03): sample period + retained depth.
// Re-read per sample (1 Hz-ish): tests flip them at runtime.
uint64_t health_history_period_ns() {
    if (const char *e = std::getenv("PCCLT_HEALTH_HISTORY_MS")) {
        long long v = atoll(e);
        if (v >= 0) return static_cast<uint64_t>(v) * 1'000'000ull;
    }
    return 1'000'000'000ull; // 1 s
}

size_t health_history_cap() {
    if (const char *e = std::getenv("PCCLT_HEALTH_HISTORY")) {
        long v = std::atol(e);
        if (v >= 0) return static_cast<size_t>(v);
    }
    return 120; // 2 min of trend at the default period
}

} // namespace

void MasterState::fold_sample_history(uint64_t now) {
    const uint64_t period = health_history_period_ns();
    if (period == 0) return; // history disabled
    if (fold_last_sample_ns_ && now - fold_last_sample_ns_ < period) return;
    HealthSample s;
    s.t_ns = now;
    s.digests = digests_total_.load(std::memory_order_relaxed);
    s.stragglers = stragglers_flagged_.load(std::memory_order_relaxed);
    s.incidents = incidents_total_.load(std::memory_order_relaxed);
    s.suppressed = incidents_suppressed_.load(std::memory_order_relaxed);
    s.queue_depth = ingest_depth_.load(std::memory_order_relaxed);
    s.queue_dropped = ingest_dropped_.load(std::memory_order_relaxed);
    const double dt_s =
        fold_last_sample_ns_ ? (now - fold_last_sample_ns_) / 1e9 : 0;
    fold_last_sample_ns_ = now;
    MutexLock lk(health_mu_);
    const uint64_t prev =
        health_history_.empty() ? 0 : health_history_.back().digests;
    s.digest_rate =
        dt_s > 0 && s.digests >= prev ? (s.digests - prev) / dt_s : 0;
    s.world = health_world_;
    s.clients = health_clients_;
    s.limbo = health_limbo_;
    s.peers = fleet_peers_.size();
    s.edges = fleet_edges_.size();
    health_history_.push_back(s);
    const size_t cap = std::max<size_t>(1, health_history_cap());
    while (health_history_.size() > cap) health_history_.pop_front();
}

void MasterState::fold_digest(IngestItem &item) {
    const proto::TelemetryDigestC2M &d = item.digest;
    const std::string from = proto::uuid_str(item.peer);
    const uint64_t now = telemetry::now_ns();

    // Resolve each digest edge's endpoint to a peer + its bandwidth-matrix
    // entry OUTSIDE health_mu_, against the fold thread's OWN mirrors
    // (fold_endpoints_ / fold_bw_, maintained incrementally from the
    // dispatcher's membership/bandwidth delta items): the dispatcher's
    // clients_/bandwidth_ are never touched from here.
    struct Resolved {
        const proto::TelemetryDigestC2M::Edge *e;
        std::string to_uuid;
        Uuid to_raw{};
        double expected_mbps = 0;      // remote -> reporter (inbound)
        double expected_out_mbps = 0;  // reporter -> remote (outbound): the
                                       // direction a watchdog CONFIRM judges
    };
    std::vector<Resolved> resolved;
    resolved.reserve(d.edges.size());
    for (const auto &e : d.edges) {
        Resolved r;
        r.e = &e;
        auto it = fold_endpoints_.find(e.endpoint);
        if (it != fold_endpoints_.end()) {
            r.to_uuid = it->second.uuid_str;
            r.to_raw = it->second.raw;
            // the straggler verdict judges the INBOUND direction
            // (remote -> reporter): the reporter's wire-stall on this
            // edge is the degradation witness, so the matrix entry to
            // compare against is remote->reporter too
            if (auto bi = fold_bw_.find(it->second.raw); bi != fold_bw_.end())
                if (auto e2 = bi->second.find(item.peer);
                    e2 != bi->second.end())
                    r.expected_mbps = e2->second;
            if (auto bo = fold_bw_.find(item.peer); bo != fold_bw_.end())
                if (auto e2 = bo->second.find(it->second.raw);
                    e2 != bo->second.end())
                    r.expected_out_mbps = e2->second;
        }
        resolved.push_back(std::move(r));
    }

    // fold into the fleet model; collect straggler TRANSITIONS (edges newly
    // below threshold) to act on after the lock drops
    struct Flagged {
        std::string endpoint, to_uuid;
        Uuid to_raw{};
        double measured = 0, expected = 0;
        // outbound = a watchdog CONFIRM (reporter -> remote): the matrix
        // substitution goes in that direction, with the achieved tx rate
        bool outbound = false;
    };
    std::vector<Flagged> newly_flagged;
    {
        MutexLock lk(health_mu_);
        auto &p = fleet_peers_[from];
        p.uuid = from;
        p.group = item.group;
        p.last_seq = d.last_seq;
        p.ring_dropped = d.ring_dropped;
        p.ring_pushed = d.ring_pushed;
        p.ring_cap = d.ring_cap;
        p.collectives_ok = d.collectives_ok;
        ++p.digests;
        p.last_digest_ns = now;
        p.departed = false;
        // phase latency histograms are cumulative peer-side: replace, not
        // merge — a missed digest loses nothing. Ids beyond this build's
        // Phase table are dropped: they would all render as phase="?" and
        // two of them would emit duplicate label sets, which Prometheus
        // rejects for the WHOLE scrape (the wire bound is looser than
        // kPhaseCount on purpose — newer peers may know more phases).
        for (const auto &[phase, h] : d.phase_hists)
            if (phase < telemetry::kPhaseCount)
                p.phase_hists[phase] =
                    telemetry::hist_dense(h.sum_ns, h.buckets);
        for (const auto &r : resolved) {
            auto &eh = fleet_edges_[{from, r.e->endpoint}];
            eh.from_uuid = from;
            eh.to_endpoint = r.e->endpoint;
            eh.to_uuid = r.to_uuid;
            eh.tx_mbps = r.e->tx_mbps;
            eh.rx_mbps = r.e->rx_mbps;
            eh.stall_ratio = r.e->stall_ratio;
            eh.tx_bytes = r.e->tx_bytes;
            eh.rx_bytes = r.e->rx_bytes;
            eh.expected_mbps = r.expected_mbps;
            eh.wd_state = r.e->wd_state;
            if (!r.e->stage_wire_hist.empty())
                eh.stage_wire_hist = telemetry::hist_dense(
                    r.e->stage_wire_hist.sum_ns, r.e->stage_wire_hist.buckets);
            if (!r.e->stall_hist.empty())
                eh.stall_hist = telemetry::hist_dense(
                    r.e->stall_hist.sum_ns, r.e->stall_hist.buckets);
            // Watchdog fast path: a CONFIRMED edge means the reporter's
            // data plane already failed over mid-collective — no rate
            // heuristics needed, the re-opt should fire NOW so the next
            // ring routes around the hop while the current op limps home.
            if (r.e->wd_state == 2 && !eh.straggler && !r.to_uuid.empty()) {
                eh.straggler = true;
                eh.wd_flagged = true;
                eh.flag_baseline_mbps = r.expected_out_mbps > 0
                                            ? r.expected_out_mbps
                                            : r.expected_mbps;
                ++stragglers_flagged_;
                newly_flagged.push_back({r.e->endpoint, r.to_uuid, r.to_raw,
                                         r.e->tx_mbps, r.expected_out_mbps,
                                         /*outbound=*/true});
            } else if (eh.straggler && eh.wd_flagged && r.e->wd_state == 0) {
                // the peer's hold expired and the edge proved itself again
                eh.straggler = false;
                eh.wd_flagged = false;
                eh.flag_baseline_mbps = 0;
            }
            // Degradation witness = the RECEIVER's wire-stall: achieved
            // ingress rate only samples link capacity while the receiver
            // is blocked on the wire (stall gate). Without it, any healthy
            // edge under a light duty cycle would read as a straggler and
            // (under REOPT) corrupt the matrix with a load-limited rate.
            const bool active = r.e->rx_mbps >= kMinActiveMbps;
            if (!eh.straggler) {
                const bool degraded =
                    active && r.expected_mbps > 0 &&
                    r.e->stall_ratio >= kMinStallRatio &&
                    r.e->rx_mbps < straggler_fraction() * r.expected_mbps;
                if (degraded) {
                    eh.straggler = true;
                    eh.flag_baseline_mbps = r.expected_mbps;
                    ++stragglers_flagged_;
                    newly_flagged.push_back({r.e->endpoint, r.to_uuid,
                                             r.to_raw, r.e->rx_mbps,
                                             r.expected_mbps});
                }
            } else if (active && !eh.wd_flagged) {
                // recovery is judged against the baseline captured when
                // the flag went up — the REOPT hook rewrites the matrix
                // with the degraded rate, and measuring against THAT
                // would self-clear the flag mid-incident. An idle edge
                // keeps its verdict: no sample, no change.
                const double base = eh.flag_baseline_mbps > 0
                                        ? eh.flag_baseline_mbps
                                        : r.expected_mbps;
                if (r.e->rx_mbps >= straggler_fraction() * base) {
                    eh.straggler = false;
                    eh.flag_baseline_mbps = 0;
                }
            }
        }
    }
    // publish AFTER the maps: digests_folded() is the "render will see this
    // digest" gate tests and the bench spin on
    digests_total_.fetch_add(1, std::memory_order_release);
    fold_hist_.record(telemetry::now_ns() - item.t_ns);

    for (const auto &f : newly_flagged) {
        PLOG(kWarn) << "straggler edge flagged: "
                    << (f.outbound ? from : f.endpoint) << " -> "
                    << (f.outbound ? f.endpoint : from) << " measured "
                    << f.measured << " Mbit/s vs matrix " << f.expected
                    << " Mbit/s ("
                    << (f.outbound ? "watchdog CONFIRMED in-collective"
                                   : "receiver wire-stall witnessed")
                    << ")";
        telemetry::Recorder::inst().instant(
            "fleet", "master_straggler", "measured_mbps",
            static_cast<uint64_t>(f.measured), "expected_mbps",
            static_cast<uint64_t>(f.expected), telemetry::intern(f.endpoint));
    }
    if (!newly_flagged.empty()) {
        // hand the consensus-side follow-ups (matrix rewrite + journal,
        // REOPT, incident broadcast) to the dispatcher's next tick: the
        // fold thread must never act on dispatcher-only state
        MutexLock lk(ingest_mu_);
        for (const auto &f : newly_flagged) {
            StragglerAction a;
            a.endpoint = f.endpoint;
            a.from_uuid = from;
            a.from_raw = item.peer;
            a.to_raw = f.to_raw;
            a.has_to = !f.to_uuid.empty();
            a.group = item.group;
            a.measured_mbps = f.measured;
            a.expected_mbps = f.expected;
            a.outbound_confirm = f.outbound;
            pending_actions_.push_back(std::move(a));
        }
    }
}

void MasterState::request_straggler_reopt(uint32_t gid) {
    auto members = group_members(gid);
    if (members.size() < 3) return; // a 2-ring has no alternative route
    std::vector<Uuid> m_uuids;
    for (auto *m : members) m_uuids.push_back(m->uuid);
    const size_t n = m_uuids.size();
    std::vector<double> cost(n * n, 0.0);
    for (size_t i = 0; i < n; ++i)
        for (size_t j = 0; j < n; ++j) {
            if (i == j) continue;
            auto bw = bandwidth_.get(m_uuids[i], m_uuids[j]);
            cost[i * n + j] = bw && *bw > 0 ? 1000.0 / *bw : 1e9;
        }
    // seed from the current ring so the improvement starts at the adopted
    // tour; membership drift since the last round falls back to identity
    std::vector<int> tour;
    const auto &ring = groups_[gid].ring;
    if (ring.size() == n) {
        for (const auto &u : ring) {
            auto it = std::find(m_uuids.begin(), m_uuids.end(), u);
            if (it == m_uuids.end()) {
                tour.clear();
                break;
            }
            tour.push_back(static_cast<int>(it - m_uuids.begin()));
        }
    }
    if (tour.size() != n) {
        tour.resize(n);
        for (size_t i = 0; i < n; ++i) tour[i] = static_cast<int>(i);
    }
    PLOG(kInfo) << "straggler re-opt requested for group " << gid
                << " (background moonshot over the refreshed matrix)";
    telemetry::Recorder::inst().instant("fleet", "master_straggler_reopt",
                                        "group", gid);
    spawn_moonshot(gid, std::move(m_uuids), std::move(cost), std::move(tour));
}

namespace {

void json_str(std::string &o, const std::string &s) {
    o += '"';
    o += telemetry::json_escape(s);
    o += '"';
}

std::string num(double v) {
    char buf[32];
    snprintf(buf, sizeof buf, "%.6g", v);
    return buf;
}

std::string num(uint64_t v) {
    char buf[24];
    snprintf(buf, sizeof buf, "%" PRIu64, v);
    return buf;
}

} // namespace

// ---------- incident black box (docs/09) ----------

namespace {

std::string incident_dir() {
    const char *e = std::getenv("PCCLT_INCIDENT_DIR");
    return e && e[0] ? std::string(e) : std::string();
}

uint64_t incident_min_ns() {
    // re-read per trigger (rare): tests flip it at runtime
    if (const char *e = std::getenv("PCCLT_INCIDENT_MIN_MS")) {
        long long v = atoll(e);
        if (v >= 0) return static_cast<uint64_t>(v) * 1'000'000ull;
    }
    return 30'000ull * 1'000'000ull;
}

} // namespace

void MasterState::maybe_incident(std::vector<Outbox> &out,
                                 const std::string &trigger, uint32_t group) {
    const std::string dir = incident_dir();
    if (dir.empty()) return; // plane disabled
    const uint64_t now = telemetry::now_ns();
    // rate limited PER TRIGGER CLASS (the prefix before ':'): a flapping
    // kick storm must not spam disk, but neither may it starve a later
    // watchdog_confirm bundle — each class carries its own window
    const std::string klass = trigger.substr(0, trigger.find(':'));
    uint64_t &last = last_incident_ns_by_class_[klass];
    if (last && now - last < incident_min_ns()) {
        // the suppression is still counted (globally and per class) and
        // visible on /health + /metrics
        incidents_suppressed_.fetch_add(1, std::memory_order_relaxed);
        IngestItem sup;
        sup.kind = IngestItem::kIncident;
        sup.inc_trigger = klass; // empty inc_id = suppressed
        enqueue(std::move(sup));
        return;
    }
    last = now;
    const std::string id = "inc-e" + std::to_string(epoch_) + "-" +
                           std::to_string(++incident_seq_);
    incidents_total_.fetch_add(1, std::memory_order_relaxed);
    {
        IngestItem rec;
        rec.kind = IngestItem::kIncident;
        rec.inc_id = id;
        rec.inc_trigger = trigger;
        rec.t_ns = now;
        enqueue(std::move(rec));
    }
    PLOG(kWarn) << "incident " << id << " (" << trigger
                << "): broadcasting black-box capture to " << clients_.size()
                << " clients";
    telemetry::Recorder::inst().instant("fleet", "master_incident", "group",
                                        group, nullptr, 0,
                                        telemetry::intern(trigger));
    proto::IncidentDumpM2C pkt;
    pkt.incident_id = id;
    pkt.trigger = trigger;
    pkt.epoch = epoch_;
    auto payload = pkt.encode();
    // fleet-wide, not group-scoped: a cross-group master sees one shared
    // control plane, and the neighbors' rings are part of the evidence
    for (auto &[cid, c] : clients_)
        out.push_back({cid, PacketType::kM2CIncidentDump, payload});
    // master-side manifest: the trigger + the fleet-health snapshot at the
    // moment of the incident (per-peer digest tails, edge EWMAs, watchdog
    // verdicts). Written lock-free on the dispatcher; a manifest is a few
    // KiB and incidents are rate-limited, so this cannot pace consensus.
    ::mkdir(dir.c_str(), 0755);
    const std::string idir = dir + "/" + id;
    ::mkdir(idir.c_str(), 0755);
    FILE *f = fopen((idir + "/manifest.json").c_str(), "w");
    if (!f) {
        PLOG(kWarn) << "incident " << id << ": cannot write manifest under "
                    << dir;
        return;
    }
    std::string o = "{\"incident_id\":";
    json_str(o, id);
    o += ",\"trigger\":";
    json_str(o, trigger);
    o += ",\"epoch\":" + num(epoch_);
    o += ",\"group\":" + num(static_cast<uint64_t>(group));
    o += ",\"t_mono_ns\":" + num(now);
    o += ",\"t_unix\":" + num(static_cast<uint64_t>(time(nullptr)));
    o += ",\"health\":" + render_health_json();
    o += "}\n";
    fwrite(o.data(), 1, o.size(), f);
    fclose(f);
}

namespace {

// /metrics cardinality + cache tunables (docs/03). Re-read per render
// (rare): tests flip them at runtime.
size_t metrics_edge_topk() {
    if (const char *e = std::getenv("PCCLT_METRICS_EDGE_TOPK")) {
        long v = std::atol(e);
        if (v >= 0) return static_cast<size_t>(v);
    }
    return 64;
}

uint64_t metrics_max_age_ns() {
    if (const char *e = std::getenv("PCCLT_METRICS_MAX_AGE_MS")) {
        long long v = atoll(e);
        if (v >= 0) return static_cast<uint64_t>(v) * 1'000'000ull;
    }
    return 1'000'000'000ull; // 1 s
}

} // namespace

std::string MasterState::render_metrics() const {
    // Render cache: N concurrent scrapers share one build. The build runs
    // WHILE HOLDING the cache lock on purpose — late scrapers serialize
    // behind the builder and get the fresh text for free instead of
    // kicking off N identical full renders under health_mu_ contention.
    const uint64_t max_age = metrics_max_age_ns();
    MutexLock lk(metrics_cache_mu_);
    const uint64_t now = telemetry::now_ns();
    if (max_age && !metrics_cache_.empty() &&
        now - metrics_cache_ns_ < max_age)
        return metrics_cache_;
    metrics_cache_ = render_metrics_uncached();
    metrics_cache_ns_ = now;
    return metrics_cache_;
}

std::string MasterState::render_metrics_uncached() const {
    const uint64_t now = telemetry::now_ns();
    std::string o;
    o.reserve(4096);
    auto gauge = [&](const char *name, const char *help) {
        o += "# HELP ";
        o += name;
        o += ' ';
        o += help;
        o += "\n# TYPE ";
        o += name;
        o += " gauge\n";
    };
    auto counter = [&](const char *name, const char *help) {
        o += "# HELP ";
        o += name;
        o += ' ';
        o += help;
        o += "\n# TYPE ";
        o += name;
        o += " counter\n";
    };
    // copy the model out under a SHORT critical section, render outside:
    // the fold thread takes health_mu_ on every digest, and a large
    // fleet's exposition is thousands of heap-allocating appends — string
    // building under the lock would stall the ingest for the whole scrape
    std::map<std::string, PeerHealth> fleet_peers_copy;
    std::map<std::pair<std::string, std::string>, EdgeHealth> fleet_edges_copy;
    std::map<uint32_t, sched::Table> fleet_schedules_copy;
    std::map<std::string, uint64_t> suppressed_by_class_copy;
    uint64_t digests_total_copy, stragglers_copy;
    uint64_t incidents_copy, incidents_suppressed_copy;
    size_t world_copy, clients_copy, limbo_copy;
    {
        MutexLock lk(health_mu_);
        fleet_peers_copy = fleet_peers_;
        fleet_edges_copy = fleet_edges_;
        fleet_schedules_copy = fleet_schedules_;
        suppressed_by_class_copy = incidents_suppressed_by_class_;
        digests_total_copy = digests_total_;
        stragglers_copy = stragglers_flagged_;
        incidents_copy = incidents_total_;
        incidents_suppressed_copy = incidents_suppressed_;
        world_copy = health_world_;
        clients_copy = health_clients_;
        limbo_copy = health_limbo_;
    }
    gauge("pcclt_master_epoch", "master incarnation (bumped per journaled restart)");
    o += "pcclt_master_epoch " + num(epoch_) + "\n";
    gauge("pcclt_master_world_size", "accepted clients across all groups");
    o += "pcclt_master_world_size " + num(static_cast<uint64_t>(world_copy)) + "\n";
    gauge("pcclt_master_clients", "connected control sessions");
    o += "pcclt_master_clients " + num(static_cast<uint64_t>(clients_copy)) + "\n";
    gauge("pcclt_master_limbo_sessions", "rehydrated sessions awaiting resume");
    o += "pcclt_master_limbo_sessions " + num(static_cast<uint64_t>(limbo_copy)) + "\n";
    counter("pcclt_master_telemetry_digests_total", "telemetry digests received");
    o += "pcclt_master_telemetry_digests_total " + num(digests_total_copy) + "\n";
    counter("pcclt_master_stragglers_flagged_total",
            "straggler edge flag transitions");
    o += "pcclt_master_stragglers_flagged_total " + num(stragglers_copy) + "\n";
    counter("pcclt_master_incidents_total",
            "black-box incident captures fired (docs/09 incident plane)");
    o += "pcclt_master_incidents_total " + num(incidents_copy) + "\n";
    counter("pcclt_master_incidents_suppressed_total",
            "incident triggers swallowed by the rate limiter");
    o += "pcclt_master_incidents_suppressed_total " +
         num(incidents_suppressed_copy) + "\n";
    // per-class suppression detail: the limiter windows are per trigger
    // class, so the operator can see WHICH storm is being swallowed
    counter("pcclt_master_incidents_suppressed_by_class_total",
            "incident triggers swallowed by the per-class rate limiter");
    {
        auto esc = [](const std::string &s) {
            std::string r;
            for (char ch : s) {
                if (ch == '\\' || ch == '"') r += '\\';
                if (ch == '\n') {
                    r += "\\n";
                    continue;
                }
                r += ch;
            }
            return r;
        };
        for (const auto &[klass, n] : suppressed_by_class_copy)
            o += "pcclt_master_incidents_suppressed_by_class_total"
                 "{trigger_class=\"" +
                 esc(klass) + "\"} " + num(n) + "\n";
    }
    // schedule plane (docs/12): what the synthesizer picked, per group
    if (!fleet_schedules_copy.empty()) {
        gauge("pcclt_schedule_version",
              "synthesized collective schedule table version per group");
        for (const auto &[gid, t] : fleet_schedules_copy)
            o += "pcclt_schedule_version{group=\"" +
                 num(static_cast<uint64_t>(gid)) + "\"} " + num(t.version) +
                 "\n";
        gauge("pcclt_schedule_kind",
              "chosen algorithm per (group, collective, size class); "
              "constant 1, the labels are the payload");
        for (const auto &[gid, t] : fleet_schedules_copy)
            for (const auto &e : t.entries)
                o += "pcclt_schedule_kind{group=\"" +
                     num(static_cast<uint64_t>(gid)) + "\",coll=\"" +
                     sched::coll_name(static_cast<sched::Coll>(e.coll)) +
                     "\",size_class=\"" +
                     num(static_cast<uint64_t>(e.size_class)) + "\",algo=\"" +
                     sched::algo_name(static_cast<sched::Algo>(e.algo)) +
                     "\"} 1\n";
    }
    gauge("pcclt_build_info",
          "build identity (constant 1; the labels are the payload)");
    o += std::string("pcclt_build_info{version=\"") + kPccltVersion +
         "\",uring=\"" + (net::uring::enabled() ? "1" : "0") +
         "\",zerocopy=\"" + (net::uring::zc_min_bytes() ? "1" : "0") +
         "\"} 1\n";
    gauge("pcclt_master_uptime_seconds",
          "seconds since this master process constructed its state machine");
    o += "pcclt_master_uptime_seconds " + num((now - start_ns_) / 1e9) + "\n";
    // ingest-queue health: a sustained depth near capacity (or any drops)
    // means the fold thread is not keeping up with the digest rate
    gauge("pcclt_master_digest_queue_depth",
          "telemetry digests waiting in the ingest queue");
    o += "pcclt_master_digest_queue_depth " +
         num(static_cast<uint64_t>(
             ingest_depth_.load(std::memory_order_relaxed))) +
         "\n";
    counter("pcclt_master_digest_queue_dropped_total",
            "telemetry digests dropped at the ingest-queue cap");
    o += "pcclt_master_digest_queue_dropped_total " +
         num(ingest_dropped_.load(std::memory_order_relaxed)) + "\n";
    gauge("pcclt_master_digest_queue_capacity",
          "ingest-queue digest cap (PCCLT_DIGEST_QUEUE_CAP)");
    o += "pcclt_master_digest_queue_capacity " +
         num(static_cast<uint64_t>(digest_queue_cap())) + "\n";
    // the master's OWN flight-recorder ring (the per-peer mirror rides the
    // digest): saturation is visible to a scraper, not just in artifacts
    {
        auto &rec = telemetry::Recorder::inst();
        gauge("pcclt_master_trace_ring_pushed",
              "events pushed into the master's flight-recorder ring");
        o += "pcclt_master_trace_ring_pushed " + num(rec.pushed()) + "\n";
        gauge("pcclt_master_trace_ring_dropped",
              "master flight-recorder events lost to ring wrap");
        o += "pcclt_master_trace_ring_dropped " + num(rec.dropped()) + "\n";
        gauge("pcclt_master_trace_ring_capacity",
              "master flight-recorder ring capacity");
        o += "pcclt_master_trace_ring_capacity " +
             num(static_cast<uint64_t>(telemetry::Recorder::ring_capacity())) +
             "\n";
    }

    // ---- latency histograms (critical-path attribution, docs/09) ----
    // Prometheus histogram exposition from the log2 grid: zero buckets are
    // elided (the `le` values present still define the boundaries), +Inf
    // always closes the series. Values are seconds.
    auto hist_le = [&](size_t i) -> std::string {
        char buf[32];
        snprintf(buf, sizeof buf, "%.9g", telemetry::hist_upper_ns(i) / 1e9);
        return buf;
    };
    auto render_hist = [&](const char *name, const std::string &labels,
                           const telemetry::HistSnapshot &h) {
        uint64_t cum = 0;
        for (size_t i = 0; i + 1 < telemetry::kHistBuckets; ++i) {
            if (!h.buckets[i]) continue;
            cum += h.buckets[i];
            o += std::string(name) + "_bucket{" + labels + ",le=\"" +
                 hist_le(i) + "\"} " + num(cum) + "\n";
        }
        cum += h.buckets[telemetry::kHistBuckets - 1];
        o += std::string(name) + "_bucket{" + labels + ",le=\"+Inf\"} " +
             num(cum) + "\n";
        o += std::string(name) + "_sum{" + labels + "} " + num(h.sum_ns / 1e9) +
             "\n";
        o += std::string(name) + "_count{" + labels + "} " + num(cum) + "\n";
    };
    auto histo = [&](const char *name, const char *help) {
        o += "# HELP ";
        o += name;
        o += ' ';
        o += help;
        o += "\n# TYPE ";
        o += name;
        o += " histogram\n";
    };
    // each family rendered in its own pass: a histogram family whose
    // bucket series are interleaved with other metrics is rejected by
    // strict OpenMetrics parsers (promtool: "metric families must be
    // grouped"), even though the classic server parser tolerates it
    auto each_phase = [&](auto &&fn) {
        for (const auto &[uuid, p] : fleet_peers_copy)
            for (const auto &[phase, h] : p.phase_hists) {
                if (h.empty()) continue;
                std::string labels =
                    "peer=\"" + uuid + "\",group=\"" +
                    num(static_cast<uint64_t>(p.group)) + "\",phase=\"" +
                    telemetry::phase_name(
                        static_cast<telemetry::Phase>(phase)) +
                    "\"";
                fn(labels, h);
            }
    };
    // ingest-thread fold latency (enqueue -> folded): the "is the fold
    // keeping up" distribution the master-scale bench gates on
    {
        auto h = fold_hist_.snapshot();
        histo("pcclt_master_digest_fold_seconds",
              "telemetry digest enqueue-to-folded latency on the ingest "
              "thread (log2 buckets)");
        uint64_t cum = 0;
        for (size_t i = 0; i + 1 < telemetry::kHistBuckets; ++i) {
            if (!h.buckets[i]) continue;
            cum += h.buckets[i];
            o += "pcclt_master_digest_fold_seconds_bucket{le=\"" + hist_le(i) +
                 "\"} " + num(cum) + "\n";
        }
        cum += h.buckets[telemetry::kHistBuckets - 1];
        o += "pcclt_master_digest_fold_seconds_bucket{le=\"+Inf\"} " +
             num(cum) + "\n";
        o += "pcclt_master_digest_fold_seconds_sum " + num(h.sum_ns / 1e9) +
             "\n";
        o += "pcclt_master_digest_fold_seconds_count " + num(cum) + "\n";
        gauge("pcclt_master_digest_fold_p50_seconds",
              "bucket-resolution median digest fold latency");
        o += "pcclt_master_digest_fold_p50_seconds " +
             num(h.quantile_ns(0.5) / 1e9) + "\n";
        gauge("pcclt_master_digest_fold_p99_seconds",
              "bucket-resolution p99 digest fold latency");
        o += "pcclt_master_digest_fold_p99_seconds " +
             num(h.quantile_ns(0.99) / 1e9) + "\n";
    }

    histo("pcclt_phase_latency_seconds",
          "per-peer data-plane phase latency distribution (log2 buckets)");
    each_phase([&](const std::string &labels, const telemetry::HistSnapshot &h) {
        render_hist("pcclt_phase_latency_seconds", labels, h);
    });
    gauge("pcclt_phase_latency_p50_seconds",
          "bucket-resolution median of the phase latency distribution");
    each_phase([&](const std::string &labels, const telemetry::HistSnapshot &h) {
        o += "pcclt_phase_latency_p50_seconds{" + labels + "} " +
             num(h.quantile_ns(0.5) / 1e9) + "\n";
    });
    gauge("pcclt_phase_latency_p99_seconds",
          "bucket-resolution p99 of the phase latency distribution");
    each_phase([&](const std::string &labels, const telemetry::HistSnapshot &h) {
        o += "pcclt_phase_latency_p99_seconds{" + labels + "} " +
             num(h.quantile_ns(0.99) / 1e9) + "\n";
    });

    // family-major, one loop per family: the text format requires a
    // family's samples to be contiguous (promlint.py enforces it; real
    // scrapers reject re-opened families), so the per-peer block cannot
    // be emitted peer-major
    auto each_peer = [&](const char *fam, auto &&val) {
        for (const auto &[uuid, p] : fleet_peers_copy)
            o += fam + ("{peer=\"" + uuid + "\",group=\"" +
                        num(static_cast<uint64_t>(p.group)) + "\"} ") +
                 val(p) + "\n";
    };
    counter("pcclt_peer_collectives_ok_total", "collectives completed ok, per peer");
    each_peer("pcclt_peer_collectives_ok_total",
              [&](const auto &p) { return num(p.collectives_ok); });
    gauge("pcclt_peer_last_seq", "newest collective seq the peer completed");
    each_peer("pcclt_peer_last_seq",
              [&](const auto &p) { return num(p.last_seq); });
    gauge("pcclt_peer_trace_ring_dropped",
          "peer flight-recorder events lost to ring wrap");
    each_peer("pcclt_peer_trace_ring_dropped",
              [&](const auto &p) { return num(p.ring_dropped); });
    gauge("pcclt_peer_trace_ring_pushed",
          "events pushed into the peer's flight-recorder ring");
    each_peer("pcclt_peer_trace_ring_pushed",
              [&](const auto &p) { return num(p.ring_pushed); });
    gauge("pcclt_peer_trace_ring_capacity",
          "the peer's flight-recorder ring capacity (dropped > 0 means its "
          "traces are truncated to the newest ring_capacity events)");
    each_peer("pcclt_peer_trace_ring_capacity",
              [&](const auto &p) { return num(p.ring_cap); });
    gauge("pcclt_peer_staleness_ms", "ms since the peer's last digest");
    each_peer("pcclt_peer_staleness_ms", [&](const auto &p) {
        return num((now - p.last_digest_ns) / 1'000'000);
    });
    gauge("pcclt_peer_up", "1 while the peer's control session is live");
    each_peer("pcclt_peer_up", [&](const auto &p) {
        return std::string(p.departed ? "0" : "1");
    });

    // ---- bounded per-edge cardinality (fleet scale, docs/09) ----
    // Full per-edge series only for the top-K edges ranked worst-first by
    // (wd_state desc, straggler, stall_ratio desc, traffic desc) under
    // PCCLT_METRICS_EDGE_TOPK (0 = unbounded). The remainder is rolled up
    // per reporting peer below — at world=1000 the flat exposition would
    // be O(world^2) series, which no scraper (or scrape window) survives.
    const size_t topk = metrics_edge_topk();
    struct Rollup {
        uint64_t edges = 0, tx_bytes = 0, rx_bytes = 0, stragglers = 0;
        double max_stall = 0;
        uint32_t max_wd = 0;
    };
    std::map<std::pair<std::string, std::string>, const EdgeHealth *> detail;
    std::map<std::string, Rollup> rollup;
    if (topk == 0 || fleet_edges_copy.size() <= topk) {
        for (const auto &[key, e] : fleet_edges_copy) detail.emplace(key, &e);
    } else {
        std::vector<const EdgeHealth *> ranked;
        ranked.reserve(fleet_edges_copy.size());
        for (const auto &[key, e] : fleet_edges_copy) ranked.push_back(&e);
        auto worse = [](const EdgeHealth *a, const EdgeHealth *b) {
            if (a->wd_state != b->wd_state) return a->wd_state > b->wd_state;
            if (a->straggler != b->straggler) return a->straggler;
            if (a->stall_ratio != b->stall_ratio)
                return a->stall_ratio > b->stall_ratio;
            return a->tx_bytes + a->rx_bytes > b->tx_bytes + b->rx_bytes;
        };
        std::nth_element(ranked.begin(),
                         ranked.begin() + static_cast<ptrdiff_t>(topk),
                         ranked.end(), worse);
        for (size_t i = 0; i < topk; ++i)
            detail.emplace(
                std::make_pair(ranked[i]->from_uuid, ranked[i]->to_endpoint),
                ranked[i]);
        for (size_t i = topk; i < ranked.size(); ++i) {
            const EdgeHealth *e = ranked[i];
            auto &r = rollup[e->from_uuid];
            ++r.edges;
            r.tx_bytes += e->tx_bytes;
            r.rx_bytes += e->rx_bytes;
            if (e->straggler) ++r.stragglers;
            r.max_stall = std::max(r.max_stall, e->stall_ratio);
            r.max_wd = std::max(r.max_wd, e->wd_state);
        }
    }

    // family-major for the same contiguity reason as the peer block above
    auto each_edge = [&](const char *fam, auto &&val) {
        for (const auto &[key, ep] : detail) {
            const EdgeHealth &e = *ep;
            o += fam + ("{from=\"" + e.from_uuid + "\",to=\"" +
                        e.to_endpoint + "\",to_peer=\"" + e.to_uuid +
                        "\"} ") + val(e) + "\n";
        }
    };
    gauge("pcclt_edge_tx_mbps", "EWMA achieved egress per edge, Mbit/s");
    each_edge("pcclt_edge_tx_mbps",
              [&](const EdgeHealth &e) { return num(e.tx_mbps); });
    gauge("pcclt_edge_rx_mbps", "EWMA achieved ingress per edge, Mbit/s");
    each_edge("pcclt_edge_rx_mbps",
              [&](const EdgeHealth &e) { return num(e.rx_mbps); });
    gauge("pcclt_edge_stall_ratio", "EWMA receiver wire-stall per interval");
    each_edge("pcclt_edge_stall_ratio",
              [&](const EdgeHealth &e) { return num(e.stall_ratio); });
    counter("pcclt_edge_tx_bytes_total", "cumulative payload bytes sent on the edge");
    each_edge("pcclt_edge_tx_bytes_total",
              [&](const EdgeHealth &e) { return num(e.tx_bytes); });
    counter("pcclt_edge_rx_bytes_total", "cumulative payload bytes received on the edge");
    each_edge("pcclt_edge_rx_bytes_total",
              [&](const EdgeHealth &e) { return num(e.rx_bytes); });
    gauge("pcclt_edge_expected_mbps", "bandwidth-matrix entry for the edge");
    each_edge("pcclt_edge_expected_mbps",
              [&](const EdgeHealth &e) { return num(e.expected_mbps); });
    gauge("pcclt_edge_straggler",
          "1 while measured throughput sits below the straggler threshold");
    each_edge("pcclt_edge_straggler", [&](const EdgeHealth &e) {
        return std::string(e.straggler ? "1" : "0");
    });
    gauge("pcclt_edge_wd_state",
          "reporter's data-plane watchdog verdict: 0 ok, 1 suspect, "
          "2 confirmed (relaying in-collective)");
    each_edge("pcclt_edge_wd_state", [&](const EdgeHealth &e) {
        return num(static_cast<uint64_t>(e.wd_state));
    });
    // per-peer rollups of the edges omitted from detail: conservation
    // holds (detail + rollup covers every edge) and the worst omitted
    // stall/wd verdict stays visible even when its edge does not
    if (!rollup.empty()) {
        gauge("pcclt_peer_edges_rolled_up",
              "edges beyond the PCCLT_METRICS_EDGE_TOPK detail set, per "
              "reporting peer");
        for (const auto &[peer, r] : rollup)
            o += "pcclt_peer_edges_rolled_up{peer=\"" + peer + "\"} " +
                 num(r.edges) + "\n";
        counter("pcclt_peer_rollup_tx_bytes_total",
                "cumulative payload bytes sent on rolled-up edges");
        for (const auto &[peer, r] : rollup)
            o += "pcclt_peer_rollup_tx_bytes_total{peer=\"" + peer + "\"} " +
                 num(r.tx_bytes) + "\n";
        counter("pcclt_peer_rollup_rx_bytes_total",
                "cumulative payload bytes received on rolled-up edges");
        for (const auto &[peer, r] : rollup)
            o += "pcclt_peer_rollup_rx_bytes_total{peer=\"" + peer + "\"} " +
                 num(r.rx_bytes) + "\n";
        gauge("pcclt_peer_rollup_max_stall_ratio",
              "worst EWMA wire-stall among rolled-up edges");
        for (const auto &[peer, r] : rollup)
            o += "pcclt_peer_rollup_max_stall_ratio{peer=\"" + peer + "\"} " +
                 num(r.max_stall) + "\n";
        gauge("pcclt_peer_rollup_max_wd_state",
              "worst watchdog verdict among rolled-up edges");
        for (const auto &[peer, r] : rollup)
            o += "pcclt_peer_rollup_max_wd_state{peer=\"" + peer + "\"} " +
                 num(static_cast<uint64_t>(r.max_wd)) + "\n";
        gauge("pcclt_peer_rollup_stragglers",
              "flagged straggler edges among rolled-up edges");
        for (const auto &[peer, r] : rollup)
            o += "pcclt_peer_rollup_stragglers{peer=\"" + peer + "\"} " +
                 num(r.stragglers) + "\n";
    }
    // per-(edge, phase) latency distributions: the histogram that names
    // the HOP a stage's wall time / stall tail binds on. One pass per
    // family, same grouping rule as the phase histograms above.
    histo("pcclt_edge_stage_latency_seconds",
          "per-edge ring-stage wall-time distribution (inbound hop)");
    for (const auto &[key, ep] : detail) {
        const EdgeHealth &e = *ep;
        if (e.stage_wire_hist.empty()) continue;
        std::string labels = "from=\"" + e.from_uuid + "\",to=\"" +
                             e.to_endpoint + "\",to_peer=\"" + e.to_uuid +
                             "\"";
        render_hist("pcclt_edge_stage_latency_seconds", labels,
                    e.stage_wire_hist);
    }
    histo("pcclt_edge_stall_latency_seconds",
          "per-edge receiver wire-stall distribution (per stage)");
    for (const auto &[key, ep] : detail) {
        const EdgeHealth &e = *ep;
        if (e.stall_hist.empty()) continue;
        std::string labels = "from=\"" + e.from_uuid + "\",to=\"" +
                             e.to_endpoint + "\",to_peer=\"" + e.to_uuid +
                             "\"";
        render_hist("pcclt_edge_stall_latency_seconds", labels,
                    e.stall_hist);
    }
    return o;
}

std::string MasterState::render_health_json(bool include_history) const {
    const uint64_t now = telemetry::now_ns();
    std::string o;
    o.reserve(2048);
    // copy-then-render, as in render_metrics: never build strings while
    // holding the lock the fold thread needs per digest
    std::map<std::string, PeerHealth> fleet_peers_copy;
    std::map<std::pair<std::string, std::string>, EdgeHealth> fleet_edges_copy;
    uint64_t digests_total_copy, stragglers_copy;
    uint64_t incidents_copy, incidents_suppressed_copy;
    std::deque<IncidentRec> incidents_recent_copy;
    std::deque<HealthSample> history_copy;
    size_t world_copy, clients_copy, limbo_copy;
    {
        MutexLock lk(health_mu_);
        fleet_peers_copy = fleet_peers_;
        fleet_edges_copy = fleet_edges_;
        digests_total_copy = digests_total_;
        stragglers_copy = stragglers_flagged_;
        incidents_copy = incidents_total_;
        incidents_suppressed_copy = incidents_suppressed_;
        incidents_recent_copy = recent_incidents_;
        if (include_history) history_copy = health_history_;
        world_copy = health_world_;
        clients_copy = health_clients_;
        limbo_copy = health_limbo_;
    }
    o += "{\"epoch\":" + num(epoch_);
    o += ",\"world_size\":" + num(static_cast<uint64_t>(world_copy));
    o += ",\"clients\":" + num(static_cast<uint64_t>(clients_copy));
    o += ",\"limbo_sessions\":" + num(static_cast<uint64_t>(limbo_copy));
    o += ",\"telemetry_digests\":" + num(digests_total_copy);
    o += ",\"stragglers_flagged\":" + num(stragglers_copy);
    o += ",\"incidents_total\":" + num(incidents_copy);
    o += ",\"incidents_suppressed\":" + num(incidents_suppressed_copy);
    // build identity + process age: mirrors the /metrics pcclt_build_info
    // gauge so a /health-only consumer sees the same facts
    o += ",\"build\":{\"version\":";
    json_str(o, kPccltVersion);
    o += ",\"uring\":";
    o += net::uring::enabled() ? "true" : "false";
    o += ",\"zerocopy\":";
    o += net::uring::zc_min_bytes() ? "true" : "false";
    o += "}";
    o += ",\"uptime_seconds\":" + num((now - start_ns_) / 1e9);
    o += ",\"digest_queue\":{\"depth\":" +
         num(static_cast<uint64_t>(
             ingest_depth_.load(std::memory_order_relaxed))) +
         ",\"dropped\":" +
         num(ingest_dropped_.load(std::memory_order_relaxed)) +
         ",\"capacity\":" + num(static_cast<uint64_t>(digest_queue_cap())) +
         "}";
    if (include_history) {
        // the /health?history=1 ring: newest-last fleet snapshots, sampled
        // by the fold thread every PCCLT_HEALTH_HISTORY_MS
        o += ",\"history\":[";
        bool first_h = true;
        for (const auto &s : history_copy) {
            if (!first_h) o += ',';
            first_h = false;
            o += "{\"age_ms\":" + num((now - s.t_ns) / 1'000'000);
            o += ",\"world\":" + num(static_cast<uint64_t>(s.world));
            o += ",\"clients\":" + num(static_cast<uint64_t>(s.clients));
            o += ",\"limbo\":" + num(static_cast<uint64_t>(s.limbo));
            o += ",\"peers\":" + num(static_cast<uint64_t>(s.peers));
            o += ",\"edges\":" + num(static_cast<uint64_t>(s.edges));
            o += ",\"digests\":" + num(s.digests);
            o += ",\"digest_rate\":" + num(s.digest_rate);
            o += ",\"stragglers\":" + num(s.stragglers);
            o += ",\"incidents\":" + num(s.incidents);
            o += ",\"suppressed\":" + num(s.suppressed);
            o += ",\"queue_depth\":" + num(static_cast<uint64_t>(s.queue_depth));
            o += ",\"queue_dropped\":" + num(s.queue_dropped);
            o += '}';
        }
        o += "]";
    }
    // newest-last recent incident ids: the pointer from a live /health
    // scrape into the PCCLT_INCIDENT_DIR bundle directories
    o += ",\"incidents\":[";
    {
        bool first_inc = true;
        for (const auto &inc : incidents_recent_copy) {
            if (!first_inc) o += ',';
            first_inc = false;
            o += "{\"id\":";
            json_str(o, inc.id);
            o += ",\"trigger\":";
            json_str(o, inc.trigger);
            o += ",\"age_ms\":" + num((now - inc.t_ns) / 1'000'000);
            o += '}';
        }
    }
    o += "]";
    o += ",\"peers\":[";
    bool first = true;
    for (const auto &[uuid, p] : fleet_peers_copy) {
        if (!first) o += ',';
        first = false;
        o += "{\"uuid\":";
        json_str(o, uuid);
        o += ",\"group\":" + num(static_cast<uint64_t>(p.group));
        o += ",\"last_seq\":" + num(p.last_seq);
        o += ",\"collectives_ok\":" + num(p.collectives_ok);
        o += ",\"ring_dropped\":" + num(p.ring_dropped);
        o += ",\"ring_pushed\":" + num(p.ring_pushed);
        o += ",\"ring_cap\":" + num(p.ring_cap);
        o += ",\"digests\":" + num(p.digests);
        o += ",\"staleness_ms\":" + num((now - p.last_digest_ns) / 1'000'000);
        o += ",\"up\":";
        o += p.departed ? "false" : "true";
        o += '}';
    }
    o += "],\"edges\":[";
    first = true;
    for (const auto &[key, e] : fleet_edges_copy) {
        if (!first) o += ',';
        first = false;
        o += "{\"from\":";
        json_str(o, e.from_uuid);
        o += ",\"to\":";
        json_str(o, e.to_endpoint);
        o += ",\"to_peer\":";
        json_str(o, e.to_uuid);
        o += ",\"tx_mbps\":" + num(e.tx_mbps);
        o += ",\"rx_mbps\":" + num(e.rx_mbps);
        o += ",\"stall_ratio\":" + num(e.stall_ratio);
        o += ",\"tx_bytes\":" + num(e.tx_bytes);
        o += ",\"rx_bytes\":" + num(e.rx_bytes);
        o += ",\"expected_mbps\":" + num(e.expected_mbps);
        o += ",\"straggler\":";
        o += e.straggler ? "true" : "false";
        o += ",\"wd_state\":" + num(static_cast<uint64_t>(e.wd_state));
        o += '}';
    }
    o += "]}";
    return o;
}

// ---------- disconnect recovery ----------

std::vector<Outbox> MasterState::on_disconnect(uint64_t conn) {
    std::vector<Outbox> out;
    auto it = clients_.find(conn);
    if (it == clients_.end()) return out;
    ClientInfo gone = it->second;
    clients_.erase(it);
    if (gone.observer) {
        // telemetry-only session: nothing consensus-side to unwind (never
        // accepted, never journaled, no bandwidth rows) — just mark its
        // fleet record down and refresh the published counts
        IngestItem dep;
        dep.kind = IngestItem::kDeparted;
        dep.peer = gone.uuid;
        enqueue(std::move(dep));
        publish_health_summary();
        return out;
    }
    if (journal_) journal_->record_client_remove(gone.uuid);
    PLOG(kInfo) << "client " << proto::uuid_str(gone.uuid) << " disconnected, world="
                << world_size();
    telemetry::Recorder::inst().instant("membership", "master_peer_left",
                                        "group", gone.peer_group, "world",
                                        world_size());
    remove_client(out, gone);
    return out;
}

// shared tail of on_disconnect and limbo expiry: the client is already out
// of clients_/limbo_ — prune its traces and re-check every consensus
void MasterState::remove_client(std::vector<Outbox> &out, const ClientInfo &gone) {
    bandwidth_.forget(gone.uuid);
    {
        // keep the fold thread's mirrors in step: bandwidth rows gone,
        // endpoint index entry released, fleet record marked down
        // (pcclt_peer_up 0; the next digest after a session resume revives)
        IngestItem fg;
        fg.kind = IngestItem::kForget;
        fg.peer = gone.uuid;
        enqueue(std::move(fg));
        IngestItem er;
        er.kind = IngestItem::kEndpointRemove;
        net::Addr a = gone.ip;
        a.port = gone.p2p_port;
        er.endpoint = a.str();
        er.peer = gone.uuid;
        enqueue(std::move(er));
        IngestItem dep;
        dep.kind = IngestItem::kDeparted;
        dep.peer = gone.uuid;
        enqueue(std::move(dep));
    }
    publish_health_summary();

    // abort running collectives in its group, prune its votes from ops
    abort_group_collectives(out, gone.peer_group);
    auto git = groups_.find(gone.peer_group);
    if (git != groups_.end()) {
        for (auto &[_, op] : git->second.ops) {
            op.initiated.erase(gone.uuid);
            op.completed.erase(gone.uuid);
        }
        // an op whose every initiator departed before commence has no
        // observable state (no commence went out): drop the record instead
        // of leaking it in the op table until the group empties (found by
        // the pcclt-verify model checker's quiescence backstop)
        for (auto it = git->second.ops.begin(); it != git->second.ops.end();) {
            if (!it->second.commenced && it->second.initiated.empty())
                it = git->second.ops.erase(it);
            else
                ++it;
        }
        // last member gone: reset the group's shared-state revision tracking.
        // A fresh cohort is a logical resume (any first revision legal, like
        // a restarted master) — without this, workers restarted from an older
        // checkpoint against a long-lived master could never sync again.
        // Limbo members count as present: their sessions may still resume.
        if (group_members(gone.peer_group).empty() &&
            !group_frozen(gone.peer_group)) {
            git->second = GroupState{};
            if (journal_) {
                journal_->record_group(gone.peer_group, 0, false);
                journal_->record_ring(gone.peer_group, {});
            }
            PLOG(kInfo) << "peer group " << gone.peer_group
                        << " emptied; shared-state revision tracking reset";
        }
    }
    recheck_all(out);
    // Moot-vote decline. If the departed client leaves NO pending joiner
    // and recheck_all did not open a round, every standing topology vote
    // is now waiting for a round that can never form: the app contract is
    // "vote while peers are pending", so the remaining non-voters never
    // will, and each parked voter would sit out its full 120 s conn-info
    // timeout and surface a spurious failure. Decline the votes exactly
    // like the mid-round tie-break does (kM2CTopologyDeferred = no-op
    // success; the voter re-votes when peers are pending again). Found by
    // the pcclt-verify model checker (scenario collective_crash: the
    // pending joiner crashes out from under its voter).
    if (!establish_in_flight_) {
        bool any_pending = false;
        for (auto &[_, c] : clients_)
            if (!c.accepted && !c.observer) any_pending = true;
        if (!any_pending)
            for (auto &[_, c] : clients_)
                // admission votes are never moot: their holder is PARKED in
                // a non-deferrable establish wait, and the vote is what lets
                // the next round form for it (code-review hardening)
                if (c.accepted && c.vote_topology && !c.admission_vote) {
                    c.vote_topology = false;
                    out.push_back(
                        {c.conn_id, PacketType::kM2CTopologyDeferred, {}});
                    PLOG(kDebug) << "topology vote of " << proto::uuid_str(c.uuid)
                                 << " declined: no pending peers left to admit";
                }
    }
}

void MasterState::recheck_all(std::vector<Outbox> &out) {
    // the reference re-checks EVERY consensus on every disconnect
    // (ccoip_master_handler.cpp:1312-1400); same discipline here
    check_establish(out);
    check_topology(out);
    std::vector<std::pair<uint32_t, uint64_t>> keys;
    for (auto &[gid, g] : groups_)
        for (auto &[tag, _] : g.ops) keys.emplace_back(gid, tag);
    for (auto &[gid, tag] : keys) check_collective(out, gid, tag);
    std::vector<uint32_t> gids;
    for (auto &[gid, _] : groups_) gids.push_back(gid);
    for (auto gid : gids) {
        check_shared_state(out, gid);
        // a disconnect may have been the last missing dist-done
        auto members = group_members(gid);
        if (!members.empty() && groups_[gid].sync_in_flight) {
            bool all = true;
            for (auto *m : members)
                if (m->sync_req && !m->dist_done) all = false;
            if (all && members[0]) {
                auto extra = on_dist_done(members[0]->conn_id);
                out.insert(out.end(), extra.begin(), extra.end());
            }
        }
    }
    check_optimize(out);
}

} // namespace pcclt::master
