#include "schedule.hpp"

#include <algorithm>
#include <cstdlib>
#include <map>
#include <tuple>

#include "protocol.hpp"

namespace pcclt::sched {

namespace {

// Matrix entries <= 0 are unmeasured edges; price them pessimistically so
// the planner never routes load-bearing traffic over an edge it has never
// seen, but keep a floor so a zeroed row cannot divide by zero.
constexpr double kDefaultMbps = 100.0;
constexpr double kFloorMbps = 0.1;
// A relayed span crosses two edges store-and-forward; windows pipeline
// the two hops, so the effective rate is the detour minimum derated, not
// halved twice. Matches the PR-10 ladder's observed relay throughput.
constexpr double kRelayDerate = 0.5;
// Only prefer the relay when the detour clearly beats the direct edge —
// the relay peer spends CPU and NIC on someone else's bytes.
constexpr double kRelayGain = 1.5;

uint64_t env_size(const char *name, uint64_t dflt) {
    if (const char *e = std::getenv(name)) {
        long long v = atoll(e);
        if (v > 0) return static_cast<uint64_t>(v);
    }
    return dflt;
}

size_t chunk_len(uint64_t count, uint32_t world, uint32_t c) {
    uint64_t base = count / world, rem = count % world;
    return base + (c < rem ? 1 : 0);
}

uint64_t chunk_start(uint64_t count, uint32_t world, uint32_t c) {
    uint64_t base = count / world, rem = count % world;
    return c * base + std::min<uint64_t>(c, rem);
}

} // namespace

const char *coll_name(Coll c) {
    switch (c) {
    case Coll::kAllReduce: return "allreduce";
    case Coll::kAllGather: return "allgather";
    case Coll::kReduceScatter: return "reduce_scatter";
    case Coll::kBroadcast: return "broadcast";
    case Coll::kAllToAll: return "alltoall";
    }
    return "?";
}

const char *algo_name(Algo a) {
    switch (a) {
    case Algo::kRing: return "ring";
    case Algo::kTree: return "tree";
    case Algo::kButterfly: return "butterfly";
    case Algo::kMesh: return "mesh";
    case Algo::kRelayRing: return "relay";
    }
    return "?";
}

Coll coll_of(proto::RedOp op) {
    switch (op) {
    case proto::RedOp::kGather: return Coll::kAllGather;
    case proto::RedOp::kReduceScatter: return Coll::kReduceScatter;
    case proto::RedOp::kBroadcast: return Coll::kBroadcast;
    case proto::RedOp::kAllToAll: return Coll::kAllToAll;
    default: return Coll::kAllReduce;
    }
}

std::optional<Algo> algo_from_name(const std::string &s) {
    if (s == "ring") return Algo::kRing;
    if (s == "tree") return Algo::kTree;
    if (s == "butterfly") return Algo::kButterfly;
    if (s == "mesh") return Algo::kMesh;
    if (s == "relay") return Algo::kRelayRing;
    return std::nullopt;
}

uint8_t size_class(uint64_t bytes) {
    uint64_t small_max = env_size("PCCLT_SCHED_SMALL_MAX", 256ull << 10);
    uint64_t large_min = env_size("PCCLT_SCHED_LARGE_MIN", 8ull << 20);
    if (large_min <= small_max) large_min = small_max + 1;
    if (bytes <= small_max) return 0;
    if (bytes >= large_min) return 2;
    return 1;
}

bool algo_valid(Coll c, Algo a, uint32_t world) {
    if (world < 2) return a == Algo::kRing;
    switch (c) {
    case Coll::kAllReduce:
        if (a == Algo::kButterfly)
            return world >= 2 && (world & (world - 1)) == 0;
        return a == Algo::kRing || a == Algo::kRelayRing;
    case Coll::kAllGather:
    case Coll::kReduceScatter:
        return a == Algo::kRing;
    case Coll::kBroadcast:
        return a == Algo::kRing || a == Algo::kTree;
    case Coll::kAllToAll:
        // the rotation tag grid is (world-1)*world wide; cap it well under
        // the 0x8000 meta bit (mesh covers big worlds anyway)
        return a == Algo::kMesh || (a == Algo::kRing && world <= 64);
    }
    return false;
}

// ---- table codec ----

const Entry *Table::find(Coll c, uint8_t sc) const {
    for (const auto &e : entries)
        if (e.coll == static_cast<uint8_t>(c) && e.size_class == sc) return &e;
    return nullptr;
}

void Table::encode_to(wire::Writer &w) const {
    w.u64(version);
    w.u32(static_cast<uint32_t>(entries.size()));
    for (const auto &e : entries) {
        w.u8(e.coll);
        w.u8(e.size_class);
        w.u8(e.algo);
        w.u32(e.root);
    }
}

std::optional<Table> Table::decode_from(wire::Reader &r) {
    Table t;
    t.version = r.u64();
    uint32_t n = r.u32();
    if (n > 4096) return std::nullopt;
    t.entries.reserve(n);
    for (uint32_t i = 0; i < n; ++i) {
        Entry e;
        e.coll = r.u8();
        e.size_class = r.u8();
        e.algo = r.u8();
        e.root = r.u32();
        t.entries.push_back(e);
    }
    return t;
}

std::vector<uint8_t> Table::encode() const {
    wire::Writer w;
    encode_to(w);
    return w.take();
}

std::optional<Table> Table::decode(std::span<const uint8_t> b) {
    try {
        wire::Reader r(b);
        return decode_from(r);
    } catch (const std::exception &) {
        return std::nullopt;
    }
}

// ---- cost model ----

double CostModel::bw(uint32_t i, uint32_t j) const {
    double v = 0;
    if (i < n && j < n && mbps.size() >= static_cast<size_t>(n) * n)
        v = mbps[static_cast<size_t>(i) * n + j];
    if (v <= 0) v = kDefaultMbps;
    return std::max(v, kFloorMbps);
}

double CostModel::cap(uint32_t i) const {
    double c = kFloorMbps;
    for (uint32_t j = 0; j < n; ++j)
        if (j != i) c = std::max(c, bw(i, j));
    return c;
}

double CostModel::t(uint32_t i, uint32_t j, double bytes) const {
    return bytes * 8.0 / (bw(i, j) * 1e6);
}

double CostModel::cost(Coll c, Algo a, const std::vector<uint32_t> &ring,
                       uint32_t root, double bytes) const {
    const uint32_t w = static_cast<uint32_t>(ring.size());
    if (w < 2) return 0;
    auto ring_min = [&] {
        double m = 1e18;
        for (uint32_t i = 0; i < w; ++i)
            m = std::min(m, bw(ring[i], ring[(i + 1) % w]));
        return m;
    };
    // star from `root`: one alpha, the slowest spoke, and the root's NIC
    // serializing (w-1) copies — per-edge emulation would not charge the
    // NIC, but physical hubs do and the planner must not be fooled.
    auto star = [&](uint32_t r, double b) {
        double slow = 0;
        for (uint32_t j = 0; j < w; ++j)
            if (ring[j] != r) slow = std::max(slow, t(r, ring[j], b));
        double nic = (w - 1) * b * 8.0 / (cap(r) * 1e6);
        return alpha_s + std::max(slow, nic);
    };
    switch (c) {
    case Coll::kAllReduce: {
        const double chunk = bytes / w;
        if (a == Algo::kRing)
            return 2.0 * (w - 1) * (alpha_s + chunk * 8.0 / (ring_min() * 1e6));
        if (a == Algo::kRelayRing) {
            // detour the single worst ring edge via its best third peer
            double mn = 1e18;
            uint32_t bi = 0;
            for (uint32_t i = 0; i < w; ++i) {
                double e = bw(ring[i], ring[(i + 1) % w]);
                if (e < mn) { mn = e; bi = i; }
            }
            const uint32_t src = ring[bi], dst = ring[(bi + 1) % w];
            double detour = 0;
            for (uint32_t k = 0; k < w; ++k) {
                if (ring[k] == src || ring[k] == dst) continue;
                detour = std::max(detour,
                                  std::min(bw(src, ring[k]), bw(ring[k], dst)));
            }
            double eff = std::max(mn, kRelayDerate * detour);
            // second-worst direct edge still bounds the ring
            double rest = 1e18;
            for (uint32_t i = 0; i < w; ++i)
                if (i != bi)
                    rest = std::min(rest, bw(ring[i], ring[(i + 1) % w]));
            eff = std::min(eff, rest);
            return 2.0 * (w - 1) *
                   (1.5 * alpha_s + chunk * 8.0 / (eff * 1e6));
        }
        if (a == Algo::kButterfly) {
            double worst = 1e18;
            for (uint32_t bit = 1; bit < w; bit <<= 1)
                for (uint32_t r = 0; r < w; ++r)
                    worst = std::min(worst, bw(ring[r], ring[r ^ bit]));
            uint32_t rounds = 0;
            for (uint32_t bit = 1; bit < w; bit <<= 1) ++rounds;
            return rounds * (alpha_s + bytes * 8.0 / (worst * 1e6));
        }
        if (a == Algo::kTree)  // fan-in reduce + fan-out bcast (cost only)
            return star(root, bytes) * 2.0;
        return 1e18;
    }
    case Coll::kAllGather:
    case Coll::kReduceScatter: {
        const double chunk = bytes / w;
        if (a == Algo::kRing)
            return (w - 1) * (alpha_s + chunk * 8.0 / (ring_min() * 1e6));
        return 1e18;
    }
    case Coll::kBroadcast: {
        if (a == Algo::kTree) return star(root, bytes);
        if (a == Algo::kRing) {
            // pipelined chain from the root along ring order: fill alphas
            // plus the payload over the slowest chain edge
            double mn = 1e18;
            uint32_t rpos = 0;
            for (uint32_t i = 0; i < w; ++i)
                if (ring[i] == root) rpos = i;
            for (uint32_t s = 0; s + 1 < w; ++s)
                mn = std::min(mn, bw(ring[(rpos + s) % w],
                                     ring[(rpos + s + 1) % w]));
            return (w - 1) * alpha_s + bytes * 8.0 / (mn * 1e6);
        }
        return 1e18;
    }
    case Coll::kAllToAll: {
        const double block = bytes / w;
        if (a == Algo::kMesh) {
            double slow = 0;
            for (uint32_t i = 0; i < w; ++i) {
                for (uint32_t j = 0; j < w; ++j)
                    if (i != j) slow = std::max(slow, t(ring[i], ring[j], block));
                slow = std::max(slow, (w - 1) * block * 8.0 /
                                          (cap(ring[i]) * 1e6));
            }
            return alpha_s + slow;
        }
        if (a == Algo::kRing)
            // rotation: the block at distance r rides r sequential hops
            return (static_cast<double>(w) * (w - 1) / 2.0) *
                   (alpha_s + block * 8.0 / (ring_min() * 1e6));
        return 1e18;
    }
    }
    return 1e18;
}

Choice choose(const CostModel &m, Coll c, const std::vector<uint32_t> &ring,
              uint64_t bytes) {
    const uint32_t w = static_cast<uint32_t>(ring.size());
    Choice best{Algo::kRing, 0,
                m.cost(c, Algo::kRing, ring, ring.empty() ? 0 : ring[0],
                       static_cast<double>(bytes))};
    if (!schedule_enabled() || w < 3) return best;
    if (auto f = forced_algo()) {
        if (algo_valid(c, *f, w)) {
            Choice ch{*f, 0, m.cost(c, *f, ring, ring[0],
                                    static_cast<double>(bytes))};
            if (*f == Algo::kRelayRing) {
                double mn = 1e18;
                for (uint32_t i = 0; i < w; ++i) {
                    double e = m.bw(ring[i], ring[(i + 1) % w]);
                    if (e < mn) { mn = e; ch.root = i; }
                }
            }
            return ch;
        }
        return best;
    }
    auto consider = [&](Algo a, uint32_t root_ring_idx, double cost) {
        if (cost < best.cost * 0.99) best = Choice{a, root_ring_idx, cost};
    };
    const double b = static_cast<double>(bytes);
    switch (c) {
    case Coll::kAllReduce: {
        if (algo_valid(c, Algo::kButterfly, w))
            consider(Algo::kButterfly, 0,
                     m.cost(c, Algo::kButterfly, ring, 0, b));
        double mn = 1e18;
        uint32_t bi = 0;
        for (uint32_t i = 0; i < w; ++i) {
            double e = m.bw(ring[i], ring[(i + 1) % w]);
            if (e < mn) { mn = e; bi = i; }
        }
        double rc = m.cost(c, Algo::kRelayRing, ring, 0, b);
        if (rc * kRelayGain < best.cost) consider(Algo::kRelayRing, bi, rc);
        break;
    }
    case Coll::kBroadcast: {
        // the real root is per-op; score each algo averaged over roots
        double ring_avg = 0, tree_avg = 0;
        for (uint32_t r = 0; r < w; ++r) {
            ring_avg += m.cost(c, Algo::kRing, ring, ring[r], b);
            tree_avg += m.cost(c, Algo::kTree, ring, ring[r], b);
        }
        best.cost = ring_avg / w;
        consider(Algo::kTree, 0, tree_avg / w);
        break;
    }
    case Coll::kAllToAll:
        consider(Algo::kMesh, 0, m.cost(c, Algo::kMesh, ring, ring[0], b));
        break;
    case Coll::kAllGather:
    case Coll::kReduceScatter:
        break;  // ring is the only executable schedule today
    }
    return best;
}

Table synthesize(const CostModel &m, const std::vector<uint32_t> &ring,
                 uint64_t version) {
    // representative payloads per size class (mid-class, honest defaults)
    const uint64_t rep[kNumSizeClasses] = {64ull << 10, 2ull << 20,
                                           32ull << 20};
    Table t;
    t.version = version;
    for (uint8_t c = 0; c < kNumColls; ++c) {
        for (uint8_t sc = 0; sc < kNumSizeClasses; ++sc) {
            Choice ch = choose(m, static_cast<Coll>(c), ring, rep[sc]);
            t.entries.push_back(Entry{c, sc, static_cast<uint8_t>(ch.algo),
                                      ch.root});
        }
    }
    return t;
}

// ---- step programs ----

Program expand(Coll c, Algo a, uint32_t n, uint32_t rank, uint32_t root,
               uint64_t bytes) {
    Program p;
    if (n < 2) return p;
    const uint32_t succ = (rank + 1) % n, pred = (rank + n - 1) % n;
    switch (c) {
    case Coll::kBroadcast: {
        if (a == Algo::kTree) {
            if (rank == root) {
                for (uint32_t j = 0; j < n; ++j)
                    if (j != root)
                        p.push_back({Step::kSend, j, 0, bytes, kXferBcast + j});
            } else {
                p.push_back({Step::kRecv, root, 0, bytes, kXferBcast + rank});
            }
        } else {  // chain along the ring from the root
            const uint32_t d = (rank + n - root) % n;
            if (d > 0)
                p.push_back({static_cast<uint8_t>(d + 1 < n ? Step::kRecvForward
                                                            : Step::kRecv),
                             pred, 0, bytes, kXferBcast + d - 1});
            if (d + 1 < n)
                p.push_back({Step::kSend, succ, 0, bytes, kXferBcast + d});
        }
        break;
    }
    case Coll::kAllToAll: {
        const uint64_t b = bytes / n;  // bytes = total per-rank payload
        if (a == Algo::kMesh) {
            p.push_back({Step::kCopy, rank, rank * b, b, 0});
            for (uint32_t j = 0; j < n; ++j)
                if (j != rank)
                    p.push_back({Step::kSend, j, j * b, b, kXferA2A + rank});
            for (uint32_t i = 0; i < n; ++i)
                if (i != rank)
                    p.push_back({Step::kRecv, i, i * b, b, kXferA2A + i});
        } else {  // rotation: round r's block rides r sequential ring hops
            p.push_back({Step::kCopy, rank, rank * b, b, 0});
            for (uint32_t r = 1; r < n; ++r) {
                for (uint32_t h = 1; h <= r; ++h) {
                    const uint32_t x = kXferA2A + (r - 1) * n + (h - 1);
                    p.push_back({Step::kSend, succ, 0, b, x});
                    p.push_back({static_cast<uint8_t>(
                                     h < r ? Step::kRecvForward : Step::kRecv),
                                 pred, 0, b, x});
                }
            }
        }
        break;
    }
    case Coll::kAllReduce: {
        if (a == Algo::kButterfly) {
            uint32_t k = 0;
            for (uint32_t bit = 1; bit < n; bit <<= 1, ++k) {
                const uint32_t partner = rank ^ bit;
                p.push_back({Step::kSend, partner, 0, bytes, kXferFly + k});
                p.push_back({Step::kRecvReduce, partner, 0, bytes,
                             kXferFly + k});
            }
            break;
        }
        // ring / relay-ring: reduce-scatter stages then all-gather stages,
        // the same tag grid ring_allreduce drives (0x0000.. / 0x4000..)
        const uint64_t cnt = bytes;  // treat as element-granular bytes
        for (uint32_t s = 0; s + 1 < n; ++s) {
            const uint32_t sc_ = (rank + n - s) % n;
            const uint32_t rc_ = (rank + n - s - 1) % n;
            p.push_back({Step::kSend, succ, chunk_start(cnt, n, sc_),
                         chunk_len(cnt, n, sc_), s});
            p.push_back({Step::kRecvReduce, pred, chunk_start(cnt, n, rc_),
                         chunk_len(cnt, n, rc_), s});
        }
        for (uint32_t s = 0; s + 1 < n; ++s) {
            const uint32_t sc_ = (rank + 1 + n - s) % n;
            const uint32_t rc_ = (rank + n - s) % n;
            p.push_back({Step::kSend, succ, chunk_start(cnt, n, sc_),
                         chunk_len(cnt, n, sc_), 0x4000u + s});
            p.push_back({Step::kRecv, pred, chunk_start(cnt, n, rc_),
                         chunk_len(cnt, n, rc_), 0x4000u + s});
        }
        break;
    }
    case Coll::kReduceScatter: {
        const uint64_t cnt = bytes;
        for (uint32_t s = 0; s + 1 < n; ++s) {
            const uint32_t sc_ = (rank + n - s) % n;
            const uint32_t rc_ = (rank + n - s - 1) % n;
            p.push_back({Step::kSend, succ, chunk_start(cnt, n, sc_),
                         chunk_len(cnt, n, sc_), s});
            p.push_back({Step::kRecvReduce, pred, chunk_start(cnt, n, rc_),
                         chunk_len(cnt, n, rc_), s});
        }
        break;
    }
    case Coll::kAllGather: {
        const uint64_t seg = bytes;
        for (uint32_t s = 0; s + 1 < n; ++s) {
            const uint32_t fwd = (rank + n - s) % n;
            const uint32_t src = (rank + n - s - 1) % n;
            p.push_back({Step::kSend, succ, fwd * seg, seg, s});
            p.push_back({static_cast<uint8_t>(s + 2 < n ? Step::kRecvForward
                                                        : Step::kRecv),
                         pred, src * seg, seg, s});
        }
        break;
    }
    }
    return p;
}

bool conserve(Coll c, Algo a, uint32_t n, uint32_t root, uint64_t bytes,
              std::string *err) {
    auto fail = [&](const std::string &m) {
        if (err) *err = m;
        return false;
    };
    // (src, dst, xfer) -> bytes, matched exactly once each way
    std::map<std::tuple<uint32_t, uint32_t, uint32_t>, uint64_t> sends, recvs;
    uint64_t sent = 0, received = 0;
    for (uint32_t r = 0; r < n; ++r) {
        for (const auto &s : expand(c, a, n, r, root, bytes)) {
            if (s.kind == Step::kSend) {
                auto key = std::make_tuple(r, s.peer, s.xfer);
                if (sends.count(key)) return fail("duplicate send key");
                sends[key] = s.bytes;
                sent += s.bytes;
            } else if (s.kind != Step::kCopy) {
                auto key = std::make_tuple(s.peer, r, s.xfer);
                if (recvs.count(key)) return fail("duplicate recv key");
                recvs[key] = s.bytes;
                received += s.bytes;
            }
        }
    }
    if (sent != received) return fail("sent != received");
    if (sends.size() != recvs.size()) return fail("unpaired transfers");
    for (const auto &[key, b] : sends) {
        auto it = recvs.find(key);
        if (it == recvs.end()) return fail("send without matching recv");
        if (it->second != b) return fail("send/recv byte mismatch");
    }
    return true;
}

// ---- env knobs ----

bool schedule_enabled() {
    const char *e = std::getenv("PCCLT_SCHEDULE");
    return !(e && e[0] == '0');
}

std::optional<Algo> forced_algo() {
    const char *e = std::getenv("PCCLT_SCHEDULE_FORCE");
    if (!e || !e[0]) return std::nullopt;
    return algo_from_name(e);
}

} // namespace pcclt::sched
