// P2P bandwidth probe for topology optimization.
// Reference parity: NetworkBenchmarkRunner (/root/reference/ccoip/src/cpp/
// benchmark_runner.cpp:11-13,95-141) — the prober floods 8 MB random
// buffers over N concurrent connections for a fixed window and reports the
// SUMMED Mbit/s; the server side accepts, counts and discards. Admission is
// per-PROBER: every connection of one probe carries the same random 16-byte
// token, the server grants the floor to one token at a time, and other
// probers are told "busy" so they back off instead of splitting capacity
// and halving each other's estimates. Env knobs: PCCLT_BENCH_SECONDS
// (default 10, like the reference), PCCLT_BENCH_CONNECTIONS (default 4,
// reference: PCCL_NUM_BENCHMARK_CONNECTIONS).
#pragma once

#include <array>
#include <cstdint>

#include "annotations.hpp"
#include "sockets.hpp"

namespace pcclt::bench {

inline constexpr int kMaxProbeConnections = 64;

double probe_seconds();
int probe_connections();

// Run one N-connection flood probe; returns summed Mbit/s across the
// connections, or <0 on failure (-1) / busy rejection (-2).
double run_probe(const net::Addr &target);

// Per-server-endpoint admission state: one prober token holds the floor.
struct ServeState {
    Mutex mu; // lock-rank: 72
    std::array<uint8_t, 16> token PCCLT_GUARDED_BY(mu){};
    int refcount PCCLT_GUARDED_BY(mu) = 0;
};

// Serve one accepted benchmark connection (counts+discards until close).
// Rejects the handshake when a different prober currently holds the floor.
void serve_connection(net::Socket sock, ServeState &state);

} // namespace pcclt::bench
