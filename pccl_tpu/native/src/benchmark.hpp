// P2P bandwidth probe for topology optimization.
// Reference parity: NetworkBenchmarkRunner (/root/reference/ccoip/src/cpp/
// benchmark_runner.cpp) — client floods random buffers for a fixed window
// and reports Mbit/s; server side accepts, counts and discards; busy
// servers reject via the handshake. Duration env: PCCLT_BENCH_SECONDS
// (default 1.0; the reference uses 10 s).
#pragma once

#include <atomic>

#include "sockets.hpp"

namespace pcclt::bench {

double probe_seconds();

// Run one outgoing probe; returns measured Mbit/s or <0 on failure/busy.
double run_probe(const net::Addr &target);

// Serve one accepted benchmark connection (counts+discards until close).
// `busy` limits concurrency: if already at limit, the handshake is rejected.
void serve_connection(net::Socket sock, std::atomic<int> &active, int max_active);

} // namespace pcclt::bench
