#include "guarded_alloc.hpp"

#include <atomic>
#include <cstdint>
#include <cstring>
#include <new>
#include <sys/mman.h>
#include <unistd.h>

namespace pcclt::galloc {

namespace {

std::atomic<size_t> g_live{0};

size_t page_size() {
    static const size_t ps = static_cast<size_t>(sysconf(_SC_PAGESIZE));
    return ps;
}

struct Header {
    void *map_base;
    size_t map_len;
    uint64_t magic;
};
constexpr uint64_t kMagic = 0x6741726445644121ull;

} // namespace

void *guarded_malloc(size_t n) {
    const size_t ps = page_size();
    // layout: [Header ... backptr][user bytes, end flush][PROT_NONE guard]
    const size_t need = sizeof(Header) + sizeof(void *) + ((n + 15) & ~size_t{15});
    const size_t data_pages = (need + ps - 1) / ps;
    const size_t map_len = (data_pages + 1) * ps;
    void *base = mmap(nullptr, map_len, PROT_READ | PROT_WRITE,
                      MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (base == MAP_FAILED) return nullptr;
    uint8_t *guard = static_cast<uint8_t *>(base) + data_pages * ps;
    if (mprotect(guard, ps, PROT_NONE) != 0) {
        munmap(base, map_len);
        return nullptr;
    }
    // user buffer flush against the guard page (16-aligned)
    uint8_t *user = guard - ((n + 15) & ~size_t{15});
    auto *h = reinterpret_cast<Header *>(base);
    h->map_base = base;
    h->map_len = map_len;
    h->magic = kMagic;
    // back-pointer to the header directly below the user buffer: O(1) free
    // with no page scanning (a scan could fault on neighboring mappings)
    memcpy(user - sizeof(void *), &h, sizeof(void *));
    g_live.fetch_add(1);
    return user;
}

void guarded_free(void *p) {
    if (!p) return;
    Header *h;
    memcpy(&h, static_cast<uint8_t *>(p) - sizeof(void *), sizeof(void *));
    if (!h || h->magic != kMagic || h->map_base != h) {
        // not ours / corrupted back-pointer — crash loudly, don't leak silently
        __builtin_trap();
    }
    size_t len = h->map_len;
    void *base = h->map_base;
    g_live.fetch_sub(1);
    munmap(base, len);
}

size_t live_count() { return g_live.load(); }

} // namespace pcclt::galloc

#ifdef PCCLT_GUARDED_ALLOC_HOOK
void *operator new(size_t n) {
    void *p = pcclt::galloc::guarded_malloc(n);
    if (!p) throw std::bad_alloc();
    return p;
}
void operator delete(void *p) noexcept { pcclt::galloc::guarded_free(p); }
void operator delete(void *p, size_t) noexcept { pcclt::galloc::guarded_free(p); }
#endif
