#include "journal.hpp"

#include <unistd.h>

#include <algorithm>
#include <cstdlib>
#include <cstring>
#include <iterator>

#include "log.hpp"
#include "wire.hpp"

namespace pcclt::journal {

namespace {
constexpr char kMagic[] = "PCCLJ1\n";
constexpr size_t kMagicLen = sizeof(kMagic) - 1;
constexpr uint32_t kMaxRecord = 16u << 20; // sanity guard on corrupt lengths
} // namespace

Journal::~Journal() {
    MutexLock lk(mu_);
    if (f_) fclose(f_);
    f_ = nullptr;
}

bool Journal::open(const std::string &path) {
    MutexLock lk(mu_);
    if (f_) return false; // already open
    path_ = path;
    fsync_ = [] {
        const char *e = std::getenv("PCCLT_JOURNAL_FSYNC");
        return e && e[0] == '1';
    }();
    replay(path); // missing/empty file is a fresh journal, not an error
    epoch_ = restored_.epoch + 1;
    if (!write_snapshot()) {
        PLOG(kError) << "journal: cannot write " << path;
        return false;
    }
    PLOG(kInfo) << "journal " << path << " open: epoch " << epoch_ << ", "
                << restored_.clients.size() << " clients, "
                << restored_.groups.size() << " groups restored";
    return true;
}

bool Journal::replay(const std::string &path) {
    FILE *f = fopen(path.c_str(), "rb");
    if (!f) return false;
    char magic[8] = {0};
    if (fread(magic, 1, kMagicLen, f) != kMagicLen ||
        memcmp(magic, kMagic, kMagicLen) != 0) {
        fclose(f);
        PLOG(kWarn) << "journal: bad magic in " << path << "; starting fresh";
        return false;
    }
    std::vector<uint8_t> buf;
    while (true) {
        uint8_t hdr[5];
        if (fread(hdr, 1, 5, f) != 5) break; // torn tail / EOF: stop replay
        uint32_t len;
        memcpy(&len, hdr, 4);
        len = wire::from_be(len);
        uint8_t type = hdr[4];
        if (len > kMaxRecord) break;
        buf.resize(len);
        if (len && fread(buf.data(), 1, len, f) != len) break; // torn record
        try {
            wire::Reader r(buf);
            switch (type) {
            case kEpoch:
                restored_.epoch = r.u64();
                break;
            case kClient: {
                ClientRec c;
                c.uuid = proto::get_uuid(r);
                c.peer_group = r.u32();
                c.ip = r.str();
                c.p2p_port = r.u16();
                c.ss_port = r.u16();
                c.bench_port = r.u16();
                c.accepted = r.u8() != 0;
                restored_.clients[c.uuid] = std::move(c);
                break;
            }
            case kClientRemove:
                restored_.clients.erase(proto::get_uuid(r));
                break;
            case kGroup: {
                uint32_t g = r.u32();
                auto &gr = restored_.groups[g];
                gr.last_revision = r.u64();
                gr.revision_initialized = r.u8() != 0;
                break;
            }
            case kRing: {
                uint32_t g = r.u32();
                uint32_t n = r.u32();
                auto &gr = restored_.groups[g];
                gr.ring.clear();
                for (uint32_t i = 0; i < n; ++i)
                    gr.ring.push_back(proto::get_uuid(r));
                break;
            }
            case kTopoRev:
                restored_.topology_revision = r.u64();
                break;
            case kSeqBound:
                restored_.next_seq = std::max(restored_.next_seq, r.u64());
                break;
            case kBandwidth: {
                BandwidthRec b;
                b.from = proto::get_uuid(r);
                b.to = proto::get_uuid(r);
                b.mbps = r.f64();
                restored_.bandwidth.push_back(b);
                break;
            }
            case kOpDone: {
                OpDoneRec rec;
                rec.group = r.u32();
                rec.tag = r.u64();
                rec.seq = r.u64();
                rec.any_aborted = r.u8() != 0;
                rec.world = r.u32();
                uint32_t n = r.u32();
                for (uint32_t i = 0; i < n; ++i)
                    rec.members.insert(proto::get_uuid(r));
                restored_.op_done[{rec.group, rec.tag}] = std::move(rec);
                break;
            }
            case kSchedule: {
                uint32_t g = r.u32();
                restored_.groups[g].schedule = r.bytes();
                break;
            }
            case kOpDoneConsumed: {
                uint32_t g = r.u32();
                uint64_t tag = r.u64();
                Uuid u = proto::get_uuid(r);
                auto it = restored_.op_done.find({g, tag});
                if (it != restored_.op_done.end()) {
                    it->second.members.erase(u);
                    if (it->second.members.empty())
                        restored_.op_done.erase(it);
                }
                break;
            }
            default:
                break; // unknown record: skip (forward compatibility)
            }
            restored_.any = true;
        } catch (...) {
            break; // short payload: torn record, stop replay
        }
    }
    fclose(f);
    // drop bandwidth rows whose peers are gone (forget() deltas are not
    // journaled; pruning at replay keeps the matrix consistent)
    std::vector<BandwidthRec> kept;
    for (auto &b : restored_.bandwidth)
        if (restored_.clients.count(b.from) && restored_.clients.count(b.to))
            kept.push_back(b);
    restored_.bandwidth = std::move(kept);
    // prune op-done replay entries owed to departed clients: only a
    // journaled (rehydratable) session can ever resume and retry the op
    for (auto it = restored_.op_done.begin(); it != restored_.op_done.end();) {
        auto &members = it->second.members;
        for (auto mit = members.begin(); mit != members.end();)
            mit = restored_.clients.count(*mit) ? std::next(mit)
                                                : members.erase(mit);
        it = members.empty() ? restored_.op_done.erase(it) : std::next(it);
    }
    // Bound what carries across epochs: each control connection delivers
    // Dones IN ORDER, so a member can only be owed a SUFFIX of its Done
    // stream — records older than the most recent kOpDoneKeep completions
    // per group were delivered long ago and are history, not liabilities.
    // Without this cap, a long-lived journal would accrete one record per
    // distinct tag ever completed.
    constexpr size_t kOpDoneKeep = 64;
    std::map<uint32_t, std::vector<uint64_t>> seqs_by_group;
    for (auto &[key, rec] : restored_.op_done)
        seqs_by_group[key.first].push_back(rec.seq);
    std::map<uint32_t, uint64_t> min_keep;
    for (auto &[g, seqs] : seqs_by_group) {
        if (seqs.size() <= kOpDoneKeep) continue;
        std::sort(seqs.begin(), seqs.end());
        min_keep[g] = seqs[seqs.size() - kOpDoneKeep];
    }
    if (!min_keep.empty())
        for (auto it = restored_.op_done.begin();
             it != restored_.op_done.end();) {
            auto mk = min_keep.find(it->first.first);
            it = (mk != min_keep.end() && it->second.seq < mk->second)
                     ? restored_.op_done.erase(it)
                     : std::next(it);
        }
    return true;
}

bool Journal::write_snapshot() {
    // compact to a temp file then rename over: a crash mid-snapshot leaves
    // the previous journal intact
    std::string tmp = path_ + ".tmp";
    FILE *f = fopen(tmp.c_str(), "wb");
    if (!f) return false;
    if (fwrite(kMagic, 1, kMagicLen, f) != kMagicLen) {
        fclose(f);
        return false;
    }
    auto put = [&](uint8_t type, const std::vector<uint8_t> &payload) {
        uint32_t len = wire::to_be(static_cast<uint32_t>(payload.size()));
        fwrite(&len, 4, 1, f);
        fwrite(&type, 1, 1, f);
        if (!payload.empty()) fwrite(payload.data(), 1, payload.size(), f);
    };
    {
        wire::Writer w;
        w.u64(epoch_);
        put(kEpoch, w.take());
    }
    {
        wire::Writer w;
        w.u64(restored_.topology_revision);
        put(kTopoRev, w.take());
    }
    {
        wire::Writer w;
        w.u64(restored_.next_seq);
        put(kSeqBound, w.take());
    }
    for (auto &[_, c] : restored_.clients) {
        wire::Writer w;
        proto::put_uuid(w, c.uuid);
        w.u32(c.peer_group);
        w.str(c.ip);
        w.u16(c.p2p_port);
        w.u16(c.ss_port);
        w.u16(c.bench_port);
        w.u8(c.accepted ? 1 : 0);
        put(kClient, w.take());
    }
    for (auto &[g, gr] : restored_.groups) {
        {
            wire::Writer w;
            w.u32(g);
            w.u64(gr.last_revision);
            w.u8(gr.revision_initialized ? 1 : 0);
            put(kGroup, w.take());
        }
        {
            wire::Writer w;
            w.u32(g);
            w.u32(static_cast<uint32_t>(gr.ring.size()));
            for (const auto &u : gr.ring) proto::put_uuid(w, u);
            put(kRing, w.take());
        }
        if (!gr.schedule.empty()) {
            wire::Writer w;
            w.u32(g);
            w.bytes(gr.schedule);
            put(kSchedule, w.take());
        }
    }
    for (auto &b : restored_.bandwidth) {
        wire::Writer w;
        proto::put_uuid(w, b.from);
        proto::put_uuid(w, b.to);
        w.f64(b.mbps);
        put(kBandwidth, w.take());
    }
    for (auto &[_, rec] : restored_.op_done) {
        wire::Writer w;
        w.u32(rec.group);
        w.u64(rec.tag);
        w.u64(rec.seq);
        w.u8(rec.any_aborted ? 1 : 0);
        w.u32(rec.world);
        w.u32(static_cast<uint32_t>(rec.members.size()));
        for (const auto &u : rec.members) proto::put_uuid(w, u);
        put(kOpDone, w.take());
    }
    if (fflush(f) != 0 || fdatasync(fileno(f)) != 0) {
        fclose(f);
        return false;
    }
    fclose(f);
    if (rename(tmp.c_str(), path_.c_str()) != 0) return false;
    f_ = fopen(path_.c_str(), "ab");
    return f_ != nullptr;
}

void Journal::append(uint8_t type, const std::vector<uint8_t> &payload) {
    MutexLock lk(mu_);
    if (!f_) return;
    uint32_t len = wire::to_be(static_cast<uint32_t>(payload.size()));
    fwrite(&len, 4, 1, f_);
    fwrite(&type, 1, 1, f_);
    if (!payload.empty()) fwrite(payload.data(), 1, payload.size(), f_);
    fflush(f_); // kernel-buffered: survives SIGKILL of this process
    if (fsync_) fdatasync(fileno(f_));
}

void Journal::record_client(const ClientRec &c) {
    wire::Writer w;
    proto::put_uuid(w, c.uuid);
    w.u32(c.peer_group);
    w.str(c.ip);
    w.u16(c.p2p_port);
    w.u16(c.ss_port);
    w.u16(c.bench_port);
    w.u8(c.accepted ? 1 : 0);
    append(kClient, w.take());
}

void Journal::record_client_remove(const Uuid &u) {
    wire::Writer w;
    proto::put_uuid(w, u);
    append(kClientRemove, w.take());
}

void Journal::record_group(uint32_t group, uint64_t last_revision, bool initialized) {
    wire::Writer w;
    w.u32(group);
    w.u64(last_revision);
    w.u8(initialized ? 1 : 0);
    append(kGroup, w.take());
}

void Journal::record_ring(uint32_t group, const std::vector<Uuid> &ring) {
    wire::Writer w;
    w.u32(group);
    w.u32(static_cast<uint32_t>(ring.size()));
    for (const auto &u : ring) proto::put_uuid(w, u);
    append(kRing, w.take());
}

void Journal::record_schedule(uint32_t group,
                              const std::vector<uint8_t> &table) {
    wire::Writer w;
    w.u32(group);
    w.bytes(table);
    append(kSchedule, w.take());
}

void Journal::record_topology_revision(uint64_t rev) {
    wire::Writer w;
    w.u64(rev);
    append(kTopoRev, w.take());
}

void Journal::record_seq_bound(uint64_t bound) {
    wire::Writer w;
    w.u64(bound);
    append(kSeqBound, w.take());
}

void Journal::record_bandwidth(const Uuid &from, const Uuid &to, double mbps) {
    wire::Writer w;
    proto::put_uuid(w, from);
    proto::put_uuid(w, to);
    w.f64(mbps);
    append(kBandwidth, w.take());
}

void Journal::record_op_done(const OpDoneRec &rec) {
    wire::Writer w;
    w.u32(rec.group);
    w.u64(rec.tag);
    w.u64(rec.seq);
    w.u8(rec.any_aborted ? 1 : 0);
    w.u32(rec.world);
    w.u32(static_cast<uint32_t>(rec.members.size()));
    for (const auto &u : rec.members) proto::put_uuid(w, u);
    append(kOpDone, w.take());
}

void Journal::record_op_done_consumed(uint32_t group, uint64_t tag,
                                      const Uuid &u) {
    wire::Writer w;
    w.u32(group);
    w.u64(tag);
    proto::put_uuid(w, u);
    append(kOpDoneConsumed, w.take());
}

} // namespace pcclt::journal
