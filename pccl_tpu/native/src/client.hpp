// Client: membership, p2p mesh, collectives, shared-state sync.
//
// Reference parity: CCoIPClientHandler + CCoIPClientState
// (/root/reference/ccoip/src/cpp/ccoip_client_handler.cpp, _state.cpp).
// Same four sockets: master control connection (matched receive), p2p listen
// + per-peer multiplex pools, shared-state distribution server, benchmark
// server. Collective workers poll master abort packets by tag, so concurrent
// reduce threads never steal the main thread's packets (the reference's
// QueuedSocket discipline, ccoip_client_handler.cpp:1235-1241).
#pragma once

#include <atomic>
#include <deque>
#include <future>
#include <map>
#include <memory>
#include <mutex>  // std::once_flag (SharedStateEntry::mat_once)
#include <optional>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "annotations.hpp"
#include "hash.hpp"
#include "pool.hpp"
#include "protocol.hpp"
#include "schedule.hpp"
#include "sockets.hpp"
#include "ss_chunk.hpp"
#include "telemetry.hpp"

namespace pcclt::client {

enum class Status : int {
    kOk = 0,
    kInvalid = 1,
    kNotConnected = 2,
    kConnectionLost = 3,
    kAborted = 4,
    kTooFewPeers = 5,
    kDuplicateTag = 6,
    kKicked = 7,
    kMasterUnreachable = 8,
    kInternal = 9,
    kContentMismatch = 10,
    kPendingAsyncOps = 11, // at the concurrent-op cap; await one first
};

struct ClientConfig {
    net::Addr master;
    uint32_t peer_group = 0;
    std::string adv_ip;            // explicit advertised address (NAT)
    uint16_t p2p_port = 48502;     // bump-allocated upward if taken
    uint16_t ss_port = 48532;
    uint16_t bench_port = 48562;
    size_t pool_size = 1;          // p2p connection pool per peer
    // Master HA reconnect (session resume after a master restart).
    // -1/0 = resolve from env at connect: PCCLT_RECONNECT_ATTEMPTS
    // (default 8; 0 disables), PCCLT_RECONNECT_BACKOFF_MS (default 100),
    // PCCLT_RECONNECT_MAX_BACKOFF_MS (default 2000). The retry loop is
    // bounded exponential backoff with jitter; p2p connections stay alive
    // throughout, so a resumed session needs no mesh rebuild.
    int reconnect_attempts = -1;
    int reconnect_backoff_ms = 0;
    int reconnect_backoff_cap_ms = 0;
};

struct ReduceDesc {
    uint64_t tag = 0;
    proto::RedOp op = proto::RedOp::kSum;
    proto::QuantAlgo quant = proto::QuantAlgo::kNone;
    proto::DType quant_dtype = proto::DType::kU8;
    // gather/reduce-scatter/all-to-all (client-side, not on the wire):
    // recv capacity in ELEMENTS. The commence-time world can exceed the
    // world the caller sized recv for (a pending joiner admitted in
    // between); the worker fails the op through the normal abort protocol
    // instead of writing world*count elements past the buffer.
    uint64_t recv_capacity = ~0ull;
    // collective-specific argument forwarded as CollectiveInit::aux:
    // broadcast root SLOT (sorted-uuid order). Matched-parameters
    // contract — members disagreeing on aux are kicked (docs/12).
    uint64_t aux = 0;
};

struct ReduceInfo {
    uint64_t tx_bytes = 0, rx_bytes = 0;
    uint32_t world = 0;
    // reduce-scatter only: which chunk of the global vector landed in recv
    // (elements). Chunk ownership follows ring position, which the
    // topology optimizer reshuffles — outputs, not inputs (docs/12).
    uint64_t rs_offset = 0, rs_count = 0;
};

struct SharedStateEntry {
    std::string name;
    proto::DType dtype = proto::DType::kF32;
    uint64_t count = 0;
    void *data = nullptr;
    bool allow_content_inequality = false;
    // Accelerator-resident state (the reference's on-GPU hashing,
    // simplehash_cuda.cu, re-designed for the host/device split here):
    // when has_precomputed_hash is set, the request-time content hash is
    // taken from precomputed_hash (computed on-device by the caller; its
    // type must match PCCLT_SS_HASH) and `data` may be UNMATERIALIZED —
    // `materialize` is then invoked (once per sync window, any serving
    // thread, before the first byte of this entry is served) to fill
    // `data` from the device. Receives always land in `data`; *updated is
    // set when they do, so the caller knows to push the bytes back.
    uint64_t precomputed_hash = 0;
    bool has_precomputed_hash = false;
    void (*materialize)(void *ctx) = nullptr;
    void *materialize_ctx = nullptr;
    int *updated = nullptr;
    // per-sync-window once flag for materialize (shared by every snapshot
    // of this entry; created when the distribution window opens)
    std::shared_ptr<std::once_flag> mat_once;
};

struct SyncInfo {
    uint64_t tx_bytes = 0, rx_bytes = 0;
    uint64_t revision = 0;
};

class Client {
public:
    explicit Client(ClientConfig cfg) : cfg_(cfg) {}
    ~Client();

    Status connect();
    void disconnect();

    Status update_topology();
    Status are_peers_pending(bool &pending);
    // own segment index in all-gather output: position among the current
    // ring's sorted peer uuids (re-query after churn)
    Status gather_slot(uint64_t *slot);
    Status optimize_topology();

    Status all_reduce_async(const void *send, void *recv, uint64_t count,
                            proto::DType dtype, const ReduceDesc &desc);
    Status await_reduce(uint64_t tag, ReduceInfo *info);
    Status all_reduce(const void *send, void *recv, uint64_t count, proto::DType dtype,
                      const ReduceDesc &desc, ReduceInfo *info);

    Status sync_shared_state(uint64_t revision, proto::SyncStrategy strategy,
                             const std::vector<SharedStateEntry> &entries,
                             SyncInfo *info);
private:
    Status sync_shared_state_impl(uint64_t revision, proto::SyncStrategy strategy,
                                  const std::vector<SharedStateEntry> &entries,
                                  SyncInfo *info);
public:

    uint32_t global_world() const;
    uint32_t group_world() const;
    uint32_t num_groups() const;
    uint32_t largest_group() const;
    // master HA: last welcome/resume-ack epoch, and sessions resumed
    uint64_t master_epoch() const { return master_epoch_.load(); }
    uint64_t reconnect_count() const { return reconnects_.load(); }
    // last shared-state revision known complete (from a sync Done or the
    // resume ack) — apps use it to skip re-syncing a revision that
    // completed group-wide just before a master crash
    uint64_t shared_state_revision() const { return last_sync_revision_.load(); }
    const proto::Uuid &uuid() const { return uuid_; }
    bool connected() const { return connected_.load(); }

    // Flight-recorder counter domain for this communicator: comm-level
    // outcome counters + per-edge byte/stall counters (telemetry.hpp).
    // Shared with every MultiplexConn this client creates.
    telemetry::Domain &tele() { return *tele_; }

private:
    struct PeerConns {
        proto::PeerEndpoint ep;
        std::vector<std::shared_ptr<net::MultiplexConn>> tx;
        std::vector<std::shared_ptr<net::MultiplexConn>> rx;
        // pool-wide RX state: large transfers stripe across the pool into
        // one shared sink table per direction
        std::shared_ptr<net::SinkTable> tx_table, rx_table;
    };
    struct AsyncOp {
        std::future<Status> result;
        ReduceInfo info;
        std::atomic<bool> abort{false};
    };
    struct DistEntry {
        const SharedStateEntry *e;
    };

    // wait conn-info, connect mesh, confirm; until ok. vote_deferrable: the
    // first wait may be answered with kM2CTopologyDeferred (vote declined
    // mid-round, returns kOk no-op) — only used by update_topology.
    Status establish_loop(bool vote_deferrable = false);
    Status establish_from_info(const proto::P2PConnInfo &info,
                               std::vector<proto::Uuid> &failed);
    void adopt(const proto::P2PConnInfo &info, const std::vector<proto::Uuid> &ring);
    Status check_kicked(); // poll for a queued kick packet
    // Master HA: bounded exponential-backoff-with-jitter reconnect +
    // kC2MSessionResume under the existing UUID. Returns kOk when the
    // session is re-bound (epoch adopted, p2p mesh untouched),
    // kMasterUnreachable when the budget is exhausted or the master
    // rejected the resume (caller must re-register from scratch).
    Status resume_master_session();
    // Classify a failed master exchange: queued kick -> kKicked; master
    // link down -> try resume (kOk resume -> kConnectionLost so the caller
    // retries the op on the live session); resume failed ->
    // kMasterUnreachable with connected_ cleared.
    Status classify_master_loss();
    Status run_reduce_worker(const void *send, void *recv, uint64_t count,
                             proto::DType dtype, ReduceDesc desc, AsyncOp *op);
    Status run_reduce_worker_impl(const void *send, void *recv, uint64_t count,
                                  proto::DType dtype, const ReduceDesc &desc,
                                  AsyncOp *op, bool is_retry,
                                  uint64_t retry_seq, uint64_t *observed_seq);
    void on_p2p_accept(net::Socket sock);
    void on_ss_accept(net::Socket sock);
    void on_bench_accept(net::Socket sock);

    // ---- shared-state chunk plane (docs/04) ----
    // Serving guard: every slice a serve thread spends reading an
    // entry's app-owned bytes sits between enter (window still open at
    // `revision`, `key` still servable, count bumped) and exit;
    // ss_close_window flips the window shut and WAITS the count out, so
    // the sync call can only return — and the app only free its buffers
    // — once no serve thread is mid-read.
    bool ss_serve_enter(uint64_t revision, const std::string &key);
    void ss_serve_exit();
    void ss_close_window();
    // Serve one legacy whole-entry request (kC2SStateRequest) on a
    // service thread; netem-paced + sync-byte metered.
    void ss_serve_legacy(net::Socket &sock, const net::Frame &req);
    // Serve one chunk-range request (kC2SChunkRequest). Returns true to
    // keep the persistent serve connection alive.
    bool ss_serve_chunk(net::Socket &sock, const net::Frame &req);
    // Multi-source fetch of the chunk-mapped outdated keys: a FetchPlan
    // dispatched across one worker per seeder (unified transport: each
    // worker rides the pooled MultiplexConns, no bespoke socket),
    // per-chunk verify/deadline/re-source, mid-round seeder promotion.
    // `req` is the request we sent the master — its per-entry chunk
    // leaves are the request-time hashes of our own buffers, the source
    // of the sparse-delta skip (chunks whose local leaf already matches
    // the brokered leaf are born done and never travel). gen0 is the
    // session generation the sync started under.
    Status ss_fetch_chunked(const proto::SharedStateSyncResp &resp,
                            const proto::SharedStateSyncC2M &req,
                            const std::vector<SharedStateEntry> &entries,
                            hash::Type ht, uint64_t gen0, uint64_t *rx_bytes);
    // One fetch worker per seeder, on the POOL (docs/04 unified
    // transport): register a sink for the range's response tag in the
    // seeder's inbound table, send kChunkReq over our tx pool, read the
    // kChunkHdr status off the queued-frame path, then wait the payload
    // into the sink — kData frames at range-relative offsets, arriving
    // striped across the seeder's pool conns or detoured through a relay
    // peer, dedupe through the one SinkTable. All waits are bounded
    // slices re-checking plan->finished(), so the dispatcher never needs
    // to shut a socket down to reclaim a straggler.
    void ss_fetch_worker(const std::shared_ptr<ssc::FetchPlan> &plan,
                         uint32_t sidx, proto::SeederRec rec,
                         uint64_t revision, hash::Type ht);
    // Legacy single-distributor fetch of `keys` (the pre-chunk-plane
    // transport, kept for tiny states / world=2 / leafless device
    // entries), now with a 30 s-class no-progress deadline and netem
    // routing on the distributor edge.
    Status ss_fetch_legacy(const proto::SharedStateSyncResp &resp,
                           const std::vector<std::string> &keys,
                           const std::vector<SharedStateEntry> &entries,
                           hash::Type ht, uint64_t *rx_bytes);

    // ---- pooled chunk serve plane (docs/04 unified transport) ----
    // RX-thread hook target for kChunkReq frames: enqueue for the serve
    // pool (never blocks; lazily spawns PCCLT_SS_SERVE_THREADS workers).
    void chunk_req_enqueue(const uint8_t *requester_uuid, uint64_t tag,
                           std::vector<uint8_t> spec);
    void chunk_serve_loop();  // serve-pool worker: drain queued requests
    // Serve ONE pooled chunk-range request: kChunkHdr status on the
    // requester's reverse link, then the payload as striped kData windows
    // (per-lane netem pacing, zerocopy — the collective TX path) with the
    // full watchdog ladder: a stalled window goes SUSPECT and re-issues
    // on a fresh pool conn, a second stall CONFIRMS the edge and detours
    // the bytes through a third peer via the acked relay plane.
    void chunk_serve_pooled(const proto::Uuid &requester, uint64_t tag,
                            const std::vector<uint8_t> &spec);
    void chunk_serve_stop_join();  // disconnect: stop + join + reap

    // p2p pool width per peer: cfg_.pool_size grown to PCCLT_STRIPE_CONNS
    // (docs/08 multipath striping), capped at 8
    size_t pool_width() const;

    net::Link tx_link(const proto::Uuid &peer);
    // waits until at least one inbound conn from `peer` is up
    net::Link rx_link(const proto::Uuid &peer, int timeout_ms);

    // ---- straggler-immune data plane (docs/05) ----
    // Install kRelayFwd/kRelayDeliver routing on a conn (must run before
    // conn->run(): the RX thread reads the handlers lock-free).
    void install_relay_handlers(const std::shared_ptr<net::MultiplexConn> &conn);
    // Dial + hello-handshake ONE p2p conn to `ep`. Transient connect/
    // handshake failures (ECONNRESET/ETIMEDOUT while a peer restarts its
    // listener) get bounded exponential backoff + jitter via the PR-3
    // reconnect_* knob family; attempts_override > 0 caps the budget
    // (the mid-op fresh-conn rung dials exactly once).
    std::shared_ptr<net::MultiplexConn> dial_p2p(
        const proto::PeerEndpoint &ep, uint32_t idx,
        const std::shared_ptr<net::SinkTable> &table,
        int attempts_override = 0);
    // failover rung 1: one extra pool conn to `peer`, appended to its pool
    // (heals the pool for later ops); Link holds ONLY the new conn
    net::Link fresh_pool_conn(const proto::Uuid &peer);
    // failover rung 2: detour a window toward `dst` through a healthy
    // third ring peer — successive windows ROTATE across all healthy
    // candidates (PCCLT_RELAY_FANOUT caps the set; 1 = the PR-10
    // single-neighbor funnel), the same round-robin the striped window
    // scheduler uses. Waits out the first (local) hop so a false return
    // lets the caller fall back to the direct path.
    bool relay_window_via(const proto::Uuid &dst, uint64_t tag, uint64_t off,
                          std::span<const uint8_t> payload);
    // end-to-end relay delivery acks (docs/05): the deliver handler sends
    // kRelayAck back to the ORIGIN over this peer's own reverse link; the
    // origin merges covered byte ranges here so drain_zombies can retire
    // CONFIRMED-stalled direct copies early instead of parking them to op
    // end. Tag-keyed merged intervals, purged per op.
    void note_relay_ack(uint64_t tag, uint64_t off, uint64_t len);
    bool relay_ack_covered(uint64_t tag, uint64_t off, size_t len);
    void purge_relay_acks(uint64_t lo, uint64_t hi);

    // Telemetry push loop (fleet observability plane, docs/09): every
    // `push_ms` fold the Domain counters into a DigestSnapshotter digest
    // and fire-and-forget it to the master over the control connection.
    // Runs on its own thread while connected; PCCLT_TELEMETRY_PUSH_MS=0 /
    // unset disables (connect never spawns the thread).
    void telemetry_push_loop(int push_ms);

    // Incident black box (docs/09): kM2CIncidentDump arrives on the
    // control reader via ControlClient::set_notify — dedupe by id and hand
    // the write to a dedicated thread (a trace dump is tens of ms; the
    // reader must keep consuming abort/commence packets meanwhile).
    void on_incident_dump(net::Frame &&f);
    // writes <PCCLT_INCIDENT_DIR>/<id>/peer-<uuid8>.trace.json (the
    // flight-recorder ring) + peer-<uuid8>.stats.json (counters + edges)
    void write_incident_bundle(const proto::IncidentDumpM2C &d);

    ClientConfig cfg_;
    proto::Uuid uuid_{};
    std::atomic<bool> connected_{false};
    // master HA state: serialized resume loop (resume_mu_ guards no data —
    // it serializes reconnect() of master_ against concurrent resumers and
    // disconnect()), observed epoch, resume count, last shared-state
    // revision seen complete (re-presented on resume). blocking-ok: the
    // whole point of this lock is holding rivals out for the duration of
    // the dial/backoff/handshake loop; waiters are resumers/disconnect
    // only, never the data plane.
    Mutex resume_mu_; // lock-rank: 10 blocking-ok
    std::atomic<uint64_t> master_epoch_{0};
    std::atomic<uint64_t> reconnects_{0};
    std::atomic<uint64_t> last_sync_revision_{0};
    // bumped on every successful resume: an exchange that started against
    // the OLD master session must not wait out its full timeout on replies
    // the new session will never produce (concurrent ops + resume race)
    std::atomic<uint64_t> session_gen_{0};
    std::shared_ptr<telemetry::Domain> tele_ =
        std::make_shared<telemetry::Domain>();
    // telemetry push thread (spawned by connect when PCCLT_TELEMETRY_PUSH_MS
    // > 0; stopped+joined by disconnect before the control conn closes)
    std::thread tele_thread_;
    std::atomic<bool> tele_stop_{false};
    // incident black box: one writer slot + the last id seen for dedupe.
    // incident_busy_ lets the control reader SKIP a new incident while the
    // previous bundle is still being written instead of blocking on a
    // join (the reader must keep consuming abort/commence packets); a
    // finished writer's join is instant.
    Mutex incident_mu_; // lock-rank: 27
    std::thread incident_thread_ PCCLT_GUARDED_BY(incident_mu_);
    std::string last_incident_id_ PCCLT_GUARDED_BY(incident_mu_);
    std::shared_ptr<std::atomic<bool>> incident_busy_
        PCCLT_GUARDED_BY(incident_mu_);

    net::ControlClient master_;
    net::Listener p2p_listener_, ss_listener_, bench_listener_;

    mutable Mutex state_mu_; // lock-rank: 20
    CondVar state_cv_; // signalled when inbound p2p conns land
    std::map<proto::Uuid, PeerConns> peers_ PCCLT_GUARDED_BY(state_mu_);
    std::vector<proto::Uuid> ring_ PCCLT_GUARDED_BY(state_mu_);
    uint64_t topo_revision_ PCCLT_GUARDED_BY(state_mu_) = 0;
    // synthesized schedule table (docs/12): adopted from P2PConnInfo's
    // trailing field and kM2CScheduleUpdate broadcasts. Introspection /
    // telemetry only — the per-op algorithm binding is the commence stamp.
    sched::Table sched_table_ PCCLT_GUARDED_BY(state_mu_);

    // relay ack ranges (leaf: RX threads write, op threads read) + the
    // fanout rotation counter for striped detours
    Mutex relay_mu_; // lock-rank: 23
    // tag -> {off -> end}, overlapping acks merged
    std::map<uint64_t, std::map<uint64_t, uint64_t>> relay_acks_
        PCCLT_GUARDED_BY(relay_mu_);
    std::atomic<uint64_t> relay_rr_{0};

    Mutex ops_mu_; // lock-rank: 22
    std::map<uint64_t, std::unique_ptr<AsyncOp>> ops_ PCCLT_GUARDED_BY(ops_mu_);
    // lazily sized to the op cap
    std::unique_ptr<util::WorkerPool> op_pool_ PCCLT_GUARDED_BY(ops_mu_);

    // Tags whose last attempt died with the master session (worker saw
    // ConnectionLost): the NEXT init of such a tag is a RETRY and is
    // flagged on the wire (CollectiveInit::retry) so a restarted master
    // may replay the journaled verdict — and ONLY then: tags are
    // app-reused across steps, so an unflagged same-tag init must form a
    // fresh op. Own leaf mutex: workers record outcomes here while
    // disconnect() holds ops_mu_ awaiting those same workers.
    Mutex retry_mu_; // lock-rank: 29
    // tag -> commence seq the dead attempt observed (0 = died pre-commence)
    std::map<uint64_t, uint64_t> retry_tags_ PCCLT_GUARDED_BY(retry_mu_);

    // reuse pool for ring receive scratch: per-op vectors would be
    // page-zeroed by the kernel on every reduce (milliseconds at 10s of MiB)
    Mutex scratch_mu_; // lock-rank: 28
    std::vector<std::vector<uint8_t>> scratch_pool_ PCCLT_GUARDED_BY(scratch_mu_);
    std::vector<uint8_t> take_scratch();
    void give_scratch(std::vector<uint8_t> v);

    // shared-state distribution window (serve only while a sync is active).
    // Chunk plane: dist_servable_ names the keys whose bytes are currently
    // canonical — clean keys from the response on, dirty keys once their
    // last chunk verified (mid-round seeder promotion). The window stays
    // OPEN on an outdated peer in chunk mode; the legacy path still
    // closes it wholesale.
    Mutex dist_mu_; // lock-rank: 24
    bool dist_open_ PCCLT_GUARDED_BY(dist_mu_) = false;
    uint64_t dist_revision_ PCCLT_GUARDED_BY(dist_mu_) = 0;
    std::map<std::string, SharedStateEntry> dist_entries_
        PCCLT_GUARDED_BY(dist_mu_);
    std::set<std::string> dist_servable_ PCCLT_GUARDED_BY(dist_mu_);
    // serve threads read entry bytes the APP owns only inside a
    // serving-guard slice (dist_serving_ held > 0); closing the window
    // waits the count out, so sync_shared_state never returns — and the
    // caller never frees its buffers — while a paced serve is mid-read.
    // Serves re-check the window between slices, bounding the wait to
    // one paced slice.
    int dist_serving_ PCCLT_GUARDED_BY(dist_mu_) = 0;
    CondVar dist_cv_;
    std::atomic<uint64_t> dist_tx_bytes_{0};

    // pooled chunk serve plane (docs/04 unified transport): kChunkReq
    // frames land on RX threads, which enqueue here; a lazily-spawned
    // serve pool (PCCLT_SS_SERVE_THREADS) drains the queue. Leaf lock:
    // enqueue/pop only, never held across serve work or another lock.
    struct ChunkServeReq {
        proto::Uuid requester{};
        uint64_t tag = 0;
        std::vector<uint8_t> spec;
    };
    Mutex chunk_mu_; // lock-rank: 21
    CondVar chunk_cv_;
    std::deque<ChunkServeReq> chunk_queue_ PCCLT_GUARDED_BY(chunk_mu_);
    bool chunk_stop_ PCCLT_GUARDED_BY(chunk_mu_) = false;
    std::vector<std::thread> chunk_threads_ PCCLT_GUARDED_BY(chunk_mu_);
    // serve scratch whose striped handles were still in flight when the
    // serve returned (ladder gave up, or a zombied direct copy behind a
    // successful relay detour): the buffer must outlive every handle.
    // Swept lazily by the serve loop; drained at disconnect AFTER the
    // peer conns close (close fails all pending handles).
    struct ChunkTxZombie {
        std::vector<net::SendHandle> hs;
        std::shared_ptr<std::vector<uint8_t>> buf;
    };
    std::vector<ChunkTxZombie> chunk_zombies_ PCCLT_GUARDED_BY(chunk_mu_);
    // fetcher-side response-tag allocator: bit 63 keeps the chunk-plane
    // namespace disjoint from collective tags (op seq << 16)
    std::atomic<uint64_t> chunk_tag_seq_{1};

    // Per-connection service threads (p2p handshakes, shared-state serving,
    // benchmark serving). Tracked so disconnect() can interrupt their sockets
    // and join them — a detached thread capturing `this` could otherwise
    // outlive the Client and touch freed state.
    struct SvcThread {
        std::thread th;
        std::shared_ptr<std::atomic<int>> fd;    // -1 once handed off or closed
        std::shared_ptr<std::atomic<bool>> done;
    };
    void spawn_service(net::Socket sock,
                       std::function<void(net::Socket &,
                                          const std::shared_ptr<std::atomic<int>> &)> body);
    Mutex svc_mu_; // lock-rank: 26
    std::vector<SvcThread> svc_threads_ PCCLT_GUARDED_BY(svc_mu_);
    bool svc_accepting_ PCCLT_GUARDED_BY(svc_mu_) = false;
};

} // namespace pcclt::client
