// In-process self-test: unit checks + multi-peer loopback end-to-end.
// Reference parity: the e2e test style of /root/reference/ccoip/tests/
// end_to_end/test_all_reduce.cpp — real master + N client instances on
// loopback threads, never network mocks.
#include <atomic>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <deque>
#include <thread>
#include <random>
#include <vector>

#include "annotations.hpp"

#include "atsp.hpp"
#include "client.hpp"
#include "netem.hpp"
#include "guarded_alloc.hpp"
#include "journal.hpp"
#include "hash.hpp"
#include "kernels.hpp"
#include "master.hpp"
#include "quantize.hpp"
#include "schedule.hpp"
#include "ss_chunk.hpp"
#include "telemetry.hpp"
#include "wire.hpp"

using namespace pcclt;

static int g_failures = 0;

// PCCLT_SELFTEST_FAST=1: reduced-iteration mode (fewer e2e worlds, smaller
// abort payload) for slow instrumented builds — the CI tsan lane runs the
// selftest this way so the client/master threading gets sanitizer coverage
// without the full-matrix wall-clock.
static bool fast_mode() {
    const char *e = std::getenv("PCCLT_SELFTEST_FAST");
    return e && e[0] == '1';
}
#define CHECK(cond)                                                                     \
    do {                                                                                \
        if (!(cond)) {                                                                  \
            fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__, __LINE__, #cond);  \
            ++g_failures;                                                               \
        }                                                                               \
    } while (0)

// Annotated lock primitives (annotations.hpp): under GCC every macro is a
// no-op and pcclt::Mutex/MutexLock/CondVar must behave exactly like the
// std::mutex protocol they wrap. Exercised here (and thus in the CI
// asan/tsan lanes) with real contention: N writers on a guarded counter, a
// CondVar producer/consumer handoff, MutexLock's drop-and-reacquire window,
// and try_lock exclusion — the race-freedom claim is what TSan verifies.
static void test_lock_annotations() {
    {
        Mutex mu;
        int counter = 0;  // guarded by mu at runtime
        std::vector<std::thread> ts;
        for (int t = 0; t < 8; ++t)
            ts.emplace_back([&] {
                for (int i = 0; i < 10'000; ++i) {
                    MutexLock lk(mu);
                    ++counter;
                }
            });
        for (auto &t : ts) t.join();
        CHECK(counter == 80'000);
    }
    {
        // CondVar handoff + MutexLock::unlock()/lock() re-acquire window
        Mutex mu;
        CondVar cv;
        std::deque<int> q;
        bool done = false;
        int sum = 0;
        std::thread consumer([&] {
            MutexLock lk(mu);
            while (true) {
                while (q.empty() && !done) cv.wait(mu);
                while (!q.empty()) {
                    int v = q.front();
                    q.pop_front();
                    lk.unlock();     // consume outside the lock
                    sum += v;
                    lk.lock();
                }
                if (done) return;
            }
        });
        for (int i = 1; i <= 100; ++i) {
            {
                MutexLock lk(mu);
                q.push_back(i);
            }
            cv.notify_one();
        }
        {
            MutexLock lk(mu);
            done = true;
        }
        cv.notify_all();
        consumer.join();
        CHECK(sum == 5050);
    }
    {
        // try_lock: held mutex must refuse, released mutex must grant
        // (structured so clang's analysis can track the try-acquire result)
        Mutex mu;
        mu.lock();
        bool got = false;
        std::thread([&] {
            if (mu.try_lock()) {
                got = true;
                mu.unlock();
            }
        }).join();
        CHECK(!got);
        mu.unlock();
        if (mu.try_lock()) {  // branch directly: keeps the analysis' lock
            mu.unlock();      // state consistent at the join point
        } else {
            CHECK(!"try_lock on a free mutex must succeed");
        }
        // timed CondVar wait must observe a timeout without a notifier;
        // loop on the deadline — a spurious wake legally returns no_timeout
        CondVar cv;
        MutexLock lk(mu);
        auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::milliseconds(10);
        while (cv.wait_until(mu, deadline) != std::cv_status::timeout) {
        }
        CHECK(std::chrono::steady_clock::now() >= deadline);
    }
    fprintf(stderr, "lock annotations: ok\n");
}

static void test_telemetry() {
    auto &rec = telemetry::Recorder::inst();
    const bool was_on = rec.on();
    rec.clear();
    rec.enable(true);
    // spans/instants from several threads land ordered and intact
    auto t0 = telemetry::now_ns();
    rec.span("unit", "alpha", t0, t0 + 1000, "seq", 7, "bytes", 42);
    std::vector<std::thread> ths;
    for (int t = 0; t < 4; ++t)
        ths.emplace_back([&] {
            for (int i = 0; i < 100; ++i)
                telemetry::Recorder::inst().instant("unit", "tick", "i",
                                                    static_cast<uint64_t>(i));
        });
    for (auto &th : ths) th.join();
    auto evs = rec.snapshot();
    CHECK(evs.size() == 401);
    for (size_t i = 1; i < evs.size(); ++i) CHECK(evs[i - 1].ts_ns <= evs[i].ts_ns);
    size_t spans = 0;
    for (const auto &e : evs)
        if (e.dur_ns) {
            ++spans;
            CHECK(std::string(e.name) == "alpha");
            CHECK(e.v0 == 7 && e.v1 == 42);
        }
    CHECK(spans == 1);
    // disabled path records nothing
    rec.enable(false);
    rec.instant("unit", "dropped");
    CHECK(rec.snapshot().size() == 401);
    // JSON dump round-trips through a file and is non-trivial
    const char *path = "/tmp/pcclt_selftest_trace.json";
    rec.enable(true);
    CHECK(rec.dump_json(path));
    FILE *f = fopen(path, "r");
    CHECK(f != nullptr);
    if (f) {
        char buf[64] = {0};
        CHECK(fread(buf, 1, 15, f) == 15);
        CHECK(strncmp(buf, "{\"traceEvents\":", 15) == 0);
        fclose(f);
        remove(path);
    }
    // interning is stable: same string -> same pointer
    CHECK(telemetry::intern("edge-x") == telemetry::intern("edge-x"));
    // domain edge counters: registration is idempotent, snapshot faithful,
    // and edges without a single established conn (pre-rekey ephemeral-port
    // stubs) are filtered from snapshots
    telemetry::Domain dom;
    dom.edge("127.0.0.1:9").conns.fetch_add(1);
    dom.edge("127.0.0.1:9").tx_bytes.fetch_add(123);
    dom.edge("127.0.0.1:9").rx_bytes.fetch_add(45);
    dom.edge("127.0.0.1:99");  // stub: never connected
    auto edges = dom.snapshot_edges();
    CHECK(edges.size() == 1);
    CHECK(edges[0].endpoint == "127.0.0.1:9");
    CHECK(edges[0].tx_bytes == 123 && edges[0].rx_bytes == 45);
    rec.clear();
    rec.enable(was_on);
}

// Observability plane units (docs/09): the digest snapshotter's EWMA fold,
// the op-sample ring, the recorder's ring-drop accounting, and the master's
// fleet-health render fed through a real digest packet round-trip.
// digest folding is asynchronous (off-dispatcher ingest): spin until the
// fold thread has published at least `n` digests, so render CHECKs see them
static void wait_folded(master::MasterState &m, uint64_t n) {
    for (int i = 0; i < 50'000 && m.digests_folded() < n; ++i)
        std::this_thread::sleep_for(std::chrono::microseconds(100));
    CHECK(m.digests_folded() >= n);
}

static void test_observability() {
    // renders in this test must never serve a stale cache entry
    setenv("PCCLT_METRICS_MAX_AGE_MS", "0", 1);
    // log2 latency histogram (attribution plane, docs/09): bucket edges,
    // overflow bucket, merge, quantile resolution, sparse<->dense
    {
        using telemetry::kHistBuckets;
        CHECK(telemetry::hist_bucket(0) == 0);
        CHECK(telemetry::hist_bucket(8191) == 0);   // < 8 µs -> bucket 0
        CHECK(telemetry::hist_bucket(8192) == 1);   // [2^13, 2^14)
        CHECK(telemetry::hist_bucket(16383) == 1);
        CHECK(telemetry::hist_bucket(16384) == 2);
        CHECK(telemetry::hist_bucket(~0ull) == kHistBuckets - 1);
        CHECK(telemetry::hist_upper_ns(0) == 8192);
        CHECK(telemetry::hist_upper_ns(kHistBuckets - 1) == ~0ull);
        telemetry::Hist h;
        h.record(0);
        h.record(10'000);
        h.record(1ull << 40);  // ~18 min: lands in the overflow bucket
        auto s = h.snapshot();
        CHECK(s.count() == 3);
        CHECK(s.buckets[0] == 1 && s.buckets[1] == 1);
        CHECK(s.buckets[kHistBuckets - 1] == 1);
        CHECK(s.sum_ns == 10'000 + (1ull << 40));
        auto m = s;
        m.merge(s);
        CHECK(m.count() == 6 && m.sum_ns == 2 * s.sum_ns);
        // quantiles resolve to the holding bucket's upper edge; the
        // overflow bucket reports its finite lower edge, never +Inf
        telemetry::Hist q;
        for (int i = 0; i < 99; ++i) q.record(10'000);
        q.record(1'000'000'000);  // one ~1 s outlier
        CHECK(q.snapshot().quantile_ns(0.5) == 16384);
        CHECK(q.snapshot().quantile_ns(1.0) >= (1ull << 30));
        CHECK(s.quantile_ns(1.0) < ~0ull);  // overflow stays finite
        // sparse wire form is lossless over the grid
        auto dn = telemetry::hist_dense(s.sum_ns, telemetry::hist_sparse(s));
        CHECK(dn.sum_ns == s.sum_ns && dn.count() == s.count());
        for (size_t i = 0; i < kHistBuckets; ++i)
            CHECK(dn.buckets[i] == s.buckets[i]);
    }

    // op-sample ring: keeps the newest kOpRing, last_seq tracks the max
    auto dom = std::make_shared<telemetry::Domain>();
    for (uint64_t i = 1; i <= 12; ++i) dom->record_op(i, i * 100, i * 10);
    auto ops = dom->recent_ops();
    CHECK(ops.size() == telemetry::Domain::kOpRing);
    CHECK(ops.front().seq == 12 - telemetry::Domain::kOpRing + 1);
    CHECK(ops.back().seq == 12 && ops.back().dur_ns == 1200);
    dom->record_op(5, 1, 1); // stale seq must not regress last_seq
    CHECK(dom->last_seq() == 12);

    // digest snapshotter: rates from interval deltas, cumulative carried
    telemetry::DigestSnapshotter snap(dom);
    dom->edge("10.0.0.1:1").conns.fetch_add(1);
    dom->edge("10.0.0.1:1").tx_bytes.fetch_add(1'000'000);
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    auto d1 = snap.snapshot();
    CHECK(d1.edges.size() == 1);
    CHECK(d1.edges[0].tx_bytes == 1'000'000);
    CHECK(d1.edges[0].tx_mbps > 0);
    CHECK(d1.last_seq == 12 && d1.ops.size() == telemetry::Domain::kOpRing);
    dom->edge("10.0.0.1:1").tx_bytes.fetch_add(500);
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    auto d2 = snap.snapshot();
    CHECK(d2.edges[0].tx_bytes == 1'000'500);
    CHECK(d2.edges[0].tx_mbps < d1.edges[0].tx_mbps); // EWMA decays

    // digest wire round-trip, incl. the trailing attribution section
    // (ring accounting + sparse phase/edge histograms)
    proto::TelemetryDigestC2M pkt;
    pkt.epoch = 3;
    pkt.last_seq = d2.last_seq;
    pkt.ring_dropped = 7;
    pkt.collectives_ok = 9;
    pkt.edges.push_back({"10.0.0.1:1", 12.5, 3.25, 0.125, 1'000'500, 77, 0, {}, {}});
    pkt.ops.push_back({12, 1200, 120});
    pkt.ring_pushed = 5000;
    pkt.ring_cap = 65536;
    pkt.phase_hists.emplace_back(
        0, proto::WireHist{123456, {{1, 42}, {7, 3}}});  // Phase::kOp
    pkt.edges[0].stage_wire_hist = {888, {{3, 5}}};
    pkt.edges[0].stall_hist = {999, {{2, 7}, {25, 1}}};  // incl. overflow
    auto dec = proto::TelemetryDigestC2M::decode(pkt.encode());
    CHECK(dec.has_value());
    CHECK(dec->epoch == 3 && dec->edges.size() == 1 && dec->ops.size() == 1);
    CHECK(dec->edges[0].endpoint == "10.0.0.1:1");
    CHECK(dec->edges[0].tx_mbps == 12.5 && dec->edges[0].rx_bytes == 77);
    CHECK(dec->ring_pushed == 5000 && dec->ring_cap == 65536);
    CHECK(dec->phase_hists.size() == 1 && dec->phase_hists[0].first == 0);
    CHECK(dec->phase_hists[0].second.sum_ns == 123456);
    CHECK(dec->phase_hists[0].second.buckets.size() == 2);
    CHECK(dec->edges[0].stall_hist.buckets.size() == 2);
    CHECK(dec->edges[0].stage_wire_hist.sum_ns == 888);
    {
        // a digest WITHOUT the tail (older peer) still decodes: chop the
        // encoded frame at the tail's start (ring_pushed u64)
        proto::TelemetryDigestC2M no_tail;
        no_tail.epoch = 3;
        no_tail.edges.push_back({"10.0.0.1:1", 1.0, 1.0, 0.0, 1, 1, 0, {}, {}});
        auto enc = no_tail.encode();
        // strip the tail: ring_pushed(8) + ring_cap(8) + n_phase(1) +
        // two empty per-edge hists (sum u64 + n u8 = 9 each)
        enc.resize(enc.size() - (8 + 8 + 1 + 9 + 9));
        auto dec2 = proto::TelemetryDigestC2M::decode(enc);
        CHECK(dec2.has_value() && dec2->ring_cap == 0 &&
              dec2->phase_hists.empty());
        // out-of-grid bucket index rejects the frame
        proto::TelemetryDigestC2M bad = no_tail;
        bad.phase_hists.emplace_back(0, proto::WireHist{1, {{26, 1}}});
        CHECK(!proto::TelemetryDigestC2M::decode(bad.encode()).has_value());
    }

    // fleet health render: a registered client's digest shows up in both
    // the Prometheus text and the /health JSON
    master::MasterState st;
    proto::HelloC2M h;
    h.p2p_port = 1;
    auto src = net::Addr::parse("10.0.0.9", 0);
    CHECK(src.has_value());
    auto out = st.on_hello(1, *src, h);
    CHECK(!out.empty());
    CHECK(st.on_telemetry_digest(1, *dec).empty()); // fire-and-forget
    CHECK(st.on_telemetry_digest(99, *dec).empty()); // unknown conn: ignored
    wait_folded(st, 1);
    auto prom = st.render_metrics();
    CHECK(prom.find("pcclt_master_telemetry_digests_total 1") != std::string::npos);
    CHECK(prom.find("pcclt_edge_tx_mbps{") != std::string::npos);
    CHECK(prom.find("to=\"10.0.0.1:1\"") != std::string::npos);
    CHECK(prom.find("pcclt_peer_last_seq{") != std::string::npos);
    // attribution plane: histogram series (cumulative le buckets + +Inf),
    // quantile summary gauges, ring-saturation gauges, incident counters
    CHECK(prom.find("pcclt_phase_latency_seconds_bucket{") != std::string::npos);
    CHECK(prom.find("phase=\"op\"") != std::string::npos);
    CHECK(prom.find("le=\"+Inf\"} 45") != std::string::npos);  // 42 + 3
    CHECK(prom.find("pcclt_phase_latency_seconds_count{") != std::string::npos);
    CHECK(prom.find("pcclt_phase_latency_p99_seconds{") != std::string::npos);
    CHECK(prom.find("pcclt_edge_stall_latency_seconds_bucket{") !=
          std::string::npos);
    CHECK(prom.find("pcclt_peer_trace_ring_pushed{") != std::string::npos);
    CHECK(prom.find("pcclt_peer_trace_ring_capacity{") != std::string::npos);
    CHECK(prom.find("pcclt_master_trace_ring_capacity ") != std::string::npos);
    CHECK(prom.find("pcclt_master_incidents_total 0") != std::string::npos);
    // build/identity + ingest-queue families (fleet-scale plane, docs/09)
    CHECK(prom.find("pcclt_build_info{version=\"") != std::string::npos);
    CHECK(prom.find("pcclt_master_uptime_seconds ") != std::string::npos);
    CHECK(prom.find("pcclt_master_digest_queue_capacity ") != std::string::npos);
    CHECK(prom.find("pcclt_master_digest_queue_dropped_total 0") !=
          std::string::npos);
    CHECK(prom.find("pcclt_master_digest_fold_seconds_bucket{") !=
          std::string::npos);
    auto health = st.render_health_json();
    CHECK(health.find("\"telemetry_digests\":1") != std::string::npos);
    CHECK(health.find("\"ring_dropped\":7") != std::string::npos);
    CHECK(health.find("\"ring_pushed\":5000") != std::string::npos);
    CHECK(health.find("\"straggler\":false") != std::string::npos);
    CHECK(health.find("\"incidents\":[]") != std::string::npos);
    CHECK(health.find("\"build\":{\"version\":") != std::string::npos);
    CHECK(health.find("\"digest_queue\":{") != std::string::npos);
    CHECK(health.find("\"history\"") == std::string::npos); // opt-in only
    CHECK(st.render_health_json(true).find("\"history\":[") !=
          std::string::npos);

    // scrape-cost guard (ROADMAP fleet-scale groundwork): a fleet-sized
    // model — 128 peers x 8 edges = 1024 edge series with full histograms
    // on every edge and phase — must render in bounded time. The bound is
    // deliberately loose (sanitizer lanes, loaded CI boxes): it catches a
    // quadratic render, not scheduler noise.
    {
        master::MasterState big;
        proto::WireHist full{1'000'000, {}};
        for (uint8_t i = 0; i < 26; ++i) full.buckets.emplace_back(i, i + 1);
        const int peers = fast_mode() ? 32 : 128;
        for (int c = 0; c < peers; ++c) {
            proto::HelloC2M h;
            h.p2p_port = static_cast<uint16_t>(1000 + c);
            auto a = net::Addr::parse("10.1." + std::to_string(c / 250) + "." +
                                          std::to_string(c % 250 + 1),
                                      0);
            CHECK(a.has_value());
            big.on_hello(static_cast<uint64_t>(c + 1), *a, h);
            proto::TelemetryDigestC2M dg;
            dg.last_seq = c;
            dg.ring_pushed = 100;
            dg.ring_cap = 65536;
            for (size_t p = 0; p < telemetry::kPhaseCount; ++p)
                dg.phase_hists.emplace_back(static_cast<uint8_t>(p), full);
            for (int e = 0; e < 8; ++e) {
                proto::TelemetryDigestC2M::Edge ed;
                ed.endpoint = "10.2.0." + std::to_string(e + 1) + ":1";
                ed.tx_mbps = 1.0;
                ed.rx_mbps = 1.0;
                ed.stage_wire_hist = full;
                ed.stall_hist = full;
                dg.edges.push_back(std::move(ed));
            }
            big.on_telemetry_digest(static_cast<uint64_t>(c + 1), dg);
        }
        wait_folded(big, static_cast<uint64_t>(peers));
        auto t0 = telemetry::now_ns();
        auto text = big.render_metrics();
        auto dt_ms = (telemetry::now_ns() - t0) / 1'000'000;
        CHECK(text.size() > 100'000);  // the series are actually there
        CHECK(text.find("pcclt_edge_stage_latency_seconds_bucket{") !=
              std::string::npos);
        CHECK(dt_ms < 15'000);
        // default top-K (64) < peers*8 edges: the tail must be rolled up
        // into per-peer aggregate series instead of dropped on the floor
        CHECK(text.find("pcclt_peer_edges_rolled_up{") != std::string::npos);
        CHECK(text.find("pcclt_peer_rollup_tx_bytes_total{") !=
              std::string::npos);
        // TOPK=0 = unbounded legacy render: full per-edge detail, no rollup
        setenv("PCCLT_METRICS_EDGE_TOPK", "0", 1);
        auto full_text = big.render_metrics();
        unsetenv("PCCLT_METRICS_EDGE_TOPK");
        CHECK(full_text.find("pcclt_peer_edges_rolled_up{") ==
              std::string::npos);
        CHECK(full_text.size() > text.size());
        fprintf(stderr,
                "observability: %d-peer scrape = %zu bytes in %llu ms "
                "(topk64) / %zu bytes (full)\n",
                peers, text.size(), (unsigned long long)dt_ms,
                full_text.size());
    }

    // recorder ring-drop accounting: overflow the 64k ring, count the loss
    auto &rec = telemetry::Recorder::inst();
    const bool was_on = rec.on();
    rec.clear();
    CHECK(rec.dropped() == 0);
    rec.enable(true);
    const uint64_t push_n = (1u << 16) + 1000;
    for (uint64_t i = 0; i < push_n; ++i)
        rec.instant("unit", "flood", "i", i);
    CHECK(rec.pushed() == push_n);
    CHECK(rec.dropped() == 1000);
    CHECK(rec.snapshot().size() == (1u << 16));
    rec.clear();
    CHECK(rec.dropped() == 0); // clear re-anchors the window
    // epoch stamping: events pushed after set_epoch carry it
    rec.set_epoch(42);
    rec.instant("unit", "stamped");
    auto evs = rec.snapshot();
    CHECK(evs.size() == 1 && evs[0].epoch == 42);
    rec.set_epoch(0);
    rec.clear();
    rec.enable(was_on);
    fprintf(stderr, "observability: ok\n");
}

// Off-dispatcher digest ingest (docs/09 fleet scale). The dispatcher's
// digest path is ENQUEUE-ONLY: it must never acquire health_mu_. The proof
// is structural — a HOLDER thread owns health_mu_ (starving the fold
// thread) while the test thread pumps digests and ticks through the
// dispatcher entry points holding nothing; the holder only releases after
// witnessing, lock still held, that every call completed and nothing
// folded. A dispatcher-side health_mu_ acquisition would park the pump
// behind the holder and the witness could never flip. (The holder thread
// exists so the dispatcher calls run lock-free on THIS thread — holding
// health_mu_ across them here would itself order lower-ranked dispatcher
// locks under rank 36.) Then the bounded-queue overflow contract: at a
// tiny cap, a flood drops-and-counts instead of back-pressuring the
// dispatcher.
static void test_master_ingest_offloop() {
    setenv("PCCLT_METRICS_MAX_AGE_MS", "0", 1);
    unsetenv("PCCLT_INCIDENT_DIR");
    {
        master::MasterState st;
        proto::HelloC2M h;
        h.p2p_port = 7;
        auto src = net::Addr::parse("10.3.0.1", 0);
        CHECK(src.has_value());
        st.on_hello(1, *src, h);
        proto::TelemetryDigestC2M dg;
        dg.edges.push_back({"10.3.0.2:7", 5.0, 5.0, 0.0, 100, 100, 0, {}, {}});
        const uint64_t n = 32;
        std::atomic<bool> held{false}, pumped{false};
        std::thread holder([&] {
            MutexLock lk(st.health_mutex_test_hook()); // fold thread starved
            held.store(true);
            for (int i = 0; i < 100000 && !pumped.load(); ++i)
                // pcclt-verify: allow-blocking(selftest starves the fold thread on purpose; this lock is only ever held standalone)
                std::this_thread::sleep_for(std::chrono::microseconds(100));
            // witnessed with health_mu_ still held: every dispatcher call
            // completed and nothing folded — the digest and tick paths
            // are lock-free w.r.t. the fleet-health maps
            CHECK(pumped.load());
            CHECK(st.digests_folded() == 0);
        });
        while (!held.load())
            std::this_thread::sleep_for(std::chrono::microseconds(50));
        for (uint64_t i = 0; i < n; ++i) {
            CHECK(st.on_telemetry_digest(1, dg).empty());
            st.on_tick();
        }
        pumped.store(true);
        holder.join();
        wait_folded(st, n);
        CHECK(st.ingest_dropped() == 0); // default cap far above the burst
    }

    // bounded-queue overflow: cap 4, fold thread starved -> the flood's
    // tail is dropped and counted; every digest that DID land still folds
    {
        setenv("PCCLT_DIGEST_QUEUE_CAP", "4", 1);
        master::MasterState st;
        proto::HelloC2M h;
        h.p2p_port = 7;
        auto src = net::Addr::parse("10.3.1.1", 0);
        CHECK(src.has_value());
        st.on_hello(1, *src, h);
        proto::TelemetryDigestC2M dg;
        dg.edges.push_back({"10.3.1.2:7", 5.0, 5.0, 0.0, 100, 100, 0, {}, {}});
        const uint64_t flood = 64;
        std::atomic<bool> held{false}, flooded{false};
        std::thread holder([&] {
            MutexLock lk(st.health_mutex_test_hook()); // fold thread starved
            held.store(true);
            for (int i = 0; i < 100000 && !flooded.load(); ++i)
                // pcclt-verify: allow-blocking(selftest starves the fold thread on purpose; this lock is only ever held standalone)
                std::this_thread::sleep_for(std::chrono::microseconds(100));
            CHECK(flooded.load());
        });
        while (!held.load())
            std::this_thread::sleep_for(std::chrono::microseconds(50));
        st.on_telemetry_digest(1, dg);
        // let the fold thread pick the first digest up and park on
        // health_mu_ (bounded poll; harmless if it parked elsewhere)
        for (int i = 0; i < 1000 && st.ingest_queue_depth() > 0; ++i)
            std::this_thread::sleep_for(std::chrono::microseconds(100));
        for (uint64_t i = 0; i < flood; ++i) st.on_telemetry_digest(1, dg);
        CHECK(st.ingest_dropped() > 0);
        flooded.store(true);
        holder.join();
        const uint64_t landed = flood + 1 - st.ingest_dropped();
        wait_folded(st, landed);
        CHECK(st.digests_folded() == landed);
        unsetenv("PCCLT_DIGEST_QUEUE_CAP");
    }

    // observer control sessions (telemetry-only): welcomed without an
    // admission round, invisible to the world, digests still fold
    {
        master::MasterState st;
        proto::HelloC2M ho;
        ho.observer = 1;
        ho.p2p_port = 1;
        auto a = net::Addr::parse("10.5.0.1", 0);
        CHECK(a.has_value());
        auto out = st.on_hello(1, *a, ho);
        CHECK(out.size() == 1 && out[0].type == proto::kM2CWelcome);
        CHECK(st.world_size() == 0); // never pending, never admitted
        // the observer flag survives the wire round-trip...
        auto rt = proto::HelloC2M::decode(ho.encode());
        CHECK(rt.has_value() && rt->observer == 1);
        // ...and a tail-less hello from an older client decodes observer=0
        auto enc = ho.encode();
        enc.pop_back();
        auto rt0 = proto::HelloC2M::decode(enc);
        CHECK(rt0.has_value() && rt0->observer == 0);
        proto::TelemetryDigestC2M dg;
        dg.edges.push_back({"10.5.0.2:7", 5.0, 5.0, 0.0, 100, 100, 0, {}, {}});
        st.on_telemetry_digest(1, dg);
        wait_folded(st, 1);
        // a real peer joining alongside admits immediately: the observer
        // holds no vote and appears in no peer list
        proto::HelloC2M hn;
        hn.p2p_port = 2;
        auto b = net::Addr::parse("10.5.0.2", 0);
        CHECK(b.has_value());
        auto out2 = st.on_hello(2, *b, hn);
        CHECK(st.world_size() == 1);
        for (const auto &o : out2)
            if (o.type == proto::kM2CP2PConnInfo) {
                auto info = proto::P2PConnInfo::decode(o.payload);
                CHECK(info.has_value() && info->peers.empty());
            }
        // observer disconnect is a fast path: no journal, no group abort
        st.on_disconnect(1);
        CHECK(st.world_size() == 1);
    }
    fprintf(stderr, "ingest offloop: ok\n");
}

// Per-trigger-class incident rate limiting (docs/09): the first
// watchdog_confirm fires a fleet-wide black-box broadcast; a second
// confirm of the same class inside the window is suppressed, counted
// globally AND per class on /metrics.
static void test_master_incident_classes() {
    setenv("PCCLT_METRICS_MAX_AGE_MS", "0", 1);
    setenv("PCCLT_INCIDENT_DIR", "/tmp/pcclt-selftest-incidents", 1);
    setenv("PCCLT_INCIDENT_MIN_MS", "600000", 1); // one fire per class
    {
        master::MasterState st;
        auto join = [&](uint64_t conn, const char *ip) {
            proto::HelloC2M h;
            h.p2p_port = 7;
            auto a = net::Addr::parse(ip, 0);
            CHECK(a.has_value());
            st.on_hello(conn, *a, h);
        };
        join(1, "10.4.0.1");
        join(2, "10.4.0.2");
        join(3, "10.4.0.3");
        auto confirm_digest = [](const char *endpoint) {
            proto::TelemetryDigestC2M d;
            proto::TelemetryDigestC2M::Edge e;
            e.endpoint = endpoint;
            e.tx_mbps = 3.0;
            e.rx_mbps = 3.0;
            e.stall_ratio = 0.9;
            e.wd_state = 2; // watchdog CONFIRMED
            d.edges.push_back(std::move(e));
            return d;
        };
        // first CONFIRM: incident broadcast reaches every control session
        st.on_telemetry_digest(1, confirm_digest("10.4.0.2:7"));
        wait_folded(st, 1);
        bool fired = false;
        for (int i = 0; i < 2000 && !fired; ++i) {
            for (const auto &o : st.on_tick())
                if (o.type == proto::kM2CIncidentDump) fired = true;
            if (!fired)
                std::this_thread::sleep_for(std::chrono::milliseconds(1));
        }
        CHECK(fired);
        // second CONFIRM, same class, inside the window: suppressed
        st.on_telemetry_digest(1, confirm_digest("10.4.0.3:7"));
        wait_folded(st, 2);
        bool suppressed = false;
        for (int i = 0; i < 2000 && !suppressed; ++i) {
            for (const auto &o : st.on_tick())
                CHECK(o.type != proto::kM2CIncidentDump);
            auto prom = st.render_metrics();
            suppressed =
                prom.find("pcclt_master_incidents_suppressed_by_class_total{"
                          "trigger_class=\"watchdog_confirm\"} 1") !=
                std::string::npos;
            if (!suppressed)
                std::this_thread::sleep_for(std::chrono::milliseconds(1));
        }
        CHECK(suppressed);
        auto prom = st.render_metrics();
        CHECK(prom.find("pcclt_master_incidents_total 1") != std::string::npos);
        CHECK(prom.find("pcclt_master_incidents_suppressed_total 1") !=
              std::string::npos);
        auto health = st.render_health_json();
        CHECK(health.find("\"incidents_suppressed\":1") != std::string::npos);
    }
    unsetenv("PCCLT_INCIDENT_MIN_MS");
    unsetenv("PCCLT_INCIDENT_DIR");
    fprintf(stderr, "incident classes: ok\n");
}

// /health history ring (docs/09): the fold thread samples fleet gauges on
// the PCCLT_HEALTH_HISTORY_MS cadence into a bounded ring, served only
// under /health?history=1.
static void test_master_health_history() {
    setenv("PCCLT_METRICS_MAX_AGE_MS", "0", 1);
    setenv("PCCLT_HEALTH_HISTORY_MS", "20", 1);
    setenv("PCCLT_HEALTH_HISTORY", "5", 1);
    {
        master::MasterState st;
        proto::HelloC2M h;
        h.p2p_port = 7;
        auto src = net::Addr::parse("10.6.0.1", 0);
        CHECK(src.has_value());
        st.on_hello(1, *src, h);
        proto::TelemetryDigestC2M dg;
        dg.edges.push_back({"10.6.0.2:7", 5.0, 5.0, 0.0, 100, 100, 0, {}, {}});
        st.on_telemetry_digest(1, dg);
        wait_folded(st, 1);
        auto count_samples = [](const std::string &j) {
            size_t n = 0;
            for (size_t p = j.find("\"age_ms\":"); p != std::string::npos;
                 p = j.find("\"age_ms\":", p + 1))
                ++n;
            return n;
        };
        // samples accumulate on the fold thread's own clock
        size_t got = 0;
        for (int i = 0; i < 4000 && got < 2; ++i) {
            got = count_samples(st.render_health_json(true));
            if (got < 2)
                std::this_thread::sleep_for(std::chrono::milliseconds(1));
        }
        CHECK(got >= 2);
        // the ring is bounded: after plenty more periods, at most the cap
        std::this_thread::sleep_for(std::chrono::milliseconds(700));
        auto hist = st.render_health_json(true);
        CHECK(count_samples(hist) >= 2 && count_samples(hist) <= 5);
        CHECK(hist.find("\"digest_rate\":") != std::string::npos);
        // plain /health never carries the ring
        CHECK(st.render_health_json().find("\"history\"") ==
              std::string::npos);
    }
    unsetenv("PCCLT_HEALTH_HISTORY_MS");
    unsetenv("PCCLT_HEALTH_HISTORY");
    fprintf(stderr, "health history: ok\n");
}

// Chaos schedule grammar + timing (netem.hpp, docs/05): parser accepts the
// documented fault kinds and skips garbage; an armed script degrades /
// blacks out the edge at its scripted offsets; runtime injection validates
// its inputs. Timing checks use generous windows (sanitizer lanes run on
// loaded single-core boxes).
static void test_chaos_schedule() {
    using namespace net::netem;
    constexpr uint64_t kMs = 1'000'000ull;

    auto fs = parse_chaos(
        "degrade@t=0s:40mbit/200ms; flap@t=100ms:50msx3; blackhole@t=1s:2s",
        "selftest");
    CHECK(fs.size() == 3);
    CHECK(fs[0].kind == ChaosFault::kDegrade && fs[0].mbps == 40.0 &&
          fs[0].start_ns == 0 && fs[0].dur_ns == 200 * kMs);
    CHECK(fs[1].kind == ChaosFault::kFlap && fs[1].repeat == 3 &&
          fs[1].start_ns == 100 * kMs && fs[1].dur_ns == 50 * kMs);
    CHECK(fs[2].kind == ChaosFault::kBlackhole &&
          fs[2].dur_ns == 2000 * kMs);
    // Unicode multiplication sign + the no-@ (fire-on-arm) form
    auto f2 = parse_chaos("flap:10ms\xc3\x97""2", "selftest");
    CHECK(f2.size() == 1 && f2[0].repeat == 2 && f2[0].start_ns == 0);
    // malformed faults are skipped, good neighbors survive
    CHECK(parse_chaos("junk", "selftest").empty());
    CHECK(parse_chaos("degrade@t=0s:xmbit/1s", "selftest").empty());
    CHECK(parse_chaos("meteor@t=0s:1s;blackhole@t=0s:1s", "selftest").size() ==
          1);

    auto st0 = chaos_stats();
    // a degrade window overrides the rate, then lifts
    Edge e;
    e.arm_chaos({ChaosFault{ChaosFault::kDegrade, 0, 150 * kMs, 1, 25.0}});
    CHECK(e.pace_enabled());  // armed chaos counts as emulation
    auto v = e.chaos_at(0);
    CHECK(v.mbps_override == 25.0 && !v.outage);
    std::this_thread::sleep_for(std::chrono::milliseconds(200));
    v = e.chaos_at(0);
    CHECK(v.mbps_override == 0 && !v.outage);

    // a blackhole stalls pace() until the outage lifts
    Edge b;
    b.arm_chaos({ChaosFault{ChaosFault::kBlackhole, 0, 120 * kMs, 1, 0}});
    auto t0 = std::chrono::steady_clock::now();
    b.pace(1);
    auto waited = std::chrono::duration_cast<std::chrono::milliseconds>(
                      std::chrono::steady_clock::now() - t0)
                      .count();
    CHECK(waited >= 60);  // slept out (most of) the outage window
    CHECK(b.delivery_delay_ns() == 0 || waited < 120);  // lifted afterwards

    // flap periodicity: outage windows of D at period 2D, `repeat` times
    Edge f;
    f.arm_chaos({ChaosFault{ChaosFault::kFlap, 0, 100 * kMs, 2, 0}});
    std::this_thread::sleep_for(std::chrono::milliseconds(40));
    CHECK(f.chaos_at(0).outage);  // inside outage 1 [0, 100ms)
    std::this_thread::sleep_for(std::chrono::milliseconds(110));
    CHECK(!f.chaos_at(0).outage);  // gap [100ms, 200ms)
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    CHECK(f.chaos_at(0).outage);  // outage 2 [200ms, 300ms)
    std::this_thread::sleep_for(std::chrono::milliseconds(160));
    CHECK(!f.chaos_at(0).outage);  // repeat budget spent

    auto st1 = chaos_stats();
    CHECK(st1.armed >= st0.armed + 3);
    CHECK(st1.activated >= st0.activated + 4);  // degrade + hole + 2 flaps

    // runtime injection validates endpoint + spec; empty spec disarms
    CHECK(inject("127.0.0.1:45997", "blackhole@t=0s:50ms"));
    CHECK(inject("127.0.0.1:45997", ""));
    CHECK(!inject("no-port", "blackhole@t=0s:1s"));
    CHECK(!inject("127.0.0.1:45997", "meteor@t=0s:1s"));
}

// Striped token bucket (netem.hpp, docs/08 "multipath striping"): K lanes
// on ONE edge share the modeled rate fairly (sum == modeled rate within
// tolerance, no lane starved), a lone lane reclaims the full rate
// (work-conserving), and a chaos blackhole stalls ALL lanes — the
// canonical-edge contract.
static void test_netem_striped_bucket() {
    using namespace net::netem;
    constexpr uint64_t kMs = 1'000'000ull;

    // (1) aggregate conservation + fairness: 4 lanes, 200 Mbit (25 MB/s).
    // 4 lanes x 16 frames x 64 KiB = 4 MiB -> 160 ms minimum on the wire.
    {
        EdgeParams p;
        p.mbps = 200;
        Edge e(p);
        const int K = 4, frames = 16;
        const size_t fb = 64 << 10;
        std::vector<double> lane_s(K);
        std::vector<std::thread> ths;
        auto t0 = std::chrono::steady_clock::now();
        for (int k = 0; k < K; ++k)
            ths.emplace_back([&, k] {
                uint32_t lane = e.alloc_lane();
                auto lt0 = std::chrono::steady_clock::now();
                for (int i = 0; i < frames; ++i) e.pace(fb, lane);
                lane_s[k] = std::chrono::duration<double>(
                                std::chrono::steady_clock::now() - lt0)
                                .count();
                e.release_lane(lane);
            });
        for (auto &t : ths) t.join();
        double total = std::chrono::duration<double>(
                           std::chrono::steady_clock::now() - t0)
                           .count();
        const double expect = K * frames * fb * 8 / (p.mbps * 1e6); // 0.168 s
        // the bucket may not EXCEED the modeled rate (the ±5% gate's hard
        // side); oversleep on a loaded host only slows it down
        CHECK(total >= 0.95 * expect);
        CHECK(total < 2.5 * expect);
        // fairness / no slot starvation: under continuous backlog every
        // lane drains at ~R/K, so all lanes finish together — a starved
        // lane would finish far later than the aggregate, a greedy one far
        // earlier
        for (int k = 0; k < K; ++k) {
            CHECK(lane_s[k] >= 0.5 * expect);
            CHECK(lane_s[k] <= total + 0.01);
        }
    }

    // (2) work-conserving reclaim: a single lane gets the FULL rate (the
    // exact pre-striping behavior) — 1 MiB @ 25 MB/s = 40 ms minimum,
    // nowhere near the 160 ms a 4-way fair share would take
    {
        EdgeParams p;
        p.mbps = 200;
        Edge e(p);
        uint32_t lane = e.alloc_lane();
        auto t0 = std::chrono::steady_clock::now();
        for (int i = 0; i < 16; ++i) e.pace(64 << 10, lane);
        double s = std::chrono::duration<double>(
                       std::chrono::steady_clock::now() - t0)
                       .count();
        e.release_lane(lane);
        CHECK(s >= 0.038);
        CHECK(s < 0.120);
    }

    // (3) chaos blackhole stalls ALL stripes: every lane's reservation is
    // pushed past the outage window (the schedule lives on the ONE
    // canonical edge, not per lane)
    {
        Edge e;  // no rate: only the chaos schedule paces
        e.arm_chaos({ChaosFault{ChaosFault::kBlackhole, 0, 150 * kMs, 1, 0}});
        std::vector<std::thread> ths;
        std::vector<double> waited(3);
        for (int k = 0; k < 3; ++k)
            ths.emplace_back([&, k] {
                uint32_t lane = e.alloc_lane();
                auto t0 = std::chrono::steady_clock::now();
                e.pace(64 << 10, lane);
                waited[k] = std::chrono::duration<double>(
                                std::chrono::steady_clock::now() - t0)
                                .count();
                e.release_lane(lane);
            });
        for (auto &t : ths) t.join();
        for (int k = 0; k < 3; ++k) CHECK(waited[k] >= 0.080);
    }

    // (4) lane ids recycle: release makes the slot reusable
    {
        Edge e;
        uint32_t a = e.alloc_lane(), b = e.alloc_lane();
        CHECK(a != b && a != 0 && b != 0);
        e.release_lane(a);
        CHECK(e.alloc_lane() == a);
    }

    // (5) per-flow cwnd cap: one lane is window-limited to cwnd/rtt even
    // on an idle edge; two lanes double the aggregate (the fat-long-pipe
    // physics striping exists for), never past the edge rate
    {
        EdgeParams p;
        p.mbps = 800;          // 100 MB/s edge
        p.rtt_ms = 40;         // rtt so the window binds
        p.cwnd_bytes = 1 << 20;  // 1 MiB / 40 ms = 25 MB/s per flow
        Edge e(p);
        CHECK(e.pace_enabled());
        uint32_t lane = e.alloc_lane();
        auto t0 = std::chrono::steady_clock::now();
        for (int i = 0; i < 16; ++i) e.pace(64 << 10, lane);  // 1 MiB
        double s = std::chrono::duration<double>(
                       std::chrono::steady_clock::now() - t0)
                       .count();
        e.release_lane(lane);
        CHECK(s >= 0.038);  // 1 MiB at 25 MB/s = 40 ms (not 10 ms at edge rate)
        CHECK(s < 0.150);
        // two flows: each window-capped, aggregate ~2x
        std::vector<std::thread> ths;
        auto t1 = std::chrono::steady_clock::now();
        for (int k = 0; k < 2; ++k)
            ths.emplace_back([&] {
                uint32_t l = e.alloc_lane();
                for (int i = 0; i < 16; ++i) e.pace(64 << 10, l);
                e.release_lane(l);
            });
        for (auto &t : ths) t.join();
        double s2 = std::chrono::duration<double>(
                        std::chrono::steady_clock::now() - t1)
                        .count();
        CHECK(s2 >= 0.038);  // 2 MiB at 2 x 25 MB/s = 40 ms
        CHECK(s2 < 0.150);   // NOT serialized to 80 ms: flows are parallel
    }
}

// Straggler-failover delivery + dedupe (SinkTable::deliver_window,
// docs/05): first arrival wins byte-exactly, duplicates and late copies
// for completed tags are dropped AND counted, windows racing registration
// park and drain — the conservation identity
// rx + rx_relay - dup == unique holds by construction.
static void test_watchdog() {
    telemetry::EdgeCounters origin;
    auto ld = [&](const std::atomic<uint64_t> &a) { return a.load(); };
    net::SinkTable t;
    std::vector<uint8_t> sink(8192, 0);

    t.register_sink(7, sink.data(), sink.size());
    std::vector<uint8_t> w(4096, 0xAA);
    t.deliver_window(7, 0, w, &origin);
    CHECK(ld(origin.rx_relay_bytes) == 4096 && ld(origin.dup_bytes) == 0);
    CHECK(t.wait_filled(7, 4096, 0) == 4096);
    CHECK(sink[0] == 0xAA && sink[4095] == 0xAA);

    // exact duplicate: dropped, counted — bytes in the sink untouched
    std::vector<uint8_t> w2(4096, 0xBB);
    t.deliver_window(7, 0, w2, &origin);
    CHECK(ld(origin.rx_relay_bytes) == 8192);
    CHECK(ld(origin.dup_bytes) == 4096 && ld(origin.dup_windows) == 1);
    CHECK(sink[0] == 0xAA);  // first arrival won

    // partial overlap: only the uncovered tail lands, the rest is dup
    std::vector<uint8_t> w3(4096, 0xCC);
    t.deliver_window(7, 2048, w3, &origin);
    CHECK(t.wait_filled(7, 6144, 0) == 6144);
    CHECK(sink[4095] == 0xAA && sink[4096] == 0xCC && sink[6143] == 0xCC);
    CHECK(ld(origin.dup_bytes) == 4096 + 2048);
    CHECK(ld(origin.dup_windows) == 1);  // partially useful != duplicate

    // a window racing ahead of registration parks, then drains deduped
    std::vector<uint8_t> small(1024, 0xDD);
    t.deliver_window(9, 0, small, &origin);
    uint64_t relayed_before = ld(origin.rx_relay_bytes);
    std::vector<uint8_t> sink2(1024, 0);
    t.register_sink(9, sink2.data(), sink2.size());
    CHECK(t.wait_filled(9, 1024, 0) == 1024);
    CHECK(sink2[0] == 0xDD);
    CHECK(ld(origin.rx_relay_bytes) == relayed_before + 1024);

    // a FULLY delivered sink retires its tag: late copies count as dup...
    t.unregister_sink(9);
    uint64_t dup_before = ld(origin.dup_bytes);
    t.deliver_window(9, 0, small, &origin);
    CHECK(ld(origin.dup_bytes) == dup_before + 1024);
    // ...but re-registration un-retires (tag reuse stays legal)
    std::fill(sink2.begin(), sink2.end(), 0);
    t.register_sink(9, sink2.data(), sink2.size());
    t.deliver_window(9, 0, small, &origin);
    CHECK(t.wait_filled(9, 1024, 0) == 1024 && sink2[0] == 0xDD);
    t.unregister_sink(9);
    t.unregister_sink(7);

    // watchdog health ladder on the counters themselves
    telemetry::EdgeCounters e;
    CHECK(e.wd_health.load() ==
          static_cast<uint32_t>(telemetry::EdgeHealth::kOk));
    e.wd_health.store(static_cast<uint32_t>(telemetry::EdgeHealth::kSuspect));
    e.wd_health.store(
        static_cast<uint32_t>(telemetry::EdgeHealth::kConfirmed));
    CHECK(e.wd_health.load() == 2u);
}

static void test_wire() {
    wire::Writer w;
    w.u8(7);
    w.u16(0x1234);
    w.u32(0xDEADBEEF);
    w.u64(0x0102030405060708ull);
    w.str("hello");
    w.f64(3.25);
    auto buf = w.take();
    // big-endian layout check
    CHECK(buf[0] == 7 && buf[1] == 0x12 && buf[2] == 0x34 && buf[3] == 0xDE);
    wire::Reader r(buf);
    CHECK(r.u8() == 7);
    CHECK(r.u16() == 0x1234);
    CHECK(r.u32() == 0xDEADBEEF);
    CHECK(r.u64() == 0x0102030405060708ull);
    CHECK(r.str() == "hello");
    CHECK(r.f64() == 3.25);
    CHECK(r.done());

    // family-tagged wire addresses (PCCP/2): both families roundtrip
    // (v6 routes end-to-end since round 4); an unknown family fails loudly
    proto::SharedStateSyncResp resp;
    resp.outdated = 1;
    resp.dist_ip = net::Addr{0x7F000001, 0};  // 127.0.0.1
    resp.dist_port = 1234;
    resp.revision = 9;
    auto dec = proto::SharedStateSyncResp::decode(resp.encode());
    CHECK(dec && dec->dist_ip == (net::Addr{0x7F000001, 0}) &&
          dec->dist_port == 1234 && dec->revision == 9);
    {
        // v6 round-trip: the family tag and 16 address bytes survive
        auto a6 = net::Addr::parse("::1", 0);
        CHECK(a6 && a6->family == 6);
        proto::SharedStateSyncResp r6;
        r6.dist_ip = *a6;
        auto d6 = proto::SharedStateSyncResp::decode(r6.encode());
        CHECK(d6 && d6->dist_ip == *a6 && d6->dist_ip.str() == "[::1]:0");
    }
    {
        // hand-encoded family-6 payload: since the round-4 v6 routing this
        // DECODES (it used to be rejected while the plumbing was v4-only)
        wire::Writer w6;
        w6.u8(1);  // outdated
        w6.u8(0);  // failed
        w6.u8(6);  // family 6
        for (int i = 0; i < 16; ++i) w6.u8(static_cast<uint8_t>(i));
        w6.u16(4321);
        w6.u64(11);
        w6.u32(0);
        w6.u32(0);
        auto d6 = proto::SharedStateSyncResp::decode(w6.take());
        CHECK(d6 && d6->dist_ip.family == 6 && d6->dist_ip.ip6[15] == 15 &&
              d6->dist_port == 4321 && d6->revision == 11);
    }
    {
        // hello carries the wire rev first; roundtrip keeps it
        proto::HelloC2M h;
        h.peer_group = 3;
        auto hd = proto::HelloC2M::decode(h.encode());
        CHECK(hd && hd->wire_rev == proto::kWireRev && hd->peer_group == 3);
    }
    {
        wire::Writer wb;
        wb.u8(1);
        wb.u8(0);
        wb.u8(9);  // unknown family: structurally invalid, decode must fail
        auto db = proto::SharedStateSyncResp::decode(wb.take());
        CHECK(!db);
    }
}

// Deterministic torn-tail sweep over every wire struct: encode a populated
// instance, then decode EVERY prefix of the encoding. Each prefix must
// decode-or-reject — never crash, never read past the buffer (the ASan
// build is the oracle) — and whatever a prefix DOES decode must re-encode
// to a fixed point (trailing sections are tail-tolerant by design, so
// short prefixes may legitimately be accepted as older-peer encodings).
// pcclt_fuzz runs the same sweep plus corruption passes; this copy keeps
// the property pinned in the default selftest lane.
template <typename T>
static void trunc_sweep(const T &v) {
    auto full = v.encode();
    CHECK(T::decode(full).has_value());
    for (size_t n = 0; n <= full.size(); ++n) {
        std::vector<uint8_t> pre(full.begin(), full.begin() + n);
        auto d = T::decode(pre);
        if (n == full.size()) CHECK(d.has_value());
        if (d) {
            auto e1 = d->encode();
            auto d2 = T::decode(e1);
            CHECK(d2 && d2->encode() == e1);
        }
    }
}

static void test_proto_truncation() {
    proto::Uuid ua{};
    for (int i = 0; i < 16; ++i) ua[i] = static_cast<uint8_t>(i + 1);
    net::Addr a4 = *net::Addr::parse("10.1.2.3", 0);

    proto::HelloC2M hello;
    hello.peer_group = 7;
    hello.adv_ip = "10.1.2.3";
    hello.observer = 1;
    trunc_sweep(hello);

    proto::SessionResumeC2M resume;
    resume.uuid = ua;
    resume.last_revision = 42;
    resume.adv_ip = "10.1.2.3";
    trunc_sweep(resume);

    proto::SessionResumeAck rack;
    rack.ok = 1;
    rack.reason = "rehydrated";
    trunc_sweep(rack);

    proto::P2PConnInfo p2p;
    p2p.revision = 9;
    p2p.peers.push_back({ua, a4, 4001, 4003, 7});
    p2p.ring = {ua};
    sched::Table table;
    table.version = 2;
    table.entries.push_back({0, 2, 0, 0});
    p2p.sched = table.encode();
    trunc_sweep(p2p);

    proto::CollectiveInit init;
    init.tag = 77;
    init.count = 1 << 20;
    init.retry = 1;
    init.retry_seq = 5;
    init.aux = 2;
    trunc_sweep(init);

    proto::SharedStateSyncC2M sync;
    sync.revision = 12;
    proto::SharedStateEntryMeta meta;
    meta.name = "weights";
    meta.count = 4096;
    meta.chunk_leaves = {1, 2, 3};
    sync.entries.push_back(meta);
    sync.chunk_bytes = 1 << 20;
    trunc_sweep(sync);

    proto::SharedStateSyncResp resp;
    resp.outdated = 1;
    resp.dist_ip = a4;
    resp.revision = 12;
    resp.outdated_keys = {"weights"};
    resp.expected_hashes = {0xAA};
    resp.has_chunk_map = 1;
    resp.chunk_bytes = 1 << 20;
    resp.seeders = {{ua, a4, 4002, 4001}};
    resp.key_leaves = {{1, 2, 3}};
    resp.key_seeders = {{0}};
    trunc_sweep(resp);

    proto::SyncKeyDoneC2M done;
    done.revision = 12;
    done.key = "weights";
    trunc_sweep(done);

    proto::SeederUpdateM2C supd;
    supd.revision = 12;
    supd.key = "weights";
    supd.seeder = {ua, a4, 4002, 4001};
    trunc_sweep(supd);

    proto::ScheduleUpdateM2C schu;
    schu.group = 7;
    schu.table = table.encode();
    trunc_sweep(schu);

    proto::TelemetryDigestC2M dig;
    dig.epoch = 3;
    proto::TelemetryDigestC2M::Edge edge;
    edge.endpoint = "10.1.2.3:4001";
    edge.wd_state = 2;
    edge.stage_wire_hist.sum_ns = 1234;
    edge.stage_wire_hist.buckets = {{3, 10}};
    dig.edges.push_back(edge);
    dig.ops.push_back({100, 5'000'000, 1'000'000});
    proto::WireHist ph;
    ph.sum_ns = 99;
    ph.buckets = {{1, 1}};
    dig.phase_hists = {{2, ph}};
    trunc_sweep(dig);

    proto::IncidentDumpM2C inc;
    inc.incident_id = "inc-e3-1";
    inc.trigger = "collective_abort";
    inc.epoch = 3;
    trunc_sweep(inc);

    proto::OptimizeResponse opt;
    opt.requests.push_back({ua, a4, 4003});
    trunc_sweep(opt);

    {   // schedule table: span-decode every prefix of a valid encoding
        auto full = table.encode();
        CHECK(sched::Table::decode(full).has_value());
        for (size_t n = 0; n < full.size(); ++n) {
            auto d = sched::Table::decode({full.data(), n});
            if (d) CHECK(sched::Table::decode(d->encode()).has_value());
        }
    }
    {   // chunk-range request, with and without the optional p2p tail
        ssc::ChunkReqSpec rq;
        rq.revision = 12;
        rq.key = "weights";
        rq.chunk_bytes = 1 << 20;
        rq.first = 3;
        rq.count = 4;
        for (bool p2pb : {false, true}) {
            rq.req_p2p = p2pb ? 4001 : 0;
            auto full = rq.encode(p2pb);
            CHECK(ssc::ChunkReqSpec::decode(full).has_value());
            for (size_t n = 0; n < full.size(); ++n) {
                std::vector<uint8_t> pre(full.begin(), full.begin() + n);
                ssc::ChunkReqSpec::decode(pre);  // decode-or-reject
            }
        }
    }
    {   // data-plane frame preamble: exact length gate, torn prefixes reject
        wire::Writer w;
        w.u32(17 + 8);
        w.u8(net::MultiplexConn::kRelayFwd);
        w.u64(0x1122334455667788ull);
        w.u64(4096);
        auto full = w.take();
        CHECK(full.size() == net::FrameHeader::kWire);
        auto fh = net::FrameHeader::parse(full.data(), full.size());
        CHECK(fh && fh->kind == net::MultiplexConn::kRelayFwd &&
              fh->payload == 8 && fh->off == 4096);
        for (size_t n = 0; n < full.size(); ++n)
            CHECK(!net::FrameHeader::parse(full.data(), n));
        // the two length gates: len < 17 and len > kMaxLen both reject
        wire::Writer bad_lo, bad_hi;
        bad_lo.u32(16);
        bad_hi.u32(net::FrameHeader::kMaxLen + 1);
        for (auto *bw : {&bad_lo, &bad_hi}) {
            bw->u8(0);
            bw->u64(0);
            bw->u64(0);
            auto b = bw->take();
            CHECK(!net::FrameHeader::parse(b.data(), b.size()));
        }
    }
    fprintf(stderr, "proto truncation sweep: ok\n");
}

static void test_hash() {
    const char *s = "the quick brown fox jumps over the lazy dog";
    uint64_t h1 = hash::simplehash(s, strlen(s));
    uint64_t h2 = hash::simplehash(s, strlen(s));
    CHECK(h1 == h2 && h1 != 0);
    std::string s2(s);
    s2[0] = 'T';
    CHECK(hash::simplehash(s2.data(), s2.size()) != h1);
    // long buffer exercising many lanes/rows
    std::vector<uint32_t> big(300000);
    for (size_t i = 0; i < big.size(); ++i) big[i] = static_cast<uint32_t>(i * 2654435761u);
    uint64_t hb = hash::simplehash(big.data(), big.size() * 4);
    big[299999] ^= 1;
    CHECK(hash::simplehash(big.data(), big.size() * 4) != hb);
    // crc32 known vector: crc32("123456789") == 0xCBF43926
    CHECK(hash::crc32("123456789", 9) == 0xCBF43926u);

    // hardware (PCLMUL) and table CRC must agree bit-for-bit across sizes,
    // alignments, and chained seeds (the dispatcher picks the HW path for
    // n >= 64, so compare against a bitwise reference)
    {
        auto ref_crc = [](const uint8_t *p, size_t n, uint32_t crc) {
            crc = ~crc;
            while (n--) {
                crc ^= *p++;
                for (int i = 0; i < 8; ++i)
                    crc = (crc >> 1) ^ (0xEDB88320u & (0u - (crc & 1)));
            }
            return ~crc;
        };
        std::mt19937_64 rng{7};
        std::vector<uint8_t> buf(100003 + 3);
        for (auto &b : buf) b = static_cast<uint8_t>(rng());
        for (size_t n : {0u, 1u, 63u, 64u, 65u, 255u, 4096u, 100003u})
            for (int off = 0; off < 3; ++off)
                CHECK(hash::crc32(buf.data() + off, n, 0x12345678u) ==
                      ref_crc(buf.data() + off, n, 0x12345678u));
    }
}

// shared-state chunk plane (docs/04): hash tree + multi-source fetch plan
static void test_ss_chunk() {
    using namespace ssc;
    // ---- hash tree: boundaries, odd sizes, leaf/root round trip ----
    CHECK(chunk_count(0, 1024) == 0);
    CHECK(chunk_count(1, 1024) == 1);
    CHECK(chunk_count(1024, 1024) == 1);
    CHECK(chunk_count(1025, 1024) == 2);
    CHECK(chunk_count(4096, 1024) == 4);
    CHECK(chunk_len(1025, 1024, 0) == 1024);
    CHECK(chunk_len(1025, 1024, 1) == 1);
    CHECK(chunk_len(1024, 1024, 0) == 1024);
    CHECK(chunk_len(1024, 1024, 1) == 0);

    std::mt19937_64 rng{42};
    std::vector<uint8_t> buf(10 * 1024 + 37);  // odd tail chunk
    for (auto &b : buf) b = static_cast<uint8_t>(rng());
    auto hv = hash::Type::kSimple;
    auto leaves = leaf_hashes(hv, buf.data(), buf.size(), 1024);
    CHECK(leaves.size() == 11);
    // each leaf is the content hash of its slice
    CHECK(leaves[0] == hash::content_hash(hv, buf.data(), 1024));
    CHECK(leaves[10] == hash::content_hash(hv, buf.data() + 10 * 1024, 37));
    uint64_t root = root_hash(hv, leaves);
    CHECK(root != 0);
    // flipping one byte in the LAST (partial) chunk changes exactly that
    // leaf, and the root
    buf.back() ^= 1;
    auto leaves2 = leaf_hashes(hv, buf.data(), buf.size(), 1024);
    CHECK(leaves2[10] != leaves[10]);
    for (size_t i = 0; i < 10; ++i) CHECK(leaves2[i] == leaves[i]);
    CHECK(root_hash(hv, leaves2) != root);
    // chunk size is part of the identity: same bytes, different grid,
    // different root (why PCCLT_SS_CHUNK_BYTES must agree group-wide)
    buf.back() ^= 1;
    auto leaves3 = leaf_hashes(hv, buf.data(), buf.size(), 2048);
    CHECK(root_hash(hv, leaves3) != root);
    // single-chunk entry: root != leaf (the tree is never the identity)
    auto lone = leaf_hashes(hv, buf.data(), 512, 1024);
    CHECK(lone.size() == 1 && root_hash(hv, lone) != lone[0]);

    // ---- fetch plan: assignment, dedupe, re-source, failover ----
    auto mk_keys = [&](std::vector<uint8_t> &dst_a, std::vector<uint8_t> &dst_b) {
        dst_a.assign(4096, 0);
        dst_b.assign(2048 + 100, 0);
        std::vector<KeySpec> ks(2);
        ks[0] = {"a", 4096, dst_a.data(), std::vector<uint64_t>(4, 1)};
        ks[1] = {"b", 2048 + 100, dst_b.data(), std::vector<uint64_t>(3, 2)};
        return ks;
    };
    {
        // two seeders drain disjoint assignments; conservation exact
        std::vector<uint8_t> da, db;
        FetchPlan p(mk_keys(da, db), 1024, 4.0, 1'000'000, 2, /*rot*/ 0);
        uint32_t s0 = p.add_seeder("h:1"), s1 = p.add_seeder("h:2");
        for (uint32_t k = 0; k < 2; ++k) {
            p.add_key_seeder(k, s0);
            p.add_key_seeder(k, s1);
        }
        uint64_t now = 1000;
        size_t assigned = 0;
        while (true) {
            bool any = false;
            for (uint32_t s : {s0, s1}) {
                auto t = p.take(s, now);
                if (!t) continue;
                any = true;
                CHECK(t->count >= 1 && t->count <= 2);  // max_range honored
                for (uint32_t i = 0; i < t->count; ++i) {
                    uint8_t *dst = p.claim(t->key, t->first + i);
                    CHECK(dst != nullptr);
                    memset(dst, 0x5A, 1);
                    p.published(t->key, t->first + i, s, t->gens[i], now + 10);
                    ++assigned;
                }
            }
            if (!any) break;
        }
        CHECK(assigned == 7);
        CHECK(p.complete_ok() && p.finished() && !p.failed_out());
        auto st = p.stats();
        CHECK(st.chunks_fetched == 7 && st.chunks_resourced == 0 &&
              st.chunks_dup == 0);
        CHECK(st.bytes_fetched == 4096 + 2048 + 100);
        CHECK(st.bytes_fetched + st.bytes_resourced - st.bytes_dup ==
              st.unique_bytes);
        auto done = p.take_completed_keys();
        CHECK(done.size() == 2);  // both keys reported exactly once
        CHECK(p.take_completed_keys().empty());
    }
    {
        // deadline expiry re-sources to the other seeder; the straggler's
        // late arrival dedupes (gen classification: fetched vs resourced)
        std::vector<uint8_t> da, db;
        auto ks = mk_keys(da, db);
        ks.pop_back();  // single key "a", 4 chunks
        FetchPlan p(std::move(ks), 1024, 4.0, 1'000'000, 4, 0);
        uint32_t s0 = p.add_seeder("h:1"), s1 = p.add_seeder("h:2");
        p.add_key_seeder(0, s0);
        p.add_key_seeder(0, s1);
        auto t0 = p.take(s0, 0);
        CHECK(t0 && t0->count == 4);
        // s1 has nothing: everything is inflight to s0
        CHECK(!p.take(s1, 0));
        // chunk 0's deadline passes -> re-sourceable
        CHECK(p.expire_overdue(5'000'000'000ull) == 4);
        auto t1 = p.take(s1, 5'000'000'000ull);
        CHECK(t1 && t1->first == 0 && t1->count == 4);
        for (uint32_t i = 0; i < 4; ++i)
            CHECK(t1->gens[i] == 2);  // second assignment generation
        // s1 delivers all four (resourced)
        for (uint32_t i = 0; i < 4; ++i) {
            uint8_t *dst = p.claim(0, i);
            CHECK(dst != nullptr);
            p.published(0, i, s1, t1->gens[i], 5'000'000'100ull);
        }
        CHECK(p.complete_ok());
        // the stuck s0 worker finally lands chunk 0 -> duplicate
        CHECK(p.claim(0, 0) == nullptr);
        p.duplicate(0, 0, s0, t0->gens[0]);
        auto st = p.stats();
        CHECK(st.chunks_resourced == 4 && st.chunks_dup == 1 &&
              st.chunks_fetched == 1);  // the dup arrival was gen-1
        CHECK(st.bytes_fetched + st.bytes_resourced - st.bytes_dup ==
              st.unique_bytes);
        CHECK(st.unique_bytes == 4096);
    }
    {
        // ghost assignments never park a chunk: expired straggler counts
        // must not keep a failed chunk invisible (kInflight) until the
        // straggler's far-future deadline — it is re-takeable the moment
        // the failure lands
        std::vector<uint8_t> da, db;
        auto ks = mk_keys(da, db);
        ks.pop_back();
        FetchPlan p(std::move(ks), 1024, 4.0, 1'000'000'000ull, 4, 0);
        uint32_t s0 = p.add_seeder("h:1"), s1 = p.add_seeder("h:2");
        p.add_key_seeder(0, s0);
        p.add_key_seeder(0, s1);
        auto t0 = p.take(s0, 0);
        CHECK(t0 && t0->count == 4);
        // staggered deadlines reach (i+1)*budget = up to 16 s here
        CHECK(p.expire_overdue(20'000'000'000ull) == 4);  // s0 straggling
        auto t1 = p.take(s1, 20'000'000'000ull);          // re-sourced to s1
        CHECK(t1 && t1->count == 4);
        for (uint32_t i = 0; i < 4; ++i) p.failed(0, i, s1);  // s1 fails them
        // s0's ghost assignment (inflight, deadline far out) must not
        // block the retry: the chunks are pending again right now
        auto t2 = p.take(s0, 20'000'000'100ull);
        CHECK(t2 && t2->count == 4);
        for (uint32_t i = 0; i < 4; ++i) {
            uint8_t *dst = p.claim(0, i);
            CHECK(dst);
            p.published(0, i, s0, t2->gens[i], 20'000'000'200ull);
        }
        CHECK(p.complete_ok());
    }
    {
        // precise invalidation: a seeder death re-sources ITS outstanding
        // chunks only — healthy inflight transfers keep their deadlines
        // (a plan-wide expiry would re-fetch everything and count it all
        // as duplicate traffic)
        std::vector<uint8_t> da, db;
        auto ks = mk_keys(da, db);
        ks.pop_back();
        FetchPlan p(std::move(ks), 1024, 4.0, 1'000'000'000ull, 2, 0);
        uint32_t s0 = p.add_seeder("h:1"), s1 = p.add_seeder("h:2");
        p.add_key_seeder(0, s0);
        p.add_key_seeder(0, s1);
        auto t0 = p.take(s0, 0);
        auto t1 = p.take(s1, 0);
        CHECK(t0 && t0->count == 2 && t1 && t1->count == 2);
        p.seeder_gone(s1);
        CHECK(p.expire_overdue(1) == 2);  // exactly s1's two chunks
    }
    {
        // seeder death: chunks fail over to the survivor; losing BOTH
        // fails the plan out (bounded, never wedges)
        std::vector<uint8_t> da, db;
        auto ks = mk_keys(da, db);
        ks.pop_back();
        FetchPlan p(std::move(ks), 1024, 4.0, 1'000'000, 4, 0);
        uint32_t s0 = p.add_seeder("h:1"), s1 = p.add_seeder("h:2");
        p.add_key_seeder(0, s0);
        p.add_key_seeder(0, s1);
        auto t0 = p.take(s0, 0);
        CHECK(t0 && t0->count == 4);
        for (uint32_t i = 0; i < 4; ++i) p.failed(0, i, s0);
        p.seeder_gone(s0);
        CHECK(!p.finished());
        CHECK(!p.take(s0, 10));  // dead seeders get nothing
        auto t1 = p.take(s1, 10);
        CHECK(t1 && t1->count == 4);
        for (uint32_t i = 0; i < 2; ++i) {
            uint8_t *dst = p.claim(0, i);
            CHECK(dst);
            p.published(0, i, s1, t1->gens[i], 20);
        }
        for (uint32_t i = 2; i < 4; ++i) p.failed(0, i, s1);
        p.seeder_gone(s1);
        CHECK(p.finished() && p.failed_out() && !p.complete_ok());
        CHECK(p.stats().seeders_lost == 2);
    }
    {
        // hash-mismatch failover: a corrupt seeder costs a re-source, an
        // honest one completes the plan (content addressing in action)
        std::vector<uint8_t> da, db;
        auto ks = mk_keys(da, db);
        ks.pop_back();
        FetchPlan p(std::move(ks), 1024, 4.0, 1'000'000, 1, 0);
        uint32_t bad = p.add_seeder("h:bad"), good = p.add_seeder("h:good");
        p.add_key_seeder(0, bad);
        p.add_key_seeder(0, good);
        size_t served_bad = 0, served_good = 0;
        while (!p.finished()) {
            if (auto t = p.take(bad, 0)) {
                p.failed(t->key, t->first, bad, /*hash_bad=*/true);
                ++served_bad;
            }
            if (auto t = p.take(good, 0)) {
                uint8_t *dst = p.claim(t->key, t->first);
                CHECK(dst);
                p.published(t->key, t->first, good, t->gens[0], 5);
                ++served_good;
            }
            CHECK(served_bad + served_good < 64);  // bounded
        }
        CHECK(p.complete_ok() && p.saw_hash_mismatch());
        CHECK(served_good == 4);
        CHECK(p.stats().hash_mismatches == served_bad);
    }
    {
        // retry-later backoff: a not-ready seeder is neither blacklisted
        // nor hammered; requeue leaves no tried mark
        std::vector<uint8_t> da, db;
        auto ks = mk_keys(da, db);
        ks.pop_back();
        FetchPlan p(std::move(ks), 1024, 4.0, 1'000'000, 4, 0);
        uint32_t s0 = p.add_seeder("h:1");
        p.add_key_seeder(0, s0);
        auto t0 = p.take(s0, 0);
        CHECK(t0 && t0->count == 4);
        for (uint32_t i = 0; i < 4; ++i) p.requeue(0, i, s0);
        p.seeder_backoff(s0, 1'000'000);
        CHECK(!p.take(s0, 500'000));        // parked during backoff
        auto t1 = p.take(s0, 2'000'000);    // and assignable after (no tried)
        CHECK(t1 && t1->count == 4);
        for (uint32_t i = 0; i < 4; ++i) {
            uint8_t *dst = p.claim(0, i);
            CHECK(dst);
            p.published(0, i, s0, t1->gens[i], 2'000'100);
        }
        CHECK(p.complete_ok());
    }
    {
        // a key with no viable source fails out via check_liveness
        // instead of spinning (empty seeder set = nobody can ever serve)
        std::vector<uint8_t> da, db;
        FetchPlan p(mk_keys(da, db), 1024, 4.0, 1'000'000, 4, 0);
        uint32_t s0 = p.add_seeder("h:1");
        p.add_key_seeder(0, s0);  // key "b" has NO seeders
        p.check_liveness();
        CHECK(p.finished() && p.failed_out());
    }
}

static void test_kernels() {
    float a[5] = {1, 2, 3, 4, 5}, b[5] = {10, 20, 30, 40, 50};
    kernels::accumulate(proto::DType::kF32, proto::RedOp::kSum, a, b, 5);
    CHECK(a[0] == 11 && a[4] == 55);
    kernels::finalize_avg(proto::DType::kF32, a, 5, 2);
    CHECK(a[0] == 5.5f);
    uint16_t h = kernels::f32_to_f16(1.5f);
    CHECK(kernels::f16_to_f32(h) == 1.5f);
    uint16_t bf = kernels::f32_to_bf16(1.5f);
    CHECK(kernels::bf16_to_f32(bf) == 1.5f);
    int32_t ia[3] = {3, 7, 9}, ib[3] = {5, 2, 9};
    kernels::accumulate(proto::DType::kI32, proto::RedOp::kMax, ia, ib, 3);
    CHECK(ia[0] == 5 && ia[1] == 7 && ia[2] == 9);

    // bf16 sum: the AVX2 fast path (when available) must be BIT-identical
    // to the scalar round-to-nearest-even reference across magnitudes,
    // signs, denormals, and an odd tail length
    {
        const size_t n = 1003;
        std::vector<uint16_t> va(n), vb(n), fast(n), slow(n);
        std::mt19937 rng{42};
        for (size_t i = 0; i < n; ++i) {
            va[i] = static_cast<uint16_t>(rng());
            vb[i] = static_cast<uint16_t>(rng());
            // avoid NaN/Inf encodings (exp all-ones): reductions over them
            // are not bit-stable across fused vs separate rounding anyway
            if ((va[i] & 0x7F80) == 0x7F80) va[i] &= 0x7F7F;
            if ((vb[i] & 0x7F80) == 0x7F80) vb[i] &= 0x7F7F;
        }
        for (size_t i = 0; i < n; ++i) {
            float s = kernels::bf16_to_f32(va[i]) + kernels::bf16_to_f32(vb[i]);
            slow[i] = kernels::f32_to_bf16(s);
        }
        fast = va;
        kernels::accumulate(proto::DType::kBF16, proto::RedOp::kSum, fast.data(),
                            vb.data(), n);
        CHECK(fast == slow);
        std::vector<uint16_t> out(n, 0);
        kernels::accumulate3(proto::DType::kBF16, proto::RedOp::kSum, out.data(),
                             va.data(), vb.data(), n);
        CHECK(out == slow);
    }
}

static void test_quant() {
    std::vector<float> x(1000);
    for (size_t i = 0; i < x.size(); ++i) x[i] = std::sin(i * 0.1f) * 5.0f;
    for (auto algo : {proto::QuantAlgo::kMinMax, proto::QuantAlgo::kZeroPointScale}) {
        auto qd = algo == proto::QuantAlgo::kMinMax ? proto::DType::kU8 : proto::DType::kI8;
        auto m = quant::compute_meta(algo, qd, proto::DType::kF32, x.data(), x.size());
        std::vector<uint8_t> q(quant::quantized_bytes(qd, x.size()));
        quant::quantize(m, x.data(), q.data(), x.size());
        std::vector<float> y(x.size());
        quant::dequantize_set(m, q.data(), y.data(), x.size());
        double max_err = 0;
        for (size_t i = 0; i < x.size(); ++i)
            max_err = std::max(max_err, std::abs(double(x[i]) - double(y[i])));
        CHECK(max_err < 10.0 / 255.0 + 1e-6); // range 10, 8-bit steps
        // meta roundtrip
        auto dec = quant::Meta::decode(m.encode());
        CHECK(dec && dec->lo == m.lo && dec->hi == m.hi);
        // requantize_self must be idempotent (bit parity invariant)
        std::vector<float> z = y;
        quant::requantize_self(m, z.data(), z.size());
        CHECK(memcmp(z.data(), y.data(), z.size() * 4) == 0);
    }
}

// bf16/f16 typed kernels: every 16-bit float value is exactly representable
// in f32, so the 16-bit path on values V must produce bit-identical
// quantized codes to the f32 path on widen(V) — same lanes, same arithmetic.
// Round-trips back to 16-bit must equal the f32 result narrowed.
static void test_quant_16bit_parity() {
    const size_t n = 4099; // odd: exercises the SIMD tail
    std::vector<uint16_t> hb(n), hf(n);
    std::vector<float> wb(n), wf(n);
    for (size_t i = 0; i < n; ++i) {
        float v = std::sin(i * 0.05f) * 3.0f + 0.25f;
        hb[i] = kernels::f32_to_bf16(v);
        hf[i] = kernels::f32_to_f16(v);
        wb[i] = kernels::bf16_to_f32(hb[i]);
        wf[i] = kernels::f16_to_f32(hf[i]);
    }
    struct Cfg {
        proto::DType src;
        const void *half;
        const float *wide;
    };
    for (auto algo : {proto::QuantAlgo::kMinMax, proto::QuantAlgo::kZeroPointScale}) {
        for (auto qd : {proto::DType::kU8, proto::DType::kU16, proto::DType::kI8}) {
            for (const Cfg &c : {Cfg{proto::DType::kBF16, hb.data(), wb.data()},
                                 Cfg{proto::DType::kF16, hf.data(), wf.data()}}) {
                auto mh = quant::compute_meta(algo, qd, c.src, c.half, n);
                auto mw = quant::compute_meta(algo, qd, proto::DType::kF32, c.wide, n);
                CHECK(mh.lo == mw.lo && mh.hi == mw.hi); // same min/max seen
                std::vector<uint8_t> qh(quant::quantized_bytes(qd, n));
                std::vector<uint8_t> qw(qh.size());
                quant::quantize(mh, c.half, qh.data(), n);
                quant::quantize(mw, c.wide, qw.data(), n);
                CHECK(qh == qw); // bit-identical codes
                // dequantize back to 16-bit == f32 dequant narrowed
                std::vector<uint16_t> back(n);
                std::vector<float> backw(n);
                quant::dequantize_set(mh, qh.data(), back.data(), n);
                quant::dequantize_set(mw, qw.data(), backw.data(), n);
                const bool bf16 = c.src == proto::DType::kBF16;
                for (size_t i = 0; i < n; ++i) {
                    uint16_t want = bf16 ? kernels::f32_to_bf16(backw[i])
                                         : kernels::f32_to_f16(backw[i]);
                    CHECK(back[i] == want);
                    if (back[i] != want) return; // don't spam 4k failures
                }
                // fused accumulate: acc = narrow(widen(acc0) + dq) per element
                std::vector<uint16_t> acc(n), acc0(n);
                for (size_t i = 0; i < n; ++i)
                    acc0[i] = acc[i] = bf16 ? kernels::f32_to_bf16(0.5f + i * 1e-4f)
                                            : kernels::f32_to_f16(0.5f + i * 1e-4f);
                quant::dequantize_accumulate(mh, proto::RedOp::kSum, qh.data(),
                                             acc.data(), n);
                for (size_t i = 0; i < n; ++i) {
                    float a = bf16 ? kernels::bf16_to_f32(acc0[i])
                                   : kernels::f16_to_f32(acc0[i]);
                    float d = backw[i];
                    uint16_t want = bf16 ? kernels::f32_to_bf16(a + d)
                                         : kernels::f32_to_f16(a + d);
                    CHECK(acc[i] == want);
                    if (acc[i] != want) return;
                }
            }
        }
    }
}

static void test_journal() {
    const char *path = "/tmp/pcclt_selftest_journal.bin";
    remove(path);
    proto::Uuid u1 = proto::uuid_random(), u2 = proto::uuid_random();
    {
        journal::Journal j;
        CHECK(j.open(path));
        CHECK(j.epoch() == 1);
        CHECK(!j.restored().any);
        j.record_client({u1, 0, "127.0.0.1", 1001, 1002, 1003, true});
        j.record_client({u2, 1, "10.0.0.2", 2001, 2002, 2003, false});
        j.record_group(0, 7, true);
        j.record_ring(0, {u1, u2});
        j.record_topology_revision(5);
        j.record_seq_bound(4096);
        j.record_bandwidth(u1, u2, 123.5);
        // a removed client must not resurrect on replay
        proto::Uuid u3 = proto::uuid_random();
        j.record_client({u3, 0, "127.0.0.3", 1, 2, 3, true});
        j.record_client_remove(u3);
    }
    {
        // snapshot + deltas -> rehydrate -> identical state, bumped epoch
        journal::Journal j;
        CHECK(j.open(path));
        CHECK(j.epoch() == 2);
        const auto &r = j.restored();
        CHECK(r.any);
        CHECK(r.clients.size() == 2);
        CHECK(r.clients.count(u1) && r.clients.count(u2));
        const auto &c1 = r.clients.at(u1);
        CHECK(c1.peer_group == 0 && c1.ip == "127.0.0.1" && c1.p2p_port == 1001 &&
              c1.ss_port == 1002 && c1.bench_port == 1003 && c1.accepted);
        CHECK(!r.clients.at(u2).accepted && r.clients.at(u2).peer_group == 1);
        CHECK(r.topology_revision == 5);
        CHECK(r.next_seq == 4096);
        CHECK(r.groups.at(0).last_revision == 7 &&
              r.groups.at(0).revision_initialized);
        CHECK(r.groups.at(0).ring == (std::vector<proto::Uuid>{u1, u2}));
        CHECK(r.bandwidth.size() == 1 && r.bandwidth[0].from == u1 &&
              r.bandwidth[0].to == u2 && r.bandwidth[0].mbps == 123.5);
    }
    {
        // torn tail (crash mid-append): replay stops clean at the valid prefix
        FILE *f = fopen(path, "ab");
        CHECK(f != nullptr);
        uint8_t torn[7] = {0, 0, 0, 50, 2, 1, 2}; // claims 50 bytes, has 2
        fwrite(torn, 1, sizeof torn, f);
        fclose(f);
        journal::Journal j;
        CHECK(j.open(path));
        CHECK(j.epoch() == 3);
        CHECK(j.restored().clients.size() == 2);
    }
    remove(path);
}

// Master HA at the state-machine level: run a 2-client world against a
// journaled MasterState, drop it (simulated SIGKILL), rehydrate a fresh
// MasterState from the same journal, and resume both sessions — same
// UUIDs, preserved ring + revision, frozen rounds while a session is
// still in limbo, bumped epoch.
static void test_master_ha_state() {
    const char *path = "/tmp/pcclt_selftest_ha_journal.bin";
    remove(path);
    using master::Outbox;
    auto find = [](const std::vector<Outbox> &out, uint64_t conn,
                   uint16_t type) -> const Outbox * {
        for (const auto &o : out)
            if (o.conn_id == conn && o.type == type) return &o;
        return nullptr;
    };
    auto uuid_of_welcome = [](const Outbox &o) {
        wire::Reader r(o.payload);
        CHECK(r.u8() == 1);
        return proto::get_uuid(r);
    };
    net::Addr ip = *net::Addr::parse("127.0.0.1", 0);
    proto::Uuid ua{}, ub{};
    {
        journal::Journal j;
        CHECK(j.open(path));
        master::MasterState st;
        st.attach_journal(&j);
        CHECK(st.epoch() == 1);
        proto::HelloC2M h;
        h.p2p_port = 100;
        h.ss_port = 101;
        h.bench_port = 102;
        auto out = st.on_hello(1, ip, h); // empty world: admitted immediately
        auto *w = find(out, 1, proto::kM2CWelcome);
        CHECK(w != nullptr);
        ua = uuid_of_welcome(*w);
        {
            // welcome carries the epoch after the uuid + banner string
            wire::Reader r(w->payload);
            r.u8();
            proto::get_uuid(r);
            r.str();
            CHECK(r.u64() == 1);
        }
        out = st.on_p2p_established(1, 1, true, {});
        CHECK(find(out, 1, proto::kM2CP2PEstablishedResp) != nullptr);
        h.p2p_port = 200;
        out = st.on_hello(2, ip, h);
        ub = uuid_of_welcome(*find(out, 2, proto::kM2CWelcome));
        out = st.on_topology_update(1); // incumbent vote admits the joiner
        CHECK(find(out, 1, proto::kM2CP2PConnInfo) != nullptr);
        CHECK(find(out, 2, proto::kM2CP2PConnInfo) != nullptr);
        out = st.on_p2p_established(1, 2, true, {});
        auto out2 = st.on_p2p_established(2, 2, true, {});
        CHECK(find(out2, 1, proto::kM2CP2PEstablishedResp) != nullptr);
        // one shared-state round at revision 3 (fresh master: any bootstraps)
        proto::SharedStateSyncC2M sync;
        sync.revision = 3;
        st.on_shared_state_sync(1, sync);
        out = st.on_shared_state_sync(2, sync);
        CHECK(find(out, 1, proto::kM2CSharedStateSyncResp) != nullptr);
        st.on_dist_done(1);
        out = st.on_dist_done(2);
        CHECK(find(out, 2, proto::kM2CSharedStateDone) != nullptr);
        // MasterState dropped here without disconnects = simulated crash
    }
    {
        journal::Journal j;
        CHECK(j.open(path));
        master::MasterState st;
        st.attach_journal(&j);
        CHECK(st.epoch() == 2);
        CHECK(st.limbo_count() == 2);
        // session resume under the OLD uuids on fresh conns
        proto::SessionResumeC2M ra;
        ra.uuid = ua;
        ra.last_revision = 3;
        auto out = st.on_session_resume(11, ip, ra);
        auto *ack = find(out, 11, proto::kM2CSessionResumeAck);
        CHECK(ack != nullptr);
        auto dec = proto::SessionResumeAck::decode(ack->payload);
        CHECK(dec && dec->ok == 1 && dec->epoch == 2 && dec->last_revision == 3);
        CHECK(st.limbo_count() == 1);
        // rounds stay FROZEN while b is still in limbo: a's collective
        // init must not commence a 1-member op
        proto::CollectiveInit ci;
        ci.tag = 9;
        ci.count = 16;
        out = st.on_collective_init(11, ci);
        CHECK(find(out, 11, proto::kM2CCollectiveCommence) == nullptr);
        proto::SessionResumeC2M rb;
        rb.uuid = ub;
        rb.last_revision = 3;
        out = st.on_session_resume(12, ip, rb);
        CHECK(st.limbo_count() == 0);
        CHECK(st.world_size() == 2); // zero re-registrations
        out = st.on_collective_init(12, ci);
        CHECK(find(out, 11, proto::kM2CCollectiveCommence) != nullptr);
        CHECK(find(out, 12, proto::kM2CCollectiveCommence) != nullptr);
        // an unknown uuid is rejected (no journaled session)
        proto::SessionResumeC2M rx;
        rx.uuid = proto::uuid_random();
        out = st.on_session_resume(13, ip, rx);
        auto rej = proto::SessionResumeAck::decode(
            find(out, 13, proto::kM2CSessionResumeAck)->payload);
        CHECK(rej && rej->ok == 0);
        // revision continuity: the next sync must expect revision 4
        proto::SharedStateSyncC2M stale;
        stale.revision = 9; // > last+1: increment violation -> kick
        out = st.on_shared_state_sync(11, stale);
        CHECK(find(out, 11, proto::kM2CKicked) != nullptr);
    }
    remove(path);
}

// Regression for the pcclt-verify model-checker finding (scenario
// restart_resume): a collective completes, the master dies AFTER one
// member's Done was delivered but BEFORE the other's — the straggler's
// retry must get the journaled verdict REPLAYED (no ghost op that its
// moved-on peer would never join).
static void test_op_done_replay() {
    const char *path = "/tmp/pcclt_selftest_opdone_journal.bin";
    remove(path);
    using master::Outbox;
    auto find = [](const std::vector<Outbox> &out, uint64_t conn,
                   uint16_t type) -> const Outbox * {
        for (const auto &o : out)
            if (o.conn_id == conn && o.type == type) return &o;
        return nullptr;
    };
    net::Addr ip = *net::Addr::parse("127.0.0.1", 0);
    proto::Uuid ua{}, ub{};
    proto::CollectiveInit ci;
    ci.tag = 5;
    ci.count = 8;
    {
        journal::Journal j;
        CHECK(j.open(path));
        master::MasterState st;
        st.attach_journal(&j);
        proto::HelloC2M h;
        h.p2p_port = 100;
        auto out = st.on_hello(1, ip, h);
        {
            wire::Reader r(find(out, 1, proto::kM2CWelcome)->payload);
            CHECK(r.u8() == 1);
            ua = proto::get_uuid(r);
        }
        st.on_p2p_established(1, 1, true, {});
        h.p2p_port = 200;
        out = st.on_hello(2, ip, h);
        {
            wire::Reader r(find(out, 2, proto::kM2CWelcome)->payload);
            CHECK(r.u8() == 1);
            ub = proto::get_uuid(r);
        }
        out = st.on_topology_update(1);
        st.on_p2p_established(1, 2, true, {});
        st.on_p2p_established(2, 2, true, {});
        // run tag 5 to full completion: both Dones emitted (and the
        // completion journaled write-ahead), then "crash"
        st.on_collective_init(1, ci);
        out = st.on_collective_init(2, ci);
        CHECK(find(out, 1, proto::kM2CCollectiveCommence) != nullptr);
        st.on_collective_complete(1, 5, false);
        out = st.on_collective_complete(2, 5, false);
        CHECK(find(out, 1, proto::kM2CCollectiveDone) != nullptr);
        CHECK(find(out, 2, proto::kM2CCollectiveDone) != nullptr);
    }
    {
        journal::Journal j;
        CHECK(j.open(path));
        CHECK(j.restored().op_done.size() == 1);
        master::MasterState st;
        st.attach_journal(&j);
        // client a resumes and RETRIES tag 5 (its Done was "lost"; the
        // client library flags the re-init as a retry): the verdict is
        // replayed — abort(0) + done, and crucially NO commence
        proto::SessionResumeC2M ra;
        ra.uuid = ua;
        auto out = st.on_session_resume(11, ip, ra);
        proto::CollectiveInit retry = ci;
        retry.retry = 1;
        retry.retry_seq = 1; // the seq the dead attempt saw at commence
        out = st.on_collective_init(11, retry);
        auto *ab = find(out, 11, proto::kM2CCollectiveAbort);
        CHECK(ab != nullptr);
        {
            wire::Reader r(ab->payload);
            CHECK(r.u64() == 5);
            CHECK(r.u8() == 0);  // verdict: completed clean
            CHECK(r.u32() == 2); // trailing op world (replayed verdicts only)
        }
        CHECK(find(out, 11, proto::kM2CCollectiveDone) != nullptr);
        CHECK(find(out, 11, proto::kM2CCollectiveCommence) == nullptr);
        // a FRESH (unflagged) init of the same tag is a genuinely new op —
        // the replay gate must NOT answer it with the stale verdict (tags
        // are app-reused per step); no commence while b is still in limbo
        out = st.on_collective_init(11, ci);
        CHECK(find(out, 11, proto::kM2CCollectiveAbort) == nullptr);
        CHECK(find(out, 11, proto::kM2CCollectiveCommence) == nullptr);
        // b resumes; a retry of a DIFFERENT incarnation (mismatched seq —
        // here 0, the attempt died pre-commence, so the recorded
        // completion cannot be its op) must NOT get the stale verdict:
        // b's owed entry is consumed and the init joins a's fresh op
        // normally — commence for both, with a seq ABOVE everything the
        // previous incarnation issued
        proto::SessionResumeC2M rb;
        rb.uuid = ub;
        st.on_session_resume(12, ip, rb);
        proto::CollectiveInit wrong = ci;
        wrong.retry = 1;
        wrong.retry_seq = 0;
        out = st.on_collective_init(12, wrong);
        CHECK(find(out, 12, proto::kM2CCollectiveAbort) == nullptr);
        auto *cm = find(out, 11, proto::kM2CCollectiveCommence);
        CHECK(cm != nullptr);
        CHECK(find(out, 12, proto::kM2CCollectiveCommence) != nullptr);
        wire::Reader r(cm->payload);
        CHECK(r.u64() == 5);
        CHECK(r.u64() >= 2); // seq resumed above the journaled bound
    }
    remove(path);
}

static void test_atsp() {
    // 4-node asymmetric instance with a known-best ring 0->1->2->3->0
    const double INF = 100;
    std::vector<double> c = {
        0, 1, INF, INF,
        INF, 0, 1, INF,
        INF, INF, 0, 1,
        1, INF, INF, 0,
    };
    auto tour = atsp::solve(c, 4, 100);
    CHECK(atsp::tour_cost(c, 4, tour) == 4.0);
    // heuristic path (n > 12)
    size_t n = 15;
    std::vector<double> big(n * n);
    for (size_t i = 0; i < n; ++i)
        for (size_t j = 0; j < n; ++j)
            big[i * n + j] = i == j ? 0.0 : 1.0 + ((i * 7 + j * 13) % 10);
    auto t2 = atsp::solve(big, n, 200);
    std::vector<bool> seen(n, false);
    for (int v : t2) seen[v] = true;
    for (size_t i = 0; i < n; ++i) CHECK(seen[i]);

    // reachability-aware Hamiltonian: edges >= limit are unusable. Ring
    // 0->2->1->3->0 is the only cycle under the limit.
    const double X = 1e9;
    std::vector<double> h = {
        0, X, 1, X,
        X, 0, X, 1,
        X, 1, 0, X,
        1, X, X, 0,
    };
    auto ht = atsp::hamiltonian(h, 4, 5e5, 100);
    CHECK(ht.size() == 4);
    CHECK(atsp::tour_cost(h, 4, ht) == 4.0);
    // no cycle exists when an edge of the unique ring is removed
    h[0 * 4 + 2] = X;
    CHECK(atsp::hamiltonian(h, 4, 5e5, 100).empty());
}

// Schedule synthesizer planner (schedule.hpp, docs/12): the alpha-beta
// model must rank algorithms the way the physics does, and every step
// program the planner can emit must conserve bytes across ranks.
static void test_schedule_planner() {
    using namespace sched;
    // choose() honors the kill switch / force overrides; the planner units
    // pin a known env so a PCCLT_SCHEDULE=0 selftest run (the forced-off
    // acceptance leg) still exercises the cost model deterministically
    const char *env_sched = std::getenv("PCCLT_SCHEDULE");
    const char *env_force = std::getenv("PCCLT_SCHEDULE_FORCE");
    std::string saved_sched = env_sched ? env_sched : "";
    std::string saved_force = env_force ? env_force : "";
    setenv("PCCLT_SCHEDULE", "1", 1);
    unsetenv("PCCLT_SCHEDULE_FORCE");
    const std::vector<uint32_t> ring4{0, 1, 2, 3};
    const uint64_t big = 64ull << 20, tiny = 1024;

    // uniform matrix: a large all-reduce is bandwidth-bound -> ring's
    // 2(n-1)/n byte factor beats the tree's root-serialized fan-in/out
    CostModel uni;
    uni.n = 4;
    uni.mbps.assign(16, 100.0);
    CHECK(uni.cost(Coll::kAllReduce, Algo::kRing, ring4, 0, double(big)) <
          uni.cost(Coll::kAllReduce, Algo::kTree, ring4, 0, double(big)));
    CHECK(choose(uni, Coll::kAllReduce, ring4, big).algo == Algo::kRing);
    // ...but a tiny all-reduce is alpha-bound: butterfly's log2(n) rounds
    // beat the ring's 2(n-1) sequential steps on a power-of-two world
    CHECK(choose(uni, Coll::kAllReduce, ring4, tiny).algo ==
          Algo::kButterfly);

    // hub-and-spoke: node 0 has fat links, spoke<->spoke crawls. The ring
    // must cross slow spoke edges; a hub-rooted tree never does.
    CostModel hub;
    hub.n = 4;
    hub.mbps.assign(16, 10.0);
    for (uint32_t i = 1; i < 4; ++i) {
        hub.mbps[0 * 4 + i] = 1000.0;
        hub.mbps[i * 4 + 0] = 1000.0;
    }
    CHECK(hub.cost(Coll::kBroadcast, Algo::kTree, ring4, 0, double(big)) <
          hub.cost(Coll::kBroadcast, Algo::kRing, ring4, 0, double(big)));
    CHECK(choose(hub, Coll::kBroadcast, ring4, big).algo == Algo::kTree);

    // one rotten ring edge with healthy detours -> relay ring wins the
    // all-reduce (world 6 keeps butterfly out of the candidate set)
    CostModel rot;
    rot.n = 6;
    rot.mbps.assign(36, 100.0);
    rot.mbps[1 * 6 + 2] = 1.0;
    std::vector<uint32_t> ring6{0, 1, 2, 3, 4, 5};
    auto rc = choose(rot, Coll::kAllReduce, ring6, big);
    CHECK(rc.algo == Algo::kRelayRing);
    CHECK(rc.root == 1);  // the detouring sender is the bottleneck's tail
    CHECK(rc.cost < rot.cost(Coll::kAllReduce, Algo::kRing, ring6, 0,
                             double(big)));

    // synthesize(): one entry per (coll, size-class), all executable, and
    // the table survives its wire round-trip bit-for-bit
    Table t = synthesize(hub, ring4, 7);
    CHECK(t.version == 7);
    CHECK(t.entries.size() == size_t(kNumColls) * kNumSizeClasses);
    for (const auto &e : t.entries)
        CHECK(algo_valid(static_cast<Coll>(e.coll),
                         static_cast<Algo>(e.algo), 4));
    auto rt = Table::decode(t.encode());
    CHECK(rt && rt->version == t.version &&
          rt->entries.size() == t.entries.size());
    const Entry *fe = t.find(Coll::kBroadcast, 2);
    CHECK(fe && fe->algo == static_cast<uint8_t>(Algo::kTree));

    // default size-class thresholds (docs/03)
    CHECK(size_class(4 * 1024) == 0);
    CHECK(size_class(1ull << 20) == 1);
    CHECK(size_class(32ull << 20) == 2);

    // byte conservation: every (coll, algo, world) the interpreter may be
    // asked to run, including odd worlds and non-divisible payloads —
    // every sent range must pair with exactly one matching receive
    for (uint32_t n : {2u, 3u, 4u, 5u, 8u}) {
        for (uint8_t ci = 0; ci < kNumColls; ++ci) {
            auto c = static_cast<Coll>(ci);
            for (Algo a : {Algo::kRing, Algo::kTree, Algo::kButterfly,
                           Algo::kMesh, Algo::kRelayRing}) {
                if (!algo_valid(c, a, n)) continue;
                for (uint64_t bytes : {uint64_t(64), uint64_t(4099),
                                       uint64_t(1) << 20}) {
                    const uint32_t root =
                        (c == Coll::kBroadcast || a == Algo::kRelayRing)
                            ? (n - 1) % n : 0;
                    std::string err;
                    if (!conserve(c, a, n, root, bytes, &err)) {
                        fprintf(stderr,
                                "conserve %s/%s n=%u b=%llu: %s\n",
                                coll_name(c), algo_name(a), n,
                                (unsigned long long)bytes, err.c_str());
                        CHECK(false);
                    }
                }
            }
        }
    }
    if (env_sched) setenv("PCCLT_SCHEDULE", saved_sched.c_str(), 1);
    else unsetenv("PCCLT_SCHEDULE");
    if (env_force) setenv("PCCLT_SCHEDULE_FORCE", saved_force.c_str(), 1);
}

// PCCLT_WIRE_CHAOS_MAP must arm on FIRST USE of an edge, not at env-parse
// time: synthesized tree/butterfly/mesh schedules dial edges the ring
// never touched, and a chaos plane that armed only already-resolved
// neighbors would silently exempt exactly the paths the synthesizer adds
// (docs/12). Registry::resolve() owns that guarantee — pin it.
static void test_chaos_late_arm() {
    using namespace net::netem;
    setenv("PCCLT_WIRE_CHAOS_MAP", "127.0.0.1:45611=blackhole@t=9s:10ms", 1);
    Registry::inst().refresh();
    auto st0 = chaos_stats();
    // an unrelated endpoint resolving must not arm the mapped schedule
    auto other = net::Addr::parse("127.0.0.1", 45613);
    CHECK(other.has_value());
    (void)Registry::inst().resolve(*other);
    CHECK(chaos_stats().armed == st0.armed);
    // the first (arbitrarily late) resolve of the mapped endpoint arms it
    auto a = net::Addr::parse("127.0.0.1", 45611);
    CHECK(a.has_value());
    auto e1 = Registry::inst().resolve(*a);
    CHECK(e1 != nullptr);
    CHECK(chaos_stats().armed == st0.armed + 1);
    CHECK(e1->pace_enabled());  // armed chaos counts as emulation
    // refresh + re-resolve keep the SAME edge and never re-arm: a mid-run
    // env re-read must not restart a timeline peers already live through
    Registry::inst().refresh();
    auto e2 = Registry::inst().resolve(*a);
    CHECK(e2.get() == e1.get());
    CHECK(chaos_stats().armed == st0.armed + 1);
    unsetenv("PCCLT_WIRE_CHAOS_MAP");
    Registry::inst().refresh();
}

// ---- end-to-end: master + N clients, fp32 ring allreduce + shared state ----

// Port base below the kernel ephemeral range (32768-60999): an in-range
// listener can lose its port to any stray outbound socket between binds
// (same rationale as tests/conftest.py's allocator). The Python suite
// allocates upward from 20000; this binary starts at 28000 to coexist.
static uint16_t alloc_test_ports(uint16_t span) {
    static uint16_t next = 28000;
    uint16_t p = next;
    next += span;
    return p;
}

// shared e2e plumbing: configured client + join-the-world wait
static client::ClientConfig peer_cfg(uint16_t master_port, uint16_t base, size_t r) {
    client::ClientConfig cfg;
    cfg.master = *net::Addr::parse("127.0.0.1", master_port);
    cfg.p2p_port = static_cast<uint16_t>(base + r * 24);
    cfg.ss_port = static_cast<uint16_t>(base + r * 24 + 8);
    cfg.bench_port = static_cast<uint16_t>(base + r * 24 + 16);
    return cfg;
}

static bool wait_world(client::Client &cl, size_t world) {
    auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(60);
    while (cl.group_world() < world) {
        if (std::chrono::steady_clock::now() > deadline) return false;
        bool pending = false;
        cl.are_peers_pending(pending);
        if (pending) cl.update_topology();
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
    return true;
}

static void test_e2e(size_t world, proto::QuantAlgo quant) {
    uint16_t port = alloc_test_ports(512);
    master::Master mm(port);
    CHECK(mm.launch());
    uint16_t base = static_cast<uint16_t>(port + 16);
    port = mm.port();

    const size_t count = 4099; // deliberately not divisible by world
    std::vector<std::thread> threads;
    std::atomic<int> ok_count{0};

    for (size_t r = 0; r < world; ++r) {
        threads.emplace_back([&, r] {
            client::Client cl(peer_cfg(port, base, r));
            if (cl.connect() != client::Status::kOk) {
                fprintf(stderr, "peer %zu: connect failed\n", r);
                return;
            }
            // wait for all peers to join (reference establishConnections helper)
            if (!wait_world(cl, world)) return;

            std::vector<float> x(count), y(count, 0.0f);
            for (size_t i = 0; i < count; ++i)
                x[i] = static_cast<float>(i % 97) + static_cast<float>(r);
            client::ReduceDesc desc;
            desc.tag = 1;
            desc.op = proto::RedOp::kSum;
            desc.quant = quant;
            desc.quant_dtype = quant == proto::QuantAlgo::kZeroPointScale
                                   ? proto::DType::kI8
                                   : proto::DType::kU8;
            client::ReduceInfo info;
            auto st = cl.all_reduce(x.data(), y.data(), count, proto::DType::kF32, desc,
                                    &info);
            if (st != client::Status::kOk) {
                fprintf(stderr, "peer %zu: allreduce failed st=%d\n", r, int(st));
                return;
            }
            bool correct = true;
            double tol = quant == proto::QuantAlgo::kNone ? 1e-4 : 1.5 * world;
            for (size_t i = 0; i < count; ++i) {
                double expect = world * double(i % 97) + world * (world - 1) / 2.0;
                if (std::abs(double(y[i]) - expect) > tol) {
                    if (correct)
                        fprintf(stderr, "peer %zu: y[%zu]=%f expect %f\n", r, i, y[i],
                                expect);
                    correct = false;
                }
            }
            if (!correct) return;

            // shared state: rank 0 has the canonical content, others fetch
            std::vector<float> state(1024, r == 0 ? 42.0f : 0.0f);
            uint64_t marker = r == 0 ? 7 : 0;
            std::vector<uint64_t> step{marker};
            client::SharedStateEntry e1{"weights", proto::DType::kF32, state.size(),
                                        state.data(), false};
            client::SharedStateEntry e2{"step", proto::DType::kU64, 1, step.data(), false};
            client::SyncInfo si;
            // strategy: rank0 sends, others receive-or-enforce
            auto strat = r == 0 ? proto::SyncStrategy::kTxOnly
                                : proto::SyncStrategy::kRxOnly;
            auto sst = cl.sync_shared_state(1, strat, {e1, e2}, &si);
            if (sst != client::Status::kOk) {
                fprintf(stderr, "peer %zu: shared state failed st=%d\n", r, int(sst));
                return;
            }
            if (state[0] != 42.0f || step[0] != 7) {
                fprintf(stderr, "peer %zu: shared state content wrong (%f, %llu)\n", r,
                        state[0], (unsigned long long)step[0]);
                return;
            }
            ok_count.fetch_add(1);
            cl.disconnect();
        });
    }
    for (auto &t : threads) t.join();
    CHECK(ok_count.load() == static_cast<int>(world));
    mm.interrupt();
    mm.join();
}

// half-precision e2e: f16/bf16 buffers sum exactly for small integers, so
// bit-exact verification works without tolerances
static void test_e2e_halfprec(size_t world, proto::DType dtype) {
    uint16_t port = alloc_test_ports(512);
    master::Master mm(port);
    CHECK(mm.launch());
    uint16_t base = static_cast<uint16_t>(port + 16);
    port = mm.port();

    const size_t count = 2053;
    std::vector<std::thread> threads;
    std::atomic<int> ok_count{0};
    for (size_t r = 0; r < world; ++r) {
        threads.emplace_back([&, r] {
            client::Client cl(peer_cfg(port, base, r));
            if (cl.connect() != client::Status::kOk) return;
            if (!wait_world(cl, world)) return;
            std::vector<uint16_t> x(count), y(count, 0);
            for (size_t i = 0; i < count; ++i) {
                float v = static_cast<float>(i % 97) + static_cast<float>(r);
                x[i] = dtype == proto::DType::kF16 ? kernels::f32_to_f16(v)
                                                   : kernels::f32_to_bf16(v);
            }
            client::ReduceDesc desc;
            desc.tag = 1;
            desc.op = proto::RedOp::kSum;
            client::ReduceInfo info;
            auto st = cl.all_reduce(x.data(), y.data(), count, dtype, desc, &info);
            if (st != client::Status::kOk) {
                fprintf(stderr, "half peer %zu: allreduce failed st=%d\n", r, int(st));
                return;
            }
            bool correct = true;
            for (size_t i = 0; i < count; ++i) {
                float got = dtype == proto::DType::kF16 ? kernels::f16_to_f32(y[i])
                                                        : kernels::bf16_to_f32(y[i]);
                float expect = world * float(i % 97) + world * (world - 1) / 2.0f;
                if (got != expect) { // exact: small integers survive half precision
                    if (correct)
                        fprintf(stderr, "half peer %zu: y[%zu]=%f expect %f\n", r, i,
                                got, expect);
                    correct = false;
                }
            }
            if (correct) ok_count.fetch_add(1);
            cl.disconnect();
        });
    }
    for (auto &t : threads) t.join();
    CHECK(ok_count.load() == static_cast<int>(world));
    mm.interrupt();
    mm.join();
}

// concurrent tags: several async reduces in flight per peer at once,
// exercising the op worker pool and per-tag demux under contention
static void test_e2e_concurrent_tags(size_t world, size_t ntags) {
    uint16_t port = alloc_test_ports(512);
    master::Master mm(port);
    CHECK(mm.launch());
    uint16_t base = static_cast<uint16_t>(port + 16);
    port = mm.port();

    const size_t count = 65537;
    std::vector<std::thread> threads;
    std::atomic<int> ok_count{0};
    for (size_t r = 0; r < world; ++r) {
        threads.emplace_back([&, r] {
            client::Client cl(peer_cfg(port, base, r));
            if (cl.connect() != client::Status::kOk) return;
            if (!wait_world(cl, world)) return;
            std::vector<std::vector<float>> xs(ntags), ys(ntags);
            for (size_t t = 0; t < ntags; ++t) {
                xs[t].resize(count);
                ys[t].assign(count, 0.0f);
                for (size_t i = 0; i < count; ++i)
                    xs[t][i] = static_cast<float>((i + t) % 89) + static_cast<float>(r);
            }
            for (size_t t = 0; t < ntags; ++t) {
                client::ReduceDesc desc;
                desc.tag = 100 + t;
                desc.op = proto::RedOp::kSum;
                auto st = cl.all_reduce_async(xs[t].data(), ys[t].data(), count,
                                              proto::DType::kF32, desc);
                if (st != client::Status::kOk) {
                    fprintf(stderr, "peer %zu tag %zu: launch failed st=%d\n", r, t,
                            int(st));
                    return;
                }
            }
            bool correct = true;
            for (size_t t = 0; t < ntags; ++t) {
                client::ReduceInfo info;
                auto st = cl.await_reduce(100 + t, &info);
                if (st != client::Status::kOk) {
                    fprintf(stderr, "peer %zu tag %zu: await failed st=%d\n", r, t,
                            int(st));
                    return;
                }
                for (size_t i = 0; i < count && correct; ++i) {
                    double expect =
                        world * double((i + t) % 89) + world * (world - 1) / 2.0;
                    if (std::abs(double(ys[t][i]) - expect) > 1e-4) {
                        fprintf(stderr, "peer %zu tag %zu: y[%zu]=%f expect %f\n", r, t,
                                i, ys[t][i], expect);
                        correct = false;
                    }
                }
            }
            if (correct) ok_count.fetch_add(1);
            cl.disconnect();
        });
    }
    for (auto &t : threads) t.join();
    CHECK(ok_count.load() == static_cast<int>(world));
    mm.interrupt();
    mm.join();
}

// abort mid-ring: one peer launches the collective then abruptly disconnects;
// the survivors must see a failed op, recover via update_topology, retry, and
// get a correct world-2 result (reference: SIGKILL churn e2e, done in-process)
// Pipelined WAN data plane forced onto an in-process world (fallback
// matrix, docs/08): PCCLT_CMA=0 turns every edge into a real TCP stream —
// the windowed pipeline's gate — and a tiny window floor makes even the
// selftest payload split into in-flight windows, so per-window quantize→
// send and the cross-stage send-ahead actually run. The same worlds then
// re-run with the pipeline forced OFF; results must be identical either
// way (the e2e checks are exact). PCCLT_URING is inherited from the
// environment: CI runs this binary once with it forced on and once forced
// off, covering the uring→poll rungs of the ladder too.
static void test_e2e_pipelined() {
    setenv("PCCLT_CMA", "0", 1);
    setenv("PCCLT_PIPELINE", "1", 1);
    setenv("PCCLT_PIPELINE_MIN_BYTES", "256", 1);
    test_e2e(3, proto::QuantAlgo::kNone);
    test_e2e(3, proto::QuantAlgo::kZeroPointScale);
    setenv("PCCLT_PIPELINE", "0", 1); // forced-off rung, still CMA-less
    test_e2e(2, proto::QuantAlgo::kNone);
    unsetenv("PCCLT_PIPELINE");
    unsetenv("PCCLT_PIPELINE_MIN_BYTES");
    unsetenv("PCCLT_CMA");
}

// Multipath striping matrix (docs/08): stripes x {uring on/off} x
// {fp32, zps} x {qwin off/on} over the CMA-less pipelined plane.
// test_e2e verifies the reduction element-wise (fp32 small-int sums are
// exact — any cross-stripe reassembly error shows up as a wrong element,
// not a tolerance miss) and the shared-state sync after it proves the
// control plane survived. PCCLT_STRIPE_CONNS alone grows the client
// pools (Client::pool_width), so no API plumbing is needed here.
static void test_e2e_striped() {
    setenv("PCCLT_CMA", "0", 1);
    setenv("PCCLT_PIPELINE", "1", 1);
    setenv("PCCLT_PIPELINE_MIN_BYTES", "256", 1);
    setenv("PCCLT_STRIPE_CONNS", "2", 1);
    test_e2e(3, proto::QuantAlgo::kNone);
    test_e2e(3, proto::QuantAlgo::kZeroPointScale);
    setenv("PCCLT_URING", "0", 1);  // poll-loop rung under striping
    test_e2e(2, proto::QuantAlgo::kNone);
    unsetenv("PCCLT_URING");
    // per-window quantization meta + quantized cross-stage send-ahead
    setenv("PCCLT_QWIN_META", "1", 1);
    test_e2e(3, proto::QuantAlgo::kZeroPointScale);
    if (!fast_mode()) {
        setenv("PCCLT_STRIPE_CONNS", "4", 1);
        test_e2e(4, proto::QuantAlgo::kNone);
        test_e2e(3, proto::QuantAlgo::kZeroPointScale);
        // qwin without striping: the send-ahead path alone
        setenv("PCCLT_STRIPE_CONNS", "1", 1);
        test_e2e(3, proto::QuantAlgo::kMinMax);
    }
    unsetenv("PCCLT_QWIN_META");
    unsetenv("PCCLT_STRIPE_CONNS");
    unsetenv("PCCLT_PIPELINE");
    unsetenv("PCCLT_PIPELINE_MIN_BYTES");
    unsetenv("PCCLT_CMA");
}

static void test_e2e_abort_mid_ring() {
    uint16_t port = alloc_test_ports(512);
    master::Master mm(port);
    CHECK(mm.launch());
    uint16_t base = static_cast<uint16_t>(port + 16);
    port = mm.port();

    const size_t world = 3;
    // 16 MB fp32: long enough to abort mid-op (1 MB under the fast/tsan
    // mode, where instrumented streaming is ~20x slower)
    const size_t count = fast_mode() ? (256u << 10) : (4u << 20);
    std::vector<std::thread> threads;
    std::atomic<int> ok_count{0};
    for (size_t r = 0; r < world; ++r) {
        threads.emplace_back([&, r] {
            client::Client cl(peer_cfg(port, base, r));
            if (cl.connect() != client::Status::kOk) return;
            if (!wait_world(cl, world)) return;
            std::vector<float> x(count), y(count, 0.0f);
            for (size_t i = 0; i < count; ++i)
                x[i] = static_cast<float>(i % 97) + static_cast<float>(r);
            client::ReduceDesc desc;
            desc.tag = 5;
            desc.op = proto::RedOp::kSum;

            if (r == 2) {
                // deserter: launch, let the ring get going, vanish without
                // goodbye semantics beyond the TCP closes in disconnect()
                (void)cl.all_reduce_async(x.data(), y.data(), count,
                                          proto::DType::kF32, desc);
                std::this_thread::sleep_for(std::chrono::milliseconds(15));
                cl.disconnect();
                ok_count.fetch_add(1);
                return;
            }
            // survivors: retry until a reduce completes; verify against the
            // world it actually ran over (the deserter may or may not have
            // contributed depending on abort timing)
            for (int attempt = 0; attempt < 50; ++attempt) {
                client::ReduceInfo info;
                auto st = cl.all_reduce(x.data(), y.data(), count,
                                        proto::DType::kF32, desc, &info);
                if (st == client::Status::kOk) {
                    bool correct = true;
                    uint32_t w = info.world;
                    for (size_t i = 0; i < count && correct; ++i) {
                        double expect = w * double(i % 97) + w * (w - 1) / 2.0;
                        if (std::abs(double(y[i]) - expect) > 1e-4) {
                            fprintf(stderr, "survivor %zu: y[%zu]=%f expect %f (w=%u)\n",
                                    r, i, y[i], expect, w);
                            correct = false;
                        }
                    }
                    if (correct) ok_count.fetch_add(1);
                    return;
                }
                // failed op: adopt the shrunken world and retry
                cl.update_topology();
                std::this_thread::sleep_for(std::chrono::milliseconds(20));
            }
            fprintf(stderr, "survivor %zu: never completed a reduce\n", r);
        });
    }
    for (auto &t : threads) t.join();
    CHECK(ok_count.load() == static_cast<int>(world));
    mm.interrupt();
    mm.join();
}

// Widened collective vocabulary + schedule-stamped algorithms end-to-end
// (docs/12): broadcast / reduce-scatter / all-to-all against closed-form
// expectations, with the synthesizer optionally FORCED onto a non-ring
// algorithm (nullptr = leave PCCLT_SCHEDULE_FORCE unset). Every payload
// size is deliberately not divisible by the world.
static void test_e2e_sched(size_t world, const char *force) {
    if (force) setenv("PCCLT_SCHEDULE_FORCE", force, 1);
    uint16_t port = alloc_test_ports(512);
    master::Master mm(port);
    CHECK(mm.launch());
    uint16_t base = static_cast<uint16_t>(port + 16);
    port = mm.port();

    const size_t count = 2053;
    std::vector<std::thread> threads;
    std::atomic<int> ok_count{0};
    std::atomic<uint64_t> nonring_ops{0};

    for (size_t r = 0; r < world; ++r) {
        threads.emplace_back([&, r] {
            client::Client cl(peer_cfg(port, base, r));
            if (cl.connect() != client::Status::kOk) {
                fprintf(stderr, "peer %zu: connect failed\n", r);
                return;
            }
            if (!wait_world(cl, world)) return;
            uint64_t slot = ~0ull;
            if (cl.gather_slot(&slot) != client::Status::kOk) {
                fprintf(stderr, "peer %zu: gather_slot failed\n", r);
                return;
            }
            client::ReduceInfo info;

            // all-reduce (force=butterfly runs the halving/doubling path)
            std::vector<float> x(count), y(count, -1.0f);
            for (size_t i = 0; i < count; ++i)
                x[i] = static_cast<float>(i % 89) + static_cast<float>(r);
            client::ReduceDesc ar;
            ar.tag = 11;
            auto st = cl.all_reduce(x.data(), y.data(), count,
                                    proto::DType::kF32, ar, &info);
            if (st != client::Status::kOk) {
                fprintf(stderr, "peer %zu: sched allreduce st=%d\n", r,
                        int(st));
                return;
            }
            for (size_t i = 0; i < count; ++i) {
                double expect = world * double(i % 89) +
                                world * (world - 1) / 2.0;
                if (std::abs(double(y[i]) - expect) > 1e-3) {
                    fprintf(stderr, "peer %zu: ar y[%zu]=%f expect %f\n", r,
                            i, y[i], expect);
                    return;
                }
            }

            // broadcast from slot 0, in place; non-roots start poisoned
            std::vector<float> b(count);
            for (size_t i = 0; i < count; ++i)
                b[i] = slot == 0 ? static_cast<float>(i % 53 + 1)
                                 : -7.0f;
            client::ReduceDesc bd;
            bd.tag = 12;
            bd.op = proto::RedOp::kBroadcast;
            bd.aux = 0;
            st = cl.all_reduce(b.data(), b.data(), count, proto::DType::kF32,
                               bd, &info);
            if (st != client::Status::kOk) {
                fprintf(stderr, "peer %zu: broadcast st=%d\n", r, int(st));
                return;
            }
            for (size_t i = 0; i < count; ++i)
                if (b[i] != static_cast<float>(i % 53 + 1)) {
                    fprintf(stderr, "peer %zu: bc b[%zu]=%f\n", r, i, b[i]);
                    return;
                }

            // reduce-scatter: chunk contents checked against rs_offset
            std::vector<float> rs(count);
            for (size_t i = 0; i < count; ++i)
                rs[i] = static_cast<float>(i % 31) + static_cast<float>(r);
            const size_t cap = (count + world - 1) / world;
            std::vector<float> chunk(cap, -1.0f);
            client::ReduceDesc rd;
            rd.tag = 13;
            rd.op = proto::RedOp::kReduceScatter;
            rd.recv_capacity = cap;
            st = cl.all_reduce(rs.data(), chunk.data(), count,
                               proto::DType::kF32, rd, &info);
            if (st != client::Status::kOk) {
                fprintf(stderr, "peer %zu: reduce-scatter st=%d\n", r,
                        int(st));
                return;
            }
            if (info.rs_count == 0 || info.rs_count > cap ||
                info.rs_offset + info.rs_count > count) {
                fprintf(stderr, "peer %zu: rs chunk [%llu,+%llu) bad\n", r,
                        (unsigned long long)info.rs_offset,
                        (unsigned long long)info.rs_count);
                return;
            }
            for (size_t k = 0; k < info.rs_count; ++k) {
                double expect = world * double((info.rs_offset + k) % 31) +
                                world * (world - 1) / 2.0;
                if (std::abs(double(chunk[k]) - expect) > 1e-3) {
                    fprintf(stderr, "peer %zu: rs chunk[%zu]=%f expect %f\n",
                            r, k, chunk[k], expect);
                    return;
                }
            }

            // all-to-all: block j carries (my_slot, j); block i of the
            // result must carry (i, my_slot)
            const size_t per = 37;
            std::vector<float> a2s(per * world), a2r(per * world, -1.0f);
            for (size_t j = 0; j < world; ++j)
                for (size_t i = 0; i < per; ++i)
                    a2s[j * per + i] =
                        static_cast<float>(slot * 100 + j) +
                        static_cast<float>(i % 5) * 0.125f;
            client::ReduceDesc ad;
            ad.tag = 14;
            ad.op = proto::RedOp::kAllToAll;
            ad.recv_capacity = per * world;
            st = cl.all_reduce(a2s.data(), a2r.data(), per,
                               proto::DType::kF32, ad, &info);
            if (st != client::Status::kOk) {
                fprintf(stderr, "peer %zu: all-to-all st=%d\n", r, int(st));
                return;
            }
            for (size_t i = 0; i < world; ++i)
                for (size_t k = 0; k < per; ++k) {
                    float expect = static_cast<float>(i * 100 + slot) +
                                   static_cast<float>(k % 5) * 0.125f;
                    if (a2r[i * per + k] != expect) {
                        fprintf(stderr,
                                "peer %zu: a2a [%zu][%zu]=%f expect %f\n", r,
                                i, k, a2r[i * per + k], expect);
                        return;
                    }
                }

            auto &cc = cl.tele().comm;
            nonring_ops.fetch_add(cc.sched_ops_tree.load() +
                                  cc.sched_ops_butterfly.load() +
                                  cc.sched_ops_mesh.load() +
                                  cc.sched_ops_relay.load());
            ok_count.fetch_add(1);
            cl.disconnect();
        });
    }
    for (auto &t : threads) t.join();
    CHECK(ok_count.load() == static_cast<int>(world));
    // a forced non-ring algorithm must actually have run somewhere (the
    // force only binds where (coll, algo, world) is executable, but every
    // force used here has at least one executable collective). With the
    // kill switch thrown (PCCLT_SCHEDULE=0 acceptance leg) the force is
    // ignored and everything above ran — correctly — over the ring.
    if (force && sched::schedule_enabled()) CHECK(nonring_ops.load() > 0);
    if (force) unsetenv("PCCLT_SCHEDULE_FORCE");
    mm.interrupt();
    mm.join();
}

int main() {
    test_lock_annotations();
    test_telemetry();
    test_observability();
    test_master_ingest_offloop();
    test_master_incident_classes();
    test_master_health_history();
    test_chaos_schedule();
    test_netem_striped_bucket();
    test_watchdog();
    test_wire();
    test_proto_truncation();
    test_hash();
    test_ss_chunk();
    test_kernels();
    test_quant();
    test_quant_16bit_parity();
    test_journal();
    test_master_ha_state();
    test_op_done_replay();
    test_atsp();
    test_schedule_planner();
    test_chaos_late_arm();
    {
        // guarded allocator: bytes usable end-to-end, balanced live count
        size_t live0 = pcclt::galloc::live_count();
        for (size_t n : {size_t{1}, size_t{16}, size_t{4095}, size_t{4096},
                         size_t{100000}}) {
            auto *p = static_cast<uint8_t *>(pcclt::galloc::guarded_malloc(n));
            CHECK(p != nullptr);
            memset(p, 0xAB, n);   // every byte writable up to the guard page
            CHECK(p[0] == 0xAB && p[n - 1] == 0xAB);
            pcclt::galloc::guarded_free(p);
        }
        CHECK(pcclt::galloc::live_count() == live0);
    }
    printf("unit tests: %s\n", g_failures ? "FAIL" : "ok");
    test_e2e(2, proto::QuantAlgo::kNone);
    printf("e2e world=2 fp32: %s\n", g_failures ? "FAIL" : "ok");
    if (!fast_mode()) {
        test_e2e(4, proto::QuantAlgo::kNone);
        printf("e2e world=4 fp32: %s\n", g_failures ? "FAIL" : "ok");
        test_e2e(3, proto::QuantAlgo::kMinMax);
        printf("e2e world=3 minmax-quantized: %s\n", g_failures ? "FAIL" : "ok");
    }
    test_e2e(3, proto::QuantAlgo::kZeroPointScale);
    printf("e2e world=3 zps-quantized: %s\n", g_failures ? "FAIL" : "ok");
    if (!fast_mode()) {
        test_e2e_halfprec(2, proto::DType::kF16);
        printf("e2e world=2 f16: %s\n", g_failures ? "FAIL" : "ok");
    }
    test_e2e_halfprec(2, proto::DType::kBF16);
    printf("e2e world=2 bf16: %s\n", g_failures ? "FAIL" : "ok");
    test_e2e_concurrent_tags(2, fast_mode() ? 2 : 4);
    printf("e2e world=2 concurrent tags: %s\n", g_failures ? "FAIL" : "ok");
    test_e2e_pipelined();
    printf("e2e pipelined data plane (fallback matrix): %s\n",
           g_failures ? "FAIL" : "ok");
    test_e2e_striped();
    printf("e2e multipath striping matrix (stripes x uring x quant x qwin): %s\n",
           g_failures ? "FAIL" : "ok");
    test_e2e_abort_mid_ring();
    printf("e2e world=3 abort mid-ring: %s\n", g_failures ? "FAIL" : "ok");
    test_e2e_sched(3, "tree");
    printf("e2e world=3 sched (tree broadcast + new collectives): %s\n",
           g_failures ? "FAIL" : "ok");
    if (!fast_mode()) {
        test_e2e_sched(4, "butterfly");
        printf("e2e world=4 sched (butterfly allreduce): %s\n",
               g_failures ? "FAIL" : "ok");
        test_e2e_sched(4, "mesh");
        printf("e2e world=4 sched (mesh all-to-all): %s\n",
               g_failures ? "FAIL" : "ok");
        test_e2e_sched(2, nullptr);
        printf("e2e world=2 sched (synthesizer default-on): %s\n",
               g_failures ? "FAIL" : "ok");
    }
    if (g_failures) {
        printf("SELFTEST FAILED (%d)\n", g_failures);
        return 1;
    }
    printf("SELFTEST PASSED\n");
    return 0;
}
