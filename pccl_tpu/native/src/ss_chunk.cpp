#include "ss_chunk.hpp"

#include <algorithm>
#include <chrono>

#include "wire.hpp"

namespace pcclt::ssc {

uint32_t chunk_count(uint64_t nbytes, uint64_t chunk_bytes) {
    if (nbytes == 0 || chunk_bytes == 0) return 0;
    return static_cast<uint32_t>((nbytes + chunk_bytes - 1) / chunk_bytes);
}

uint64_t chunk_len(uint64_t nbytes, uint64_t chunk_bytes, uint32_t idx) {
    uint64_t off = static_cast<uint64_t>(idx) * chunk_bytes;
    if (off >= nbytes) return 0;
    return std::min(chunk_bytes, nbytes - off);
}

std::vector<uint64_t> leaf_hashes(hash::Type t, const void *data,
                                  uint64_t nbytes, uint64_t chunk_bytes) {
    std::vector<uint64_t> leaves;
    uint32_t n = chunk_count(nbytes, chunk_bytes);
    leaves.reserve(n);
    const auto *p = static_cast<const uint8_t *>(data);
    for (uint32_t i = 0; i < n; ++i)
        leaves.push_back(hash::content_hash(
            t, p + static_cast<uint64_t>(i) * chunk_bytes,
            chunk_len(nbytes, chunk_bytes, i)));
    return leaves;
}

uint64_t root_hash(hash::Type t, const std::vector<uint64_t> &leaves) {
    // hash the big-endian leaf array so the root is endian-stable on the
    // wire like every other hash this protocol ships
    std::vector<uint8_t> buf;
    buf.reserve(leaves.size() * 8);
    for (uint64_t h : leaves) {
        uint64_t be = wire::to_be(h);
        const auto *p = reinterpret_cast<const uint8_t *>(&be);
        buf.insert(buf.end(), p, p + 8);
    }
    return hash::content_hash(t, buf.data(), buf.size());
}

// ----------------------------------------------------------- request wire

std::vector<uint8_t> ChunkReqSpec::encode(bool with_p2p) const {
    wire::Writer w;
    w.u64(revision);
    w.str(key);
    w.u64(chunk_bytes);
    w.u32(first);
    w.u32(count);
    if (with_p2p) w.u16(req_p2p);
    return w.take();
}

std::optional<ChunkReqSpec> ChunkReqSpec::decode(
        const std::vector<uint8_t> &b) {
    ChunkReqSpec s;
    try {
        wire::Reader r(b);
        s.revision = r.u64();
        s.key = r.str();
        s.chunk_bytes = r.u64();
        s.first = r.u32();
        s.count = r.u32();
        // the p2p port tail is optional: the pooled spec stops at count.
        // A torn tail (1 stray byte) is still a reject, not a fuzzer
        // finding — the reader throws and we fall back to "absent".
        try {
            s.req_p2p = r.u16();
        } catch (...) {}
    } catch (...) {
        return std::nullopt;
    }
    return s;
}

// ------------------------------------------------------------- FetchPlan

FetchPlan::FetchPlan(std::vector<KeySpec> keys, uint64_t chunk_bytes,
                     double factor, uint64_t min_ns, uint32_t max_range,
                     uint64_t rot_seed)
    : chunk_bytes_(chunk_bytes == 0 ? 1 : chunk_bytes),
      factor_(factor > 0 ? factor : 4.0),
      min_ns_(min_ns),
      max_range_(max_range == 0 ? 1 : max_range),
      rot_seed_(rot_seed) {
    MutexLock lk(mu_);
    for (auto &ks : keys) {
        Key k;
        k.nchunks = chunk_count(ks.nbytes, chunk_bytes_);
        k.chunks.resize(k.nchunks);
        total_bytes_ += ks.nbytes;
        total_chunks_ += k.nchunks;
        // sparse revision delta (docs/04): chunks whose request-time local
        // hash already equals the expected leaf are born done — a
        // drag-along peer one revision behind fetches only what changed
        if (ks.local_leaves.size() == k.nchunks &&
            ks.leaves.size() == k.nchunks) {
            for (uint32_t ci = 0; ci < k.nchunks; ++ci) {
                if (ks.local_leaves[ci] != ks.leaves[ci]) continue;
                k.chunks[ci].state = CState::kDone;
                uint64_t len = chunk_len(ks.nbytes, chunk_bytes_, ci);
                stats_.chunks_delta_skipped++;
                stats_.bytes_delta_skipped += len;
                done_chunks_++;
                k.done++;
            }
        }
        k.spec = std::move(ks);
        keys_.push_back(std::move(k));
    }
    // a zero-chunk key (empty entry) — or one whose chunks were ALL
    // delta-skipped — is born complete and must still report (promotion)
    for (uint32_t i = 0; i < keys_.size(); ++i)
        if (keys_[i].done == keys_[i].nchunks && !keys_[i].reported) {
            keys_[i].reported = true;
            completed_keys_.push_back(i);
        }
}

uint32_t FetchPlan::add_seeder(const std::string &endpoint) {
    MutexLock lk(mu_);
    auto it = seeder_idx_.find(endpoint);
    if (it != seeder_idx_.end()) return it->second;
    uint32_t idx = static_cast<uint32_t>(seeders_.size());
    seeders_.push_back(Seeder{endpoint, true, 0});
    seeder_idx_[endpoint] = idx;
    cv_.notify_all();
    return idx;
}

void FetchPlan::add_key_seeder(uint32_t key, uint32_t seeder) {
    MutexLock lk(mu_);
    if (key >= keys_.size() || seeder >= seeders_.size()) return;
    keys_[key].seeders.insert(seeder);
    cv_.notify_all();
}

void FetchPlan::seeder_gone(uint32_t seeder) {
    MutexLock lk(mu_);
    if (seeder >= seeders_.size() || !seeders_[seeder].alive) return;
    seeders_[seeder].alive = false;
    stats_.seeders_lost++;
    // its outstanding assignments can never complete: re-source exactly
    // those now. deadline_ns is per-chunk (the NEWEST assignment's), so
    // only zero it when the dead seeder owns EVERY outstanding
    // assignment — with a healthy co-owner inflight, its live deadline
    // stands and the dead straggler entry is reaped by the worker's own
    // failure report
    for (auto &k : keys_)
        for (auto &c : k.chunks)
            if (c.state == CState::kInflight && !c.owners.empty() &&
                c.owners.count(seeder) == c.owners.size())
                c.deadline_ns = 0;
    maybe_fail_out();
    cv_.notify_all();
}

void FetchPlan::seeder_backoff(uint32_t seeder, uint64_t until_ns) {
    MutexLock lk(mu_);
    if (seeder >= seeders_.size()) return;
    seeders_[seeder].backoff_until_ns = until_ns;
}

bool FetchPlan::seeder_alive(uint32_t seeder) const {
    MutexLock lk(mu_);
    return seeder < seeders_.size() && seeders_[seeder].alive;
}

std::string FetchPlan::seeder_endpoint(uint32_t seeder) const {
    MutexLock lk(mu_);
    return seeder < seeders_.size() ? seeders_[seeder].endpoint : std::string();
}

size_t FetchPlan::seeder_count() const {
    MutexLock lk(mu_);
    return seeders_.size();
}

uint64_t FetchPlan::budget_locked() const {
    uint64_t b = ewma_ns_ > 0
                     ? static_cast<uint64_t>(ewma_ns_ * factor_)
                     : min_ns_ * 4;  // no sample yet: generous first envelope
    return std::max(b, min_ns_);
}

uint64_t FetchPlan::chunk_budget_ns() const {
    MutexLock lk(mu_);
    return budget_locked();
}

bool FetchPlan::assignable(const Key &k, const Chunk &c,
                           uint32_t seeder) const {
    if (c.state != CState::kPending) return false;
    if (c.tried.count(seeder)) return false;
    return k.seeders.count(seeder) != 0;
}

std::optional<FetchPlan::Take> FetchPlan::take(uint32_t seeder,
                                               uint64_t now_ns) {
    MutexLock lk(mu_);
    if (failed_out_ || done_chunks_ == total_chunks_) return std::nullopt;
    if (seeder >= seeders_.size() || !seeders_[seeder].alive) return std::nullopt;
    if (seeders_[seeder].backoff_until_ns > now_ns) return std::nullopt;
    const size_t nk = keys_.size();
    if (nk == 0) return std::nullopt;
    // per-peer key rotation + per-seeder offset: a swarm of joiners starts
    // on DIFFERENT keys (promotion multiplies seeders) and two seeders of
    // one joiner start on different keys (less range overlap)
    size_t start = (rot_seed_ + seeder) % nk;
    for (size_t pass = 0; pass < nk; ++pass) {
        uint32_t ki = static_cast<uint32_t>((start + pass) % nk);
        Key &k = keys_[ki];
        if (k.done == k.nchunks || k.seeders.count(seeder) == 0) continue;
        for (uint32_t ci = 0; ci < k.nchunks; ++ci) {
            if (!assignable(k, k.chunks[ci], seeder)) continue;
            Take t;
            t.key = ki;
            t.first = ci;
            uint64_t budget = budget_locked();
            while (t.count < max_range_ && ci + t.count < k.nchunks &&
                   assignable(k, k.chunks[ci + t.count], seeder)) {
                Chunk &c = k.chunks[ci + t.count];
                c.state = CState::kInflight;
                c.inflight++;
                c.attempts++;
                c.owners.insert(seeder);
                c.taken_ns = now_ns;
                // staggered: later chunks of the run arrive serially
                c.deadline_ns = now_ns + (t.count + 1) * budget;
                t.gens.push_back(c.attempts);
                t.count++;
            }
            return t;
        }
    }
    return std::nullopt;
}

uint8_t *FetchPlan::claim(uint32_t key, uint32_t idx) {
    MutexLock lk(mu_);
    if (key >= keys_.size()) return nullptr;
    Key &k = keys_[key];
    if (idx >= k.nchunks) return nullptr;
    Chunk &c = k.chunks[idx];
    if (c.state == CState::kDone || c.state == CState::kWriting) return nullptr;
    c.state = CState::kWriting;
    return k.spec.dst + static_cast<uint64_t>(idx) * chunk_bytes_;
}

void FetchPlan::abandon(uint32_t key, uint32_t idx) {
    MutexLock lk(mu_);
    Chunk &c = keys_[key].chunks[idx];
    if (c.state == CState::kWriting) c.state = CState::kPending;
    cv_.notify_all();
}

void FetchPlan::published(uint32_t key, uint32_t idx, uint32_t seeder,
                          uint32_t gen, uint64_t now_ns) {
    MutexLock lk(mu_);
    Key &k = keys_[key];
    Chunk &c = k.chunks[idx];
    uint64_t len = chunk_len(k.spec.nbytes, chunk_bytes_, idx);
    if (gen <= 1) {
        stats_.chunks_fetched++;
        stats_.bytes_fetched += len;
    } else {
        stats_.chunks_resourced++;
        stats_.bytes_resourced += len;
    }
    if (c.inflight > 0) c.inflight--;
    auto own = c.owners.find(seeder);
    if (own != c.owners.end()) c.owners.erase(own);
    if (c.state != CState::kWriting) return;  // defensive: claim protocol
    c.state = CState::kDone;
    stats_.unique_bytes += len;
    done_chunks_++;
    k.done++;
    if (k.done == k.nchunks && !k.reported) {
        k.reported = true;
        completed_keys_.push_back(key);
    }
    // EWMA over completed fetch round-trips (the watchdog envelope's
    // feed). Only the LATEST assignment's arrival is a valid sample:
    // taken_ns was overwritten by any re-take, so an older generation
    // landing now would be measured from the wrong start and feed an
    // artificially tiny sample into the deadline — a premature-expiry
    // feedback loop
    if (gen == c.attempts && c.taken_ns && now_ns > c.taken_ns) {
        double sample = static_cast<double>(now_ns - c.taken_ns);
        ewma_ns_ = ewma_ns_ <= 0 ? sample : 0.7 * ewma_ns_ + 0.3 * sample;
    }
    cv_.notify_all();
}

void FetchPlan::duplicate(uint32_t key, uint32_t idx, uint32_t seeder,
                          uint32_t gen) {
    MutexLock lk(mu_);
    Key &k = keys_[key];
    uint64_t len = chunk_len(k.spec.nbytes, chunk_bytes_, idx);
    if (gen <= 1) {
        stats_.chunks_fetched++;
        stats_.bytes_fetched += len;
    } else {
        stats_.chunks_resourced++;
        stats_.bytes_resourced += len;
    }
    stats_.chunks_dup++;
    stats_.bytes_dup += len;
    Chunk &c = k.chunks[idx];
    if (c.inflight > 0) c.inflight--;
    auto own = c.owners.find(seeder);
    if (own != c.owners.end()) c.owners.erase(own);
    cv_.notify_all();
}

void FetchPlan::fail_locked(uint32_t key, uint32_t idx, uint32_t seeder,
                            bool hash_bad) {
    Key &k = keys_[key];
    Chunk &c = k.chunks[idx];
    if (hash_bad) stats_.hash_mismatches++;
    if (seeder < seeders_.size()) c.tried.insert(seeder);
    if (c.inflight > 0) c.inflight--;
    auto own = c.owners.find(seeder);
    if (own != c.owners.end()) c.owners.erase(own);
    // re-assignable NOW even with a ghost assignment outstanding (an
    // expired straggler's count): waiting out the ghost's far-future
    // deadline would park the chunk invisibly — not kPending for
    // maybe_fail_out's exhaustion scan, not takeable. kPending with
    // inflight > 0 is already a legal post-expiry state; a straggler's
    // late arrival dedupes via the claim protocol.
    if (c.state == CState::kInflight) c.state = CState::kPending;
    maybe_fail_out();
}

void FetchPlan::failed(uint32_t key, uint32_t idx, uint32_t seeder,
                       bool hash_bad) {
    MutexLock lk(mu_);
    if (key >= keys_.size() || idx >= keys_[key].nchunks) return;
    fail_locked(key, idx, seeder, hash_bad);
    cv_.notify_all();
}

void FetchPlan::requeue(uint32_t key, uint32_t idx, uint32_t seeder) {
    MutexLock lk(mu_);
    if (key >= keys_.size() || idx >= keys_[key].nchunks) return;
    Chunk &c = keys_[key].chunks[idx];
    if (c.inflight > 0) c.inflight--;
    auto own = c.owners.find(seeder);
    if (own != c.owners.end()) c.owners.erase(own);
    // same ghost rule as fail_locked: a refusal must leave the chunk
    // takeable by other seeders immediately
    if (c.state == CState::kInflight) c.state = CState::kPending;
    cv_.notify_all();
}

void FetchPlan::abort() {
    MutexLock lk(mu_);
    failed_out_ = true;
    cv_.notify_all();
}

void FetchPlan::check_liveness() {
    MutexLock lk(mu_);
    if (failed_out_ || done_chunks_ == total_chunks_) return;
    maybe_fail_out();
}

size_t FetchPlan::expire_overdue(uint64_t now_ns) {
    MutexLock lk(mu_);
    size_t n = 0;
    for (auto &k : keys_)
        for (auto &c : k.chunks)
            if (c.state == CState::kInflight && now_ns >= c.deadline_ns) {
                // overdue: make it assignable AGAIN without failing the
                // outstanding fetch — first verified arrival wins, the
                // loser dedupes. The slow seeder is NOT marked tried (it
                // may merely be paced); a second expiry against it will
                // fail through the worker's own recv deadline instead.
                c.state = CState::kPending;
                ++n;
            }
    if (n) cv_.notify_all();
    return n;
}

void FetchPlan::maybe_fail_out() {
    // a pending chunk that every alive eligible seeder has already failed
    // starts a new wave (tried sets clear); kMaxWaves fruitless waves — or
    // no alive eligible seeder at all — fails the plan
    bool any_alive_for_all = true;
    bool any_exhausted = false;
    for (auto &k : keys_) {
        if (k.done == k.nchunks) continue;
        bool key_has_alive = false;
        for (uint32_t s : k.seeders)
            if (s < seeders_.size() && seeders_[s].alive) key_has_alive = true;
        if (!key_has_alive) {
            any_alive_for_all = false;
            continue;
        }
        for (auto &c : k.chunks) {
            if (c.state != CState::kPending) continue;
            bool open = false;
            for (uint32_t s : k.seeders)
                if (s < seeders_.size() && seeders_[s].alive &&
                    c.tried.count(s) == 0)
                    open = true;
            if (!open) any_exhausted = true;
        }
    }
    if (!any_alive_for_all) {
        failed_out_ = true;
        cv_.notify_all();
        return;
    }
    if (any_exhausted) {
        if (++waves_ > kMaxWaves) {
            failed_out_ = true;
        } else {
            for (auto &k : keys_)
                for (auto &c : k.chunks)
                    if (c.state == CState::kPending) c.tried.clear();
        }
        cv_.notify_all();
    }
}

std::vector<uint32_t> FetchPlan::take_completed_keys() {
    MutexLock lk(mu_);
    auto v = std::move(completed_keys_);
    completed_keys_.clear();
    return v;
}

bool FetchPlan::finished() const {
    MutexLock lk(mu_);
    return failed_out_ || done_chunks_ == total_chunks_;
}

bool FetchPlan::complete_ok() const {
    MutexLock lk(mu_);
    return done_chunks_ == total_chunks_;
}

bool FetchPlan::failed_out() const {
    MutexLock lk(mu_);
    return failed_out_;
}

bool FetchPlan::saw_hash_mismatch() const {
    MutexLock lk(mu_);
    return stats_.hash_mismatches > 0;
}

PlanStats FetchPlan::stats() const {
    MutexLock lk(mu_);
    return stats_;
}

const KeySpec &FetchPlan::key_spec(uint32_t key) const {
    MutexLock lk(mu_);
    return keys_[key].spec;
}

size_t FetchPlan::key_count() const {
    MutexLock lk(mu_);
    return keys_.size();
}

uint32_t FetchPlan::key_chunks(uint32_t key) const {
    MutexLock lk(mu_);
    return keys_[key].nchunks;
}

void FetchPlan::wait_event(int timeout_ms) {
    MutexLock lk(mu_);
    if (failed_out_ || done_chunks_ == total_chunks_) return;
    cv_.wait_for(mu_, std::chrono::milliseconds(timeout_ms));
}

}  // namespace pcclt::ssc
