#include "reduce.hpp"

#include <cstdlib>
#include <cstring>
#include <set>
#include <vector>

#include "kernels.hpp"
#include "log.hpp"
#include "quantize.hpp"
#include "telemetry.hpp"

namespace pcclt::reduce {

namespace {

// PCCLT_PROF=1 → log per-op phase timings. A thin consumer of the
// telemetry recorder's clock + accumulators (telemetry.hpp) — the same
// numbers land in the flight-recorder event stream when PCCLT_TRACE is on.
bool prof_enabled() {
    static const bool on = [] {
        const char *e = std::getenv("PCCLT_PROF");
        return e && e[0] == '1';
    }();
    return on;
}

// Per-op phase accumulators (ns). wait_ns is wire-stall: time the op thread
// spent blocked on bytes that had not arrived yet — the per-edge stall
// counter and the "wire_stall" trace event both read from it.
struct Prof {
    uint64_t wait_ns = 0, compute_ns = 0, join_ns = 0, reg_ns = 0,
             quant_ns = 0, dequant_ns = 0;
};

using telemetry::now_ns;

constexpr uint64_t kMetaBit = 0x8000;
constexpr size_t kSubChunk = 2 << 20; // streaming granularity (bytes)

// ---- pipelined data plane (docs/08 "windowed pipeline") ----
// Each ring stage's payload is split into up to PCCLT_PIPELINE_WINDOW
// in-flight windows per edge: quantize of window k+1 overlaps the send of
// window k, and (unquantized) the NEXT stage's send of window k launches
// the moment window k of this stage's chunk finishes accumulating — so a
// fat-long-pipe link pays the per-stage one-way delay once per pipeline
// fill instead of once per stage. Env is re-read per op (tests flip it at
// runtime); windows never shrink below PCCLT_PIPELINE_MIN_BYTES, so small
// payloads degrade to the exact single-window behavior of old.
size_t env_size(const char *name, long long dflt) {
    if (const char *e = std::getenv(name)) {
        long long v = atoll(e);
        if (v >= 0) return static_cast<size_t>(v);
    }
    return static_cast<size_t>(dflt);
}

bool pipeline_enabled() {
    const char *e = std::getenv("PCCLT_PIPELINE");
    return !(e && e[0] == '0');
}

size_t pipeline_windows(size_t bytes) {
    size_t w = env_size("PCCLT_PIPELINE_WINDOW", 4);
    size_t min_b = env_size("PCCLT_PIPELINE_MIN_BYTES", 256 << 10);
    if (min_b == 0) min_b = 1;
    w = std::min(w, bytes / min_b);
    return std::max<size_t>(1, w);
}

struct ChunkSpan {
    size_t start_elem, n_elems;
};

ChunkSpan chunk_of(size_t count, uint32_t world, uint32_t c) {
    size_t base = count / world, rem = count % world;
    size_t start = c * base + std::min<size_t>(c, rem);
    size_t len = base + (c < rem ? 1 : 0);
    return {start, len};
}

// ---- multipath striping (docs/08 "multipath striping") ----
// How many pool conns an op's window chain round-robins across:
// PCCLT_STRIPE_CONNS, default min(4, pool size); 1 = PR-8's pinned
// single-conn behavior. A single TCP flow over a fat-long-pipe is
// serialization-limited (one TX thread paces/writes frame by frame, and
// every scheduler oversleep is wire time lost); K stripes keep K
// reservations queued in the edge's striped bucket, so the modeled wire
// never idles while one sender thread is between frames. Cross-conn
// reassembly is the SinkTable's ordinary byte-range extent/claim
// bookkeeping — arrival order across stripes does not matter — and the
// PR-10 watchdog ladder applies per stripe (each window is its own
// tracked handle).
size_t stripe_conns(size_t pool) {
    size_t s = env_size("PCCLT_STRIPE_CONNS", 0);
    if (s == 0) s = 4;  // unset (or explicit 0): the default policy
    return std::max<size_t>(1, std::min(s, pool));
}

// ---- per-window quantization meta (docs/08, PCCLT_QWIN_META=1) ----
// Legacy wire format: ONE whole-chunk meta frame at offset 0 of
// tag|kMetaBit, computed before the first window can leave — the reason
// the quantized ring barriers at stage tops. The per-window protocol
// sends window w's meta as its own small frame at offset w+1, payload
// [u8 version=1][u8 qw][Meta::encode()], so stage s+1's quantized windows
// launch from inside stage s's accumulation callback exactly like the
// fp32 send-ahead. The offset keying makes the format self-describing
// (receivers never guess the sender's window grid — qw rides every
// frame), version-gated for forward evolution, and numerics are
// bit-identical at equal meta: quantize/dequantize are untouched, only
// WHICH meta covers which elements changes. Off by default: per-window
// metas change quantized results vs the whole-chunk grid (all ranks agree
// either way), so the mode is an explicit, group-consistent opt-in.
bool qwin_enabled() {
    const char *e = std::getenv("PCCLT_QWIN_META");
    return e && e[0] == '1';
}

std::vector<uint8_t> qwin_encode(uint32_t qw, const quant::Meta &m) {
    std::vector<uint8_t> out;
    out.reserve(2 + 40);
    out.push_back(1);  // version
    out.push_back(static_cast<uint8_t>(qw));
    auto enc = m.encode();
    out.insert(out.end(), enc.begin(), enc.end());
    return out;
}

// Receiver-side meta set for one stage: legacy whole-chunk, or per-window
// frames collected lazily as they arrive (any order, any conn).
// Forwarding re-encodes from the decoded metas (qwin_encode /
// Meta::encode are deterministic, so the re-emitted frames are
// byte-identical to the originals).
struct RxMeta {
    bool any = false;         // at least one frame decoded (mode known)
    bool per_window = false;
    uint32_t qw = 1;
    quant::Meta whole;
    std::vector<std::optional<quant::Meta>> win;

    bool have(uint32_t w) const {
        if (!any) return false;
        if (!per_window) return true;
        return w < win.size() && win[w].has_value();
    }
    const quant::Meta &get(uint32_t w) const {
        return per_window ? *win[w] : whole;
    }
};

// Pull meta frames for `mtag` until window `need_w` (or the legacy whole
// meta) is decodable. Bounded waits so master aborts and conn death
// interrupt the wait. false = abort/death/decode failure.
bool fetch_meta(RingCtx &ctx, uint64_t mtag, RxMeta &ms, uint32_t need_w) {
    const auto deadline = now_ns() + 60'000'000'000ull;
    while (!ms.have(need_w)) {
        if (ctx.should_abort && ctx.should_abort()) return false;
        if (!ctx.rx.alive()) return false;
        if (now_ns() > deadline) return false;
        auto fr = ctx.rx.table().recv_queued_any(mtag, 100);
        if (!fr) continue;
        if (fr->first == 0) {
            auto m = quant::Meta::decode(fr->second);
            if (!m) return false;
            ms.whole = *m;
            ms.per_window = false;
            ms.any = true;
        } else {
            const auto &p = fr->second;
            if (p.size() < 2 || p[0] != 1) return false;  // unknown version
            uint32_t qw = p[1];
            uint32_t w = static_cast<uint32_t>(fr->first - 1);
            if (qw == 0 || w >= qw) return false;
            auto m = quant::Meta::decode({p.begin() + 2, p.end()});
            if (!m) return false;
            ms.per_window = true;
            ms.any = true;
            ms.qw = qw;
            if (ms.win.size() < qw) ms.win.resize(qw);
            ms.win[w] = *m;
        }
    }
    return true;
}

// Which window of chunk_of(n, qw, ·) covers element e (inverse of the
// chunk_of start formula: the first `rem` windows are one element longer).
uint32_t window_of(size_t n, uint32_t qw, size_t e) {
    size_t base = n / qw, rem = n % qw;
    if (e < rem * (base + 1)) return static_cast<uint32_t>(e / (base + 1));
    return static_cast<uint32_t>(rem + (e - rem * (base + 1)) / base);
}

// Run fn(meta, e0, e1) over [e0, e1) split at the meta set's window
// boundaries, fetching late metas as needed. false = fetch failed.
bool for_each_meta_span(RingCtx &ctx, uint64_t mtag, RxMeta &ms,
                        size_t n_elems, size_t e0, size_t e1,
                        const std::function<void(const quant::Meta &, size_t,
                                                 size_t)> &fn) {
    while (e0 < e1) {
        uint32_t w = ms.per_window ? window_of(n_elems, ms.qw, e0) : 0;
        if (!ms.have(w) && !fetch_meta(ctx, mtag, ms, w)) return false;
        size_t hi = e1;
        if (ms.per_window) {
            auto ws = chunk_of(n_elems, ms.qw, w);
            hi = std::min(e1, ws.start_elem + ws.n_elems);
        }
        fn(ms.get(w), e0, hi);
        e0 = hi;
    }
    return true;
}

// Emit ONE window [base_off, base_off+len) of `tag`, striped into
// `stripes` sub-spans round-robin across the pool. Striping WITHIN the
// window (not window-per-conn) is load-bearing: a whole window parked on
// one fair-share lane drains at R/K, so every window of a stage would
// finish simultaneously at stage end and the cross-stage send-ahead
// would degenerate to stage-serial (measured: 0.82x). Sub-striping keeps
// window completion staggered exactly like the pinned chain — sub j of
// every window rides conn (rot+j), so each conn's in-order queue is the
// window sequence — while K senders keep K reservations live in the
// striped bucket. Sub floor 64 KiB keeps frames meaningful; stripes == 1
// or small windows go as one in-order stream (the PR-8 behavior).
void striped_window_send(net::Link &tx, uint64_t tag, const uint8_t *src,
                         uint64_t base_off, size_t len, size_t rot,
                         size_t stripes, telemetry::EdgeCounters *edge,
                         std::vector<net::SendHandle> *hs) {
    constexpr size_t kSubMin = 64 << 10;
    if (stripes <= 1 || len < 2 * kSubMin) {
        hs->push_back(tx.send_at(tag, base_off, {src, len}, rot));
        return;
    }
    size_t sub = (len + stripes - 1) / stripes;
    if (sub < kSubMin) sub = kSubMin;
    for (size_t off = 0, j = 0; off < len; off += sub, ++j) {
        size_t n = std::min(sub, len - off);
        hs->push_back(tx.send_at(tag, base_off + off, {src + off, n},
                                 rot + j % stripes));
    }
    if (edge) {
        edge->tx_stripe_windows.fetch_add(1, std::memory_order_relaxed);
        edge->tx_stripe_bytes.fetch_add(len, std::memory_order_relaxed);
    }
}

// Launch completed windows [*ahead_off, prefix) of the NEXT stage's send
// chunk (`src`, `total` bytes, granule `wb`) — called from inside a
// stream_recv accumulation callback, so the next stage's first bytes are
// on the wire while this stage's later windows are still arriving. A
// sub-window tail is absorbed into the last window. Each window stripes
// across `stripes` pool conns via striped_window_send (multipath
// striping; 1 = the PR-8 pinned single-conn chain). The one place this
// arithmetic lives; both ring_allreduce and ring_allgather ride it —
// with prefix == total it doubles as the striped stage-top submit.
void send_ahead_windows(net::Link &tx, uint64_t tag, const uint8_t *src,
                        size_t total, size_t wb, size_t prefix, size_t rot,
                        size_t *ahead_off, std::vector<net::SendHandle> *hs,
                        size_t stripes = 1,
                        telemetry::EdgeCounters *edge = nullptr) {
    auto &rec = telemetry::Recorder::inst();
    const bool wt = rec.on() && telemetry::win_trace_enabled();
    while (*ahead_off < total) {
        size_t seg = std::min(wb, total - *ahead_off);
        if (total - (*ahead_off + seg) < wb) seg = total - *ahead_off;
        if (prefix < *ahead_off + seg) break;
        striped_window_send(tx, tag, src + *ahead_off, *ahead_off, seg, rot,
                            stripes, edge, hs);
        if (wt)
            rec.instant("window", "win_submit", "off", *ahead_off, "bytes",
                        seg, nullptr, "seq", rot);
        *ahead_off += seg;
    }
}

// ---- edge watchdog + live window failover (docs/05 three-stage ladder) --
//
// Sender-side per-window progress deadlines: a window (send handle) that
// misses factor x its EWMA-predicted drain time marks the outbound edge
// SUSPECT and is RE-ISSUED over a fresh pool connection on the same edge
// (flap recovery). If that also stalls, the edge is CONFIRMED and the
// window — plus everything after it this op, and whole stages of later
// ops while the verdict holds — detours through a healthy neighbor
// (kRelayFwd). The receiver dedupes by byte range with first-arrival-wins
// (SinkTable::place_deduped), so duplicate copies are dropped + counted
// and numerics/byte-conservation hold exactly. Stalled direct handles the
// op moved past become "zombies": their borrowed buffer spans stay valid
// until they complete, so the op waits them out at the RS->AG boundary
// (before the all-gather overwrites sent chunks) and at op end.
struct Wd {
    bool on = false;
    bool relay_all = false;    // CONFIRMED: direct sends bypassed this op
    bool skip_reissue = false; // edge has prior history: escalate faster
    bool tripped = false;      // any escalation this op: blocks the clear
    std::vector<net::SendHandle> zombies;
    net::Link fresh;           // rung-1 extra pool conn (dialed once/op)
    bool fresh_tried = false;
    // Every direct send is launch-stamped here; the watchdog polls handle
    // AGE both at the stage join and from inside stream_recv's wait slices
    // — in a coupled ring stall the op thread lives in the RECEIVE loop
    // (everyone's progress gates on the slow hop) and a join-only deadline
    // would never observe its own stalled egress.
    std::vector<std::pair<net::SendHandle, uint64_t>> inflight;
    // handles already escalated (relayed): the join must zombie them, not
    // escalate twice
    std::set<const net::SendState *> detoured;
};

void wd_track(Wd &wd, const std::vector<net::SendHandle> &hs, size_t from = 0) {
    if (!wd.on) return;
    const uint64_t t = now_ns();
    for (size_t i = from; i < hs.size(); ++i)
        if (hs[i] && !hs[i]->span.empty()) wd.inflight.emplace_back(hs[i], t);
}

uint64_t wd_deadline_ns(const RingCtx &ctx, const telemetry::EdgeCounters *e,
                        size_t bytes) {
    uint64_t rate = e->wd_rate_bps.load(std::memory_order_relaxed);
    // unseeded edges get a generous fixed envelope: a fresh world must not
    // trip on its very first (cold, possibly slow) window
    uint64_t base = rate > 0
                        ? static_cast<uint64_t>(bytes * 1e9 / rate)
                        : 500'000'000ull;
    auto dl = static_cast<uint64_t>(base * ctx.wd_factor);
    return std::max(dl, ctx.wd_min_ns);
}

void wd_update_rate(telemetry::EdgeCounters *e, size_t bytes, uint64_t dur_ns) {
    // tiny windows and sub-ms joins sample scheduler noise, not the wire
    if (!e || dur_ns < 1'000'000 || bytes < (64u << 10)) return;
    auto rate = static_cast<uint64_t>(bytes * 1e9 / dur_ns);
    uint64_t old = e->wd_rate_bps.load(std::memory_order_relaxed);
    e->wd_rate_bps.store(
        old ? static_cast<uint64_t>(0.7 * old + 0.3 * rate) : rate,
        std::memory_order_relaxed);
}

void wd_init(Wd &wd, RingCtx &ctx) {
    if (ctx.wd_factor <= 0 || !ctx.tx_edge) return;
    // same-host zero-copy links opt out entirely: they have no WAN
    // straggler mode worth a detour, and keeping them out makes relay
    // frames and in-flight CMA fills mutually exclusive by construction —
    // do_cma_fill writes outside the lock WITHOUT a dedupe claim, so a
    // concurrent failover copy into the same sink would race it and break
    // the delivered-unique conservation accounting
    if (ctx.tx.cma_eligible()) return;
    wd.on = true;
    auto *e = ctx.tx_edge;
    uint32_t h = e->wd_health.load(std::memory_order_relaxed);
    using telemetry::EdgeHealth;
    if (h == static_cast<uint32_t>(EdgeHealth::kConfirmed)) {
        uint64_t since = e->wd_confirmed_at_ns.load(std::memory_order_relaxed);
        if (ctx.relay_window && now_ns() - since < ctx.wd_hold_ns) {
            wd.relay_all = true;  // verdict still holds: start in relay mode
        } else {
            // hold expired: re-probe the edge directly, but remember the
            // history — a re-trip skips the reissue rung and relays at once
            e->wd_health.store(static_cast<uint32_t>(EdgeHealth::kSuspect),
                               std::memory_order_relaxed);
            wd.skip_reissue = true;
        }
    } else if (h == static_cast<uint32_t>(EdgeHealth::kSuspect)) {
        wd.skip_reissue = true;
    }
}

void wd_mark(telemetry::EdgeCounters *e, telemetry::EdgeHealth v) {
    auto nv = static_cast<uint32_t>(v);
    uint32_t cur = e->wd_health.load(std::memory_order_relaxed);
    while (cur < nv && !e->wd_health.compare_exchange_weak(
                           cur, nv, std::memory_order_relaxed)) {
    }
    if (v == telemetry::EdgeHealth::kSuspect)
        e->wd_suspects.fetch_add(1, std::memory_order_relaxed);
    if (v == telemetry::EdgeHealth::kConfirmed) {
        e->wd_confirms.fetch_add(1, std::memory_order_relaxed);
        e->wd_confirmed_at_ns.store(now_ns(), std::memory_order_relaxed);
    }
}

// detour [p, p+bytes) for `tag` through the relay in bounded windows (the
// receiver's stream overlap granularity); false = no relay path
bool wd_relay_span(RingCtx &ctx, uint64_t tag, uint64_t base_off,
                   const uint8_t *p, size_t bytes) {
    if (!ctx.relay_window) return false;
    constexpr size_t kRelayWin = 1u << 20;
    for (size_t off = 0; off < bytes; off += kRelayWin) {
        size_t n = std::min(kRelayWin, bytes - off);
        if (!ctx.relay_window(tag, base_off + off, {p + off, n})) return false;
        // planned kRelayRing detours are a CHOSEN schedule, not a failover:
        // they get their own conservation counter so dashboards can tell
        // the two apart (docs/12)
        if (ctx.planned_relay) {
            if (ctx.tele)
                ctx.tele->comm.sched_relay_planned_bytes.fetch_add(
                    n, std::memory_order_relaxed);
        } else if (ctx.tx_edge) {
            ctx.tx_edge->wd_relays.fetch_add(1, std::memory_order_relaxed);
        }
    }
    return true;
}

// Escalation ladder for ONE stalled window: SUSPECT -> re-issue over a
// fresh pool conn on the same edge (flap recovery) -> CONFIRMED + relay
// through a healthy neighbor. On success the direct handle (and a losing
// re-issue) become zombies and the handle is marked detoured so neither
// the join nor a later poll escalates it twice. Returns false when no
// rung could take the window (caller keeps waiting the old way).
bool wd_escalate(Wd &wd, RingCtx &ctx, const net::SendHandle &h) {
    auto &rec = telemetry::Recorder::inst();
    using telemetry::EdgeHealth;
    const size_t b = h->span.size();
    wd.tripped = true;
    wd_mark(ctx.tx_edge, EdgeHealth::kSuspect);
    if (rec.on())
        rec.instant("watchdog", "edge_suspect", "bytes", b, "seq", ctx.op_seq,
                    ctx.tx_endpoint);
    net::SendHandle h2;
    if (!wd.skip_reissue) {
        if (!wd.fresh_tried) {
            wd.fresh_tried = true;
            if (ctx.fresh_tx_conn) wd.fresh = ctx.fresh_tx_conn();
        }
        if (wd.fresh.valid()) {
            h2 = wd.fresh.send_at(h->tag, h->off, h->span, 0);
            ctx.tx_edge->wd_reissues.fetch_add(1, std::memory_order_relaxed);
            // the re-issue race gets a per-window allowance of its own: a
            // flapped conn recovers here, a degraded EDGE (shared bucket)
            // stalls both copies and escalates
            const uint64_t rdl = wd_deadline_ns(ctx, ctx.tx_edge, b);
            const uint64_t r0 = now_ns();
            while (now_ns() - r0 < rdl) {
                if (h->done() && h2->done()) break;  // both failed: relay
                if ((h->done() && h->wait(0)) || (h2->done() && h2->wait(0))) {
                    // first success wins; the loser keeps draining and its
                    // frames dedupe receiver-side
                    if (!h->done()) {
                        wd.detoured.insert(h.get());
                        wd.zombies.push_back(h);
                    }
                    if (!h2->done()) wd.zombies.push_back(h2);
                    return true;
                }
                // park on whichever copy is still in flight (waiting on a
                // DONE handle returns immediately — a failed direct copy
                // must not turn this race into a busy-spin)
                (h->done() ? h2 : h)->wait(20);
            }
        }
    }
    // --- CONFIRMED: relay the window through a neighbor ---
    if (wd_relay_span(ctx, h->tag, h->off, h->span.data(), b)) {
        wd_mark(ctx.tx_edge, EdgeHealth::kConfirmed);
        wd.relay_all = true;
        if (rec.on())
            rec.instant("watchdog", "edge_confirm", "bytes", b, "seq",
                        ctx.op_seq, ctx.tx_endpoint);
        wd.detoured.insert(h.get());
        wd.zombies.push_back(h);
        if (h2 && !h2->done()) wd.zombies.push_back(h2);
        return true;
    }
    if (h2 && !h2->done()) wd.zombies.push_back(h2);
    return false;
}

// Age-based stall poll, run from stream_recv wait slices AND the stage
// join. In a coupled ring stall every peer's op thread lives in its
// RECEIVE loop (progress gates on the slow hop) and each stage join sees
// handles that completed "just in time" — so the verdict anchors on how
// long the OLDEST pending direct send has been in flight vs the deadline
// for the WHOLE pending backlog (launches overlap; judging each window in
// isolation would false-trip deep healthy queues and miss slow shallow
// ones).
void wd_poll(Wd &wd, RingCtx &ctx) {
    if (!wd.on) return;
    const uint64_t now = now_ns();
    const net::SendHandle *oldest = nullptr;
    uint64_t oldest_t = ~0ull;
    size_t backlog = 0;
    for (auto it = wd.inflight.begin(); it != wd.inflight.end();) {
        const auto &h = it->first;
        if (wd.detoured.count(h.get())) {
            it = wd.inflight.erase(it);
            continue;
        }
        if (h->done()) {
            // healthy-state completions feed the EWMA baseline (a flagged
            // edge's drain times would poison the recovered-state deadline).
            // Anti-poisoning clamp: a completion an order of magnitude
            // under the current envelope is evidence of degradation, not a
            // new baseline — adapting to it stretches the deadline exactly
            // as fast as the fault stretches drains and blinds the age
            // trigger (measured: a uniform 30x degrade under striping
            // never tripped, because each steady sub-window completion
            // re-taught the EWMA the degraded rate before any poll caught
            // an over-age handle). Modest slowdowns — congestion, fair-
            // share queue depth — still adapt (< 8x keeps feeding).
            if (ctx.tx_edge->wd_health.load(std::memory_order_relaxed) == 0) {
                const uint64_t dur = now - it->second;
                const uint64_t rate =
                    ctx.tx_edge->wd_rate_bps.load(std::memory_order_relaxed);
                const bool degraded_sample =
                    rate > 0 && dur > 0 &&
                    static_cast<double>(h->span.size()) * 1e9 / dur <
                        rate / 8.0;
                if (!degraded_sample)
                    wd_update_rate(ctx.tx_edge, h->span.size(),
                                   now - it->second);
            }
            if (telemetry::win_trace_enabled() &&
                telemetry::Recorder::inst().on())
                telemetry::Recorder::inst().instant(
                    "window", "win_drained", "bytes", h->span.size(),
                    "age_ns", now - it->second, nullptr, "seq", ctx.op_seq);
            it = wd.inflight.erase(it);
            continue;
        }
        if (wd.relay_all) {
            // edge already confirmed: detour every still-pending window now
            if (wd_relay_span(ctx, h->tag, h->off, h->span.data(),
                              h->span.size())) {
                wd.detoured.insert(h.get());
                wd.zombies.push_back(h);
                it = wd.inflight.erase(it);
                continue;
            }
        }
        backlog += h->span.size();
        if (it->second < oldest_t) {
            oldest_t = it->second;
            oldest = &it->first;
        }
        ++it;
    }
    if (!oldest || wd.relay_all) return;
    if (now - oldest_t > wd_deadline_ns(ctx, ctx.tx_edge, backlog)) {
        net::SendHandle h = *oldest;  // escalate mutates inflight bookkeeping
        wd_escalate(wd, ctx, h);
    }
}

// watchdog-aware stage join, replacing Link::wait_all on the TX handles.
// Waits in slices, running the same age/backlog poll as the receive loop;
// escalated handles surface as zombies. Returns false only when a window
// could not be delivered by ANY rung (direct, re-issue, relay) — the
// caller fails the op exactly as before.
bool wd_join(Wd &wd, RingCtx &ctx, std::vector<net::SendHandle> &hs) {
    bool ok = true;
    for (auto &h : hs) {
        if (!h) continue;
        const size_t b = h->span.size();
        while (!h->done() && !wd.detoured.count(h.get())) {
            if (b > 0 && wd.relay_all) {
                if (wd_relay_span(ctx, h->tag, h->off, h->span.data(), b)) {
                    wd.detoured.insert(h.get());
                    wd.zombies.push_back(h);
                    break;
                }
            }
            if (b > 0) wd_poll(wd, ctx);
            if (wd.detoured.count(h.get())) break;
            h->wait(50);
        }
        if (wd.detoured.count(h.get())) {
            // already zombied by whichever site detoured it (wd_escalate /
            // wd_poll / the relay branch above) — nothing more to do
            continue;
        }
        if (!h->wait(0)) {
            // failed outright (conn death/flap): the relay rescues it
            if (b > 0 && wd_relay_span(ctx, h->tag, h->off, h->span.data(),
                                       b)) {
                wd.tripped = true;
                wd_mark(ctx.tx_edge, telemetry::EdgeHealth::kConfirmed);
                wd.relay_all = true;
            } else {
                ok = false;
            }
        }
    }
    // sweep completed handles out of wd.inflight NOW: the per-handle loop
    // above exits on done() without a final poll, and a handle left in the
    // map until the next stage's poll would feed the EWMA an inflated
    // drain time and stamp its win_drained event with a stale age
    wd_poll(wd, ctx);
    return ok;
}

// A clean op proves the edge: every direct window met its deadline and no
// rung ran — a SUSPECT verdict (prior history / expired hold) drops back
// to OK so digests, the master's straggler flag, the EWMA feed and the
// reissue rung all recover once the edge behaves again. CONFIRMED is not
// cleared here: only wd_init's hold-expiry re-probe can demote it.
void wd_op_clean(Wd &wd, RingCtx &ctx) {
    if (!wd.on || wd.tripped || wd.relay_all) return;
    uint32_t susp = static_cast<uint32_t>(telemetry::EdgeHealth::kSuspect);
    ctx.tx_edge->wd_health.compare_exchange_strong(
        susp, 0, std::memory_order_relaxed);
}

// Per-stage attribution (docs/09 critical-path plane): every ring stage's
// wall time and its stall slice land in the always-on edge/phase
// histograms, and — recorder on — in an enriched stage span carrying
// (stage, seq, stall_ns) plus the inbound edge endpoint, the tuple
// tools/trace_critic reconstructs the binding chain from. Call sites wrap
// this in a ScopeExit so the FAILING stage of an aborted op still leaves
// its span — the incident bundle's whole point is that exact evidence.
void stage_attrib(RingCtx &ctx, const Prof &prof, const char *name,
                  uint32_t s, uint64_t t0, uint64_t wait0) {
    const uint64_t t1 = now_ns();
    const uint64_t stall = prof.wait_ns - wait0;
    if (ctx.tele)
        ctx.tele->record_phase(telemetry::Phase::kStageWire, t1 - t0);
    if (ctx.rx_edge) {
        ctx.rx_edge->stage_wire_hist.record(t1 - t0);
        ctx.rx_edge->stall_hist.record(stall);
    }
    auto &rec = telemetry::Recorder::inst();
    if (rec.on())
        rec.span("collective", name, t0, t1, "stage", s, "seq", ctx.op_seq,
                 ctx.rx_endpoint, "stall_ns", stall);
}

template <class F> struct ScopeExit {
    F f;
    ~ScopeExit() { f(); }
};
template <class F> ScopeExit(F) -> ScopeExit<F>;

// Post-failover zombie wait, attributed: stalled direct copies crawl out
// at the DEGRADED rate, and on the transition op this wait can dominate
// the wall time — trace_critic must see where it went.
void drain_zombies(RingCtx &ctx, std::vector<net::SendHandle> &zs) {
    if (zs.empty()) return;
    const uint64_t t0 = now_ns();
    // End-to-end relay acks (docs/05): a zombie whose span the FINAL
    // receiver already confirmed delivered (via the relay) is dead weight
    // crawling out at the degraded rate — flag it cancelled so the TX
    // path stops at the next frame boundary and fails the handle without
    // touching the span again. The conn itself stays alive (it may be the
    // op's only pool conn, still carrying metas and later re-probes); the
    // drain below then waits at most one in-flight frame per conn instead
    // of whole spans at the degraded rate. Only a CONFIRMED edge
    // qualifies — its direct windows are already detoured.
    if (ctx.relay_acked && ctx.tx_edge &&
        ctx.tx_edge->wd_health.load(std::memory_order_relaxed) ==
            static_cast<uint32_t>(telemetry::EdgeHealth::kConfirmed)) {
        for (auto &h : zs) {
            if (!h || h->done() || h->span.empty()) continue;
            if (!ctx.relay_acked(h->tag, h->off, h->span.size())) continue;
            h->cancel.store(true, std::memory_order_relaxed);
            if (ctx.tele)
                ctx.tele->comm.relay_retired_early.fetch_add(
                    1, std::memory_order_relaxed);
        }
    }
    net::Link::wait_all(zs);
    zs.clear();
    auto &rec = telemetry::Recorder::inst();
    if (rec.on())
        rec.span("collective", "zombie_drain", t0, now_ns(), "seq",
                 ctx.op_seq, nullptr, 0, ctx.tx_endpoint);
}

// Wait until `target` bytes for `tag` arrived, reducing/consuming via
// `on_data(src, lo, hi)` in slices aligned to `elem_size`. Two transports:
//  - same-host fused pull (registered consumer_pull): the peer's bytes are
//    process_vm_readv'd in cache-sized slices on THIS thread and reduced
//    while hot — no scratch round-trip through DRAM;
//  - TCP streaming: the RX thread fills `scratch` (the registered sink) and
//    slices are reduced from there as the contiguous prefix grows.
// Returns false on abort/conn loss.
bool stream_recv(RingCtx &ctx, uint64_t tag, size_t target, size_t elem_size,
                 const uint8_t *scratch,
                 const std::function<void(const uint8_t *src, size_t lo, size_t hi)> &on_data,
                 Prof *prof = nullptr, bool fill_if_unmapped = false,
                 size_t step = 0, Wd *wd = nullptr) {
    // step: wait/consume granularity — the windowed pipeline passes its
    // window granule so cross-stage send-ahead fires per window instead of
    // per kSubChunk (0 = the classic sub-chunk streaming)
    if (step == 0 || step > kSubChunk) step = kSubChunk;
    using Claim = net::SinkTable::CmaClaim;
    size_t consumed = 0;
    // receiver-side watchdog witness: contiguous-prefix progress past its
    // deadline envelope marks the INBOUND edge SUSPECT — per-direction
    // verdict (the sender side owns failover; this side feeds the digest).
    // Disabled when rx and tx alias the same EdgeCounters (world == 2:
    // predecessor == successor): the rx clean-stream clear and the rx
    // whole-stream EWMA would stomp the TX ladder's state mid-escalation.
    const bool rx_wd =
        ctx.wd_factor > 0 && ctx.rx_edge && ctx.rx_edge != ctx.tx_edge;
    uint64_t rx_t0 = rx_wd ? now_ns() : 0;
    uint64_t last_prog_t = rx_t0;
    size_t last_prog = 0;
    bool rx_suspected = false;
    while (consumed < target) {
        if (consumed == 0) {
            // a pending same-host descriptor covers the whole payload: pull
            // it fused with the reduction on this thread
            auto t0 = now_ns();
            Claim c = ctx.rx.table().consume_cma(
                tag, target, elem_size,
                [&](const uint8_t *src, size_t lo, size_t n) {
                    on_data(src, lo, lo + n);
                    consumed = lo + n;
                    return !(ctx.should_abort && ctx.should_abort());
                },
                fill_if_unmapped);
            if (prof) prof->compute_ns += now_ns() - t0;
            if (c == Claim::kDone) break;
            if (c == Claim::kCancelled) return false;
            // kNone: no descriptor (yet) -> TCP path below re-polls;
            // kFailed: sender falls back to TCP streaming into the sink
        }
        size_t want = std::min(target, consumed + step);
        // bounded wait so master aborts / peer death interrupt the stream;
        // while nothing has streamed in, also wake the moment a claimable
        // same-host descriptor arrives (the loop claims it above)
        auto t0 = now_ns();
        bool cma_pending = false;
        size_t filled = ctx.rx.table().wait_filled(tag, want, 100, &cma_pending);
        if (prof) prof->wait_ns += now_ns() - t0;
        // sender-side stall poll from the RECEIVE loop: in a coupled ring
        // stall the op thread lives here, never long in the stage join —
        // the age-based verdict must run where the thread actually is
        if (wd && wd->on) wd_poll(*wd, ctx);
        if (rx_wd) {
            if (filled > last_prog) {
                last_prog = filled;
                last_prog_t = now_ns();
            } else if (!rx_suspected &&
                       now_ns() - last_prog_t >
                           wd_deadline_ns(ctx, ctx.rx_edge,
                                          std::min(step, target))) {
                rx_suspected = true;
                ctx.rx_edge->wd_suspects.fetch_add(1,
                                                   std::memory_order_relaxed);
                uint32_t zero = 0;
                ctx.rx_edge->wd_health.compare_exchange_strong(
                    zero,
                    static_cast<uint32_t>(telemetry::EdgeHealth::kSuspect),
                    std::memory_order_relaxed);
                if (telemetry::Recorder::inst().on())
                    telemetry::Recorder::inst().instant(
                        "watchdog", "rx_stall_suspect", "filled", filled,
                        "target", target, ctx.rx_endpoint, "seq",
                        ctx.op_seq);
            }
        }
        if (cma_pending) {
            if (consumed == 0) continue; // claim fused at the top of the loop
            // fused no longer possible (TCP bytes already consumed): a late
            // CMA stripe must still be filled + acked or both sides hang
            ctx.rx.table().fill_pending(tag);
            continue;
        }
        // consume only whole elements
        size_t usable = (filled / elem_size) * elem_size;
        if (usable > consumed) {
            t0 = now_ns();
            on_data(scratch + consumed, consumed, usable);
            if (prof) prof->compute_ns += now_ns() - t0;
            if (telemetry::win_trace_enabled() &&
                telemetry::Recorder::inst().on())
                telemetry::Recorder::inst().instant(
                    "window", "rx_slice", "lo", consumed, "hi", usable,
                    nullptr, "seq", ctx.op_seq);
            consumed = usable;
        }
        if (consumed >= target) break;
        if (ctx.should_abort && ctx.should_abort()) return false;
        if (!ctx.rx.alive()) return false;
    }
    if (rx_wd) {
        // inbound EWMA baseline: whole-stream achieved rate (includes the
        // fused compute — an under-estimate, i.e. a LONGER rx deadline;
        // the witness stays conservative)
        wd_update_rate(ctx.rx_edge, target, now_ns() - rx_t0);
        if (!rx_suspected) {
            // clean stream: a suspect verdict from a past op clears once
            // the edge delivers inside its envelope again
            uint32_t susp =
                static_cast<uint32_t>(telemetry::EdgeHealth::kSuspect);
            ctx.rx_edge->wd_health.compare_exchange_strong(
                susp, 0, std::memory_order_relaxed);
        }
    }
    return true;
}

} // namespace

Result ring_allreduce(RingCtx &ctx, const void *send, void *recv, size_t count) {
    const size_t esz = proto::dtype_size(ctx.dtype);
    const uint32_t world = ctx.world, rank = ctx.rank;
    if (world < 2) { // degenerate ring: the reduction is the input itself
        if (send != recv) memcpy(recv, send, count * esz);
        return Result::kOk;
    }
    auto *out = static_cast<uint8_t *>(recv);
    const bool quantized = ctx.quant != proto::QuantAlgo::kNone;
    const size_t qsz = quantized ? proto::dtype_size(ctx.q_dtype) : esz;
    const uint64_t base_tag = ctx.op_seq << 16;

    // working copy + abort restore (external backup preferred: lets the
    // caller also restore after a post-hoc abort verdict)
    std::vector<uint8_t> backup_local;
    const bool in_place = send == recv;
    // out-of-place unquantized: no upfront copy — stage-0 sends read straight
    // from `send` and the first accumulation of each chunk is a 3-operand
    // op(a=send, b=rx) into recv, so the full-buffer memcpy never happens
    const bool lazy = !in_place && !quantized;
    const auto *src8 = static_cast<const uint8_t *>(send);
    const uint8_t *restore_src;
    if (in_place) {
        if (ctx.backup) {
            restore_src = ctx.backup;
        } else {
            backup_local.resize(count * esz);
            memcpy(backup_local.data(), recv, count * esz);
            restore_src = backup_local.data();
        }
    } else {
        if (!lazy) memcpy(recv, send, count * esz);
        restore_src = src8;
    }
    // NOTE: purge_range below also unregisters any sink still registered for
    // this op's tags (meta tags included: kMetaBit < 0x10000), waiting out a
    // busy RX write first — so every fail() exit leaves no sink pointing into
    // the pooled scratch buffer. On the TX side it acks dropped CMA
    // descriptors so the peer's pending sends complete.
    // WAN pipelining gate: windowed TX + cross-stage send-ahead. Off on
    // same-host CMA links — there the fused whole-chunk descriptor claim is
    // already zero-copy and windowed frames would only fragment it — so the
    // loopback fast path is bit-for-bit the old one.
    const bool pipelined = pipeline_enabled() && !ctx.tx.cma_eligible();
    // multipath striping (docs/08): windows round-robin across this many
    // pool conns; 1 (default with a 1-conn pool) is the PR-8 pinned chain
    const size_t stripes = pipelined ? stripe_conns(ctx.tx.size()) : 1;
    // per-window quantization meta (PCCLT_QWIN_META=1): quantized stages
    // send one meta per window, which unlocks the quantized cross-stage
    // send-ahead below. Wire format is self-describing per frame, so this
    // gate only needs to agree with what THIS rank sends.
    const bool qwin = quantized && pipelined && qwin_enabled();
    // Cross-stage send-ahead state (unquantized + qwin quantized): handles
    // + contiguous byte progress of the NEXT stage's chunk, launched from
    // inside the current stage's accumulation callback as windows complete.
    std::vector<net::SendHandle> ahead_hs;
    size_t ahead_off = 0;
    // qwin send-ahead bookkeeping: next window of the NEXT stage's chunk
    // to quantize+ship, and that chunk's window grid
    uint32_t q_ahead_w = 0, q_ahead_qw = 0;
    // edge watchdog (docs/05): relay mode persists across ops via the
    // tx edge's health verdict while the CONFIRMED hold lasts
    Wd wd;
    wd_init(wd, ctx);
    // planned relay (docs/12 kRelayRing): the master stamped THIS rank as
    // the bottleneck sender — route the op through the acked relay plane
    // from the start, exactly the CONFIRMED detour minus the verdict. The
    // wire/dedupe/ack machinery is identical; only the accounting differs
    // (sched_relay_planned_bytes, not the emergency wd counters).
    if (ctx.planned_relay && ctx.relay_window) wd.relay_all = true;

    auto restore = [&] {
        // purge FIRST: stage-ahead all-gather sinks point into `recv`, and an
        // RX thread may still be writing through one — the restore memcpy
        // must not race with (or be overwritten by) such a write
        ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
        ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
        memcpy(recv, restore_src, count * esz);
    };
    auto fail = [&](bool conn_lost) {
        // in-flight send-ahead windows borrow spans of `recv`: they must
        // complete (or fail with their conn) before restore can overwrite it
        net::Link::wait_all(ahead_hs);
        // ...as do zombie direct sends the failover moved past
        net::Link::wait_all(wd.zombies);
        wd.zombies.clear();
        PLOG(kDebug) << "ring seq=" << ctx.op_seq << " failing (conn_lost="
                     << conn_lost << "), purging";
        restore();
        PLOG(kDebug) << "ring seq=" << ctx.op_seq << " fail restore done";
        return conn_lost ? Result::kConnectionLost : Result::kAborted;
    };

    // scratch buffers (pooled by the caller when possible). TWO slots,
    // alternating by stage: the next stage's sink is registered BEFORE this
    // stage's stream is consumed, so symmetric peers' data never races ahead
    // of registration into the queued-copy slow path (at most two stages can
    // be in flight: the peer cannot send stage s+2 before consuming our
    // stage s+1, which we only send after consuming stage s)
    size_t max_chunk = chunk_of(count, world, 0).n_elems;
    std::vector<uint8_t> scratch_local;
    std::vector<uint8_t> &rx_vec = ctx.scratch ? *ctx.scratch : scratch_local;
    if (rx_vec.size() < 2 * max_chunk * qsz) rx_vec.resize(2 * max_chunk * qsz);
    // qwin: TWO tx slots alternating by stage — the cross-stage send-ahead
    // quantizes stage s+1's windows while stage s's in-flight sends still
    // borrow its slot (joined at stage s's end, one stage before reuse)
    std::vector<uint8_t> tx_scratch(quantized ? (qwin ? 2 : 1) * max_chunk * qsz
                                              : 0);
    auto tx_scratch_at = [&](uint32_t seq) {
        return tx_scratch.data() + (qwin ? (seq % 2) * max_chunk * qsz : 0);
    };

    // Async TX via the conn's dedicated sender thread (or the same-host CMA
    // descriptor path). The payload span must stay untouched until the
    // handles complete, which stage-end join_tx guarantees.
    auto launch_tx = [&](uint64_t tag, std::vector<uint8_t> meta,
                         std::span<const uint8_t> payload) {
        std::vector<net::SendHandle> hs;
        if (!meta.empty()) hs.push_back(ctx.tx.send_meta(tag | kMetaBit, std::move(meta)));
        if (wd.relay_all &&
            wd_relay_span(ctx, tag, 0, payload.data(), payload.size()))
            return hs;  // confirmed edge: the whole chunk detours (metas
                        // stay direct — a degraded pipe still moves 100 B)
        auto ph = ctx.tx.send_async(tag, payload, ctx.op_seq);
        hs.insert(hs.end(), ph.begin(), ph.end());
        wd_track(wd, hs);
        return hs;
    };
    // Phase accumulators are always collected: the per-edge stall counter
    // consumes wait_ns unconditionally, and the clock pairs are vdso reads
    // around multi-hundred-µs slices. Only EVENT emission is gated, on the
    // recorder's relaxed atomic flag.
    auto &rec = telemetry::Recorder::inst();
    const bool trace = rec.on();
    // verbose per-window lifecycle tier (docs/09 attribution plane)
    const bool wtrace = trace && telemetry::win_trace_enabled();
    Prof prof;
    auto op_t0 = now_ns();
    auto join_tx = [&](std::vector<net::SendHandle> &hs) -> bool {
        auto t0 = now_ns();
        bool ok = wd.on ? wd_join(wd, ctx, hs) : net::Link::wait_all(hs);
        prof.join_ns += now_ns() - t0;
        // watchdog on: wd_poll already emitted win_drained (with age_ns)
        // when it erased each completed handle — emitting here too would
        // double-count every window in the verbose tier
        if (wtrace && !wd.on && rec.on())
            for (const auto &h : hs)
                if (h && h->done())  // drain observed at the stage join
                    rec.instant("window", "win_drained", "bytes",
                                h->span.size(), nullptr, 0, nullptr, "seq",
                                ctx.op_seq);
        return ok;
    };
    auto reg_sink = [&](uint64_t tag, uint8_t *base, size_t cap, bool consumer_pull) {
        auto t0 = now_ns();
        ctx.rx.table().register_sink(tag, base, cap, consumer_pull);
        prof.reg_ns += now_ns() - t0;
    };
    auto quant_timed = [&](auto &&fn) {
        auto t0 = now_ns();
        fn();
        prof.quant_ns += now_ns() - t0;
    };
    auto dequant_timed = [&](auto &&fn) {
        auto t0 = now_ns();
        fn();
        prof.dequant_ns += now_ns() - t0;
    };
    // send_ahead_windows bound to this op's state. The receiver's sink for
    // the next stage is already registered (reg_stage runs one stage
    // ahead); a frame that still races registration lands on the
    // queued-copy path, never lost.
    auto send_ahead = [&](uint64_t next_tag, const uint8_t *src,
                          size_t chunk_bytes, size_t wb, size_t prefix) {
        size_t pre = ahead_hs.size();
        send_ahead_windows(ctx.tx, next_tag, src, chunk_bytes, wb, prefix,
                           ctx.op_seq, &ahead_off, &ahead_hs, stripes,
                           ctx.tx_edge);
        wd_track(wd, ahead_hs, pre);
    };
    // striped stage-top submit: the whole chunk's windows leave NOW,
    // round-robin across the pool (stripes == 1 degenerates to the PR-8
    // single-conn in-order stream, which is cheaper than per-window
    // framing when there is nothing to stripe across)
    auto stage_top_windows = [&](uint64_t tag, const uint8_t *src,
                                 size_t total, size_t wb,
                                 std::vector<net::SendHandle> *hs) {
        if (stripes <= 1) {
            hs->push_back(ctx.tx.send_at(tag, 0, {src, total}, ctx.op_seq));
        } else {
            size_t off0 = 0;
            size_t pre = hs->size();
            send_ahead_windows(ctx.tx, tag, src, total, wb, total, ctx.op_seq,
                               &off0, hs, stripes, ctx.tx_edge);
            wd_track(wd, *hs, pre);
        }
    };
    // qwin cross-stage send-ahead: quantize + ship completed windows of
    // the NEXT quantized stage's chunk (the one accumulating right now)
    // from inside the current stage's consume callback — per-window meta
    // makes each window independently decodable, so the quantized ring
    // stops barriering at stage tops. `self_dq` keeps the AG-0 owner's
    // bit-parity self-dequantize riding the same (cache-hot) window.
    auto q_send_ahead = [&](uint64_t next_tag, uint8_t *src_f32,
                            size_t n_elems, uint8_t *qdst, size_t done_elems,
                            bool self_dq) {
        if (q_ahead_qw == 0)
            // the wire meta frame carries qw as one byte (qwin_encode):
            // clamp the grid so an extreme PCCLT_PIPELINE_WINDOW cannot
            // truncate it into a decode failure on the receiver
            q_ahead_qw = static_cast<uint32_t>(std::min<size_t>(
                pipeline_windows(n_elems * qsz), 255));
        auto &rec2 = telemetry::Recorder::inst();
        const bool wt = rec2.on() && telemetry::win_trace_enabled();
        size_t pre = ahead_hs.size();
        while (q_ahead_w < q_ahead_qw) {
            auto ws = chunk_of(n_elems, q_ahead_qw, q_ahead_w);
            if (ws.start_elem + ws.n_elems > done_elems) break;
            quant::Meta m;
            const uint64_t qt0 = now_ns();
            quant_timed([&] {
                m = quant::compute_meta(ctx.quant, ctx.q_dtype, ctx.dtype,
                                        src_f32 + ws.start_elem * esz,
                                        ws.n_elems);
                quant::quantize(m, src_f32 + ws.start_elem * esz,
                                qdst + ws.start_elem * qsz, ws.n_elems);
            });
            if (wt)
                rec2.span("window", "win_quant", qt0, now_ns(), "win",
                          q_ahead_w, "seq", ctx.op_seq);
            if (self_dq)
                dequant_timed([&] {
                    quant::dequantize_set(m, qdst + ws.start_elem * qsz,
                                          src_f32 + ws.start_elem * esz,
                                          ws.n_elems);
                });
            ahead_hs.push_back(ctx.tx.send_meta_at(
                next_tag | kMetaBit, q_ahead_w + 1,
                qwin_encode(q_ahead_qw, m)));
            striped_window_send(ctx.tx, next_tag, qdst + ws.start_elem * qsz,
                                ws.start_elem * qsz, ws.n_elems * qsz,
                                ctx.op_seq, stripes,
                                stripes > 1 ? ctx.tx_edge : nullptr,
                                &ahead_hs);
            ahead_off += ws.n_elems * qsz;
            ++q_ahead_w;
        }
        wd_track(wd, ahead_hs, pre);
    };
    // window granule for a chunk, 0 = no windowing (pipeline off or chunk
    // below the window floor)
    auto win_bytes = [&](size_t chunk_bytes) -> size_t {
        // relay mode sends whole stage chunks through the detour — the
        // cross-stage send-ahead would direct-send around it
        if (!pipelined || wd.relay_all) return 0;
        size_t w = pipeline_windows(chunk_bytes);
        if (w <= 1) return 0;
        return std::max(esz, chunk_bytes / w / esz * esz);
    };

    // stage sequence: reduce-scatter stages seq 0..world-2, then all-gather
    // stages seq world-1..2*world-3; each has a known tag, scratch slot and
    // receive size, so sinks can be registered one stage ahead
    const uint32_t rs_stages = world - 1;
    const uint32_t total_stages = 2 * (world - 1);
    auto scratch_at = [&](uint32_t seq) {
        return rx_vec.data() + (seq % 2) * max_chunk * qsz;
    };
    auto reg_stage = [&](uint32_t seq) {
        if (seq >= total_stages) return;
        if (seq < rs_stages) {
            // reduce-scatter: into the stage's scratch slot for streamed
            // accumulate (quantized: quantized bytes, meta arrives separately).
            // consumer_pull: same-host descriptors are claimed by the op
            // thread and reduced fused, skipping the scratch DRAM round-trip
            const uint32_t recv_c = (rank + world - seq - 1) % world;
            reg_sink(base_tag | seq, scratch_at(seq),
                     chunk_of(count, world, recv_c).n_elems * qsz, true);
            return;
        }
        const uint32_t s = seq - rs_stages;
        const uint64_t tag = base_tag | (0x4000u + s);
        const auto span = chunk_of(count, world, (rank + world - s) % world);
        if (quantized) {
            reg_sink(tag, scratch_at(seq), span.n_elems * qsz, true);
        } else {
            // zero-copy all-gather: the reduced chunk lands straight in the
            // result buffer. consumer_pull so the single copy runs on the OP
            // thread (mapped-region memcpy, or — via fill_if_unmapped — a
            // process_vm_readv pull into the sink), not on the RX thread
            // with a park/wake per slice. Registering one stage early is
            // safe: the peer only sends this chunk after it has consumed
            // (and for CMA, pulled) everything we previously sent from this
            // region.
            reg_sink(tag, out + span.start_elem * esz, span.n_elems * esz, true);
        }
    };
    reg_stage(0); // before ANY tx: inbound bytes always find a live sink

    // ---------------- phase 1: reduce-scatter ----------------
    auto rs_t0 = now_ns();
    for (uint32_t s = 0; s + 1 < world; ++s) {
        PLOG(kDebug) << "ring seq=" << ctx.op_seq << " rs stage " << s;
        const uint64_t stage_t0 = now_ns();
        const uint64_t stage_wait0 = prof.wait_ns;
        // scope-exit, not end-of-loop: a failing stage's early return must
        // still leave its (truncated) span — incident forensics need it
        ScopeExit stage_span{[&, s] {
            stage_attrib(ctx, prof, "rs_stage", s, stage_t0, stage_wait0);
        }};
        const uint64_t tag = base_tag | s;
        const uint32_t send_c = (rank + world - s) % world;
        const uint32_t recv_c = (rank + world - s - 1) % world;
        const auto send_span = chunk_of(count, world, send_c);
        const auto recv_span = chunk_of(count, world, recv_c);
        uint8_t *send_ptr = out + send_span.start_elem * esz;
        uint8_t *recv_ptr = out + recv_span.start_elem * esz;

        uint8_t *rx_scratch = scratch_at(s);
        std::vector<net::SendHandle> tx_job;
        if (quantized) {
            uint8_t *qbuf = tx_scratch_at(s);
            if (ahead_off > 0) {
                // qwin cross-stage send-ahead: this chunk's windows (and
                // their per-window metas) already left from inside stage
                // s-1's accumulation callback — the quantized ring no
                // longer barriers at the stage top
                tx_job = std::move(ahead_hs);
                ahead_hs.clear();
            } else {
                const size_t qw = pipelined && !wd.relay_all
                                      ? pipeline_windows(send_span.n_elems * qsz)
                                      : 1;
                if (qwin && qw > 1) {
                    // per-window meta stage-top launch (stage 0): same
                    // emission path as the send-ahead, everything complete
                    q_ahead_w = 0;
                    q_ahead_qw = 0;
                    q_send_ahead(tag, send_ptr, send_span.n_elems, qbuf,
                                 send_span.n_elems, /*self_dq=*/false);
                    tx_job = std::move(ahead_hs);
                    ahead_hs.clear();
                } else {
                    quant::Meta meta;
                    quant_timed([&] {
                        meta = quant::compute_meta(ctx.quant, ctx.q_dtype,
                                                   ctx.dtype, send_ptr,
                                                   send_span.n_elems);
                    });
                    if (qw <= 1) {
                        quant_timed([&] {
                            quant::quantize(meta, send_ptr, qbuf,
                                            send_span.n_elems);
                        });
                        tx_job = launch_tx(tag, meta.encode(),
                                           {qbuf, send_span.n_elems * qsz});
                    } else {
                        // per-window quantize→send overlap: window k+1
                        // quantizes while window k is on the wire. ONE meta
                        // for the whole chunk — wire format and numerics
                        // are unchanged; windows stripe across the pool.
                        tx_job.push_back(
                            ctx.tx.send_meta(tag | kMetaBit, meta.encode()));
                        for (size_t w = 0; w < qw; ++w) {
                            auto ws = chunk_of(send_span.n_elems,
                                               static_cast<uint32_t>(qw),
                                               static_cast<uint32_t>(w));
                            const uint64_t qt0 = now_ns();
                            quant_timed([&] {
                                quant::quantize(meta,
                                                send_ptr + ws.start_elem * esz,
                                                qbuf + ws.start_elem * qsz,
                                                ws.n_elems);
                            });
                            if (wtrace)
                                rec.span("window", "win_quant", qt0, now_ns(),
                                         "win", w, "seq", ctx.op_seq);
                            size_t pre = tx_job.size();
                            striped_window_send(
                                ctx.tx, tag, qbuf + ws.start_elem * qsz,
                                ws.start_elem * qsz, ws.n_elems * qsz,
                                ctx.op_seq, stripes,
                                stripes > 1 ? ctx.tx_edge : nullptr, &tx_job);
                            wd_track(wd, tx_job, pre);
                            if (wtrace)
                                rec.instant("window", "win_submit", "off",
                                            ws.start_elem * qsz, "bytes",
                                            ws.n_elems * qsz, nullptr, "seq",
                                            ctx.op_seq);
                        }
                    }
                }
            }
            ahead_off = 0;
            q_ahead_w = 0;
            q_ahead_qw = 0;
            ctx.tx_bytes += send_span.n_elems * qsz;

            // sink for THIS stage was registered a stage ahead; open the
            // next stage's sink before consuming, then take peer meta
            // (first frame pins legacy-vs-per-window mode; stragglers are
            // fetched lazily from inside the consume callback)
            reg_stage(s + 1);
            RxMeta ms;
            if (!fetch_meta(ctx, tag | kMetaBit, ms, 0)) {
                join_tx(tx_job);
                return fail(!ctx.rx.alive());
            }
            // qwin send-ahead target: the chunk accumulating here IS what
            // the next stage (RS s+1, or AG 0 at the phase boundary) sends
            const bool qa = qwin && !wd.relay_all;
            const uint64_t next_tag =
                s + 2 < world ? (base_tag | (s + 1)) : (base_tag | 0x4000u);
            const bool next_is_ag0 = s + 2 >= world;
            uint8_t *next_qbuf = tx_scratch_at(s + 1);
            size_t q_rx_step = 0;
            if (qa) {
                size_t nq = pipeline_windows(recv_span.n_elems * qsz);
                if (nq > 1)
                    q_rx_step = std::max(
                        qsz, recv_span.n_elems * qsz / nq / qsz * qsz);
            }
            bool meta_ok = true;
            bool ok = stream_recv(
                ctx, tag, recv_span.n_elems * qsz, qsz, rx_scratch,
                [&](const uint8_t *src, size_t lo, size_t hi) {
                    size_t e0 = lo / qsz, e1 = hi / qsz;
                    if (!for_each_meta_span(
                            ctx, tag | kMetaBit, ms, recv_span.n_elems, e0, e1,
                            [&](const quant::Meta &m, size_t a, size_t b) {
                                dequant_timed([&] {
                                    quant::dequantize_accumulate(
                                        m, ctx.op, src + (a - e0) * qsz,
                                        recv_ptr + a * esz, b - a);
                                });
                            }))
                        meta_ok = false;
                    if (qa && meta_ok)
                        q_send_ahead(next_tag, recv_ptr, recv_span.n_elems,
                                     next_qbuf, e1, next_is_ag0);
                },
                &prof, /*fill_if_unmapped=*/false, q_rx_step, &wd);
            ctx.rx.table().unregister_sink(tag);
            bool tx_ok = join_tx(tx_job);
            if (!ok || !meta_ok || !tx_ok)
                return fail(!ctx.rx.alive() || !ctx.tx.alive());
            ctx.rx_bytes += recv_span.n_elems * qsz;
        } else {
            // stage 0 sends the pristine chunk, readable from `send` directly;
            // later stages send chunks accumulated into recv at stage s-1
            const uint8_t *tx_ptr =
                (lazy && s == 0) ? src8 + send_span.start_elem * esz : send_ptr;
            const size_t send_bytes = send_span.n_elems * esz;
            if (ahead_off > 0) {
                // leading windows already left during stage s-1's accumulate
                tx_job = std::move(ahead_hs);
                ahead_hs.clear();
                if (ahead_off < send_bytes)
                    tx_job.push_back(ctx.tx.send_at(
                        tag, ahead_off, {tx_ptr + ahead_off,
                                         send_bytes - ahead_off},
                        ctx.op_seq));
            } else if (size_t swb = win_bytes(send_bytes); pipelined && swb) {
                // windowed stage-top, striped round-robin across the pool
                // (stripes == 1: the PR-8 single-conn in-order stream —
                // with the striped per-lane bucket, stripes no longer race
                // each other's pacing slots, so the old stall is gone)
                stage_top_windows(tag, tx_ptr, send_bytes, swb, &tx_job);
            } else {
                tx_job = launch_tx(tag, {}, {tx_ptr, send_bytes});
            }
            ahead_off = 0;
            ctx.tx_bytes += send_bytes;
            const uint8_t *local_ptr =
                lazy ? src8 + recv_span.start_elem * esz : recv_ptr;
            reg_stage(s + 1); // next stage's sink opens before we consume
            // the chunk accumulating here IS what the next stage (RS s+1,
            // or AG 0 at the phase boundary) sends — the ring invariant the
            // cross-stage send-ahead rides
            const size_t chunk_bytes = recv_span.n_elems * esz;
            const uint64_t next_tag =
                s + 2 < world ? (base_tag | (s + 1)) : (base_tag | 0x4000u);
            const size_t wb = win_bytes(chunk_bytes);
            bool ok = stream_recv(ctx, tag, chunk_bytes, esz, rx_scratch,
                                  [&](const uint8_t *src, size_t lo, size_t hi) {
                                      size_t e0 = lo / esz, e1 = hi / esz;
                                      kernels::accumulate3(ctx.dtype, ctx.op,
                                                           recv_ptr + e0 * esz,
                                                           local_ptr + e0 * esz,
                                                           src, e1 - e0);
                                      if (wb)
                                          send_ahead(next_tag, recv_ptr,
                                                     chunk_bytes, wb, hi);
                                  }, &prof, /*fill_if_unmapped=*/false, wb,
                                  &wd);
            ctx.rx.table().unregister_sink(tag);
            bool tx_ok = join_tx(tx_job);
            if (!ok || !tx_ok) return fail(!ctx.rx.alive() || !ctx.tx.alive());
            ctx.rx_bytes += chunk_bytes;
        }
    }

    // RS->AG boundary: zombie direct sends borrow spans of chunks the
    // all-gather is about to overwrite — they must drain (or fail with
    // their conn) first. Only the transition op pays this; later ops under
    // a held CONFIRMED verdict start in relay mode and leave no zombies.
    drain_zombies(ctx, wd.zombies);

    if (trace)
        rec.span("collective", "reduce_scatter", rs_t0, now_ns(), "seq",
                 ctx.op_seq, "bytes", (count * esz / world) * (world - 1));

    // ---------------- phase 2: all-gather ----------------
    // after reduce-scatter, this rank owns fully-reduced chunk (rank+1)%world.
    // Quantized path: own chunk is quantized ONCE; received chunks are
    // forwarded verbatim (no re-quantization), and the owner self-dequantizes
    // for bit parity (reference reduce.cpp:673-738).
    auto ag_t0 = now_ns();
    std::vector<uint8_t> fwd_q;      // quantized bytes to forward next stage
    std::vector<uint8_t> fwd_meta;   // encoded meta to forward (legacy mode)
    RxMeta fwd_ms;  // meta set received last stage: per-window chunks must
                    // forward per-window even when OUR env has qwin off
    for (uint32_t s = 0; s + 1 < world; ++s) {
        PLOG(kDebug) << "ring seq=" << ctx.op_seq << " ag stage " << s;
        const uint64_t stage_t0 = now_ns();
        const uint64_t stage_wait0 = prof.wait_ns;
        ScopeExit stage_span{[&, s] {
            stage_attrib(ctx, prof, "ag_stage", s, stage_t0, stage_wait0);
        }};
        const uint64_t tag = base_tag | (0x4000u + s);
        const uint32_t send_c = (rank + 1 + world - s) % world;
        const uint32_t recv_c = (rank + world - s) % world;
        const auto send_span = chunk_of(count, world, send_c);
        const auto recv_span = chunk_of(count, world, recv_c);
        uint8_t *send_ptr = out + send_span.start_elem * esz;
        uint8_t *recv_ptr = out + recv_span.start_elem * esz;
        uint8_t *rx_scratch = scratch_at(rs_stages + s);

        std::vector<net::SendHandle> tx_job;
        if (quantized) {
            bool launched = false;
            if (ahead_off > 0) {
                // qwin: this stage's windows (own chunk at s == 0 via the
                // last RS stage's accumulate, a forwarded chunk at s > 0
                // via the previous AG stage's forward-ahead) already left
                tx_job = std::move(ahead_hs);
                ahead_hs.clear();
                launched = true;
            } else if (s == 0) {
                const size_t qw =
                    pipelined && !wd.relay_all
                        ? pipeline_windows(send_span.n_elems * qsz)
                        : 1;
                if (qwin && qw > 1) {
                    // per-window meta stage-top launch; the owner's
                    // bit-parity self-dequantize rides each window
                    q_ahead_w = 0;
                    q_ahead_qw = 0;
                    q_send_ahead(tag, send_ptr, send_span.n_elems,
                                 tx_scratch_at(rs_stages), send_span.n_elems,
                                 /*self_dq=*/true);
                    tx_job = std::move(ahead_hs);
                    ahead_hs.clear();
                    launched = true;
                } else {
                    quant::Meta meta;
                    quant_timed([&] {
                        meta = quant::compute_meta(ctx.quant, ctx.q_dtype,
                                                   ctx.dtype, send_ptr,
                                                   send_span.n_elems);
                        fwd_q.resize(send_span.n_elems * qsz);
                    });
                    fwd_meta = meta.encode();
                    if (qw > 1) {
                        // per-window quantize→send overlap (one whole-chunk
                        // meta, wire format unchanged); windows stripe
                        // across the pool; the owner's bit-parity
                        // self-dequantize rides the same window while it is
                        // still cache-hot
                        tx_job.push_back(
                            ctx.tx.send_meta(tag | kMetaBit, fwd_meta));
                        for (size_t w = 0; w < qw; ++w) {
                            auto ws = chunk_of(send_span.n_elems,
                                               static_cast<uint32_t>(qw),
                                               static_cast<uint32_t>(w));
                            const uint64_t qt0 = now_ns();
                            quant_timed([&] {
                                quant::quantize(
                                    meta, send_ptr + ws.start_elem * esz,
                                    fwd_q.data() + ws.start_elem * qsz,
                                    ws.n_elems);
                            });
                            if (wtrace)
                                rec.span("window", "win_quant", qt0, now_ns(),
                                         "win", w, "seq", ctx.op_seq);
                            size_t pre = tx_job.size();
                            striped_window_send(
                                ctx.tx, tag,
                                fwd_q.data() + ws.start_elem * qsz,
                                ws.start_elem * qsz, ws.n_elems * qsz,
                                ctx.op_seq, stripes,
                                stripes > 1 ? ctx.tx_edge : nullptr, &tx_job);
                            wd_track(wd, tx_job, pre);
                            if (wtrace)
                                rec.instant("window", "win_submit", "off",
                                            ws.start_elem * qsz, "bytes",
                                            ws.n_elems * qsz, nullptr, "seq",
                                            ctx.op_seq);
                            dequant_timed([&] {
                                quant::dequantize_set(
                                    meta, fwd_q.data() + ws.start_elem * qsz,
                                    send_ptr + ws.start_elem * esz,
                                    ws.n_elems);
                            });
                        }
                        launched = true;
                    } else {
                        quant_timed([&] {
                            quant::quantize(meta, send_ptr, fwd_q.data(),
                                            send_span.n_elems);
                        });
                        dequant_timed([&] {
                            // bit parity: owner keeps what the others decode
                            quant::dequantize_set(meta, fwd_q.data(), send_ptr,
                                                  send_span.n_elems);
                        });
                    }
                }
            } else if (fwd_ms.per_window) {
                // stage-top forward of a chunk the previous hop quantized
                // with per-window metas (we did not forward-ahead — e.g.
                // relay mode): re-emit every meta frame, then the bytes.
                // The format is per-frame self-describing, so this works
                // whether or not OUR env opted into qwin.
                for (uint32_t w = 0; w < fwd_ms.qw; ++w)
                    tx_job.push_back(ctx.tx.send_meta_at(
                        tag | kMetaBit, w + 1,
                        qwin_encode(fwd_ms.qw, fwd_ms.get(w))));
                if (!(wd.relay_all &&
                      wd_relay_span(ctx, tag, 0, fwd_q.data(), fwd_q.size()))) {
                    size_t swb = win_bytes(fwd_q.size());
                    if (swb)
                        stage_top_windows(tag, fwd_q.data(), fwd_q.size(),
                                          swb, &tx_job);
                    else {
                        auto ph = ctx.tx.send_async(tag, fwd_q, ctx.op_seq);
                        tx_job.insert(tx_job.end(), ph.begin(), ph.end());
                        wd_track(wd, tx_job);
                    }
                }
                launched = true;
            }
            ahead_off = 0;
            q_ahead_w = 0;
            q_ahead_qw = 0;
            if (!launched) tx_job = launch_tx(tag, fwd_meta, fwd_q);
            ctx.tx_bytes += send_span.n_elems * qsz;

            reg_stage(rs_stages + s + 1); // sink for THIS stage opened earlier
            RxMeta ms;
            if (!fetch_meta(ctx, tag | kMetaBit, ms, 0)) {
                join_tx(tx_job);
                return fail(!ctx.rx.alive());
            }
            // forwarding stages must keep the raw quantized bytes: the fused
            // CMA path consumes from a bounce buffer, so mirror each slice
            // into rx_scratch (cache-hot, and only when actually forwarding)
            const bool fwd_needed = s + 2 < world;
            // qwin forward-ahead: re-emit received windows (and their meta
            // frames) toward the NEXT stage from inside this consume
            // callback — the all-gather's stage-top barrier disappears
            const bool fa = qwin && fwd_needed && !wd.relay_all;
            const uint64_t fnext_tag = base_tag | (0x4000u + s + 1);
            uint32_t fwd_w = 0, fwd_qw = 0;
            auto fwd_ahead = [&](size_t done_elems) {
                if (fwd_qw == 0) {
                    fwd_qw = ms.per_window
                                 ? ms.qw
                                 : static_cast<uint32_t>(pipeline_windows(
                                       recv_span.n_elems * qsz));
                    if (fwd_qw < 1) fwd_qw = 1;
                    if (!ms.per_window)
                        // legacy upstream: ONE whole-chunk meta forwards
                        // ahead of the windows, byte-identical re-encode
                        ahead_hs.push_back(ctx.tx.send_meta_at(
                            fnext_tag | kMetaBit, 0, ms.whole.encode()));
                }
                size_t pre = ahead_hs.size();
                while (fwd_w < fwd_qw) {
                    auto ws = chunk_of(recv_span.n_elems, fwd_qw, fwd_w);
                    if (ws.start_elem + ws.n_elems > done_elems) break;
                    if (ms.per_window)
                        ahead_hs.push_back(ctx.tx.send_meta_at(
                            fnext_tag | kMetaBit, fwd_w + 1,
                            qwin_encode(ms.qw, ms.get(fwd_w))));
                    striped_window_send(ctx.tx, fnext_tag,
                                        rx_scratch + ws.start_elem * qsz,
                                        ws.start_elem * qsz,
                                        ws.n_elems * qsz, ctx.op_seq, stripes,
                                        stripes > 1 ? ctx.tx_edge : nullptr,
                                        &ahead_hs);
                    ahead_off += ws.n_elems * qsz;
                    ++fwd_w;
                }
                wd_track(wd, ahead_hs, pre);
            };
            size_t q_rx_step = 0;
            if (fa) {
                size_t nq = pipeline_windows(recv_span.n_elems * qsz);
                if (nq > 1)
                    q_rx_step = std::max(
                        qsz, recv_span.n_elems * qsz / nq / qsz * qsz);
            }
            bool meta_ok = true;
            bool ok = stream_recv(
                ctx, tag, recv_span.n_elems * qsz, qsz, rx_scratch,
                [&](const uint8_t *src, size_t lo, size_t hi) {
                    if (fwd_needed && src != rx_scratch + lo)
                        memcpy(rx_scratch + lo, src, hi - lo);
                    size_t e0 = lo / qsz, e1 = hi / qsz;
                    if (!for_each_meta_span(
                            ctx, tag | kMetaBit, ms, recv_span.n_elems, e0, e1,
                            [&](const quant::Meta &m, size_t a, size_t b) {
                                dequant_timed([&] {
                                    quant::dequantize_set(
                                        m, src + (a - e0) * qsz,
                                        recv_ptr + a * esz, b - a);
                                });
                            }))
                        meta_ok = false;
                    if (fa && meta_ok) fwd_ahead(e1);
                },
                &prof, /*fill_if_unmapped=*/false, q_rx_step, &wd);
            ctx.rx.table().unregister_sink(tag);
            bool tx_ok = join_tx(tx_job);
            if (!ok || !meta_ok || !tx_ok)
                return fail(!ctx.rx.alive() || !ctx.tx.alive());
            ctx.rx_bytes += recv_span.n_elems * qsz;
            if (fwd_needed && ahead_off == 0) {
                // forward what we received on the next stage; the send buffer
                // must be distinct from rx_scratch (next stage writes into it)
                fwd_q.assign(rx_scratch, rx_scratch + recv_span.n_elems * qsz);
                if (!ms.per_window) fwd_meta = ms.whole.encode();
                fwd_ms = std::move(ms);
            }
        } else {
            const size_t send_bytes = send_span.n_elems * esz;
            if (ahead_off > 0) {
                tx_job = std::move(ahead_hs);
                ahead_hs.clear();
                if (ahead_off < send_bytes)
                    tx_job.push_back(ctx.tx.send_at(
                        tag, ahead_off, {send_ptr + ahead_off,
                                         send_bytes - ahead_off},
                        ctx.op_seq));
            } else if (size_t swb = win_bytes(send_bytes); pipelined && swb) {
                // windowed stage-top, striped (see the reduce-scatter note)
                stage_top_windows(tag, send_ptr, send_bytes, swb, &tx_job);
            } else {
                tx_job = launch_tx(tag, {}, {send_ptr, send_bytes});
            }
            ahead_off = 0;
            ctx.tx_bytes += send_bytes;
            // zero-copy sink was registered a stage ahead; open the next
            reg_stage(rs_stages + s + 1);
            const size_t chunk_bytes = recv_span.n_elems * esz;
            const uint64_t next_tag = base_tag | (0x4000u + s + 1);
            const size_t wb = s + 2 < world ? win_bytes(chunk_bytes) : 0;
            bool ok = stream_recv(ctx, tag, chunk_bytes, esz, recv_ptr,
                                  [&](const uint8_t *src, size_t lo, size_t hi) {
                                      // mapped-region consume: the copy into
                                      // the result IS the stage; TCP/pulled
                                      // bytes already landed in the sink
                                      if (src != recv_ptr + lo)
                                          kernels::copy_stream(recv_ptr + lo, src,
                                                               hi - lo);
                                      if (wb)
                                          send_ahead(next_tag, recv_ptr,
                                                     chunk_bytes, wb, hi);
                                  }, &prof, /*fill_if_unmapped=*/true, wb,
                                  &wd);
            ctx.rx.table().unregister_sink(tag);
            bool tx_ok = join_tx(tx_job);
            if (!ok || !tx_ok) return fail(!ctx.rx.alive() || !ctx.tx.alive());
            ctx.rx_bytes += chunk_bytes;
        }
    }

    if (ctx.op == proto::RedOp::kAvg)
        kernels::finalize_avg(ctx.dtype, recv, count, world);

    // zombie direct sends still borrow result-buffer spans; the purge also
    // needs their tags quiet before retiring the op's range
    drain_zombies(ctx, wd.zombies);
    wd_op_clean(wd, ctx);  // clean direct op: SUSPECT history drops to OK
    ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
    ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
    uint64_t op_t1 = now_ns();
    if (ctx.rx_edge)  // receiver wire-stall charged to the inbound edge
        ctx.rx_edge->stall_ns.fetch_add(prof.wait_ns, std::memory_order_relaxed);
    if (ctx.tele) {  // digest op sample (last-N phase timings)
        ctx.tele->record_op(ctx.op_seq, op_t1 - op_t0, prof.wait_ns);
        // attribution histograms (docs/09): the distributions /metrics
        // renders — per-op so the tail a coupled ring binds on is visible
        using telemetry::Phase;
        ctx.tele->record_phase(Phase::kOp, op_t1 - op_t0);
        ctx.tele->record_phase(Phase::kStall, prof.wait_ns);
        if (quantized) {
            ctx.tele->record_phase(Phase::kQuantize, prof.quant_ns);
            ctx.tele->record_phase(Phase::kDequantize, prof.dequant_ns);
        }
    }
    if (trace) {
        rec.span("collective", "all_gather", ag_t0, op_t1, "seq", ctx.op_seq,
                 "bytes", (count * esz / world) * (world - 1));
        rec.span("collective", "allreduce", op_t0, op_t1, "seq", ctx.op_seq,
                 "bytes", count * esz);
        rec.instant("collective", "wire_stall", "ns", prof.wait_ns, "seq",
                    ctx.op_seq);
        if (quantized) {
            rec.instant("collective", "quantize", "ns", prof.quant_ns, "seq",
                        ctx.op_seq);
            rec.instant("collective", "dequantize", "ns", prof.dequant_ns,
                        "seq", ctx.op_seq);
        }
    }
    if (prof_enabled())
        PLOG(kInfo) << "reduce prof: total=" << (op_t1 - op_t0) / 1e6
                    << "ms wait=" << prof.wait_ns / 1e6
                    << " compute=" << prof.compute_ns / 1e6
                    << " quant=" << prof.quant_ns / 1e6
                    << " dequant=" << prof.dequant_ns / 1e6
                    << " join=" << prof.join_ns / 1e6
                    << " reg=" << prof.reg_ns / 1e6;
    return Result::kOk;
}

Result ring_allgather(RingCtx &ctx, const void *send, void *recv, size_t count) {
    const size_t esz = proto::dtype_size(ctx.dtype);
    const uint32_t world = ctx.world, rank = ctx.rank;
    const size_t seg = count * esz;
    auto *out = static_cast<uint8_t *>(recv);
    auto slot = [&](uint32_t ring_rank) -> size_t {
        return ctx.slots.empty() ? ring_rank : ctx.slots[ring_rank];
    };
    // own segment lands at its slot regardless of world size
    if (out + slot(rank) * seg != send)
        kernels::copy_stream(out + slot(rank) * seg, send, seg);
    if (world < 2) return Result::kOk;

    const uint64_t base_tag = ctx.op_seq << 16;
    auto fail = [&](bool conn_lost) {
        // no restore: the gather only writes recv, and a retry overwrites
        // every segment — but sinks must not outlive this frame's buffers
        ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
        ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
        return conn_lost ? Result::kConnectionLost : Result::kAborted;
    };
    // stage s receives the segment of ring rank (rank - s - 1); register one
    // stage ahead so symmetric peers never race registration (same protocol
    // as the all-reduce's gather phase)
    auto reg_stage = [&](uint32_t s) {
        if (s >= world - 1) return;
        const uint32_t src_rank = (rank + world - s - 1) % world;
        ctx.rx.table().register_sink(base_tag | s, out + slot(src_rank) * seg,
                                     seg, /*consumer_pull=*/true);
    };
    reg_stage(0);
    auto &rec = telemetry::Recorder::inst();
    const bool trace = rec.on();
    Prof prof;
    auto op_t0 = now_ns();
    // same windowed cross-stage send-ahead as the all-reduce (docs/08):
    // the segment received at stage s is the one forwarded at stage s+1
    const bool pipelined = pipeline_enabled() && !ctx.tx.cma_eligible();
    const size_t stripes = pipelined ? stripe_conns(ctx.tx.size()) : 1;
    Wd wd;
    wd_init(wd, ctx);
    size_t wb = 0;
    if (pipelined && !wd.relay_all) {
        size_t w = pipeline_windows(seg);
        if (w > 1) wb = std::max(esz, seg / w / esz * esz);
    }
    std::vector<net::SendHandle> ahead_hs;
    size_t ahead_off = 0;
    for (uint32_t s = 0; s + 1 < world; ++s) {
        const uint64_t tag = base_tag | s;
        const uint64_t stage_t0 = now_ns();
        const uint64_t stage_wait0 = prof.wait_ns;
        ScopeExit stage_span{[&, s] {
            stage_attrib(ctx, prof, "gather_stage", s, stage_t0, stage_wait0);
        }};
        const uint32_t fwd_rank = (rank + world - s) % world; // own at s=0
        const uint8_t *src = s == 0 ? static_cast<const uint8_t *>(send)
                                    : out + slot(fwd_rank) * seg;
        std::vector<net::SendHandle> tx_job;
        if (wd.relay_all && ahead_off == 0 &&
            wd_relay_span(ctx, tag, 0, src, seg)) {
            // confirmed edge: the whole segment detours via the relay
        } else if (ahead_off > 0) {
            tx_job = std::move(ahead_hs);
            ahead_hs.clear();
            if (ahead_off < seg)
                tx_job.push_back(ctx.tx.send_at(tag, ahead_off,
                                                {src + ahead_off,
                                                 seg - ahead_off},
                                                ctx.op_seq));
        } else {
            if (wb) {
                // windowed stage-top, striped round-robin across the pool
                // (stripes == 1: the PR-8 single-conn in-order stream)
                if (stripes <= 1) {
                    tx_job.push_back(
                        ctx.tx.send_at(tag, 0, {src, seg}, ctx.op_seq));
                } else {
                    size_t off0 = 0;
                    send_ahead_windows(ctx.tx, tag, src, seg, wb, seg,
                                       ctx.op_seq, &off0, &tx_job, stripes,
                                       ctx.tx_edge);
                    wd_track(wd, tx_job);
                }
            } else {
                tx_job = ctx.tx.send_async(tag, {src, seg}, ctx.op_seq);
            }
        }
        ahead_off = 0;
        ctx.tx_bytes += seg;
        const uint32_t src_rank = (rank + world - s - 1) % world;
        uint8_t *dst = out + slot(src_rank) * seg;
        reg_stage(s + 1);
        const uint64_t next_tag = base_tag | (s + 1);
        const size_t swb = s + 2 < world ? wb : 0;
        bool ok = stream_recv(ctx, tag, seg, esz, dst,
                              [&](const uint8_t *p, size_t lo, size_t hi) {
                                  if (p != dst + lo)
                                      kernels::copy_stream(dst + lo, p, hi - lo);
                                  if (swb)
                                      send_ahead_windows(ctx.tx, next_tag, dst,
                                                         seg, swb, hi,
                                                         ctx.op_seq, &ahead_off,
                                                         &ahead_hs, stripes,
                                                         ctx.tx_edge);
                              }, &prof, /*fill_if_unmapped=*/true, swb, &wd);
        ctx.rx.table().unregister_sink(tag);
        bool tx_ok = wd.on ? wd_join(wd, ctx, tx_job)
                           : net::Link::wait_all(tx_job);
        if (!ok || !tx_ok) {
            net::Link::wait_all(ahead_hs); // next-stage windows borrow `out`
            net::Link::wait_all(wd.zombies);
            return fail(!ctx.rx.alive() || !ctx.tx.alive());
        }
        ctx.rx_bytes += seg;
    }
    // zombie sends borrow spans of `out`
    drain_zombies(ctx, wd.zombies);
    wd_op_clean(wd, ctx);
    ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
    ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
    uint64_t op_t1 = now_ns();
    if (ctx.rx_edge)
        ctx.rx_edge->stall_ns.fetch_add(prof.wait_ns, std::memory_order_relaxed);
    if (ctx.tele) {
        ctx.tele->record_op(ctx.op_seq, op_t1 - op_t0, prof.wait_ns);
        ctx.tele->record_phase(telemetry::Phase::kOp, op_t1 - op_t0);
        ctx.tele->record_phase(telemetry::Phase::kStall, prof.wait_ns);
    }
    if (trace) {
        rec.span("collective", "allgather", op_t0, op_t1, "seq", ctx.op_seq,
                 "bytes", static_cast<uint64_t>(world) * seg);
        rec.instant("collective", "wire_stall", "ns", prof.wait_ns, "seq",
                    ctx.op_seq);
    }
    return Result::kOk;
}

// ---- synthesized-schedule interpreter (docs/12) ----
// The executors below run the step programs sched::expand emits for the
// commence-stamped algorithm. Ring-edge algorithms (chain broadcast, a2a
// rotation, reduce-scatter) ride the full watchdog ladder; non-ring
// transfers (tree, butterfly, mesh) resolve links per step through the
// client-bound ctx.link_to / ctx.link_from and poll aborts via
// stream_recv exactly like the ring.
namespace {

// RAII swap of the ctx's inbound link so stream_recv / fetch_meta (which
// read ctx.rx) can run against an arbitrary peer of a synthesized
// schedule. Links are shared_ptr bundles, so the copies are cheap.
struct RxSwap {
    RingCtx &ctx;
    net::Link saved_rx;
    telemetry::EdgeCounters *saved_edge;
    const char *saved_ep;
    RxSwap(RingCtx &c, net::Link l, telemetry::EdgeCounters *edge = nullptr)
        : ctx(c), saved_rx(c.rx), saved_edge(c.rx_edge),
          saved_ep(c.rx_endpoint) {
        ctx.rx = std::move(l);
        ctx.rx_edge = edge;
        ctx.rx_endpoint = nullptr;
    }
    ~RxSwap() {
        ctx.rx = std::move(saved_rx);
        ctx.rx_edge = saved_edge;
        ctx.rx_endpoint = saved_ep;
    }
};

// Link to / from a ring index: ring neighbors reuse the op's pinned
// links (watchdog state and all); everything else goes through the
// client-bound resolvers. An invalid Link fails the op as kConnectionLost.
net::Link sched_link_to(RingCtx &ctx, uint32_t r) {
    if (ctx.world >= 2 && r == (ctx.rank + 1) % ctx.world) return ctx.tx;
    if (ctx.link_to) return ctx.link_to(r);
    return {};
}

net::Link sched_link_from(RingCtx &ctx, uint32_t r) {
    if (ctx.world >= 2 && r == (ctx.rank + ctx.world - 1) % ctx.world)
        return ctx.rx;
    if (ctx.link_from) return ctx.link_from(r, 30000);
    return {};
}

telemetry::EdgeCounters *sched_edge(RingCtx &ctx, uint32_t r) {
    return ctx.edge_of ? ctx.edge_of(r) : nullptr;
}

void note_steps(RingCtx &ctx, size_t n) {
    if (ctx.tele)
        ctx.tele->comm.sched_steps.fetch_add(n, std::memory_order_relaxed);
}

} // namespace

Result ring_reduce_scatter(RingCtx &ctx, const void *send, void *recv,
                           size_t count, uint64_t *out_offset,
                           uint64_t *out_count) {
    const size_t esz = proto::dtype_size(ctx.dtype);
    const uint32_t world = ctx.world, rank = ctx.rank;
    if (world < 2) {
        if (send != recv) memcpy(recv, send, count * esz);
        if (out_offset) *out_offset = 0;
        if (out_count) *out_count = count;
        return Result::kOk;
    }
    const bool quantized = ctx.quant != proto::QuantAlgo::kNone;
    const size_t qsz = quantized ? proto::dtype_size(ctx.q_dtype) : esz;
    const uint64_t base_tag = ctx.op_seq << 16;
    // the local fold is always a SUM: RedOp::kReduceScatter on the wire
    // marks the collective KIND, not an arithmetic operator
    const auto fold = proto::RedOp::kSum;

    // layout inside the (pooled) scratch: full-count accumulator, then two
    // alternating rx chunk slots, then (quantized) one tx staging slot
    const size_t max_chunk = chunk_of(count, world, 0).n_elems;
    const size_t work_b = count * esz;
    std::vector<uint8_t> scratch_local;
    std::vector<uint8_t> &buf = ctx.scratch ? *ctx.scratch : scratch_local;
    const size_t need =
        work_b + 2 * max_chunk * qsz + (quantized ? max_chunk * qsz : 0);
    if (buf.size() < need) buf.resize(need);
    uint8_t *working = buf.data();
    auto scratch_at = [&](uint32_t s) {
        return buf.data() + work_b + (s % 2) * max_chunk * qsz;
    };
    uint8_t *qtx =
        quantized ? buf.data() + work_b + 2 * max_chunk * qsz : nullptr;
    memcpy(working, send, work_b);

    Wd wd;
    wd_init(wd, ctx);
    auto fail = [&](bool conn_lost) {
        net::Link::wait_all(wd.zombies);
        wd.zombies.clear();
        ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
        ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
        return conn_lost ? Result::kConnectionLost : Result::kAborted;
    };

    auto &rec = telemetry::Recorder::inst();
    Prof prof;
    auto op_t0 = now_ns();
    note_steps(ctx, sched::expand(sched::Coll::kReduceScatter,
                                  sched::Algo::kRing, world, rank, 0, count)
                        .size());
    // same one-stage-ahead sink protocol as the all-reduce's RS phase
    auto reg_stage = [&](uint32_t s) {
        if (s + 1 >= world) return;
        const uint32_t rc = (rank + world - s - 1) % world;
        ctx.rx.table().register_sink(base_tag | s, scratch_at(s),
                                     chunk_of(count, world, rc).n_elems * qsz,
                                     /*consumer_pull=*/true);
    };
    reg_stage(0);
    for (uint32_t s = 0; s + 1 < world; ++s) {
        const uint64_t stage_t0 = now_ns();
        const uint64_t stage_wait0 = prof.wait_ns;
        ScopeExit stage_span{[&, s] {
            stage_attrib(ctx, prof, "rsc_stage", s, stage_t0, stage_wait0);
        }};
        const uint64_t tag = base_tag | s;
        const auto send_span = chunk_of(count, world, (rank + world - s) % world);
        const auto recv_span =
            chunk_of(count, world, (rank + world - s - 1) % world);
        uint8_t *send_ptr = working + send_span.start_elem * esz;

        std::vector<net::SendHandle> tx_job;
        if (quantized) {
            // an escalated earlier window still borrows qtx — drain before
            // the staging slot is overwritten (spans must stay valid)
            if (!wd.zombies.empty()) drain_zombies(ctx, wd.zombies);
            quant::Meta m = quant::compute_meta(ctx.quant, ctx.q_dtype,
                                                ctx.dtype, send_ptr,
                                                send_span.n_elems);
            quant::quantize(m, send_ptr, qtx, send_span.n_elems);
            tx_job.push_back(ctx.tx.send_meta(tag | kMetaBit, m.encode()));
            if (!(wd.relay_all &&
                  wd_relay_span(ctx, tag, 0, qtx, send_span.n_elems * qsz))) {
                auto ph = ctx.tx.send_async(tag, {qtx, send_span.n_elems * qsz},
                                            ctx.op_seq);
                tx_job.insert(tx_job.end(), ph.begin(), ph.end());
                wd_track(wd, tx_job);
            }
        } else {
            // sent chunks of `working` are never rewritten by later stages,
            // so fp32 zombie spans stay valid until the op-end drain
            if (!(wd.relay_all &&
                  wd_relay_span(ctx, tag, 0, send_ptr,
                                send_span.n_elems * esz))) {
                tx_job = ctx.tx.send_async(
                    tag, {send_ptr, send_span.n_elems * esz}, ctx.op_seq);
                wd_track(wd, tx_job);
            }
        }
        ctx.tx_bytes += send_span.n_elems * qsz;

        reg_stage(s + 1);
        uint8_t *acc = working + recv_span.start_elem * esz;
        bool meta_ok = true;
        bool ok;
        if (quantized) {
            RxMeta ms;
            if (!fetch_meta(ctx, tag | kMetaBit, ms, 0)) {
                wd.on ? wd_join(wd, ctx, tx_job) : net::Link::wait_all(tx_job);
                return fail(!ctx.rx.alive());
            }
            ok = stream_recv(
                ctx, tag, recv_span.n_elems * qsz, qsz, scratch_at(s),
                [&](const uint8_t *src, size_t lo, size_t hi) {
                    size_t e0 = lo / qsz, e1 = hi / qsz;
                    if (!for_each_meta_span(
                            ctx, tag | kMetaBit, ms, recv_span.n_elems, e0, e1,
                            [&](const quant::Meta &m2, size_t a, size_t b) {
                                quant::dequantize_accumulate(
                                    m2, fold, src + (a - e0) * qsz,
                                    acc + a * esz, b - a);
                            }))
                        meta_ok = false;
                },
                &prof, /*fill_if_unmapped=*/false, 0, &wd);
        } else {
            ok = stream_recv(
                ctx, tag, recv_span.n_elems * esz, esz, scratch_at(s),
                [&](const uint8_t *src, size_t lo, size_t hi) {
                    kernels::accumulate(ctx.dtype, fold, acc + lo, src,
                                        (hi - lo) / esz);
                },
                &prof, /*fill_if_unmapped=*/false, 0, &wd);
        }
        ctx.rx.table().unregister_sink(tag);
        bool tx_ok =
            wd.on ? wd_join(wd, ctx, tx_job) : net::Link::wait_all(tx_job);
        if (!ok || !meta_ok || !tx_ok)
            return fail(!ctx.rx.alive() || !ctx.tx.alive());
        ctx.rx_bytes += recv_span.n_elems * qsz;
    }

    // ownership follows ring position: after world-1 stages this rank
    // holds the fully-reduced chunk (rank+1) % world
    const auto own = chunk_of(count, world, (rank + 1) % world);
    memcpy(recv, working + own.start_elem * esz, own.n_elems * esz);
    if (out_offset) *out_offset = own.start_elem;
    if (out_count) *out_count = own.n_elems;

    drain_zombies(ctx, wd.zombies);
    wd_op_clean(wd, ctx);
    ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
    ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
    uint64_t op_t1 = now_ns();
    if (ctx.rx_edge)
        ctx.rx_edge->stall_ns.fetch_add(prof.wait_ns,
                                        std::memory_order_relaxed);
    if (ctx.tele) {
        ctx.tele->record_op(ctx.op_seq, op_t1 - op_t0, prof.wait_ns);
        ctx.tele->record_phase(telemetry::Phase::kOp, op_t1 - op_t0);
        ctx.tele->record_phase(telemetry::Phase::kStall, prof.wait_ns);
    }
    if (rec.on())
        rec.span("collective", "reduce_scatter_only", op_t0, op_t1, "seq",
                 ctx.op_seq, "bytes", count * esz);
    return Result::kOk;
}

Result run_broadcast(RingCtx &ctx, void *buf, size_t count) {
    const size_t esz = proto::dtype_size(ctx.dtype);
    const uint32_t world = ctx.world, rank = ctx.rank;
    if (world < 2) return Result::kOk;
    const uint32_t root = ctx.sched_root % world;
    const bool quantized = ctx.quant != proto::QuantAlgo::kNone;
    const size_t qsz = quantized ? proto::dtype_size(ctx.q_dtype) : esz;
    const size_t wire_b = count * qsz;
    const uint64_t base_tag = ctx.op_seq << 16;
    auto *out = static_cast<uint8_t *>(buf);
    // chain steps ride the ring's pinned edges; the star's fan-out/-in
    // edges resolve per step (no watchdog ladder — abort polls cover them)
    const bool chain = ctx.sched_algo != sched::Algo::kTree;

    const auto prog = sched::expand(sched::Coll::kBroadcast, ctx.sched_algo,
                                    world, rank, root, wire_b);
    note_steps(ctx, prog.size());
    const sched::Step *in_step = nullptr;
    std::vector<const sched::Step *> sends;
    for (const auto &st : prog) {
        if (st.kind == sched::Step::kSend) sends.push_back(&st);
        else in_step = &st;
    }

    std::vector<uint8_t> qloc(quantized ? wire_b : 0);
    auto &rec = telemetry::Recorder::inst();
    Prof prof;
    auto op_t0 = now_ns();
    auto finish = [&](Result res) {
        uint64_t op_t1 = now_ns();
        if (ctx.rx_edge)
            ctx.rx_edge->stall_ns.fetch_add(prof.wait_ns,
                                            std::memory_order_relaxed);
        if (res == Result::kOk && ctx.tele) {
            ctx.tele->record_op(ctx.op_seq, op_t1 - op_t0, prof.wait_ns);
            ctx.tele->record_phase(telemetry::Phase::kOp, op_t1 - op_t0);
            ctx.tele->record_phase(telemetry::Phase::kStall, prof.wait_ns);
        }
        if (res == Result::kOk && rec.on())
            rec.span("collective", "broadcast", op_t0, op_t1, "seq",
                     ctx.op_seq, "bytes", count * esz);
        return res;
    };

    if (!in_step) {
        // ---- root: quantize once, fan the payload out per step ----
        Wd wd;
        if (chain) wd_init(wd, ctx);  // chain egress is the ring tx edge
        quant::Meta m;
        std::vector<uint8_t> menc;
        if (quantized) {
            m = quant::compute_meta(ctx.quant, ctx.q_dtype, ctx.dtype, out,
                                    count);
            quant::quantize(m, out, qloc.data(), count);
            menc = m.encode();
        }
        const uint8_t *payload = quantized ? qloc.data() : out;
        std::vector<net::SendHandle> hs;
        std::vector<net::Link> used;
        for (const auto *st : sends) {
            net::Link l = sched_link_to(ctx, st->peer);
            if (!l.valid()) {
                net::Link::wait_all(hs);
                for (auto &u : used)
                    u.table().purge_range(base_tag, base_tag + 0x10000);
                return finish(Result::kConnectionLost);
            }
            const uint64_t tag = base_tag | st->xfer;
            if (quantized) hs.push_back(l.send_meta(tag | kMetaBit, menc));
            if (!(chain && wd.relay_all &&
                  wd_relay_span(ctx, tag, 0, payload, wire_b))) {
                size_t pre = hs.size();
                auto ph = l.send_async(tag, {payload, wire_b}, ctx.op_seq);
                hs.insert(hs.end(), ph.begin(), ph.end());
                if (chain) wd_track(wd, hs, pre);
            }
            used.push_back(std::move(l));
            ctx.tx_bytes += wire_b;
        }
        bool ok = wd.on ? wd_join(wd, ctx, hs) : net::Link::wait_all(hs);
        drain_zombies(ctx, wd.zombies);
        if (wd.on) wd_op_clean(wd, ctx);
        for (auto &u : used)
            u.table().purge_range(base_tag, base_tag + 0x10000);
        ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
        ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
        if (!ok)
            return finish(ctx.should_abort && ctx.should_abort()
                              ? Result::kAborted
                              : Result::kConnectionLost);
        if (quantized)
            // bit parity: the root keeps exactly what the receivers decode
            quant::requantize_self(m, out, count);
        return finish(Result::kOk);
    }

    // ---- receiver: star leaf, chain tail, or chain store-and-forward ----
    const sched::Step *fwd = sends.empty() ? nullptr : sends[0];
    const uint64_t in_tag = base_tag | in_step->xfer;
    const uint64_t out_tag = fwd ? (base_tag | fwd->xfer) : 0;
    const bool from_pred = in_step->peer == (rank + world - 1) % world;
    net::Link lf = sched_link_from(ctx, in_step->peer);
    if (!lf.valid()) return finish(Result::kConnectionLost);
    Wd wd;
    if (chain && fwd) wd_init(wd, ctx);  // forward egress is the ring tx
    uint8_t *sink = quantized ? qloc.data() : out;
    std::vector<net::SendHandle> tx_job;
    size_t fwd_off = 0;
    bool meta_ok = true;
    bool ok;
    RxMeta ms;
    {
        RxSwap swap(ctx, lf,
                    from_pred ? ctx.rx_edge : sched_edge(ctx, in_step->peer));
        ctx.rx.table().register_sink(in_tag, sink, wire_b,
                                     /*consumer_pull=*/true);
        if (quantized && !fetch_meta(ctx, in_tag | kMetaBit, ms, 0)) {
            ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
            return finish(ctx.rx.alive() ? Result::kAborted
                                         : Result::kConnectionLost);
        }
        if (quantized && fwd) {
            // forward the meta ahead of the bytes — deterministic re-encode
            // keeps every hop's frames byte-identical to the root's
            if (ms.per_window) {
                for (uint32_t w = 0; w < ms.qw; ++w)
                    tx_job.push_back(ctx.tx.send_meta_at(
                        out_tag | kMetaBit, w + 1,
                        qwin_encode(ms.qw, ms.get(w))));
            } else {
                tx_job.push_back(
                    ctx.tx.send_meta(out_tag | kMetaBit, ms.whole.encode()));
            }
        }
        ok = stream_recv(
            ctx, in_tag, wire_b, qsz, sink,
            [&](const uint8_t *src, size_t lo, size_t hi) {
                if (src != sink + lo) memcpy(sink + lo, src, hi - lo);
                if (fwd && !wd.relay_all) {
                    size_t pre = tx_job.size();
                    tx_job.push_back(ctx.tx.send_at(out_tag, lo,
                                                    {sink + lo, hi - lo},
                                                    ctx.op_seq));
                    if (wd.on) wd_track(wd, tx_job, pre);
                    fwd_off = hi;
                }
            },
            &prof, /*fill_if_unmapped=*/true, 0,
            (chain && fwd && wd.on) ? &wd : nullptr);
        if (ok && fwd && fwd_off < wire_b) {
            // relay mode (from the start, or flipped mid-stream): the
            // remaining span detours; receivers dedupe by byte range
            if (!(wd.relay_all &&
                  wd_relay_span(ctx, out_tag, fwd_off, sink + fwd_off,
                                wire_b - fwd_off))) {
                size_t pre = tx_job.size();
                tx_job.push_back(ctx.tx.send_at(
                    out_tag, fwd_off, {sink + fwd_off, wire_b - fwd_off},
                    ctx.op_seq));
                if (wd.on) wd_track(wd, tx_job, pre);
            }
        }
        ctx.rx.table().unregister_sink(in_tag);
        bool tx_ok =
            wd.on ? wd_join(wd, ctx, tx_job) : net::Link::wait_all(tx_job);
        if (ok && meta_ok && tx_ok && quantized) {
            // decode into the user buffer (metas are all fetched by now for
            // the legacy whole-chunk mode; per-window stragglers pull here)
            if (!for_each_meta_span(
                    ctx, in_tag | kMetaBit, ms, count, 0, count,
                    [&](const quant::Meta &m2, size_t a, size_t b) {
                        quant::dequantize_set(m2, sink + a * qsz,
                                              out + a * esz, b - a);
                    }))
                meta_ok = false;
        }
        ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
        if (!ok || !meta_ok || !tx_ok) {
            drain_zombies(ctx, wd.zombies);
            ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
            return finish(!ctx.rx.alive() || !ctx.tx.alive()
                              ? Result::kConnectionLost
                              : Result::kAborted);
        }
    }
    ctx.rx_bytes += wire_b;
    if (fwd) ctx.tx_bytes += wire_b;
    drain_zombies(ctx, wd.zombies);
    if (wd.on) wd_op_clean(wd, ctx);
    ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
    return finish(Result::kOk);
}

Result run_all_to_all(RingCtx &ctx, const void *send, void *recv,
                      size_t count_per_peer) {
    const size_t esz = proto::dtype_size(ctx.dtype);
    const uint32_t world = ctx.world, rank = ctx.rank;
    auto *out = static_cast<uint8_t *>(recv);
    const auto *src8 = static_cast<const uint8_t *>(send);
    auto slot = [&](uint32_t r) -> size_t {
        return ctx.slots.empty() ? r : ctx.slots[r];
    };
    const size_t bb = count_per_peer * esz;
    if (world < 2) {
        if (send != recv) memcpy(recv, send, bb);
        return Result::kOk;
    }
    const bool quantized = ctx.quant != proto::QuantAlgo::kNone;
    const size_t qsz = quantized ? proto::dtype_size(ctx.q_dtype) : esz;
    const size_t qb = count_per_peer * qsz;
    const uint64_t base_tag = ctx.op_seq << 16;
    // the rotation tag grid is (world-1)*world wide: past 64 ranks it
    // would cross the butterfly/meta tag space (algo_valid), so oversized
    // worlds deterministically run the mesh — every rank sees the same
    // commence world, so every rank takes the same branch
    sched::Algo algo = ctx.sched_algo;
    if (algo != sched::Algo::kMesh && world > 64) algo = sched::Algo::kMesh;
    const auto prog =
        sched::expand(sched::Coll::kAllToAll, algo, world, rank, 0,
                      static_cast<uint64_t>(qb) * world);
    note_steps(ctx, prog.size());
    auto &rec = telemetry::Recorder::inst();
    Prof prof;
    auto op_t0 = now_ns();
    auto finish = [&](Result res) {
        uint64_t op_t1 = now_ns();
        if (ctx.rx_edge)
            ctx.rx_edge->stall_ns.fetch_add(prof.wait_ns,
                                            std::memory_order_relaxed);
        if (res == Result::kOk && ctx.tele) {
            ctx.tele->record_op(ctx.op_seq, op_t1 - op_t0, prof.wait_ns);
            ctx.tele->record_phase(telemetry::Phase::kOp, op_t1 - op_t0);
            ctx.tele->record_phase(telemetry::Phase::kStall, prof.wait_ns);
        }
        if (res == Result::kOk && rec.on())
            rec.span("collective", "all_to_all", op_t0, op_t1, "seq",
                     ctx.op_seq, "bytes",
                     static_cast<uint64_t>(bb) * world);
        return res;
    };

    if (algo == sched::Algo::kMesh) {
        // ---- direct mesh: every block one hop over the full p2p mesh ----
        std::vector<uint8_t> qrx(quantized ? (size_t)world * qb : 0);
        std::vector<uint8_t> qtx(quantized ? (size_t)world * qb : 0);
        struct RxEnt {
            uint32_t peer;
            uint64_t tag;
            net::Link link;
        };
        std::vector<RxEnt> rx_ents;
        std::vector<net::Link> tx_links;
        std::vector<net::SendHandle> hs;
        auto purge_all = [&] {
            for (auto &e : rx_ents)
                e.link.table().purge_range(base_tag, base_tag + 0x10000);
            for (auto &l : tx_links)
                l.table().purge_range(base_tag, base_tag + 0x10000);
            ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
            ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
        };
        auto fail = [&](bool conn_lost) {
            net::Link::wait_all(hs);
            purge_all();
            return finish(conn_lost ? Result::kConnectionLost
                                    : Result::kAborted);
        };
        // register EVERY inbound sink before the first send leaves —
        // register_sink drains queued racing frames, so symmetric peers
        // firing immediately is safe
        for (const auto &st : prog) {
            if (st.kind != sched::Step::kRecv) continue;
            net::Link lf = sched_link_from(ctx, st.peer);
            if (!lf.valid()) return fail(true);
            uint8_t *sink = quantized ? qrx.data() + (size_t)st.peer * qb
                                      : out + slot(st.peer) * bb;
            lf.table().register_sink(base_tag | st.xfer, sink, qb,
                                     /*consumer_pull=*/true);
            rx_ents.push_back({st.peer, base_tag | st.xfer, std::move(lf)});
        }
        for (const auto &st : prog) {
            if (st.kind == sched::Step::kCopy) {
                if (out + slot(rank) * bb != src8 + slot(rank) * bb)
                    memcpy(out + slot(rank) * bb, src8 + slot(rank) * bb, bb);
                continue;
            }
            if (st.kind != sched::Step::kSend) continue;
            net::Link lt = sched_link_to(ctx, st.peer);
            if (!lt.valid()) return fail(true);
            const uint64_t tag = base_tag | st.xfer;
            const uint8_t *block = src8 + slot(st.peer) * bb;
            if (quantized) {
                // per-destination meta: each block is its own tensor slice
                uint8_t *q = qtx.data() + (size_t)st.peer * qb;
                quant::Meta m = quant::compute_meta(ctx.quant, ctx.q_dtype,
                                                    ctx.dtype, block,
                                                    count_per_peer);
                quant::quantize(m, block, q, count_per_peer);
                hs.push_back(lt.send_meta(tag | kMetaBit, m.encode()));
                auto ph = lt.send_async(tag, {q, qb}, ctx.op_seq);
                hs.insert(hs.end(), ph.begin(), ph.end());
            } else {
                auto ph = lt.send_async(tag, {block, bb}, ctx.op_seq);
                hs.insert(hs.end(), ph.begin(), ph.end());
            }
            tx_links.push_back(std::move(lt));
            ctx.tx_bytes += qb;
        }
        for (auto &e : rx_ents) {
            uint8_t *sink = quantized ? qrx.data() + (size_t)e.peer * qb
                                      : out + slot(e.peer) * bb;
            RxSwap swap(ctx, e.link, sched_edge(ctx, e.peer));
            bool meta_ok = true;
            bool ok = stream_recv(
                ctx, e.tag, qb, qsz, sink,
                [&](const uint8_t *p, size_t lo, size_t hi) {
                    if (p != sink + lo) memcpy(sink + lo, p, hi - lo);
                },
                &prof, /*fill_if_unmapped=*/true);
            if (ok && quantized) {
                RxMeta ms;
                if (fetch_meta(ctx, e.tag | kMetaBit, ms, 0)) {
                    meta_ok = for_each_meta_span(
                        ctx, e.tag | kMetaBit, ms, count_per_peer, 0,
                        count_per_peer,
                        [&](const quant::Meta &m, size_t a, size_t b) {
                            quant::dequantize_set(
                                m, sink + a * qsz,
                                out + slot(e.peer) * bb + a * esz, b - a);
                        });
                } else {
                    meta_ok = false;
                }
            }
            ctx.rx.table().unregister_sink(e.tag);
            if (!ok || !meta_ok) return fail(!ctx.rx.alive());
            ctx.rx_bytes += qb;
        }
        bool tx_ok = net::Link::wait_all(hs);
        hs.clear();
        purge_all();
        return finish(tx_ok ? Result::kOk : Result::kConnectionLost);
    }

    // ---- ring rotation: round r's block rides r store-and-forward hops
    // over the pinned ring edges (full watchdog ladder applies) ----
    std::vector<uint8_t> abuf(qb), bbuf(qb);
    Wd wd;
    wd_init(wd, ctx);
    auto fail = [&](bool conn_lost) {
        net::Link::wait_all(wd.zombies);
        wd.zombies.clear();
        ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
        ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
        return finish(conn_lost ? Result::kConnectionLost : Result::kAborted);
    };
    if (out + slot(rank) * bb != src8 + slot(rank) * bb)
        memcpy(out + slot(rank) * bb, src8 + slot(rank) * bb, bb);
    quant::Meta m_cur;
    for (uint32_t r = 1; r < world; ++r) {
        const uint32_t dst = (rank + r) % world;
        const uint8_t *block = src8 + slot(dst) * bb;
        if (quantized) {
            m_cur = quant::compute_meta(ctx.quant, ctx.q_dtype, ctx.dtype,
                                        block, count_per_peer);
            quant::quantize(m_cur, block, abuf.data(), count_per_peer);
        } else {
            memcpy(abuf.data(), block, bb);
        }
        for (uint32_t h = 1; h <= r; ++h) {
            // an escalated earlier hop's zombie still borrows the buffer
            // about to become this hop's sink — spans must stay valid
            if (!wd.zombies.empty()) drain_zombies(ctx, wd.zombies);
            const uint64_t tag =
                base_tag |
                (sched::kXferA2A + (r - 1) * world + (h - 1));
            ctx.rx.table().register_sink(tag, bbuf.data(), qb,
                                         /*consumer_pull=*/true);
            std::vector<net::SendHandle> tx_job;
            if (quantized)
                // the block's meta travels with it hop by hop
                // (deterministic re-encode: byte-identical frames)
                tx_job.push_back(
                    ctx.tx.send_meta(tag | kMetaBit, m_cur.encode()));
            if (!(wd.relay_all &&
                  wd_relay_span(ctx, tag, 0, abuf.data(), qb))) {
                auto ph = ctx.tx.send_async(tag, {abuf.data(), qb},
                                            ctx.op_seq);
                tx_job.insert(tx_job.end(), ph.begin(), ph.end());
                wd_track(wd, tx_job);
            }
            ctx.tx_bytes += qb;
            RxMeta ms;
            if (quantized && !fetch_meta(ctx, tag | kMetaBit, ms, 0)) {
                wd.on ? wd_join(wd, ctx, tx_job)
                      : net::Link::wait_all(tx_job);
                return fail(!ctx.rx.alive());
            }
            bool ok = stream_recv(
                ctx, tag, qb, qsz, bbuf.data(),
                [&](const uint8_t *p, size_t lo, size_t hi) {
                    if (p != bbuf.data() + lo)
                        memcpy(bbuf.data() + lo, p, hi - lo);
                },
                &prof, /*fill_if_unmapped=*/true, 0, &wd);
            ctx.rx.table().unregister_sink(tag);
            bool tx_ok = wd.on ? wd_join(wd, ctx, tx_job)
                               : net::Link::wait_all(tx_job);
            if (!ok || !tx_ok)
                return fail(!ctx.rx.alive() || !ctx.tx.alive());
            ctx.rx_bytes += qb;
            if (quantized) m_cur = ms.whole;
            std::swap(abuf, bbuf);
        }
        const uint32_t from = (rank + world - r) % world;
        if (quantized)
            quant::dequantize_set(m_cur, abuf.data(), out + slot(from) * bb,
                                  count_per_peer);
        else
            memcpy(out + slot(from) * bb, abuf.data(), bb);
    }
    drain_zombies(ctx, wd.zombies);
    wd_op_clean(wd, ctx);
    ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
    ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
    return finish(Result::kOk);
}

Result butterfly_allreduce(RingCtx &ctx, const void *send, void *recv,
                           size_t count) {
    const uint32_t world = ctx.world;
    // recursive doubling needs a power-of-two world; algo_valid gates the
    // planner, but a stale stamp must degrade, not corrupt
    if (world < 2 || (world & (world - 1)) != 0)
        return ring_allreduce(ctx, send, recv, count);
    const size_t esz = proto::dtype_size(ctx.dtype);
    const uint32_t rank = ctx.rank;
    auto *out = static_cast<uint8_t *>(recv);
    const bool quantized = ctx.quant != proto::QuantAlgo::kNone;
    const size_t qsz = quantized ? proto::dtype_size(ctx.q_dtype) : esz;
    const size_t wire_b = count * qsz;
    const uint64_t base_tag = ctx.op_seq << 16;

    // working copy + abort restore (same contract as the ring)
    std::vector<uint8_t> backup_local;
    const uint8_t *restore_src;
    if (send == recv) {
        if (ctx.backup) {
            restore_src = ctx.backup;
        } else {
            backup_local.assign(out, out + count * esz);
            restore_src = backup_local.data();
        }
    } else {
        memcpy(out, send, count * esz);
        restore_src = static_cast<const uint8_t *>(send);
    }

    std::vector<uint8_t> txb(wire_b), rxb(wire_b);
    std::vector<net::Link> used;
    auto fail = [&](bool conn_lost) {
        for (auto &l : used)
            l.table().purge_range(base_tag, base_tag + 0x10000);
        ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
        ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
        memcpy(out, restore_src, count * esz);
        return conn_lost ? Result::kConnectionLost : Result::kAborted;
    };
    auto &rec = telemetry::Recorder::inst();
    Prof prof;
    auto op_t0 = now_ns();
    note_steps(ctx, sched::expand(sched::Coll::kAllReduce,
                                  sched::Algo::kButterfly, world, rank, 0,
                                  wire_b)
                        .size());
    uint32_t k = 0;
    for (uint32_t bit = 1; bit < world; bit <<= 1, ++k) {
        const uint32_t partner = rank ^ bit;
        const uint64_t tag = base_tag | (sched::kXferFly + k);
        net::Link lt = sched_link_to(ctx, partner);
        net::Link lf = sched_link_from(ctx, partner);
        if (!lt.valid() || !lf.valid()) return fail(true);
        used.push_back(lt);
        used.push_back(lf);
        std::vector<net::SendHandle> hs;
        if (quantized) {
            // both partners quantize their partial, exchange, then fold the
            // SAME two quantized buffers in rank order — bit-identical
            // results on both sides of every round
            quant::Meta mine = quant::compute_meta(ctx.quant, ctx.q_dtype,
                                                   ctx.dtype, out, count);
            quant::quantize(mine, out, txb.data(), count);
            lf.table().register_sink(tag, rxb.data(), wire_b,
                                     /*consumer_pull=*/true);
            hs.push_back(lt.send_meta(tag | kMetaBit, mine.encode()));
            auto ph = lt.send_async(tag, {txb.data(), wire_b}, ctx.op_seq);
            hs.insert(hs.end(), ph.begin(), ph.end());
            RxMeta ms;
            bool ok;
            {
                RxSwap swap(ctx, lf, sched_edge(ctx, partner));
                ok = stream_recv(
                    ctx, tag, wire_b, qsz, rxb.data(),
                    [&](const uint8_t *p, size_t lo, size_t hi) {
                        if (p != rxb.data() + lo)
                            memcpy(rxb.data() + lo, p, hi - lo);
                    },
                    &prof, /*fill_if_unmapped=*/true);
                if (ok && !fetch_meta(ctx, tag | kMetaBit, ms, 0)) ok = false;
                ctx.rx.table().unregister_sink(tag);
            }
            bool tx_ok = net::Link::wait_all(hs);
            if (!ok || !tx_ok) return fail(!lf.alive() || !lt.alive());
            const bool low = rank < partner;
            quant::dequantize_set(low ? mine : ms.whole,
                                  low ? txb.data() : rxb.data(), out, count);
            quant::dequantize_accumulate(low ? ms.whole : mine, ctx.op,
                                         low ? rxb.data() : txb.data(), out,
                                         count);
        } else {
            // x op y is commutative per element: both partners compute the
            // same fold bit-for-bit without any ordering protocol
            memcpy(txb.data(), out, wire_b);
            lf.table().register_sink(tag, rxb.data(), wire_b,
                                     /*consumer_pull=*/true);
            auto ph = lt.send_async(tag, {txb.data(), wire_b}, ctx.op_seq);
            hs.insert(hs.end(), ph.begin(), ph.end());
            bool ok;
            {
                RxSwap swap(ctx, lf, sched_edge(ctx, partner));
                ok = stream_recv(
                    ctx, tag, wire_b, esz, rxb.data(),
                    [&](const uint8_t *p, size_t lo, size_t hi) {
                        if (p != rxb.data() + lo)
                            memcpy(rxb.data() + lo, p, hi - lo);
                    },
                    &prof, /*fill_if_unmapped=*/true);
                ctx.rx.table().unregister_sink(tag);
            }
            bool tx_ok = net::Link::wait_all(hs);
            if (!ok || !tx_ok) return fail(!lf.alive() || !lt.alive());
            kernels::accumulate(ctx.dtype, ctx.op, out, rxb.data(), count);
        }
        ctx.tx_bytes += wire_b;
        ctx.rx_bytes += wire_b;
    }
    if (ctx.op == proto::RedOp::kAvg)
        kernels::finalize_avg(ctx.dtype, out, count, world);
    for (auto &l : used) l.table().purge_range(base_tag, base_tag + 0x10000);
    ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
    ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
    uint64_t op_t1 = now_ns();
    if (ctx.rx_edge)
        ctx.rx_edge->stall_ns.fetch_add(prof.wait_ns,
                                        std::memory_order_relaxed);
    if (ctx.tele) {
        ctx.tele->record_op(ctx.op_seq, op_t1 - op_t0, prof.wait_ns);
        ctx.tele->record_phase(telemetry::Phase::kOp, op_t1 - op_t0);
        ctx.tele->record_phase(telemetry::Phase::kStall, prof.wait_ns);
    }
    if (rec.on())
        rec.span("collective", "butterfly_allreduce", op_t0, op_t1, "seq",
                 ctx.op_seq, "bytes", count * esz);
    return Result::kOk;
}

} // namespace pcclt::reduce
