#include "reduce.hpp"

#include <cstdlib>
#include <cstring>
#include <vector>

#include "kernels.hpp"
#include "log.hpp"
#include "quantize.hpp"
#include "telemetry.hpp"

namespace pcclt::reduce {

namespace {

// PCCLT_PROF=1 → log per-op phase timings. A thin consumer of the
// telemetry recorder's clock + accumulators (telemetry.hpp) — the same
// numbers land in the flight-recorder event stream when PCCLT_TRACE is on.
bool prof_enabled() {
    static const bool on = [] {
        const char *e = std::getenv("PCCLT_PROF");
        return e && e[0] == '1';
    }();
    return on;
}

// Per-op phase accumulators (ns). wait_ns is wire-stall: time the op thread
// spent blocked on bytes that had not arrived yet — the per-edge stall
// counter and the "wire_stall" trace event both read from it.
struct Prof {
    uint64_t wait_ns = 0, compute_ns = 0, join_ns = 0, reg_ns = 0,
             quant_ns = 0;
};

using telemetry::now_ns;

constexpr uint64_t kMetaBit = 0x8000;
constexpr size_t kSubChunk = 2 << 20; // streaming granularity (bytes)

// ---- pipelined data plane (docs/08 "windowed pipeline") ----
// Each ring stage's payload is split into up to PCCLT_PIPELINE_WINDOW
// in-flight windows per edge: quantize of window k+1 overlaps the send of
// window k, and (unquantized) the NEXT stage's send of window k launches
// the moment window k of this stage's chunk finishes accumulating — so a
// fat-long-pipe link pays the per-stage one-way delay once per pipeline
// fill instead of once per stage. Env is re-read per op (tests flip it at
// runtime); windows never shrink below PCCLT_PIPELINE_MIN_BYTES, so small
// payloads degrade to the exact single-window behavior of old.
size_t env_size(const char *name, long long dflt) {
    if (const char *e = std::getenv(name)) {
        long long v = atoll(e);
        if (v >= 0) return static_cast<size_t>(v);
    }
    return static_cast<size_t>(dflt);
}

bool pipeline_enabled() {
    const char *e = std::getenv("PCCLT_PIPELINE");
    return !(e && e[0] == '0');
}

size_t pipeline_windows(size_t bytes) {
    size_t w = env_size("PCCLT_PIPELINE_WINDOW", 4);
    size_t min_b = env_size("PCCLT_PIPELINE_MIN_BYTES", 256 << 10);
    if (min_b == 0) min_b = 1;
    w = std::min(w, bytes / min_b);
    return std::max<size_t>(1, w);
}

// Launch completed windows [*ahead_off, prefix) of the NEXT stage's send
// chunk (`src`, `total` bytes, granule `wb`) — called from inside a
// stream_recv accumulation callback, so the next stage's first bytes are
// on the wire while this stage's later windows are still arriving. A
// sub-window tail is absorbed into the last window. The one place this
// arithmetic lives; both ring_allreduce and ring_allgather ride it.
void send_ahead_windows(net::Link &tx, uint64_t tag, const uint8_t *src,
                        size_t total, size_t wb, size_t prefix, size_t rot,
                        size_t *ahead_off, std::vector<net::SendHandle> *hs) {
    while (*ahead_off < total) {
        size_t seg = std::min(wb, total - *ahead_off);
        if (total - (*ahead_off + seg) < wb) seg = total - *ahead_off;
        if (prefix < *ahead_off + seg) break;
        hs->push_back(tx.send_at(tag, *ahead_off, {src + *ahead_off, seg}, rot));
        *ahead_off += seg;
    }
}

struct ChunkSpan {
    size_t start_elem, n_elems;
};

ChunkSpan chunk_of(size_t count, uint32_t world, uint32_t c) {
    size_t base = count / world, rem = count % world;
    size_t start = c * base + std::min<size_t>(c, rem);
    size_t len = base + (c < rem ? 1 : 0);
    return {start, len};
}

// Wait until `target` bytes for `tag` arrived, reducing/consuming via
// `on_data(src, lo, hi)` in slices aligned to `elem_size`. Two transports:
//  - same-host fused pull (registered consumer_pull): the peer's bytes are
//    process_vm_readv'd in cache-sized slices on THIS thread and reduced
//    while hot — no scratch round-trip through DRAM;
//  - TCP streaming: the RX thread fills `scratch` (the registered sink) and
//    slices are reduced from there as the contiguous prefix grows.
// Returns false on abort/conn loss.
bool stream_recv(RingCtx &ctx, uint64_t tag, size_t target, size_t elem_size,
                 const uint8_t *scratch,
                 const std::function<void(const uint8_t *src, size_t lo, size_t hi)> &on_data,
                 Prof *prof = nullptr, bool fill_if_unmapped = false,
                 size_t step = 0) {
    // step: wait/consume granularity — the windowed pipeline passes its
    // window granule so cross-stage send-ahead fires per window instead of
    // per kSubChunk (0 = the classic sub-chunk streaming)
    if (step == 0 || step > kSubChunk) step = kSubChunk;
    using Claim = net::SinkTable::CmaClaim;
    size_t consumed = 0;
    while (consumed < target) {
        if (consumed == 0) {
            // a pending same-host descriptor covers the whole payload: pull
            // it fused with the reduction on this thread
            auto t0 = now_ns();
            Claim c = ctx.rx.table().consume_cma(
                tag, target, elem_size,
                [&](const uint8_t *src, size_t lo, size_t n) {
                    on_data(src, lo, lo + n);
                    consumed = lo + n;
                    return !(ctx.should_abort && ctx.should_abort());
                },
                fill_if_unmapped);
            if (prof) prof->compute_ns += now_ns() - t0;
            if (c == Claim::kDone) break;
            if (c == Claim::kCancelled) return false;
            // kNone: no descriptor (yet) -> TCP path below re-polls;
            // kFailed: sender falls back to TCP streaming into the sink
        }
        size_t want = std::min(target, consumed + step);
        // bounded wait so master aborts / peer death interrupt the stream;
        // while nothing has streamed in, also wake the moment a claimable
        // same-host descriptor arrives (the loop claims it above)
        auto t0 = now_ns();
        bool cma_pending = false;
        size_t filled = ctx.rx.table().wait_filled(tag, want, 100, &cma_pending);
        if (prof) prof->wait_ns += now_ns() - t0;
        if (cma_pending) {
            if (consumed == 0) continue; // claim fused at the top of the loop
            // fused no longer possible (TCP bytes already consumed): a late
            // CMA stripe must still be filled + acked or both sides hang
            ctx.rx.table().fill_pending(tag);
            continue;
        }
        // consume only whole elements
        size_t usable = (filled / elem_size) * elem_size;
        if (usable > consumed) {
            t0 = now_ns();
            on_data(scratch + consumed, consumed, usable);
            if (prof) prof->compute_ns += now_ns() - t0;
            consumed = usable;
        }
        if (consumed >= target) break;
        if (ctx.should_abort && ctx.should_abort()) return false;
        if (!ctx.rx.alive()) return false;
    }
    return true;
}

} // namespace

Result ring_allreduce(RingCtx &ctx, const void *send, void *recv, size_t count) {
    const size_t esz = proto::dtype_size(ctx.dtype);
    const uint32_t world = ctx.world, rank = ctx.rank;
    if (world < 2) { // degenerate ring: the reduction is the input itself
        if (send != recv) memcpy(recv, send, count * esz);
        return Result::kOk;
    }
    auto *out = static_cast<uint8_t *>(recv);
    const bool quantized = ctx.quant != proto::QuantAlgo::kNone;
    const size_t qsz = quantized ? proto::dtype_size(ctx.q_dtype) : esz;
    const uint64_t base_tag = ctx.op_seq << 16;

    // working copy + abort restore (external backup preferred: lets the
    // caller also restore after a post-hoc abort verdict)
    std::vector<uint8_t> backup_local;
    const bool in_place = send == recv;
    // out-of-place unquantized: no upfront copy — stage-0 sends read straight
    // from `send` and the first accumulation of each chunk is a 3-operand
    // op(a=send, b=rx) into recv, so the full-buffer memcpy never happens
    const bool lazy = !in_place && !quantized;
    const auto *src8 = static_cast<const uint8_t *>(send);
    const uint8_t *restore_src;
    if (in_place) {
        if (ctx.backup) {
            restore_src = ctx.backup;
        } else {
            backup_local.resize(count * esz);
            memcpy(backup_local.data(), recv, count * esz);
            restore_src = backup_local.data();
        }
    } else {
        if (!lazy) memcpy(recv, send, count * esz);
        restore_src = src8;
    }
    // NOTE: purge_range below also unregisters any sink still registered for
    // this op's tags (meta tags included: kMetaBit < 0x10000), waiting out a
    // busy RX write first — so every fail() exit leaves no sink pointing into
    // the pooled scratch buffer. On the TX side it acks dropped CMA
    // descriptors so the peer's pending sends complete.
    // WAN pipelining gate: windowed TX + cross-stage send-ahead. Off on
    // same-host CMA links — there the fused whole-chunk descriptor claim is
    // already zero-copy and windowed frames would only fragment it — so the
    // loopback fast path is bit-for-bit the old one.
    const bool pipelined = pipeline_enabled() && !ctx.tx.cma_eligible();
    // Cross-stage send-ahead state (unquantized): handles + contiguous byte
    // progress of the NEXT stage's chunk, launched from inside the current
    // stage's accumulation callback as windows complete.
    std::vector<net::SendHandle> ahead_hs;
    size_t ahead_off = 0;

    auto restore = [&] {
        // purge FIRST: stage-ahead all-gather sinks point into `recv`, and an
        // RX thread may still be writing through one — the restore memcpy
        // must not race with (or be overwritten by) such a write
        ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
        ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
        memcpy(recv, restore_src, count * esz);
    };
    auto fail = [&](bool conn_lost) {
        // in-flight send-ahead windows borrow spans of `recv`: they must
        // complete (or fail with their conn) before restore can overwrite it
        net::Link::wait_all(ahead_hs);
        PLOG(kDebug) << "ring seq=" << ctx.op_seq << " failing (conn_lost="
                     << conn_lost << "), purging";
        restore();
        PLOG(kDebug) << "ring seq=" << ctx.op_seq << " fail restore done";
        return conn_lost ? Result::kConnectionLost : Result::kAborted;
    };

    // scratch buffers (pooled by the caller when possible). TWO slots,
    // alternating by stage: the next stage's sink is registered BEFORE this
    // stage's stream is consumed, so symmetric peers' data never races ahead
    // of registration into the queued-copy slow path (at most two stages can
    // be in flight: the peer cannot send stage s+2 before consuming our
    // stage s+1, which we only send after consuming stage s)
    size_t max_chunk = chunk_of(count, world, 0).n_elems;
    std::vector<uint8_t> scratch_local;
    std::vector<uint8_t> &rx_vec = ctx.scratch ? *ctx.scratch : scratch_local;
    if (rx_vec.size() < 2 * max_chunk * qsz) rx_vec.resize(2 * max_chunk * qsz);
    std::vector<uint8_t> tx_scratch(quantized ? max_chunk * qsz : 0);

    // Async TX via the conn's dedicated sender thread (or the same-host CMA
    // descriptor path). The payload span must stay untouched until the
    // handles complete, which stage-end join_tx guarantees.
    auto launch_tx = [&](uint64_t tag, std::vector<uint8_t> meta,
                         std::span<const uint8_t> payload) {
        std::vector<net::SendHandle> hs;
        if (!meta.empty()) hs.push_back(ctx.tx.send_meta(tag | kMetaBit, std::move(meta)));
        auto ph = ctx.tx.send_async(tag, payload, ctx.op_seq);
        hs.insert(hs.end(), ph.begin(), ph.end());
        return hs;
    };
    // Phase accumulators are always collected: the per-edge stall counter
    // consumes wait_ns unconditionally, and the clock pairs are vdso reads
    // around multi-hundred-µs slices. Only EVENT emission is gated, on the
    // recorder's relaxed atomic flag.
    auto &rec = telemetry::Recorder::inst();
    const bool trace = rec.on();
    Prof prof;
    auto op_t0 = now_ns();
    auto join_tx = [&](const std::vector<net::SendHandle> &hs) -> bool {
        auto t0 = now_ns();
        bool ok = net::Link::wait_all(hs);
        prof.join_ns += now_ns() - t0;
        return ok;
    };
    auto reg_sink = [&](uint64_t tag, uint8_t *base, size_t cap, bool consumer_pull) {
        auto t0 = now_ns();
        ctx.rx.table().register_sink(tag, base, cap, consumer_pull);
        prof.reg_ns += now_ns() - t0;
    };
    auto quant_timed = [&](auto &&fn) {
        auto t0 = now_ns();
        fn();
        prof.quant_ns += now_ns() - t0;
    };
    // send_ahead_windows bound to this op's state. The receiver's sink for
    // the next stage is already registered (reg_stage runs one stage
    // ahead); a frame that still races registration lands on the
    // queued-copy path, never lost.
    auto send_ahead = [&](uint64_t next_tag, const uint8_t *src,
                          size_t chunk_bytes, size_t wb, size_t prefix) {
        send_ahead_windows(ctx.tx, next_tag, src, chunk_bytes, wb, prefix,
                           ctx.op_seq, &ahead_off, &ahead_hs);
    };
    // window granule for a chunk, 0 = no windowing (pipeline off or chunk
    // below the window floor)
    auto win_bytes = [&](size_t chunk_bytes) -> size_t {
        if (!pipelined) return 0;
        size_t w = pipeline_windows(chunk_bytes);
        if (w <= 1) return 0;
        return std::max(esz, chunk_bytes / w / esz * esz);
    };

    // stage sequence: reduce-scatter stages seq 0..world-2, then all-gather
    // stages seq world-1..2*world-3; each has a known tag, scratch slot and
    // receive size, so sinks can be registered one stage ahead
    const uint32_t rs_stages = world - 1;
    const uint32_t total_stages = 2 * (world - 1);
    auto scratch_at = [&](uint32_t seq) {
        return rx_vec.data() + (seq % 2) * max_chunk * qsz;
    };
    auto reg_stage = [&](uint32_t seq) {
        if (seq >= total_stages) return;
        if (seq < rs_stages) {
            // reduce-scatter: into the stage's scratch slot for streamed
            // accumulate (quantized: quantized bytes, meta arrives separately).
            // consumer_pull: same-host descriptors are claimed by the op
            // thread and reduced fused, skipping the scratch DRAM round-trip
            const uint32_t recv_c = (rank + world - seq - 1) % world;
            reg_sink(base_tag | seq, scratch_at(seq),
                     chunk_of(count, world, recv_c).n_elems * qsz, true);
            return;
        }
        const uint32_t s = seq - rs_stages;
        const uint64_t tag = base_tag | (0x4000u + s);
        const auto span = chunk_of(count, world, (rank + world - s) % world);
        if (quantized) {
            reg_sink(tag, scratch_at(seq), span.n_elems * qsz, true);
        } else {
            // zero-copy all-gather: the reduced chunk lands straight in the
            // result buffer. consumer_pull so the single copy runs on the OP
            // thread (mapped-region memcpy, or — via fill_if_unmapped — a
            // process_vm_readv pull into the sink), not on the RX thread
            // with a park/wake per slice. Registering one stage early is
            // safe: the peer only sends this chunk after it has consumed
            // (and for CMA, pulled) everything we previously sent from this
            // region.
            reg_sink(tag, out + span.start_elem * esz, span.n_elems * esz, true);
        }
    };
    reg_stage(0); // before ANY tx: inbound bytes always find a live sink

    // ---------------- phase 1: reduce-scatter ----------------
    auto rs_t0 = now_ns();
    for (uint32_t s = 0; s + 1 < world; ++s) {
        PLOG(kDebug) << "ring seq=" << ctx.op_seq << " rs stage " << s;
        telemetry::Span stage_span("collective", "rs_stage", "stage", s,
                                   "seq", ctx.op_seq);
        const uint64_t tag = base_tag | s;
        const uint32_t send_c = (rank + world - s) % world;
        const uint32_t recv_c = (rank + world - s - 1) % world;
        const auto send_span = chunk_of(count, world, send_c);
        const auto recv_span = chunk_of(count, world, recv_c);
        uint8_t *send_ptr = out + send_span.start_elem * esz;
        uint8_t *recv_ptr = out + recv_span.start_elem * esz;

        uint8_t *rx_scratch = scratch_at(s);
        std::vector<net::SendHandle> tx_job;
        quant::Meta rx_meta;
        if (quantized) {
            quant::Meta meta;
            quant_timed([&] {
                meta = quant::compute_meta(ctx.quant, ctx.q_dtype, ctx.dtype,
                                           send_ptr, send_span.n_elems);
            });
            const size_t qw =
                pipelined ? pipeline_windows(send_span.n_elems * qsz) : 1;
            if (qw <= 1) {
                quant_timed([&] {
                    quant::quantize(meta, send_ptr, tx_scratch.data(),
                                    send_span.n_elems);
                });
                tx_job = launch_tx(tag, meta.encode(),
                                   {tx_scratch.data(), send_span.n_elems * qsz});
            } else {
                // per-window quantize→send overlap: window k+1 quantizes
                // while window k is on the wire. ONE meta for the whole
                // chunk — wire format and numerics are unchanged.
                tx_job.push_back(ctx.tx.send_meta(tag | kMetaBit, meta.encode()));
                for (size_t w = 0; w < qw; ++w) {
                    auto ws = chunk_of(send_span.n_elems,
                                       static_cast<uint32_t>(qw),
                                       static_cast<uint32_t>(w));
                    quant_timed([&] {
                        quant::quantize(meta, send_ptr + ws.start_elem * esz,
                                        tx_scratch.data() + ws.start_elem * qsz,
                                        ws.n_elems);
                    });
                    tx_job.push_back(ctx.tx.send_at(
                        tag, ws.start_elem * qsz,
                        {tx_scratch.data() + ws.start_elem * qsz,
                         ws.n_elems * qsz},
                        ctx.op_seq));
                }
            }
            ctx.tx_bytes += send_span.n_elems * qsz;

            // sink for THIS stage was registered a stage ahead; open the
            // next stage's sink before consuming, then take peer meta
            reg_stage(s + 1);
            auto mraw = ctx.rx.table().recv_queued(tag | kMetaBit, 60'000);
            if (!mraw) {
                join_tx(tx_job);
                return fail(!ctx.rx.alive());
            }
            auto m = quant::Meta::decode(*mraw);
            if (!m) {
                join_tx(tx_job);
                return fail(false);
            }
            rx_meta = *m;
            bool ok = stream_recv(ctx, tag, recv_span.n_elems * qsz, qsz, rx_scratch,
                                  [&](const uint8_t *src, size_t lo, size_t hi) {
                                      size_t e0 = lo / qsz, e1 = hi / qsz;
                                      quant::dequantize_accumulate(
                                          rx_meta, ctx.op, src,
                                          recv_ptr + e0 * esz, e1 - e0);
                                  }, &prof);
            ctx.rx.table().unregister_sink(tag);
            bool tx_ok = join_tx(tx_job);
            if (!ok || !tx_ok) return fail(!ctx.rx.alive() || !ctx.tx.alive());
            ctx.rx_bytes += recv_span.n_elems * qsz;
        } else {
            // stage 0 sends the pristine chunk, readable from `send` directly;
            // later stages send chunks accumulated into recv at stage s-1
            const uint8_t *tx_ptr =
                (lazy && s == 0) ? src8 + send_span.start_elem * esz : send_ptr;
            const size_t send_bytes = send_span.n_elems * esz;
            if (ahead_off > 0) {
                // leading windows already left during stage s-1's accumulate
                tx_job = std::move(ahead_hs);
                ahead_hs.clear();
                if (ahead_off < send_bytes)
                    tx_job.push_back(ctx.tx.send_at(
                        tag, ahead_off, {tx_ptr + ahead_off,
                                         send_bytes - ahead_off},
                        ctx.op_seq));
            } else if (pipelined && win_bytes(send_bytes)) {
                // single-conn in-order stream: striping across the pool
                // would race page-aligned segments through the shared edge
                // bucket and stall the receiver's contiguous prefix — the
                // pipeline rides in-order arrival
                tx_job.push_back(
                    ctx.tx.send_at(tag, 0, {tx_ptr, send_bytes}, ctx.op_seq));
            } else {
                tx_job = launch_tx(tag, {}, {tx_ptr, send_bytes});
            }
            ahead_off = 0;
            ctx.tx_bytes += send_bytes;
            const uint8_t *local_ptr =
                lazy ? src8 + recv_span.start_elem * esz : recv_ptr;
            reg_stage(s + 1); // next stage's sink opens before we consume
            // the chunk accumulating here IS what the next stage (RS s+1,
            // or AG 0 at the phase boundary) sends — the ring invariant the
            // cross-stage send-ahead rides
            const size_t chunk_bytes = recv_span.n_elems * esz;
            const uint64_t next_tag =
                s + 2 < world ? (base_tag | (s + 1)) : (base_tag | 0x4000u);
            const size_t wb = win_bytes(chunk_bytes);
            bool ok = stream_recv(ctx, tag, chunk_bytes, esz, rx_scratch,
                                  [&](const uint8_t *src, size_t lo, size_t hi) {
                                      size_t e0 = lo / esz, e1 = hi / esz;
                                      kernels::accumulate3(ctx.dtype, ctx.op,
                                                           recv_ptr + e0 * esz,
                                                           local_ptr + e0 * esz,
                                                           src, e1 - e0);
                                      if (wb)
                                          send_ahead(next_tag, recv_ptr,
                                                     chunk_bytes, wb, hi);
                                  }, &prof, /*fill_if_unmapped=*/false, wb);
            ctx.rx.table().unregister_sink(tag);
            bool tx_ok = join_tx(tx_job);
            if (!ok || !tx_ok) return fail(!ctx.rx.alive() || !ctx.tx.alive());
            ctx.rx_bytes += chunk_bytes;
        }
    }

    if (trace)
        rec.span("collective", "reduce_scatter", rs_t0, now_ns(), "seq",
                 ctx.op_seq, "bytes", (count * esz / world) * (world - 1));

    // ---------------- phase 2: all-gather ----------------
    // after reduce-scatter, this rank owns fully-reduced chunk (rank+1)%world.
    // Quantized path: own chunk is quantized ONCE; received chunks are
    // forwarded verbatim (no re-quantization), and the owner self-dequantizes
    // for bit parity (reference reduce.cpp:673-738).
    auto ag_t0 = now_ns();
    std::vector<uint8_t> fwd_q;      // quantized bytes to forward next stage
    std::vector<uint8_t> fwd_meta;   // encoded meta to forward
    for (uint32_t s = 0; s + 1 < world; ++s) {
        PLOG(kDebug) << "ring seq=" << ctx.op_seq << " ag stage " << s;
        telemetry::Span stage_span("collective", "ag_stage", "stage", s,
                                   "seq", ctx.op_seq);
        const uint64_t tag = base_tag | (0x4000u + s);
        const uint32_t send_c = (rank + 1 + world - s) % world;
        const uint32_t recv_c = (rank + world - s) % world;
        const auto send_span = chunk_of(count, world, send_c);
        const auto recv_span = chunk_of(count, world, recv_c);
        uint8_t *send_ptr = out + send_span.start_elem * esz;
        uint8_t *recv_ptr = out + recv_span.start_elem * esz;
        uint8_t *rx_scratch = scratch_at(rs_stages + s);

        std::vector<net::SendHandle> tx_job;
        if (quantized) {
            bool launched = false;
            if (s == 0) {
                quant::Meta meta;
                quant_timed([&] {
                    meta = quant::compute_meta(ctx.quant, ctx.q_dtype,
                                               ctx.dtype, send_ptr,
                                               send_span.n_elems);
                    fwd_q.resize(send_span.n_elems * qsz);
                });
                fwd_meta = meta.encode();
                const size_t qw =
                    pipelined ? pipeline_windows(send_span.n_elems * qsz) : 1;
                if (qw > 1) {
                    // per-window quantize→send overlap (one whole-chunk
                    // meta, wire format unchanged); the owner's bit-parity
                    // self-dequantize rides the same window while it is
                    // still cache-hot
                    tx_job.push_back(
                        ctx.tx.send_meta(tag | kMetaBit, fwd_meta));
                    for (size_t w = 0; w < qw; ++w) {
                        auto ws = chunk_of(send_span.n_elems,
                                           static_cast<uint32_t>(qw),
                                           static_cast<uint32_t>(w));
                        quant_timed([&] {
                            quant::quantize(meta,
                                            send_ptr + ws.start_elem * esz,
                                            fwd_q.data() + ws.start_elem * qsz,
                                            ws.n_elems);
                        });
                        tx_job.push_back(ctx.tx.send_at(
                            tag, ws.start_elem * qsz,
                            {fwd_q.data() + ws.start_elem * qsz,
                             ws.n_elems * qsz},
                            ctx.op_seq));
                        quant_timed([&] {
                            quant::dequantize_set(
                                meta, fwd_q.data() + ws.start_elem * qsz,
                                send_ptr + ws.start_elem * esz, ws.n_elems);
                        });
                    }
                    launched = true;
                } else {
                    quant_timed([&] {
                        quant::quantize(meta, send_ptr, fwd_q.data(),
                                        send_span.n_elems);
                        // bit parity: owner keeps what the others decode
                        quant::dequantize_set(meta, fwd_q.data(), send_ptr,
                                              send_span.n_elems);
                    });
                }
            }
            if (!launched) tx_job = launch_tx(tag, fwd_meta, fwd_q);
            ctx.tx_bytes += fwd_q.size();

            reg_stage(rs_stages + s + 1); // sink for THIS stage opened earlier
            auto mraw = ctx.rx.table().recv_queued(tag | kMetaBit, 60'000);
            if (!mraw) {
                join_tx(tx_job);
                return fail(!ctx.rx.alive());
            }
            auto m = quant::Meta::decode(*mraw);
            if (!m) {
                join_tx(tx_job);
                return fail(false);
            }
            // forwarding stages must keep the raw quantized bytes: the fused
            // CMA path consumes from a bounce buffer, so mirror each slice
            // into rx_scratch (cache-hot, and only when actually forwarding)
            const bool fwd_needed = s + 2 < world;
            bool ok = stream_recv(ctx, tag, recv_span.n_elems * qsz, qsz, rx_scratch,
                                  [&](const uint8_t *src, size_t lo, size_t hi) {
                                      if (fwd_needed && src != rx_scratch + lo)
                                          memcpy(rx_scratch + lo, src, hi - lo);
                                      size_t e0 = lo / qsz, e1 = hi / qsz;
                                      quant::dequantize_set(*m, src,
                                                            recv_ptr + e0 * esz, e1 - e0);
                                  }, &prof);
            ctx.rx.table().unregister_sink(tag);
            bool tx_ok = join_tx(tx_job);
            if (!ok || !tx_ok) return fail(!ctx.rx.alive() || !ctx.tx.alive());
            ctx.rx_bytes += recv_span.n_elems * qsz;
            if (fwd_needed) {
                // forward what we received on the next stage; the send buffer
                // must be distinct from rx_scratch (next stage writes into it)
                fwd_q.assign(rx_scratch, rx_scratch + recv_span.n_elems * qsz);
                fwd_meta = mraw.value();
            }
        } else {
            const size_t send_bytes = send_span.n_elems * esz;
            if (ahead_off > 0) {
                tx_job = std::move(ahead_hs);
                ahead_hs.clear();
                if (ahead_off < send_bytes)
                    tx_job.push_back(ctx.tx.send_at(
                        tag, ahead_off, {send_ptr + ahead_off,
                                         send_bytes - ahead_off},
                        ctx.op_seq));
            } else if (pipelined && win_bytes(send_bytes)) {
                // single-conn in-order stream (see the reduce-scatter note)
                tx_job.push_back(
                    ctx.tx.send_at(tag, 0, {send_ptr, send_bytes}, ctx.op_seq));
            } else {
                tx_job = launch_tx(tag, {}, {send_ptr, send_bytes});
            }
            ahead_off = 0;
            ctx.tx_bytes += send_bytes;
            // zero-copy sink was registered a stage ahead; open the next
            reg_stage(rs_stages + s + 1);
            const size_t chunk_bytes = recv_span.n_elems * esz;
            const uint64_t next_tag = base_tag | (0x4000u + s + 1);
            const size_t wb = s + 2 < world ? win_bytes(chunk_bytes) : 0;
            bool ok = stream_recv(ctx, tag, chunk_bytes, esz, recv_ptr,
                                  [&](const uint8_t *src, size_t lo, size_t hi) {
                                      // mapped-region consume: the copy into
                                      // the result IS the stage; TCP/pulled
                                      // bytes already landed in the sink
                                      if (src != recv_ptr + lo)
                                          kernels::copy_stream(recv_ptr + lo, src,
                                                               hi - lo);
                                      if (wb)
                                          send_ahead(next_tag, recv_ptr,
                                                     chunk_bytes, wb, hi);
                                  }, &prof, /*fill_if_unmapped=*/true, wb);
            ctx.rx.table().unregister_sink(tag);
            bool tx_ok = join_tx(tx_job);
            if (!ok || !tx_ok) return fail(!ctx.rx.alive() || !ctx.tx.alive());
            ctx.rx_bytes += chunk_bytes;
        }
    }

    if (ctx.op == proto::RedOp::kAvg)
        kernels::finalize_avg(ctx.dtype, recv, count, world);

    ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
    ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
    uint64_t op_t1 = now_ns();
    if (ctx.rx_edge)  // receiver wire-stall charged to the inbound edge
        ctx.rx_edge->stall_ns.fetch_add(prof.wait_ns, std::memory_order_relaxed);
    if (ctx.tele)  // digest op sample (last-N phase timings)
        ctx.tele->record_op(ctx.op_seq, op_t1 - op_t0, prof.wait_ns);
    if (trace) {
        rec.span("collective", "all_gather", ag_t0, op_t1, "seq", ctx.op_seq,
                 "bytes", (count * esz / world) * (world - 1));
        rec.span("collective", "allreduce", op_t0, op_t1, "seq", ctx.op_seq,
                 "bytes", count * esz);
        rec.instant("collective", "wire_stall", "ns", prof.wait_ns, "seq",
                    ctx.op_seq);
        if (quantized)
            rec.instant("collective", "quantize", "ns", prof.quant_ns, "seq",
                        ctx.op_seq);
    }
    if (prof_enabled())
        PLOG(kInfo) << "reduce prof: total=" << (op_t1 - op_t0) / 1e6
                    << "ms wait=" << prof.wait_ns / 1e6
                    << " compute=" << prof.compute_ns / 1e6
                    << " quant=" << prof.quant_ns / 1e6
                    << " join=" << prof.join_ns / 1e6
                    << " reg=" << prof.reg_ns / 1e6;
    return Result::kOk;
}

Result ring_allgather(RingCtx &ctx, const void *send, void *recv, size_t count) {
    const size_t esz = proto::dtype_size(ctx.dtype);
    const uint32_t world = ctx.world, rank = ctx.rank;
    const size_t seg = count * esz;
    auto *out = static_cast<uint8_t *>(recv);
    auto slot = [&](uint32_t ring_rank) -> size_t {
        return ctx.slots.empty() ? ring_rank : ctx.slots[ring_rank];
    };
    // own segment lands at its slot regardless of world size
    if (out + slot(rank) * seg != send)
        kernels::copy_stream(out + slot(rank) * seg, send, seg);
    if (world < 2) return Result::kOk;

    const uint64_t base_tag = ctx.op_seq << 16;
    auto fail = [&](bool conn_lost) {
        // no restore: the gather only writes recv, and a retry overwrites
        // every segment — but sinks must not outlive this frame's buffers
        ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
        ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
        return conn_lost ? Result::kConnectionLost : Result::kAborted;
    };
    // stage s receives the segment of ring rank (rank - s - 1); register one
    // stage ahead so symmetric peers never race registration (same protocol
    // as the all-reduce's gather phase)
    auto reg_stage = [&](uint32_t s) {
        if (s >= world - 1) return;
        const uint32_t src_rank = (rank + world - s - 1) % world;
        ctx.rx.table().register_sink(base_tag | s, out + slot(src_rank) * seg,
                                     seg, /*consumer_pull=*/true);
    };
    reg_stage(0);
    auto &rec = telemetry::Recorder::inst();
    const bool trace = rec.on();
    Prof prof;
    auto op_t0 = now_ns();
    // same windowed cross-stage send-ahead as the all-reduce (docs/08):
    // the segment received at stage s is the one forwarded at stage s+1
    const bool pipelined = pipeline_enabled() && !ctx.tx.cma_eligible();
    size_t wb = 0;
    if (pipelined) {
        size_t w = pipeline_windows(seg);
        if (w > 1) wb = std::max(esz, seg / w / esz * esz);
    }
    std::vector<net::SendHandle> ahead_hs;
    size_t ahead_off = 0;
    for (uint32_t s = 0; s + 1 < world; ++s) {
        const uint64_t tag = base_tag | s;
        telemetry::Span stage_span("collective", "gather_stage", "stage", s,
                                   "seq", ctx.op_seq);
        const uint32_t fwd_rank = (rank + world - s) % world; // own at s=0
        const uint8_t *src = s == 0 ? static_cast<const uint8_t *>(send)
                                    : out + slot(fwd_rank) * seg;
        std::vector<net::SendHandle> tx_job;
        if (ahead_off > 0) {
            tx_job = std::move(ahead_hs);
            ahead_hs.clear();
            if (ahead_off < seg)
                tx_job.push_back(ctx.tx.send_at(tag, ahead_off,
                                                {src + ahead_off,
                                                 seg - ahead_off},
                                                ctx.op_seq));
        } else {
            if (wb) // single-conn in-order stream (see the all-reduce note)
                tx_job.push_back(
                    ctx.tx.send_at(tag, 0, {src, seg}, ctx.op_seq));
            else
                tx_job = ctx.tx.send_async(tag, {src, seg}, ctx.op_seq);
        }
        ahead_off = 0;
        ctx.tx_bytes += seg;
        const uint32_t src_rank = (rank + world - s - 1) % world;
        uint8_t *dst = out + slot(src_rank) * seg;
        reg_stage(s + 1);
        const uint64_t next_tag = base_tag | (s + 1);
        const size_t swb = s + 2 < world ? wb : 0;
        bool ok = stream_recv(ctx, tag, seg, esz, dst,
                              [&](const uint8_t *p, size_t lo, size_t hi) {
                                  if (p != dst + lo)
                                      kernels::copy_stream(dst + lo, p, hi - lo);
                                  if (swb)
                                      send_ahead_windows(ctx.tx, next_tag, dst,
                                                         seg, swb, hi,
                                                         ctx.op_seq, &ahead_off,
                                                         &ahead_hs);
                              }, &prof, /*fill_if_unmapped=*/true, swb);
        ctx.rx.table().unregister_sink(tag);
        bool tx_ok = net::Link::wait_all(tx_job);
        if (!ok || !tx_ok) {
            net::Link::wait_all(ahead_hs); // next-stage windows borrow `out`
            return fail(!ctx.rx.alive() || !ctx.tx.alive());
        }
        ctx.rx_bytes += seg;
    }
    ctx.tx.table().purge_range(base_tag, base_tag + 0x10000);
    ctx.rx.table().purge_range(base_tag, base_tag + 0x10000);
    uint64_t op_t1 = now_ns();
    if (ctx.rx_edge)
        ctx.rx_edge->stall_ns.fetch_add(prof.wait_ns, std::memory_order_relaxed);
    if (ctx.tele)
        ctx.tele->record_op(ctx.op_seq, op_t1 - op_t0, prof.wait_ns);
    if (trace) {
        rec.span("collective", "allgather", op_t0, op_t1, "seq", ctx.op_seq,
                 "bytes", static_cast<uint64_t>(world) * seg);
        rec.instant("collective", "wire_stall", "ns", prof.wait_ns, "seq",
                    ctx.op_seq);
    }
    return Result::kOk;
}

} // namespace pcclt::reduce
