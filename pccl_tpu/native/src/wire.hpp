// Big-endian wire buffers.
// Reference parity: PacketReadBuffer/PacketWriteBuffer
// (/root/reference/ccoip/internal_include/ccoip_packet_buffer.hpp) — network
// byte order for all integers, length-prefixed strings/byte spans.
#pragma once

#include <bit>
#include <cstdint>
#include <cstring>
#include <span>
#include <stdexcept>
#include <string>
#include <vector>

namespace pcclt::wire {

// 64 MiB guard for control packets (bulk data uses the multiplex framing).
inline constexpr uint64_t kMaxControlPacket = 64ull << 20;

template <typename T> T to_be(T v) {
    static_assert(std::is_integral_v<T>);
    if constexpr (std::endian::native == std::endian::little) {
        if constexpr (sizeof(T) == 2) return static_cast<T>(__builtin_bswap16(static_cast<uint16_t>(v)));
        else if constexpr (sizeof(T) == 4) return static_cast<T>(__builtin_bswap32(static_cast<uint32_t>(v)));
        else if constexpr (sizeof(T) == 8) return static_cast<T>(__builtin_bswap64(static_cast<uint64_t>(v)));
        else return v;
    }
    return v;
}
template <typename T> T from_be(T v) { return to_be(v); }

class Writer {
public:
    template <typename T> void u(T v) {
        static_assert(std::is_integral_v<T>);
        T be = to_be(v);
        append(&be, sizeof be);
    }
    void u8(uint8_t v) { append(&v, 1); }
    void u16(uint16_t v) { u(v); }
    void u32(uint32_t v) { u(v); }
    void u64(uint64_t v) { u(v); }
    void f64(double v) {
        uint64_t bits;
        memcpy(&bits, &v, 8);
        u64(bits);
    }
    void str(const std::string &s) {
        u32(static_cast<uint32_t>(s.size()));
        append(s.data(), s.size());
    }
    void bytes(std::span<const uint8_t> b) {
        u64(b.size());
        append(b.data(), b.size());
    }
    void raw(const void *p, size_t n) { append(p, n); }

    const std::vector<uint8_t> &data() const { return buf_; }
    std::vector<uint8_t> take() { return std::move(buf_); }

private:
    void append(const void *p, size_t n) {
        auto *b = static_cast<const uint8_t *>(p);
        buf_.insert(buf_.end(), b, b + n);
    }
    std::vector<uint8_t> buf_;
};

class Reader {
public:
    explicit Reader(std::span<const uint8_t> data) : data_(data) {}

    template <typename T> T u() {
        static_assert(std::is_integral_v<T>);
        T v;
        need(sizeof v);
        memcpy(&v, data_.data() + pos_, sizeof v);
        pos_ += sizeof v;
        return from_be(v);
    }
    uint8_t u8() {
        need(1);
        return data_[pos_++];
    }
    uint16_t u16() { return u<uint16_t>(); }
    uint32_t u32() { return u<uint32_t>(); }
    uint64_t u64() { return u<uint64_t>(); }
    double f64() {
        uint64_t bits = u64();
        double v;
        memcpy(&v, &bits, 8);
        return v;
    }
    std::string str() {
        uint32_t n = u32();
        need(n);
        std::string s(reinterpret_cast<const char *>(data_.data() + pos_), n);
        pos_ += n;
        return s;
    }
    std::vector<uint8_t> bytes() {
        uint64_t n = u64();
        need(n);
        std::vector<uint8_t> b(data_.begin() + pos_, data_.begin() + pos_ + n);
        pos_ += n;
        return b;
    }
    size_t remaining() const { return data_.size() - pos_; }
    bool done() const { return pos_ == data_.size(); }

private:
    void need(size_t n) const {
        // n is attacker-controlled (length fields); pos_ + n can wrap
        if (n > data_.size() - pos_) throw std::runtime_error("wire: short read");
    }
    std::span<const uint8_t> data_;
    size_t pos_ = 0;
};

} // namespace pcclt::wire
