// Standalone master executable.
// Reference parity: ccoip_master binary (/root/reference/ccoip_master/src/
// main.cpp) — listens on the default port, SIGINT/SIGTERM interrupts.
#include <csignal>
#include <cstdio>
#include <cstdlib>

#include "../include/pcclt.h"

static pccltMaster_t *g_master = nullptr;

static void on_signal(int) {
    if (g_master) pccltInterruptMaster(g_master);
}

int main(int argc, char **argv) {
    uint16_t port = 48501;
    const char *journal = nullptr; // nullptr = PCCLT_MASTER_JOURNAL env
    if (argc > 1) port = static_cast<uint16_t>(atoi(argv[1]));
    if (argc > 2) journal = argv[2]; // HA: journal path (see journal.hpp)
    if (pccltCreateMasterEx("0.0.0.0", port, journal, &g_master) != pccltSuccess)
        return 1;
    if (pccltRunMaster(g_master) != pccltSuccess) {
        fprintf(stderr, "failed to launch master on port %u\n", port);
        return 1;
    }
    printf("pcclt master listening on port %u (epoch %llu)\n",
           pccltMasterPort(g_master),
           (unsigned long long)pccltMasterEpoch(g_master));
    fflush(stdout);
    signal(SIGINT, on_signal);
    signal(SIGTERM, on_signal);
    pccltMasterAwaitTermination(g_master);
    pccltDestroyMaster(g_master);
    return 0;
}
