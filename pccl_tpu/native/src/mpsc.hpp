// Lock-free intrusive MPSC queue (Vyukov-style).
//
// Reference parity: tinysockets' MPSC queue feeding the multiplexed socket's
// dedicated TX thread (/root/reference/tinysockets/mpsc/include/
// MPSCQueue.hpp, used at multiplexed_socket.cpp:129-136). Redesigned as the
// classic intrusive exchange-based MPSC: producers do one atomic exchange +
// one store; the single consumer walks next-pointers. No fixed capacity, no
// CAS loops, no allocation inside the queue itself. Consumer wakeup is the
// caller's concern (pair with park::Event).
#pragma once

#include <atomic>

namespace pcclt::mpsc {

struct Node {
    std::atomic<Node *> next{nullptr};
};

// Multi-producer single-consumer queue of intrusive nodes. push() is
// wait-free for producers. pop() must only be called from one thread.
class Queue {
public:
    Queue() : head_(&stub_), tail_(&stub_) { stub_.next.store(nullptr); }

    void push(Node *n) {
        n->next.store(nullptr, std::memory_order_relaxed);
        Node *prev = head_.exchange(n, std::memory_order_acq_rel);
        prev->next.store(n, std::memory_order_release);
    }

    // Single-consumer pop; nullptr when empty OR when a producer is mid-push
    // (the caller's park/retry loop absorbs the transient state). A popped
    // node is fully detached and may be freed immediately.
    Node *pop() {
        Node *tail = tail_;
        Node *next = tail->next.load(std::memory_order_acquire);
        if (tail == &stub_) {
            if (!next) return nullptr;
            tail_ = next;
            tail = next;
            next = tail->next.load(std::memory_order_acquire);
        }
        if (next) {
            tail_ = next;
            return tail;
        }
        if (tail != head_.load(std::memory_order_acquire))
            return nullptr; // producer mid-push; retry later
        push(&stub_);       // re-link the stub behind the last element
        next = tail->next.load(std::memory_order_acquire);
        if (next) {
            tail_ = next;
            return tail;
        }
        return nullptr; // racing producer will finish the link; retry later
    }

private:
    std::atomic<Node *> head_; // producers push here
    Node *tail_;               // consumer-private
    Node stub_;
};

} // namespace pcclt::mpsc
