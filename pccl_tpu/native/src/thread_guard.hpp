// Runtime single-threaded-invariant enforcement.
//
// Reference parity: THREAD_GUARD(tid) (/root/reference/ccoip/internal/
// thread_guard.hpp:9-13, used e.g. ccoip_master_handler.cpp:66) — state
// machines that are single-threaded BY DESIGN terminate loudly if ever
// entered from a second thread, instead of corrupting state silently.
#pragma once

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <thread>

namespace pcclt {

// Place one per guarded class; call check() at every entry point.
class ThreadGuard {
public:
    void check(const char *where) {
        // atomic CAS bind: a concurrent first entry from two threads is
        // exactly the violation we exist to catch — the loser must abort,
        // not racily co-bind
        auto self = std::hash<std::thread::id>{}(std::this_thread::get_id());
        size_t expected = kUnbound;
        if (owner_.compare_exchange_strong(expected, self)) return;
        if (expected != self) {
            std::fprintf(stderr,
                         "FATAL: single-threaded invariant violated at %s\n",
                         where);
            std::abort();
        }
    }

private:
    static constexpr size_t kUnbound = ~size_t{0};
    std::atomic<size_t> owner_{kUnbound};
};

#define PCCLT_THREAD_GUARD(guard) (guard).check(__func__)

} // namespace pcclt
