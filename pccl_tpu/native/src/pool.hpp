// Fixed-size worker pool for async collective ops.
//
// Reference parity: pi::threadpool::ThreadPool (vendored pithreadpool,
// owned by the client state at ccoip_client_state.hpp:98, sized by
// PCCL_MAX_CONCURRENT_COLLECTIVE_OPS default 16) — collective workers run
// on pooled threads instead of a fresh std::thread per op, so launching a
// burst of concurrent reduces costs queue pushes, not thread spawns.
#pragma once

#include <deque>
#include <functional>
#include <thread>
#include <vector>

#include "annotations.hpp"

namespace pcclt::util {

class WorkerPool {
public:
    explicit WorkerPool(size_t threads) {
        threads_.reserve(threads);
        for (size_t i = 0; i < threads; ++i)
            threads_.emplace_back([this] { run(); });
    }

    ~WorkerPool() {
        {
            MutexLock lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto &t : threads_) t.join();
    }

    WorkerPool(const WorkerPool &) = delete;
    WorkerPool &operator=(const WorkerPool &) = delete;

    void submit(std::function<void()> fn) {
        {
            MutexLock lk(mu_);
            q_.push_back(std::move(fn));
        }
        cv_.notify_one();
    }

private:
    void run() {
        for (;;) {
            std::function<void()> fn;
            {
                MutexLock lk(mu_);
                while (!stop_ && q_.empty()) cv_.wait(mu_);
                if (stop_ && q_.empty()) return;
                fn = std::move(q_.front());
                q_.pop_front();
            }
            fn();
        }
    }

    Mutex mu_; // lock-rank: 70
    CondVar cv_;
    std::deque<std::function<void()>> q_ PCCLT_GUARDED_BY(mu_);
    std::vector<std::thread> threads_;
    bool stop_ PCCLT_GUARDED_BY(mu_) = false;
};

} // namespace pcclt::util
