// Fixed-size worker pool for async collective ops.
//
// Reference parity: pi::threadpool::ThreadPool (vendored pithreadpool,
// owned by the client state at ccoip_client_state.hpp:98, sized by
// PCCL_MAX_CONCURRENT_COLLECTIVE_OPS default 16) — collective workers run
// on pooled threads instead of a fresh std::thread per op, so launching a
// burst of concurrent reduces costs queue pushes, not thread spawns.
#pragma once

#include <condition_variable>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace pcclt::util {

class WorkerPool {
public:
    explicit WorkerPool(size_t threads) {
        threads_.reserve(threads);
        for (size_t i = 0; i < threads; ++i)
            threads_.emplace_back([this] { run(); });
    }

    ~WorkerPool() {
        {
            std::lock_guard lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto &t : threads_) t.join();
    }

    WorkerPool(const WorkerPool &) = delete;
    WorkerPool &operator=(const WorkerPool &) = delete;

    void submit(std::function<void()> fn) {
        {
            std::lock_guard lk(mu_);
            q_.push_back(std::move(fn));
        }
        cv_.notify_one();
    }

private:
    void run() {
        for (;;) {
            std::function<void()> fn;
            {
                std::unique_lock lk(mu_);
                cv_.wait(lk, [this] { return stop_ || !q_.empty(); });
                if (stop_ && q_.empty()) return;
                fn = std::move(q_.front());
                q_.pop_front();
            }
            fn();
        }
    }

    std::mutex mu_;
    std::condition_variable cv_;
    std::deque<std::function<void()>> q_;
    std::vector<std::thread> threads_;
    bool stop_ = false;
};

} // namespace pcclt::util
