// Asymmetric TSP solver for bandwidth-aware ring ordering.
// Reference parity: libtsp's tspAsymmetricSolve / ImproveSolution
// (/root/reference/ccoip/src/cpp/topolgy_optimizer.cpp:50-62,134-146 usage)
// — exact for small N, heuristic beyond. This implementation:
//   n <= 12 : Held-Karp exact dynamic program
//   n  > 12 : best-of-all-starts nearest neighbor + 2-opt + Or-opt local
//             search under a millisecond budget, with random restarts.
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <vector>

namespace pcclt::atsp {

// cost: n*n row-major, cost[i*n+j] = directed edge i->j; diagonal ignored.
// Returns a tour as a permutation of [0, n).
std::vector<int> solve(const std::vector<double> &cost, size_t n, int budget_ms);

// Improve an existing tour in place (keeps it valid); returns improved cost.
// `stop` (optional) is polled between passes so a shutting-down owner can
// cancel a long budget promptly.
double improve(const std::vector<double> &cost, size_t n, std::vector<int> &tour,
               int budget_ms, const std::atomic<bool> *stop = nullptr);

double tour_cost(const std::vector<double> &cost, size_t n, const std::vector<int> &tour);

// Hamiltonian cycle using only edges with cost < limit (reachability-aware
// ring build, reference ccoip_master_state.cpp:1660-1770 backtracking).
// Returns empty if none found within the budget. Neighbors are tried
// cheapest-first, so the result doubles as a reasonable-quality tour.
std::vector<int> hamiltonian(const std::vector<double> &cost, size_t n, double limit,
                             int budget_ms);

} // namespace pcclt::atsp
