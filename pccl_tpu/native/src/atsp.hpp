// Asymmetric TSP solver for bandwidth-aware ring ordering.
// Reference parity: libtsp's tspAsymmetricSolve / ImproveSolution
// (/root/reference/ccoip/src/cpp/topolgy_optimizer.cpp:50-62,134-146 usage)
// — exact for small N, heuristic beyond. This implementation:
//   n <= 12 : Held-Karp exact dynamic program
//   n  > 12 : best-of-all-starts nearest neighbor + 2-opt + Or-opt local
//             search under a millisecond budget, with random restarts.
#pragma once

#include <cstddef>
#include <cstdint>
#include <vector>

namespace pcclt::atsp {

// cost: n*n row-major, cost[i*n+j] = directed edge i->j; diagonal ignored.
// Returns a tour as a permutation of [0, n).
std::vector<int> solve(const std::vector<double> &cost, size_t n, int budget_ms);

// Improve an existing tour in place (keeps it valid); returns improved cost.
double improve(const std::vector<double> &cost, size_t n, std::vector<int> &tour,
               int budget_ms);

double tour_cost(const std::vector<double> &cost, size_t n, const std::vector<int> &tour);

} // namespace pcclt::atsp
