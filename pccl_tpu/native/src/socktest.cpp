// Socket-layer unit tests: Socket framing, Listener, ControlClient matching,
// MultiplexConn/SinkTable demux, Link striping, and the bandwidth probe.
//
// Reference parity: tinysockets/tests/ (test_server_socket.cpp 1,235 LoC,
// test_queued_socket.cpp 645 LoC) — the riskiest concurrency code in the
// tree gets direct coverage: register-while-receiving races, cancel
// mid-stream, purge under load, queued->sink handoff, death notification.
// Built as its own binary (pcclt_socktest) and run under ASan/UBSan/TSan
// configs (reference: cmake/testing.cmake wires sanitizers into every gtest).

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

#include "benchmark.hpp"
#include "protocol.hpp"
#include "shm.hpp"
#include "sockets.hpp"
#include "telemetry.hpp"
#include "uring.hpp"
#include "wire.hpp"

using namespace pcclt;

static int failures = 0;

#define CHECK(cond)                                                            \
    do {                                                                       \
        if (!(cond)) {                                                         \
            fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__, __LINE__,    \
                    #cond);                                                    \
            failures++;                                                        \
        }                                                                      \
    } while (0)

namespace {

struct ConnPair {
    std::shared_ptr<net::MultiplexConn> a, b;
    std::shared_ptr<net::SinkTable> ta, tb;
};

// Build a connected MultiplexConn pair over loopback. Each side gets its own
// SinkTable unless shared tables are passed in (pool striping tests), and
// its own telemetry domain when one is passed (metering tests). The
// throwaway listener is stopped before returning, so no accept callback can
// outlive this scope.
ConnPair make_pair_conns(std::shared_ptr<net::SinkTable> ta = nullptr,
                         std::shared_ptr<net::SinkTable> tb = nullptr,
                         std::shared_ptr<pcclt::telemetry::Domain> da = nullptr,
                         std::shared_ptr<pcclt::telemetry::Domain> db = nullptr) {
    ConnPair p;
    p.ta = ta ? ta : std::make_shared<net::SinkTable>();
    p.tb = tb ? tb : std::make_shared<net::SinkTable>();
    auto accepted = std::make_shared<std::atomic<bool>>(false);
    auto accepted_sock = std::make_shared<net::Socket>();
    net::Listener listener;
    CHECK(listener.listen(0, 1, /*loopback_only=*/true));
    listener.run_async([accepted, accepted_sock](net::Socket s) {
        *accepted_sock = std::move(s);
        accepted->store(true);
    });
    net::Socket c;
    CHECK(c.connect(net::Addr{127u << 24 | 1, listener.port()}, 5000));
    for (int i = 0; i < 500 && !accepted->load(); ++i)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    CHECK(accepted->load());
    listener.stop();
    p.a = std::make_shared<net::MultiplexConn>(std::move(c), p.ta, da);
    p.b = std::make_shared<net::MultiplexConn>(std::move(*accepted_sock), p.tb,
                                               db);
    p.ta->attach(p.a);
    p.tb->attach(p.b);
    p.a->run();
    p.b->run();
    return p;
}

std::vector<uint8_t> pattern(size_t n, uint64_t seed) {
    std::vector<uint8_t> v(n);
    std::mt19937_64 rng{seed};
    for (auto &b : v) b = static_cast<uint8_t>(rng());
    return v;
}

// ---------------- Socket + framing ----------------

void test_frame_roundtrip() {
    net::Listener lis;
    CHECK(lis.listen(0, 1, true));
    net::Socket srv;
    std::atomic<bool> got{false};
    lis.run_async([&](net::Socket s) {
        srv = std::move(s);
        got.store(true);
    });
    net::Socket cli;
    CHECK(cli.connect(net::Addr{127u << 24 | 1, lis.port()}, 5000));
    for (int i = 0; i < 5000 && !got.load(); ++i)
        std::this_thread::sleep_for(std::chrono::milliseconds(1));
    CHECK(got.load());

    Mutex mu;
    // empty payload
    CHECK(net::send_frame(cli, mu, 7, {}));
    auto f = net::recv_frame(srv, 2000);
    CHECK(f && f->type == 7 && f->payload.empty());

    // large payload crosses the coalescing threshold
    auto big = pattern(1 << 20, 42);
    CHECK(net::send_frame(cli, mu, 9, big));
    f = net::recv_frame(srv, 5000);
    CHECK(f && f->type == 9 && f->payload == big);

    // timeout on silence (bounded recv must not block forever)
    auto t0 = std::chrono::steady_clock::now();
    f = net::recv_frame(srv, 150);
    auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                  std::chrono::steady_clock::now() - t0)
                  .count();
    CHECK(!f && ms >= 100 && ms < 3000);

    // a frame with an oversized length header is rejected, not allocated
    uint32_t bad_len = wire::to_be(static_cast<uint32_t>(wire::kMaxControlPacket + 3));
    uint16_t type = 0;
    CHECK(cli.send_all(&bad_len, 4));
    CHECK(cli.send_all(&type, 2));
    f = net::recv_frame(srv, 2000);
    CHECK(!f);
    fprintf(stderr, "frame roundtrip: ok\n");
}

void test_listener_port_bump() {
    net::Listener a, b;
    CHECK(a.listen(0, 1, true));
    // deliberately collide on a's port; the bump allocator walks upward
    CHECK(b.listen(a.port(), 8, true));
    CHECK(b.port() != a.port());
    CHECK(b.port() > a.port() && b.port() <= a.port() + 8);
    fprintf(stderr, "listener port bump: ok\n");
}

// ---------------- ControlClient ----------------

void test_control_client_matching() {
    net::Listener lis;
    CHECK(lis.listen(0, 1, true));
    net::Socket srv;
    std::atomic<bool> got{false};
    lis.run_async([&](net::Socket s) {
        srv = std::move(s);
        got.store(true);
    });
    net::ControlClient cc;
    CHECK(cc.connect(net::Addr{127u << 24 | 1, lis.port()}));
    std::atomic<int> disconnects{0};
    cc.run([&] { disconnects.fetch_add(1); });
    for (int i = 0; i < 5000 && !got.load(); ++i)
        std::this_thread::sleep_for(std::chrono::milliseconds(1));
    CHECK(got.load());

    Mutex mu;
    std::vector<uint8_t> p1{1}, p2{2}, p3{3};
    CHECK(net::send_frame(srv, mu, 100, p1));
    CHECK(net::send_frame(srv, mu, 100, p2));
    CHECK(net::send_frame(srv, mu, 200, p3));

    // predicate skips p1 and matches p2 even though p1 arrived first
    auto f = cc.recv_match(100, [](const std::vector<uint8_t> &p) {
        return !p.empty() && p[0] == 2;
    }, 2000);
    CHECK(f && f->payload == p2);
    // p1 is still queued and matches an unconditional receive
    f = cc.recv_match(100, nullptr, 2000);
    CHECK(f && f->payload == p1);
    // type-based match across types
    f = cc.recv_match_any({200, 300}, nullptr, 2000);
    CHECK(f && f->type == 200 && f->payload == p3);

    // no_wait polls: nothing queued -> immediate nullopt
    auto t0 = std::chrono::steady_clock::now();
    f = cc.recv_match(100, nullptr, -1, /*no_wait=*/true);
    CHECK(!f);
    CHECK(std::chrono::steady_clock::now() - t0 < std::chrono::seconds(1));

    // timeout on empty queue
    f = cc.recv_match(100, nullptr, 120);
    CHECK(!f);

    // client->server direction
    CHECK(cc.send(42, p1));
    auto sf = net::recv_frame(srv, 2000);
    CHECK(sf && sf->type == 42 && sf->payload == p1);

    // disconnect wakes blocked waiters and fires the callback exactly once
    std::thread waiter([&] {
        auto r = cc.recv_match(999, nullptr, 10'000);
        CHECK(!r);
    });
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    srv.shutdown();
    srv.close();
    waiter.join();
    for (int i = 0; i < 500 && cc.connected(); ++i)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    CHECK(!cc.connected());
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    CHECK(disconnects.load() == 1);
    fprintf(stderr, "control client matching: ok\n");
}

// ---------------- MultiplexConn / SinkTable ----------------

void test_mux_basic_and_ooo(bool allow_cma) {
    auto p = make_pair_conns();
    const size_t n = 256 * 1024;
    auto data = pattern(n, 7);

    // basic: sink registered first, single send
    std::vector<uint8_t> dst(n, 0);
    p.b->table().register_sink(1, dst.data(), n);
    CHECK(p.a->send_bytes(1, data, allow_cma));
    CHECK(p.b->table().wait_filled(1, n, 10'000) == n);
    p.b->table().unregister_sink(1);
    CHECK(dst == data);

    // out-of-order offsets: second half lands before first half;
    // prefix tracking must absorb the queued extent
    std::vector<uint8_t> dst2(n, 0);
    p.b->table().register_sink(2, dst2.data(), n);
    auto h1 = p.a->send_async(2, n / 2, {data.data() + n / 2, n / 2}, false);
    CHECK(h1->wait(10'000));
    CHECK(p.b->table().wait_filled(2, 1, 2'000) == 0); // gap: no prefix yet
    auto h2 = p.a->send_async(2, 0, {data.data(), n / 2}, false);
    CHECK(h2->wait(10'000));
    CHECK(p.b->table().wait_filled(2, n, 10'000) == n);
    p.b->table().unregister_sink(2);
    CHECK(dst2 == data);
    fprintf(stderr, "mux basic+ooo (cma=%d): ok\n", allow_cma ? 1 : 0);
}

void test_mux_queued_handoff() {
    auto p = make_pair_conns();
    const size_t n = 64 * 1024;
    auto data = pattern(n, 11);

    // data races ahead of registration: frames for an unregistered tag are
    // queued with offsets and drained into the sink at register time
    CHECK(p.a->send_bytes(3, data, /*allow_cma=*/false));
    std::this_thread::sleep_for(std::chrono::milliseconds(200)); // let RX land
    std::vector<uint8_t> dst(n, 0);
    p.b->table().register_sink(3, dst.data(), n);
    CHECK(p.b->table().wait_filled(3, n, 10'000) == n);
    p.b->table().unregister_sink(3);
    CHECK(dst == data);

    // small metadata frames with no sink are received via recv_queued
    std::vector<uint8_t> meta{9, 8, 7, 6};
    CHECK(p.a->send_copy(4, meta)->wait(5'000));
    auto got = p.b->table().recv_queued(4, 5'000);
    CHECK(got && *got == meta);

    // recv_queued honors its timeout when nothing arrives
    auto t0 = std::chrono::steady_clock::now();
    got = p.b->table().recv_queued(5, 150);
    CHECK(!got);
    CHECK(std::chrono::steady_clock::now() - t0 < std::chrono::seconds(3));
    fprintf(stderr, "mux queued handoff: ok\n");
}

void test_mux_purge_and_cancel() {
    auto p = make_pair_conns();
    const size_t n = 4 * 1024 * 1024;
    auto data = pattern(n, 13);

    // cancel mid-stream: unregister while the sender is still streaming.
    // Must not crash, must not write into freed memory (ASan would catch),
    // and the connection must stay usable for the next op.
    {
        auto dst = std::make_unique<std::vector<uint8_t>>(n, 0);
        p.b->table().register_sink(6, dst->data(), n);
        auto hs = p.a->send_async(6, 0, data, /*allow_cma=*/false);
        p.b->table().wait_filled(6, 64 * 1024, 5'000); // some bytes flowing
        p.b->table().unregister_sink(6);               // cancel mid-transfer
        dst.reset();                                    // buffer gone
        hs->wait(10'000); // sender completes (stream drained or dropped)
    }

    // leftover frames for tag 6 may still be queued; purge clears them and
    // the link still works for fresh tags afterwards
    p.b->table().purge_range(0, 100);
    const size_t m = 128 * 1024;
    auto data2 = pattern(m, 17);
    std::vector<uint8_t> dst2(m, 0);
    p.b->table().register_sink(101, dst2.data(), m);
    CHECK(p.a->send_bytes(101, data2, false));
    CHECK(p.b->table().wait_filled(101, m, 10'000) == m);
    p.b->table().unregister_sink(101);
    CHECK(dst2 == data2);
    fprintf(stderr, "mux purge+cancel: ok\n");
}

void test_mux_concurrent_tags() {
    auto p = make_pair_conns();
    const int ntags = 8;
    const size_t n = 128 * 1024;
    std::vector<std::vector<uint8_t>> payloads, dsts(ntags);
    payloads.reserve(ntags);
    for (int t = 0; t < ntags; ++t) {
        payloads.push_back(pattern(n, 100 + t));
        dsts[t].assign(n, 0);
        p.b->table().register_sink(200 + t, dsts[t].data(), n);
    }
    std::vector<std::thread> senders;
    senders.reserve(ntags);
    for (int t = 0; t < ntags; ++t)
        senders.emplace_back([&, t] {
            CHECK(p.a->send_bytes(200 + t, payloads[t], /*allow_cma=*/t % 2 == 0));
        });
    for (auto &th : senders) th.join();
    for (int t = 0; t < ntags; ++t) {
        CHECK(p.b->table().wait_filled(200 + t, n, 10'000) == n);
        p.b->table().unregister_sink(200 + t);
        CHECK(dsts[t] == payloads[t]);
    }
    fprintf(stderr, "mux concurrent tags: ok\n");
}

void test_mux_dup_accounting() {
    // Byte-conservation identity under relay-vs-direct races and re-issued
    // queue races: at quiescence, per receiving domain,
    //   rx_bytes + rx_relay_bytes - dup_bytes == unique payload delivered.
    auto db = std::make_shared<telemetry::Domain>();
    auto p = make_pair_conns(nullptr, nullptr, nullptr, db);
    const size_t n = 128 * 1024;
    auto data = pattern(n, 23);

    // relay window publishes [0, n/2), then a direct frame covers the full
    // [0, n) — the direct commit's n/2 overlap must land in dup_bytes
    // (model-checker finding: partial-overlap commits used to count zero)
    std::vector<uint8_t> dst(n, 0);
    p.tb->register_sink(50, dst.data(), n);
    auto &origin = db->edge("origin-peer");
    p.tb->deliver_window(50, 0, {data.begin(), data.begin() + n / 2},
                         &origin);
    auto h = p.a->send_async(50, 0, data, /*allow_cma=*/false);
    CHECK(h->wait(10'000));
    CHECK(p.tb->wait_filled(50, n, 10'000) == n);
    p.tb->unregister_sink(50);
    CHECK(dst == data);

    // the same (tag, off, len) window re-issued while no sink exists must
    // not queue twice (model-checker finding: register_sink's drain
    // publishes with no dup accounting, so the second copy is dropped and
    // charged at rx time)
    const size_t m = 64 * 1024;
    auto data2 = pattern(m, 29);
    CHECK(p.a->send_async(51, 0, data2, false)->wait(10'000));
    CHECK(p.a->send_async(51, 0, data2, false)->wait(10'000));
    std::this_thread::sleep_for(std::chrono::milliseconds(200)); // let RX land
    std::vector<uint8_t> dst2(m, 0);
    p.tb->register_sink(51, dst2.data(), m);
    CHECK(p.tb->wait_filled(51, m, 10'000) == m);
    p.tb->unregister_sink(51);
    CHECK(dst2 == data2);

    // the synthetic origin edge never carried a conn, so snapshot_edges()
    // filters it as a pre-rekey stub — read its counters directly
    uint64_t rx = origin.rx_bytes.load();
    uint64_t relay = origin.rx_relay_bytes.load();
    uint64_t dup = origin.dup_bytes.load();
    for (const auto &e : db->snapshot_edges()) {
        rx += e.rx_bytes;
        relay += e.rx_relay_bytes;
        dup += e.dup_bytes;
    }
    // unique payload: n (tag 50) + m (tag 51). Expected flows: rx = n + 2m
    // (direct full window + both re-issued copies), relay = n/2, dup = n/2
    // (direct overlap) + m (dropped duplicate queue copy).
    CHECK(rx + relay - dup == n + m);
    CHECK(relay == n / 2);
    CHECK(dup == n / 2 + m);
    fprintf(stderr,
            "mux dup accounting: ok (rx=%llu relay=%llu dup=%llu unique=%zu)\n",
            (unsigned long long)rx, (unsigned long long)relay,
            (unsigned long long)dup, n + m);
}

void test_mux_death_wakes_waiters() {
    auto p = make_pair_conns();
    std::vector<uint8_t> dst(1024, 0);
    p.b->table().register_sink(300, dst.data(), dst.size());

    std::thread waiter([&] {
        // must return (short prefix) once the only member conn dies, well
        // before the 30 s timeout
        auto t0 = std::chrono::steady_clock::now();
        p.b->table().wait_filled(300, dst.size(), 30'000);
        auto waited = std::chrono::steady_clock::now() - t0;
        CHECK(waited < std::chrono::seconds(25));
    });
    std::thread qwaiter([&] {
        auto r = p.b->table().recv_queued(301, 30'000);
        CHECK(!r); // dead link -> no frame will ever arrive
    });
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    p.a->close(); // peer goes away; b's RX loop sees EOF
    // give the death propagation a moment, then make sure waiters finish
    waiter.join();
    qwaiter.join();
    CHECK(!p.b->alive() || !p.a->alive());
    p.b->table().unregister_sink(300);
    fprintf(stderr, "mux death wakes waiters: ok\n");
}

// ---------------- registered shm regions (shm.hpp zero-copy path) --------

void test_shm_zero_copy_paths() {
    const size_t n = 512 * 1024; // > cma_min so the descriptor path engages

    // 1) sink-fill route: registered source buffer, plain sink. The receiver
    //    resolves the descriptor to its mapping and memcpys (no pvr).
    {
        auto p = make_pair_conns();
        auto *src = static_cast<uint8_t *>(shm::alloc(n));
        CHECK(src != nullptr);
        auto data = pattern(n, 23);
        memcpy(src, data.data(), n);
        std::vector<uint8_t> dst(n, 0);
        p.b->table().register_sink(1, dst.data(), n);
        CHECK(p.a->send_bytes(1, {src, n}, /*allow_cma=*/true));
        CHECK(p.b->table().wait_filled(1, n, 10'000) == n);
        p.b->table().unregister_sink(1);
        CHECK(dst == data);

        // 2) consumer-pull route: the consume callback must see the bytes in
        //    order, front to back, summing to the exact payload
        std::vector<uint8_t> scratch(n, 0);
        p.b->table().register_sink(2, scratch.data(), n, /*consumer_pull=*/true);
        auto h = p.a->send_async(2, 0, {src, n}, true);
        std::vector<uint8_t> got(n, 0);
        size_t seen = 0;
        auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(10);
        while (seen < n && std::chrono::steady_clock::now() < deadline) {
            bool pending = false;
            p.b->table().wait_filled(2, n, 100, &pending);
            if (!pending) continue;
            auto claim = p.b->table().consume_cma(
                2, n, 1, [&](const uint8_t *s, size_t lo, size_t len) {
                    memcpy(got.data() + lo, s, len);
                    seen = lo + len;
                    return true;
                });
            CHECK(claim == net::SinkTable::CmaClaim::kDone);
        }
        CHECK(seen == n);
        CHECK(got == data);
        CHECK(h->wait(10'000));
        p.b->table().unregister_sink(2);

        // 3) retire: free the region mid-connection; the NEXT send (from a
        //    fresh region) must still land correctly, and the freed base
        //    must be rejected on double free
        CHECK(shm::free_buf(src));
        CHECK(!shm::free_buf(src));
        auto *src2 = static_cast<uint8_t *>(shm::alloc(n));
        CHECK(src2 != nullptr);
        auto data2 = pattern(n, 29);
        memcpy(src2, data2.data(), n);
        std::vector<uint8_t> dst2(n, 0);
        p.b->table().register_sink(3, dst2.data(), n);
        CHECK(p.a->send_bytes(3, {src2, n}, true));
        CHECK(p.b->table().wait_filled(3, n, 10'000) == n);
        p.b->table().unregister_sink(3);
        CHECK(dst2 == data2);
        CHECK(shm::free_buf(src2));
    }

    // 4) fill_if_unmapped: a copy-consumer whose descriptor is NOT in any
    //    registered region gets routed into the sink on the calling thread
    //    (single pvr copy) instead of bouncing through the callback
    {
        auto p = make_pair_conns();
        auto data = pattern(n, 31); // plain heap buffer: unmapped
        std::vector<uint8_t> dst(n, 0);
        p.b->table().register_sink(4, dst.data(), n, /*consumer_pull=*/true);
        auto h = p.a->send_async(4, 0, data, true);
        size_t filled = 0;
        auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(10);
        bool callback_hit = false;
        while (filled < n && std::chrono::steady_clock::now() < deadline) {
            bool pending = false;
            filled = p.b->table().wait_filled(4, n, 100, &pending);
            if (pending) {
                auto claim = p.b->table().consume_cma(
                    4, n, 1,
                    [&](const uint8_t *, size_t, size_t) {
                        callback_hit = true;
                        return true;
                    },
                    /*fill_if_unmapped=*/true);
                // unmapped: must route to the sink, never the callback
                CHECK(claim == net::SinkTable::CmaClaim::kNone);
            }
        }
        CHECK(!callback_hit);
        CHECK(filled == n);
        CHECK(h->wait(10'000));
        p.b->table().unregister_sink(4);
        CHECK(dst == data);
    }
    CHECK(shm::live_regions() == 0);
    fprintf(stderr, "shm zero-copy paths: ok\n");
}

void test_link_striping() {
    // two conns sharing the receiver-side SinkTable; Link stripes one large
    // payload across the pool and the sink reassembles a contiguous prefix
    auto shared_rx = std::make_shared<net::SinkTable>();
    auto p1 = make_pair_conns(nullptr, shared_rx);
    auto p2 = make_pair_conns(nullptr, shared_rx);
    net::Link link({p1.a, p2.a}, p1.ta); // sender-side view

    const size_t n = 8 * 1024 * 1024;
    auto data = pattern(n, 23);
    std::vector<uint8_t> dst(n, 0);
    shared_rx->register_sink(400, dst.data(), n);
    auto handles = link.send_async(400, data, 0, /*allow_cma=*/false);
    CHECK(!handles.empty());
    CHECK(net::Link::wait_all(handles, 30'000));
    CHECK(shared_rx->wait_filled(400, n, 30'000) == n);
    shared_rx->unregister_sink(400);
    CHECK(dst == data);
    fprintf(stderr, "link striping: ok\n");
}

// ---------------- bandwidth probe ----------------

void test_bench_probe() {
    setenv("PCCLT_BENCH_SECONDS", "0.3", 1);
    setenv("PCCLT_BENCH_CONNECTIONS", "2", 1);

    bench::ServeState state;
    net::Listener lis;
    CHECK(lis.listen(0, 1, true));
    std::vector<std::thread> servers;
    Mutex servers_mu;
    lis.run_async([&](net::Socket s) {
        MutexLock lk(servers_mu);
        servers.emplace_back(
            [&state, sock = std::move(s)]() mutable {
                bench::serve_connection(std::move(sock), state);
            });
    });

    net::Addr target{127u << 24 | 1, lis.port()};
    // a finished probe's serve threads may still be draining (refcount not
    // yet back to 0), briefly reporting busy — retry like production does
    auto probe_retry = [&](net::Addr t) {
        double m = -2.0;
        for (int i = 0; i < 100 && m == -2.0; ++i) {
            m = bench::run_probe(t);
            if (m == -2.0)
                std::this_thread::sleep_for(std::chrono::milliseconds(50));
        }
        return m;
    };
    double m1 = probe_retry(target);
    double m2 = probe_retry(target);
    CHECK(m1 > 0 && m2 > 0);
    // stability: consecutive loopback estimates within a factor of 2
    // (the ±10% production claim needs a real NIC; CI loopback is noisier)
    CHECK(std::max(m1, m2) / std::min(m1, m2) < 2.0);

    // busy rejection: a fake prober holds the floor with a different token
    // (the previous probe's serve threads may still be draining, so acquiring
    // the floor can take a few tries)
    net::Socket holder;
    std::array<uint8_t, 16> token{};
    token.fill(0xEE);
    bool held = false;
    for (int i = 0; i < 100 && !held; ++i) {
        holder = net::Socket{};
        CHECK(holder.connect(target, 5000));
        Mutex mu;
        CHECK(net::send_frame(holder, mu, proto::kBenchHello, token));
        auto ack = net::recv_frame(holder, 5000);
        CHECK(ack && !ack->payload.empty());
        held = ack && !ack->payload.empty() && ack->payload[0] == 1;
        if (!held) {
            holder.close();
            std::this_thread::sleep_for(std::chrono::milliseconds(50));
        }
    }
    CHECK(held);
    CHECK(bench::run_probe(target) == -2.0); // told busy, not halved
    holder.shutdown();
    holder.close();

    lis.stop();
    {
        MutexLock lk(servers_mu);
        for (auto &t : servers) t.join();
    }
    unsetenv("PCCLT_BENCH_SECONDS");
    unsetenv("PCCLT_BENCH_CONNECTIONS");
    fprintf(stderr, "bench probe: ok\n");
}

// Conn pair with per-side telemetry domains, so a test can meter exactly
// what one transfer moved (the shared default domain accumulates across
// the whole binary).
struct MeteredPair {
    ConnPair p;
    std::shared_ptr<telemetry::Domain> da, db;
};

MeteredPair make_metered_pair() {
    MeteredPair m;
    m.da = std::make_shared<telemetry::Domain>();
    m.db = std::make_shared<telemetry::Domain>();
    m.p = make_pair_conns(nullptr, nullptr, m.da, m.db);
    return m;
}

struct LegStats {
    uint64_t tx_bytes = 0, tx_frames = 0, rx_bytes = 0, rx_frames = 0,
             zc_frames = 0, zc_reaps = 0;
};

// one A→B transfer of `n` bytes over a fresh metered pair under the
// CURRENT env (PCCLT_URING / PCCLT_ZEROCOPY_MIN_BYTES / chunk size),
// returning the per-edge accounting both sides observed
LegStats run_stream_leg(size_t n, uint64_t tag) {
    auto m = make_metered_pair();
    auto data = pattern(n, 0xC0FFEE ^ tag);
    std::vector<uint8_t> dst(n, 0);
    m.p.tb->register_sink(tag, dst.data(), n);
    CHECK(m.p.a->send_bytes(tag, data, /*allow_cma=*/false));
    CHECK(m.p.tb->wait_filled(tag, n, 10'000) == n);
    m.p.tb->unregister_sink(tag);
    CHECK(dst == data);
    m.p.a->close();
    m.p.b->close();
    LegStats out;
    for (const auto &e : m.da->snapshot_edges()) {
        out.tx_bytes += e.tx_bytes;
        out.tx_frames += e.tx_frames;
        out.zc_frames += e.tx_zc_frames;
        out.zc_reaps += e.tx_zc_reaps;
    }
    for (const auto &e : m.db->snapshot_edges()) {
        out.rx_bytes += e.rx_bytes;
        out.rx_frames += e.rx_frames;
    }
    return out;
}

void test_uring_stream_modes() {
    // The fallback-matrix oracle: the SAME payload, streamed through every
    // rung of the ladder (uring+zerocopy → uring → poll loop), must land
    // bit-identical with IDENTICAL per-edge accounting — byte conservation
    // and frame counts are invariant to the backend, and every frame's
    // header+payload left as one vectored submission (a header/body split
    // would double the frame count on the wire).
    setenv("PCCLT_MULTIPLEX_CHUNK_SIZE", "262144", 1); // 3 MB -> 12 frames
    const size_t n = 3u << 20;
    const uint64_t frames = 12;

    setenv("PCCLT_URING", "0", 1);
    LegStats poll = run_stream_leg(n, 60);
    CHECK(poll.tx_bytes == n && poll.rx_bytes == n);
    CHECK(poll.tx_frames == frames && poll.rx_frames == frames);
    CHECK(poll.zc_frames == 0 && poll.zc_reaps == 0);

    if (net::uring::kernel_level() < 1) {
        // skip WITH reason, never silently: CI greps for either verdict
        fprintf(stderr, "uring stream modes: SKIP (io_uring unavailable on "
                        "this kernel; poll-loop leg verified)\n");
        unsetenv("PCCLT_MULTIPLEX_CHUNK_SIZE");
        unsetenv("PCCLT_URING");
        return;
    }

    setenv("PCCLT_URING", "1", 1);
    setenv("PCCLT_ZEROCOPY_MIN_BYTES", "0", 1); // rung: uring, no zerocopy
    LegStats ur = run_stream_leg(n, 61);
    CHECK(ur.tx_bytes == n && ur.rx_bytes == n);
    CHECK(ur.tx_frames == frames && ur.rx_frames == frames);
    CHECK(ur.zc_frames == 0 && ur.zc_reaps == 0);

    bool zc = net::uring::kernel_level() >= 2;
    if (zc) {
        // rung: uring + MSG_ZEROCOPY on every frame; each ZC send must be
        // reaped exactly once (pages returned) before its handle completed
        setenv("PCCLT_ZEROCOPY_MIN_BYTES", "1", 1);
        LegStats z = run_stream_leg(n, 62);
        CHECK(z.tx_bytes == n && z.rx_bytes == n);
        CHECK(z.tx_frames == frames && z.rx_frames == frames);
        CHECK(z.zc_frames == frames);
        CHECK(z.zc_reaps == z.zc_frames);
    } else {
        fprintf(stderr, "uring stream modes: zerocopy rung SKIP (kernel "
                        "lacks SENDMSG_ZC)\n");
    }
    unsetenv("PCCLT_ZEROCOPY_MIN_BYTES");
    unsetenv("PCCLT_URING");
    unsetenv("PCCLT_MULTIPLEX_CHUNK_SIZE");
    fprintf(stderr, "uring stream modes: ok (12 frames each rung%s)\n",
            zc ? ", zc reaped" : "");
}

void test_uring_wire_pacing() {
    // netem must shape the io_uring path identically to the poll loop: the
    // per-edge egress bucket paces every frame BEFORE submission, so a
    // batched submit cannot outrun the emulated wire.
    if (net::uring::kernel_level() < 1) {
        fprintf(stderr, "uring wire pacing: SKIP (io_uring unavailable on "
                        "this kernel)\n");
        return;
    }
    setenv("PCCLT_URING", "1", 1);
    setenv("PCCLT_WIRE_MBPS", "200", 1); // 25 MB/s
    auto m = make_metered_pair();
    CHECK(!m.p.a->cma_eligible());
    const size_t n = 4 * 1024 * 1024;
    auto data = pattern(n, 77);
    std::vector<uint8_t> dst(n, 0);
    m.p.tb->register_sink(70, dst.data(), n);
    auto t0 = std::chrono::steady_clock::now();
    CHECK(m.p.a->send_bytes(70, data, /*allow_cma=*/true));
    CHECK(m.p.tb->wait_filled(70, n, 10'000) == n);
    double s = std::chrono::duration<double>(
                   std::chrono::steady_clock::now() - t0).count();
    m.p.tb->unregister_sink(70);
    CHECK(dst == data);
    CHECK(s >= 0.140); // 4 MB at 25 MB/s = 160 ms minimum
    CHECK(s < 2.0);
    uint64_t tx = 0, rx = 0;
    for (const auto &e : m.da->snapshot_edges()) tx += e.tx_bytes;
    for (const auto &e : m.db->snapshot_edges()) rx += e.rx_bytes;
    CHECK(tx == n && rx == n); // conservation under emulation + uring
    m.p.a->close();
    m.p.b->close();
    unsetenv("PCCLT_WIRE_MBPS");
    unsetenv("PCCLT_URING");
    fprintf(stderr, "uring wire pacing: ok (%.0f ms for 4 MB @ 25 MB/s)\n",
            s * 1e3);
}

void test_wire_pacing() {
    // PCCLT_WIRE_MBPS throttles egress to the emulated rate and must defeat
    // the same-host zero-copy transports (a WAN cannot be bypassed). Rate is
    // re-read per conn construction, so setting it here affects this pair.
    setenv("PCCLT_WIRE_MBPS", "200", 1); // 25 MB/s
    auto p = make_pair_conns();
    CHECK(!p.a->cma_eligible()); // pacing forces the TCP wire path
    const size_t n = 4 * 1024 * 1024;
    auto data = pattern(n, 23);
    std::vector<uint8_t> dst(n, 0);
    p.b->table().register_sink(30, dst.data(), n);
    auto t0 = std::chrono::steady_clock::now();
    CHECK(p.a->send_bytes(30, data, /*allow_cma=*/true));
    CHECK(p.b->table().wait_filled(30, n, 10'000) == n);
    double s = std::chrono::duration<double>(
                   std::chrono::steady_clock::now() - t0).count();
    p.b->table().unregister_sink(30);
    CHECK(dst == data);
    // 4 MB at 25 MB/s = 160 ms minimum; loopback unpaced would be < 10 ms.
    CHECK(s >= 0.140);
    CHECK(s < 2.0); // and the pacer must not be wildly over-throttling
    unsetenv("PCCLT_WIRE_MBPS");
    auto q = make_pair_conns(); // refreshes the pacer off for later tests
    fprintf(stderr, "wire pacing: ok (%.0f ms for 4 MB @ 25 MB/s)\n", s * 1e3);
}

void test_wire_per_edge() {
    // Per-edge emulation (netem.hpp): ONE process models a heterogeneous
    // mesh. The map keys the connector's egress by the listener's endpoint;
    // the reverse direction has no entry and stays free — asymmetry the old
    // process-global pacer could not express.
    auto accepted = std::make_shared<std::atomic<bool>>(false);
    auto accepted_sock = std::make_shared<net::Socket>();
    net::Listener listener;
    CHECK(listener.listen(0, 1, /*loopback_only=*/true));
    listener.run_async([accepted, accepted_sock](net::Socket s) {
        *accepted_sock = std::move(s);
        accepted->store(true);
    });
    // 100 Mbit/s toward the listener port + toward a second (canonical)
    // endpoint used to exercise set_wire_peer re-resolution below. Set
    // BEFORE the conns construct: the registry re-reads env per conn.
    // 1009 is a privileged port, outside any sane ip_local_port_range
    // (this CI container uses 16000-65535, stock Linux 32768-60999), so
    // the accepted conn's kernel-assigned source port can never collide
    // with the canonical-endpoint map key.
    char map[128];
    snprintf(map, sizeof map, "127.0.0.1:%u=100,127.0.0.1:1009=100",
             listener.port());
    setenv("PCCLT_WIRE_MBPS_MAP", map, 1);
    net::Socket c;
    CHECK(c.connect(net::Addr{127u << 24 | 1, listener.port()}, 5000));
    for (int i = 0; i < 500 && !accepted->load(); ++i)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    CHECK(accepted->load());
    listener.stop();
    auto ta = std::make_shared<net::SinkTable>();
    auto tb = std::make_shared<net::SinkTable>();
    auto a = std::make_shared<net::MultiplexConn>(std::move(c), ta);
    auto b = std::make_shared<net::MultiplexConn>(std::move(*accepted_sock), tb);
    ta->attach(a);
    tb->attach(b);
    a->run();
    b->run();

    CHECK(!a->cma_eligible()); // a's edge is emulated: zero-copy defeated
    CHECK(b->cma_eligible());  // b's edge (ephemeral peer port) is free

    const size_t n = 2 * 1024 * 1024; // 2 MB @ 12.5 MB/s = 160 ms minimum
    auto data = pattern(n, 31);
    std::vector<uint8_t> dst(n, 0);
    tb->register_sink(40, dst.data(), n);
    auto t0 = std::chrono::steady_clock::now();
    CHECK(a->send_bytes(40, data, /*allow_cma=*/true));
    CHECK(tb->wait_filled(40, n, 10'000) == n);
    double slow_s = std::chrono::duration<double>(
                        std::chrono::steady_clock::now() - t0).count();
    tb->unregister_sink(40);
    CHECK(dst == data);
    CHECK(slow_s >= 0.140);
    CHECK(slow_s < 2.0);

    // reverse direction: unconstrained — must be far under the paced time.
    // Best of 3: a single 2 MB loopback pass can eat a ~200 ms scheduler
    // stall on a loaded 2-core host, which is NOT the pacing under test.
    double fast_s = 1e9;
    for (int rep = 0; rep < 3; ++rep) {
        std::vector<uint8_t> dst2(n, 0);
        uint64_t tag = 41 + 100 * rep;
        ta->register_sink(tag, dst2.data(), n);
        t0 = std::chrono::steady_clock::now();
        CHECK(b->send_bytes(tag, data, /*allow_cma=*/false));
        CHECK(ta->wait_filled(tag, n, 10'000) == n);
        fast_s = std::min(fast_s,
                          std::chrono::duration<double>(
                              std::chrono::steady_clock::now() - t0).count());
        ta->unregister_sink(tag);
        CHECK(dst2 == data);
    }
    CHECK(fast_s < slow_s / 2.0);

    // set_wire_peer re-keys b by a "canonical" endpoint with a map entry
    // (what the P2P hello does for accepted conns): b's egress now paces
    CHECK(b->socket().peer_addr().port != 1009); // ephemeral != canonical
    b->set_wire_peer(net::Addr{127u << 24 | 1, 1009});
    std::vector<uint8_t> dst3(n, 0);
    ta->register_sink(42, dst3.data(), n);
    t0 = std::chrono::steady_clock::now();
    CHECK(b->send_bytes(42, data, /*allow_cma=*/false));
    CHECK(ta->wait_filled(42, n, 10'000) == n);
    double rekeyed_s = std::chrono::duration<double>(
                           std::chrono::steady_clock::now() - t0).count();
    ta->unregister_sink(42);
    CHECK(dst3 == data);
    CHECK(rekeyed_s >= 0.140);

    a->close();
    b->close();
    unsetenv("PCCLT_WIRE_MBPS_MAP");
    fprintf(stderr,
            "wire per-edge: ok (paced %.0f ms / free %.0f ms / rekeyed "
            "%.0f ms for 2 MB @ 12.5 MB/s)\n",
            slow_s * 1e3, fast_s * 1e3, rekeyed_s * 1e3);
}

} // namespace

int main() {
    test_frame_roundtrip();
    test_listener_port_bump();
    test_control_client_matching();
    test_mux_basic_and_ooo(false);
    test_mux_basic_and_ooo(true); // same-host CMA path
    test_mux_queued_handoff();
    test_mux_purge_and_cancel();
    test_mux_concurrent_tags();
    test_mux_dup_accounting();
    test_mux_death_wakes_waiters();
    test_shm_zero_copy_paths();
    test_link_striping();
    test_uring_stream_modes();
    test_uring_wire_pacing();
    test_wire_pacing();
    test_wire_per_edge();
    test_bench_probe();
    if (failures) {
        fprintf(stderr, "SOCKTEST FAILED (%d checks)\n", failures);
        return 1;
    }
    fprintf(stderr, "SOCKTEST PASSED\n");
    return 0;
}
