// Leveled stream logger for the native core.
// Reference parity: /root/reference/log/include/pccl_log.hpp (stream logger,
// env-selected level) — re-designed as a small macro-free API.
// Env: PCCLT_LOG_LEVEL in {TRACE, DEBUG, INFO, WARN, ERROR, FATAL}; default INFO.
#pragma once

#include <sstream>
#include <string>

namespace pcclt::log {

enum class Level : int { kTrace = 0, kDebug, kInfo, kWarn, kError, kFatal };

Level threshold();
void set_threshold(Level lv);
void write(Level lv, const std::string &msg);

// Usage: PLOG(kDebug) << "x=" << x;
class Line {
public:
    explicit Line(Level lv) : lv_(lv) {}
    ~Line() { write(lv_, ss_.str()); }
    template <typename T> Line &operator<<(const T &v) {
        ss_ << v;
        return *this;
    }

private:
    Level lv_;
    std::ostringstream ss_;
};

} // namespace pcclt::log

#define PLOG(level)                                                            \
    if (::pcclt::log::Level::level >= ::pcclt::log::threshold())               \
    ::pcclt::log::Line(::pcclt::log::Level::level)
