// Clang thread-safety annotations + annotated lock primitives.
//
// Compile-time lock discipline for the native core: every shared field
// declares the mutex that guards it (PCCLT_GUARDED_BY) and every function
// declares its lock contract (PCCLT_REQUIRES / PCCLT_ACQUIRE / ...), so a
// forgotten lock is a BUILD ERROR under `clang++ -Werror=thread-safety`
// (cmake -DPCCLT_ANALYZE=ON, or `python -m tools.pcclt_check --checker tsa`
// which drives the same analysis through libclang) instead of a data race
// TSan catches only when a test happens to hit it. The macro set mirrors
// the abseil/LLVM discipline (clang.llvm.org/docs/ThreadSafetyAnalysis);
// under GCC (the default toolchain) every macro expands to nothing and
// pcclt::Mutex is a zero-overhead veneer over std::mutex — verified by
// pcclt_selftest's test_lock_annotations in the asan/tsan lanes.
//
// Usage rules (enforced tree-wide, see docs/11_static_analysis.md):
//  * shared state uses pcclt::Mutex, never bare std::mutex — the analysis
//    only understands annotated capabilities;
//  * scoped locking uses pcclt::MutexLock (a SCOPED_CAPABILITY);
//  * condition waits use pcclt::CondVar, whose wait(mu) REQUIRES(mu) —
//    std::condition_variable's unique_lock protocol is invisible to the
//    analysis and would leak unannotated unlock/relock pairs;
//  * single-threaded-by-design classes keep using the runtime
//    PCCLT_THREAD_GUARD (thread_guard.hpp) — that invariant ("only one
//    thread ever enters") is not expressible as a capability.
#pragma once

#include <chrono>
#include <condition_variable>
#include <mutex>

#if defined(__clang__) && defined(__has_attribute)
#define PCCLT_TSA(x) __attribute__((x))
#else
#define PCCLT_TSA(x) // no-op: GCC/MSVC have no thread-safety analysis
#endif

// --- capability declarations ---
#define PCCLT_CAPABILITY(x) PCCLT_TSA(capability(x))
#define PCCLT_SCOPED_CAPABILITY PCCLT_TSA(scoped_lockable)

// --- data annotations ---
#define PCCLT_GUARDED_BY(x) PCCLT_TSA(guarded_by(x))
#define PCCLT_PT_GUARDED_BY(x) PCCLT_TSA(pt_guarded_by(x))

// --- function contracts ---
#define PCCLT_REQUIRES(...) PCCLT_TSA(requires_capability(__VA_ARGS__))
#define PCCLT_REQUIRES_SHARED(...) \
    PCCLT_TSA(requires_shared_capability(__VA_ARGS__))
#define PCCLT_ACQUIRE(...) PCCLT_TSA(acquire_capability(__VA_ARGS__))
#define PCCLT_RELEASE(...) PCCLT_TSA(release_capability(__VA_ARGS__))
#define PCCLT_TRY_ACQUIRE(...) PCCLT_TSA(try_acquire_capability(__VA_ARGS__))
#define PCCLT_EXCLUDES(...) PCCLT_TSA(locks_excluded(__VA_ARGS__))
#define PCCLT_RETURN_CAPABILITY(x) PCCLT_TSA(lock_returned(x))

// --- ordering + escape hatch ---
#define PCCLT_ACQUIRED_BEFORE(...) PCCLT_TSA(acquired_before(__VA_ARGS__))
#define PCCLT_ACQUIRED_AFTER(...) PCCLT_TSA(acquired_after(__VA_ARGS__))
// For the handful of protocols the analysis cannot express (lock handoff
// across threads, init-before-publish). Every use must carry a comment
// saying WHY the invariant holds.
#define PCCLT_NO_TSA PCCLT_TSA(no_thread_safety_analysis)

namespace pcclt {

// std::mutex with a declared capability. Same layout, same codegen (every
// member is a forwarding inline), but lockable state the analysis can track.
class PCCLT_CAPABILITY("mutex") Mutex {
public:
    Mutex() = default;
    Mutex(const Mutex &) = delete;
    Mutex &operator=(const Mutex &) = delete;

    void lock() PCCLT_ACQUIRE() { mu_.lock(); }
    void unlock() PCCLT_RELEASE() { mu_.unlock(); }
    bool try_lock() PCCLT_TRY_ACQUIRE(true) { return mu_.try_lock(); }

private:
    friend class CondVar;
    std::mutex mu_;
};

// RAII scoped lock over Mutex (abseil's MutexLock + ReleasableMutexLock in
// one: unlock()/lock() allow the wait_not_busy-style drop-and-reacquire
// windows the socket layer needs, tracked by the analysis).
class PCCLT_SCOPED_CAPABILITY MutexLock {
public:
    explicit MutexLock(Mutex &mu) PCCLT_ACQUIRE(mu) : mu_(mu), held_(true) {
        mu_.lock();
    }
    ~MutexLock() PCCLT_RELEASE() {
        if (held_) mu_.unlock();
    }
    MutexLock(const MutexLock &) = delete;
    MutexLock &operator=(const MutexLock &) = delete;

    void unlock() PCCLT_RELEASE() {
        held_ = false;
        mu_.unlock();
    }
    void lock() PCCLT_ACQUIRE() {
        mu_.lock();
        held_ = true;
    }

private:
    Mutex &mu_;
    bool held_;
};

// Condition variable whose waits take the annotated Mutex DIRECTLY (it
// satisfies BasicLockable), so the unlock-while-waiting/relock-on-wake
// protocol stays inside one REQUIRES(mu) call the analysis understands.
class CondVar {
public:
    void notify_one() noexcept { cv_.notify_one(); }
    void notify_all() noexcept { cv_.notify_all(); }

    void wait(Mutex &mu) PCCLT_REQUIRES(mu) { cv_.wait(mu); }

    template <typename Rep, typename Period>
    std::cv_status wait_for(Mutex &mu,
                            const std::chrono::duration<Rep, Period> &d)
        PCCLT_REQUIRES(mu) {
        return cv_.wait_for(mu, d);
    }

    template <typename Clock, typename Duration>
    std::cv_status wait_until(Mutex &mu,
                              const std::chrono::time_point<Clock, Duration> &tp)
        PCCLT_REQUIRES(mu) {
        return cv_.wait_until(mu, tp);
    }

private:
    std::condition_variable_any cv_;
};

} // namespace pcclt
