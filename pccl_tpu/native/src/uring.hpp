// Minimal raw-syscall io_uring backend for the WAN data plane.
//
// Why not liburing: the build must not grow dependencies, and the distro
// header on the build hosts predates SEND_ZC — so the (stable, versioned)
// kernel ABI is declared here directly and everything goes through
// syscall(2). Scope is deliberately tiny: one submission/completion ring
// per user, batched linked SQEs, no SQPOLL, no registered buffers.
//
// Fallback ladder (docs/08_performance.md):
//   level 2: io_uring + MSG_ZEROCOPY  (IORING_OP_SENDMSG_ZC, kernel >= 6.1)
//   level 1: io_uring                 (batched SENDMSG/RECV, kernel >= 5.19
//                                      for MSG_WAITALL retry semantics)
//   level 0: the classic poll + sendmsg/recv loop in sockets.cpp
//
// kernel_level() probes once per process (io_uring_setup + opcode probe);
// enabled() additionally consults PCCLT_URING on every call so tests can
// flip the env at runtime (0 = force the poll loop, 1/unset = use io_uring
// when the kernel has it). Zerocopy is gated by PCCLT_ZEROCOPY_MIN_BYTES
// (0 disables; frames below the threshold are cheaper to copy than to pin).
//
// Threading: a Ring is NOT thread-safe — each user owns one (the conn TX
// ring is only touched under wr_mu_, the RX ring only on the RX thread),
// so the backend itself needs no locks.
#pragma once

#include <cstddef>
#include <cstdint>

namespace pcclt::net::uring {

// ---- kernel ABI (linux/io_uring.h, stable) ----

struct Sqe {
    uint8_t opcode = 0;
    uint8_t flags = 0;
    uint16_t ioprio = 0;
    int32_t fd = -1;
    uint64_t off = 0;
    uint64_t addr = 0;   // buffer (RECV) or struct msghdr * (SENDMSG[_ZC])
    uint32_t len = 0;    // buffer length (RECV) or 1 (SENDMSG[_ZC])
    uint32_t msg_flags = 0;
    uint64_t user_data = 0;
    uint16_t buf_index = 0;
    uint16_t personality = 0;
    int32_t splice_fd_in = 0;
    uint64_t addr3 = 0;
    uint64_t pad2 = 0;
};
static_assert(sizeof(Sqe) == 64, "io_uring_sqe ABI");

inline constexpr uint8_t kOpSendmsg = 9;
inline constexpr uint8_t kOpSend = 26;
inline constexpr uint8_t kOpRecv = 27;
inline constexpr uint8_t kOpSendmsgZc = 48;
inline constexpr uint8_t kSqeIoLink = 1u << 2;   // IOSQE_IO_LINK
inline constexpr uint32_t kCqeFMore = 1u << 1;   // IORING_CQE_F_MORE
inline constexpr uint32_t kCqeFNotif = 1u << 3;  // IORING_CQE_F_NOTIF

// ---- feature detection ----

// 0 = no usable io_uring; 1 = batched SENDMSG/RECV; 2 = + SENDMSG_ZC.
// Probed once per process (setup + IORING_REGISTER_PROBE) — the result is
// a kernel property and cannot change at runtime.
int kernel_level();

// PCCLT_URING env gate over kernel_level(): "0" forces level 0; anything
// else (incl. unset) uses what the kernel has. Read per call — conns
// sample it at construction, so tests flip behavior per connection.
bool enabled();

// Zerocopy threshold in bytes: 0 = zerocopy off (also when the kernel
// lacks SENDMSG_ZC). Default 1 MiB — below that, pinning pages +
// completion reaping costs more than one copy into the socket buffer.
size_t zc_min_bytes();

// ---- one submission/completion ring ----

class Ring {
public:
    Ring() = default;
    ~Ring();
    Ring(const Ring &) = delete;
    Ring &operator=(const Ring &) = delete;

    // mmap the rings; false → caller takes the poll-loop fallback
    bool init(unsigned entries);
    bool valid() const { return ring_fd_ >= 0; }

    // Next free SQE (zeroed), or nullptr when the SQ is full (callers size
    // batches under `entries`, so null is a programming-error guard, not a
    // flow-control mechanism).
    Sqe *get_sqe();

    // A prepared-but-unsubmitted SQE, counting back from the local tail
    // (back == 1 → most recently prepared). Lets a caller set link flags
    // once the batch's final size is known — nothing is visible to the
    // kernel until submit() publishes the tail.
    Sqe *sqe_at_tail(unsigned back) {
        return &sqes_[(sqe_tail_ - back) & sq_mask_];
    }

    // Publish all prepared SQEs in ONE io_uring_enter (the batched-
    // submission point). Returns number consumed, or -errno.
    int submit();

    struct Cqe {
        uint64_t user_data = 0;
        int32_t res = 0;
        uint32_t flags = 0;
    };
    // Block until a completion is available and pop it. false on a hard
    // ring error (caller fails the stream like any socket error).
    bool next_cqe(Cqe &out);
    // Pop a completion only if one is already posted (no kernel wait).
    // Backs the lazy MSG_ZEROCOPY notif reaping: later submits scoop
    // earlier batches' notifs without ever blocking for them.
    bool peek_cqe(Cqe &out);

private:
    void unmap();

    int ring_fd_ = -1;
    unsigned sq_entries_ = 0, cq_entries_ = 0;
    uint32_t sq_mask_ = 0, cq_mask_ = 0;
    // local SQE cursor (kernel tail published at submit())
    uint32_t sqe_tail_ = 0;
    uint8_t *sq_ring_ = nullptr, *cq_ring_ = nullptr;
    size_t sq_ring_sz_ = 0, cq_ring_sz_ = 0;
    bool single_mmap_ = false;
    Sqe *sqes_ = nullptr;
    size_t sqes_sz_ = 0;
    uint32_t *sq_khead_ = nullptr, *sq_ktail_ = nullptr, *sq_array_ = nullptr;
    uint32_t *cq_khead_ = nullptr, *cq_ktail_ = nullptr;
    uint8_t *cqes_ = nullptr;  // io_uring_cqe[] (16 bytes each)
};

}  // namespace pcclt::net::uring
