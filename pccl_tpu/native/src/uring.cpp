#include "uring.hpp"

#include <atomic>
#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

#include "log.hpp"

namespace pcclt::net::uring {

namespace {

// setup/enter/register syscall numbers are identical across the 64-bit
// ABIs (asm-generic); the distro unistd.h may predate them
#ifndef __NR_io_uring_setup
#define __NR_io_uring_setup 425
#endif
#ifndef __NR_io_uring_enter
#define __NR_io_uring_enter 426
#endif
#ifndef __NR_io_uring_register
#define __NR_io_uring_register 427
#endif

struct SqOffsets {
    uint32_t head, tail, ring_mask, ring_entries, flags, dropped, array, resv1;
    uint64_t user_addr;
};
struct CqOffsets {
    uint32_t head, tail, ring_mask, ring_entries, overflow, cqes, flags, resv1;
    uint64_t user_addr;
};
struct Params {
    uint32_t sq_entries, cq_entries, flags, sq_thread_cpu, sq_thread_idle,
        features, wq_fd;
    uint32_t resv[3];
    SqOffsets sq_off;
    CqOffsets cq_off;
};
struct CqeRaw {
    uint64_t user_data;
    int32_t res;
    uint32_t flags;
};
struct ProbeOp {
    uint8_t op, resv;
    uint16_t flags;
    uint32_t resv2;
};
struct ProbeHdr {
    uint8_t last_op, ops_len;
    uint16_t resv;
    uint32_t resv2[3];
    // ProbeOp ops[] follows
};

constexpr uint64_t kOffSqRing = 0;
constexpr uint64_t kOffCqRing = 0x8000000ull;
constexpr uint64_t kOffSqes = 0x10000000ull;
constexpr uint32_t kEnterGetevents = 1u;
constexpr uint32_t kFeatSingleMmap = 1u;
constexpr unsigned kRegisterProbe = 8;
constexpr uint16_t kOpSupported = 1u;

int sys_setup(unsigned entries, Params *p) {
    return static_cast<int>(syscall(__NR_io_uring_setup, entries, p));
}
int sys_enter(int fd, unsigned to_submit, unsigned min_complete,
              unsigned flags) {
    return static_cast<int>(syscall(__NR_io_uring_enter, fd, to_submit,
                                    min_complete, flags, nullptr, 0));
}
int sys_register(int fd, unsigned opcode, void *arg, unsigned nr_args) {
    return static_cast<int>(
        syscall(__NR_io_uring_register, fd, opcode, arg, nr_args));
}

uint32_t load_acq(const uint32_t *p) {
    return std::atomic_ref<const uint32_t>(*p).load(std::memory_order_acquire);
}
void store_rel(uint32_t *p, uint32_t v) {
    std::atomic_ref<uint32_t>(*p).store(v, std::memory_order_release);
}

// IORING_OP_SOCKET landed in 5.19 — the same release as the MSG_WAITALL
// retry semantics for send/recv that the batched backend depends on — so
// its presence in the opcode probe is the version gate: anything older
// (incl. pre-5.6 kernels whose REGISTER_PROBE itself fails) stays on the
// poll loop rather than having routine short reads kill connections.
constexpr uint8_t kOpSocket = 45;

int probe_kernel() {
    Params p{};
    int fd = sys_setup(4, &p);
    if (fd < 0) return 0;  // ENOSYS / EPERM / io_uring_disabled sysctl
    alignas(8) uint8_t buf[sizeof(ProbeHdr) + 256 * sizeof(ProbeOp)] = {};
    auto *hdr = reinterpret_cast<ProbeHdr *>(buf);
    auto *ops = reinterpret_cast<ProbeOp *>(buf + sizeof(ProbeHdr));
    int level = 0;
    if (sys_register(fd, kRegisterProbe, buf, 256) == 0 &&
        hdr->last_op >= kOpSocket &&
        (ops[kOpSendmsg].flags & kOpSupported) &&
        (ops[kOpRecv].flags & kOpSupported) &&
        (ops[kOpSocket].flags & kOpSupported)) {
        level = 1;
        if (hdr->last_op >= kOpSendmsgZc &&
            (ops[kOpSendmsgZc].flags & kOpSupported))
            level = 2;
    }
    close(fd);
    return level;
}

}  // namespace

int kernel_level() {
    static const int level = probe_kernel();
    return level;
}

bool enabled() {
    const char *e = std::getenv("PCCLT_URING");
    if (e && e[0] == '0') return false;
    return kernel_level() >= 1;
}

size_t zc_min_bytes() {
    if (kernel_level() < 2) return 0;
    if (const char *e = std::getenv("PCCLT_ZEROCOPY_MIN_BYTES")) {
        long long v = atoll(e);
        return v <= 0 ? 0 : static_cast<size_t>(v);
    }
    return 1u << 20;
}

Ring::~Ring() { unmap(); }

void Ring::unmap() {
    if (sqes_) munmap(sqes_, sqes_sz_);
    if (sq_ring_) munmap(sq_ring_, sq_ring_sz_);
    if (cq_ring_ && !single_mmap_) munmap(cq_ring_, cq_ring_sz_);
    sqes_ = nullptr;
    sq_ring_ = cq_ring_ = nullptr;
    if (ring_fd_ >= 0) close(ring_fd_);
    ring_fd_ = -1;
}

bool Ring::init(unsigned entries) {
    Params p{};
    int fd = sys_setup(entries, &p);
    if (fd < 0) return false;
    ring_fd_ = fd;
    sq_entries_ = p.sq_entries;
    cq_entries_ = p.cq_entries;
    single_mmap_ = (p.features & kFeatSingleMmap) != 0;
    sq_ring_sz_ = p.sq_off.array + p.sq_entries * sizeof(uint32_t);
    cq_ring_sz_ = p.cq_off.cqes + p.cq_entries * sizeof(CqeRaw);
    size_t sq_map = single_mmap_ ? std::max(sq_ring_sz_, cq_ring_sz_)
                                 : sq_ring_sz_;
    void *sq = mmap(nullptr, sq_map, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, fd, kOffSqRing);
    if (sq == MAP_FAILED) {
        unmap();
        return false;
    }
    sq_ring_ = static_cast<uint8_t *>(sq);
    sq_ring_sz_ = sq_map;
    if (single_mmap_) {
        cq_ring_ = sq_ring_;
    } else {
        void *cq = mmap(nullptr, cq_ring_sz_, PROT_READ | PROT_WRITE,
                        MAP_SHARED | MAP_POPULATE, fd, kOffCqRing);
        if (cq == MAP_FAILED) {
            unmap();
            return false;
        }
        cq_ring_ = static_cast<uint8_t *>(cq);
    }
    sqes_sz_ = p.sq_entries * sizeof(Sqe);
    void *sqes = mmap(nullptr, sqes_sz_, PROT_READ | PROT_WRITE,
                      MAP_SHARED | MAP_POPULATE, fd, kOffSqes);
    if (sqes == MAP_FAILED) {
        unmap();
        return false;
    }
    sqes_ = static_cast<Sqe *>(sqes);
    sq_khead_ = reinterpret_cast<uint32_t *>(sq_ring_ + p.sq_off.head);
    sq_ktail_ = reinterpret_cast<uint32_t *>(sq_ring_ + p.sq_off.tail);
    sq_mask_ = *reinterpret_cast<uint32_t *>(sq_ring_ + p.sq_off.ring_mask);
    sq_array_ = reinterpret_cast<uint32_t *>(sq_ring_ + p.sq_off.array);
    cq_khead_ = reinterpret_cast<uint32_t *>(cq_ring_ + p.cq_off.head);
    cq_ktail_ = reinterpret_cast<uint32_t *>(cq_ring_ + p.cq_off.tail);
    cq_mask_ = *reinterpret_cast<uint32_t *>(cq_ring_ + p.cq_off.ring_mask);
    cqes_ = cq_ring_ + p.cq_off.cqes;
    sqe_tail_ = *sq_ktail_;
    return true;
}

Sqe *Ring::get_sqe() {
    uint32_t head = load_acq(sq_khead_);
    if (sqe_tail_ - head >= sq_entries_) return nullptr;
    Sqe *s = &sqes_[sqe_tail_ & sq_mask_];
    *s = Sqe{};
    sq_array_[sqe_tail_ & sq_mask_] = sqe_tail_ & sq_mask_;
    ++sqe_tail_;
    return s;
}

int Ring::submit() {
    uint32_t ktail = *sq_ktail_;
    unsigned to_submit = sqe_tail_ - ktail;
    if (to_submit == 0) return 0;
    store_rel(sq_ktail_, sqe_tail_);
    while (true) {
        int r = sys_enter(ring_fd_, to_submit, 0, 0);
        if (r >= 0) return r;
        if (errno == EINTR) continue;
        return -errno;
    }
}

bool Ring::peek_cqe(Cqe &out) {
    uint32_t head = *cq_khead_;
    uint32_t tail = load_acq(cq_ktail_);
    if (head == tail) return false;
    const auto *c = reinterpret_cast<const CqeRaw *>(
        cqes_ + (head & cq_mask_) * sizeof(CqeRaw));
    out = {c->user_data, c->res, c->flags};
    store_rel(cq_khead_, head + 1);
    return true;
}

bool Ring::next_cqe(Cqe &out) {
    while (true) {
        uint32_t head = *cq_khead_;
        uint32_t tail = load_acq(cq_ktail_);
        if (head != tail) {
            const auto *c = reinterpret_cast<const CqeRaw *>(
                cqes_ + (head & cq_mask_) * sizeof(CqeRaw));
            out = {c->user_data, c->res, c->flags};
            store_rel(cq_khead_, head + 1);
            return true;
        }
        int r = sys_enter(ring_fd_, 0, 1, kEnterGetevents);
        if (r < 0 && errno != EINTR && errno != EAGAIN && errno != EBUSY) {
            PLOG(kError) << "io_uring_enter(GETEVENTS) failed: "
                         << strerror(errno);
            return false;
        }
    }
}

}  // namespace pcclt::net::uring
