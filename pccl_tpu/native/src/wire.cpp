#include "wire.hpp"
// header-only; this TU anchors the target.
