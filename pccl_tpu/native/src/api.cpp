// C API shim over the C++ client/master.
// Reference parity: src/pccl.cpp (validation + enum translation over CCoIP).
#include "../include/pcclt.h"

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <string>
#include <thread>
#include <vector>

#include "client.hpp"
#include "hash.hpp"
#include "journal.hpp"
#include "log.hpp"
#include "master.hpp"
#include "netem.hpp"
#include "shm.hpp"
#include "sockets.hpp"
#include "telemetry.hpp"
#include "version.hpp"

using pcclt::client::Client;
using pcclt::client::ClientConfig;
using pcclt::client::ReduceDesc;
using pcclt::client::Status;
using pcclt::master::Master;

struct pccltComm {
    Client *client;
};
struct pccltMaster {
    Master *master;
    bool launched = false;
};

namespace {

pccltResult_t to_result(Status s) {
    switch (s) {
    case Status::kOk: return pccltSuccess;
    case Status::kInvalid: return pccltInvalidArgument;
    case Status::kNotConnected: return pccltNotConnected;
    case Status::kConnectionLost: return pccltConnectionLost;
    case Status::kAborted: return pccltOperationAborted;
    case Status::kTooFewPeers: return pccltTooFewPeers;
    case Status::kDuplicateTag: return pccltDuplicateTag;
    case Status::kKicked: return pccltKicked;
    case Status::kMasterUnreachable: return pccltMasterUnreachable;
    case Status::kContentMismatch: return pccltContentMismatch;
    case Status::kPendingAsyncOps: return pccltPendingAsyncOps;
    default: return pccltInternalError;
    }
}

pcclt::proto::DType to_dtype(pccltDataType_t d) {
    return static_cast<pcclt::proto::DType>(d);
}

ReduceDesc to_desc(const pccltReduceDescriptor_t *d) {
    ReduceDesc r;
    r.tag = d->tag;
    r.op = static_cast<pcclt::proto::RedOp>(d->op);
    r.quant = static_cast<pcclt::proto::QuantAlgo>(d->quant_algo);
    r.quant_dtype = to_dtype(d->quant_dtype);
    return r;
}

void fill_info(pccltReduceInfo_t *out, const pcclt::client::ReduceInfo &in) {
    if (!out) return;
    out->tx_bytes = in.tx_bytes;
    out->rx_bytes = in.rx_bytes;
    out->world_size = in.world;
}

} // namespace

extern "C" {

pccltResult_t pccltInit(void) { return pccltSuccess; }

const char *pccltGetBuildInfo(void) {
    // version comes from version.hpp so this banner and the
    // pcclt_build_info metric can never drift apart
    static const std::string info = std::string("pcclt ") + pcclt::kPccltVersion +
                                    " (PCCP/2, tpu-native pccl-capability core)";
    return info.c_str();
}

// ---------------- master ----------------

pccltResult_t pccltCreateMasterEx(const char *listen_ip, uint16_t port,
                                  const char *journal_path, pccltMaster_t **out) {
    (void)listen_ip; // listens on all interfaces
    if (!out) return pccltInvalidArgument;
    std::string journal;
    if (journal_path) journal = journal_path; // "" = force-disable
    else if (const char *e = std::getenv("PCCLT_MASTER_JOURNAL")) journal = e;
    auto *m = new pccltMaster{new Master(port ? port : 48501, journal)};
    *out = m;
    return pccltSuccess;
}

pccltResult_t pccltCreateMaster(const char *listen_ip, uint16_t port,
                                pccltMaster_t **out) {
    return pccltCreateMasterEx(listen_ip, port, nullptr, out);
}

uint64_t pccltMasterEpoch(pccltMaster_t *m) { return m ? m->master->epoch() : 0; }

pccltResult_t pccltRunMaster(pccltMaster_t *m) {
    if (!m || m->launched) return pccltInvalidUsage;
    if (!m->master->launch()) return pccltInternalError;
    m->launched = true;
    return pccltSuccess;
}

pccltResult_t pccltInterruptMaster(pccltMaster_t *m) {
    if (!m) return pccltInvalidArgument;
    m->master->interrupt();
    return pccltSuccess;
}

pccltResult_t pccltMasterAwaitTermination(pccltMaster_t *m) {
    if (!m) return pccltInvalidArgument;
    m->master->join();
    return pccltSuccess;
}

pccltResult_t pccltDestroyMaster(pccltMaster_t *m) {
    if (!m) return pccltInvalidArgument;
    m->master->interrupt();
    m->master->join();
    delete m->master;
    delete m;
    return pccltSuccess;
}

uint16_t pccltMasterPort(pccltMaster_t *m) { return m ? m->master->port() : 0; }

uint16_t pccltMasterMetricsPort(pccltMaster_t *m) {
    return m ? m->master->metrics_port() : 0;
}

pccltResult_t pccltMasterGetHealth(pccltMaster_t *m, char *buf, uint64_t cap,
                                   uint64_t *need) {
    if (!m || !need || (cap && !buf)) return pccltInvalidArgument;
    std::string j = m->master->health_json();
    *need = j.size();
    if (cap) {
        uint64_t n = j.size() < cap - 1 ? j.size() : cap - 1;
        memcpy(buf, j.data(), n);
        buf[n] = 0;
    }
    return pccltSuccess;
}

// ---------------- communicator ----------------

pccltResult_t pccltCreateCommunicator(const pccltCommCreateParams_t *params,
                                      pccltComm_t **out) {
    if (!params || !out || !params->master_ip) return pccltInvalidArgument;
    auto addr = pcclt::net::Addr::parse(params->master_ip,
                                        params->master_port ? params->master_port : 48501);
    if (!addr) return pccltInvalidArgument;
    ClientConfig cfg;
    cfg.master = *addr;
    cfg.peer_group = params->peer_group;
    if (params->advertised_ip) cfg.adv_ip = params->advertised_ip;
    if (params->p2p_port) cfg.p2p_port = params->p2p_port;
    if (params->ss_port) cfg.ss_port = params->ss_port;
    if (params->bench_port) cfg.bench_port = params->bench_port;
    cfg.pool_size = params->p2p_connection_pool_size ? params->p2p_connection_pool_size : 1;
    cfg.reconnect_attempts = params->reconnect_attempts;
    cfg.reconnect_backoff_ms = static_cast<int>(params->reconnect_backoff_ms);
    cfg.reconnect_backoff_cap_ms =
        static_cast<int>(params->reconnect_backoff_cap_ms);
    *out = new pccltComm{new Client(cfg)};
    return pccltSuccess;
}

pccltResult_t pccltDestroyCommunicator(pccltComm_t *c) {
    if (!c) return pccltInvalidArgument;
    delete c->client;
    delete c;
    return pccltSuccess;
}

pccltResult_t pccltConnect(pccltComm_t *c) {
    if (!c) return pccltInvalidArgument;
    return to_result(c->client->connect());
}

pccltResult_t pccltGetAttribute(pccltComm_t *c, pccltAttribute_t attr, int64_t *out) {
    if (!c || !out) return pccltInvalidArgument;
    switch (attr) {
    case PCCLT_ATTR_GLOBAL_WORLD_SIZE: *out = c->client->global_world(); break;
    case PCCLT_ATTR_PEER_GROUP_WORLD_SIZE: *out = c->client->group_world(); break;
    case PCCLT_ATTR_NUM_DISTINCT_PEER_GROUPS: *out = c->client->num_groups(); break;
    case PCCLT_ATTR_LARGEST_PEER_GROUP_WORLD_SIZE: *out = c->client->largest_group(); break;
    case PCCLT_ATTR_MASTER_EPOCH:
        *out = static_cast<int64_t>(c->client->master_epoch());
        break;
    case PCCLT_ATTR_RECONNECT_COUNT:
        *out = static_cast<int64_t>(c->client->reconnect_count());
        break;
    case PCCLT_ATTR_SHARED_STATE_REVISION:
        *out = static_cast<int64_t>(c->client->shared_state_revision());
        break;
    default: return pccltInvalidArgument;
    }
    return pccltSuccess;
}

pccltResult_t pccltUpdateTopology(pccltComm_t *c) {
    if (!c) return pccltInvalidArgument;
    return to_result(c->client->update_topology());
}

pccltResult_t pccltArePeersPending(pccltComm_t *c, int *pending) {
    if (!c || !pending) return pccltInvalidArgument;
    bool p = false;
    auto st = c->client->are_peers_pending(p);
    *pending = p ? 1 : 0;
    return to_result(st);
}

pccltResult_t pccltOptimizeTopology(pccltComm_t *c) {
    if (!c) return pccltInvalidArgument;
    return to_result(c->client->optimize_topology());
}

// RedOp::kGather (5) is deliberately NOT reachable through the reduce
// descriptor: its recv sizing differs (world*count), and only
// pccltAllGather — which carries recv_capacity — may select it.
static bool valid_reduce_op(const pccltReduceDescriptor_t *d) { return d->op <= 4; }

pccltResult_t pccltAllReduce(pccltComm_t *c, const void *sendbuf, void *recvbuf,
                             uint64_t count, pccltDataType_t dtype,
                             const pccltReduceDescriptor_t *desc,
                             pccltReduceInfo_t *info) {
    if (!c || !desc || !valid_reduce_op(desc)) return pccltInvalidArgument;
    pcclt::client::ReduceInfo ri;
    auto st = c->client->all_reduce(sendbuf, recvbuf, count, to_dtype(dtype),
                                    to_desc(desc), &ri);
    fill_info(info, ri);
    return to_result(st);
}

pccltResult_t pccltAllGather(pccltComm_t *c, const void *sendbuf, void *recvbuf,
                             uint64_t send_count, uint64_t recv_capacity,
                             pccltDataType_t dtype, uint64_t tag,
                             pccltReduceInfo_t *info) {
    if (!c) return pccltInvalidArgument;
    pcclt::client::ReduceDesc d;
    d.tag = tag;
    d.op = pcclt::proto::RedOp::kGather;
    d.recv_capacity = recv_capacity;
    pcclt::client::ReduceInfo ri;
    auto st = c->client->all_reduce(sendbuf, recvbuf, send_count,
                                    to_dtype(dtype), d, &ri);
    fill_info(info, ri);
    return to_result(st);
}

pccltResult_t pccltGatherSlot(pccltComm_t *c, uint64_t *slot) {
    if (!c || !slot) return pccltInvalidArgument;
    return to_result(c->client->gather_slot(slot));
}

// ---- widened collective vocabulary (docs/12). The kind markers (RedOp 6..8)
// are selected HERE, never via the reduce descriptor — their buffer sizing
// differs, so each export carries what the worker's capacity check needs.

pccltResult_t pccltReduceScatter(pccltComm_t *c, const void *sendbuf,
                                 void *recvbuf, uint64_t count,
                                 uint64_t recv_capacity, pccltDataType_t dtype,
                                 const pccltReduceDescriptor_t *desc,
                                 uint64_t *recv_offset, uint64_t *recv_count,
                                 pccltReduceInfo_t *info) {
    if (!c || !desc || !valid_reduce_op(desc)) return pccltInvalidArgument;
    pcclt::client::ReduceDesc d = to_desc(desc);
    d.op = pcclt::proto::RedOp::kReduceScatter;
    d.recv_capacity = recv_capacity;
    pcclt::client::ReduceInfo ri;
    auto st = c->client->all_reduce(sendbuf, recvbuf, count, to_dtype(dtype),
                                    d, &ri);
    if (recv_offset) *recv_offset = ri.rs_offset;
    if (recv_count) *recv_count = ri.rs_count;
    fill_info(info, ri);
    return to_result(st);
}

pccltResult_t pccltBroadcast(pccltComm_t *c, void *buf, uint64_t count,
                             uint64_t root_slot, pccltDataType_t dtype,
                             const pccltReduceDescriptor_t *desc,
                             pccltReduceInfo_t *info) {
    if (!c || !desc) return pccltInvalidArgument;
    pcclt::client::ReduceDesc d = to_desc(desc);
    d.op = pcclt::proto::RedOp::kBroadcast;
    d.aux = root_slot;  // matched-parameters contract: mismatches kick
    pcclt::client::ReduceInfo ri;
    // in place: send == recv arms the worker's snapshot, the abort-retry
    // restore source for root and non-root alike
    auto st = c->client->all_reduce(buf, buf, count, to_dtype(dtype), d, &ri);
    fill_info(info, ri);
    return to_result(st);
}

pccltResult_t pccltAllToAll(pccltComm_t *c, const void *sendbuf, void *recvbuf,
                            uint64_t count_per_peer, uint64_t recv_capacity,
                            pccltDataType_t dtype,
                            const pccltReduceDescriptor_t *desc,
                            pccltReduceInfo_t *info) {
    if (!c || !desc) return pccltInvalidArgument;
    pcclt::client::ReduceDesc d = to_desc(desc);
    d.op = pcclt::proto::RedOp::kAllToAll;
    d.recv_capacity = recv_capacity;
    pcclt::client::ReduceInfo ri;
    auto st = c->client->all_reduce(sendbuf, recvbuf, count_per_peer,
                                    to_dtype(dtype), d, &ri);
    fill_info(info, ri);
    return to_result(st);
}

pccltResult_t pccltAllReduceAsync(pccltComm_t *c, const void *sendbuf, void *recvbuf,
                                  uint64_t count, pccltDataType_t dtype,
                                  const pccltReduceDescriptor_t *desc) {
    if (!c || !desc || !valid_reduce_op(desc)) return pccltInvalidArgument;
    return to_result(
        c->client->all_reduce_async(sendbuf, recvbuf, count, to_dtype(dtype), to_desc(desc)));
}

pccltResult_t pccltAwaitAsyncReduce(pccltComm_t *c, uint64_t tag,
                                    pccltReduceInfo_t *info) {
    if (!c) return pccltInvalidArgument;
    pcclt::client::ReduceInfo ri;
    auto st = c->client->await_reduce(tag, &ri);
    fill_info(info, ri);
    return to_result(st);
}

pccltResult_t pccltAllReduceMultipleWithRetry(pccltComm_t *c, const void *const *sendbufs,
                                              void *const *recvbufs, const uint64_t *counts,
                                              pccltDataType_t dtype,
                                              const pccltReduceDescriptor_t *descs,
                                              uint64_t n_ops, pccltReduceInfo_t *infos) {
    if (!c || !sendbufs || !recvbufs || !counts || !descs) return pccltInvalidArgument;
    for (uint64_t i = 0; i < n_ops; ++i)
        if (!valid_reduce_op(&descs[i])) return pccltInvalidArgument;
    std::vector<bool> done(n_ops, false);
    while (true) {
        // launch outstanding ops windowed over the concurrent-op cap (a
        // batch larger than PCCLT_MAX_CONCURRENT_COLLECTIVE_OPS drains the
        // oldest in-flight op to free a worker slot — the reference never
        // windows because its pool of 32 exceeds its test batches), await
        // them, retry failures with the (possibly shrunken) world —
        // reference pcclAllReduceMultipleWithRetry
        bool any_launched = false;
        bool all_ok = true;
        std::deque<uint64_t> inflight;
        pccltResult_t hard_rc = pccltSuccess;
        auto drain_one = [&]() {
            uint64_t j = inflight.front();
            inflight.pop_front();
            pcclt::client::ReduceInfo ri;
            auto st = c->client->await_reduce(descs[j].tag, &ri);
            if (st == Status::kOk) {
                done[j] = true;
                fill_info(infos ? &infos[j] : nullptr, ri);
            } else if (st == Status::kAborted || st == Status::kConnectionLost) {
                all_ok = false; // retried next round
            } else if (hard_rc == pccltSuccess) {
                hard_rc = to_result(st);
            }
        };
        for (uint64_t i = 0; i < n_ops && hard_rc == pccltSuccess; ++i) {
            if (done[i]) continue;
            for (;;) {
                auto st = c->client->all_reduce_async(sendbufs[i], recvbufs[i],
                                                      counts[i], to_dtype(dtype),
                                                      to_desc(&descs[i]));
                if (st == Status::kOk) {
                    inflight.push_back(i);
                    any_launched = true;
                    break;
                }
                if (st == Status::kPendingAsyncOps && !inflight.empty()) {
                    drain_one();
                    if (hard_rc != pccltSuccess) break;
                    continue;
                }
                // genuine launch failure (or the pool is full of OTHER
                // callers' ops): await whatever we already launched —
                // returning with in-flight ops would leave workers
                // referencing caller buffers and their tags permanently
                // "duplicate"
                while (!inflight.empty()) drain_one();
                return st == Status::kTooFewPeers ? pccltTooFewPeers : to_result(st);
            }
        }
        while (!inflight.empty()) drain_one();
        if (hard_rc != pccltSuccess) return hard_rc;
        if (!any_launched) return pccltSuccess;
        if (all_ok) return pccltSuccess;
        // re-establish the mesh before retrying
        auto st = c->client->update_topology();
        if (st != Status::kOk) return to_result(st);
        if (c->client->group_world() < 2) return pccltTooFewPeers;
    }
}

uint64_t pccltHashBuffer(int hash_type, const void *data, uint64_t nbytes) {
    auto t = hash_type == 1   ? pcclt::hash::Type::kCrc32
             : hash_type == 2 ? pcclt::hash::Type::kSimpleTpu
                              : pcclt::hash::Type::kSimple;
    return pcclt::hash::content_hash(t, data, nbytes);
}

pccltResult_t pccltShmAlloc(uint64_t nbytes, void **out) {
    if (!out || nbytes == 0) return pccltInvalidArgument;
    void *p = pcclt::shm::alloc(nbytes);
    if (!p) return pccltInternalError;
    *out = p;
    return pccltSuccess;
}

pccltResult_t pccltShmFree(void *ptr) {
    if (!ptr) return pccltInvalidArgument;
    return pcclt::shm::free_buf(ptr) ? pccltSuccess : pccltInvalidArgument;
}

pccltResult_t pccltWireModelQuery(const char *ip, uint16_t port, double *mbps,
                                  double *rtt_ms, double *jitter_ms,
                                  double *drop) {
    if (!ip) return pccltInvalidArgument;
    auto addr = pcclt::net::Addr::parse(ip, port);
    if (!addr) return pccltInvalidArgument;
    auto &reg = pcclt::net::netem::Registry::inst();
    reg.refresh();
    auto params = reg.resolve(*addr)->params();
    if (mbps) *mbps = params.mbps;
    if (rtt_ms) *rtt_ms = params.rtt_ms;
    if (jitter_ms) *jitter_ms = params.jitter_ms;
    if (drop) *drop = params.drop;
    return pccltSuccess;
}

pccltResult_t pccltCommGetStats(pccltComm_t *c, pccltCommStats_t *out) {
    if (!c || !out) return pccltInvalidArgument;
    const auto &m = c->client->tele().comm;
    auto ld = [](const std::atomic<uint64_t> &a) {
        return a.load(std::memory_order_relaxed);
    };
    out->collectives_ok = ld(m.collectives_ok);
    out->collectives_aborted = ld(m.collectives_aborted);
    out->collectives_connection_lost = ld(m.collectives_lost);
    out->topology_updates = ld(m.topology_updates);
    out->topology_optimizes = ld(m.topology_optimizes);
    out->syncs_ok = ld(m.syncs_ok);
    out->syncs_failed = ld(m.syncs_failed);
    out->sync_hash_mismatches = ld(m.sync_hash_mismatches);
    out->kicked = ld(m.kicked);
    out->peers_joined = ld(m.peers_joined);
    out->peers_left = ld(m.peers_left);
    out->master_reconnects = ld(m.master_reconnects);
    out->p2p_conns_reused = ld(m.p2p_conns_reused);
    out->telemetry_digests = ld(m.telemetry_digests);
    // process-global ring accounting (the recorder is shared by every comm
    // in the process): nonzero = traces are truncated to the newest 64k
    out->trace_ring_dropped = pcclt::telemetry::Recorder::inst().dropped();
    out->trace_ring_pushed = pcclt::telemetry::Recorder::inst().pushed();
    out->trace_ring_capacity = pcclt::telemetry::Recorder::ring_capacity();
    out->relay_forwarded = ld(m.relay_forwarded);
    // chaos accounting is process-global like the netem registry itself
    auto cs = pcclt::net::netem::chaos_stats();
    out->chaos_faults_armed = cs.armed;
    out->chaos_faults_activated = cs.activated;
    out->ss_chunks_fetched = ld(m.ss_chunks_fetched);
    out->ss_chunks_resourced = ld(m.ss_chunks_resourced);
    out->ss_chunks_dup = ld(m.ss_chunks_dup);
    out->ss_chunk_bytes_fetched = ld(m.ss_chunk_bytes_fetched);
    out->ss_chunk_bytes_resourced = ld(m.ss_chunk_bytes_resourced);
    out->ss_chunk_bytes_dup = ld(m.ss_chunk_bytes_dup);
    out->ss_seeder_chunks_served = ld(m.ss_seeder_chunks_served);
    out->ss_seeder_promotions = ld(m.ss_seeder_promotions);
    out->ss_seeders_lost = ld(m.ss_seeders_lost);
    out->ss_legacy_syncs = ld(m.ss_legacy_syncs);
    out->relay_acks = ld(m.relay_acks);
    out->relay_retired_early = ld(m.relay_retired_early);
    out->sched_ops_ring = ld(m.sched_ops_ring);
    out->sched_ops_tree = ld(m.sched_ops_tree);
    out->sched_ops_butterfly = ld(m.sched_ops_butterfly);
    out->sched_ops_mesh = ld(m.sched_ops_mesh);
    out->sched_ops_relay = ld(m.sched_ops_relay);
    out->sched_steps = ld(m.sched_steps);
    out->sched_relay_planned_bytes = ld(m.sched_relay_planned_bytes);
    out->ss_chunks_delta_skipped = ld(m.ss_chunks_delta_skipped);
    out->ss_chunk_bytes_delta_skipped = ld(m.ss_chunk_bytes_delta_skipped);
    return pccltSuccess;
}

pccltResult_t pccltCommGetEdgeStats(pccltComm_t *c, pccltEdgeStats_t *out,
                                    uint64_t cap, uint64_t *count) {
    if (!c || !count || (cap && !out)) return pccltInvalidArgument;
    auto edges = c->client->tele().snapshot_edges();
    *count = edges.size();
    for (uint64_t i = 0; i < cap && i < edges.size(); ++i) {
        auto &e = edges[i];
        auto &o = out[i];
        snprintf(o.endpoint, sizeof o.endpoint, "%s", e.endpoint.c_str());
        o.tx_bytes = e.tx_bytes;
        o.rx_bytes = e.rx_bytes;
        o.tx_frames = e.tx_frames;
        o.rx_frames = e.rx_frames;
        o.connects = e.conns;
        o.stall_ms = e.stall_ns / 1000000;
        o.tx_zc_frames = e.tx_zc_frames;
        o.tx_zc_reaps = e.tx_zc_reaps;
        o.wd_state = e.wd_health;
        o.wd_suspects = e.wd_suspects;
        o.wd_confirms = e.wd_confirms;
        o.wd_reissues = e.wd_reissues;
        o.wd_relays = e.wd_relays;
        o.rx_relay_bytes = e.rx_relay_bytes;
        o.rx_relay_windows = e.rx_relay_windows;
        o.dup_bytes = e.dup_bytes;
        o.dup_windows = e.dup_windows;
        o.tx_sync_bytes = e.tx_sync_bytes;
        o.rx_sync_bytes = e.rx_sync_bytes;
        o.tx_stripe_windows = e.tx_stripe_windows;
        o.tx_stripe_bytes = e.tx_stripe_bytes;
    }
    return pccltSuccess;
}

pccltResult_t pccltNetemInject(const char *endpoint, const char *spec) {
    if (!endpoint || !spec) return pccltInvalidArgument;
    return pcclt::net::netem::inject(endpoint, spec) ? pccltSuccess
                                                     : pccltInvalidArgument;
}

pccltResult_t pccltTraceEnable(int on) {
    pcclt::telemetry::Recorder::inst().enable(on != 0);
    return pccltSuccess;
}

pccltResult_t pccltTraceClear(void) {
    pcclt::telemetry::Recorder::inst().clear();
    return pccltSuccess;
}

pccltResult_t pccltTraceDump(const char *path) {
    std::string p = path ? std::string(path)
                         : pcclt::telemetry::Recorder::env_trace_path();
    if (p.empty()) return pccltInvalidArgument;
    return pcclt::telemetry::Recorder::inst().dump_json(p) ? pccltSuccess
                                                           : pccltInternalError;
}

// ---------------- fleet-scale bench hooks (docs/09) ----------------

pccltResult_t pccltDigestFlood(const char *ip, uint16_t port, uint32_t peers,
                               uint32_t edges_per_peer, double hz,
                               double seconds, uint32_t threads,
                               uint64_t *digests_sent, double *wall_seconds) {
    if (!ip || peers == 0 || edges_per_peer == 0 || hz <= 0 || seconds <= 0)
        return pccltInvalidArgument;
    auto addr = pcclt::net::Addr::parse(ip, port);
    if (!addr) return pccltInvalidArgument;
    if (threads == 0) threads = 2;
    if (threads > peers) threads = peers;

    // Simulated-fleet digest bot: one OBSERVER control session per simulated
    // peer (the master folds digests per session uuid), each pushing a
    // pre-encoded kC2MTelemetryDigest at `hz`. Payloads are encoded once up
    // front so the loop measures master-side ingest, not client-side encode.
    std::atomic<uint64_t> sent{0};
    std::atomic<int> failed{0};
    const auto t0 = std::chrono::steady_clock::now();
    std::vector<std::thread> workers;
    workers.reserve(threads);
    for (uint32_t t = 0; t < threads; ++t) {
        workers.emplace_back([&, t] {
            struct Conn {
                pcclt::net::Socket sock;
                pcclt::Mutex mu; // send_frame write serialization (worker-local)
                std::vector<uint8_t> digest;
            };
            std::vector<std::unique_ptr<Conn>> conns;
            for (uint32_t p = t; p < peers; p += threads) {
                auto c = std::make_unique<Conn>();
                if (!c->sock.connect(*addr, 5000)) {
                    failed.fetch_add(1, std::memory_order_relaxed);
                    return;
                }
                pcclt::proto::HelloC2M h;
                h.observer = 1;
                if (!pcclt::net::send_frame(c->sock, c->mu,
                                            pcclt::proto::kC2MHello, h.encode()) ||
                    !pcclt::net::recv_frame(c->sock, 10000)) { // welcome
                    failed.fetch_add(1, std::memory_order_relaxed);
                    return;
                }
                // one digest per simulated peer: unique endpoints so the
                // fleet edge table reaches peers * edges_per_peer entries,
                // with per-edge + per-phase histograms populated the way a
                // real data-plane digest would be
                pcclt::proto::TelemetryDigestC2M d;
                d.interval_ms = static_cast<uint64_t>(1000.0 / hz);
                d.collectives_ok = 1;
                d.ring_pushed = 1024;
                d.ring_cap = 65536;
                for (uint32_t e = 0; e < edges_per_peer; ++e) {
                    pcclt::proto::TelemetryDigestC2M::Edge ed;
                    char ep[64];
                    snprintf(ep, sizeof ep, "10.%u.%u.%u:9100", (p >> 8) & 255,
                             p & 255, e & 255);
                    ed.endpoint = ep;
                    ed.tx_mbps = 800 + (p % 100);
                    ed.rx_mbps = 790 + (e % 50);
                    ed.stall_ratio = 0.01;
                    ed.tx_bytes = 1 << 20;
                    ed.rx_bytes = 1 << 20;
                    for (uint8_t b = 10; b < 14; ++b) {
                        ed.stage_wire_hist.buckets.push_back({b, 16});
                        ed.stage_wire_hist.sum_ns += 16u << b;
                    }
                    ed.stall_hist.buckets.push_back({12, 2});
                    ed.stall_hist.sum_ns = 2u << 12;
                    d.edges.push_back(std::move(ed));
                }
                for (uint64_t s = 0; s < 4; ++s)
                    d.ops.push_back({s + 1, 5000000 + s * 1000, 100000});
                pcclt::proto::WireHist ph;
                for (uint8_t b = 18; b < 22; ++b) {
                    ph.buckets.push_back({b, 8});
                    ph.sum_ns += 8u << b;
                }
                d.phase_hists.push_back({0, std::move(ph)});
                c->digest = d.encode();
                conns.push_back(std::move(c));
            }
            // paced rounds: every conn pushes one digest per 1/hz tick
            const auto start = std::chrono::steady_clock::now();
            uint64_t rounds = static_cast<uint64_t>(seconds * hz + 0.5);
            if (rounds == 0) rounds = 1;
            for (uint64_t r = 0; r < rounds; ++r) {
                for (auto &c : conns) {
                    if (pcclt::net::send_frame(c->sock, c->mu,
                                               pcclt::proto::kC2MTelemetryDigest,
                                               c->digest))
                        sent.fetch_add(1, std::memory_order_relaxed);
                    else
                        failed.fetch_add(1, std::memory_order_relaxed);
                }
                auto next = start + std::chrono::duration_cast<
                                        std::chrono::steady_clock::duration>(
                                        std::chrono::duration<double>((r + 1) / hz));
                std::this_thread::sleep_until(next);
            }
        });
    }
    for (auto &w : workers) w.join();
    const double wall =
        std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
            .count();
    if (digests_sent) *digests_sent = sent.load(std::memory_order_relaxed);
    if (wall_seconds) *wall_seconds = wall;
    return failed.load(std::memory_order_relaxed) ? pccltMasterUnreachable
                                                  : pccltSuccess;
}

pccltResult_t pccltAdmissionProbe(const char *ip, uint16_t port,
                                  uint32_t rounds, double *mean_seconds,
                                  double *p99_seconds) {
    if (!ip || rounds == 0) return pccltInvalidArgument;
    auto addr = pcclt::net::Addr::parse(ip, port);
    if (!addr) return pccltInvalidArgument;
    // Dispatcher round-latency probe: each round is one observer hello ->
    // welcome round trip. The hello is parsed, admitted and answered ON the
    // dispatcher thread, so the round trip measures exactly the queueing an
    // admission/topology frame would see — without perturbing the world
    // (observers are never admitted). TCP connect happens before the timer.
    std::vector<double> samples;
    samples.reserve(rounds);
    for (uint32_t r = 0; r < rounds; ++r) {
        pcclt::net::Socket sock;
        pcclt::Mutex mu;
        if (!sock.connect(*addr, 5000)) return pccltMasterUnreachable;
        pcclt::proto::HelloC2M h;
        h.observer = 1;
        const auto t0 = std::chrono::steady_clock::now();
        if (!pcclt::net::send_frame(sock, mu, pcclt::proto::kC2MHello,
                                    h.encode()) ||
            !pcclt::net::recv_frame(sock, 10000))
            return pccltMasterUnreachable;
        samples.push_back(std::chrono::duration<double>(
                              std::chrono::steady_clock::now() - t0)
                              .count());
    }
    std::sort(samples.begin(), samples.end());
    double sum = 0;
    for (double s : samples) sum += s;
    if (mean_seconds) *mean_seconds = sum / static_cast<double>(samples.size());
    if (p99_seconds)
        *p99_seconds = samples[std::min(samples.size() - 1,
                                        static_cast<size_t>(
                                            static_cast<double>(samples.size()) *
                                            0.99))];
    return pccltSuccess;
}

pccltResult_t pccltMasterReplayBench(const char *journal_path, uint32_t clients,
                                     double *write_seconds,
                                     double *replay_seconds) {
    if (!journal_path || clients == 0) return pccltInvalidArgument;
    using Clock = std::chrono::steady_clock;
    // phase 1: append `clients` session deltas the way a live master would
    double write_s = 0;
    {
        pcclt::journal::Journal j;
        if (!j.open(journal_path)) return pccltInvalidArgument;
        const auto w0 = Clock::now();
        for (uint32_t i = 0; i < clients; ++i) {
            pcclt::journal::ClientRec c;
            c.uuid = pcclt::proto::uuid_random();
            c.peer_group = 0;
            char ip[32];
            snprintf(ip, sizeof ip, "10.%u.%u.%u", (i >> 16) & 255,
                     (i >> 8) & 255, i & 255);
            c.ip = ip;
            c.p2p_port = 9000;
            c.ss_port = 9001;
            c.bench_port = 9002;
            c.accepted = true;
            j.record_client(c);
        }
        write_s = std::chrono::duration<double>(Clock::now() - w0).count();
    }
    // phase 2: cold restart — replay + compacted snapshot + state rehydrate
    pcclt::journal::Journal j2;
    const auto r0 = Clock::now();
    if (!j2.open(journal_path)) return pccltInternalError;
    pcclt::master::MasterState st;
    st.attach_journal(&j2);
    const double replay_s =
        std::chrono::duration<double>(Clock::now() - r0).count();
    if (st.limbo_count() != clients) return pccltInternalError;
    if (write_seconds) *write_seconds = write_s;
    if (replay_seconds) *replay_seconds = replay_s;
    return pccltSuccess;
}

pccltResult_t pccltSynchronizeSharedState(pccltComm_t *c, pccltSharedState_t *state,
                                          pccltSyncStrategy_t strategy,
                                          pccltSharedStateSyncInfo_t *info) {
    if (!c || !state || (state->count && !state->infos)) return pccltInvalidArgument;
    std::vector<pcclt::client::SharedStateEntry> entries;
    for (uint64_t i = 0; i < state->count; ++i) {
        auto &ti = state->infos[i];
        if (!ti.name || !ti.data) return pccltInvalidArgument;
        pcclt::client::SharedStateEntry e;
        e.name = ti.name;
        e.dtype = to_dtype(ti.dtype);
        e.count = ti.count;
        e.data = ti.data;
        e.allow_content_inequality = ti.allow_content_inequality != 0;
        e.precomputed_hash = ti.precomputed_hash;
        e.has_precomputed_hash = ti.has_precomputed_hash != 0;
        e.materialize = ti.materialize;
        e.materialize_ctx = ti.materialize_ctx;
        ti.updated = 0;
        e.updated = &ti.updated;
        entries.push_back(std::move(e));
    }
    pcclt::client::SyncInfo si;
    auto st = c->client->sync_shared_state(
        state->revision, static_cast<pcclt::proto::SyncStrategy>(strategy), entries, &si);
    if (info) {
        info->tx_bytes = si.tx_bytes;
        info->rx_bytes = si.rx_bytes;
        info->revision = si.revision;
    }
    return to_result(st);
}

} // extern "C"
