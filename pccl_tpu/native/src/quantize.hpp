// On-the-wire quantization: MinMax (float -> uintN affine on [min,max])
// and ZeroPointScale (piquant-style asymmetric int8/uint8).
//
// Reference parity: /root/reference/ccoip/internal/quantize.hpp (MinMax own
// kernels; ZeroPointScale delegated to the piquant library) and the fused
// dequantize+accumulate path of reduce_kernels.cpp:361-427. The
// quantize-dequantize "self-destruction" used for bit parity
// (quantize.hpp:190-199) is `requantize_self`.
#pragma once

#include <cstddef>
#include <cstdint>
#include <vector>

#include "protocol.hpp"

namespace pcclt::quant {

struct Meta {
    proto::QuantAlgo algo = proto::QuantAlgo::kNone;
    proto::DType src_dtype = proto::DType::kF32;
    proto::DType q_dtype = proto::DType::kU8;
    double lo = 0.0;    // MinMax: min;      ZPS: scale
    double hi = 0.0;    // MinMax: max;      ZPS: zero_point
    std::vector<uint8_t> encode() const;
    static std::optional<Meta> decode(const std::vector<uint8_t> &);
};

size_t quantized_bytes(proto::DType q_dtype, size_t count);

// Compute quantization parameters from data (min/max scan).
Meta compute_meta(proto::QuantAlgo algo, proto::DType q_dtype, proto::DType src_dtype,
                  const void *src, size_t count);

// q_out must hold quantized_bytes(q_dtype, count).
void quantize(const Meta &m, const void *src, void *q_out, size_t count);

// dst = dequant(q)           (op == set)
void dequantize_set(const Meta &m, const void *q, void *dst, size_t count);
// dst = red_op(dst, dequant(q))  — fused dequantize+accumulate
void dequantize_accumulate(const Meta &m, proto::RedOp op, const void *q, void *dst,
                           size_t count);

// In-place quantize-then-dequantize so the chunk owner loses exactly the
// precision every other peer loses (bit-parity invariant).
void requantize_self(const Meta &m, void *data, size_t count);

} // namespace pcclt::quant
