// Collective schedule synthesizer (docs/12).
// The master already measures a full bandwidth matrix but only used it to
// solve ATSP for ring ORDER; on hub-and-spoke and two-datacenter maps the
// ring itself is the wrong algorithm. This planner costs candidate
// schedules — ATSP ring, bandwidth-weighted tree (star fan-out),
// recursive-doubling butterfly, direct mesh, and a multi-hop relay ring
// over the acked kRelayFwd routes — with an alpha-beta model parameterized
// from the measured matrix, and emits an explicit per-rank step program
// (send/recv/reduce/forward addressed by peer + byte range). The master
// picks and versions one entry per (collective, size-class) at
// optimize-topology time; clients execute the stamped algorithm through
// the step interpreter in reduce.cpp.
#pragma once

#include <cstdint>
#include <optional>
#include <span>
#include <string>
#include <vector>

#include "wire.hpp"

namespace pcclt::proto {
enum class RedOp : uint8_t;  // protocol.hpp (avoid the heavy include here)
}

namespace pcclt::sched {

// Collective kinds the interpreter speaks. Values are wire-stable.
enum class Coll : uint8_t {
    kAllReduce = 0,
    kAllGather = 1,
    kReduceScatter = 2,
    kBroadcast = 3,
    kAllToAll = 4,
};
inline constexpr uint8_t kNumColls = 5;

// Candidate algorithms. Values are wire-stable (stamped on the commence).
enum class Algo : uint8_t {
    kRing = 0,       // ATSP ring (chain for broadcast, rotation for a2a)
    kTree = 1,       // bandwidth-weighted star from a root
    kButterfly = 2,  // recursive doubling (power-of-two worlds)
    kMesh = 3,       // direct pairwise sends (all-to-all)
    kRelayRing = 4,  // ring with the bottleneck edge detoured via kRelayFwd
};

const char *coll_name(Coll c);
const char *algo_name(Algo a);
std::optional<Algo> algo_from_name(const std::string &s);

// The RedOp doubles as the collective-kind marker for the widened
// vocabulary (kGather/kReduceScatter/kBroadcast/kAllToAll, docs/12);
// arithmetic ops are plain all-reduces.
Coll coll_of(proto::RedOp op);

// ---- size classes ----
// 0 = small (latency-bound), 1 = medium, 2 = large (bandwidth-bound).
// Thresholds: PCCLT_SCHED_SMALL_MAX (default 256 KiB) and
// PCCLT_SCHED_LARGE_MIN (default 8 MiB), re-read per call so tests can
// flip them at runtime.
inline constexpr uint8_t kNumSizeClasses = 3;
uint8_t size_class(uint64_t bytes);

// Which (collective, algorithm) pairs the interpreter can execute for a
// given world size. The cost model will price inexecutable combinations
// (e.g. tree all-reduce) for planner sanity tests, but choose() and the
// master only ever stamp executable ones.
bool algo_valid(Coll c, Algo a, uint32_t world);

// ---- versioned schedule table (wire format, journaled) ----
struct Entry {
    uint8_t coll = 0;        // Coll
    uint8_t size_class = 0;  // 0..kNumSizeClasses-1
    uint8_t algo = 0;        // Algo
    uint32_t root = 0;       // kRelayRing: ring index of the detouring
                             // sender; unused otherwise (broadcast roots
                             // are per-op, stamped from the user's slot)
};

struct Table {
    uint64_t version = 0;
    std::vector<Entry> entries;

    bool empty() const { return entries.empty(); }
    const Entry *find(Coll c, uint8_t sc) const;

    void encode_to(wire::Writer &w) const;
    static std::optional<Table> decode_from(wire::Reader &r);
    std::vector<uint8_t> encode() const;
    static std::optional<Table> decode(std::span<const uint8_t> b);
};

// ---- alpha-beta cost model ----
// mbps is an n*n row-major matrix (src row, dst col); entries <= 0 mean
// unmeasured and fall back to a conservative default. Per-node egress
// serialization is modeled through cap(): a star root pushing (n-1)
// copies shares its NIC even when per-edge emulation would not.
struct CostModel {
    uint32_t n = 0;
    std::vector<double> mbps;
    double alpha_s = 1e-3;  // per-transfer setup latency (seconds)

    double bw(uint32_t i, uint32_t j) const;   // mbps, floored
    double cap(uint32_t i) const;              // max outgoing edge (mbps)
    // seconds to move `bytes` over edge i->j, excluding alpha
    double t(uint32_t i, uint32_t j, double bytes) const;
    // total seconds for one collective of `bytes` payload per rank over
    // ring order `ring` (ring position -> matrix index). root is a matrix
    // index (broadcast origin / relay bottleneck), ignored where unused.
    double cost(Coll c, Algo a, const std::vector<uint32_t> &ring,
                uint32_t root, double bytes) const;
};

struct Choice {
    Algo algo = Algo::kRing;
    uint32_t root = 0;  // ring index (kRelayRing bottleneck sender)
    double cost = 0;
};

// Best executable algorithm for one (collective, payload). Broadcast is
// scored averaged over all candidate roots (the actual root is per-op).
// PCCLT_SCHEDULE_FORCE overrides when the forced algo is executable;
// PCCLT_SCHEDULE=0 pins everything to the ring.
Choice choose(const CostModel &m, Coll c, const std::vector<uint32_t> &ring,
              uint64_t bytes);

// Full table: one entry per (collective, size-class), costed at a
// representative payload for the class.
Table synthesize(const CostModel &m, const std::vector<uint32_t> &ring,
                 uint64_t version);

// ---- per-rank step programs ----
// Steps address peers by RING index and payloads by byte range in the
// collective's address space. The interpreter in reduce.cpp executes
// these; conserve() proves every byte sent is received exactly once.
struct Step {
    enum Kind : uint8_t {
        kSend = 0,        // send [off, off+bytes) to peer as transfer xfer
        kRecv = 1,        // receive xfer from peer into [off, off+bytes)
        kRecvReduce = 2,  // receive and fold into the accumulator
        kRecvForward = 3, // receive and forward windows to the next hop
        kCopy = 4,        // local move (peer == self)
    };
    uint8_t kind = 0;
    uint32_t peer = 0;
    uint64_t off = 0;
    uint64_t bytes = 0;
    uint32_t xfer = 0;  // low tag bits; unique per transfer within the op
};
using Program = std::vector<Step>;

// Wire-tag bases for synthesized transfers; disjoint from the ring
// all-reduce's stage grid (0x0000/0x4000) and below kMetaBit (0x8000).
inline constexpr uint32_t kXferBcast = 0x0010;
inline constexpr uint32_t kXferA2A = 0x0600;
inline constexpr uint32_t kXferFly = 0x0700;

Program expand(Coll c, Algo a, uint32_t n, uint32_t rank, uint32_t root,
               uint64_t bytes);

// Cross-rank conservation: expand() for every rank, then require every
// send to pair with exactly one matching receive (same endpoints, xfer,
// byte count) and vice versa. err (optional) gets a human-readable
// reason on failure.
bool conserve(Coll c, Algo a, uint32_t n, uint32_t root, uint64_t bytes,
              std::string *err = nullptr);

// ---- env knobs (docs/03) ----
bool schedule_enabled();            // PCCLT_SCHEDULE != 0 (default on)
std::optional<Algo> forced_algo();  // PCCLT_SCHEDULE_FORCE

} // namespace pcclt::sched
