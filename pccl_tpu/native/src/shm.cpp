#include "shm.hpp"

#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <map>

#include "annotations.hpp"
#include "log.hpp"

namespace pcclt::shm {

namespace {

struct Registry {
    Mutex mu; // lock-rank: 54
    // by base address
    std::map<uintptr_t, Region> live PCCLT_GUARDED_BY(mu);
    uint64_t next_id PCCLT_GUARDED_BY(mu) = 1;
    uint64_t retire_seq PCCLT_GUARDED_BY(mu) = 0;
    // retires <= this were dropped
    uint64_t trimmed_seq PCCLT_GUARDED_BY(mu) = 0;
    // (seq, base)
    std::vector<std::pair<uint64_t, uint64_t>> retires PCCLT_GUARDED_BY(mu);
};

Registry &reg() {
    static Registry r;
    return r;
}

int memfd(size_t len) {
    char name[64];
    snprintf(name, sizeof name, "pcclt-shm-%d", static_cast<int>(getpid()));
    int fd = static_cast<int>(syscall(SYS_memfd_create, name, 0u));
    if (fd < 0) return -1;
    if (ftruncate(fd, static_cast<off_t>(len)) != 0) {
        ::close(fd);
        return -1;
    }
    return fd;
}

} // namespace

void *alloc(size_t len) {
    if (len == 0) return nullptr;
    int fd = memfd(len);
    if (fd < 0) {
        PLOG(kWarn) << "shm: memfd_create failed (errno " << errno << ")";
        return nullptr;
    }
    void *p = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (p == MAP_FAILED) {
        ::close(fd);
        PLOG(kWarn) << "shm: mmap failed (errno " << errno << ")";
        return nullptr;
    }
    madvise(p, len, MADV_HUGEPAGE); // advisory; fewer TLB misses on big pulls
    auto &r = reg();
    MutexLock lk(r.mu);
    Region region;
    region.id = r.next_id++;
    region.fd = fd;
    region.base = static_cast<uint8_t *>(p);
    region.len = len;
    r.live.emplace(reinterpret_cast<uintptr_t>(p), region);
    return p;
}

bool free_buf(void *p) {
    auto &r = reg();
    Region region;
    {
        MutexLock lk(r.mu);
        auto it = r.live.find(reinterpret_cast<uintptr_t>(p));
        if (it == r.live.end()) return false;
        region = it->second;
        r.live.erase(it);
        r.retires.emplace_back(++r.retire_seq, reinterpret_cast<uint64_t>(p));
        if (r.retires.size() > 4096) {
            // compact: conns whose cursor is behind the trim point get a
            // reset feed (retire-everything) instead of silently missing
            // the dropped entries
            r.trimmed_seq = r.retires.front().first;
            r.retires.erase(r.retires.begin());
        }
    }
    // release the pages but burn the virtual range: a peer that has not yet
    // drained the retire can never resolve a future buffer at this address
    mmap(region.base, region.len, PROT_NONE,
         MAP_FIXED | MAP_PRIVATE | MAP_ANONYMOUS | MAP_NORESERVE, -1, 0);
    ::close(region.fd);
    return true;
}

std::optional<Region> find(const void *p, size_t len) {
    auto &r = reg();
    MutexLock lk(r.mu);
    auto addr = reinterpret_cast<uintptr_t>(p);
    auto it = r.live.upper_bound(addr);
    if (it == r.live.begin()) return std::nullopt;
    --it;
    const Region &region = it->second;
    if (addr >= it->first && addr + len <= it->first + region.len) return region;
    return std::nullopt;
}

RetireFeed drain_retires(uint64_t *cursor) {
    auto &r = reg();
    MutexLock lk(r.mu);
    RetireFeed out;
    out.reset = *cursor < r.trimmed_seq;
    if (!out.reset)
        for (const auto &[seq, base] : r.retires)
            if (seq > *cursor) out.bases.push_back(base);
    *cursor = r.retire_seq;
    return out;
}

size_t live_regions() {
    auto &r = reg();
    MutexLock lk(r.mu);
    return r.live.size();
}

} // namespace pcclt::shm
