// Per-edge network emulation ("netem"): keyed wire models for loopback
// meshes that pretend to be heterogeneous WANs.
//
// The round-4/5 wire emulation was process-global — one PCCLT_WIRE_MBPS
// leaky bucket and one PCCLT_WIRE_RTT_MS delay line shared by every
// connection — which can A/B a uniform WAN but cannot express the thing
// the ATSP topology optimizer exists for: a mesh where ONE edge is slow
// and routing around it wins (see "Don't Let a Few Network Failures Slow
// the Entire AllReduce", arxiv 2606.01680). This subsystem replaces the
// singletons with a registry of per-remote-endpoint Edge models:
//
//   PCCLT_WIRE_MBPS_MAP=ip:port=mbps,ip=mbps,...    egress bandwidth
//   PCCLT_WIRE_RTT_MS_MAP=ip:port=ms,...            round-trip time
//   PCCLT_WIRE_JITTER_MS_MAP=ip:port=ms,...         uniform extra delay
//   PCCLT_WIRE_DROP_MAP=ip:port=p,...               frame-loss probability
//   PCCLT_WIRE_CWND_MAP=ip:port=bytes,...           per-FLOW cwnd cap
//     (global twin PCCLT_WIRE_CWND_BYTES; needs a modeled rtt): one flow
//     moves at most cwnd/rtt bytes/s even on an idle edge — the reason a
//     single TCP flow cannot fill a high-BDP pipe and striping exists
//
// Key resolution is exact "ip:port" first, then bare-"ip" wildcard, then
// the process-global PCCLT_WIRE_MBPS / PCCLT_WIRE_RTT_MS vars — which thus
// keep their old meaning as defaults: with no *_MAP set, every connection
// resolves to the single shared default Edge and behavior is bit-for-bit
// the old global pacer/delay line. Per-field fallback: an endpoint listed
// only in the mbps map takes its rtt from the global default, and so on.
// Malformed map entries are skipped with a warning; the rest apply.
//
// An Edge is SHARED by every connection resolved to the same key (the
// whole point of the old "global, not per-conn" rule, now per edge): Link
// striping across a conn pool toward one peer cannot manufacture
// bandwidth, because all pool members drain one bucket. refresh() is
// called per conn construction and updates parameters of existing Edge
// objects in place, so a process can re-point the env between connections
// (bench legs, tests) without restarting — and without splitting buckets.
//
// STRIPED bucket (docs/08 "multipath striping"): the one bucket is divided
// into per-sender LANES. Each concurrent sender (pool conn) registers a
// lane via alloc_lane(); a frame on lane L reserves a slot in L's own
// sub-schedule and drains at R / K, where K is the number of lanes
// backlogged at reservation time — so K conns on one edge sum to the
// modeled rate (never exceed it), idle lanes are reclaimed the moment they
// go quiet (work conserving), and no lane head-of-line-blocks another's
// pacing slots the way the old single-reservation queue did. Chaos
// schedules, watchdog deadlines and byte metering still see the ONE
// canonical edge: an outage pushes every lane's next slot past the window,
// a degrade rescales every lane's drain rate. Lane 0 is the shared default
// for callers that never registered (shared-state serves, bench probes).
//
// Drop emulation is TCP-honest: PCCP frames ride TCP, which never loses
// frames, so a "dropped" frame is delivered late by a retransmit penalty
// (~RTO: max(RTT, 200 ms)) instead of vanishing. Jitter and drop can
// reorder delivery within a tag; the SinkTable's extent bookkeeping
// already absorbs out-of-order offsets (real jittery networks reorder
// too — that is what the emulation is for).
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <optional>
#include <string>
#include <vector>

#include "annotations.hpp"
#include "net_addr.hpp"

namespace pcclt::net::netem {

// one edge's emulated parameters (0 = that dimension off)
struct EdgeParams {
    double mbps = 0;       // egress bandwidth, megabits/s
    double rtt_ms = 0;     // round-trip time; delivery delays by rtt/2
    double jitter_ms = 0;  // uniform extra delivery delay in [0, jitter)
    double drop = 0;       // P(frame "lost") -> delivered late by ~RTO
    // per-FLOW congestion-window cap in bytes: one flow (pacing lane) can
    // carry at most cwnd/rtt bytes/s even when the edge has headroom —
    // the fat-long-pipe physics that makes a single TCP flow unable to
    // fill a high-BDP link and parallel flows the standard fix. 0 (or no
    // modeled rtt) = off. PCCLT_WIRE_CWND_BYTES / PCCLT_WIRE_CWND_MAP.
    double cwnd_bytes = 0;
};

// ---- chaos layer: time-scripted fault schedules (docs/05) ----
//
// PCCLT_WIRE_CHAOS_MAP=ip:port=fault;fault,...  where each fault is one of
//   degrade@t=<T>:<R>mbit/<D>   at T, cap the edge to R Mbit/s for D
//   flap@t=<T>:<D>x<N>          N outages of D each, one outage per 2D period
//   blackhole@t=<T>:<D>         total outage (no frame moves) for D
// T/D accept 5s / 200ms / plain seconds; 'x' may also be the Unicode '×'.
// Faults for one edge are ';'-separated (',' separates edges, '=' after the
// endpoint key). t=0 means "on arming": env schedules arm when the registry
// first installs them (once per process per key — the per-conn refresh
// never re-arms a running script); pccltNetemInject arms at call time, so
// tests and the stress orchestrator can fire faults mid-run
// deterministically.
struct ChaosFault {
    enum Kind : int { kDegrade = 0, kFlap = 1, kBlackhole = 2 };
    Kind kind = kDegrade;
    uint64_t start_ns = 0;   // relative to the schedule's arm time
    uint64_t dur_ns = 0;     // one window (degrade/blackhole) or one outage
    uint32_t repeat = 1;     // flap: number of outages
    double mbps = 0;         // degrade: the capped rate
};

// what the schedule says the wire looks like *right now*
struct ChaosVerdict {
    bool outage = false;        // flap/blackhole window active
    uint64_t outage_end_ns = 0; // absolute mono ns the outage lifts
    double mbps_override = 0;   // >0: degrade window active at this rate
};

// Parse one ';'-separated fault schedule. Malformed faults are skipped
// with a warning (mirroring parse_map); empty result = nothing usable.
std::vector<ChaosFault> parse_chaos(const std::string &spec,
                                    const char *what);

// "5s" / "200ms" / bare seconds -> ns; nullopt on garbage. Exposed for
// the decode fuzzer (the chaos grammar's duration leaf).
std::optional<uint64_t> parse_dur_ns(const std::string &s);

// PCCLT_WIRE_CHAOS_MAP split: values contain '=' (t=5s) and faults are
// ';'-joined, so the generic parse_map (last-'=' split, numeric values)
// cannot serve — entries split on ',', the key at the FIRST '='.
// Exposed for tests and the decode fuzzer.
std::map<std::string, std::string> parse_chaos_map(const char *spec);

// Arm `spec` on the edge resolved for `endpoint` ("ip:port") right now
// (offsets relative to the call). Returns false when the spec parses to
// nothing or the endpoint is not a valid ip:port. Backs pccltNetemInject.
bool inject(const std::string &endpoint, const std::string &spec);

// process-wide chaos accounting (stress orchestrator CHAOS SUMMARY):
// schedules armed, and fault windows that actually activated (a flap of
// N outages counts N activations)
struct ChaosStats {
    uint64_t armed = 0;
    uint64_t activated = 0;
};
ChaosStats chaos_stats();

// One emulated edge: this process -> one remote endpoint. Holds the
// reservation-based leaky bucket (shared by every conn on the edge) and
// computes per-frame delivery delays. Parameters are atomics so refresh()
// can retune a live edge without racing the data path.
class Edge {
public:
    explicit Edge(const EdgeParams &p = {}) { configure(p); }
    void configure(const EdgeParams &p);
    EdgeParams params() const;

    bool pace_enabled() const {
        return ns_per_byte_.load(std::memory_order_relaxed) > 0 ||
               cwnd_npb_.load(std::memory_order_relaxed) > 0 ||
               chaos_armed_.load(std::memory_order_relaxed);
    }
    bool delay_enabled() const {
        return owd_ns_.load(std::memory_order_relaxed) > 0 ||
               jitter_ns_.load(std::memory_order_relaxed) > 0 ||
               drop_.load(std::memory_order_relaxed) > 0 ||
               chaos_armed_.load(std::memory_order_relaxed);
    }
    // any emulation at all: callers use this to defeat the same-host
    // zero-copy transports (an emulated WAN cannot be bypassed)
    bool emulated() const { return pace_enabled() || delay_enabled(); }

    // Arm a chaos schedule NOW (fault offsets relative to this call).
    // Replaces any prior schedule on the edge; an empty list disarms.
    void arm_chaos(std::vector<ChaosFault> faults);
    // the schedule's verdict at mono time `now_ns` (0 = current time)
    ChaosVerdict chaos_at(uint64_t now_ns = 0);

    // Reserve [next, next+bytes*ns_per_byte*K) in `lane`'s sub-schedule of
    // the edge's bucket (K = lanes backlogged at reservation — the fair
    // share) and sleep until the frame has fully drained. Small frames
    // (<= 4 KiB) charge the bucket but may run a bounded window ahead of
    // the wire — the same qdisc-interleaving allowance the old global
    // pacer had. With one lane active the reservation degenerates to the
    // exact pre-striping single-bucket behavior.
    void pace(size_t bytes, uint32_t lane = 0);

    // Register / retire a pacing lane (one per pool conn). Lane 0 is
    // never handed out: it is the shared default for unregistered callers.
    uint32_t alloc_lane();
    void release_lane(uint32_t lane);

    // Per-frame delivery delay: owd (rtt/2) + U[0, jitter) + the
    // retransmit penalty when the frame rolls a "loss". 0 = deliver now.
    uint64_t delivery_delay_ns();

private:
    // schedule scan under mu_ (pace/delivery already hold it)
    ChaosVerdict chaos_eval(uint64_t now_ns) PCCLT_REQUIRES(mu_);

    std::atomic<double> ns_per_byte_{0};
    // per-flow cwnd cap as ns/byte (rtt / cwnd_bytes); 0 = off
    std::atomic<double> cwnd_npb_{0};
    std::atomic<uint64_t> owd_ns_{0};
    std::atomic<uint64_t> jitter_ns_{0};
    std::atomic<double> drop_{0};
    std::atomic<bool> chaos_armed_{false};

    Mutex mu_;  // bucket + rng + chaos script; lock-rank: 62
    // striped bucket: end of the last reserved slot PER LANE. Lane 0 (the
    // unregistered-caller default) always exists; lane_used_ marks live
    // registrations so released lanes stop counting toward the fair share
    // and their slots are reclaimed by the next alloc.
    std::vector<uint64_t> lane_next_ PCCLT_GUARDED_BY(mu_) = {0};
    std::vector<uint8_t> lane_used_ PCCLT_GUARDED_BY(mu_) = {1};
    // splitmix64 state (jitter/drop)
    uint64_t rng_ PCCLT_GUARDED_BY(mu_) = 0x9E3779B97F4A7C15ull;
    // chaos script: armed fault list + arm time; fired_ marks fault
    // windows already counted as activated (flap: one bit per outage is
    // overkill — the first outage of a fault marks it, per-outage
    // activations are counted by index in fired_outages_)
    std::vector<ChaosFault> chaos_ PCCLT_GUARDED_BY(mu_);
    uint64_t chaos_t0_ PCCLT_GUARDED_BY(mu_) = 0;
    std::vector<uint32_t> fired_outages_ PCCLT_GUARDED_BY(mu_);
};

// Deadline-ordered delivery timer shared by every delayed edge: one
// (lazily started, intentionally leaked) thread runs visibility flips at
// their per-frame deadlines. Replaces the old fixed-owd DeliveryDelay —
// the delay now arrives per call, so one line serves heterogeneous edges.
class DelayLine {
public:
    static DelayLine &inst();
    // run fn once delay_ns has elapsed from now
    void deliver(uint64_t delay_ns, std::function<void()> fn);

private:
    DelayLine() = default;
    void timer_loop();
    Mutex mu_; // lock-rank: 64
    CondVar cv_;
    // deadline -> fn
    std::multimap<uint64_t, std::function<void()>> q_ PCCLT_GUARDED_BY(mu_);
    bool running_ PCCLT_GUARDED_BY(mu_) = false;
};

// Parse one "k=v,k=v,..." map env value. Malformed entries (no '=',
// empty key, unparsable value, out-of-range value) are skipped with a
// warning and do not poison their neighbors. Exposed for tests.
std::map<std::string, double> parse_map(const char *spec, const char *name);

// Registry of Edge models keyed by canonical remote endpoint.
class Registry {
public:
    static Registry &inst();

    // Re-read the PCCLT_WIRE_* env (globals + maps). Called per conn
    // construction, mirroring the old per-conn WirePacer refresh.
    void refresh();

    // Resolve the Edge for a remote endpoint: exact "ip:port" entry in any
    // map -> per-endpoint Edge; bare-"ip" wildcard -> per-ip Edge (shared
    // by every port on that host); otherwise the shared default Edge
    // (global PCCLT_WIRE_MBPS / PCCLT_WIRE_RTT_MS, old semantics).
    std::shared_ptr<Edge> resolve(const Addr &peer);

    // the globals-backed fallback edge (also what unresolved conns use)
    std::shared_ptr<Edge> default_edge();

private:
    Registry() { refresh(); }
    // runtime chaos injection force-creates per-endpoint entries
    friend bool inject(const std::string &endpoint, const std::string &spec);
    EdgeParams params_for(const std::string &exact_key,
                          const std::string &ip_key) const PCCLT_REQUIRES(mu_);

    mutable Mutex mu_; // lock-rank: 60
    // never null after ctor
    std::shared_ptr<Edge> default_ PCCLT_GUARDED_BY(mu_);
    struct Entry {
        std::shared_ptr<Edge> edge;
        std::string exact_key, ip_key;  // for in-place refresh
    };
    // by matched key
    std::map<std::string, Entry> edges_ PCCLT_GUARDED_BY(mu_);
    std::map<std::string, double> mbps_ PCCLT_GUARDED_BY(mu_),
        rtt_ PCCLT_GUARDED_BY(mu_), jitter_ PCCLT_GUARDED_BY(mu_),
        drop_ PCCLT_GUARDED_BY(mu_), cwnd_ PCCLT_GUARDED_BY(mu_);
    EdgeParams global_ PCCLT_GUARDED_BY(mu_);
    // PCCLT_WIRE_CHAOS_MAP schedules by key. A key arms ONCE per process
    // (first resolve that matches it): refresh() re-reads the env but an
    // armed script keeps its original t0 — mid-run re-reads must not
    // restart a fault timeline that peers are already living through.
    std::map<std::string, std::string> chaos_specs_ PCCLT_GUARDED_BY(mu_);
    std::map<std::string, bool> chaos_armed_keys_ PCCLT_GUARDED_BY(mu_);
};

}  // namespace pcclt::net::netem
