// Single source of truth for the library version string: the C API build
// banner (pccltGetBuildInfo) and the /metrics // /health build_info
// surfaces must never drift apart.
#pragma once

namespace pcclt {

inline constexpr const char *kPccltVersion = "0.1.0";

} // namespace pcclt
