// Master node: accepts client control connections and drives the
// MasterState machine from a single dispatcher thread.
//
// Reference parity: CCoIPMaster/CCoIPMasterHandler (/root/reference/ccoip/
// src/cpp/ccoip_master_handler.cpp) — the reference uses one libuv loop
// thread; here each connection has a cheap blocking reader thread that
// feeds a single MPSC event queue, preserving the deterministic
// single-threaded state machine property.
#pragma once

#include <atomic>
#include <deque>
#include <map>
#include <memory>
#include <thread>

#include "annotations.hpp"
#include "master_state.hpp"
#include "sockets.hpp"
#include "thread_guard.hpp"

namespace pcclt::master {

// single-threaded by design: the MasterState machine is mutated only by
// dispatcher_loop(); state_guard_ aborts loudly on a second entrant
// (enforced by pcclt-check's `guards` checker — keep this marker on the
// class that owns the ThreadGuard)
class Master {
public:
    // journal_path non-empty enables master HA: authoritative state is
    // write-ahead-logged there and rehydrated on the next launch (same
    // world view, bumped epoch; see journal.hpp).
    explicit Master(uint16_t port, std::string journal_path = {})
        : port_(port), journal_path_(std::move(journal_path)) {}
    ~Master() { interrupt(); join(); }

    bool launch();
    void interrupt();
    void join();
    uint16_t port() const { return port_; }
    uint64_t epoch() const { return state_.epoch(); }
    // observability plane: bound metrics/health HTTP port (0 = disabled —
    // PCCLT_MASTER_METRICS_PORT unset), and the /health JSON on demand
    // (pccltMasterGetHealth / MasterNode.health() read it without HTTP)
    uint16_t metrics_port() const { return metrics_port_; }
    std::string health_json() const { return state_.render_health_json(); }

private:
    struct Conn {
        net::Socket sock;
        Mutex write_mu; // lock-rank: io (serializes this conn's fd)
        std::thread reader;
        net::Addr src_ip{};
    };
    struct Event {
        enum Kind { kPacket, kDisconnect } kind;
        uint64_t conn_id;
        net::Frame frame;
    };

    void dispatcher_loop();
    void push_event(Event ev);
    void apply_outbox(const std::vector<Outbox> &out);
    // one plain-HTTP exchange on the metrics listener's accept thread:
    // GET /metrics (Prometheus text) | /health (JSON). Short timeouts —
    // a stalled scraper must not wedge the accept loop for long.
    void serve_metrics_conn(net::Socket sock);

    uint16_t port_;
    std::string journal_path_;
    journal::Journal journal_;
    net::Listener listener_;
    net::Listener metrics_listener_;
    uint16_t metrics_port_ = 0;
    MasterState state_;
    ThreadGuard state_guard_;
    Mutex conns_mu_; // lock-rank: 30
    std::map<uint64_t, std::shared_ptr<Conn>> conns_ PCCLT_GUARDED_BY(conns_mu_);
    uint64_t next_conn_id_ PCCLT_GUARDED_BY(conns_mu_) = 1;

    Mutex ev_mu_; // lock-rank: 32
    CondVar ev_cv_;
    std::deque<Event> events_ PCCLT_GUARDED_BY(ev_mu_);
    std::thread dispatcher_;
    std::atomic<bool> running_{false};
};

} // namespace pcclt::master
