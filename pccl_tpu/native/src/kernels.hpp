// Elementwise reduce kernels over all 12 wire dtypes.
// Reference parity: /root/reference/ccoip/src/cpp/reduce_kernels.cpp —
// op structs Set/Sum/Prod/Max/Min (+Avg via Sum + finalize divide),
// dispatched over dtype. fp16/bf16 accumulate in float32.
#pragma once

#include <cstddef>

#include "protocol.hpp"

namespace pcclt::kernels {

// dst[i] = op(dst[i], src[i]); op kSum/kAvg both accumulate via add.
void accumulate(proto::DType dt, proto::RedOp op, void *dst, const void *src,
                size_t count);

// dst[i] = op(a[i], b[i]) — lets the ring's first accumulation of a chunk
// combine the local contribution and the received bytes without first
// memcpy-ing the whole send buffer into recv. dst == a is allowed.
void accumulate3(proto::DType dt, proto::RedOp op, void *dst, const void *a,
                 const void *b, size_t count);

// dst[i] = src[i]
void assign(proto::DType dt, void *dst, const void *src, size_t count);

// Bulk copy with non-temporal stores on cache-exceeding sizes (the
// destination is written once and consumed later, so skipping the
// read-for-ownership saves a third of the bus traffic). Falls back to
// memcpy below 256 KiB or without SSE2.
void copy_stream(void *dst, const void *src, size_t n);

// Avg finalization: dst[i] /= world (float dtypes; integer dtypes divide)
void finalize_avg(proto::DType dt, void *dst, size_t count, uint64_t world);

// fp16/bf16 <-> f32 converters (shared with quantization). All are defined
// inline so `#pragma omp simd` loops over 16-bit sources can widen in the
// vector lanes instead of paying a cross-TU call per element.
inline float f16_to_f32(uint16_t h) {
    uint32_t sign = (h & 0x8000u) << 16;
    uint32_t exp = (h >> 10) & 0x1F;
    uint32_t mant = h & 0x3FF;
    uint32_t u;
    if (exp == 0) {
        if (mant == 0) {
            u = sign;
        } else { // subnormal
            int e = -1;
            do {
                ++e;
                mant <<= 1;
            } while (!(mant & 0x400));
            mant &= 0x3FF;
            u = sign | ((127 - 15 - e) << 23) | (mant << 13);
        }
    } else if (exp == 0x1F) {
        u = sign | 0x7F800000u | (mant << 13);
    } else {
        u = sign | ((exp - 15 + 127) << 23) | (mant << 13);
    }
    float f;
    __builtin_memcpy(&f, &u, 4);
    return f;
}
inline uint16_t f32_to_f16(float f) {
    uint32_t u;
    __builtin_memcpy(&u, &f, 4);
    uint32_t sign = (u >> 16) & 0x8000u;
    int32_t exp = static_cast<int32_t>((u >> 23) & 0xFF) - 127 + 15;
    uint32_t mant = u & 0x7FFFFF;
    if (exp >= 0x1F)
        return static_cast<uint16_t>(
            sign | 0x7C00 |
            (((u & 0x7F800000) == 0x7F800000 && mant) ? 0x200 : 0));
    if (exp <= 0) {
        if (exp < -10) return static_cast<uint16_t>(sign);
        mant |= 0x800000;
        uint32_t shift = static_cast<uint32_t>(14 - exp);
        uint32_t half = mant >> shift;
        uint32_t rem = mant & ((1u << shift) - 1);
        uint32_t halfway = 1u << (shift - 1);
        if (rem > halfway || (rem == halfway && (half & 1))) ++half;
        return static_cast<uint16_t>(sign | half);
    }
    uint32_t half = (static_cast<uint32_t>(exp) << 10) | (mant >> 13);
    uint32_t rem = mant & 0x1FFF;
    if (rem > 0x1000 || (rem == 0x1000 && (half & 1))) ++half;
    return static_cast<uint16_t>(sign | half);
}
inline float bf16_to_f32(uint16_t b) {
    uint32_t u = static_cast<uint32_t>(b) << 16;
    float f;
    __builtin_memcpy(&f, &u, 4);
    return f;
}
inline uint16_t f32_to_bf16(float f) {
    uint32_t u;
    __builtin_memcpy(&u, &f, 4);
    // round-to-nearest-even
    uint32_t rounding = 0x7FFF + ((u >> 16) & 1);
    return static_cast<uint16_t>((u + rounding) >> 16);
}

} // namespace pcclt::kernels
