// Elementwise reduce kernels over all 12 wire dtypes.
// Reference parity: /root/reference/ccoip/src/cpp/reduce_kernels.cpp —
// op structs Set/Sum/Prod/Max/Min (+Avg via Sum + finalize divide),
// dispatched over dtype. fp16/bf16 accumulate in float32.
#pragma once

#include <cstddef>

#include "protocol.hpp"

namespace pcclt::kernels {

// dst[i] = op(dst[i], src[i]); op kSum/kAvg both accumulate via add.
void accumulate(proto::DType dt, proto::RedOp op, void *dst, const void *src,
                size_t count);

// dst[i] = op(a[i], b[i]) — lets the ring's first accumulation of a chunk
// combine the local contribution and the received bytes without first
// memcpy-ing the whole send buffer into recv. dst == a is allowed.
void accumulate3(proto::DType dt, proto::RedOp op, void *dst, const void *a,
                 const void *b, size_t count);

// dst[i] = src[i]
void assign(proto::DType dt, void *dst, const void *src, size_t count);

// Bulk copy with non-temporal stores on cache-exceeding sizes (the
// destination is written once and consumed later, so skipping the
// read-for-ownership saves a third of the bus traffic). Falls back to
// memcpy below 256 KiB or without SSE2.
void copy_stream(void *dst, const void *src, size_t n);

// Avg finalization: dst[i] /= world (float dtypes; integer dtypes divide)
void finalize_avg(proto::DType dt, void *dst, size_t count, uint64_t world);

// fp16/bf16 <-> f32 scalar converters (shared with quantization)
float f16_to_f32(uint16_t h);
uint16_t f32_to_f16(float f);
inline float bf16_to_f32(uint16_t b) {
    uint32_t u = static_cast<uint32_t>(b) << 16;
    float f;
    __builtin_memcpy(&f, &u, 4);
    return f;
}
inline uint16_t f32_to_bf16(float f) {
    uint32_t u;
    __builtin_memcpy(&u, &f, 4);
    // round-to-nearest-even
    uint32_t rounding = 0x7FFF + ((u >> 16) & 1);
    return static_cast<uint16_t>((u + rounding) >> 16);
}

} // namespace pcclt::kernels
