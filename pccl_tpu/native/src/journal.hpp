// Master-state journal: write-ahead log of the DURABLE subset of
// MasterState, so a restarted master resumes with the same world view
// (same client UUIDs, peer-group membership, ring order, shared-state
// revision, bandwidth matrix) under a bumped epoch instead of resetting
// the world.
//
// Design: the journal records STATE transitions, not protocol events —
// replay is a pure reconstruction of the durable fields, never a re-run
// of the consensus machine (in-flight votes/ops are deliberately NOT
// durable; they die with the master and clients simply retry). The file
// is a framed append-only log: a snapshot prefix (rewritten compacted on
// every open) followed by delta records. A torn tail from a crash
// mid-append is tolerated: replay stops at the first short frame.
//
// Framing: magic "PCCLJ1\n" then records of [u32 len][u8 type][payload],
// payloads in the big-endian wire format (wire.hpp). Appends are
// fflush()ed per record — the threat model is process death (SIGKILL),
// where kernel-buffered writes survive; set PCCLT_JOURNAL_FSYNC=1 to
// fdatasync each record against power loss at a latency cost.
#pragma once

#include <cstdint>
#include <cstdio>
#include <map>
#include <set>
#include <string>
#include <vector>

#include "annotations.hpp"
#include "net_addr.hpp"
#include "protocol.hpp"

namespace pcclt::journal {

using proto::Uuid;

struct ClientRec {
    Uuid uuid{};
    uint32_t peer_group = 0;
    std::string ip; // Addr::str() form (family-tagged by syntax)
    uint16_t p2p_port = 0, ss_port = 0, bench_port = 0;
    bool accepted = false;
};

struct GroupRec {
    uint64_t last_revision = 0;
    bool revision_initialized = false;
    std::vector<Uuid> ring;
    // encoded sched::Table (docs/12): the synthesized per-collective
    // schedule survives a master restart next to the ring order it was
    // costed against (version lives inside the encoding). Empty = none.
    std::vector<uint8_t> schedule;
};

struct BandwidthRec {
    Uuid from{}, to{};
    double mbps = 0;
};

// A collective that COMPLETED (verdict decided, Done emitted) — written
// write-ahead, BEFORE the Done packets leave the process. A restarted
// master uses these to REPLAY the verdict to a member whose Done was lost
// in the crash: without the record, that member re-initiates the op while
// its peers (who saw Done) have moved on — a cross-wait that stalls the
// whole group until timeouts tear it down (found by the pcclt-verify
// model checker, scenario restart_resume). `members` tracks who may still
// need the replay; entries are consumed as members resume and retry.
struct OpDoneRec {
    uint32_t group = 0;
    uint64_t tag = 0;
    uint64_t seq = 0;
    bool any_aborted = false;
    uint32_t world = 0;      // op world at commence (replayed to the client)
    std::set<Uuid> members;  // who may still need the replay (shrinks)
};

// Rehydrated view of the durable master state after replay.
struct Restored {
    uint64_t epoch = 0;             // epoch of the PREVIOUS incarnation
    uint64_t topology_revision = 0;
    uint64_t next_seq = 1;          // safe restart point for collective seqs
    std::map<Uuid, ClientRec> clients;
    std::map<uint32_t, GroupRec> groups;
    std::vector<BandwidthRec> bandwidth;
    // completed-collective verdicts still owed to members (replay on
    // re-init after a restart; see OpDoneRec)
    std::map<std::pair<uint32_t, uint64_t>, OpDoneRec> op_done;
    bool any = false;               // true when the file held prior state
};

class Journal {
public:
    ~Journal();

    // Replays an existing journal at `path` (if any) into restored(),
    // bumps the epoch, then rewrites the file as a compacted snapshot of
    // the restored state and leaves it open for appends. Returns false
    // when the file cannot be opened/created for writing.
    bool open(const std::string &path);

    const Restored &restored() const { return restored_; }
    // epoch of THIS incarnation (restored().epoch + 1, or 1 when fresh)
    uint64_t epoch() const { return epoch_; }

    // --- delta appends (thread-safe; no-ops until open() succeeded) ---
    void record_client(const ClientRec &c);
    void record_client_remove(const Uuid &u);
    void record_group(uint32_t group, uint64_t last_revision, bool initialized);
    void record_ring(uint32_t group, const std::vector<Uuid> &ring);
    // encoded sched::Table for the group (docs/12); journaled whenever a
    // new schedule version is synthesized at optimize-topology time
    void record_schedule(uint32_t group, const std::vector<uint8_t> &table);
    void record_topology_revision(uint64_t rev);
    void record_seq_bound(uint64_t bound);
    void record_bandwidth(const Uuid &from, const Uuid &to, double mbps);
    // write-ahead completed-collective verdict (call BEFORE emitting the
    // Done packets) + per-member consumption as replays are delivered
    void record_op_done(const OpDoneRec &rec);
    void record_op_done_consumed(uint32_t group, uint64_t tag, const Uuid &u);

    bool is_open() const {
        MutexLock lk(mu_);
        return f_ != nullptr;
    }

private:
    enum RecType : uint8_t {
        kEpoch = 1,
        kClient = 2,
        kClientRemove = 3,
        kGroup = 4,
        kRing = 5,
        kTopoRev = 6,
        kBandwidth = 7,
        kSeqBound = 8,
        kOpDone = 9,
        kOpDoneConsumed = 10,
        kSchedule = 11,
    };

    void append(uint8_t type, const std::vector<uint8_t> &payload)
        PCCLT_EXCLUDES(mu_);
    bool replay(const std::string &path) // fills restored_; torn-tail tolerant
        PCCLT_REQUIRES(mu_);
    bool write_snapshot() PCCLT_REQUIRES(mu_); // compacted restored_ + new epoch

    mutable Mutex mu_; // lock-rank: io (serializes this FILE*)
    FILE *f_ PCCLT_GUARDED_BY(mu_) = nullptr;
    std::string path_ PCCLT_GUARDED_BY(mu_);
    // restored_/epoch_ are written once inside open() (under mu_) before the
    // journal is published to any other thread; the const accessors read
    // them lock-free afterwards, so they carry no guard annotation.
    Restored restored_;
    uint64_t epoch_ = 1;
    bool fsync_ PCCLT_GUARDED_BY(mu_) = false;
};

} // namespace pcclt::journal
