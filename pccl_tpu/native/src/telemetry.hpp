// Flight-recorder telemetry: event ring buffer + monotonic counters.
//
// Two pieces, deliberately split by scope:
//
//  * Recorder — a PROCESS-global fixed-size event ring (spans + instants)
//    behind one relaxed atomic flag. Writers claim a slot with a relaxed
//    fetch_add and publish it with a per-slot seqlock, so the hot path is
//    mutex-free; readers (snapshot/dump — rare) retry torn slots. Enabled
//    by `PCCLT_TRACE=path` (dumped as Chrome trace-event JSON at process
//    exit; `%p` in the path expands to the pid) or via pccltTraceEnable.
//    Disabled cost: one relaxed load + branch per would-be event.
//
//  * Domain — a counter registry attached to ONE comm (or master): comm-
//    level monotonic counters (collectives by outcome, topology rounds,
//    sync outcomes incl. hash mismatches, kicks, membership churn) plus
//    per-edge counters keyed by the same canonical remote endpoint string
//    as netem ("ip:port", Addr::str()) — bytes/frames tx+rx, connections,
//    receiver wire-stall time. Counters are always on: they are relaxed
//    atomic adds at per-frame granularity (frames are 256 KiB..8 MiB), so
//    there is nothing worth gating. Multiple communicators in one process
//    (loopback tests) each get their own Domain, so per-comm attribution
//    survives in-process worlds; standalone conns (socktest) fall back to
//    a shared default Domain.
//
// The PCCLT_PROF=1 per-op phase log (reduce.cpp) is a thin consumer of the
// same clock + accumulators instead of its own chrono calls.
//
// Motivated by the WAN-training diagnosis gap ("was outer step 7 slow
// because of the wire, a straggler peer, or quantization?") — per-edge,
// per-phase visibility as called for by arxiv 2606.01680.
#pragma once

#include <array>
#include <atomic>
#include <bit>
#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <type_traits>
#include <vector>

#include "annotations.hpp"

namespace pcclt::telemetry {

// CLOCK_MONOTONIC ns — the one clock every producer (and the Python
// profiler via time.perf_counter, which is CLOCK_MONOTONIC on Linux)
// shares, so native and Python events merge onto one timeline.
uint64_t now_ns();

// Intern a dynamic string (kick reasons, endpoint labels) into a leaked
// process-wide table so events can carry `const char *` only. Bounded use:
// callers intern from small closed sets, never per-frame.
const char *intern(const std::string &s);

// THE JSON string escaper for every hand-rolled JSON emitter in the
// native tree (trace dumps, /health, incident bundles): returns the
// escaped CONTENTS (no surrounding quotes) — quote/backslash prefixed,
// control chars as \u00XX, never dropped. One copy so an escaping fix
// can't land in one emitter and drift from the others.
std::string json_escape(const std::string &s);

// PCCLT_TRACE_WINDOWS=1 (cached once): per-window data-plane lifecycle
// events (win_quant / win_submit / win_drained / rx_slice / rx_frame) —
// the verbose attribution tier on top of the recorder. Only
// meaningful while the recorder itself is on; callers must check both.
bool win_trace_enabled();

// ------------------------------------------------------------- histograms
//
// Log2-bucket latency histograms (critical-path attribution plane,
// docs/09). Always-on like the counters: record() is two relaxed atomic
// adds, so every op/stage/stall duration lands in a DISTRIBUTION, not
// just an average — averages hide the tail, and in a coupled ring the
// tail IS the step time. Bucket 0 covers [0, 8 µs); bucket i covers
// [2^(12+i), 2^(13+i)) ns; the last bucket is the overflow (>= ~137 s).

constexpr size_t kHistBuckets = 26;

// exclusive upper edge of bucket i in ns (the Prometheus `le` boundary);
// the last bucket is +Inf
inline uint64_t hist_upper_ns(size_t i) {
    return i + 1 >= kHistBuckets ? ~0ull : (1ull << (13 + i));
}

inline size_t hist_bucket(uint64_t ns) {
    uint64_t q = ns >> 13;
    size_t idx = q == 0 ? 0 : static_cast<size_t>(std::bit_width(q));
    return idx < kHistBuckets ? idx : kHistBuckets - 1;
}

// Plain (non-atomic) copy: what snapshots, digests and the master's fleet
// model carry. Buckets are per-bucket counts (NOT cumulative); renderers
// accumulate for the Prometheus `le` form.
struct HistSnapshot {
    std::array<uint64_t, kHistBuckets> buckets{};
    uint64_t sum_ns = 0;
    uint64_t count() const {
        uint64_t c = 0;
        for (auto b : buckets) c += b;
        return c;
    }
    void merge(const HistSnapshot &o) {
        for (size_t i = 0; i < kHistBuckets; ++i) buckets[i] += o.buckets[i];
        sum_ns += o.sum_ns;
    }
    // bucket-resolution quantile (upper edge of the bucket holding the
    // q-th sample): good to a factor of 2, which is what a log2 histogram
    // promises — enough to tell an 8 ms stall tail from an 800 ms one
    uint64_t quantile_ns(double q) const;
    bool empty() const { return count() == 0; }
};

// sparse <-> dense bucket conversion for the wire form (proto::WireHist
// carries (idx, count) pairs; out-of-grid indices are dropped on fold)
std::vector<std::pair<uint8_t, uint64_t>> hist_sparse(const HistSnapshot &h);
HistSnapshot hist_dense(uint64_t sum_ns,
                        const std::vector<std::pair<uint8_t, uint64_t>> &b);

class Hist {
public:
    void record(uint64_t ns) {
        buckets_[hist_bucket(ns)].fetch_add(1, std::memory_order_relaxed);
        sum_ns_.fetch_add(ns, std::memory_order_relaxed);
    }
    HistSnapshot snapshot() const {
        HistSnapshot s;
        for (size_t i = 0; i < kHistBuckets; ++i)
            s.buckets[i] = buckets_[i].load(std::memory_order_relaxed);
        s.sum_ns = sum_ns_.load(std::memory_order_relaxed);
        return s;
    }

private:
    std::atomic<uint64_t> buckets_[kHistBuckets] = {};
    std::atomic<uint64_t> sum_ns_{0};
};

// Data-plane phases a duration can be attributed to. Comm-level phases
// live on the Domain (one Hist each); the wire-facing pair (kStageWire,
// kStall) additionally lives per edge, so a distribution can name the
// hop, not just the peer.
enum class Phase : uint8_t {
    kOp = 0,        // whole collective (ring entry to ring exit)
    kCommenceWait,  // init sent -> commence received (master consensus)
    kOpSetup,       // commence -> ring links ready (snapshot + link waits)
    kQuantize,      // quantize kernel time within the op
    kDequantize,    // dequantize/accumulate kernel time within the op
    kStageWire,     // one ring stage wall time (wire + overlap compute)
    kStall,         // receiver wire-stall (op thread blocked on bytes)
    // shared-state chunk plane (docs/04): per-chunk fetch round-trip
    // (request -> last byte, netem included) and per-chunk hash-verify
    // time — the distributions that attribute a slow join
    kSyncFetch,
    kSyncVerify,
    kCount
};
constexpr size_t kPhaseCount = static_cast<size_t>(Phase::kCount);
const char *phase_name(Phase p);

// ---------------------------------------------------------------- counters

// per-direction edge watchdog verdict (docs/05 three-stage ladder)
enum class EdgeHealth : uint32_t {
    kOk = 0,       // progressing within its deadline envelope
    kSuspect = 1,  // one window missed its deadline; failover re-issued it
    kConfirmed = 2 // re-issue stalled too; data plane is relaying around it
};

struct EdgeCounters {
    std::atomic<uint64_t> tx_bytes{0};   // data payload bytes sent (TCP or CMA)
    std::atomic<uint64_t> rx_bytes{0};   // data payload bytes received
    std::atomic<uint64_t> tx_frames{0};  // data sends (frames / CMA descriptors)
    std::atomic<uint64_t> rx_frames{0};
    std::atomic<uint64_t> conns{0};      // connections established on this edge
    std::atomic<uint64_t> stall_ns{0};   // receiver wire-stall charged to this edge
    // io_uring zerocopy (docs/08 fallback ladder): frames sent SENDMSG_ZC,
    // and their completion notifications reaped (the kernel released the
    // pinned pages). Quiescent invariant: tx_zc_reaps == tx_zc_frames —
    // every ZC send's pages were returned before its handle completed.
    std::atomic<uint64_t> tx_zc_frames{0};
    std::atomic<uint64_t> tx_zc_reaps{0};
    // ---- straggler-immune data plane (docs/05) ----
    // watchdog verdict for this edge (EdgeHealth; worst of tx/rx
    // witnesses) + transition counters; cleared back to kOk when the edge
    // proves itself again (reduce.cpp probe / topology change)
    std::atomic<uint32_t> wd_health{0};
    std::atomic<uint64_t> wd_confirmed_at_ns{0};  // mono ns of the verdict
    std::atomic<uint64_t> wd_suspects{0};   // SUSPECT verdicts raised
    std::atomic<uint64_t> wd_confirms{0};   // SUSPECT -> CONFIRMED escalations
    std::atomic<uint64_t> wd_reissues{0};   // windows re-issued on a fresh conn
    std::atomic<uint64_t> wd_relays{0};     // windows relayed via a neighbor (tx)
    // EWMA achieved per-window egress rate (bytes/s) the watchdog derives
    // deadlines from; persists across ops so a mid-run fault is judged
    // against the healthy baseline
    std::atomic<uint64_t> wd_rate_bps{0};
    // receiver side: relayed payload delivered here, charged to the edge
    // of the ORIGIN peer (the hop the relay routed around), and duplicate
    // arrivals dropped by the (op, stage, window) first-arrival-wins
    // dedupe. Conservation invariant per inbound edge at quiescence:
    //   rx_bytes + rx_relay_bytes - dup_bytes == unique payload delivered.
    std::atomic<uint64_t> rx_relay_bytes{0};
    std::atomic<uint64_t> rx_relay_windows{0};
    std::atomic<uint64_t> dup_bytes{0};
    std::atomic<uint64_t> dup_windows{0};
    // ---- shared-state chunk plane (docs/04) ----
    // sync payload bytes moved on this edge: chunk/legacy state served
    // (tx) and fetched (rx). Kept apart from tx_bytes/rx_bytes — those
    // count the collective data plane and carry a conservation invariant
    // the sync traffic must not dilute.
    std::atomic<uint64_t> tx_sync_bytes{0};
    std::atomic<uint64_t> rx_sync_bytes{0};
    // ---- multipath striping (docs/08) ----
    // windows (and their payload bytes) submitted round-robin across the
    // pool by the striped window scheduler (PCCLT_STRIPE_CONNS > 1).
    // Subset of tx_bytes/tx_frames — accounting, not conservation.
    std::atomic<uint64_t> tx_stripe_windows{0};
    std::atomic<uint64_t> tx_stripe_bytes{0};
    // ---- critical-path attribution (docs/09) ----
    // latency distributions for the two phases where the EDGE is the
    // attribution key: per-ring-stage wall time on the inbound hop, and
    // receiver wire-stall slices charged to it. Always-on log2 buckets.
    Hist stage_wire_hist;
    Hist stall_hist;
};

struct CommCounters {
    std::atomic<uint64_t> collectives_ok{0};
    std::atomic<uint64_t> collectives_aborted{0};
    std::atomic<uint64_t> collectives_lost{0};
    std::atomic<uint64_t> topology_updates{0};
    std::atomic<uint64_t> topology_optimizes{0};
    std::atomic<uint64_t> syncs_ok{0};
    std::atomic<uint64_t> syncs_failed{0};
    std::atomic<uint64_t> sync_hash_mismatches{0};
    std::atomic<uint64_t> kicked{0};
    std::atomic<uint64_t> peers_joined{0};
    std::atomic<uint64_t> peers_left{0};
    // master HA: control sessions resumed after a master restart, and p2p
    // connections kept alive across a topology round (blip, not rebuild)
    std::atomic<uint64_t> master_reconnects{0};
    std::atomic<uint64_t> p2p_conns_reused{0};
    // observability plane: telemetry digests pushed to the master
    // (kC2MTelemetryDigest; 0 unless PCCLT_TELEMETRY_PUSH_MS enables it)
    std::atomic<uint64_t> telemetry_digests{0};
    // straggler-immune data plane: windows this peer forwarded as the
    // RELAY hop (neither sender nor final receiver of the window)
    std::atomic<uint64_t> relay_forwarded{0};
    // end-to-end relay delivery acks received back at the ORIGIN
    // (kRelayAck), and CONFIRMED-stalled zombie sends retired early
    // because an ack fully covered their span (docs/05)
    std::atomic<uint64_t> relay_acks{0};
    std::atomic<uint64_t> relay_retired_early{0};
    // ---- shared-state chunk plane (docs/04) ----
    // Conservation identity at sync completion (asserted by the swarm
    // bench): ss_chunk_bytes_fetched + ss_chunk_bytes_resourced -
    // ss_chunk_bytes_dup == unique chunk bytes delivered.
    std::atomic<uint64_t> ss_chunks_fetched{0};    // first-assignment arrivals
    std::atomic<uint64_t> ss_chunks_resourced{0};  // re-sourced arrivals
    std::atomic<uint64_t> ss_chunks_dup{0};        // already-delivered arrivals
    std::atomic<uint64_t> ss_chunk_bytes_fetched{0};
    std::atomic<uint64_t> ss_chunk_bytes_resourced{0};
    std::atomic<uint64_t> ss_chunk_bytes_dup{0};
    std::atomic<uint64_t> ss_seeder_chunks_served{0};  // chunks this peer served
    std::atomic<uint64_t> ss_seeder_promotions{0};     // keys promoted mid-round
    std::atomic<uint64_t> ss_seeders_lost{0};          // sources lost mid-fetch
    std::atomic<uint64_t> ss_legacy_syncs{0};          // fell back to 1-seeder path
    // sparse revision delta (docs/04): chunks whose request-time local
    // leaf already matched the expected leaf — born done, never travel.
    // Extends the identity: unique delivered + bytes_delta_skipped ==
    // total dirty-key bytes.
    std::atomic<uint64_t> ss_chunks_delta_skipped{0};
    std::atomic<uint64_t> ss_chunk_bytes_delta_skipped{0};
    // ---- synthesized schedules (docs/12) ----
    // Ops executed per stamped algorithm, interpreter steps executed, and
    // PLANNED relay bytes (kRelayRing detours) — kept separate from the
    // watchdog ladder's emergency wd_relays/rx_relay_bytes so dashboards
    // can tell a chosen detour from a failover.
    std::atomic<uint64_t> sched_ops_ring{0};
    std::atomic<uint64_t> sched_ops_tree{0};
    std::atomic<uint64_t> sched_ops_butterfly{0};
    std::atomic<uint64_t> sched_ops_mesh{0};
    std::atomic<uint64_t> sched_ops_relay{0};
    std::atomic<uint64_t> sched_steps{0};
    std::atomic<uint64_t> sched_relay_planned_bytes{0};
};

struct EdgeSnapshot {
    std::string endpoint;
    uint64_t tx_bytes = 0, rx_bytes = 0, tx_frames = 0, rx_frames = 0,
             conns = 0, stall_ns = 0, tx_zc_frames = 0, tx_zc_reaps = 0;
    uint32_t wd_health = 0;
    uint64_t wd_suspects = 0, wd_confirms = 0, wd_reissues = 0, wd_relays = 0,
             rx_relay_bytes = 0, rx_relay_windows = 0, dup_bytes = 0,
             dup_windows = 0;
    uint64_t tx_sync_bytes = 0, rx_sync_bytes = 0;
    uint64_t tx_stripe_windows = 0, tx_stripe_bytes = 0;
    HistSnapshot stage_wire_hist, stall_hist;
};

// One completed collective's coarse timing, kept in a small per-Domain
// ring so a telemetry digest can carry the last-N phase timings without
// reading (or enabling) the event ring.
struct OpSample {
    uint64_t seq = 0;       // master-issued collective seq
    uint64_t dur_ns = 0;    // whole-op wall time (ring entry to ring exit)
    uint64_t stall_ns = 0;  // receiver wire-stall within the op
};

class Domain {
public:
    CommCounters comm;

    // Counters for the edge toward `endpoint` (canonical "ip:port", the
    // netem key). The returned reference is never invalidated.
    EdgeCounters &edge(const std::string &endpoint);

    std::vector<EdgeSnapshot> snapshot_edges() const;

    // Record one completed collective (reduce.cpp, op end). Keeps the
    // newest kOpRing samples and the highest seq observed.
    void record_op(uint64_t seq, uint64_t dur_ns, uint64_t stall_ns);
    // newest-last, at most kOpRing entries
    std::vector<OpSample> recent_ops() const;
    uint64_t last_seq() const { return last_seq_.load(std::memory_order_relaxed); }

    // comm-level phase latency distributions (critical-path attribution):
    // one always-on log2 histogram per Phase. The edge-keyed pair
    // (kStageWire/kStall) is ALSO recorded here so the comm-level view
    // stays complete when edge resolution is unavailable.
    void record_phase(Phase p, uint64_t ns) {
        phase_hist_[static_cast<size_t>(p)].record(ns);
    }
    HistSnapshot phase_snapshot(Phase p) const {
        return phase_hist_[static_cast<size_t>(p)].snapshot();
    }

    static constexpr size_t kOpRing = 8;

private:
    mutable Mutex mu_; // lock-rank: 66
    // values are never erased and pointees never move: edge() hands out
    // references that outlive the lock (counter adds are lock-free atomics)
    std::map<std::string, std::unique_ptr<EdgeCounters>> edges_
        PCCLT_GUARDED_BY(mu_);
    mutable Mutex op_mu_; // lock-rank: 67
    OpSample ops_[kOpRing] PCCLT_GUARDED_BY(op_mu_);
    uint64_t op_head_ PCCLT_GUARDED_BY(op_mu_) = 0;
    std::atomic<uint64_t> last_seq_{0};
    Hist phase_hist_[kPhaseCount];  // lock-free like the edge counters
};

// Shared fallback for conns constructed without a comm (socktest, tools).
const std::shared_ptr<Domain> &default_domain();

// ---------------------------------------------------------------- events

struct Event {
    uint64_t ts_ns = 0;          // CLOCK_MONOTONIC
    uint64_t dur_ns = 0;         // 0 = instant
    const char *cat = "";        // static string
    const char *name = "";       // static string
    const char *arg0 = nullptr;  // optional arg names (static/interned)
    const char *arg1 = nullptr;
    const char *arg2 = nullptr;
    uint64_t v0 = 0, v1 = 0, v2 = 0;
    const char *detail = nullptr;  // optional interned string arg
    // master epoch at push time (set_epoch — welcome/resume/journal
    // rehydrate). Stamped into every event so tools/trace_merge can
    // correlate per-peer traces on (epoch, seq) across master restarts.
    uint64_t epoch = 0;
    uint32_t tid = 0;
};

class Recorder {
public:
    static Recorder &inst();

    bool on() const { return on_.load(std::memory_order_relaxed); }
    void enable(bool on) { on_.store(on, std::memory_order_relaxed); }

    // [t0, t1) span. All const char* must be static or interned.
    void span(const char *cat, const char *name, uint64_t t0_ns, uint64_t t1_ns,
              const char *arg0 = nullptr, uint64_t v0 = 0,
              const char *arg1 = nullptr, uint64_t v1 = 0,
              const char *detail = nullptr,
              const char *arg2 = nullptr, uint64_t v2 = 0);
    void instant(const char *cat, const char *name,
                 const char *arg0 = nullptr, uint64_t v0 = 0,
                 const char *arg1 = nullptr, uint64_t v1 = 0,
                 const char *detail = nullptr,
                 const char *arg2 = nullptr, uint64_t v2 = 0);

    // time-ordered copy of the ring (newest kCap events survive)
    std::vector<Event> snapshot() const;
    void clear();

    // events pushed since the last clear(), and how many of those were
    // LOST to ring wrap (overwritten before any snapshot could see them).
    // A nonzero drop count means traces/digests are silently truncated —
    // surfaced in Communicator.stats() and the PCCLT_TRACE dump header.
    uint64_t pushed() const {
        return head_.load(std::memory_order_relaxed) -
               base_.load(std::memory_order_relaxed);
    }
    uint64_t dropped() const {
        uint64_t p = pushed();
        return p > kCap ? p - kCap : 0;
    }
    // ring capacity (events that survive a capture window) — surfaced on
    // /metrics so a scraper can judge pushed/dropped against it
    static constexpr size_t ring_capacity() { return kCap; }

    // Master epoch stamped into every subsequent event (client: welcome /
    // resume ack; master: journal rehydrate). Process-global like the
    // recorder itself; 0 = no master contact yet.
    void set_epoch(uint64_t e) { epoch_.store(e, std::memory_order_relaxed); }
    uint64_t epoch() const { return epoch_.load(std::memory_order_relaxed); }

    // Chrome trace-event JSON (chrome://tracing, ui.perfetto.dev). ts/dur
    // in microseconds on the raw CLOCK_MONOTONIC timebase, so a consumer
    // holding a perf_counter anchor can align Python sections exactly.
    bool dump_json(const std::string &path) const;

    // The PCCLT_TRACE path with %p expanded, or empty when unset.
    static std::string env_trace_path();

private:
    Recorder();
    void push(const Event &ev);

    static constexpr size_t kCap = 1 << 16;  // newest 64k events survive
    // Seqlock slot. The event bytes live in relaxed atomic WORDS (not a
    // plain Event) so a concurrent reader's torn copy is detected by the
    // generation double-check without a data race (Boehm, "Can seqlocks
    // get along with programming language memory models?"); the fences in
    // push()/snapshot() provide the store-store / load-load ordering the
    // relaxed accesses need.
    static_assert(std::is_trivially_copyable_v<Event>);
    static constexpr size_t kEvWords = (sizeof(Event) + 7) / 8;
    struct Slot {
        std::atomic<uint64_t> seq{0};  // 0 free; odd = writing; even = gen done
        std::atomic<uint64_t> w[kEvWords] = {};
    };
    std::atomic<bool> on_{false};
    std::atomic<uint64_t> head_{0};
    std::atomic<uint64_t> base_{0};  // head_ at the last clear()
    std::atomic<uint64_t> epoch_{0};
    std::unique_ptr<Slot[]> ring_;
};

// ---------------------------------------------------------------- digests
//
// Tier 1 of the fleet observability plane (docs/09): fold the always-on
// counters into a compact fixed-size digest suitable for pushing to the
// master on a cadence (kC2MTelemetryDigest). Rates are EWMAs over the
// push intervals so a transient dip neither vanishes (a point sample
// would miss it) nor sticks forever (a lifetime mean would dilute it).

struct EdgeDigest {
    std::string endpoint;    // canonical "ip:port" (netem/telemetry key)
    double tx_mbps = 0;      // EWMA achieved egress, megabits/s
    double rx_mbps = 0;      // EWMA achieved ingress, megabits/s
    double stall_ratio = 0;  // EWMA wire-stall ns per interval ns (0..~1)
    uint64_t tx_bytes = 0;   // cumulative counters at snapshot time —
    uint64_t rx_bytes = 0;   //   the master re-exports these, so a scrape
                             //   can be reconciled against peer stats()
    uint32_t wd_state = 0;   // EdgeHealth at snapshot time: a CONFIRMED
                             //   edge tells the master to fire the
                             //   straggler re-opt without waiting for the
                             //   rate-based detector to notice
    // cumulative latency distributions for the edge-keyed phases (the
    // master re-exports these as Prometheus histogram series; cumulative,
    // not interval, so a missed digest never loses samples)
    HistSnapshot stage_wire_hist, stall_hist;
};

// (the master epoch is NOT part of the digest fold: the push loop stamps
// it onto the wire packet directly from the session state)
struct Digest {
    uint64_t last_seq = 0;     // newest collective seq completed locally
    uint64_t interval_ns = 0;  // wall time folded into this digest
    uint64_t ring_dropped = 0; // flight-recorder events lost to wrap
    uint64_t ring_pushed = 0;  // events pushed since the last clear
    uint64_t ring_cap = 0;     // recorder ring capacity (saturation gauge)
    uint64_t collectives_ok = 0;
    std::vector<EdgeDigest> edges;
    std::vector<OpSample> ops; // last-N completed op timings (newest last)
    // comm-level phase latency distributions, cumulative (indexed by
    // telemetry::Phase; empty hists are skipped on the wire)
    std::array<HistSnapshot, kPhaseCount> phases{};
};

// Folds a Domain's counters into interval rates. Owned and driven by ONE
// thread (the client's telemetry push thread); not thread-safe itself —
// the counters it reads are.
class DigestSnapshotter {
public:
    explicit DigestSnapshotter(std::shared_ptr<Domain> d, double alpha = 0.3)
        : d_(std::move(d)), alpha_(alpha) {}

    // Delta since the previous snapshot() (first call: since construction
    // counters, rates seeded from the first interval).
    Digest snapshot();

private:
    std::shared_ptr<Domain> d_;
    double alpha_;
    uint64_t prev_t_ = now_ns();
    struct PrevEdge {
        uint64_t tx_bytes = 0, rx_bytes = 0, stall_ns = 0;
        double tx_mbps = 0, rx_mbps = 0, stall_ratio = 0;
        bool seeded = false;
    };
    std::map<std::string, PrevEdge> prev_;
};

// RAII span: records [ctor, dtor) when the recorder is enabled at ctor time.
class Span {
public:
    Span(const char *cat, const char *name, const char *arg0 = nullptr,
         uint64_t v0 = 0, const char *arg1 = nullptr, uint64_t v1 = 0)
        : cat_(cat), name_(name), arg0_(arg0), arg1_(arg1), v0_(v0), v1_(v1),
          t0_(Recorder::inst().on() ? now_ns() : 0) {}
    ~Span() {
        if (t0_)
            Recorder::inst().span(cat_, name_, t0_, now_ns(), arg0_, v0_,
                                  arg1_, v1_);
    }
    Span(const Span &) = delete;
    Span &operator=(const Span &) = delete;

private:
    const char *cat_, *name_, *arg0_, *arg1_;
    uint64_t v0_, v1_, t0_;
};

}  // namespace pcclt::telemetry
