// Registered shared-memory regions: the same-host ZERO-copy transport.
//
// pcclt's CMA fast path (sockets.hpp) moves same-host payloads with ONE
// kernel copy (process_vm_readv). Buffers allocated through this registry go
// further: they live in memfd-backed shared memory, the owning process
// announces {pid, fd, base, len} to each same-host peer connection, and the
// peer maps the region via /proc/<pid>/fd/<fd> — the SAME ptrace-permission
// model process_vm_readv already requires. From then on any CMA descriptor
// whose span lies inside a registered region resolves to a direct local
// pointer on the receiver: ring reduce-scatter accumulates straight out of
// the sender's buffer (no copy at all), and all-gather fills are a plain
// memcpy instead of a syscall pull.
//
// This is the registered-buffer concept of NCCL (ncclCommRegister) and
// MPI-3 RMA windows, redesigned for pcclt's descriptor/ack protocol. The
// reference (jundi69/pccl) has no same-host fast path at all — its
// MultiplexedIOSocket always streams over TCP (reference
// tinysockets/src/multiplexed_socket.cpp) — so this subsystem is a
// pcclt-specific performance layer, not a port.
//
// Lifecycle rules:
//  - alloc() creates + registers a region (memfd, MAP_SHARED).
//  - free_buf() retires it: the registry bumps a retire sequence that every
//    conn's TX thread drains into kShmRetire frames BEFORE its next data
//    send, so peers unmap before the address range can be reused by a
//    later allocation. The memory itself is unmapped immediately.
//  - a SIGKILL'd owner leaks nothing persistent: memfds die with the
//    process (peer mappings stay readable until they unmap — exactly what
//    an in-flight consumer needs to fail soft).
#pragma once

#include <cstddef>
#include <cstdint>
#include <optional>
#include <vector>

namespace pcclt::shm {

struct Region {
    uint64_t id = 0;   // process-unique, never reused
    int fd = -1;       // memfd (owner process)
    uint8_t *base = nullptr;
    size_t len = 0;
};

// Allocate `len` bytes of registered shared memory (nullptr on failure).
void *alloc(size_t len);

// Retire a registered region by base pointer. Returns false if `p` is not
// a live registered base. The pages are released immediately, but the
// virtual range stays reserved PROT_NONE forever — a later allocation can
// never occupy an address a peer might still resolve through a stale
// mapping, so a straggling descriptor can fault soft but never read the
// wrong buffer. (Virtual-only cost; 64-bit address space is not scarce.)
bool free_buf(void *p);

// Region containing [p, p+len), if any.
std::optional<Region> find(const void *p, size_t len);

// Retire feed for conn TX threads: all retires with seq > *cursor, oldest
// first; advances *cursor. Each entry is the retired region's base address
// in THIS process (the peer resolves it against its announce records).
// `reset` is set when the feed was compacted past the caller's cursor
// (a conn that lagged thousands of frees behind): the caller must then
// retire EVERYTHING it has announced on its conn — losing individual
// entries can never silently leak a peer mapping.
struct RetireFeed {
    bool reset = false;
    std::vector<uint64_t> bases;
};
RetireFeed drain_retires(uint64_t *cursor);

// Number of live registered regions (tests / introspection).
size_t live_regions();

} // namespace pcclt::shm
