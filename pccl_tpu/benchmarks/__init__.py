"""On-hardware benchmarks for the model families (tokens/s, MFU)."""

from . import model_bench  # noqa: F401
