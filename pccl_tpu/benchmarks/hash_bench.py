"""On-chip shared-state hash benchmark: the clean-sync invariant.

The reference hashes CUDA buffers on the GPU so a clean shared-state sync
never stages device memory to host (/root/reference/ccoip/src/cuda/
simplehash_cuda.cu). This leg measures the TPU twin of that invariant:
`jax_simplehash_device` (hash type 2 — the digest computed on the chip,
8 bytes crossing to the host) against the staging path (`device_get` the
whole array, hash on host) at growing state sizes. On the axon dev tunnel
D2H sustains ~0.03 GB/s, so the staging path scales with state size into
tens of seconds while the device digest stays flat — which is exactly the
claim: clean-sync cost is independent of state size.

Run as __main__ in a subprocess (libtpu is process-exclusive); prints one
JSON line.
"""

from __future__ import annotations

import time
from typing import Dict


def run_hash_bench(sizes_mb=(16, 64, 256)) -> Dict[str, float]:
    import jax
    import jax.numpy as jnp

    from ..ops.hashing import jax_simplehash_device, simplehash_tpu

    if not any(d.platform == "tpu" for d in jax.devices()):
        raise RuntimeError("no TPU device present")

    out: Dict[str, float] = {}
    for mb in sizes_mb:
        n = mb * (1 << 20) // 4
        arr = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
        arr.block_until_ready()

        # device digest: the int() conversion inside is the host readback
        # fence (8 bytes through the tunnel)
        h_dev = jax_simplehash_device(arr)      # warmup incl. compile
        t0 = time.perf_counter()
        h_dev = jax_simplehash_device(arr)
        out[f"devhash_{mb}mb_s"] = time.perf_counter() - t0

        # staging path: what from_jax (eager) pays every sync — the full
        # array through the tunnel, then the host-side twin
        if mb <= 64:  # 256 MB staging would take ~10 s/GB-scale minutes
            import numpy as np

            t0 = time.perf_counter()
            host = np.asarray(jax.device_get(arr))
            h_host = simplehash_tpu(host)
            out[f"stagehash_{mb}mb_s"] = time.perf_counter() - t0
            assert h_host == h_dev, "device/host digest parity broke"
    return out


if __name__ == "__main__":
    import json

    print(json.dumps({k: round(v, 4) for k, v in run_hash_bench().items()}))
