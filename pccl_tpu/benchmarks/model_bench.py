"""On-chip train-step benchmark: tokens/s and MFU on the real TPU.

The reference's culture is to publish its headline numbers
(/root/reference/docs/md/01_Introduction.md:8 — "45 Gbit/s sustained");
its model compute lives in torch training loops
(/root/reference/python/examples/nanogptddp/train_pccl.py). pccl_tpu's
equivalent headline is the thing the reference cannot measure at all: the
jitted bf16 train step (parallel/train.py:build_train_step) executing on an
actual TPU chip, reported as tokens/s and model-FLOPs utilization.

Methodology notes:

- **Fencing.** `block_until_ready` is not a reliable execution fence through
  every TPU transport (observed: a chained-matmul "benchmark" reporting 19×
  the chip's peak because readiness resolved before execution). The only
  trustworthy fence is a host readback of data that depends on the work, so
  each timed window ends with `float(loss)` — which a training loop does
  anyway. Steps inside a window chain through the donated params, so the
  window measures the real back-to-back step rate, including dispatch.

- **MFU convention.** Model FLOPs are the algorithmic count (6·matmul-params
  per token + 12·L·T·d attention, the PaLM-appendix formula); recompute done
  by the flash-attention backward does NOT count toward the numerator, so
  the reported MFU is conservative.
"""

from __future__ import annotations

import statistics
import time
from typing import Any, Dict

import numpy as np


# Peak dense bf16 FLOP/s per chip, by `device_kind` prefix (public TPU
# datasheet numbers). Used as the MFU denominator.
PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v5": 459.0,
    "TPU v6 lite": 918.0,   # v6e / Trillium
    "TPU v6e": 918.0,
}

# Per-family on-chip bench shapes: largest preset whose train state
# (fp32 params + 2 AdamW moments + transient fp32 grads) plus activations
# fits a single 16 GB v5e comfortably. Tuned empirically on the chip:
# remat is mandatory (every no-remat shape at these sizes OOMs — dense b8
# wants 34.6 GB), and XLA's dense attention beats the pallas flash kernel
# at T<=2048 (the kernel pays grid overhead per tiny block; it earns its
# keep at long T where dense probs don't fit — see ops/flash_attention.py).
DEFAULT_SHAPES = {
    # gpt: the "dots" policy (save weight-matmul outputs, recompute the
    # rest) beats full remat at b12 (31.3% vs 30.1% MFU) with HBM headroom
    "gpt": dict(preset="gpt2-medium", batch=12, seq=1024, remat="dots"),
    # llama: full remat at b4 (36.2%) beats dots, which only fits b2 (34.8%)
    "llama": dict(preset="700m", batch=4, seq=2048, remat=True),
}


def peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for prefix, tf in sorted(PEAK_BF16_TFLOPS.items(),
                             key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return tf
    raise ValueError(f"unknown TPU device kind {kind!r}; "
                     "add it to PEAK_BF16_TFLOPS")


def flops_per_token(cfg, seq: int) -> float:
    """Algorithmic train FLOPs per token (fwd 2×matmul-params + attention,
    backward = 2× forward)."""
    from ..models import llama

    d, L = cfg.n_embd, cfg.n_layer
    if isinstance(cfg, llama.LlamaConfig):
        kv = cfg.n_kv_head * cfg.head_dim
        per_layer = d * d + d * 2 * kv + d * d + 3 * d * cfg.ffn_dim
        head = cfg.vocab_size * d            # untied unembedding
    else:
        per_layer = 12 * d * d               # qkv + out + mlp_in + mlp_out
        head = cfg.vocab_size * d            # tied unembedding matmul
    matmul_params = L * per_layer + head
    # attention: QK^T + AV are 2·T·d each fwd per layer → ×3 for fwd+bwd
    return 6.0 * matmul_params + 12.0 * L * seq * d


def _named_config(family: str, preset: str, seq: int, **overrides):
    from ..models import gpt, llama

    mod = llama if family == "llama" else gpt
    return mod.named_config(preset, block_size=seq, **overrides)


def run_tpu_train_bench(family: str = "gpt", preset: str | None = None,
                        batch: int | None = None, seq: int | None = None,
                        steps_per_window: int = 8, windows: int = 5,
                        use_flash: bool = False,
                        remat: "bool | str | None" = None,
                        repeat_kv: bool = False,
                        loss_chunk: int = 0,
                        **cfg_overrides) -> Dict[str, Any]:
    """Measure the jitted train step on the first TPU device.

    Returns {config, tokens_s (median), tokens_s_min/max, step_s, mfu,
    model_tflops_per_step, loss_first, loss_last}. Raises RuntimeError when
    no TPU is present (callers skip-guard)."""
    import jax
    import jax.numpy as jnp

    tpus = [d for d in jax.devices() if d.platform == "tpu"]
    if not tpus:
        raise RuntimeError("no TPU device present")
    dev = tpus[0]

    shape = dict(DEFAULT_SHAPES[family])
    if preset:
        shape["preset"] = preset
    if batch:
        shape["batch"] = batch
    if seq:
        shape["seq"] = seq
    if remat is not None:
        shape["remat"] = remat
    B, T = shape["batch"], shape["seq"]
    do_remat = shape.get("remat", False)
    cfg = _named_config(family, shape["preset"], T, **cfg_overrides)

    from jax.sharding import Mesh
    from ..parallel import train as train_lib
    from ..ops.flash_attention import flash_attention

    mesh = Mesh(np.array(tpus[:1]).reshape(1, 1), ("dp", "tp"))
    attn_fn = flash_attention if use_flash else None
    if repeat_kv and use_flash:
        # A/B ablation: the round-4 degraded path — materialize K/V at the
        # full head count in HBM before the kernel, forfeiting GQA's
        # KV-bytes shrink. Measures what the GQA-native kernels buy.
        def attn_fn(q, k, v):  # noqa: F811 — deliberate override
            H, Hkv = q.shape[2], k.shape[2]
            if Hkv != H:
                k = jnp.repeat(k, H // Hkv, axis=2)
                v = jnp.repeat(v, H // Hkv, axis=2)
            return flash_attention(q, k, v)
    with mesh:
        params, tx, opt_state = train_lib.make_train_state(
            jax.random.PRNGKey(0), cfg, mesh)
        step = train_lib.build_train_step(cfg, tx, mesh, attn_fn=attn_fn,
                                          remat=do_remat,
                                          loss_chunk=loss_chunk or None)

        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)),
                             dtype=jnp.int32)
        targets = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)),
                              dtype=jnp.int32)

        # warmup: compile + one full readback fence
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        loss_first = float(loss)

        rates = []
        loss_last = loss_first
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(steps_per_window):
                params, opt_state, loss = step(params, opt_state, tokens,
                                               targets)
            loss_last = float(loss)          # host readback = the fence
            dt = time.perf_counter() - t0
            rates.append(steps_per_window * B * T / dt)

    # Trimmed-window policy: the axon dev tunnel that fences each window
    # (the float(loss) host readback) occasionally stalls for hundreds of
    # ms, collapsing one window to ~45% of the others — a transport
    # artifact, not step-time variance (the same config re-run shows the
    # stall migrating between windows). Windows below 60% of the best are
    # excluded from the headline median; the RAW min/max and the count of
    # trimmed windows stay in the artifact so the spread is never hidden.
    trimmed = [r for r in rates if r >= 0.6 * max(rates)]
    tok_s = statistics.median(trimmed)
    ftok = flops_per_token(cfg, T)
    peak = peak_tflops(dev) * 1e12
    return {
        "stall_windows": len(rates) - len(trimmed),
        "config": f"{family}/{shape['preset']} b{B}x{T} "
                  f"{'flash' if use_flash else 'dense'}"
                  f"{'+remat' if do_remat is True else ''}"
                  f"{'+remat:' + do_remat if isinstance(do_remat, str) else ''}"
                  f"{'+ce:' + str(loss_chunk) if loss_chunk else ''}"
                  f"{'+repeatkv' if repeat_kv else ''}"
                  f" ({dev.device_kind})",
        "tokens_s": round(tok_s, 1),
        "tokens_s_min": round(min(rates), 1),
        "tokens_s_max": round(max(rates), 1),
        "step_s": round(B * T / tok_s, 4),
        "model_tflops_per_step": round(ftok * B * T / 1e12, 2),
        "mfu": round(tok_s * ftok / peak, 4),
        "loss_first": round(loss_first, 3),
        "loss_last": round(loss_last, 3),
    }


if __name__ == "__main__":
    import json
    import sys

    fam = sys.argv[1] if len(sys.argv) > 1 else "gpt"
    kw = {}
    for a in sys.argv[2:]:
        k, v = a.split("=")
        if k == "preset":
            kw[k] = v
        elif k == "remat":
            kw[k] = v if v in ("dots", "sqrt") else bool(int(v))
        elif k in ("use_flash", "untie_head", "repeat_kv"):
            kw[k] = bool(int(v))
        else:
            kw[k] = int(v)  # batch/seq/windows + int config overrides
                            # (n_head, n_embd, ... — ablation legs)
    print(json.dumps(run_tpu_train_bench(fam, **kw)))
