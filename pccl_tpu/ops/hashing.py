"""Device-independent content hashing — the Python twin of the native hash.

Reference parity: the reference's simplehash deliberately makes its CPU
implementation emulate the CUDA grid (256-thread blocks, warp shuffles) so
CPU and GPU produce identical digests (/root/reference/ccoip/src/cpp/
simplehash/simplehash_cpu.cpp:7-58) — bit parity across devices is the core
invariant of shared-state drift detection.

TPU-first re-design (matches pccl_tpu/native/src/hash.cpp exactly): bytes →
little-endian u32 words (zero-padded tail); word i feeds lane (i % 256) via
Horner with P; lanes combine with a second Horner pass with Q, seeded with
the byte length; murmur-style avalanche finalizes. The lane structure means
the whole digest is expressible as vectorized numpy over a [n_chunks, 256]
word matrix — no per-element Python loop — and the SAME digest is reproduced
by the C++ core (pccltHashBuffer), so a TPU host process can hash staged HBM
bytes wherever convenient and compare against any peer.

CRC32 (hash type 1) needs no twin: the native implementation matches
zlib.crc32 (IEEE reflected polynomial).
"""

from __future__ import annotations

import numpy as np

LANES = 256
P = np.uint64(0x100000001B3)          # FNV-1a prime
Q = np.uint64(0x9E3779B97F4A7C15)     # 2^64 / phi
SEED = np.uint64(0xCBF29CE484222325)  # FNV offset basis
_M1 = np.uint64(0xFF51AFD7ED558CCD)
_M2 = np.uint64(0xC4CEB9FE1A85EC53)


_BLOCK = 4096  # full rows folded per vectorized step


def _p_powers(n: int) -> np.ndarray:
    """P^0..P^n with uint64 wraparound, computed once at import."""
    with np.errstate(over="ignore"):
        pows = np.empty(n + 1, dtype=np.uint64)
        pows[0] = np.uint64(1)
        for i in range(1, n + 1):
            pows[i] = pows[i - 1] * P
    return pows


_P_POWS = _p_powers(_BLOCK)


def _avalanche64(x: np.uint64) -> np.uint64:
    with np.errstate(over="ignore"):
        x ^= x >> np.uint64(33)
        x *= _M1
        x ^= x >> np.uint64(33)
        x *= _M2
        x ^= x >> np.uint64(33)
    return x


def simplehash(buf) -> int:
    """Digest of a bytes-like / ndarray's raw content. Bit-identical to the
    native pcclt::hash::simplehash."""
    if isinstance(buf, np.ndarray):
        data = np.ascontiguousarray(buf).view(np.uint8).reshape(-1)
    else:
        data = np.frombuffer(memoryview(buf), dtype=np.uint8)
    nbytes = data.size

    n_words = (nbytes + 3) // 4
    padded = np.zeros(((n_words + LANES - 1) // LANES) * LANES * 4,
                      dtype=np.uint8)
    padded[:nbytes] = data
    words = padded.view("<u4").astype(np.uint64).reshape(-1, LANES)

    # lane[l] = Horner over its word column. Full rows fold in blocks of B
    # (lane = lane * P^B + Σ words[r] * P^(B-1-r)), so the work is a
    # vectorized weighted sum instead of a per-row Python loop.
    lane = np.full(LANES, SEED, dtype=np.uint64)
    n_rows = n_words // LANES          # full rows of the word matrix
    with np.errstate(over="ignore"):
        pows = _P_POWS
        r = 0
        while r < n_rows:
            b = min(_BLOCK, n_rows - r)
            block = words[r:r + b]
            weights = pows[b - 1::-1][:, None]      # P^(b-1) ... P^0
            lane = lane * pows[b] + (block * weights).sum(axis=0,
                                                          dtype=np.uint64)
            r += b
        if n_rows * LANES != n_words:  # partial last row
            k = n_words - n_rows * LANES
            lane[:k] = lane[:k] * P + words[n_rows, :k]
        acc = SEED ^ (np.uint64(nbytes) * Q)
        for lv in lane:
            acc = acc * Q + lv
    return int(_avalanche64(acc))


def jax_simplehash(arr) -> int:
    """Digest of a jax.Array's content: stages to host once (over ICI for a
    sharded array) and hashes the canonical row-major bytes. Every device
    layout of the same logical array yields the same digest."""
    import jax

    host = np.asarray(jax.device_get(arr))
    return simplehash(host)
