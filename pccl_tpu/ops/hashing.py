"""Device-independent content hashing — the Python twin of the native hash.

Reference parity: the reference's simplehash deliberately makes its CPU
implementation emulate the CUDA grid (256-thread blocks, warp shuffles) so
CPU and GPU produce identical digests (/root/reference/ccoip/src/cpp/
simplehash/simplehash_cpu.cpp:7-58) — bit parity across devices is the core
invariant of shared-state drift detection.

TPU-first re-design (matches pccl_tpu/native/src/hash.cpp exactly): bytes →
little-endian u32 words (zero-padded tail); word i feeds lane (i % 256) via
Horner with P; lanes combine with a second Horner pass with Q, seeded with
the byte length; murmur-style avalanche finalizes. The lane structure means
the whole digest is expressible as vectorized numpy over a [n_chunks, 256]
word matrix — no per-element Python loop — and the SAME digest is reproduced
by the C++ core (pccltHashBuffer), so a TPU host process can hash staged HBM
bytes wherever convenient and compare against any peer.

CRC32 (hash type 1) needs no twin: the native implementation matches
zlib.crc32 (IEEE reflected polynomial).
"""

from __future__ import annotations

import numpy as np

LANES = 256
P = np.uint64(0x100000001B3)          # FNV-1a prime
Q = np.uint64(0x9E3779B97F4A7C15)     # 2^64 / phi
SEED = np.uint64(0xCBF29CE484222325)  # FNV offset basis
_M1 = np.uint64(0xFF51AFD7ED558CCD)
_M2 = np.uint64(0xC4CEB9FE1A85EC53)


_BLOCK = 4096  # full rows folded per vectorized step


def _p_powers(n: int) -> np.ndarray:
    """P^0..P^n with uint64 wraparound, computed once at import."""
    with np.errstate(over="ignore"):
        pows = np.empty(n + 1, dtype=np.uint64)
        pows[0] = np.uint64(1)
        for i in range(1, n + 1):
            pows[i] = pows[i - 1] * P
    return pows


_P_POWS = _p_powers(_BLOCK)


def _avalanche64(x: np.uint64) -> np.uint64:
    with np.errstate(over="ignore"):
        x ^= x >> np.uint64(33)
        x *= _M1
        x ^= x >> np.uint64(33)
        x *= _M2
        x ^= x >> np.uint64(33)
    return x


def simplehash(buf) -> int:
    """Digest of a bytes-like / ndarray's raw content. Bit-identical to the
    native pcclt::hash::simplehash."""
    if isinstance(buf, np.ndarray):
        data = np.ascontiguousarray(buf).view(np.uint8).reshape(-1)
    else:
        data = np.frombuffer(memoryview(buf), dtype=np.uint8)
    nbytes = data.size

    n_words = (nbytes + 3) // 4
    padded = np.zeros(((n_words + LANES - 1) // LANES) * LANES * 4,
                      dtype=np.uint8)
    padded[:nbytes] = data
    words = padded.view("<u4").astype(np.uint64).reshape(-1, LANES)

    # lane[l] = Horner over its word column. Full rows fold in blocks of B
    # (lane = lane * P^B + Σ words[r] * P^(B-1-r)), so the work is a
    # vectorized weighted sum instead of a per-row Python loop.
    lane = np.full(LANES, SEED, dtype=np.uint64)
    n_rows = n_words // LANES          # full rows of the word matrix
    with np.errstate(over="ignore"):
        pows = _P_POWS
        r = 0
        while r < n_rows:
            b = min(_BLOCK, n_rows - r)
            block = words[r:r + b]
            weights = pows[b - 1::-1][:, None]      # P^(b-1) ... P^0
            lane = lane * pows[b] + (block * weights).sum(axis=0,
                                                          dtype=np.uint64)
            r += b
        if n_rows * LANES != n_words:  # partial last row
            k = n_words - n_rows * LANES
            lane[:k] = lane[:k] * P + words[n_rows, :k]
        acc = SEED ^ (np.uint64(nbytes) * Q)
        for lv in lane:
            acc = acc * Q + lv
    return int(_avalanche64(acc))


def jax_simplehash(arr) -> int:
    """Digest of a jax.Array's content: stages to host once (over ICI for a
    sharded array) and hashes the canonical row-major bytes. Every device
    layout of the same logical array yields the same digest."""
    import jax

    host = np.asarray(jax.device_get(arr))
    return simplehash(host)


# --- TPU-native hash (hash type 2, pcclt::hash::kSimpleTpu) ---------------
# The digest an accelerator can compute over HBM-RESIDENT bytes with pure
# u32 arithmetic: a clean shared-state sync then ships 8 bytes over the
# wire instead of staging the array to host (on the axon dev tunnel D2H
# runs at ~0.03 GB/s, so hashing 1 GB of resident state via staging costs
# ~30 s even when nothing changed). The reference hashes CUDA buffers
# on-GPU for the same reason (/root/reference/ccoip/src/cuda/
# simplehash_cuda.cu, dispatched at ccoip_client_handler.cpp:383-416).
#
# Definition (bit-identical across this numpy twin, the C++ twin
# hash.cpp:simplehash_tpu, and the jitted device digest below): LE u32
# words, word i -> (row i // 65536, lane i % 65536), the last row
# zero-padded to the full lane grid; two parallel u32 Horner planes per
# lane (A/B with distinct primes/seeds); 16 levels of pairwise murmur3-
# step lane folding (non-linear — see _mix2); the two u32 plane digests
# concatenate to 64 bits, XOR the Q-scaled byte length, avalanche.

TPU_LANES = 65536
_TPA, _TSA = np.uint32(0x01000193), np.uint32(0x811C9DC5)
_TPB, _TSB = np.uint32(0x85EBCA6B), np.uint32(0x9E3779B9)


def _u32_powers(p: np.uint32, n: int) -> np.ndarray:
    """[p^n-1 ... p^1 p^0] mod 2^32 (the row weights for n rows)."""
    with np.errstate(over="ignore"):
        out = np.empty(n, dtype=np.uint32)
        acc = np.uint32(1)
        for i in range(n - 1, -1, -1):
            out[i] = acc
            acc = acc * p
    return out


_MC1, _MC2 = np.uint32(0xCC9E2D51), np.uint32(0x1B873593)
_MC5, _MC6 = np.uint32(5), np.uint32(0xE6546B64)


def _rotl32(x, r: int):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _mix2(h, k):
    """murmur3 stream step as a 2→1 lane combiner (h absorbs k). The
    combine must be NON-LINEAR with rotations: a linear fold (a*C + b or
    (a*C) ^ b) of IDENTICAL halves — exactly what uniform content such as
    zero-init params produces — cancels structurally (x*(C+1) accumulates
    even factors; (x*C)^x clears the lowest set bit per level), and 16
    levels of that made every constant array hash identically. Rotate +
    distinct multipliers break the alignment."""
    k = _rotl32(k * _MC1, 15) * _MC2
    return _rotl32(h ^ k, 13) * _MC5 + _MC6


def _tpu_fold(lane_a, lane_b):
    """Pairwise lane fold, generic over numpy/jnp arrays: 16 levels of
    _mix2 halving the lane vector (identical graph on device and host)."""
    half = TPU_LANES // 2
    while half >= 1:
        lane_a = _mix2(lane_a[:half], lane_a[half:2 * half])
        lane_b = _mix2(lane_b[:half], lane_b[half:2 * half])
        half //= 2
    return lane_a[0], lane_b[0]


def _tpu_finalize(acc_a, acc_b, nbytes: int) -> int:
    """64-bit tail (host arithmetic): concat planes, mix length, avalanche."""
    with np.errstate(over="ignore"):
        d = (np.uint64(acc_a) << np.uint64(32)) | np.uint64(acc_b)
        return int(_avalanche64(d ^ (np.uint64(nbytes) * Q)))


def _tpu_fold_mix(lane_a: np.ndarray, lane_b: np.ndarray,
                  nbytes: int) -> int:
    with np.errstate(over="ignore"):
        a, b = _tpu_fold(lane_a, lane_b)
    return _tpu_finalize(a, b, nbytes)


def simplehash_tpu(buf) -> int:
    """numpy twin of the TPU-native hash. Bit-identical to the C++
    pcclt::hash::simplehash_tpu and to jax_simplehash_device."""
    if isinstance(buf, np.ndarray):
        data = np.ascontiguousarray(buf).view(np.uint8).reshape(-1)
    else:
        data = np.frombuffer(memoryview(buf), dtype=np.uint8)
    nbytes = data.size
    n_words = (nbytes + 3) // 4
    rows = (n_words + TPU_LANES - 1) // TPU_LANES
    padded = np.zeros(max(rows, 1) * TPU_LANES * 4, dtype=np.uint8)
    padded[:nbytes] = data
    words = padded.view("<u4").reshape(-1, TPU_LANES)[:rows]

    with np.errstate(over="ignore"):
        wa = _u32_powers(_TPA, rows)[:, None]
        wb = _u32_powers(_TPB, rows)[:, None]
        pa_rows = (wa[0, 0] * _TPA) if rows else np.uint32(1)  # _TPA^rows
        pb_rows = (wb[0, 0] * _TPB) if rows else np.uint32(1)
        lane_a = (words * wa).sum(axis=0, dtype=np.uint32) + _TSA * pa_rows
        lane_b = (words * wb).sum(axis=0, dtype=np.uint32) + _TSB * pb_rows
    return _tpu_fold_mix(lane_a, lane_b, nbytes)


def _words_u32(x):
    """Canonical LE u32 word stream of a flattened jax array (device op).
    Supports 1/2/4-byte dtypes; 8-byte dtypes raise (callers fall back to
    the staging hash — TPUs run with 32-bit ints by default anyway)."""
    import jax.numpy as jnp
    from jax import lax

    x = x.reshape(-1)
    size = x.dtype.itemsize
    if size == 4:
        return lax.bitcast_convert_type(x, jnp.uint32)
    if size == 2:
        h = lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
        if h.shape[0] % 2:
            h = jnp.concatenate([h, jnp.zeros(1, jnp.uint32)])
        h = h.reshape(-1, 2)
        return h[:, 0] | (h[:, 1] << 16)
    if size == 1:
        b = lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.uint32)
        pad = (-b.shape[0]) % 4
        if pad:
            b = jnp.concatenate([b, jnp.zeros(pad, jnp.uint32)])
        b = b.reshape(-1, 4)
        return b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16) | (b[:, 3] << 24)
    raise ValueError(f"no device word stream for itemsize {size}")


import functools


@functools.lru_cache(maxsize=512)
def _device_planes_fn(shape, dtype_name):
    """Jitted (lane_a, lane_b) digest planes for one (shape, dtype) —
    cached so repeated syncs of the same state pay dispatch, not retrace
    (a fresh inner @jax.jit per call costs ~1.2 s through the dev
    tunnel; the cached fn costs the dispatch + 8-byte readback)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def planes(x):
        w = _words_u32(x)
        n = w.shape[0]
        rows = max(1, -(-n // TPU_LANES))
        pad = rows * TPU_LANES - n
        if pad:
            w = jnp.concatenate([w, jnp.zeros(pad, jnp.uint32)])
        w = w.reshape(rows, TPU_LANES)
        wa = jnp.asarray(_u32_powers(_TPA, rows)[:, None])
        wb = jnp.asarray(_u32_powers(_TPB, rows)[:, None])
        with np.errstate(over="ignore"):
            pa_rows = np.uint32(_u32_powers(_TPA, rows)[0] * _TPA)
            pb_rows = np.uint32(_u32_powers(_TPB, rows)[0] * _TPB)
        lane_a = (w * wa).sum(axis=0, dtype=jnp.uint32) + _TSA * pa_rows
        lane_b = (w * wb).sum(axis=0, dtype=jnp.uint32) + _TSB * pb_rows
        return _tpu_fold(lane_a, lane_b)   # fold ON DEVICE: 8 bytes out

    return planes


def jax_simplehash_device(arr) -> int:
    """TPU-native digest of a jax.Array computed ON DEVICE: only the two
    u32 plane accumulators (8 bytes) cross to the host. Bit-identical to
    simplehash_tpu of the same logical bytes; the row-weight constants
    are baked at trace time (shapes are static)."""
    nbytes = arr.size * arr.dtype.itemsize
    if arr.size == 0:
        # rows=0 case: the device graph below pads to one zero row, which
        # would advance every Horner chain once and diverge from the
        # twins' rows=0 digest — hash the empty byte stream on host
        return simplehash_tpu(np.empty(0, np.uint8))
    acc_a, acc_b = _device_planes_fn(tuple(arr.shape), str(arr.dtype))(arr)
    return _tpu_finalize(np.uint32(acc_a), np.uint32(acc_b), nbytes)
