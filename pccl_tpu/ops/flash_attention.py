"""Flash attention — fused causal attention pallas kernel for one TPU core.

The single-chip hot op under the flagship model (the reference has no model
compute at all — its examples lean on torch SDPA; here the TPU-native
equivalent is a pallas kernel feeding the MXU).

Layout: grid over (batch·heads, q blocks); for each q block the kernel
streams K/V blocks from VMEM with online softmax in fp32 scratch, skipping
k blocks strictly above the causal diagonal (trip count depends only on the
q-block index, so the loop stays statically boundable). Logits never
materialize beyond a [block_q, block_k] tile.

On non-TPU backends `flash_attention` falls back to the jnp reference
implementation (CI runs on a virtual CPU mesh); `interpret=True` forces the
pallas interpreter for kernel-logic tests anywhere.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def reference_attention(q, k, v, causal: bool = True):
    """Dense jnp causal attention; q,k,v: [B, T, H, Dh]."""
    Dh = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / math.sqrt(Dh)
    if causal:
        T = q.shape[1]
        qi = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        ki = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        logits = jnp.where(ki <= qi, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                  seq_len: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale        # [block_q, Dh]

    nk = seq_len // block_k
    if causal:
        # last k block any row of this q block may attend to (ceil division)
        nk = jnp.minimum(nk, ((qi + 1) * block_q + block_k - 1) // block_k)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = q @ k.T                                  # [block_q, block_k]
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + p @ v
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_bhtd(qt, kt, vt, *, block_q: int, block_k: int, causal: bool,
                interpret: bool):
    """qt,kt,vt: [BH, T, Dh] → [BH, T, Dh]."""
    BH, T, Dh = qt.shape
    scale = 1.0 / math.sqrt(Dh)
    kernel = functools.partial(_flash_kernel, block_q=block_q, block_k=block_k,
                               seq_len=T, causal=causal, scale=scale)
    grid = (BH, T // block_q)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BH, T, Dh), qt.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, Dh), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, T, Dh), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, T, Dh), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, Dh), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qt, kt, vt)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, causal, block_q, block_k, interpret):
    B, T, H, Dh = q.shape

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, Dh)

    out = _flash_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v), block_q=block_q,
                      block_k=block_k, causal=causal, interpret=interpret)
    return out.reshape(B, H, T, Dh).transpose(0, 2, 1, 3)


def _flash_diff_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_diff(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_diff_bwd(causal, block_q, block_k, interpret, res, g):
    # Backward recomputes through the dense reference path (O(T²) logits in
    # the backward only); a fused flash backward kernel can swap in here
    # without changing the public API.
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: reference_attention(q_, k_, v_, causal=causal),
        q, k, v)
    return vjp(g)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Fused causal attention. q,k,v: [B, T, H, Dh] → [B, T, H, Dh].

    Uses the pallas kernel on TPU (or under `interpret`); falls back to the
    dense jnp path elsewhere or when T doesn't tile. Differentiable: the
    forward runs the fused kernel, the backward recomputes via the dense
    reference attention (custom_vjp), so it drops into build_train_step."""
    B, T, H, Dh = q.shape
    on_tpu = jax.default_backend() == "tpu"
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if not (on_tpu or interpret) or T % block_q or T % block_k:
        return reference_attention(q, k, v, causal=causal)
    return _flash_diff(q, k, v, causal, block_q, block_k, interpret)
