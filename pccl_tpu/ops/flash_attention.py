"""Flash attention — fused causal attention pallas kernel for one TPU core.

The single-chip hot op under the flagship model (the reference has no model
compute at all — its examples lean on torch SDPA; here the TPU-native
equivalent is a pallas kernel feeding the MXU).

Layout: grid over (batch·heads, q blocks); for each q block the kernel
streams K/V blocks from VMEM with online softmax in fp32 scratch, skipping
k blocks strictly above the causal diagonal (trip count depends only on the
q-block index, so the loop stays statically boundable). Logits never
materialize beyond a [block_q, block_k] tile — in EITHER direction: the
backward is a fused FlashAttention-2-style pair of kernels (dq, then
dk/dv) that rebuild p = exp(s − lse) from the forward's saved log-sum-exp,
so long-context training never touches a [T, T] tensor. All gemms run with
bf16 operands and fp32 accumulation on the MXU.

On non-TPU backends `flash_attention` falls back to the jnp reference
implementation (CI runs on a virtual CPU mesh); `interpret=True` forces the
pallas interpreter for kernel-logic tests anywhere.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def reference_attention(q, k, v, causal: bool = True):
    """Dense jnp causal attention; q,k,v: [B, T, H, Dh]. One source of
    truth with the ring fallback: softmax == exp(logits − lse)."""
    return dense_attention_with_lse(q, k, v, causal)[0]


def _causal_mask(s, row0, col0, bq: int, bk: int):
    """Mask entries of the [bq, bk] score tile whose absolute column index
    exceeds its row index. Shared by the forward and BOTH backward kernels
    so masking semantics can never desynchronize between directions."""
    rows = row0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = col0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(cols <= rows, s, -1e30)


def _causal_nk(qi, nk, block_q: int, block_k: int):
    """Last k block (exclusive) any row of q block `qi` may attend to."""
    return jnp.minimum(nk, ((qi + 1) * block_q + block_k - 1) // block_k)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
                  block_k: int, seq_len: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    # the matmuls stay in the input dtype (bf16) with fp32 ACCUMULATION —
    # fp32 operands would run the MXU at a fraction of its rate, and at
    # long T the QK^T/PV gemms are the whole kernel
    q = q_ref[0]                                     # [block_q, Dh]

    nk = seq_len // block_k
    if causal:
        nk = _causal_nk(qi, nk, block_q, block_k)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k, block_q, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])              # f32 [block_q, block_k]
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_new = acc * corr[:, None] + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    # log-sum-exp per row: everything the backward needs to rebuild p
    # from scratch (p = exp(s - lse)) without storing any [T, T] tensor.
    # lse rides as [BH, 1, T] (full-T row block, revisited across the q
    # grid dim) — TPU lowering wants the last two block dims (8, 128)-
    # divisible or equal to the array's, which a [1, block_q] tile isn't.
    lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = m + jnp.log(l)


def _flash_bhtd(qt, kt, vt, *, block_q: int, block_k: int, causal: bool,
                interpret: bool):
    """qt,kt,vt: [BH, T, Dh] → ([BH, T, Dh] out, [BH, T] f32 lse)."""
    BH, T, Dh = qt.shape
    scale = 1.0 / math.sqrt(Dh)
    kernel = functools.partial(_flash_kernel, block_q=block_q, block_k=block_k,
                               seq_len=T, causal=causal, scale=scale)
    grid = (BH, T // block_q)
    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((BH, T, Dh), qt.dtype),
                   jax.ShapeDtypeStruct((BH, 1, T), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, Dh), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, T, Dh), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, T, Dh), lambda i, j: (i, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((1, block_q, Dh), lambda i, j: (i, j, 0)),
                   pl.BlockSpec((1, 1, T), lambda i, j: (i, 0, 0))),
        interpret=interpret,
    )(qt, kt, vt)


# --- fused backward (FlashAttention-2 shape): two kernels, no [T, T]
# tensor ever materialized. dq: grid over q blocks, inner loop over the
# causal k range. dk/dv: grid over k blocks, inner loop over the q range
# at or below the diagonal. Both rebuild p = exp(s − lse) from the saved
# log-sum-exp and use delta = rowsum(do · o) for the softmax jacobian:
#   ds = p ⊙ (do·vᵀ − delta) · scale
# All gemms run in the input dtype on the MXU with fp32 accumulation.

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_q: int, block_k: int, seq_len: int,
                         causal: bool, scale: float):
    qi = pl.program_id(1)
    q = q_ref[0]                                     # [bq, Dh]
    do = do_ref[0]
    lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]   # [bq] f32
    delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]

    nk = seq_len // block_k
    if causal:
        nk = _causal_nk(qi, nk, block_q, block_k)

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(k.dtype)
        return dq + lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    dq_ref[0] = lax.fori_loop(0, nk, body, dq0).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, block_k: int,
                          seq_len: int, causal: bool, scale: float):
    ki = pl.program_id(1)
    k = k_ref[0]                                     # [bk, Dh]
    v = v_ref[0]

    nq = seq_len // block_q
    j0 = (ki * block_k) // block_q if causal else 0

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(j * block_q, block_q), :]
        do = do_ref[0, pl.ds(j * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(j * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(j * block_q, block_q)]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, j * block_q, ki * block_k, block_q, block_k)
        p = jnp.exp(s - lse[:, None])                # [bq, bk] f32
        pt = p.astype(do.dtype)
        dv = dv + lax.dot_general(pt, do, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        dk = dk + lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((block_k, k_ref.shape[-1]), jnp.float32)
    dk, dv = lax.fori_loop(j0, nq, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_bhtd(qt, kt, vt, ot, do, lse, *, block_q: int, block_k: int,
                    causal: bool, interpret: bool, delta_override=None):
    """Fused backward over [BH, T, Dh] tensors → (dq, dk, dv).

    delta_override: callers differentiating an (out, lse) PAIR pass
    delta − dlse here (flash_attention_with_lse's backward)."""
    BH, T, Dh = qt.shape
    scale = 1.0 / math.sqrt(Dh)
    if delta_override is None:
        delta = jnp.sum(do.astype(jnp.float32) * ot.astype(jnp.float32),
                        axis=-1)[:, None, :]         # [BH, 1, T]
    else:
        delta = delta_override
    common = dict(block_q=block_q, block_k=block_k, seq_len=T, causal=causal,
                  scale=scale)
    row = lambda i, j: (i, j, 0)  # noqa: E731
    full = lambda i, j: (i, 0, 0)  # noqa: E731
    vec_blk = pl.BlockSpec((1, 1, T), lambda i, j: (i, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((BH, T, Dh), qt.dtype),
        grid=(BH, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, Dh), row),       # q
            pl.BlockSpec((1, T, Dh), full),            # k
            pl.BlockSpec((1, T, Dh), full),            # v
            pl.BlockSpec((1, block_q, Dh), row),       # do
            vec_blk,                                   # lse
            vec_blk,                                   # delta
        ],
        out_specs=pl.BlockSpec((1, block_q, Dh), row),
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        out_shape=(jax.ShapeDtypeStruct((BH, T, Dh), kt.dtype),
                   jax.ShapeDtypeStruct((BH, T, Dh), vt.dtype)),
        grid=(BH, T // block_k),
        in_specs=[
            pl.BlockSpec((1, T, Dh), full),            # q
            pl.BlockSpec((1, block_k, Dh), row),       # k
            pl.BlockSpec((1, block_k, Dh), row),       # v
            pl.BlockSpec((1, T, Dh), full),            # do
            vec_blk,                                   # lse
            vec_blk,                                   # delta
        ],
        out_specs=(pl.BlockSpec((1, block_k, Dh), row),
                   pl.BlockSpec((1, block_k, Dh), row)),
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta)
    return dq, dk, dv


def _to_bhtd(x):
    B, T, H, Dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, Dh)


def _from_bhtd(x, B, H):
    BH, T, Dh = x.shape
    return x.reshape(B, H, T, Dh).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, causal, block_q, block_k, interpret):
    B, _, H, _ = q.shape
    out, _ = _flash_bhtd(_to_bhtd(q), _to_bhtd(k), _to_bhtd(v),
                         block_q=block_q, block_k=block_k, causal=causal,
                         interpret=interpret)
    return _from_bhtd(out, B, H)


def _flash_diff_fwd(q, k, v, causal, block_q, block_k, interpret):
    B, _, H, _ = q.shape
    qt, kt, vt = _to_bhtd(q), _to_bhtd(k), _to_bhtd(v)
    out, lse = _flash_bhtd(qt, kt, vt, block_q=block_q, block_k=block_k,
                           causal=causal, interpret=interpret)
    return _from_bhtd(out, B, H), (qt, kt, vt, out, lse, B, H)


def _flash_diff_bwd(causal, block_q, block_k, interpret, res, g):
    # Fused flash backward: rebuilds p from the saved lse per tile — the
    # O(T²) score matrix never exists in HBM in either direction, which is
    # what makes long-context training fit (a dense backward at T=8192
    # wants a 4 GB probs tensor PER LAYER).
    qt, kt, vt, ot, lse, B, H = res
    dq, dk, dv = _flash_bwd_bhtd(qt, kt, vt, ot, _to_bhtd(g), lse,
                                 block_q=block_q, block_k=block_k,
                                 causal=causal, interpret=interpret)
    return (_from_bhtd(dq, B, H), _from_bhtd(dk, B, H), _from_bhtd(dv, B, H))


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def snap_block(b: int, T: int) -> int:
    """Snap a block size DOWN to a divisor of T so mid-size T (1280,
    2560, ...) stays on the kernel instead of silently falling back to the
    dense O(T^2) path; below 128 the tile no longer fills the MXU, so the
    caller's divisibility check then routes to the fallback. Shared by
    flash_attention and the ring-attention per-shard path."""
    b = min(b, T)
    while b >= 128 and T % b:
        b //= 2
    return b


def dense_attention_with_lse(q, k, v, causal: bool = True):
    """jnp twin of flash_attention_with_lse for non-TPU backends: returns
    (out [B,T,H,Dh], lse [B,H,T] f32). Plain jnp, so autodiff covers it."""
    Dh = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / math.sqrt(Dh)
    if causal:
        T = q.shape[1]
        qi = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        ki = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        logits = jnp.where(ki <= qi, logits, -1e30)
    lse = jax.nn.logsumexp(logits, axis=-1)          # [B, H, T]
    p = jnp.exp(logits - lse[..., None]).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, causal, block_q, block_k, interpret):
    """Fused attention returning (out, lse [B, H, T] f32) — the form block-
    combiners need (ring attention folds per-shard results by lse). Both
    outputs are differentiable: the backward folds the incoming dlse into
    delta (d lse/d s = p, so ds = p ⊙ (dp − (delta − dlse))) and reuses the
    same fused kernels."""
    B, _, H, _ = q.shape
    out, lse = _flash_bhtd(_to_bhtd(q), _to_bhtd(k), _to_bhtd(v),
                           block_q=block_q, block_k=block_k, causal=causal,
                           interpret=interpret)
    T = lse.shape[-1]
    return _from_bhtd(out, B, H), lse.reshape(B, H, T)


def _flash_lse_fwd(q, k, v, causal, block_q, block_k, interpret):
    B, _, H, _ = q.shape
    qt, kt, vt = _to_bhtd(q), _to_bhtd(k), _to_bhtd(v)
    out, lse = _flash_bhtd(qt, kt, vt, block_q=block_q, block_k=block_k,
                           causal=causal, interpret=interpret)
    T = lse.shape[-1]
    return ((_from_bhtd(out, B, H), lse.reshape(B, H, T)),
            (qt, kt, vt, out, lse, B, H))


def _flash_lse_bwd(causal, block_q, block_k, interpret, res, g):
    do, dlse = g
    qt, kt, vt, ot, lse, B, H = res
    dot = _to_bhtd(do)
    # delta_eff = rowsum(do·o) − dlse: the lse cotangent enters every ds
    # tile through the same row-broadcast slot delta occupies, so the
    # kernels need no change — see _flash_bwd_bhtd's delta_override
    delta = (jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                     axis=-1)
             - dlse.reshape(ot.shape[0], ot.shape[1]))[:, None, :]
    dq, dk, dv = _flash_bwd_bhtd(qt, kt, vt, ot, dot, lse,
                                 block_q=block_q, block_k=block_k,
                                 causal=causal, interpret=interpret,
                                 delta_override=delta)
    return (_from_bhtd(dq, B, H), _from_bhtd(dk, B, H), _from_bhtd(dv, B, H))


flash_attention_with_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 256,
                    block_k: int = 512, interpret: bool = False):
    """Fused causal attention. q,k,v: [B, T, H, Dh] → [B, T, H, Dh].

    Uses the pallas kernels on TPU (or under `interpret`); falls back to
    the dense jnp path elsewhere or when T doesn't tile. Differentiable:
    forward AND backward are fused kernels (custom_vjp over the saved
    log-sum-exp), so it drops into build_train_step and stays O(T) in
    memory for long-context training."""
    B, T, H, Dh = q.shape
    on_tpu = jax.default_backend() == "tpu"
    block_q, block_k = snap_block(block_q, T), snap_block(block_k, T)
    if not (on_tpu or interpret) or T % block_q or T % block_k:
        return reference_attention(q, k, v, causal=causal)
    return _flash_diff(q, k, v, causal, block_q, block_k, interpret)
