"""Flash attention — fused causal attention pallas kernels for one TPU core.

The single-chip hot op under the flagship model (the reference has no model
compute at all — its examples lean on torch SDPA; here the TPU-native
equivalent is a pallas kernel feeding the MXU).

Layout (k-blocked, round 5): the grid streams K/V through VMEM in
`block_k` tiles — K/V are grid dimensions, not full-T VMEM residents, so
VMEM per step is O(block) and the kernels reach T=16384/32768 where the
round-4 full-T layout tripped the ~16 MB scoped-VMEM limit. The forward
grid is (B·H, q blocks, k blocks) with the online-softmax state (running
max m, normalizer l, output accumulator) carried across the innermost k
dimension in fp32 VMEM scratch; TPU pallas executes the grid sequentially,
so the carry is exact. Causal skipping is zero-FLOP: k blocks strictly
above the diagonal run no gemms (`pl.when`), and their BlockSpec index is
clamped to the last visible block so the pipeline re-uses the resident
tile instead of fetching dead bytes.

Logits never materialize beyond a [block_q, block_k] tile in EITHER
direction: the backward is a fused FlashAttention-2-style pair of kernels
(dq, then dk/dv) that rebuild p = exp(s − lse) from the forward's saved
log-sum-exp, so long-context training never touches a [T, T] tensor. All
gemms run with bf16 operands and fp32 accumulation on the MXU.

GQA is native (round 5): K/V may carry fewer heads than Q
(n_kv_head = H / G). The kernels never repeat K/V — the q-head grid index
maps onto its kv head inside the BlockSpec index maps (kv row = i // G for
the forward/dq grids), and the dk/dv kernel accumulates the G q-heads
sharing a kv head in scratch over an extra grid dimension. HBM holds and
moves only Hkv-shaped K/V, which is the entire point of the architecture
(the reference never faces this: its CUDA examples use torch SDPA,
/root/reference/python/examples; grouped-query K/V shrinkage is a
TPU-side design goal, not a port).

On non-TPU backends `flash_attention` falls back to the jnp reference
implementation (CI runs on a virtual CPU mesh); `interpret=True` forces the
pallas interpreter for kernel-logic tests anywhere.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Lane width of the VPU: the online-softmax running stats (m, l) live in
# VMEM scratch replicated across this many lanes so every update is a
# full-width vector op instead of a sub-tile.
_LANES = 128


def reference_attention(q, k, v, causal: bool = True):
    """Dense jnp causal attention; q: [B, T, H, Dh], k/v: [B, T, Hkv, Dh]
    (Hkv may divide H — GQA). One source of truth with the ring fallback:
    softmax == exp(logits − lse)."""
    return dense_attention_with_lse(q, k, v, causal)[0]


def _causal_mask(s, row0, col0, bq: int, bk: int):
    """Mask entries of the [bq, bk] score tile whose absolute column index
    exceeds its row index. Shared by the forward and BOTH backward kernels
    so masking semantics can never desynchronize between directions."""
    rows = row0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = col0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(cols <= rows, s, -1e30)


def _causal_nk(qi, nk, block_q: int, block_k: int):
    """Last k block (exclusive) any row of q block `qi` may attend to."""
    return jnp.minimum(nk, ((qi + 1) * block_q + block_k - 1) // block_k)


def _causal_j0(ki, block_q: int, block_k: int):
    """First q block (inclusive) that can see any column of k block `ki`."""
    return (ki * block_k) // block_q


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc,
                      *, block_q: int, block_k: int, causal: bool,
                      scale: float):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    nk_eff = _causal_nk(qi, nk, block_q, block_k) if causal else nk

    @pl.when(ki == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, -1e30)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    @pl.when(ki < nk_eff)
    def _step():
        # the matmuls stay in the input dtype (bf16) with fp32
        # ACCUMULATION — fp32 operands would run the MXU at a fraction of
        # its rate, and at long T the QK^T/PV gemms are the whole kernel
        q = q_ref[0]                                 # [bq, Dh]
        k = k_ref[0]                                 # [bk, Dh]
        v = v_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, ki * block_k, block_q, block_k)
        m_prev = m_sc[...]                           # [bq, LANES] f32
        l_prev = l_sc[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])                # f32 [bq, bk]
        m_sc[...] = m_new
        l_sc[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_sc[...] = acc_sc[...] * corr[:, :1] + pv

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_sc[:, :1]
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)
        # log-sum-exp per row: everything the backward needs to rebuild p
        # from scratch (p = exp(s - lse)) without storing any [T, T]
        # tensor. lse rides as [BH, 1, T] (full-T row block — tiny: T·4
        # bytes) because TPU lowering wants the last two block dims
        # (8, 128)-divisible or equal to the array's.
        lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = \
            m_sc[:, 0] + jnp.log(l_sc[:, 0])


def _kv_index(i, G: int):
    """Row of the [B·Hkv, T, Dh] K/V array feeding q-head row `i` of
    [B·H, ...]: with q head h sharing kv head h // G and i = b·H + h,
    (b·H + h) // G = b·Hkv + h // G exactly (H = G·Hkv)."""
    return i // G if G > 1 else i


def _make_kv_map(nk: int, G: int, block_q: int, block_k: int, causal: bool):
    """BlockSpec index map for K/V on the (BH, q blocks, k blocks) grids
    (forward and dq backward — ONE definition so their fetch behavior can
    never desynchronize). Causal k indices above the diagonal clamp to the
    last visible block: the pipeline sees an unchanged index and skips the
    fetch, so dead tiles cost no HBM bandwidth."""
    def kv_map(i, qi, ki):
        kj = jnp.minimum(ki, _causal_nk(qi, nk, block_q, block_k) - 1) \
            if causal else ki
        return (_kv_index(i, G), kj, 0)
    return kv_map


def _flash_bhtd(qt, kt, vt, *, block_q: int, block_k: int, causal: bool,
                interpret: bool):
    """qt: [BH, T, Dh]; kt/vt: [BKV, T, Dh], BKV dividing BH (GQA) →
    ([BH, T, Dh] out, [BH, T] f32 lse). K/V stream through VMEM in
    block_k tiles (grid dim 2); softmax state carries in VMEM scratch."""
    BH, T, Dh = qt.shape
    G = BH // kt.shape[0]
    scale = 1.0 / math.sqrt(Dh)
    nk = T // block_k
    kernel = functools.partial(_flash_fwd_kernel, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale)
    kv_map = _make_kv_map(nk, G, block_q, block_k, causal)
    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((BH, T, Dh), qt.dtype),
                   jax.ShapeDtypeStruct((BH, 1, T), jnp.float32)),
        grid=(BH, T // block_q, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, Dh), lambda i, j, s: (i, j, 0)),
            pl.BlockSpec((1, block_k, Dh), kv_map),
            pl.BlockSpec((1, block_k, Dh), kv_map),
        ],
        out_specs=(pl.BlockSpec((1, block_q, Dh), lambda i, j, s: (i, j, 0)),
                   pl.BlockSpec((1, 1, T), lambda i, j, s: (i, 0, 0))),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # normalizer l
            pltpu.VMEM((block_q, Dh), jnp.float32),       # output accum
        ],
        interpret=interpret,
    )(qt, kt, vt)


# --- fused backward (FlashAttention-2 shape): two kernels, no [T, T]
# tensor ever materialized. dq: grid (BH, q blocks, k blocks), dq carried
# in scratch across the k dim. dk/dv: grid (BKV, k blocks, G, q blocks),
# dk/dv carried in scratch across the (g, q) dims — the G q-heads sharing
# a kv head accumulate into ONE Hkv-shaped gradient without any repeated
# K/V or G×-sized temporaries. Both rebuild p = exp(s − lse) from the
# saved log-sum-exp and use delta = rowsum(do · o) for the softmax
# jacobian:   ds = p ⊙ (do·vᵀ − delta) · scale
# All gemms run in the input dtype on the MXU with fp32 accumulation.

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_sc, *, block_q: int, block_k: int,
                         causal: bool, scale: float):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    nk_eff = _causal_nk(qi, nk, block_q, block_k) if causal else nk

    @pl.when(ki == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    @pl.when(ki < nk_eff)
    def _step():
        q = q_ref[0]                                 # [bq, Dh]
        do = do_ref[0]
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]    # [bq] f32
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]
        k = k_ref[0]                                 # [bk, Dh]
        v = v_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, ki * block_k, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(k.dtype)
        dq_sc[...] = dq_sc[...] + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_sc[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_sc, dv_sc, *, block_q: int,
                          block_k: int, causal: bool, scale: float):
    ki, g, qi = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)
    j0 = _causal_j0(ki, block_q, block_k) if causal else 0

    @pl.when((g == 0) & (qi == 0))
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    @pl.when(qi >= j0)
    def _step():
        k = k_ref[0]                                 # [bk, Dh]
        v = v_ref[0]
        q = q_ref[0]                                 # [bq, Dh]
        do = do_ref[0]
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, ki * block_k, block_q, block_k)
        p = jnp.exp(s - lse[:, None])                # [bq, bk] f32
        pt = p.astype(do.dtype)
        dv_sc[...] = dv_sc[...] + lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        dk_sc[...] = dk_sc[...] + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when((g == pl.num_programs(2) - 1) & (qi == nq - 1))
    def _finalize():
        dk_ref[0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _flash_bwd_bhtd(qt, kt, vt, ot, do, lse, *, block_q: int, block_k: int,
                    causal: bool, interpret: bool, n_kv_head: int = 0,
                    delta_override=None):
    """Fused backward; qt/ot/do: [BH, T, Dh], kt/vt: [BKV, T, Dh] →
    (dq [BH..], dk [BKV..], dv [BKV..]).

    n_kv_head: Hkv (needed to invert i_kv → q-head rows in the dkv grid;
    0 means MHA, BKV == BH). delta_override: callers differentiating an
    (out, lse) PAIR pass delta − dlse here (flash_attention_with_lse's
    backward)."""
    BH, T, Dh = qt.shape
    BKV = kt.shape[0]
    G = BH // BKV
    Hkv = n_kv_head if n_kv_head else BKV            # MHA: any split works
    H = Hkv * G
    scale = 1.0 / math.sqrt(Dh)
    nq, nk = T // block_q, T // block_k
    if delta_override is None:
        delta = jnp.sum(do.astype(jnp.float32) * ot.astype(jnp.float32),
                        axis=-1)[:, None, :]         # [BH, 1, T]
    else:
        delta = delta_override
    common = dict(block_q=block_q, block_k=block_k, causal=causal,
                  scale=scale)
    row3 = lambda i, j, s: (i, j, 0)  # noqa: E731
    vec3 = pl.BlockSpec((1, 1, T), lambda i, j, s: (i, 0, 0))
    kv_map3 = _make_kv_map(nk, G, block_q, block_k, causal)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((BH, T, Dh), qt.dtype),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, Dh), row3),      # q
            pl.BlockSpec((1, block_k, Dh), kv_map3),   # k
            pl.BlockSpec((1, block_k, Dh), kv_map3),   # v
            pl.BlockSpec((1, block_q, Dh), row3),      # do
            vec3,                                      # lse
            vec3,                                      # delta
        ],
        out_specs=pl.BlockSpec((1, block_q, Dh), row3),
        scratch_shapes=[pltpu.VMEM((block_q, Dh), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta)

    # dk/dv grid: (BKV, k blocks, G, q blocks) — q innermost so the
    # scratch carry sweeps all (g, q) pairs of one kv-head k block before
    # the output tile flushes. Under causality q blocks strictly above
    # the diagonal are zero-FLOP and their fetch index clamps to j0.
    def q_row(i_kv, ki, g, qi):
        qj = jnp.maximum(qi, _causal_j0(ki, block_q, block_k)) \
            if causal else qi
        return ((i_kv // Hkv) * H + (i_kv % Hkv) * G + g, qj, 0)

    def q_vec(i_kv, ki, g, qi):
        return ((i_kv // Hkv) * H + (i_kv % Hkv) * G + g, 0, 0)

    kv_row = lambda i_kv, ki, g, qi: (i_kv, ki, 0)  # noqa: E731
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        out_shape=(jax.ShapeDtypeStruct((BKV, T, Dh), kt.dtype),
                   jax.ShapeDtypeStruct((BKV, T, Dh), vt.dtype)),
        grid=(BKV, nk, G, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, Dh), q_row),     # q
            pl.BlockSpec((1, block_k, Dh), kv_row),    # k
            pl.BlockSpec((1, block_k, Dh), kv_row),    # v
            pl.BlockSpec((1, block_q, Dh), q_row),     # do
            pl.BlockSpec((1, 1, T), q_vec),            # lse
            pl.BlockSpec((1, 1, T), q_vec),            # delta
        ],
        out_specs=(pl.BlockSpec((1, block_k, Dh), kv_row),
                   pl.BlockSpec((1, block_k, Dh), kv_row)),
        scratch_shapes=[pltpu.VMEM((block_k, Dh), jnp.float32),
                        pltpu.VMEM((block_k, Dh), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta)
    return dq, dk, dv


def _to_bhtd(x):
    B, T, H, Dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, Dh)


def _from_bhtd(x, B, H):
    BH, T, Dh = x.shape
    return x.reshape(B, H, T, Dh).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, causal, block_q, block_k, interpret):
    B, _, H, _ = q.shape
    out, _ = _flash_bhtd(_to_bhtd(q), _to_bhtd(k), _to_bhtd(v),
                         block_q=block_q, block_k=block_k, causal=causal,
                         interpret=interpret)
    return _from_bhtd(out, B, H)


def _flash_diff_fwd(q, k, v, causal, block_q, block_k, interpret):
    B, _, H, _ = q.shape
    Hkv = k.shape[2]
    qt, kt, vt = _to_bhtd(q), _to_bhtd(k), _to_bhtd(v)
    out, lse = _flash_bhtd(qt, kt, vt, block_q=block_q, block_k=block_k,
                           causal=causal, interpret=interpret)
    return _from_bhtd(out, B, H), (qt, kt, vt, out, lse, B, H, Hkv)


def _flash_diff_bwd(causal, block_q, block_k, interpret, res, g):
    # Fused flash backward: rebuilds p from the saved lse per tile — the
    # O(T²) score matrix never exists in HBM in either direction, which is
    # what makes long-context training fit (a dense backward at T=8192
    # wants a 4 GB probs tensor PER LAYER).
    qt, kt, vt, ot, lse, B, H, Hkv = res
    dq, dk, dv = _flash_bwd_bhtd(qt, kt, vt, ot, _to_bhtd(g), lse,
                                 block_q=block_q, block_k=block_k,
                                 causal=causal, interpret=interpret,
                                 n_kv_head=Hkv)
    return (_from_bhtd(dq, B, H), _from_bhtd(dk, B, Hkv),
            _from_bhtd(dv, B, Hkv))


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def default_blocks(T: int, Dh: int) -> tuple:
    """Measured-on-chip default tile sizes (v5e, bf16, fwd+bwd sweep at
    T=8192..32768). With K/V streamed per q block, refetch traffic scales
    1/block_q — arithmetic intensity of the refetch is ~block_q flops/byte
    vs the v5e ridge of ~240 — so blocks must be LARGE: (1024, 1024) for
    Dh=64 (17.6 vs 23.0 ms at the round-4 (256, 512)), (2048, 1024) for
    Dh=128 (10.7 vs 18.5 ms). bk=2048 or bq=4096 trip the VMEM ceiling
    (fp32 [bq, bk] score tiles), and so does bq=2048 at Dh=128 once the
    kernel sits under a remat'd scan (T=16384 train: scoped-vmem over by
    420K from the remat stack) — hence bq drops back to 1024 for
    T > 8192 (a tile-size cap only; the k-blocked kernels themselves run
    to T=32768+)."""
    bq = 2048 if (Dh >= 128 and T <= 8192) else 1024
    return snap_block(bq, T), snap_block(1024, T)


def snap_block(b: int, T: int) -> int:
    """Snap a block size DOWN to a divisor of T so mid-size T (1280,
    2560, ...) stays on the kernel instead of silently falling back to the
    dense O(T^2) path. A snapped block can drop below 128 (e.g. T=320 →
    64) and still divide T: that tile underfills the MXU but the kernel
    still runs and still beats the dense path's O(T²) memory — only when
    NO power-of-two ≥ min(b, T)/… divides T does the caller's divisibility
    check route to the fallback. Shared by flash_attention and the
    ring-attention per-shard path."""
    b = min(b, T)
    while b >= 128 and T % b:
        b //= 2
    return b


def dense_attention_with_lse(q, k, v, causal: bool = True):
    """jnp twin of flash_attention_with_lse for non-TPU backends: returns
    (out [B,T,H,Dh], lse [B,H,T] f32). Accepts GQA-shaped K/V ([B,T,Hkv,
    Dh], Hkv dividing H) by repeating — the fallback optimizes for
    correctness, the kernels for bytes. Plain jnp, so autodiff covers it."""
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    Dh = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / math.sqrt(Dh)
    if causal:
        T = q.shape[1]
        qi = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        ki = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        logits = jnp.where(ki <= qi, logits, -1e30)
    lse = jax.nn.logsumexp(logits, axis=-1)          # [B, H, T]
    p = jnp.exp(logits - lse[..., None]).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, causal, block_q, block_k, interpret):
    """Fused attention returning (out, lse [B, H, T] f32) — the form block-
    combiners need (ring attention folds per-shard results by lse). Both
    outputs are differentiable: the backward folds the incoming dlse into
    delta (d lse/d s = p, so ds = p ⊙ (dp − (delta − dlse))) and reuses the
    same fused kernels. K/V may be GQA-shaped ([B, T, Hkv, Dh])."""
    B, _, H, _ = q.shape
    out, lse = _flash_bhtd(_to_bhtd(q), _to_bhtd(k), _to_bhtd(v),
                           block_q=block_q, block_k=block_k, causal=causal,
                           interpret=interpret)
    T = lse.shape[-1]
    return _from_bhtd(out, B, H), lse.reshape(B, H, T)


def _flash_lse_fwd(q, k, v, causal, block_q, block_k, interpret):
    B, _, H, _ = q.shape
    Hkv = k.shape[2]
    qt, kt, vt = _to_bhtd(q), _to_bhtd(k), _to_bhtd(v)
    out, lse = _flash_bhtd(qt, kt, vt, block_q=block_q, block_k=block_k,
                           causal=causal, interpret=interpret)
    T = lse.shape[-1]
    return ((_from_bhtd(out, B, H), lse.reshape(B, H, T)),
            (qt, kt, vt, out, lse, B, H, Hkv))


def _flash_lse_bwd(causal, block_q, block_k, interpret, res, g):
    do, dlse = g
    qt, kt, vt, ot, lse, B, H, Hkv = res
    dot = _to_bhtd(do)
    # delta_eff = rowsum(do·o) − dlse: the lse cotangent enters every ds
    # tile through the same row-broadcast slot delta occupies, so the
    # kernels need no change — see _flash_bwd_bhtd's delta_override
    delta = (jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                     axis=-1)
             - dlse.reshape(ot.shape[0], ot.shape[1]))[:, None, :]
    dq, dk, dv = _flash_bwd_bhtd(qt, kt, vt, ot, dot, lse,
                                 block_q=block_q, block_k=block_k,
                                 causal=causal, interpret=interpret,
                                 n_kv_head=Hkv, delta_override=delta)
    return (_from_bhtd(dq, B, H), _from_bhtd(dk, B, Hkv),
            _from_bhtd(dv, B, Hkv))


flash_attention_with_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 0,
                    block_k: int = 0, interpret: bool = False):
    """Fused causal attention. q: [B, T, H, Dh], k/v: [B, T, Hkv, Dh]
    (Hkv == H for MHA, Hkv dividing H for GQA) → [B, T, H, Dh].

    Uses the pallas kernels on TPU (or under `interpret`); falls back to
    the dense jnp path elsewhere or when T doesn't tile. Differentiable:
    forward AND backward are fused kernels (custom_vjp over the saved
    log-sum-exp), so it drops into build_train_step and stays O(T) in
    memory for long-context training."""
    B, T, H, Dh = q.shape
    if H % k.shape[2]:
        raise ValueError(f"GQA requires n_kv_head to divide n_head; got "
                         f"H={H}, Hkv={k.shape[2]}")
    on_tpu = jax.default_backend() == "tpu"
    dbq, dbk = default_blocks(T, Dh)
    block_q = snap_block(block_q, T) if block_q else dbq
    block_k = snap_block(block_k, T) if block_k else dbk
    if not (on_tpu or interpret) or T % block_q or T % block_k:
        return reference_attention(q, k, v, causal=causal)
    return _flash_diff(q, k, v, causal, block_q, block_k, interpret)
