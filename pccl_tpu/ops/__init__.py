"""pccl_tpu.ops — TPU compute ops: fused kernels and sequence parallelism.

flash_attention: pallas causal attention for one core (MXU-tiled, online
softmax). ring_attention: sequence-parallel attention over a mesh axis via
shard_map + ppermute (long-context capability; rides ICI).
"""

from .flash_attention import flash_attention, reference_attention  # noqa: F401
from .ring_attention import make_ring_attn_fn, ring_attention  # noqa: F401
