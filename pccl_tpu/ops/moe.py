"""Mixture-of-Experts with expert parallelism over a mesh axis.

Capability beyond the reference (SURVEY.md §2.3: no EP anywhere in the
reference); on TPU expert parallelism is the canonical way to scale MLP
capacity, so it lives here as a core op.

Design (switch-style top-1 routing, Mesh-TensorFlow dispatch algebra):

- tokens are sharded over the `ep` axis (their data dim); the stacked expert
  FFN weights are sharded over the same axis (experts_per_device = E / S);
- each device routes its local tokens: top-1 expert, gate probability,
  position-in-expert via cumsum, tokens beyond the per-expert capacity C are
  dropped (standard switch behavior; capacity_factor scales C);
- dispatch/combine are einsums against a one-hot [n, E, C] mask — XLA fuses
  them into gathers/scatters;
- the only cross-device traffic is one `lax.all_to_all` carrying the
  dispatched buckets to their expert's device and one bringing results back
  — both ride ICI.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int) -> Dict[str, jax.Array]:
    """Gate + stacked expert FFN weights ([E, ...] leading dim)."""
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = 1.0 / jnp.sqrt(d_model)
    s2 = 1.0 / jnp.sqrt(d_ff)
    return {
        "gate": jax.random.normal(k1, (d_model, n_experts), jnp.float32) * s1,
        "w_in": jax.random.normal(k2, (n_experts, d_model, d_ff), jnp.float32) * s1,
        "w_out": jax.random.normal(k3, (n_experts, d_ff, d_model), jnp.float32) * s2,
    }


def shard_moe_params(params: Dict[str, jax.Array], mesh: Mesh,
                     axis: str = "ep") -> Dict[str, jax.Array]:
    """Experts over `axis`; the gate is replicated."""
    return {
        "gate": jax.device_put(params["gate"], NamedSharding(mesh, P())),
        "w_in": jax.device_put(params["w_in"], NamedSharding(mesh, P(axis))),
        "w_out": jax.device_put(params["w_out"], NamedSharding(mesh, P(axis))),
    }


def _route(x2d: jax.Array, gate: jax.Array, capacity: int):
    """Top-1 routing. x2d: [n, d] -> (dispatch [n, E, C], gate probs [n])."""
    n = x2d.shape[0]
    logits = x2d.astype(jnp.float32) @ gate
    probs = jax.nn.softmax(logits, axis=-1)           # [n, E]
    expert = jnp.argmax(probs, axis=-1)               # [n]
    p = jnp.max(probs, axis=-1)                       # [n]
    onehot = jax.nn.one_hot(expert, gate.shape[-1], dtype=jnp.float32)
    pos = jnp.cumsum(onehot, axis=0) * onehot         # 1-based slot per expert
    within = pos <= capacity
    dispatch = (onehot * within)[:, :, None] * \
        jax.nn.one_hot((pos - 1).astype(jnp.int32), capacity,
                       dtype=jnp.float32)  # [n, E, C]
    return dispatch, p


def _expert_ffn(buckets: jax.Array, w_in: jax.Array, w_out: jax.Array,
                compute_dtype) -> jax.Array:
    """buckets: [..., El, C, d] against local experts [El, d, f]/[El, f, d]."""
    h = jnp.einsum("...ecd,edf->...ecf", buckets.astype(compute_dtype),
                   w_in.astype(compute_dtype))
    h = jax.nn.gelu(h)
    return jnp.einsum("...ecf,efd->...ecd", h, w_out.astype(compute_dtype))


def moe_mlp_dense(x: jax.Array, params: Dict[str, jax.Array], *,
                  capacity_factor: float = 1.0,
                  compute_dtype=jnp.bfloat16) -> jax.Array:
    """Single-device reference. x: [B, T, d] -> [B, T, d]."""
    B, T, d = x.shape
    E = params["gate"].shape[-1]
    n = B * T
    C = max(1, int(n * capacity_factor / E))
    x2d = x.reshape(n, d)
    dispatch, p = _route(x2d, params["gate"], C)
    buckets = jnp.einsum("nec,nd->ecd", dispatch, x2d.astype(jnp.float32))
    y = _expert_ffn(buckets, params["w_in"], params["w_out"], compute_dtype)
    out = jnp.einsum("nec,ecd->nd", dispatch, y.astype(jnp.float32))
    return (out * p[:, None]).reshape(B, T, d).astype(x.dtype)


def moe_mlp_ep(x: jax.Array, params: Dict[str, jax.Array], mesh: Mesh, *,
               axis: str = "ep", capacity_factor: float = 1.0,
               compute_dtype=jnp.bfloat16) -> jax.Array:
    """Expert-parallel MoE MLP. x: [B, T, d] with B sharded over `axis`;
    expert weights sharded over `axis`. Bit-matches moe_mlp_dense when no
    token exceeds capacity (same routing, same per-token math)."""
    S = mesh.shape[axis]
    E = params["gate"].shape[-1]
    assert E % S == 0, f"{E} experts not divisible by {S} devices"
    El = E // S
    B, T, d = x.shape
    assert B % S == 0, f"batch {B} not shardable over {S} devices"
    # per-SHARD capacity: each shard dispatches up to C slots per expert, so
    # an expert's total load is bounded by S*C = n_global*cf/E — the same
    # global bound as dense, with all_to_all traffic proportional to the
    # LOCAL token count. (Drop accounting is per shard: a shard routing more
    # than C of its own tokens to one expert drops the excess, where dense
    # would only drop past the global bound — standard EP behavior.)
    n_local = (B // S) * T
    C = max(1, -(-int(n_local * capacity_factor) // E))

    def per_device(x_local, gate, w_in, w_out):
        b, t, _ = x_local.shape
        x2d = x_local.reshape(b * t, d)
        dispatch, p = _route(x2d, gate, C)            # [n_l, E, C_local...]
        buckets = jnp.einsum("nec,nd->ecd", dispatch, x2d.astype(jnp.float32))
        # to expert homes: [E, C, d] -> [S, El, C, d], scatter dim 0
        send = buckets.reshape(S, El, C, d)
        recv = lax.all_to_all(send, axis, 0, 0)       # [S, El, C, d]
        y = _expert_ffn(recv, w_in, w_out, compute_dtype)
        back = lax.all_to_all(y.astype(jnp.float32), axis, 0, 0)
        y_buckets = back.reshape(E, C, d)
        out = jnp.einsum("nec,ecd->nd", dispatch, y_buckets)
        return (out * p[:, None]).reshape(b, t, d).astype(x_local.dtype)

    fn = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(axis)),
        out_specs=P(axis),
        axis_names={axis},
    )
    return fn(x, params["gate"], params["w_in"], params["w_out"])
