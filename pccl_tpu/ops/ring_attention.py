"""Ring attention — sequence parallelism over a mesh axis.

Long-context capability the reference lacks entirely (SURVEY.md §2.3: "no
TP/PP/SP/EP/CP/ring-attention anywhere in the reference"); on TPU it is a
first-class requirement, so it lives here as a core op, not an example.

Design (Liu et al., Ring Attention; implemented the XLA-collective way):
Q/K/V are sequence-sharded over mesh axis `sp`. Each step, every device
computes blockwise attention of its resident Q block against the currently
held K/V block, folds the result into an online-softmax accumulator
(running max `m`, normalizer `l`, weighted sum `o`), then rotates K/V one
hop around the ring with `lax.ppermute` — after sp_size steps every Q block
has seen every K/V block while K/V traffic only ever crosses neighboring
devices (rides ICI, never DCN). XLA's latency-hiding scheduler overlaps the
ppermute with the next block's compute; peak memory per device is O(T²/n²)
for logits instead of O(T²).

Causality uses GLOBAL positions (rank-offset iota), so the result is
bit-equivalent in exact arithmetic to dense causal attention over the full
sequence.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _block_attn(q, k, v, q_pos, k_pos, m, l, o, causal: bool, scale: float):
    """One online-softmax accumulation step.

    q,k,v: [B, Tl, H, Dh]; m,l: [B, H, Tl]; o: [B, Tl, H, Dh] (fp32).
    Returns updated (m, l, o)."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
        logits = jnp.where(mask, logits, -1e30)
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))          # [B, H, Tl]
    corr = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new[..., None])                    # [B, H, Tq, Tk]
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _ring_attn_local(q, k, v, *, axis_name: str, causal: bool,
                     manual_axes: tuple):
    """Per-device body under shard_map. q,k,v: [B, Tl, H, Dh] (local)."""
    B, Tl, H, Dh = q.shape
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(Dh)
    q32, k0, v0 = q, k, v

    q_pos = r * Tl + jnp.arange(Tl)

    # initial accumulators must carry the same varying-manual-axes type as
    # the loop outputs (shard_map's varying-axis tracking)
    def _vary(x):
        if hasattr(lax, "pcast"):
            return lax.pcast(x, manual_axes, to="varying")
        return lax.pvary(x, manual_axes)  # removed in newer JAX

    m0 = _vary(jnp.full((B, H, Tl), -1e30, jnp.float32))
    l0 = _vary(jnp.zeros((B, H, Tl), jnp.float32))
    o0 = _vary(jnp.zeros((B, Tl, H, Dh), jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        m, l, o, kb, vb = carry
        src = (r - s) % n                      # whose block we hold at step s
        k_pos = src * Tl + jnp.arange(Tl)
        m, l, o = _block_attn(q32, kb, vb, q_pos, k_pos, m, l, o, causal, scale)
        # rotate K/V to the next rank (skippable on the last step, but a
        # static-trip-count scan keeps XLA free to overlap it with compute)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return m, l, o, kb, vb

    m, l, o, _, _ = lax.fori_loop(0, n, body, (m0, l0, o0, k0, v0))
    # causal rows always see at least the diagonal, so l > 0
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   *, axis: str = "sp", batch_axis: Optional[str] = "dp",
                   causal: bool = True) -> jax.Array:
    """Sequence-parallel causal attention.

    q,k,v: [B, T, H, Dh] with T sharded over mesh axis `axis` and B
    (optionally) over `batch_axis`. Returns [B, T, H, Dh], same layout.
    Composes inside an outer jit."""
    ba = batch_axis if batch_axis and batch_axis in mesh.shape else None
    spec = P(ba, axis)
    manual = tuple(mesh.axis_names)
    fn = jax.shard_map(
        partial(_ring_attn_local, axis_name=axis, causal=causal,
                manual_axes=manual),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)


def make_ring_attn_fn(mesh: Mesh, axis: str = "sp",
                      batch_axis: Optional[str] = "dp"):
    """Adapter matching models.gpt's attn_fn signature (q, k, v) -> out."""
    def attn(q, k, v):
        return ring_attention(q, k, v, mesh, axis=axis, batch_axis=batch_axis,
                              causal=True)
    return attn
