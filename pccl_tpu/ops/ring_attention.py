"""Ring attention — sequence parallelism over a mesh axis.

Long-context capability the reference lacks entirely (SURVEY.md §2.3: "no
TP/PP/SP/EP/CP/ring-attention anywhere in the reference"); on TPU it is a
first-class requirement, so it lives here as a core op, not an example.

Design (Liu et al., Ring Attention; implemented the XLA-collective way):
Q/K/V are sequence-sharded over mesh axis `sp`. Each step, every device
runs ONE per-shard attention of its resident Q block against the currently
held K/V block — the fused flash-attention pallas kernels on TPU (forward
and backward; no [Tl, Tl] tensor ever), the jnp twin elsewhere — and folds
the (out, log-sum-exp) pair into its accumulator, then rotates K/V one
hop around the ring with `lax.ppermute` — after sp_size steps every Q block
has seen every K/V block while K/V traffic only ever crosses neighboring
devices (rides ICI, never DCN). XLA's latency-hiding scheduler overlaps the
ppermute with the next step's kernel; peak per-device attention memory is
one kernel tile on TPU (O(T²/n²) dense logits on the jnp fallback).

Causality uses GLOBAL positions (rank-offset iota), so the result is
bit-equivalent in exact arithmetic to dense causal attention over the full
sequence.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _shard_attn_with_lse(q, k, v, blk_causal: bool):
    """Per-shard attention returning (out, lse [B, H, Tl]) — the fused
    pallas kernels on TPU (forward AND backward; no [Tl, Tl] tensor),
    the jnp twin elsewhere. Blocks snapped to divisors of Tl."""
    from .flash_attention import (default_blocks, dense_attention_with_lse,
                                  flash_attention_with_lse)

    Tl = q.shape[1]
    bq, bk = default_blocks(Tl, q.shape[-1])
    if jax.default_backend() == "tpu" and Tl % bq == 0 and Tl % bk == 0:
        return flash_attention_with_lse(q, k, v, blk_causal, bq, bk, False)
    return dense_attention_with_lse(q, k, v, blk_causal)


def _ring_attn_local(q, k, v, *, axis_name: str, causal: bool):
    """Per-device body under shard_map. q,k,v: [B, Tl, H, Dh] (local).

    The ring is UNROLLED over the (static) axis size: at step s the device
    holds the K/V block of rank (r − s) mod n, so under causal masking the
    visibility of the whole block is all-or-nothing — s == 0 is the
    diagonal (a causal per-shard call), s > 0 is fully visible iff r ≥ s.
    Each step is therefore ONE per-shard attention (the fused flash kernel
    on TPU) plus a log-sum-exp fold:

        lse' = logaddexp(lse, lse_s)
        o'   = o·exp(lse − lse') + o_s·exp(lse_s − lse')

    with an invisible step entering as lse_s = −inf (weight exactly 0).
    Step 0 runs first and is always visible, so the accumulator lse is
    finite from the first fold and no −inf − −inf NaN can arise.
    ppermute rotates K/V between steps; XLA's latency-hiding scheduler
    overlaps the rotation with the next step's kernel.

    Tradeoff of the unroll: HLO size and compile time grow linearly with
    the sp axis size (×2 with the backward) — negligible at sp ≤ 8, worth
    a scan over the uniform s > 0 steps (step 0 peeled) if sp worlds of
    dozens of devices become a target."""
    B, Tl, H, Dh = q.shape
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    o = jnp.zeros((B, Tl, H, Dh), jnp.float32)
    lse = jnp.full((B, H, Tl), -jnp.inf, jnp.float32)
    kb, vb = k, v
    for s in range(n):
        o_s, lse_s = _shard_attn_with_lse(q, kb, vb, causal and s == 0)
        if causal and s > 0:
            visible = r >= s                       # whole-block visibility
            lse_s = jnp.where(visible, lse_s, -jnp.inf)
        lse_new = jnp.logaddexp(lse, lse_s)
        w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
        w_new = jnp.exp(lse_s - lse_new).transpose(0, 2, 1)[..., None]
        o = o * w_old + o_s.astype(jnp.float32) * w_new
        lse = lse_new
        if s != n - 1:
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
    return o.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   *, axis: str = "sp", batch_axis: Optional[str] = "dp",
                   causal: bool = True) -> jax.Array:
    """Sequence-parallel causal attention.

    q,k,v: [B, T, H, Dh] with T sharded over mesh axis `axis` and B
    (optionally) over `batch_axis`. Returns [B, T, H, Dh], same layout.
    Composes inside an outer jit."""
    import inspect

    ba = batch_axis if batch_axis and batch_axis in mesh.shape else None
    spec = P(ba, axis)
    # pallas_call outputs carry no varying-mesh-axes annotation, which the
    # replication checker refuses inside a checked shard_map; the kwarg
    # was renamed check_rep -> check_vma across jax versions
    params = inspect.signature(jax.shard_map).parameters
    kw = ({"check_vma": False} if "check_vma" in params
          else {"check_rep": False} if "check_rep" in params else {})
    fn = jax.shard_map(
        partial(_ring_attn_local, axis_name=axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **kw,
    )
    return fn(q, k, v)


def make_ring_attn_fn(mesh: Mesh, axis: str = "sp",
                      batch_axis: Optional[str] = "dp"):
    """Adapter matching models.gpt's attn_fn signature (q, k, v) -> out."""
    def attn(q, k, v):
        return ring_attention(q, k, v, mesh, axis=axis, batch_axis=batch_axis,
                              causal=True)
    return attn
