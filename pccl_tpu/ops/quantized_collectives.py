"""In-jit quantized ring all-reduce over a mesh axis (ICI).

The native stack quantizes the DCN hop (reference piquant path, SURVEY.md
§2 #12); this module brings the same wire-shrink to the IN-JIT dimension:
an int8 ring all-reduce built from `lax.ppermute`, so gradient syncs over
a mesh axis move ~4x fewer bytes across ICI at a bounded precision cost.
(Technique family: EQuARX — quantized all-reduce inside XLA,
arXiv 2506.17615, PAPERS.md; re-designed here around pcclt's bit-parity
invariant rather than ported.)

Algorithm (mirrors the native ring, reduce.cpp):

- reduce-scatter: N-1 `ppermute` steps; each hop carries blockwise
  symmetric int8 codes + one fp32 scale per block. The receiver
  dequantizes and accumulates in fp32, then REQUANTIZES the partial sum
  for the next hop (fresh scales — partial sums outgrow input scales).
- all-gather: the fully-reduced chunk is quantized ONCE by its owner and
  forwarded VERBATIM; the owner self-dequantizes its own chunk. Every
  rank therefore decodes byte-identical codes — the same bit-parity
  invariant the native path keeps (reference reduce.cpp:673-738), which
  the shared-state hash machinery depends on.

Use when the axis is bandwidth-bound (big flat gradient vectors over a
large `dp` axis); for small tensors plain `lax.pmean` wins.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _quantize_block(x: jax.Array, block: int):
    """Blockwise symmetric int8: codes in [-127,127], one fp32 scale per
    block. x is 1-D with size % block == 0."""
    xb = x.reshape(-1, block)
    s = jnp.max(jnp.abs(xb), axis=1, keepdims=True) / 127.0
    s = jnp.where(s > 0, s, 1.0)
    q = jnp.clip(jnp.round(xb / s), -127, 127).astype(jnp.int8)
    return q.reshape(-1), s.reshape(-1).astype(jnp.float32)


def _dequantize_block(q: jax.Array, s: jax.Array, block: int) -> jax.Array:
    return (q.reshape(-1, block).astype(jnp.float32) *
            s.reshape(-1, 1)).reshape(-1)


def quantized_ring_all_reduce(x: jax.Array, axis_name: str, *,
                              block: int = 256, mean: bool = False) -> jax.Array:
    """int8 ring all-reduce of `x` (any shape, fp32/bf16) over `axis_name`.
    Call inside shard_map/pjit manual context. Returns fp32 cast back to
    x.dtype; every rank returns bit-identical values."""
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    orig_dtype = x.dtype
    orig_shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    # pad so the vector splits into n chunks of whole blocks
    chunk = -(-flat.size // (n * block)) * block  # ceil to block multiple
    orig_size = flat.size
    flat = jnp.pad(flat, (0, n * chunk - flat.size))
    chunks = flat.reshape(n, chunk)

    if n == 1:
        out = chunks.reshape(-1)[:orig_size]
        return out.reshape(orig_shape).astype(orig_dtype)

    fwd = [(i, (i + 1) % n) for i in range(n)]

    # ---- reduce-scatter: after step s, the partial sum of chunk
    # (rank - s - 1) has visited ranks rank-s-1..rank ----
    def rs_step(s, carry):
        acc_q, acc_s = carry  # quantized partial for the chunk we just sent
        q = lax.ppermute(acc_q, axis_name, fwd)
        sc = lax.ppermute(acc_s, axis_name, fwd)
        # we now hold the partial for chunk (rank - s - 1); fold in ours
        idx = (rank - s - 1) % n
        mine = lax.dynamic_index_in_dim(chunks, idx, axis=0, keepdims=False)
        acc = _dequantize_block(q, sc, block) + mine
        return _quantize_block(acc, block)

    q0, s0 = _quantize_block(
        lax.dynamic_index_in_dim(chunks, rank, axis=0, keepdims=False), block)
    qf, sf = lax.fori_loop(0, n - 1, rs_step, (q0, s0))
    # qf/sf: fully-reduced chunk (rank + 1) % n, quantized by THIS rank —
    # exactly once, so the all-gather can forward it verbatim

    # ---- all-gather: verbatim forwarding for bit parity ----
    own_idx = (rank + 1) % n
    out_chunks = jnp.zeros((n, chunk), jnp.float32)
    own_deq = _dequantize_block(qf, sf, block)  # owner self-dequantizes
    out_chunks = lax.dynamic_update_index_in_dim(out_chunks, own_deq, own_idx,
                                                 axis=0)

    def ag_step(s, carry):
        out, q, sc = carry
        q = lax.ppermute(q, axis_name, fwd)
        sc = lax.ppermute(sc, axis_name, fwd)
        # arrived: the packet forwarded s hops originated at rank (r - s),
        # which owns chunk (r - s + 1)
        idx = (rank - s + 1) % n
        out = lax.dynamic_update_index_in_dim(
            out, _dequantize_block(q, sc, block), idx, axis=0)
        return out, q, sc

    out_chunks, _, _ = lax.fori_loop(
        1, n, lambda s, c: ag_step(s, c), (out_chunks, qf, sf))

    out = out_chunks.reshape(-1)[:orig_size]
    if mean:
        out = out / n
    return out.reshape(orig_shape).astype(orig_dtype)


def quantized_pmean(tree, axis_name: str, *, block: int = 256):
    """Tree-mapped quantized mean over a mesh axis — drop-in for
    `jax.lax.pmean` where ICI bandwidth dominates and int8 precision is
    acceptable (DiLoCo outer averaging, gradient sync on fat axes)."""
    return jax.tree.map(
        partial(quantized_ring_all_reduce, axis_name=axis_name, block=block,
                mean=True), tree)
