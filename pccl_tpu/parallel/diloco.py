"""DiLoCo — low-communication data parallelism over the WAN ring.

Capability parity: the reference ships sync DiLoCo
(/root/reference/python/examples/nanogpt_diloco/sync_diloco.py:396-510,
docs/md/07-.../02-SyncDiloco.md) and async one-step-delayed DiLoCo
(async_diloco.py, docs/md/07-.../03-AsyncDiloco.md) as torch training loops
over the pccl bindings. Here the same algorithm is a library component,
designed TPU-first:

- the inner loop is whatever jitted SPMD train step the caller owns
  (pccl_tpu.parallel.train); DiLoCo never sees it;
- pseudo-gradients (outer_params - inner_params) are computed ON DEVICE by a
  jitted function that flattens every leaf into ONE contiguous fp32 vector —
  a single large buffer is the shape the ring reduce wants (few tags, big
  chunks saturate the pipe), and the flatten/unflatten round-trip is free
  for XLA to fuse;
- only that one vector crosses host↔device per outer step; the outer
  (Nesterov SGD) update runs jitted on device;
- the WAN hop supports on-the-wire quantization (MinMax / ZeroPointScale),
  mirroring the reference's piquant path;
- fault tolerance follows the reference contract: ConnectionLost/Aborted →
  update_topology() → retry with the surviving world.

Shared-state integration: `shared_state()` exposes outer params + outer
optimizer momentum + step as a revisioned pccl_tpu.comm.SharedState so
late joiners catch up bit-identically (reference sync_diloco.py keeps the
same three groups in its shared state).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..comm import (
    Communicator,
    DataType,
    QuantizationAlgorithm,
    ReduceOp,
    SharedState,
    SharedStateSyncStrategy,
    TensorInfo,
)
from . import codec
from .ring import avg_all_reduce_windowed


@dataclasses.dataclass(frozen=True)
class DilocoConfig:
    """Hyperparameters of the outer loop (reference defaults:
    sync_diloco.py outer SGD lr=0.7, nesterov momentum=0.9, H~50-500)."""

    outer_lr: float = 0.7
    outer_momentum: float = 0.9
    nesterov: bool = True
    inner_steps: int = 50
    quantization: QuantizationAlgorithm = QuantizationAlgorithm.NONE
    quantized_dtype: DataType = DataType.UINT8
    max_retries: int = 16
    # Stage the pseudo-gradient in a REGISTERED shm buffer (comm.shm_ndarray)
    # so same-host peers take the zero-copy collective path. Costs one extra
    # params-sized copy per outer step, so enable it when peers share hosts
    # (workers per TPU host, bench loops); leave off for pure-WAN rings.
    shm_staging: bool = False
    # Split the outer reduce into this many concurrent tagged collectives
    # (ring.avg_all_reduce_windowed) — the reference's MultipleWithRetry
    # recipe for saturating fat pipes with multiple flows. 1 = single op.
    comm_windows: int = 1


from .codec import build_codec


class Diloco:
    """Synchronous DiLoCo driver around a Communicator.

    Usage::

        dl = Diloco(comm, params, cfg)
        while training:
            comm.update_topology()                 # admit joiners
            dl.sync_shared_state()                 # catch up if outdated
            params = dl.params()                   # donation-safe copy
            for _ in range(cfg.inner_steps):
                params, opt_state, loss = inner_step(params, opt_state, ...)
            params = dl.outer_step(params)         # WAN ring + outer SGD

    The returned `params` after outer_step are the new global (outer) params,
    already on device with the original shardings — continue inner training
    from them (reference: sync_diloco.py resets inner params to outer).
    """

    def __init__(self, comm: Optional[Communicator], params: Any,
                 cfg: DilocoConfig = DilocoConfig()):
        self.comm = comm
        self.cfg = cfg
        self.step = 0
        self._delta_fn, self._flat_fn, self._unflat_fn, self.count = build_codec(params)
        self._shm_stage = None  # lazy registered staging buffer (cfg.shm_staging)
        # leaf shardings of the template, reapplied after every unflatten so
        # outer params keep the caller's TP/DP layout
        self._shardings = codec.leaf_shardings(params)
        # outer params live on device as PRIVATE copies: the caller's train
        # step typically donates its param buffers (train.build_train_step
        # uses donate_argnums), which would delete aliased arrays under us.
        # Committed placement from step 0: uncommitted inputs would retrace
        # the jitted helpers once their outputs come back committed — at
        # 100M+ params each spurious retrace costs seconds.
        self.outer_params = self._restore_shardings(jax.tree.map(jnp.copy, params))
        self._momentum_vec = jax.device_put(jnp.zeros((self.count,), jnp.float32))

        lr, mu, nesterov = cfg.outer_lr, cfg.outer_momentum, cfg.nesterov

        def _apply(outer_vec, mom, delta):
            mom = mu * mom + delta
            upd = delta + mu * mom if nesterov else mom
            return outer_vec - lr * upd, mom

        # outer_vec and momentum are dead after the call — donate their
        # buffers so the update runs in place instead of allocating 2 more
        # param-sized arrays
        self._apply_fn = jax.jit(_apply, donate_argnums=(0, 1))

    # -- the outer step --

    def params(self) -> Any:
        """Fresh copy of the current outer params, safe to hand to a
        donating train step (the driver keeps its own private buffers)."""
        return jax.tree.map(jnp.copy, self.outer_params)

    def _restore_shardings(self, tree: Any) -> Any:
        return codec.restore_shardings(tree, self._shardings)

    def _reduce_host(self, vec: np.ndarray) -> int:
        assert self.comm is not None
        return avg_all_reduce_windowed(
            self.comm, vec, windows=self.cfg.comm_windows,
            quantization=self.cfg.quantization,
            quantized_dtype=self.cfg.quantized_dtype,
            max_retries=self.cfg.max_retries)

    # tag band for pipelined window reduces: disjoint from the blocking
    # default 0, user small tags, the MultipleWithRetry band (1<<16), and
    # the auto band (1<<32); deterministic so every peer matches by window
    _WINDOW_TAG_BASE = 1 << 20

    def _ensure_shm_stage(self) -> None:
        if self._shm_stage is None:
            from pccl_tpu.comm.api import shm_ndarray

            self._shm_stage = shm_ndarray(self.count, np.float32)

    def _reduce_pipelined(self, delta) -> bool:
        """Overlapped outer reduce: device->host of window k+1 overlaps the
        ring reduce of window k (the windows are independent tagged
        collectives). Falls back (returns False) when windowing is off or
        the vector is too small; failed windows retry over the survivor
        world via MultipleWithRetry, completed ones stand — the documented
        mixed-world windowed semantics."""
        from pccl_tpu.comm import PcclError, TooFewPeersError
        from .ring import _MIN_WINDOW_ELEMS

        k = min(self.cfg.comm_windows, max(1, self.count // _MIN_WINDOW_ELEMS), 8)
        if k <= 1:
            return False
        self._ensure_shm_stage()
        bounds = [self.count * i // k for i in range(k + 1)]
        # slice on device and start every D2H up front; np.asarray(win)
        # then only blocks for ITS window while later windows keep copying
        wins = [jax.lax.slice_in_dim(delta, bounds[i], bounds[i + 1], axis=0)
                for i in range(k)]
        for w in wins:
            try:
                w.copy_to_host_async()
            except AttributeError:  # older jax: device_get blocks per window
                break
        handles, views, failed = [], [], []
        for i, w in enumerate(wins):
            view = self._shm_stage[bounds[i]:bounds[i + 1]]
            np.copyto(view, np.asarray(w, dtype=np.float32))
            views.append(view)
            # launch this window's ring while the next window's D2H runs.
            # A launch-time failure must NOT escape with earlier windows
            # still in flight on this shared buffer — record it for the
            # retry batch and keep going to the join below.
            try:
                handles.append((i, self.comm.all_reduce_async(
                    view, view, op=ReduceOp.AVG,
                    tag=self._WINDOW_TAG_BASE + i)))
            except TooFewPeersError:
                pass  # alone: the window is its own average
            except PcclError:
                failed.append(i)
        for i, h in handles:
            try:
                h.wait()
            except TooFewPeersError:
                pass
            except PcclError:
                failed.append(i)
        if failed:
            # survivors agree on the failed SET (exactly-one-abort
            # accounting), but not necessarily its order (launch-time vs
            # wait-time detection interleave differently per peer) — and
            # MultipleWithRetry assigns tags by list POSITION. Sort so the
            # retry batch pairs the same window across all peers.
            failed = sorted(set(failed))
            self.comm.update_topology()
            try:
                self.comm.all_reduce_multiple_with_retry(
                    [views[i] for i in failed], op=ReduceOp.AVG)
            except TooFewPeersError:
                pass
        return True

    def outer_step(self, inner_params: Any) -> Any:
        """Average pseudo-gradients across peers, apply outer Nesterov SGD,
        return the new global params (device pytree).

        The returned tree is a fresh copy safe to hand to a donating train
        step; the driver keeps its own buffers for the next pseudo-gradient."""
        delta = self._delta_fn(self.outer_params, inner_params)
        # quantized rings send from quantize scratch, not from the staged
        # buffer — shm staging would be a pure extra copy there, so gate it
        use_shm = (self.cfg.shm_staging and self.comm is not None
                   and self.cfg.quantization == QuantizationAlgorithm.NONE)
        if use_shm and self.cfg.comm_windows > 1 and self._reduce_pipelined(delta):
            host = self._shm_stage
        else:
            # np.asarray: device_get already yields a host ndarray — a second
            # np.array copy would cost another params-sized memcpy per step
            host = np.asarray(jax.device_get(delta), dtype=np.float32)
            if use_shm:
                self._ensure_shm_stage()
                np.copyto(self._shm_stage, host)
                host = self._shm_stage  # same-host peers reduce zero-copy
            elif not host.flags["WRITEABLE"] or not host.flags["C_CONTIGUOUS"]:
                host = np.array(host, dtype=np.float32)  # reduces in place
            if self.comm is not None:
                self._reduce_host(host)
        outer_vec = self._flat_fn(self.outer_params)
        new_vec, self._momentum_vec = self._apply_fn(
            outer_vec, self._momentum_vec,
            jax.device_put(host, outer_vec.sharding))
        self.outer_params = self._restore_shardings(self._unflat_fn(new_vec))
        self.step += 1
        return jax.tree.map(jnp.copy, self.outer_params)

    # -- shared state --

    def shared_state(self) -> SharedState:
        """Outer params + momentum + step as a revisioned SharedState.
        Revision = outer step count (one-increment rule of the master,
        reference ccoip_master_state.cpp:1066-1090)."""
        self._ss_vec = np.array(
            jax.device_get(self._flat_fn(self.outer_params)), dtype=np.float32)
        self._ss_mom = np.array(jax.device_get(self._momentum_vec),
                                  dtype=np.float32)
        self._ss_step = np.array([self.step], dtype=np.uint64)
        return SharedState([
            TensorInfo.from_numpy("diloco.outer_params", self._ss_vec),
            TensorInfo.from_numpy("diloco.outer_momentum", self._ss_mom),
            TensorInfo.from_numpy("diloco.step", self._ss_step),
        ], revision=self.step)

    def sync_shared_state(
            self,
            strategy: SharedStateSyncStrategy = SharedStateSyncStrategy.ENFORCE_POPULAR):
        """Sync outer state with the group; adopt whatever wins the election
        into self.outer_params / momentum / step. Returns the
        SharedStateSyncInfo (tx/rx bytes, revision); take the adopted params
        via self.params() — a donation-safe copy, NOT self.outer_params,
        which aliases the driver's private buffers."""
        assert self.comm is not None
        st = self.shared_state()
        info = self.comm.sync_shared_state(st, strategy)
        # adopt (possibly received) content
        self.step = int(self._ss_step[0])
        self._momentum_vec = jnp.asarray(self._ss_mom)
        self.outer_params = self._restore_shardings(
            self._unflat_fn(jnp.asarray(self._ss_vec)))
        return info


class AsyncDiloco(Diloco):
    """One-step-delayed DiLoCo: the reduce of outer step t overlaps with the
    inner compute of step t+1 (reference async_diloco.py,
    docs/md/07-.../03-AsyncDiloco.md:1-112).

    outer_step_async(inner_params) kicks the WAN reduce on a background
    thread and returns IMMEDIATELY with params to continue training from
    (the current outer params — the delayed update lands next call).
    Call .finish() (or the next outer_step_async) to join the in-flight
    reduce and apply it.
    """

    def __init__(self, comm, params, cfg: DilocoConfig = DilocoConfig()):
        super().__init__(comm, params, cfg)
        self._inflight: Optional[threading.Thread] = None
        self._inflight_host: Optional[np.ndarray] = None
        self._err: Optional[BaseException] = None
        self._baseline: Optional[Any] = None  # outer params inner started from

    def _reduce_bg(self, host: np.ndarray) -> None:
        try:
            if self.comm is not None:
                self._reduce_host(host)
        except BaseException as e:  # noqa: BLE001 — surfaced on join
            self._err = e

    def _join_inflight(self) -> None:
        if self._inflight is None:
            return
        self._inflight.join()
        self._inflight = None
        if self._err is not None:
            err, self._err = self._err, None
            self._inflight_host = None
            raise err
        host = self._inflight_host
        self._inflight_host = None
        outer_vec = self._flat_fn(self.outer_params)
        new_vec, self._momentum_vec = self._apply_fn(
            outer_vec, self._momentum_vec, jnp.asarray(host))
        self.outer_params = self._restore_shardings(self._unflat_fn(new_vec))
        self.step += 1

    def outer_step_async(self, inner_params: Any) -> Any:
        """Apply the previous in-flight reduce (if any), launch the reduce of
        this step's pseudo-gradient, return params to continue from."""
        # the pseudo-gradient baseline is the outer params the inner phase
        # STARTED from — before the delayed update from step t-1 lands
        # (reference async semantics, docs/md/07-.../03-AsyncDiloco.md)
        baseline = self._baseline if self._baseline is not None else self.outer_params
        delta = self._delta_fn(baseline, inner_params)
        host = np.array(jax.device_get(delta), dtype=np.float32)
        self._join_inflight()
        self._inflight_host = host
        self._inflight = threading.Thread(target=self._reduce_bg, args=(host,),
                                          daemon=True)
        self._inflight.start()
        self._baseline = self.outer_params
        # fresh copy: the caller's train step may donate what we return
        return jax.tree.map(jnp.copy, self.outer_params)

    def sync_shared_state(
            self,
            strategy: SharedStateSyncStrategy = SharedStateSyncStrategy.ENFORCE_POPULAR):
        """Land (or fail) the in-flight delayed update BEFORE the election so
        the offered state is self-consistent, and drop the pseudo-gradient
        baseline afterwards — adopted params invalidate it (the delta would
        otherwise include the whole sync jump)."""
        self._join_inflight()
        info = super().sync_shared_state(strategy)
        self._baseline = None
        return info

    def finish(self) -> Any:
        """Join any in-flight reduce and apply it; returns final outer params
        (fresh copy, donation-safe)."""
        self._join_inflight()
        return jax.tree.map(jnp.copy, self.outer_params)
