"""DiLoCo — low-communication data parallelism over the WAN ring.

Capability parity: the reference ships sync DiLoCo
(/root/reference/python/examples/nanogpt_diloco/sync_diloco.py:396-510,
docs/md/07-.../02-SyncDiloco.md) and async one-step-delayed DiLoCo
(async_diloco.py, docs/md/07-.../03-AsyncDiloco.md) as torch training loops
over the pccl bindings. Here the same algorithm is a library component,
designed TPU-first:

- the inner loop is whatever jitted SPMD train step the caller owns
  (pccl_tpu.parallel.train); DiLoCo never sees it;
- pseudo-gradients (outer_params - inner_params) are computed ON DEVICE by a
  jitted function that flattens every leaf into ONE contiguous fp32 vector —
  a single large buffer is the shape the ring reduce wants (few tags, big
  chunks saturate the pipe), and the flatten/unflatten round-trip is free
  for XLA to fuse;
- only that one vector crosses host↔device per outer step; the outer
  (Nesterov SGD) update runs jitted on device;
- the WAN hop supports on-the-wire quantization (MinMax / ZeroPointScale),
  mirroring the reference's piquant path;
- fault tolerance follows the reference contract: ConnectionLost/Aborted →
  update_topology() → retry with the surviving world.

Shared-state integration: `shared_state()` exposes outer params + outer
optimizer momentum + step as a revisioned pccl_tpu.comm.SharedState so
late joiners catch up bit-identically (reference sync_diloco.py keeps the
same three groups in its shared state).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..comm import (
    Communicator,
    DataType,
    QuantizationAlgorithm,
    ReduceOp,
    SharedState,
    SharedStateSyncStrategy,
    TensorInfo,
)
from . import codec
from .ring import avg_all_reduce_windowed


@dataclasses.dataclass(frozen=True)
class DilocoConfig:
    """Hyperparameters of the outer loop (reference defaults:
    sync_diloco.py outer SGD lr=0.7, nesterov momentum=0.9, H~50-500)."""

    outer_lr: float = 0.7
    outer_momentum: float = 0.9
    nesterov: bool = True
    inner_steps: int = 50
    quantization: QuantizationAlgorithm = QuantizationAlgorithm.NONE
    quantized_dtype: DataType = DataType.UINT8
    max_retries: int = 16
    # Stage the pseudo-gradient in a REGISTERED shm buffer (comm.shm_ndarray)
    # so same-host peers take the zero-copy collective path. Costs one extra
    # params-sized copy per outer step, so enable it when peers share hosts
    # (workers per TPU host, bench loops); leave off for pure-WAN rings.
    shm_staging: bool = False
    # Split the outer reduce into this many concurrent tagged collectives
    # (ring.avg_all_reduce_windowed) — the reference's MultipleWithRetry
    # recipe for saturating fat pipes with multiple flows. 1 = single op.
    comm_windows: int = 1
    # Record a per-phase wall-clock breakdown of each outer step in
    # Diloco.last_profile (fences phases with block_until_ready, so leave
    # off in production — it defeats the pipelined reduce overlap).
    profile: bool = False


from .codec import build_codec


class Diloco:
    """Synchronous DiLoCo driver around a Communicator.

    Usage::

        dl = Diloco(comm, params, cfg)
        while training:
            comm.update_topology()                 # admit joiners
            dl.sync_shared_state()                 # catch up if outdated
            params = dl.params()                   # donation-safe copy
            for _ in range(cfg.inner_steps):
                params, opt_state, loss = inner_step(params, opt_state, ...)
            params = dl.outer_step(params)         # WAN ring + outer SGD

    The returned `params` after outer_step are the new global (outer) params,
    already on device with the original shardings — continue inner training
    from them (reference: sync_diloco.py resets inner params to outer).
    """

    def __init__(self, comm: Optional[Communicator], params: Any,
                 cfg: DilocoConfig = DilocoConfig()):
        self.comm = comm
        self.cfg = cfg
        self.step = 0
        c = build_codec(params)
        self._delta_fn, self._flat_fn, self._unflat_fn = c.flat_delta, c.flat, c.unflat
        self._delta_vec_fn, self.count = c.flat_delta_vec, c.count
        self._shm_stage = None  # lazy registered staging buffers (cfg.shm_staging)
        self._shm_out = None
        self._host_out = None  # pooled recv for the unstaged out-of-place ring
        # leaf shardings of the template, reapplied after every unflatten so
        # outer params keep the caller's TP/DP layout
        self._shardings = codec.leaf_shardings(params)
        # The CANONICAL outer state is the flat fp32 vector — the form every
        # per-step consumer wants (pseudo-gradient subtract, ring reduce,
        # outer SGD, shared-state offer). The param TREE is materialized only
        # at the API boundary (params(), outer_step return, the outer_params
        # property), where _unflat_fn's jit outputs are fresh buffers and so
        # donation-safe without a defensive full-tree copy. This removes two
        # params-sized copies and one flatten per outer step vs. keeping the
        # tree canonical. Committed placement from step 0: uncommitted inputs
        # would retrace the jitted helpers once their outputs come back
        # committed — at 100M+ params each spurious retrace costs seconds.
        self._outer_vec = self._flat_fn(params)
        self._momentum_vec = jax.device_put(jnp.zeros((self.count,), jnp.float32))
        # last in-flight apply output: overwriting the reused shm staging
        # buffer must wait for it (device_put on the CPU backend can alias
        # staged host memory zero-copy, so a pending apply may still read it)
        self._applied = None
        self.last_profile: Optional[dict] = None

        lr, mu, nesterov = cfg.outer_lr, cfg.outer_momentum, cfg.nesterov

        def _apply(outer_vec, mom, delta):
            mom = mu * mom + delta
            upd = delta + mu * mom if nesterov else mom
            return outer_vec - lr * upd, mom

        # outer_vec and momentum are dead after the call — donate their
        # buffers so the update runs in place instead of allocating 2 more
        # param-sized arrays
        self._apply_fn = jax.jit(_apply, donate_argnums=(0, 1))

        # fused apply+unflatten for the sync outer step: ONE dispatch yields
        # the updated vector, the momentum, and the output tree — XLA slices
        # the tree leaves out of the same pass that writes the update, so
        # the separate unflat dispatch (a full params-sized re-read; 0.58 s
        # at 100M params on the bench host) disappears from the step
        unflat = c.unflat

        def _apply_tree(outer_vec, mom, delta):
            new_vec, mom = _apply(outer_vec, mom, delta)
            return new_vec, mom, unflat(new_vec)

        self._apply_tree_fn = jax.jit(_apply_tree, donate_argnums=(0, 1))

    # -- the outer step --

    @property
    def outer_params(self) -> Any:
        """Current outer params as a device pytree (fresh buffers, laid out
        with the caller's shardings). Assignment flattens back into the
        canonical vector."""
        return self._restore_shardings(self._unflat_fn(self._outer_vec))

    @outer_params.setter
    def outer_params(self, tree: Any) -> None:
        self._outer_vec = self._flat_fn(tree)

    def params(self) -> Any:
        """Current outer params, safe to hand to a donating train step (the
        driver keeps only the flat vector; these buffers are fresh)."""
        return self.outer_params

    def _restore_shardings(self, tree: Any) -> Any:
        return codec.restore_shardings(tree, self._shardings)

    def _reduce_host(self, vec: np.ndarray, out: np.ndarray = None) -> int:
        assert self.comm is not None
        return avg_all_reduce_windowed(
            self.comm, vec, windows=self.cfg.comm_windows, out=out,
            quantization=self.cfg.quantization,
            quantized_dtype=self.cfg.quantized_dtype,
            max_retries=self.cfg.max_retries)

    # tag band for pipelined window reduces: disjoint from the blocking
    # default 0, user small tags, the MultipleWithRetry band (1<<16), and
    # the auto band (1<<32); deterministic so every peer matches by window
    _WINDOW_TAG_BASE = 1 << 20

    def _ensure_shm_stage(self) -> None:
        if self._shm_stage is None:
            from pccl_tpu.comm.api import shm_ndarray

            # double-buffered: the ring reduces stage -> out out-of-place,
            # which skips the native in-place abort-restore backup (a full
            # params-sized memcpy per outer step)
            self._shm_stage = shm_ndarray(self.count, np.float32)
            self._shm_out = shm_ndarray(self.count, np.float32)

    def _reduce_pipelined(self, delta) -> bool:
        """Overlapped outer reduce: device->host of window k+1 overlaps the
        ring reduce of window k (the windows are independent tagged
        collectives). Falls back (returns False) when windowing is off or
        the vector is too small; failed windows retry over the survivor
        world via MultipleWithRetry, completed ones stand — the documented
        mixed-world windowed semantics."""
        from pccl_tpu.comm import PcclError, TooFewPeersError
        from .ring import _MIN_WINDOW_ELEMS

        k = min(self.cfg.comm_windows, max(1, self.count // _MIN_WINDOW_ELEMS), 8)
        if k <= 1:
            return False
        self._ensure_shm_stage()
        # the stage may still be read by the previous step's apply (CPU
        # backend device_put can alias it zero-copy) — wait it out
        if self._applied is not None:
            jax.block_until_ready(self._applied)
            self._applied = None
        bounds = [self.count * i // k for i in range(k + 1)]
        # slice on device and start every D2H up front; np.asarray(win)
        # then only blocks for ITS window while later windows keep copying
        wins = [jax.lax.slice_in_dim(delta, bounds[i], bounds[i + 1], axis=0)
                for i in range(k)]
        for w in wins:
            try:
                w.copy_to_host_async()
            except AttributeError:  # older jax: device_get blocks per window
                break
        handles, views, failed = [], [], []
        for i, w in enumerate(wins):
            view = self._shm_stage[bounds[i]:bounds[i + 1]]
            out_view = self._shm_out[bounds[i]:bounds[i + 1]]
            np.copyto(view, np.asarray(w, dtype=np.float32))
            views.append(out_view)
            # launch this window's ring while the next window's D2H runs —
            # out-of-place into the second stage, so the native ring skips
            # its in-place abort-restore backup copy. A launch-time failure
            # must NOT escape with earlier windows still in flight on this
            # shared buffer — record it for the retry batch and keep going
            # to the join below.
            try:
                handles.append((i, self.comm.all_reduce_async(
                    view, out_view, op=ReduceOp.AVG,
                    tag=self._WINDOW_TAG_BASE + i)))
            except TooFewPeersError:
                np.copyto(out_view, view)  # alone: the window is its own avg
            except PcclError:
                # never launched: the out view holds stale bytes — seed it
                # with the input so the in-place retry below reduces real data
                np.copyto(out_view, view)
                failed.append(i)
        for i, h in handles:
            try:
                h.wait()
            except TooFewPeersError:
                np.copyto(views[i], self._shm_stage[bounds[i]:bounds[i + 1]])
            except PcclError:
                # aborted mid-op: the native ring restored the out view from
                # the untouched staged input, so the retry sees real data
                failed.append(i)
        if failed:
            # survivors agree on the failed SET (exactly-one-abort
            # accounting), but not necessarily its order (launch-time vs
            # wait-time detection interleave differently per peer) — and
            # MultipleWithRetry assigns tags by list POSITION. Sort so the
            # retry batch pairs the same window across all peers.
            failed = sorted(set(failed))
            self.comm.update_topology()
            try:
                self.comm.all_reduce_multiple_with_retry(
                    [views[i] for i in failed], op=ReduceOp.AVG)
            except TooFewPeersError:
                pass
        return True

    def outer_step(self, inner_params: Any) -> Any:
        """Average pseudo-gradients across peers, apply outer Nesterov SGD,
        return the new global params (device pytree).

        ``inner_params`` is CONSUMED (its buffers are donated to the
        pseudo-gradient computation — see codec.build_codec); continue
        training from the returned tree. The returned tree has fresh
        buffers, safe to hand to a donating train step; the driver keeps
        only the canonical flat vector.

        With ``cfg.profile`` set, ``self.last_profile`` holds a per-phase
        wall-clock breakdown (seconds) of this step — each phase is fenced
        with block_until_ready, which serializes the device pipeline, so
        profiled steps run slightly slower than unprofiled ones."""
        prof: Optional[dict] = {} if self.cfg.profile else None
        cpu_mark = [time.process_time()]

        def mark(name, t0, *sync):
            if prof is not None:
                for a in sync:
                    jax.block_until_ready(a)
                t1 = time.perf_counter()
                prof[name] = t1 - t0
                # cpu seconds alongside wall: on a contended host the gap
                # between them is scheduler wait / peer wait, not phase work
                c1 = time.process_time()
                prof[name + "_cpu"] = c1 - cpu_mark[0]
                cpu_mark[0] = c1
                return t1
            return t0

        t = time.perf_counter()
        delta = self._delta_vec_fn(self._outer_vec, inner_params)
        t = mark("delta_compute", t, delta)
        # quantized rings send from quantize scratch, not from the staged
        # buffer — shm staging would be a pure extra copy there, so gate it
        use_shm = (self.cfg.shm_staging and self.comm is not None
                   and self.cfg.quantization == QuantizationAlgorithm.NONE)
        if (use_shm and self.cfg.comm_windows > 1
                and self._reduce_pipelined(delta)):
            # pipelined: D2H of window k+1 overlaps the ring of window k, so
            # the phases are not separable — profiled, this records as one
            # combined phase. The branch must NOT depend on cfg.profile:
            # the reduce path is a cross-peer protocol (window tags must
            # match on every rank), and profile is a local flag.
            host = self._shm_out
            t = mark("d2h_stage_ring_pipelined", t)
        else:
            # np.asarray: device_get already yields a host ndarray — a second
            # np.array copy would cost another params-sized memcpy per step
            host = np.asarray(jax.device_get(delta), dtype=np.float32)
            t = mark("d2h", t)
            if self._applied is not None:  # see _reduce_pipelined
                jax.block_until_ready(self._applied)
                self._applied = None
            if use_shm:
                self._ensure_shm_stage()
                np.copyto(self._shm_stage, host)
                t = mark("stage_copy", t)
                if self.comm is not None:
                    # out-of-place between the two registered stages: the
                    # same-host ring reduces zero-copy AND skips the native
                    # in-place backup memcpy
                    self._reduce_host(self._shm_stage, out=self._shm_out)
                host = self._shm_out
            else:
                if not host.flags["C_CONTIGUOUS"]:
                    host = np.ascontiguousarray(host, dtype=np.float32)
                t = mark("stage_copy", t)
                if self.comm is not None:
                    if self._host_out is None or self._host_out.size != self.count:
                        self._host_out = np.empty(self.count, np.float32)
                    self._reduce_host(host, out=self._host_out)
                    host = self._host_out
            t = mark("ring_reduce", t)
        new_vec, self._momentum_vec, out = self._apply_tree_fn(
            self._outer_vec, self._momentum_vec,
            jax.device_put(host, self._outer_vec.sharding))
        self._outer_vec = self._applied = new_vec
        t = mark("h2d_apply", t, new_vec)
        self.step += 1
        # tree materialization fused into the apply dispatch above; what's
        # left here is only the (usually no-op) sharding restore
        out = self._restore_shardings(out)
        mark("unflat_out", t, out)
        if prof is not None:
            prof["total"] = sum(v for k, v in prof.items() if not k.endswith("_cpu"))
            self.last_profile = prof
        return out

    # -- shared state --

    def shared_state(self) -> SharedState:
        """Outer params + momentum + step as a revisioned SharedState.
        Revision = outer step count (one-increment rule of the master,
        reference ccoip_master_state.cpp:1066-1090)."""
        self._ss_vec = np.array(
            jax.device_get(self._outer_vec), dtype=np.float32)
        self._ss_mom = np.array(jax.device_get(self._momentum_vec),
                                  dtype=np.float32)
        self._ss_step = np.array([self.step], dtype=np.uint64)
        return SharedState([
            TensorInfo.from_numpy("diloco.outer_params", self._ss_vec),
            TensorInfo.from_numpy("diloco.outer_momentum", self._ss_mom),
            TensorInfo.from_numpy("diloco.step", self._ss_step),
        ], revision=self.step)

    def sync_shared_state(
            self,
            strategy: SharedStateSyncStrategy = SharedStateSyncStrategy.ENFORCE_POPULAR):
        """Sync outer state with the group; adopt whatever wins the election
        into the outer vector / momentum / step. Returns the
        SharedStateSyncInfo (tx/rx bytes, revision); take the adopted params
        via self.params()."""
        assert self.comm is not None
        st = self.shared_state()
        info = self.comm.sync_shared_state(st, strategy)
        # adopt (possibly received) content
        self.step = int(self._ss_step[0])
        self._momentum_vec = jnp.asarray(self._ss_mom)
        self._outer_vec = jnp.asarray(self._ss_vec)
        return info


class AsyncDiloco(Diloco):
    """One-step-delayed DiLoCo: the reduce of outer step t overlaps with the
    inner compute of step t+1 (reference async_diloco.py,
    docs/md/07-.../03-AsyncDiloco.md:1-112).

    outer_step_async(inner_params) kicks the WAN reduce on a background
    thread and returns IMMEDIATELY with params to continue training from
    (the current outer params — the delayed update lands next call).
    Call .finish() (or the next outer_step_async) to join the in-flight
    reduce and apply it.
    """

    def __init__(self, comm, params, cfg: DilocoConfig = DilocoConfig()):
        super().__init__(comm, params, cfg)
        self._inflight: Optional[threading.Thread] = None
        self._inflight_host: Optional[np.ndarray] = None
        self._async_out: Optional[np.ndarray] = None  # pooled reduce output
        self._err: Optional[BaseException] = None
        # flat outer vector the inner phase started from (pseudo-gradient
        # baseline — before the delayed update from step t-1 lands)
        self._baseline: Optional[jax.Array] = None

    def _reduce_bg(self, host: np.ndarray, out: np.ndarray) -> None:
        try:
            if self.comm is not None:
                # out-of-place into the pooled buffer: skips the native
                # in-place snapshot memcpy (same win as the sync path)
                self._reduce_host(host, out=out)
            else:
                np.copyto(out, host)
        except BaseException as e:  # noqa: BLE001 — surfaced on join
            self._err = e

    def _join_inflight(self) -> None:
        if self._inflight is None:
            return
        self._inflight.join()
        self._inflight = None
        if self._err is not None:
            err, self._err = self._err, None
            self._inflight_host = None
            raise err
        self._inflight_host = None
        # NOT the fused _apply_tree_fn: the async path reads outer_params at
        # times decoupled from the join (sync_shared_state may adopt a new
        # vector in between, and donating callers need fresh buffers per
        # read), so a cached tree would be a staleness hazard for a minor
        # win in a phase that already overlaps inner compute.
        new_vec, self._momentum_vec = self._apply_fn(
            self._outer_vec, self._momentum_vec, jnp.asarray(self._async_out))
        self._outer_vec = self._applied = new_vec
        self.step += 1

    def outer_step_async(self, inner_params: Any) -> Any:
        """Apply the previous in-flight reduce (if any), launch the reduce of
        this step's pseudo-gradient, return params to continue from.

        Like the sync path, ``inner_params`` is CONSUMED (buffers donated
        into the pseudo-gradient); read any eval/logging values from it
        BEFORE this call and continue from the returned tree."""
        # the pseudo-gradient baseline is the outer vector the inner phase
        # STARTED from — before the delayed update from step t-1 lands
        # (reference async semantics, docs/md/07-.../03-AsyncDiloco.md)
        baseline = self._baseline if self._baseline is not None else self._outer_vec
        delta = self._delta_vec_fn(baseline, inner_params)
        host = np.array(jax.device_get(delta), dtype=np.float32)
        self._join_inflight()
        if self._async_out is None:
            self._async_out = np.empty(self.count, np.float32)
        # the pooled out buffer may still feed the apply just dispatched
        # (jnp.asarray can alias it zero-copy on the CPU backend) — the
        # background ring must not overwrite it until that apply lands
        if self._applied is not None:
            jax.block_until_ready(self._applied)
            self._applied = None
        self._inflight_host = host
        self._inflight = threading.Thread(target=self._reduce_bg,
                                          args=(host, self._async_out),
                                          daemon=True)
        self._inflight.start()
        self._baseline = self._outer_vec
        # fresh jit-output buffers: safe for a donating train step
        return self.outer_params

    def sync_shared_state(
            self,
            strategy: SharedStateSyncStrategy = SharedStateSyncStrategy.ENFORCE_POPULAR):
        """Land (or fail) the in-flight delayed update BEFORE the election so
        the offered state is self-consistent, and drop the pseudo-gradient
        baseline afterwards — adopted params invalidate it (the delta would
        otherwise include the whole sync jump)."""
        self._join_inflight()
        info = super().sync_shared_state(strategy)
        self._baseline = None
        return info

    def finish(self) -> Any:
        """Join any in-flight reduce and apply it; returns final outer params
        (fresh buffers, donation-safe)."""
        self._join_inflight()
        return self.outer_params
