"""Sharded training-step construction for the flagship GPT.

Builds a jitted SPMD train step over a Mesh: parameters laid out by the
tensor-parallel rules in mesh.py, batch sharded over dp, optimizer = AdamW
(optax). Gradients reduce over dp implicitly through XLA's SPMD partitioner —
inside a slice this rides ICI; across slices the DiLoCo outer loop
(pccl_tpu/parallel/diloco.py) moves pseudo-gradients over the CCoIP-style ring.

Reference parity: this replaces the torch training loops in
/root/reference/python/examples/ (train_pccl.py, sync_diloco.py) as the
in-slice compute engine.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import gpt
from . import mesh as mesh_lib


def make_train_state(key, cfg: gpt.GPTConfig, mesh, lr: float = 3e-4):
    """Init params + AdamW optimizer state, placed with TP/DP shardings."""
    param_sharding = mesh_lib.gpt_param_sharding(mesh)
    init = jax.jit(gpt.init_params, static_argnames=("cfg",),
                   out_shardings=param_sharding)
    params = init(key, cfg)
    tx = optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.1)
    opt_state = jax.jit(tx.init, out_shardings=None)(params)
    return params, tx, opt_state


def build_train_step(cfg: gpt.GPTConfig, tx, mesh, attn_fn=None,
                     seq_axis: str | None = None):
    """Returns jitted (params, opt_state, tokens, targets) -> (params, opt_state, loss).

    attn_fn: optional attention override (e.g. ring attention for sequence
    parallelism over `seq_axis`)."""
    param_sharding = mesh_lib.gpt_param_sharding(mesh)
    data_sharding = mesh_lib.batch_sharding(mesh, seq_axis=seq_axis)

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(gpt.loss_fn)(
            params, tokens, targets, cfg, attn_fn)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(param_sharding, None, data_sharding, data_sharding),
        out_shardings=(param_sharding, None, None),
        donate_argnums=(0, 1),
    )
