"""Sharded training-step construction for the model families.

Builds a jitted SPMD train step over a Mesh: parameters laid out by the
tensor-parallel rules in mesh.py (dispatched on the config's family — GPT or
Llama), batch sharded over dp, optimizer = AdamW (optax). Gradients reduce
over dp implicitly through XLA's SPMD partitioner — inside a slice this rides
ICI; across slices the DiLoCo outer loop (pccl_tpu/parallel/diloco.py) moves
pseudo-gradients over the CCoIP-style ring.

Reference parity: this replaces the torch training loops in
/root/reference/python/examples/ (train_pccl.py, sync_diloco.py) as the
in-slice compute engine.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import gpt, llama
from . import mesh as mesh_lib


def family(cfg):
    """(model module, param-sharding builder) for a config's family — the
    public dispatch examples and user loops should use."""
    if isinstance(cfg, llama.LlamaConfig):
        return llama, mesh_lib.llama_param_sharding
    return gpt, mesh_lib.gpt_param_sharding


def make_train_state(key, cfg, mesh, lr: float = 3e-4, schedule=None):
    """Init params + AdamW optimizer state, placed with TP/DP shardings.

    schedule: optional optax schedule (steps -> lr) used INSTEAD of the
    constant `lr` — e.g. cosine_warmup_schedule below (the reference
    loops' warmup + cosine decay, sync_diloco_fsdp.py:get_lr)."""
    model, sharding_fn = family(cfg)
    param_sharding = sharding_fn(mesh, cfg)
    init = jax.jit(model.init_params, static_argnames=("cfg",),
                   out_shardings=param_sharding)
    params = init(key, cfg)
    tx = optax.adamw(schedule if schedule is not None else lr,
                     b1=0.9, b2=0.95, weight_decay=0.1)
    opt_state = jax.jit(tx.init, out_shardings=None)(params)
    return params, tx, opt_state


def cosine_warmup_schedule(lr: float, total_steps: int,
                           warmup_steps: int = 0, min_lr: float = 0.0):
    """The reference loops' LR policy (linear warmup -> cosine decay to
    min_lr; /root/reference/python/examples/nanogpt_diloco/
    sync_diloco_fsdp.py:get_lr), as an optax schedule usable by
    make_train_state(schedule=...) — the schedule runs INSIDE the jitted
    step off the optimizer's step count, no host-side LR pokes."""
    warmup_steps = max(0, warmup_steps)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0 if warmup_steps else lr, peak_value=lr,
        warmup_steps=warmup_steps,
        # optax requires decay_steps > warmup_steps (the cosine part must
        # be non-empty) — warmup >= total collapses to warmup-then-min_lr
        decay_steps=max(warmup_steps + 1, total_steps), end_value=min_lr)


def accum_value_and_grad(base_lg, accum_steps: int):
    """Wrap a (params, tokens, targets) -> (loss, grads) function with
    scan-based microbatch accumulation: the wrapped function takes
    [A, B, T] tokens/targets, runs one microbatch's activations at a time
    under `lax.scan`, and accumulates grads in an fp32 tree. Loss and
    grads are the exact mean over all A·B sequences (CE is a per-sequence
    mean, so averaging A microbatch means equals the full-batch mean).
    Shapes are static under jit, so a data pipeline whose leading axis
    disagrees with `accum_steps` fails LOUDLY at trace time instead of
    silently mis-scaling gradients."""

    def fn(params, tokens, targets):
        a = tokens.shape[0]
        assert a == accum_steps, (
            f"got {a} microbatches, step was built for accum_steps="
            f"{accum_steps}")

        def micro(carry, tt):
            loss_sum, grad_acc = carry
            loss, grads = base_lg(params, tt[0], tt[1])
            grad_acc = jax.tree.map(
                lambda acc, g: acc + g.astype(jnp.float32), grad_acc, grads)
            return (loss_sum + loss, grad_acc), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (loss_sum, grads), _ = jax.lax.scan(
            micro, (jnp.zeros((), jnp.float32), zeros), (tokens, targets))
        return loss_sum / a, jax.tree.map(lambda g: g / a, grads)

    return fn


def build_train_step(cfg, tx, mesh, attn_fn=None,
                     seq_axis: str | None = None, remat: "bool | str" = False,
                     loss_chunk: "int | None" = None,
                     accum_steps: int = 1):
    """Returns jitted (params, opt_state, tokens, targets) -> (params, opt_state, loss).

    attn_fn: optional attention override (e.g. ring attention for sequence
    parallelism over `seq_axis`). remat: per-block activation checkpointing
    (models/_common.py:maybe_checkpoint) — True trades ~1/3 more FLOPs for
    O(1-layer) activation memory, the standard fit-big-batches move on a
    16 GB chip; "dots" saves weight-matmul outputs and recomputes only the
    rest (less recompute, more memory than True). loss_chunk: compute the
    vocab matmul + CE in recompute-checkpointed sequence chunks so the
    full [B, T, vocab] logits never exist (the T ≥ 32k memory enabler;
    models/_common.py:chunked_ce_loss).

    accum_steps: gradient accumulation (reference parity: the torch loops'
    gradient_accumulation_steps, e.g. sync_diloco_fsdp.py). With A > 1 the
    step takes tokens/targets shaped [A, B, T] — an EXPLICIT leading
    microbatch axis, scanned with `lax.scan` so one microbatch's
    activations are live at a time while per-microbatch grads accumulate
    in an fp32 tree; batch sharding applies to the B axis. Loss and grads
    are the exact mean over all A·B sequences (CE is a per-sequence mean,
    so averaging A microbatch means equals the full-batch mean — grads
    match a single [A·B, T] step bitwise up to reduction order)."""
    model, sharding_fn = family(cfg)
    param_sharding = sharding_fn(mesh, cfg)
    data_sharding = mesh_lib.batch_sharding(mesh, seq_axis=seq_axis)
    if accum_steps > 1:
        # [A, B, T]: microbatch axis unsharded, batch over dp as usual
        spec = data_sharding.spec
        data_sharding = NamedSharding(mesh, P(None, *spec))

    base_lg = jax.value_and_grad(
        lambda p, tok, tgt: model.loss_fn(p, tok, tgt, cfg, attn_fn, remat,
                                          loss_chunk))
    lg = accum_value_and_grad(base_lg, accum_steps) if accum_steps > 1 \
        else base_lg

    def step(params, opt_state, tokens, targets):
        loss, grads = lg(params, tokens, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(param_sharding, None, data_sharding, data_sharding),
        out_shardings=(param_sharding, None, None),
        donate_argnums=(0, 1),
    )
