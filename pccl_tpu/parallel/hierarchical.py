"""Hierarchical all-reduce: ICI inside the slice, the TCP ring across slices.

This is the TPU north star of the build (BASELINE.json, SURVEY.md §5
"Distributed communication backend"): each TPU slice is ONE logical peer of
the CCoIP-style ring. The reference has no equivalent — its peers are single
CUDA hosts — so this module is new design, not a port.

Data path for a global all-reduce of a sharded array tree:

  1. **intra-slice reduce (ICI, jitted)** — if the tree carries a
     data-parallel axis to fold (e.g. per-device gradients under shard_map),
     a `psum`/mean over the mesh axis runs on-device; for trees produced by
     an SPMD `jit` step the gradients are already slice-reduced and this is
     the identity.
  2. **host staging** — the fp32 flat vector (codec.build_codec) is fetched
     once per slice. With `jax.sharding`, `device_get` of a fully-addressable
     array performs the gather over ICI, not over PCIe per-shard.
  3. **inter-slice ring (DCN)** — this process, acting as its slice's one
     peer, runs the fault-tolerant ring all-reduce with optional on-the-wire
     quantization (the reference's piquant path over WAN).
  4. **broadcast back (ICI)** — `device_put` with the original sharding lays
     the result back out across the slice; every device receives identical
     bytes, preserving the bit-parity invariant the shared-state machinery
     depends on (reference simplehash design, SURVEY.md §2 #13).

Fault tolerance: ConnectionLost/Aborted → update_topology() → retry, same
contract as the flat ring (reference README.md:90-130).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..comm import Communicator, DataType, QuantizationAlgorithm
from .codec import build_codec, leaf_shardings, restore_shardings
from .ring import avg_all_reduce_windowed


def local_mean(tree: Any, mesh, axis: str = "dp") -> Any:
    """Explicit intra-slice mean over a mesh axis via shard_map + psum.

    Each leaf's LEADING dim is the per-device stack (length = mesh axis
    size × k); the output folds it away: [n·k, ...] → [k, ...] holding the
    mean, replicated. Only needed when the caller holds per-device values
    OUTSIDE an SPMD jit step; gradients from a jitted step are already
    reduced by XLA."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]

    def _mean(x):
        return jax.lax.psum(x, axis) / n

    fn = jax.shard_map(lambda t: jax.tree.map(_mean, t), mesh=mesh,
                       in_specs=P(axis), out_specs=P())
    return fn(tree)


class HierarchicalAllReduce:
    """Slice-as-one-peer global averaging.

    Usage (one process per slice)::

        h = HierarchicalAllReduce(comm, grads_template)
        grads = h.all_reduce(grads)       # global mean across all slices

    `comm=None` degrades to the single-slice case (identity), so the same
    training loop runs on one slice or many.
    """

    def __init__(self, comm: Optional[Communicator], template: Any, *,
                 quantization: QuantizationAlgorithm = QuantizationAlgorithm.NONE,
                 quantized_dtype: DataType = DataType.UINT8,
                 max_retries: int = 16, shm_staging: bool = False,
                 windows: int = 1):
        self.comm = comm
        self.quantization = quantization
        self.quantized_dtype = quantized_dtype
        self.max_retries = max_retries
        # windows>1: split the reduce into concurrent tagged collectives
        # (ring.avg_all_reduce_windowed) to saturate fat pipes
        self.windows = windows
        # shm_staging: stage the flat vector in a registered shm buffer so
        # same-host slices ring-reduce zero-copy (one extra copy per reduce;
        # see DilocoConfig.shm_staging for the trade-off)
        self.shm_staging = shm_staging
        self._shm_stage = None
        self._codec = build_codec(template)
        # sharding of the template leaves, reapplied on the way back
        self._shardings = leaf_shardings(template)

    @property
    def count(self) -> int:
        return self._codec.count

    def _ring_avg(self, vec: np.ndarray) -> int:
        assert self.comm is not None
        return avg_all_reduce_windowed(
            self.comm, vec, windows=self.windows,
            quantization=self.quantization,
            quantized_dtype=self.quantized_dtype, max_retries=self.max_retries)

    def all_reduce(self, tree: Any) -> Any:
        """Global mean of `tree` across slices. Returns a tree with the
        original dtypes and shardings."""
        vec = self._codec.flat(tree)
        if self.comm is None:
            return self._codec.unflat(vec)
        # np.asarray: device_get already yields a host ndarray — a second
        # np.array copy would cost another params-sized memcpy per reduce
        host = np.asarray(jax.device_get(vec), dtype=np.float32)
        # quantized rings send from quantize scratch, not the staged buffer —
        # shm staging would be a pure extra copy there (see DilocoConfig)
        if self.shm_staging and self.quantization == QuantizationAlgorithm.NONE:
            if self._shm_stage is None:
                from pccl_tpu.comm.api import shm_ndarray

                self._shm_stage = shm_ndarray(self._codec.count, np.float32)
            np.copyto(self._shm_stage, host)
            host = self._shm_stage  # same-host slices reduce zero-copy
        elif not host.flags["WRITEABLE"] or not host.flags["C_CONTIGUOUS"]:
            host = np.array(host, dtype=np.float32)  # ring reduces in place
        self._ring_avg(host)
        out = self._codec.unflat(jnp.asarray(host))
        return restore_shardings(out, self._shardings)
