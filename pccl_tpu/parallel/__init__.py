from . import mesh  # noqa: F401
from .codec import PytreeCodec, build_codec  # noqa: F401


def __getattr__(name):
    # defer optax / ..models / ..comm imports until first use
    if name in ("diloco", "hierarchical", "train"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
