"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

Capability beyond the reference (SURVEY.md §2.3: the reference has no
TP/PP/SP anywhere); on TPU the layer-stacked GPT (pccl_tpu.models.gpt keeps
per-layer params stacked on a leading [n_layer] dim precisely so the block
stack is `lax.scan`-shaped) pipelines naturally: shard the layer dim over a
`pp` mesh axis and rotate activations stage-to-stage with `lax.ppermute`.

Schedule: plain GPipe. With S stages and M microbatches, the loop runs
M + S - 1 ticks; at each tick every stage runs its local layer chunk on the
activation it holds, then passes it to the next stage. Stage 0 feeds a new
microbatch per tick; stage S-1 emits a finished microbatch per tick (after
the S-1-tick fill bubble). Bubble fraction = (S-1)/(M+S-1) — pick M >= S.
The tick loop is a `lax.scan`, so the whole pipeline is differentiable and
the backward pass is the reverse pipeline, scheduled by XLA.

Collectives ride ICI: `ppermute` only ever touches neighboring stages.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import gpt


def pipeline_spec(mesh: Mesh, axis: str = "pp"):
    """Sharding for the stacked per-layer param tree: leading layer dim over
    `axis`, other dims replicated (composable with tp by extending specs)."""
    def spec_of(leaf):
        return NamedSharding(mesh, P(axis, *([None] * (leaf.ndim - 1))))
    return spec_of


def shard_layer_params(layers: Any, mesh: Mesh, axis: str = "pp") -> Any:
    """Place a stacked layer tree ([L, ...] leaves) with L over `axis`."""
    sp = pipeline_spec(mesh, axis)
    return jax.tree.map(lambda l: jax.device_put(l, sp(l)), layers)


def _run_local_stack(layers_local: Any, x: jax.Array, cfg: gpt.GPTConfig,
                     attn_fn) -> jax.Array:
    """One stage's chunk of the block stack: scan over the local layers."""
    def body(h, layer):
        return gpt._block(h, layer, cfg, attn_fn), None

    out, _ = lax.scan(body, x, layers_local)
    return out


def pipeline_blocks(x: jax.Array, layers: Any, cfg: gpt.GPTConfig, mesh: Mesh,
                    *, axis: str = "pp", microbatches: int = 0,
                    attn_fn=None) -> jax.Array:
    """Run the transformer block stack pipelined over mesh axis `axis`.

    x: [B, T, d] activations (replicated over `axis`); layers: stacked tree
    with leading [n_layer] dims sharded over `axis`. Returns [B, T, d].
    microbatches=0 picks the stage count (minimum bubble-free choice)."""
    S = mesh.shape[axis]
    B = x.shape[0]
    M = microbatches or S
    assert B % M == 0, f"batch {B} not divisible by {M} microbatches"
    assert cfg.n_layer % S == 0, f"{cfg.n_layer} layers not divisible by {S} stages"
    mb = B // M

    xs = x.reshape(M, mb, *x.shape[1:])
    # pad with bubble inputs for the drain ticks
    pad = jnp.zeros((S - 1, mb, *x.shape[1:]), x.dtype)
    xs_padded = jnp.concatenate([xs, pad], axis=0) if S > 1 else xs

    def per_stage(layers_local, xs_padded):
        stage = lax.axis_index(axis)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(cur, t):
            # pass last tick's outputs forward; stage 0 takes microbatch t
            prev = lax.ppermute(cur, axis, perm)
            fed = lax.dynamic_index_in_dim(xs_padded, t, 0, keepdims=False)
            inp = jnp.where(stage == 0, fed, prev)
            out = _run_local_stack(layers_local, inp, cfg, attn_fn)
            return out, out

        cur0 = jnp.zeros((mb, *xs_padded.shape[2:]), x.dtype)
        if hasattr(lax, "pcast"):
            cur0 = lax.pcast(cur0, axis, to="varying")
        elif hasattr(lax, "pvary"):
            cur0 = lax.pvary(cur0, (axis,))  # older JAX varying-axes tracking
        _, ys = lax.scan(tick, cur0, jnp.arange(M + S - 1))
        # microbatch m finishes on the LAST stage at tick m + S - 1
        done = lax.dynamic_slice_in_dim(ys, S - 1, M, axis=0)
        # replicate the result: only stage S-1 holds real outputs
        done = jnp.where(stage == S - 1, done, jnp.zeros_like(done))
        return lax.psum(done, axis)

    fn = jax.shard_map(
        per_stage, mesh=mesh,
        in_specs=(P(axis), P()), out_specs=P(),
        axis_names={axis},
    )
    out = fn(layers, xs_padded)
    return out.reshape(B, *x.shape[1:])


def build_pipelined_forward(cfg: gpt.GPTConfig, mesh: Mesh, *,
                            axis: str = "pp", microbatches: int = 0,
                            attn_fn=None) -> Callable:
    """(params, tokens) -> logits with the block stack pipelined over `axis`.

    Embedding, final norm and the tied head stay replicated (they are a
    small fraction of compute); per-layer params must be sharded with
    shard_layer_params. Compose under an outer jit."""
    def forward(params, tokens):
        x = params["tok_emb"][tokens].astype(cfg.compute_dtype)
        layers = {k: params[k] for k in gpt._LAYER_KEYS}
        x = pipeline_blocks(x, layers, cfg, mesh, axis=axis,
                            microbatches=microbatches, attn_fn=attn_fn)
        x = gpt._rmsnorm(x, params["lnf_g"])
        return x.astype(jnp.float32) @ params["tok_emb"].T.astype(jnp.float32)

    return forward
