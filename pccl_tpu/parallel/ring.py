"""Shared fault-tolerance policy for WAN-ring collectives.

One implementation of the reference's retry contract (README.md:90-130):
ConnectionLost/Aborted → update_topology() → retry with the surviving world;
TooFewPeers → the caller is alone and the reduce degenerates to identity.
Used by both DiLoCo and the hierarchical all-reduce.
"""

from __future__ import annotations

import numpy as np

from ..comm import (
    Communicator,
    ConnectionLostError,
    DataType,
    OperationAbortedError,
    QuantizationAlgorithm,
    ReduceOp,
    Result,
    TooFewPeersError,
)


def avg_all_reduce_with_retry(
        comm: Communicator, vec: np.ndarray, *, out: np.ndarray = None,
        quantization: QuantizationAlgorithm = QuantizationAlgorithm.NONE,
        quantized_dtype: DataType = DataType.UINT8,
        max_retries: int = 16) -> int:
    """AVG all-reduce `vec` over the ring, retrying across peer churn.
    With `out`, the reduce runs out-of-place into it — the native ring then
    skips its in-place abort-restore backup (a full params-sized memcpy per
    op) and `vec` is left untouched. Returns the world size that completed
    the reduce (1 = alone)."""
    recv = vec if out is None else out
    for _ in range(max_retries):
        try:
            info = comm.all_reduce(vec, recv, op=ReduceOp.AVG,
                                   quantization=quantization,
                                   quantized_dtype=quantized_dtype)
            return info.world_size
        except (ConnectionLostError, OperationAbortedError):
            # world shrank mid-op; the native core restored the recv buffer
            # from the untouched send — adopt the survivor ring and go again
            comm.update_topology()
        except TooFewPeersError:
            if recv is not vec:
                np.copyto(recv, vec)  # alone: the reduction is the input
            return 1
    raise ConnectionLostError(
        Result.CONNECTION_LOST,
        f"all_reduce failed after {max_retries} retries")


# below this, windowing costs more in per-op overhead than it buys in
# concurrency (each window is its own tagged collective with its own
# consensus round)
_MIN_WINDOW_ELEMS = 1 << 20


def avg_all_reduce_windowed(
        comm: Communicator, vec: np.ndarray, *, windows: int = 1,
        out: np.ndarray = None,
        quantization: QuantizationAlgorithm = QuantizationAlgorithm.NONE,
        quantized_dtype: DataType = DataType.UINT8,
        max_retries: int = 16) -> int:
    """AVG all-reduce `vec` in place, split into `windows` concurrent
    tagged collectives over the connection pool (the reference's
    pcclAllReduceMultipleWithRetry recipe — its DiLoCo loop reduces
    per-parameter tensors concurrently to saturate fat pipes; here the flat
    vector is windowed instead). windows<=1 or a small vec degrades to the
    single-op path. Returns the smallest world size any window completed
    with (1 = alone). On churn mid-batch, completed windows stand (averaged
    over the old world) while failed ones retry over the survivors — the
    same mixed-world semantics the reference's retry loop has.

    max_retries only bounds the single-op path: the windowed path uses the
    native MultipleWithRetry policy, which retries failed windows until
    they succeed or the caller is alone (the reference's unbounded
    contract)."""
    windows = min(windows, max(1, vec.size // _MIN_WINDOW_ELEMS))
    if windows <= 1:
        return avg_all_reduce_with_retry(
            comm, vec, out=out, quantization=quantization,
            quantized_dtype=quantized_dtype, max_retries=max_retries)
    if out is not None:
        # the MultipleWithRetry band reduces in place; land the batch in
        # `out` so the caller's contract (result in out, vec untouched)
        # holds — at the cost of one staging copy
        np.copyto(out, vec)
        vec = out
    views = np.array_split(vec, windows)  # contiguous views into vec
    try:
        infos = comm.all_reduce_multiple_with_retry(
            views, op=ReduceOp.AVG, quantization=quantization,
            quantized_dtype=quantized_dtype)
        return min(i.world_size for i in infos)
    except TooFewPeersError:
        return 1
