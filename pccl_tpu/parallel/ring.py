"""Shared fault-tolerance policy for WAN-ring collectives.

One implementation of the reference's retry contract (README.md:90-130):
ConnectionLost/Aborted → update_topology() → retry with the surviving world;
TooFewPeers → the caller is alone and the reduce degenerates to identity.
Used by both DiLoCo and the hierarchical all-reduce.
"""

from __future__ import annotations

import numpy as np

from ..comm import (
    Communicator,
    ConnectionLostError,
    DataType,
    OperationAbortedError,
    QuantizationAlgorithm,
    ReduceOp,
    Result,
    TooFewPeersError,
)


def avg_all_reduce_with_retry(
        comm: Communicator, vec: np.ndarray, *,
        quantization: QuantizationAlgorithm = QuantizationAlgorithm.NONE,
        quantized_dtype: DataType = DataType.UINT8,
        max_retries: int = 16) -> int:
    """AVG all-reduce `vec` in place over the ring, retrying across peer
    churn. Returns the world size that completed the reduce (1 = alone)."""
    for _ in range(max_retries):
        try:
            info = comm.all_reduce(vec, op=ReduceOp.AVG,
                                   quantization=quantization,
                                   quantized_dtype=quantized_dtype)
            return info.world_size
        except (ConnectionLostError, OperationAbortedError):
            # world shrank mid-op; the native core restored the src buffer —
            # adopt the survivor ring and go again
            comm.update_topology()
        except TooFewPeersError:
            return 1
    raise ConnectionLostError(
        Result.CONNECTION_LOST,
        f"all_reduce failed after {max_retries} retries")
