"""Device-mesh construction and sharding rules.

This is the in-slice half of the framework's parallelism story: inside one TPU
slice, scaling is expressed as `jax.sharding` annotations over a `Mesh` and XLA
inserts the ICI collectives (psum / all-gather / reduce-scatter). Across
slices, the CCoIP-equivalent WAN ring (pccl_tpu.comm) carries the traffic —
see pccl_tpu/parallel/hierarchical.py.

Capability parity note: the reference's only parallelism dimensions are
data-parallel peers and peer groups (SURVEY.md §2.3 — e.g. FSDP×PCCL grid in
/root/reference/docs/md/8_CommonFootguns.md). The TPU build adds in-slice
tensor/sequence sharding because on TPU that is how a "peer" (slice) reaches
its compute roofline.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def factor_mesh(n: int, n_axes: int = 2) -> Tuple[int, ...]:
    """Factor n devices into a balanced (dp, tp, ...) shape, dp first."""
    dims = [1] * n_axes
    rem = n
    # greedily pull factors of 2 into tp (last axis) then dp
    i = n_axes - 1
    while rem % 2 == 0 and dims[i] < 8:
        dims[i] *= 2
        rem //= 2
        if dims[i] >= 4:
            i = max(0, i - 1)
    dims[0] *= rem
    return tuple(dims)


def make_mesh(devices: Sequence[jax.Device] | None = None,
              axis_names: Tuple[str, ...] = ("dp", "tp"),
              shape: Tuple[int, ...] | None = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = factor_mesh(len(devices), len(axis_names))
    arr = np.array(devices[: int(np.prod(shape))]).reshape(shape)
    return Mesh(arr, axis_names)


# --- GPT sharding rules (keyed to pccl_tpu.models.gpt.init_params layout) ---

GPT_PARAM_SPECS: Dict[str, P] = {
    # vocab-parallel embedding (megatron-style); head is the transpose
    "tok_emb": P("tp", None),
    "ln1_g": P(None, None),
    "ln2_g": P(None, None),
    # column-parallel in-projections: shard output features over tp
    "attn_qkv": P(None, None, "tp"),
    "mlp_in": P(None, None, "tp"),
    # row-parallel out-projections: shard input features over tp
    "attn_out": P(None, "tp", None),
    "mlp_out": P(None, "tp", None),
    "lnf_g": P(None),
}

# present only when GPTConfig.untie_head (the tied-head ablation)
_GPT_HEAD_SPEC = P(None, "tp")  # vocab-parallel, like llama's


def _drop_missing_axes(spec: P, mesh: Mesh) -> P:
    """Replace axis names absent from `mesh` with None (replicated)."""
    return P(*[a if (a in mesh.shape) else None for a in spec])


def gpt_param_sharding(mesh: Mesh, cfg=None) -> Dict[str, NamedSharding]:
    specs = dict(GPT_PARAM_SPECS)
    if cfg is not None and getattr(cfg, "untie_head", False):
        specs["head"] = _GPT_HEAD_SPEC
    return {k: NamedSharding(mesh, _drop_missing_axes(spec, mesh))
            for k, spec in specs.items()}


# --- Llama sharding rules (pccl_tpu.models.llama.init_params layout) ---

LLAMA_PARAM_SPECS: Dict[str, P] = {
    "tok_emb": P("tp", None),
    "ln1_g": P(None, None),
    "ln2_g": P(None, None),
    # column-parallel in-projections (q, grouped kv, both MLP branches)
    "attn_q": P(None, None, "tp"),
    "attn_kv": P(None, None, "tp"),
    "mlp_gate": P(None, None, "tp"),
    "mlp_up": P(None, None, "tp"),
    # row-parallel out-projections
    "attn_out": P(None, "tp", None),
    "mlp_down": P(None, "tp", None),
    "lnf_g": P(None),
    "head": P(None, "tp"),  # untied unembedding: vocab-parallel
}


def llama_param_sharding(mesh: Mesh, cfg=None) -> Dict[str, NamedSharding]:
    return {k: NamedSharding(mesh, _drop_missing_axes(spec, mesh))
            for k, spec in LLAMA_PARAM_SPECS.items()}


def batch_sharding(mesh: Mesh, seq_axis: str | None = None) -> NamedSharding:
    """Tokens [B, T]: batch over dp, optionally sequence over `seq_axis`."""
    return NamedSharding(mesh, _drop_missing_axes(P("dp", seq_axis), mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
