"""Pytree ↔ flat fp32 vector codec, jitted.

Both DiLoCo (pseudo-gradients) and the hierarchical ICI+WAN all-reduce move
pytrees over the TCP ring as ONE contiguous fp32 buffer: fewer wire tags and
larger chunks keep the ring pipeline full, and XLA fuses the
flatten/unflatten with neighboring device computation.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class PytreeCodec(NamedTuple):
    flat_delta: Callable[[Any, Any], jax.Array]  # (outer, inner) -> fp32 vec
    flat: Callable[[Any], jax.Array]             # tree -> fp32 vec
    unflat: Callable[[jax.Array], Any]           # fp32 vec -> tree
    count: int
    # (outer_vec, inner_tree) -> fp32 delta vec: the form DiLoCo wants when
    # the outer state is held flat — one flatten instead of two, and no
    # tree materialization of the outer side at all
    flat_delta_vec: Callable[[jax.Array, Any], jax.Array] = None


def leaf_shardings(tree: Any) -> Any:
    """Tree of per-leaf shardings (None for leaves without one)."""
    return jax.tree.map(
        lambda l: l.sharding if hasattr(l, "sharding") else None, tree)


def restore_shardings(tree: Any, shardings: Any) -> Any:
    """Lay `tree` back out with `shardings` captured via leaf_shardings."""
    return jax.tree.map(
        lambda l, s: jax.device_put(l, s) if s is not None else l,
        tree, shardings, is_leaf=lambda x: x is None)


def build_codec(template: Any) -> PytreeCodec:
    """Build jitted flatten/unflatten functions shaped to `template`."""
    leaves, treedef = jax.tree.flatten(template)
    sizes = [int(np.prod(l.shape)) if l.shape else 1 for l in leaves]
    total = int(sum(sizes))
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]

    def _flat_delta(outer, inner):
        ls_o = jax.tree.leaves(outer)
        ls_i = jax.tree.leaves(inner)
        parts = [(o.astype(jnp.float32) - i.astype(jnp.float32)).reshape(-1)
                 for o, i in zip(ls_o, ls_i)]
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    def _flat(tree):
        parts = [l.astype(jnp.float32).reshape(-1) for l in jax.tree.leaves(tree)]
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    def _unflat(vec):
        out = []
        off = 0
        for sz, shp, dt in zip(sizes, shapes, dtypes):
            out.append(vec[off:off + sz].reshape(shp).astype(dt))
            off += sz
        return jax.tree.unflatten(treedef, out)

    def _flat_delta_vec(outer_vec, inner):
        return outer_vec - _flat(inner)

    # flat_delta_vec donates the INNER tree: it is dead the moment the
    # pseudo-gradient exists (DiLoCo callers continue from outer_step's
    # return), and donation lets XLA back the delta with inner's buffers.
    # This matters at scale: on the CPU backend a fresh multi-GB output
    # costs ~25x the op itself in allocation/fault pathology (measured:
    # 0.6 s donated vs 22 s fresh for a 2 GB subtract) — donation is the
    # difference between a 1B-param outer step working and crawling.
    # A caller that reuses the tree after outer_step gets jax's loud
    # "Array has been deleted", not silent corruption.
    return PytreeCodec(jax.jit(_flat_delta), jax.jit(_flat), jax.jit(_unflat),
                       total, jax.jit(_flat_delta_vec, donate_argnums=(1,)))
