"""Llama-family decoder — the second model family of pccl_tpu.

The reference exercises its library with one model family (nanoGPT,
/root/reference/python/examples/nanogptddp/train_pccl.py); this adds the
other architecture modern open-weight training actually runs — grouped-query
attention, SwiGLU MLPs, untied unembedding — built on the same TPU-first
substrate as models/gpt.py:

- stacked per-layer arrays under `lax.scan` (one traced layer body),
- bfloat16 compute on the MXU with fp32 norms/params,
- rotary embeddings, causal iota masking, static shapes,
- tensor-parallel weight layouts keyed the same way as GPT's
  (column-parallel in-projections, row-parallel out-projections; see
  mesh.LLAMA_PARAM_SPECS).

GQA is native end to end (round 5): K/V stay Hkv-shaped from the kv
projection through the attention op — the flash kernels and the ring
path consume grouped K/V directly (head mapping lives in the kernels'
BlockSpec index maps, ops/flash_attention.py), so HBM never holds a
repeated K/V tensor and the architecture's KV-bytes advantage survives
exactly where it matters, long context. Only the plain-jnp fallback
`_attention` repeats internally (correctness path, CPU CI).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ._common import chunked_ce_loss, gather_ce_loss, scan_blocks


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layer: int = 8
    n_head: int = 8
    n_kv_head: int = 4          # grouped-query: kv heads < query heads
    n_embd: int = 512
    ffn_dim: int = 1408         # SwiGLU hidden (≈ 8/3 · d, rounded to 64)
    block_size: int = 1024
    rope_theta: float = 500000.0
    compute_dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        assert self.n_embd % self.n_head == 0
        return self.n_embd // self.n_head

    def __post_init__(self):
        assert self.n_head % self.n_kv_head == 0


def _init_linear(key, fan_in: int, shape) -> jax.Array:
    std = 1.0 / math.sqrt(fan_in)
    return jax.random.normal(key, shape, dtype=jnp.float32) * std


def init_params(key: jax.Array, cfg: LlamaConfig) -> Dict[str, jax.Array]:
    """Parameter pytree; per-layer tensors carry a leading [n_layer] dim."""
    d, L, Dh = cfg.n_embd, cfg.n_layer, cfg.head_dim
    kv = cfg.n_kv_head * Dh
    ks = jax.random.split(key, 9)
    scale_res = 1.0 / math.sqrt(2 * L)
    return {
        "tok_emb": jax.random.normal(ks[0], (cfg.vocab_size, d), jnp.float32) * 0.02,
        "ln1_g": jnp.ones((L, d), jnp.float32),
        "ln2_g": jnp.ones((L, d), jnp.float32),
        "attn_q": _init_linear(ks[1], d, (L, d, d)),                # column parallel
        "attn_kv": _init_linear(ks[2], d, (L, d, 2 * kv)),          # column parallel
        "attn_out": _init_linear(ks[3], d, (L, d, d)) * scale_res,  # row parallel
        "mlp_gate": _init_linear(ks[4], d, (L, d, cfg.ffn_dim)),    # column parallel
        "mlp_up": _init_linear(ks[5], d, (L, d, cfg.ffn_dim)),      # column parallel
        "mlp_down": _init_linear(ks[6], cfg.ffn_dim,
                                 (L, cfg.ffn_dim, d)) * scale_res,  # row parallel
        "lnf_g": jnp.ones((d,), jnp.float32),
        "head": _init_linear(ks[7], d, (d, cfg.vocab_size)),        # untied
    }


def _rmsnorm(x: jax.Array, gain: jax.Array) -> jax.Array:
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (y * gain).astype(x.dtype)


def _rope(x: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over the last dim. x: [B, T, H, Dh]."""
    _, T, _, Dh = x.shape
    half = Dh // 2
    freqs = jnp.exp(-math.log(theta) * jnp.arange(half, dtype=jnp.float32) / half)
    ang = jnp.arange(T, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def _attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Causal attention fallback. q: [B, T, H, Dh], k/v: [B, T, Hkv, Dh]
    (GQA folded here by repeating — the kernel paths never do)."""
    _, T, H, Dh = q.shape
    if k.shape[2] != H:
        k = jnp.repeat(k, H // k.shape[2], axis=2)
        v = jnp.repeat(v, H // v.shape[2], axis=2)
    scale = 1.0 / math.sqrt(Dh)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    qi = lax.broadcasted_iota(jnp.int32, (T, T), 0)
    ki = lax.broadcasted_iota(jnp.int32, (T, T), 1)
    logits = jnp.where(ki <= qi, logits.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block(x: jax.Array, layer: Dict[str, jax.Array], cfg: LlamaConfig,
           attn_fn=None) -> jax.Array:
    B, T, d = x.shape
    H, Hkv, Dh = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    h = _rmsnorm(x, layer["ln1_g"])
    q = (h @ layer["attn_q"].astype(h.dtype)).reshape(B, T, H, Dh)
    kvp = h @ layer["attn_kv"].astype(h.dtype)  # [B, T, 2·Hkv·Dh]
    k, v = jnp.split(kvp, 2, axis=-1)
    k = k.reshape(B, T, Hkv, Dh)
    v = v.reshape(B, T, Hkv, Dh)
    q = _rope(q, cfg.rope_theta)
    k = _rope(k, cfg.rope_theta)
    # K/V go to attn_fn Hkv-shaped: the flash/ring kernels are GQA-native
    # (kv-head mapping in their index maps), so no repeated K/V ever
    # exists in HBM; jnp fallbacks repeat internally for correctness only
    att = (attn_fn or _attention)(q, k, v).reshape(B, T, d)
    x = x + att @ layer["attn_out"].astype(att.dtype)
    h = _rmsnorm(x, layer["ln2_g"])
    gated = jax.nn.silu(h @ layer["mlp_gate"].astype(h.dtype)) * \
        (h @ layer["mlp_up"].astype(h.dtype))
    return x + gated @ layer["mlp_down"].astype(h.dtype)


_LAYER_KEYS = ("ln1_g", "ln2_g", "attn_q", "attn_kv", "attn_out",
               "mlp_gate", "mlp_up", "mlp_down")


def hidden(params: Dict[str, jax.Array], tokens: jax.Array, cfg: LlamaConfig,
           attn_fn=None, remat: "bool | str" = False) -> jax.Array:
    """tokens: int32 [B, T] → final-norm hidden states [B, T, d] (the
    pre-head activations; forward() applies the vocab matmul)."""
    x = params["tok_emb"][tokens].astype(cfg.compute_dtype)
    layers = {k: params[k] for k in _LAYER_KEYS}
    x = scan_blocks(lambda h, layer: _block(h, layer, cfg, attn_fn),
                    x, layers, remat)
    return _rmsnorm(x, params["lnf_g"])


def forward(params: Dict[str, jax.Array], tokens: jax.Array, cfg: LlamaConfig,
            attn_fn=None, remat: "bool | str" = False) -> jax.Array:
    """tokens: int32 [B, T] → logits float32 [B, T, vocab].

    remat: checkpoint each block (see models/gpt.py:forward)."""
    x = hidden(params, tokens, cfg, attn_fn, remat)
    # untied head: bf16 operands on the MXU, fp32 accumulation (see gpt.py)
    return jnp.matmul(x, params["head"].astype(x.dtype),
                      preferred_element_type=jnp.float32)


def loss_fn(params, tokens, targets, cfg: LlamaConfig, attn_fn=None,
            remat: "bool | str" = False,
            loss_chunk: "int | None" = None) -> jax.Array:
    """Mean next-token CE; loss_chunk chunks the vocab matmul + CE with
    recompute checkpointing (models/_common.py:chunked_ce_loss) so the
    full [B, T, vocab] logits never exist — the T ≥ 32768 enabler. Must
    divide T (raises rather than silently running the full-logits path
    into an opaque OOM)."""
    T = targets.shape[1]
    if loss_chunk and T % loss_chunk:
        raise ValueError(f"loss_chunk {loss_chunk} must divide T={T}")
    if loss_chunk and T > loss_chunk:
        x = hidden(params, tokens, cfg, attn_fn, remat)
        return chunked_ce_loss(x, params["head"], targets, loss_chunk)
    logits = forward(params, tokens, cfg, attn_fn, remat=remat)
    return gather_ce_loss(logits, targets)


@partial(jax.jit, static_argnames=("cfg",))
def forward_jit(params, tokens, cfg: LlamaConfig):
    return forward(params, tokens, cfg)


def tiny_config(**overrides) -> LlamaConfig:
    base = dict(vocab_size=512, n_layer=2, n_head=4, n_kv_head=2, n_embd=128,
                ffn_dim=320, block_size=128)
    base.update(overrides)
    return LlamaConfig(**base)


# ladder roughly tracking the open-weight llama-class shapes
PRESETS = {
    # nano: the examples' CI default — 2-peer loopback convergence fits a
    # single-core test budget (mirrors gpt.PRESETS["nano"])
    "nano": dict(vocab_size=256, n_layer=2, n_head=4, n_kv_head=2, n_embd=64,
                 ffn_dim=192, block_size=64),
    "tiny": dict(vocab_size=512, n_layer=2, n_head=4, n_kv_head=2, n_embd=128,
                 ffn_dim=320, block_size=128),
    # 700m: the largest rung whose fp32 AdamW state (params + 2 moments +
    # transient grads ≈ 11 GB) fits a single 16 GB v5e chip with headroom —
    # the single-chip benchmark shape. head_dim 128 keeps the MXU tiled.
    "700m": dict(vocab_size=32000, n_layer=24, n_head=12, n_kv_head=4,
                 n_embd=1536, ffn_dim=4096, block_size=2048),
    "1b": dict(vocab_size=32000, n_layer=16, n_head=32, n_kv_head=8,
               n_embd=2048, ffn_dim=5632, block_size=2048),
    "7b": dict(vocab_size=32000, n_layer=32, n_head=32, n_kv_head=32,
               n_embd=4096, ffn_dim=11008, block_size=4096),
    "8b": dict(vocab_size=128256, n_layer=32, n_head=32, n_kv_head=8,
               n_embd=4096, ffn_dim=14336, block_size=8192,
               rope_theta=500000.0),
}


def named_config(name: str, **overrides) -> LlamaConfig:
    base = dict(PRESETS[name])
    base.update(overrides)
    return LlamaConfig(**base)
