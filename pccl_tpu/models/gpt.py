"""Decoder-only transformer — the flagship model of pccl_tpu.

Capability parity: the reference library is exercised end-to-end by nanoGPT
training loops (/root/reference/python/examples/nanogptddp/train_pccl.py,
/root/reference/python/examples/nanogpt_diloco/sync_diloco.py). This module is
the TPU-native equivalent model those loops train — written jax-first rather
than as a torch translation:

- parameters are a flat pytree of stacked per-layer arrays and the block stack
  runs under `lax.scan`, so XLA traces ONE layer body regardless of depth
  (fast compiles, and the natural substrate for pipeline parallelism);
- compute in bfloat16 on the MXU, parameters/accumulators in float32;
- rotary position embeddings (no learned position table to shard);
- static shapes everywhere; causal masking via iota comparison inside the
  attention body (no materialized [T, T] python-side mask objects);
- tensor-parallel friendly weight layouts: attention QKV / MLP in-projections
  are "column parallel" (shard output features), output projections are
  "row parallel" (shard input features). See pccl_tpu/parallel/mesh.py for
  the sharding rules keyed by these names.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ._common import chunked_ce_loss, gather_ce_loss, scan_blocks


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    block_size: int = 1024
    dropout: float = 0.0  # dropout is a no-op under jit benchmarking; kept for parity
    rope_theta: float = 10000.0
    compute_dtype: Any = jnp.bfloat16
    # untie the unembedding from tok_emb (GPT-2 ties them; the untied
    # variant exists to ABLATE the tied head's backward — tok_emb's grad
    # is then a pure embedding scatter instead of scatter + dense matmul
    # grad fused into one accumulation). See docs/08_performance.md.
    untie_head: bool = False

    @property
    def head_dim(self) -> int:
        assert self.n_embd % self.n_head == 0
        return self.n_embd // self.n_head


def _init_linear(key, fan_in: int, shape) -> jax.Array:
    std = 1.0 / math.sqrt(fan_in)
    return jax.random.normal(key, shape, dtype=jnp.float32) * std


def init_params(key: jax.Array, cfg: GPTConfig) -> Dict[str, jax.Array]:
    """Parameter pytree. Per-layer tensors carry a leading [n_layer] dim."""
    d, L = cfg.n_embd, cfg.n_layer
    ks = jax.random.split(key, 8)
    scale_res = 1.0 / math.sqrt(2 * L)  # GPT-2 style residual scaling
    params = {
        "tok_emb": jax.random.normal(ks[0], (cfg.vocab_size, d), jnp.float32) * 0.02,
        # blocks (stacked over layer dim for lax.scan)
        "ln1_g": jnp.ones((L, d), jnp.float32),
        "ln2_g": jnp.ones((L, d), jnp.float32),
        "attn_qkv": _init_linear(ks[1], d, (L, d, 3 * d)),          # column parallel
        "attn_out": _init_linear(ks[2], d, (L, d, d)) * scale_res,  # row parallel
        "mlp_in": _init_linear(ks[3], d, (L, d, 4 * d)),            # column parallel
        "mlp_out": _init_linear(ks[4], 4 * d, (L, 4 * d, d)) * scale_res,  # row parallel
        "lnf_g": jnp.ones((d,), jnp.float32),
    }
    if cfg.untie_head:
        params["head"] = _init_linear(ks[5], d, (d, cfg.vocab_size))
    return params


def _rmsnorm(x: jax.Array, gain: jax.Array) -> jax.Array:
    # norm in fp32 for stability, cast back to compute dtype
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (y * gain).astype(x.dtype)


def _rope(x: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over the last dim. x: [B, T, H, Dh]."""
    _, T, _, Dh = x.shape
    half = Dh // 2
    freqs = jnp.exp(-math.log(theta) * jnp.arange(half, dtype=jnp.float32) / half)
    ang = jnp.arange(T, dtype=jnp.float32)[:, None] * freqs[None, :]  # [T, half]
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def _attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Causal attention. q,k,v: [B, T, H, Dh] → [B, T, H, Dh]."""
    B, T, H, Dh = q.shape
    scale = 1.0 / math.sqrt(Dh)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    qi = lax.broadcasted_iota(jnp.int32, (T, T), 0)
    ki = lax.broadcasted_iota(jnp.int32, (T, T), 1)
    logits = jnp.where(ki <= qi, logits.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block(x: jax.Array, layer: Dict[str, jax.Array], cfg: GPTConfig,
           attn_fn=None) -> jax.Array:
    B, T, d = x.shape
    H, Dh = cfg.n_head, cfg.head_dim
    h = _rmsnorm(x, layer["ln1_g"])
    qkv = h @ layer["attn_qkv"].astype(h.dtype)  # [B, T, 3d]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = _rope(q.reshape(B, T, H, Dh), cfg.rope_theta)
    k = _rope(k.reshape(B, T, H, Dh), cfg.rope_theta)
    v = v.reshape(B, T, H, Dh)
    att = (attn_fn or _attention)(q, k, v).reshape(B, T, d)
    x = x + att @ layer["attn_out"].astype(att.dtype)
    h = _rmsnorm(x, layer["ln2_g"])
    h = jax.nn.gelu(h @ layer["mlp_in"].astype(h.dtype))
    return x + h @ layer["mlp_out"].astype(h.dtype)


_LAYER_KEYS = ("ln1_g", "ln2_g", "attn_qkv", "attn_out", "mlp_in", "mlp_out")


def hidden(params: Dict[str, jax.Array], tokens: jax.Array, cfg: GPTConfig,
           attn_fn=None, remat: "bool | str" = False) -> jax.Array:
    """tokens: int32 [B, T] → final-norm hidden states [B, T, d] (the
    pre-head activations; forward() applies the vocab matmul)."""
    x = params["tok_emb"][tokens].astype(cfg.compute_dtype)
    layers = {k: params[k] for k in _LAYER_KEYS}
    x = scan_blocks(lambda h, layer: _block(h, layer, cfg, attn_fn),
                    x, layers, remat)
    return _rmsnorm(x, params["lnf_g"])


def _head_mat(params, cfg: GPTConfig) -> jax.Array:
    """[d, vocab] unembedding. Weight-tied by default: the lazy .T folds
    into the consuming matmul."""
    return params["head"] if cfg.untie_head else params["tok_emb"].T


def forward(params: Dict[str, jax.Array], tokens: jax.Array, cfg: GPTConfig,
            attn_fn=None, remat: "bool | str" = False) -> jax.Array:
    """tokens: int32 [B, T] → logits float32 [B, T, vocab].

    attn_fn: optional (q, k, v) -> out override for the attention op —
    e.g. ops.flash_attention (fused single-chip kernel) or
    ops.ring_attention.make_ring_attn_fn(mesh) (sequence parallelism).

    remat: checkpoint each block — the backward recomputes the layer
    forward instead of stashing per-layer activations, so HBM holds one
    layer's activations at a time (how big batches fit a 16 GB chip)."""
    x = hidden(params, tokens, cfg, attn_fn, remat)
    # weight-tied head (default): bf16 operands on the MXU, fp32
    # accumulation — the vocab matmul is a large share of the model's
    # FLOPs and fp32 operands would run it off the fast systolic path
    return jnp.matmul(x, _head_mat(params, cfg).astype(x.dtype),
                      preferred_element_type=jnp.float32)


def loss_fn(params, tokens, targets, cfg: GPTConfig, attn_fn=None,
            remat: "bool | str" = False,
            loss_chunk: "int | None" = None) -> jax.Array:
    """Mean next-token cross-entropy (gather − logsumexp form; see
    models/_common.py). targets: int32 [B, T].

    loss_chunk: compute the vocab matmul + CE in recompute-checkpointed
    sequence chunks of this size (models/_common.py:chunked_ce_loss) —
    the full [B, T, vocab] logits never exist, which is what fits
    T ≥ 32768 on a 16 GB chip. Must divide T (a silent fall-back to the
    full-logits path would resurface as an opaque multi-GB XLA OOM in
    exactly the configs loss_chunk exists to rescue)."""
    T = targets.shape[1]
    if loss_chunk and T % loss_chunk:
        raise ValueError(f"loss_chunk {loss_chunk} must divide T={T}")
    if loss_chunk and T > loss_chunk:
        x = hidden(params, tokens, cfg, attn_fn, remat)
        return chunked_ce_loss(x, _head_mat(params, cfg), targets, loss_chunk)
    logits = forward(params, tokens, cfg, attn_fn, remat=remat)
    return gather_ce_loss(logits, targets)


@partial(jax.jit, static_argnames=("cfg",))
def forward_jit(params, tokens, cfg: GPTConfig):
    return forward(params, tokens, cfg)


def tiny_config(**overrides) -> GPTConfig:
    """Small config for tests / compile checks."""
    base = dict(vocab_size=512, n_layer=2, n_head=4, n_embd=128, block_size=128)
    base.update(overrides)
    return GPTConfig(**base)


# Named presets: tiny for CI, the GPT-2 ladder for real runs (the reference's
# examples train nanoGPT at gpt2/124M scale — train_pccl.py model args).
# vocab 50304 = GPT-2's 50257 padded to a multiple of 64 for MXU-friendly
# embedding/unembedding matmuls.
PRESETS = {
    # nano: the examples' CI default — small enough that a 2-peer loopback
    # convergence run fits a single-core test budget
    "nano": dict(vocab_size=256, n_layer=2, n_head=4, n_embd=64, block_size=64),
    "tiny": dict(vocab_size=512, n_layer=2, n_head=4, n_embd=128, block_size=128),
    "gpt2": dict(vocab_size=50304, n_layer=12, n_head=12, n_embd=768,
                 block_size=1024),
    "gpt2-medium": dict(vocab_size=50304, n_layer=24, n_head=16, n_embd=1024,
                        block_size=1024),
    "gpt2-large": dict(vocab_size=50304, n_layer=36, n_head=20, n_embd=1280,
                       block_size=1024),
    "gpt2-xl": dict(vocab_size=50304, n_layer=48, n_head=25, n_embd=1600,
                    block_size=1024),
}


def named_config(name: str, **overrides) -> GPTConfig:
    """Preset config by name (see PRESETS); overrides win."""
    base = dict(PRESETS[name])
    base.update(overrides)
    return GPTConfig(**base)
