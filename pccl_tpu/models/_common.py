"""Pieces shared by the model families (gpt.py, llama.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def maybe_checkpoint(block_fn, remat):
    """Per-block activation checkpointing: the backward recomputes the
    layer forward instead of stashing per-layer activations, so HBM holds
    one layer's activations at a time (how big batches fit a 16 GB chip).
    prevent_cse=False is safe (and fast) under lax.scan.

    remat: False = stash everything; True = full remat; "dots" = save
    weight-matmul outputs and recompute only the cheap/batched rest
    (jax checkpoint_dots_with_no_batch_dims) — a middle point trading
    HBM back for recompute FLOPs."""
    if not remat:
        return block_fn
    if remat is True:
        policy = None
    elif remat == "dots":
        policy = jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    else:
        raise ValueError(f"unknown remat mode {remat!r}; use False, True, "
                         "or 'dots'")
    return jax.checkpoint(block_fn, prevent_cse=False, policy=policy)


def gather_ce_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy, written as gather(logits) − logsumexp
    rather than log_softmax so no second [B, T, vocab] tensor is
    materialized (the logp stash costs ~1.6 GB at gpt2 vocab and b8x1024 —
    real HBM on a 16 GB chip)."""
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(jax.nn.logsumexp(logits, axis=-1) - tgt)
