"""Pieces shared by the model families (gpt.py, llama.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def maybe_checkpoint(block_fn, remat):
    """Per-block activation checkpointing: the backward recomputes the
    layer forward instead of stashing per-layer activations, so HBM holds
    one layer's activations at a time (how big batches fit a 16 GB chip).
    prevent_cse=False is safe (and fast) under lax.scan.

    remat: False = stash everything; True = full remat; "dots" = save
    weight-matmul outputs and recompute only the cheap/batched rest
    (jax checkpoint_dots_with_no_batch_dims) — a middle point trading
    HBM back for recompute FLOPs."""
    if not remat:
        return block_fn
    if remat == "sqrt":
        # "sqrt" is a SCAN topology (two-level grouping), not a per-block
        # policy — silently treating it as full remat here would hand a
        # direct caller per-block checkpointing with none of the grouping
        raise ValueError("remat='sqrt' is handled by scan_blocks, not "
                         "maybe_checkpoint")
    if remat is True:
        policy = None
    elif remat == "dots":
        policy = jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    else:
        raise ValueError(f"unknown remat mode {remat!r}; use False, True, "
                         "'dots', or 'sqrt'")
    return jax.checkpoint(block_fn, prevent_cse=False, policy=policy)


def _sqrt_divisor(n: int) -> int:
    """Largest divisor of n that is ≤ √n (1 for primes)."""
    g = 1
    d = 1
    while d * d <= n:
        if n % d == 0:
            g = d
        d += 1
    return g


def scan_blocks(block_fn, x, layers, remat):
    """The per-layer scan with the chosen checkpointing topology.

    False/True/"dots": one scan over L layers, each block wrapped by
    maybe_checkpoint — the backward holds L per-layer scan carries
    ([B, T, d] block inputs) plus one block's internals.

    "sqrt": two-level checkpointing — an outer scan over layer GROUPS of
    G ≈ √L, the whole group body inside jax.checkpoint AND each block
    checkpointed within it, so the backward holds L/G group inputs +
    (during one group's recompute) G block inputs + one block's
    internals: (L/G + G)·[B, T, d] instead of L — ~2.4× less activation
    memory at L=24 for one extra forward of recompute (~+2/3 total
    FLOPs vs remat=True's +1/3). The long-context lever when per-layer
    remat's saved block inputs themselves no longer fit."""
    if remat != "sqrt":
        blk = maybe_checkpoint(block_fn, remat)

        def body(h, layer):
            return blk(h, layer), None

        x, _ = lax.scan(body, x, layers)
        return x
    L = jax.tree.leaves(layers)[0].shape[0]
    G = _sqrt_divisor(L)
    if G == 1:  # prime L: grouping degenerates to plain full remat
        return scan_blocks(block_fn, x, layers, True)
    grouped = jax.tree.map(
        lambda a: a.reshape(L // G, G, *a.shape[1:]), layers)
    blk = maybe_checkpoint(block_fn, True)

    def group_body(h, group):
        def inner(h2, layer):
            return blk(h2, layer), None

        h, _ = lax.scan(inner, h, group)
        return h, None

    x, _ = lax.scan(jax.checkpoint(group_body, prevent_cse=False),
                    x, grouped)
    return x


def gather_ce_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy, written as gather(logits) − logsumexp
    rather than log_softmax so no second [B, T, vocab] tensor is
    materialized (the logp stash costs ~1.6 GB at gpt2 vocab and b8x1024 —
    real HBM on a 16 GB chip)."""
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(jax.nn.logsumexp(logits, axis=-1) - tgt)


def chunked_ce_loss(x: jax.Array, head_mat: jax.Array, targets: jax.Array,
                    chunk: int) -> jax.Array:
    """Mean next-token CE that never materializes the full [B, T, vocab]
    logits: a scan over sequence chunks computes each chunk's logits inside
    ``jax.checkpoint`` (the backward recomputes them), so peak logits
    memory is [B, chunk, vocab]. At T=32768 / 32k vocab the full-logits
    path holds a 4.2 GB fp32 tensor PLUS its cotangent — the single
    largest resident of a long-context train step and the difference
    between fitting a 16 GB chip and OOM; the chunked path holds ~260 MB
    at chunk=2048. Cost: the head matmul runs once more in the backward
    (+2·T·d·V FLOPs, ~1 % of a long-context step).

    x: [B, T, d] final hidden states; head_mat: [d, vocab] (pass ``W.T``
    lazily for tied heads — XLA folds the transpose into the matmul);
    targets: int32 [B, T]. ``chunk`` must divide T."""
    B, T, d = x.shape
    n = T // chunk
    assert n * chunk == T, f"loss chunk {chunk} must divide T={T}"
    xs = jnp.moveaxis(x.reshape(B, n, chunk, d), 1, 0)
    ts = jnp.moveaxis(targets.reshape(B, n, chunk), 1, 0)

    @jax.checkpoint
    def chunk_nll(xc, tc):
        logits = jnp.matmul(xc, head_mat.astype(xc.dtype),
                            preferred_element_type=jnp.float32)
        tgt = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        return jnp.sum(jax.nn.logsumexp(logits, axis=-1) - tgt)

    def body(acc, ct):
        return acc + chunk_nll(*ct), None

    tot, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts))
    return tot / (B * T)
