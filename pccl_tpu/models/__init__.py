from . import gpt, llama  # noqa: F401
