from . import gpt  # noqa: F401
