"""Version / build info for pccl_tpu.

Reference parity: pcclGetBuildInfo (/root/reference/include/pccl.h:458).
"""

__version__ = "0.1.0"

BUILD_INFO = {
    "name": "pccl_tpu",
    "version": __version__,
    # Pod Collective Communication Protocol; rev 2 = family-tagged wire
    # addresses (IPv6-ready format, IPv4-first plumbing)
    "protocol": "PCCP/2",
}
