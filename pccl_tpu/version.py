"""Version / build info for pccl_tpu.

Reference parity: pcclGetBuildInfo (/root/reference/include/pccl.h:458).
"""

__version__ = "0.1.0"

BUILD_INFO = {
    "name": "pccl_tpu",
    "version": __version__,
    "protocol": "PCCP/1",  # Pod Collective Communication Protocol, wire rev 1
}
