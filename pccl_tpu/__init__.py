"""pccl_tpu — TPU-native fault-tolerant collective communications framework.

Capabilities (parity with the PCCL reference, re-designed TPU-first):
- fault-tolerant collective ops over plain TCP/IP with dynamic peer
  join/leave at any point in training (pccl_tpu.comm);
- bit-identical shared-state synchronization with hash-based drift detection;
- on-the-wire quantization (min-max and zero-point/scale);
- bandwidth-aware ring topology optimization (ATSP);
- TPU device type: collectives on HBM-resident JAX arrays, hierarchical
  reduction — jax.lax.psum over ICI inside a slice, CCoIP-style WAN ring
  across slices (pccl_tpu.parallel.hierarchical).

Native core: the runtime (sockets, wire protocol, master, ring reduce,
quantization, hashing) is C++ in pccl_tpu/native, loaded via ctypes.
"""

from .version import __version__  # noqa: F401
