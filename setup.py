"""CMake-driven build for the native core (libpcclt.so).

Reference parity: the reference ships a pip-installable package whose
setup bundles the compiled core with the Python bindings
(python/framework/pccl/setup.py). Here the native build is CMake + Ninja
(falling back to plain Makefiles when ninja is absent) and the resulting
libpcclt.so is installed as package data under ``pccl_tpu/_lib/``, which
is the loader's packaged-install search location (comm/_native.py).
"""

import shutil
import subprocess
import sys
from pathlib import Path

from setuptools import Extension, setup
from setuptools.command.build_ext import build_ext


class CMakeBuild(build_ext):
    def run(self):
        src = Path(__file__).resolve().parent / "pccl_tpu" / "native"
        build_dir = Path(self.build_temp) / "pcclt-native"
        build_dir.mkdir(parents=True, exist_ok=True)
        gen = ["-G", "Ninja"] if shutil.which("ninja") else []
        subprocess.check_call(
            ["cmake", "-S", str(src), "-B", str(build_dir),
             "-DCMAKE_BUILD_TYPE=Release", *gen])
        subprocess.check_call(
            ["cmake", "--build", str(build_dir), "--target", "pcclt",
             "--parallel"])
        so = build_dir / "libpcclt.so"
        if not so.exists():
            sys.exit("CMake build produced no libpcclt.so")
        dest = Path(self.build_lib) / "pccl_tpu" / "_lib"
        dest.mkdir(parents=True, exist_ok=True)
        shutil.copy2(so, dest / "libpcclt.so")


setup(
    # one placeholder extension forces build_ext into every build/install
    ext_modules=[Extension("pccl_tpu._native_build_marker", sources=[])],
    cmdclass={"build_ext": CMakeBuild},
)
