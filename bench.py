#!/usr/bin/env python
"""Headline benchmark + BASELINE.md config sweep.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Headline (BASELINE config 1): fp32 all-reduce busbw, 2 loopback peers.
Baseline: the reference's best sustained all-reduce number is 45 Gbit/s
(= 5.625 GB/s, collocated nodes, "limited only by NIC speed" —
/root/reference/docs/md/01_Introduction.md:8; see BASELINE.md). vs_baseline is
value / 5.625.

"extra" carries the remaining BASELINE configs (all on the native stack):
  quant4_busbw_gbps     — config 2: int8-ZPS quantized concurrent reduces,
                          4 peers (reference concurrent_reduce_test workload)
  shared_state4_step_s  — config 3: SyncSharedState + allreduce per step,
                          4 peers
  diloco_outer_step_s   — DiLoCo outer-step wall-clock, 100M params, 2 peers

PCCLT_BENCH_FAST=1 skips the extra configs (headline only).
"""

import json
import os
import sys

BASELINE_GBPS = 45.0 / 8.0  # 45 Gbit/s → GB/s


def main() -> None:
    nbytes = int(os.environ.get("PCCLT_BENCH_BYTES", str(64 << 20)))
    # 16 iterations: the median is stable to ~5% on a loaded single-core
    # host (10 left ~15% run-to-run spread)
    iters = int(os.environ.get("PCCLT_BENCH_ITERS", "16"))

    busbw = None
    extra = {}
    try:
        from pccl_tpu.comm import native_bench  # native C++ stack, preferred

        stats = native_bench.run_allreduce_bench(nbytes=nbytes, iters=iters,
                                                 return_stats=True)
        busbw = stats["med"]
        extra["headline_gbps_minmax"] = [round(stats["min"], 3),
                                         round(stats["max"], 3)]
        # flight-recorder phase breakdown for the headline op (mean per
        # reduce, seconds): where a regression lives — ring phases vs
        # wire-stall (docs/09_observability.md)
        if "phases" in stats:
            extra["allreduce_phases_s"] = stats["phases"]
        path = "native"
    except Exception as e:  # noqa: BLE001 — fall back to pure-python path
        print(f"bench: native path unavailable ({type(e).__name__}: {e}); "
              "using python fallback", file=sys.stderr)
        from pccl_tpu.comm import pybench

        busbw = pybench.run_allreduce_bench(nbytes=nbytes, iters=iters)
        path = "python-fallback"

    if path == "native" and os.environ.get("PCCLT_BENCH_FAST", "0") != "1":
        for key, fn in [
            ("bf16_busbw_gbps", native_bench.run_allreduce_bench_bf16),
            ("quant4_busbw_gbps", native_bench.run_quantized_concurrent_bench),
            # fp32 twin of config 2: records the loopback inversion (fp32
            # beats u8 on a free wire) in the artifact itself
            ("concurrent4_fp32_busbw_gbps",
             lambda: native_bench.run_quantized_concurrent_bench(
                 quantize=False)),
            ("shared_state4_step_s", native_bench.run_shared_state_bench),
            # world-8 burst of 12 tagged 8M-element reduces (the reference
            # concurrent_reduce_test workload at scale)
            ("soak8_step_s", native_bench.run_soak_bench),
        ]:
            try:
                extra[key] = round(fn(), 4)
            except Exception as e:  # noqa: BLE001 — extras must not kill headline
                print(f"bench: {key} failed ({type(e).__name__}: {e})",
                      file=sys.stderr)
                extra[key] = None
        try:
            med, phases = native_bench.run_diloco_outer_bench()
            extra["diloco_outer_step_s"] = round(med, 4)
            extra["diloco_phases_s"] = phases  # one fenced step's breakdown
        except Exception as e:  # noqa: BLE001
            print(f"bench: diloco failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["diloco_outer_step_s"] = None
            extra["diloco_phases_s"] = None
        # BASELINE config 5 churn clause: 4 peers, one SIGKILL + rejoin
        # mid-run; steady vs churn-window outer-step time
        try:
            for k, v in native_bench.run_diloco_churn_bench().items():
                extra[k] = round(v, 4) if isinstance(v, float) else v
        except Exception as e:  # noqa: BLE001
            print(f"bench: diloco churn failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            for k in ("diloco_steady_step_s", "diloco_churn_step_s",
                      "worlds_seen", "steps_completed", "rejoiner_joined"):
                extra[k] = None
        # THE driver-configured BASELINE metric: DiLoCo outer step at 1B
        # params (4 GB fp32 per peer). Gated on RAM — each peer wants
        # ~25 GB; skip quietly on small hosts.
        try:
            avail_kb = 0
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemAvailable"):
                        avail_kb = int(line.split()[1])
                        break
            if avail_kb > 70 * 1024 * 1024:
                for k, v in native_bench.run_diloco_1b_bench().items():
                    extra[k] = (round(v, 4) if isinstance(v, float)
                                else [round(x, 4) for x in v])
            else:
                print("bench: skipping 1B diloco leg "
                      f"(MemAvailable {avail_kb >> 20} GB < 70)",
                      file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"bench: diloco 1b failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["diloco_1b_step_s"] = None
        # BASELINE config 4 shape: 2 emulated slices, plain vs quantized DCN
        try:
            for k, v in native_bench.run_hierarchical_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: hierarchical failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["hier2_step_s"] = None
            extra["hier2_q8_step_s"] = None
        # BASELINE config 4 under its real wire: same hierarchical shape,
        # cross-slice hop paced to 100 Mbit/s — where the quantized DCN
        # hop must win (on unpaced loopback the A/B inverts)
        try:
            for k, v in native_bench.run_hierarchical_wan_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: hierarchical wan failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["hier2_wan_quant_speedup"] = None
        # one paced DiLoCo outer step, fp32 ring vs u8-ZPS ring
        try:
            for k, v in native_bench.run_diloco_wan_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: diloco wan failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["diloco_wan_quant_speedup"] = None
        # the constrained-wire A/B: quantization's reason to exist. 4-peer
        # ring over an emulated 100 Mbit/s WAN egress (PCCLT_WIRE_MBPS),
        # fp32 vs u8-ZPS, both reported as fp32-equivalent busbw.
        try:
            for k, v in native_bench.run_wan_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: wan failed ({type(e).__name__}: {e})", file=sys.stderr)
            extra["wan_quant_speedup"] = None
        # bf16 twin: the TPU gradient dtype, plain vs u8-ZPS (typed SIMD
        # widen-to-f32 kernels), bytes-adjusted on the same paced wire
        try:
            for k, v in native_bench.run_wan_bf16_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: wan bf16 failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["wan_bf16_quant_speedup"] = None
        # the fat-pipe A/B: same ring on an emulated 1 Gbit/s x 50 ms RTT
        # pipe (bandwidth pacing + delivery delay line), single flow vs 4
        # concurrent windowed collectives — the regime windowing exists for
        try:
            for k, v in native_bench.run_wan_rtt_windowed_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: wan rtt failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["wan_rtt_windowed_speedup"] = None
        # the pipelined data plane on the SAME fat-long-pipe map: one flow,
        # windowed quantize→send→recv→dequant pipeline + io_uring batched
        # submission (docs/08) — must beat both r05 keys above
        try:
            base = {k: extra.get(k) for k in ("wan_rtt_single_busbw_gbps",
                                              "wan_rtt_windowed_busbw_gbps")}
            for k, v in native_bench.run_wan_pipelined_bench(
                    baselines=base).items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: wan pipelined failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["wan_pipelined_speedup"] = None
        # multipath striping on the SAME fat-long-pipe map (docs/08): the
        # full pipelined plane with the op's window chain striped across 4
        # pool conns sharing one striped-bucket edge, vs the same plane
        # pinned to ONE conn (the PR-8 baseline) — same run, same host
        try:
            for k, v in native_bench.run_wan_striped_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: wan striped failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["wan_striped_speedup"] = None
        # master HA recovery: SIGKILL the journaled master mid-run, restart
        # on the same port; master_recovery_s = SIGKILL -> first
        # post-restart collective completing over resumed sessions
        # (docs/10_high_availability.md). Includes the ~0.5 s scripted
        # outage window, so the floor is downtime + one resume backoff.
        try:
            for k, v in native_bench.run_master_recovery_bench().items():
                extra[k] = round(v, 4) if isinstance(v, float) else v
        except Exception as e:  # noqa: BLE001
            print(f"bench: master recovery failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["master_recovery_s"] = None
        # the topology-optimizer proof: 4 peers on a heterogeneous emulated
        # mesh (per-edge netem, one pessimal 25 Mbit edge on the naive
        # ring); after optimize_topology() the ATSP ring routes around the
        # degraded link — the reference's headline capability, measured
        try:
            for k, v in native_bench.run_topology_opt_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: topology opt failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["topology_opt_speedup"] = None
        # the schedule synthesizer's proof (docs/12): forced tree vs ring
        # broadcast on a hub-and-spoke wire, forced mesh vs ring all-to-all
        # on a two-datacenter wire — same-run ring baselines, same wire
        try:
            for k, v in native_bench.run_schedule_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: schedule bench failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["sched_hub_speedup"] = None
            extra["sched_2dc_speedup"] = None
        # the observability plane's cost, pinned in history: loopback step
        # time with digest pushes + trace capture ON vs OFF (docs/09's
        # <= 1% bound; counters are always on in both legs)
        try:
            for k, v in native_bench.run_telemetry_overhead_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: telemetry overhead failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["telemetry_overhead_pct"] = None
        # critical-path attribution (docs/09): every BENCH run explains its
        # own numbers — trace_critic decomposes a paced 2-peer world's
        # steps into stall/codec/setup fractions + the dominant verdict
        try:
            for k, v in native_bench.run_attribution_bench().items():
                extra[k] = round(v, 4) if isinstance(v, float) else v
        except Exception as e:  # noqa: BLE001
            print(f"bench: attribution failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["attribution_coverage"] = None
        # straggler-immune data plane (docs/05): mid-run edge degradation →
        # wall-clock to the first back-to-baseline step (watchdog →
        # re-issue → relay ladder), plus the armed-but-idle plane's step
        # overhead (<= 1% bound)
        try:
            for k, v in native_bench.run_degraded_recovery_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: degraded recovery failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["degraded_recovery_s"] = None
            extra["relay_overhead_pct"] = None
        # shared-state chunk plane (docs/04): N cold joiners over the
        # content-addressed multi-source fetch vs the single-seeder
        # baseline (acceptance gate >= 2x), conservation byte-exact
        try:
            for k, v in native_bench.run_sync_swarm_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: sync swarm failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["sync_swarm_speedup"] = None
        # fleet-scale observability (docs/09): 1000 observer sessions x 8
        # edges at ~12 Hz through the off-dispatcher ingest queue; the
        # scrape gate (bounded top-K /metrics < 1 s, promlint-clean, zero
        # queue drops) plus journal-replay cold-restart cost
        try:
            for k, v in native_bench.run_master_scale_bench().items():
                extra[k] = round(v, 6) if isinstance(v, float) else v
        except Exception as e:  # noqa: BLE001
            print(f"bench: master scale failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["master_scale_ingest_rate"] = None

    # On-chip model legs: the jitted bf16 train step on the real TPU —
    # tokens/s + MFU per family (skip-guarded when no TPU is attached;
    # everything above runs the native CPU stack regardless).
    #
    # Every TPU touch happens in a SUBPROCESS: standard libtpu is
    # process-exclusive, so if this parent initialized the backend (even
    # just to probe jax.devices()), the spawned rank-0 of the diloco-tpu
    # leg could never acquire the chip. Probe, model legs, and the diloco
    # leg therefore each run sequentially in their own process.
    if os.environ.get("PCCLT_BENCH_FAST", "0") != "1":
        import subprocess

        # a wedged TPU runtime (hung libtpu lock) must degrade to "no TPU
        # attached", not abort the bench with the CPU results unsaved
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(any(d.platform == 'tpu' "
                 "for d in jax.devices()))"],
                capture_output=True, text=True, timeout=300)
            tpu_attached = probe.stdout.strip().endswith("True")
        except (subprocess.TimeoutExpired, OSError):
            tpu_attached = False
        # the dev tunnel to the chip goes down for hours at a time; cache
        # each successful on-chip pass so a bench run that catches the
        # tunnel dead can still carry the most recent REAL measurements —
        # clearly labeled as cached, never mixed into the live keys
        cache_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                  ".tpu_bench_cache.json")
        if tpu_attached:
            for fam in ("gpt", "llama"):
                try:
                    p = subprocess.run(
                        [sys.executable, "-m",
                         "pccl_tpu.benchmarks.model_bench", fam],
                        capture_output=True, text=True, timeout=900,
                        check=True)
                    r = json.loads(p.stdout.strip().splitlines()[-1])
                    extra[f"tpu_train_tokens_s_{fam}"] = r["tokens_s"]
                    extra[f"tpu_mfu_{fam}"] = r["mfu"]
                    extra[f"tpu_config_{fam}"] = r["config"]
                    extra[f"tpu_step_s_{fam}"] = r["step_s"]
                    extra[f"tpu_tokens_s_minmax_{fam}"] = [
                        r["tokens_s_min"], r["tokens_s_max"]]
                except Exception as e:  # noqa: BLE001
                    print(f"bench: tpu {fam} failed ({type(e).__name__}: {e})",
                          file=sys.stderr)
                    extra[f"tpu_train_tokens_s_{fam}"] = None
            # long-context legs: single-chip training through the fused
            # k-blocked flash fwd+bwd pallas kernels (a dense backward at
            # these T wants a multi-GB probs tensor per layer; the round-4
            # full-T-resident kernels topped out at T=8192 on the VMEM
            # ceiling). The llama leg is GQA-native: Hkv-shaped K/V all
            # the way through the kernels.
            for key, fam, seq, ab in (
                    ("tpu_longctx", "gpt", 8192, ()),
                    ("tpu_longctx16k", "gpt", 16384, ()),
                    ("tpu_longctx_llama", "llama", 8192, ()),
                    ("tpu_longctx16k_llama", "llama", 16384, ()),
                    # T=32768: enabled by the chunked CE (loss_chunk) —
                    # the full [1, 32768, vocab] fp32 logits + cotangent
                    # alone would blow the 15.75 GB chip
                    ("tpu_longctx32k", "gpt", 32768, ("loss_chunk=2048",)),
                    ("tpu_longctx32k_llama", "llama", 32768,
                     ("loss_chunk=2048",)),
                    # the GQA A/B: same llama leg with K/V repeated to full
                    # head count in HBM before the kernel (the degraded
                    # round-4 path) — the GQA-native win is the ratio
                    ("tpu_longctx_llama_repeatkv", "llama", 8192,
                     ("repeat_kv=1",))):
                try:
                    p = subprocess.run(
                        [sys.executable, "-m",
                         "pccl_tpu.benchmarks.model_bench", fam, "batch=1",
                         f"seq={seq}", "use_flash=1", "remat=1", *ab],
                        capture_output=True, text=True, timeout=900,
                        check=True)
                    r = json.loads(p.stdout.strip().splitlines()[-1])
                    extra[f"{key}_tokens_s"] = r["tokens_s"]
                    extra[f"{key}_mfu"] = r["mfu"]
                    extra[f"{key}_config"] = r["config"]
                except Exception as e:  # noqa: BLE001
                    print(f"bench: {key} failed ({type(e).__name__}: {e})",
                          file=sys.stderr)
                    extra[f"{key}_tokens_s"] = None
            # clean-sync invariant: the on-device shared-state digest
            # (hash type 2) stays flat across state sizes while the
            # staging path scales with the tunnel's D2H rate
            try:
                p = subprocess.run(
                    [sys.executable, "-m", "pccl_tpu.benchmarks.hash_bench"],
                    capture_output=True, text=True, timeout=600, check=True)
                for k, v in json.loads(
                        p.stdout.strip().splitlines()[-1]).items():
                    extra[f"tpu_{k}"] = v
            except Exception as e:  # noqa: BLE001
                print(f"bench: hash bench failed ({type(e).__name__}: {e})",
                      file=sys.stderr)
                extra["tpu_devhash_256mb_s"] = None
            # headline aliases point at the flagship (gpt) leg
            extra["tpu_train_tokens_s"] = extra.get("tpu_train_tokens_s_gpt")
            extra["tpu_mfu"] = extra.get("tpu_mfu_gpt")
            # on-chip DiLoCo outer step over a paced wire: rank 0 stages
            # from the real TPU; the pipelined leg hides D2H under the
            # ring. Spawned peers acquire the chip themselves — this
            # parent never holds it.
            try:
                for k, v in native_bench.run_diloco_tpu_bench().items():
                    extra[k] = round(v, 4) if isinstance(v, float) else v
            except Exception as e:  # noqa: BLE001
                print(f"bench: diloco tpu failed ({type(e).__name__}: {e})",
                      file=sys.stderr)
                extra["diloco_tpu_step_s"] = None
            # async DiLoCo's overlap, on chip: steady-state step ≈ inner
            # compute with the paced ring hidden, vs the sync twin's
            # compute+wire sum (VERDICT r4 #5)
            try:
                for k, v in native_bench.run_async_diloco_tpu_bench().items():
                    extra[k] = round(v, 4) if isinstance(v, float) else v
            except Exception as e:  # noqa: BLE001
                print(f"bench: async diloco tpu failed "
                      f"({type(e).__name__}: {e})", file=sys.stderr)
                extra["async_diloco_tpu_step_s"] = None
            try:
                tpu_keys = {k: v for k, v in extra.items()
                            if k.startswith(("tpu_", "diloco_tpu",
                                             "async_diloco_tpu"))
                            and v is not None}
                if tpu_keys:
                    import time

                    # MERGE into the existing cache: a partially failed
                    # pass (tunnel drops mid-run, some legs None) must not
                    # wipe the surviving legs' last real measurements.
                    # The file is deliberately git-TRACKED — it is the
                    # insurance artifact for rounds where the tunnel is
                    # dead at bench time.
                    merged = {}
                    try:
                        with open(cache_path) as f:
                            merged = json.load(f)
                    except (OSError, ValueError):
                        pass
                    merged.update(tpu_keys)
                    merged["cached_at"] = time.strftime(
                        "%Y-%m-%d %H:%M:%S UTC", time.gmtime())
                    with open(cache_path, "w") as f:
                        json.dump(merged, f)
            except OSError:
                pass
        else:
            print("bench: no TPU attached; skipping on-chip model legs",
                  file=sys.stderr)
            try:
                with open(cache_path) as f:
                    cached = json.load(f)
                cached["note"] = ("TPU tunnel unreachable at bench time; "
                                  "these are this repo's most recent "
                                  "on-chip measurements, reproducible via "
                                  "pccl_tpu.benchmarks.model_bench")
                extra["tpu_cached"] = cached
            except (OSError, ValueError):
                pass

    print(json.dumps({
        "metric": f"allreduce_busbw_fp32_2peer_loopback({path})",
        "value": round(busbw, 3),
        "unit": "GB/s",
        "vs_baseline": round(busbw / BASELINE_GBPS, 3),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
