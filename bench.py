#!/usr/bin/env python
"""Headline benchmark + BASELINE.md config sweep.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Headline (BASELINE config 1): fp32 all-reduce busbw, 2 loopback peers.
Baseline: the reference's best sustained all-reduce number is 45 Gbit/s
(= 5.625 GB/s, collocated nodes, "limited only by NIC speed" —
/root/reference/docs/md/01_Introduction.md:8; see BASELINE.md). vs_baseline is
value / 5.625.

"extra" carries the remaining BASELINE configs (all on the native stack):
  quant4_busbw_gbps     — config 2: int8-ZPS quantized concurrent reduces,
                          4 peers (reference concurrent_reduce_test workload)
  shared_state4_step_s  — config 3: SyncSharedState + allreduce per step,
                          4 peers
  diloco_outer_step_s   — DiLoCo outer-step wall-clock, 100M params, 2 peers

PCCLT_BENCH_FAST=1 skips the extra configs (headline only).
"""

import json
import os
import sys

BASELINE_GBPS = 45.0 / 8.0  # 45 Gbit/s → GB/s


def main() -> None:
    nbytes = int(os.environ.get("PCCLT_BENCH_BYTES", str(64 << 20)))
    # 16 iterations: the median is stable to ~5% on a loaded single-core
    # host (10 left ~15% run-to-run spread)
    iters = int(os.environ.get("PCCLT_BENCH_ITERS", "16"))

    busbw = None
    extra = {}
    try:
        from pccl_tpu.comm import native_bench  # native C++ stack, preferred

        busbw = native_bench.run_allreduce_bench(nbytes=nbytes, iters=iters)
        path = "native"
    except Exception as e:  # noqa: BLE001 — fall back to pure-python path
        print(f"bench: native path unavailable ({type(e).__name__}: {e}); "
              "using python fallback", file=sys.stderr)
        from pccl_tpu.comm import pybench

        busbw = pybench.run_allreduce_bench(nbytes=nbytes, iters=iters)
        path = "python-fallback"

    if path == "native" and os.environ.get("PCCLT_BENCH_FAST", "0") != "1":
        for key, fn in [
            ("bf16_busbw_gbps", native_bench.run_allreduce_bench_bf16),
            ("quant4_busbw_gbps", native_bench.run_quantized_concurrent_bench),
            ("shared_state4_step_s", native_bench.run_shared_state_bench),
        ]:
            try:
                extra[key] = round(fn(), 4)
            except Exception as e:  # noqa: BLE001 — extras must not kill headline
                print(f"bench: {key} failed ({type(e).__name__}: {e})",
                      file=sys.stderr)
                extra[key] = None
        try:
            med, phases = native_bench.run_diloco_outer_bench()
            extra["diloco_outer_step_s"] = round(med, 4)
            extra["diloco_phases_s"] = phases  # one fenced step's breakdown
        except Exception as e:  # noqa: BLE001
            print(f"bench: diloco failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["diloco_outer_step_s"] = None
            extra["diloco_phases_s"] = None
        # BASELINE config 5 churn clause: 4 peers, one SIGKILL + rejoin
        # mid-run; steady vs churn-window outer-step time
        try:
            for k, v in native_bench.run_diloco_churn_bench().items():
                extra[k] = round(v, 4) if isinstance(v, float) else v
        except Exception as e:  # noqa: BLE001
            print(f"bench: diloco churn failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            for k in ("diloco_steady_step_s", "diloco_churn_step_s",
                      "worlds_seen", "steps_completed", "rejoiner_joined"):
                extra[k] = None
        # BASELINE config 4 shape: 2 emulated slices, plain vs quantized DCN
        try:
            for k, v in native_bench.run_hierarchical_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: hierarchical failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["hier2_step_s"] = None
            extra["hier2_q8_step_s"] = None
        # the constrained-wire A/B: quantization's reason to exist. 4-peer
        # ring over an emulated 100 Mbit/s WAN egress (PCCLT_WIRE_MBPS),
        # fp32 vs u8-ZPS, both reported as fp32-equivalent busbw.
        try:
            for k, v in native_bench.run_wan_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: wan failed ({type(e).__name__}: {e})", file=sys.stderr)
            extra["wan_quant_speedup"] = None
        # bf16 twin: the TPU gradient dtype, plain vs u8-ZPS (typed SIMD
        # widen-to-f32 kernels), bytes-adjusted on the same paced wire
        try:
            for k, v in native_bench.run_wan_bf16_bench().items():
                extra[k] = round(v, 4)
        except Exception as e:  # noqa: BLE001
            print(f"bench: wan bf16 failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            extra["wan_bf16_quant_speedup"] = None

    print(json.dumps({
        "metric": f"allreduce_busbw_fp32_2peer_loopback({path})",
        "value": round(busbw, 3),
        "unit": "GB/s",
        "vs_baseline": round(busbw / BASELINE_GBPS, 3),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
