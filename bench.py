#!/usr/bin/env python
"""Headline benchmark: fp32 all-reduce busbw, 2 loopback peers.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's best sustained all-reduce number is 45 Gbit/s
(= 5.625 GB/s, collocated nodes, "limited only by NIC speed" —
/root/reference/docs/md/01_Introduction.md:8; see BASELINE.md). vs_baseline is
value / 5.625.
"""

import json
import os
import sys

BASELINE_GBPS = 45.0 / 8.0  # 45 Gbit/s → GB/s


def main() -> None:
    nbytes = int(os.environ.get("PCCLT_BENCH_BYTES", str(64 << 20)))
    iters = int(os.environ.get("PCCLT_BENCH_ITERS", "10"))

    busbw = None
    try:
        from pccl_tpu.comm import native_bench  # native C++ stack, preferred

        busbw = native_bench.run_allreduce_bench(nbytes=nbytes, iters=iters)
        path = "native"
    except Exception as e:  # noqa: BLE001 — fall back to pure-python path
        print(f"bench: native path unavailable ({type(e).__name__}: {e}); "
              "using python fallback", file=sys.stderr)
        from pccl_tpu.comm import pybench

        busbw = pybench.run_allreduce_bench(nbytes=nbytes, iters=iters)
        path = "python-fallback"

    print(json.dumps({
        "metric": f"allreduce_busbw_fp32_2peer_loopback({path})",
        "value": round(busbw, 3),
        "unit": "GB/s",
        "vs_baseline": round(busbw / BASELINE_GBPS, 3),
    }))


if __name__ == "__main__":
    main()
