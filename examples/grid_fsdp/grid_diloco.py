"""2D-grid DiLoCo: sharded outer state × per-shard rings (FSDP × PCCL).

Reference parity: /root/reference/python/examples/nanogpt_diloco/
sync_diloco_fsdp.py (peer group = FSDP shard index, shared state = the local
shard of outer params + momentum, grid-fullness gate) and the footguns doc
/root/reference/docs/md/8_CommonFootguns.md:4-100 (the 2D matrix of FSDP
ranks × PCCL dynamic membership, `global < fsdp_world × largest_group` →
wait, and the memory-mapping recipe for same-host shard exchange).

The grid, TPU-first. Each process is one cell (shard g, replica r):

                     ring (comm, peer group = g)
                 ┌───────────────┬───────────────┐
    shard 0      │ cell (0, 0)   │ cell (0, 1)   │  ← group 0 ring averages
                 ├───────────────┼───────────────┤    pseudo-grad shard 0
    shard 1      │ cell (1, 0)   │ cell (1, 1)   │  ← group 1 ring averages
                 └───────────────┴───────────────┘    pseudo-grad shard 1
                    replica 0       replica 1
                 └── column = one host, shards exchanged via grid file ──┘

- INTRA-CELL: the model itself is sharded over the cell's local device mesh
  (tensor-parallel axis; XLA inserts the ICI collectives). This replaces the
  reference's cross-process NCCL/FSDP dimension — on TPU the fast
  interconnect is inside the slice, so the heavy per-inner-step sharding
  stays in-process where it costs nothing to coordinate.
- CROSS-REPLICA: the flat fp32 outer state is split into `--num-shards`
  contiguous shards. A cell's SHARED STATE (and its ring traffic) is only
  its own shard — each ring carries 1/G of the bytes, exactly the
  reference's per-rank sharding of the outer reduce.
- CROSS-SHARD (same column/host): groups publish their updated shard into a
  mapped grid file (`--grid-file`, one per host); cells assemble the full
  outer vector from it before each inner phase. This is the footguns doc's
  recommended memory-mapping alternative to cross-process FSDP gathers.

Grid-fullness gate (the FSDP×PCCL deadlock footgun): no cell may start an
outer iteration until `global_world == num_shards × largest_group` — a
partially-joined column would wedge its groups' rings, so everyone admits
and waits until the grid is rectangular.

Consistency: the ring average is bitwise identical on every member, and the
outer SGD on a shard is deterministic host arithmetic from ring output +
previous shard — so a shard's content stays bit-identical across its group
(the shared-state hash check passes with rx_bytes=0). Adjacent groups may
run at most ONE outer step apart (a cell at step s only needs every shard
at ≥ s), so a cell can observe a neighbor shard one step newer — harmless
drift in inner INIT only, never in shared state.

Fault tolerance, per the reference's own caveat (footguns doc §"Reduced
fault tolerance"): the COLUMN is the failure unit. If one cell dies, the
grid is no longer rectangular and every cell holds at the fullness gate
until the dead cell's column-mates are also gone (or a replacement joins) —
exactly the reference's behavior, where a dead GPU takes its whole FSDP
column down via the NCCL timeout. When an entire column dies, each group's
ring retries down to the survivor world and training continues.

Run (2 shards × 2 replicas, one host):
    python -m pccl_tpu.comm.master --port 48500 &
    for g in 0 1; do for r in 0 1; do
        python examples/grid_fsdp/grid_diloco.py --master-port 48500 \
            --num-shards 2 --peer-group $g --base-port $((56000+g*200+r*100)) \
            --min-replicas 2 &
    done; done
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np

import common


class GridFile:
    """Per-host mapped exchange of outer-state shards.

    Layout: int64 [magic, num_shards, count] identity header, then int64[G]
    sequence header (outer step of each shard's content, -1 = never
    written), then the float32[count] full outer vector. Writers publish
    data-then-seq; readers wait for every seq ≥ their step. Same-host mmap
    coherence makes this ordering sufficient (this file never crosses
    hosts — each column has its own).

    Lifecycle: the file is scoped to ONE run — every cell unlinks it on
    clean exit (`remove`, idempotent), and an incompatible pre-existing
    file (wrong shape/magic — e.g. a crashed run with a different model or
    shard count) is a LOUD error, never attached. A crashed run of the
    same shape must be cleaned up by the launcher (`rm <grid-file>`); its
    stale sequence numbers cannot be told apart from a live cohort's."""

    MAGIC = 0x70636C74_67726964  # "pclt" "grid"
    MAGIC_FILL = -1
    _HDR = 3  # identity int64s before the per-shard sequence header

    def __init__(self, path: str, num_shards: int, count: int):
        self.path = path
        self.g = num_shards
        self.count = count
        nbytes = 8 * (self._HDR + num_shards) + 4 * count
        if not os.path.exists(path):
            # initialize privately, then hardlink into place: the file
            # appears ATOMICALLY with identity + -1 sentinels set, so a
            # racing attacher can never read a zero-filled header (seq 0
            # would claim step-0 content that was never published)
            tmp = f"{path}.init.{os.getpid()}"
            mm = np.memmap(tmp, dtype=np.uint8, mode="w+", shape=(nbytes,))
            hdr = mm[:8 * self._HDR].view(np.int64)
            hdr[0], hdr[1], hdr[2] = self.MAGIC, num_shards, count
            mm[8 * self._HDR:8 * (self._HDR + num_shards)].view(
                np.int64)[:] = self.MAGIC_FILL
            mm.flush()
            del mm
            try:
                os.link(tmp, path)
            except FileExistsError:
                pass  # another cell won the race — validate + attach below
            finally:
                os.unlink(tmp)
        if os.path.getsize(path) != nbytes:
            raise RuntimeError(
                f"stale/incompatible grid file {path} "
                f"({os.path.getsize(path)} bytes, want {nbytes}) — remove "
                "it; grid files are scoped to one run")
        self._mm = np.memmap(path, dtype=np.uint8, mode="r+", shape=(nbytes,))
        hdr = self._mm[:8 * self._HDR].view(np.int64)
        if not (hdr[0] == self.MAGIC and hdr[1] == num_shards
                and hdr[2] == count):
            raise RuntimeError(
                f"grid file {path} identity mismatch "
                f"(magic/shards/count = {list(hdr)}, want "
                f"[{self.MAGIC}, {num_shards}, {count}]) — remove it")
        self.seq = self._mm[8 * self._HDR:
                            8 * (self._HDR + num_shards)].view(np.int64)
        self.vec = self._mm[8 * (self._HDR + num_shards):].view(np.float32)
        self.bounds = [count * i // num_shards for i in range(num_shards + 1)]

    def remove(self) -> None:
        """Best-effort end-of-run unlink (idempotent across cells; mapped
        views of same-run laggards stay valid on the unlinked inode)."""
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def publish(self, shard: int, step: int, data: np.ndarray) -> None:
        lo, hi = self.bounds[shard], self.bounds[shard + 1]
        self.vec[lo:hi] = data
        self._mm.flush()  # data lands before the sequence tick
        self.seq[shard] = step

    def wait_all(self, step: int, timeout: float = 300.0) -> None:
        deadline = time.time() + timeout
        while bool(np.any(self.seq < step)):
            if time.time() > deadline:
                raise TimeoutError(
                    f"grid shards stuck below step {step}: {list(self.seq)}")
            time.sleep(0.002)

    def read_full(self) -> np.ndarray:
        return np.array(self.vec, dtype=np.float32)


def wait_grid_full(comm, num_shards: int, ever_full: bool = False,
                   grid: "GridFile" = None, step: int = 0,
                   timeout: float = 300.0) -> None:
    """Admit pending peers until the grid is rectangular (footguns doc:
    proceed only when global == num_shards × largest group).

    ``ever_full``: once a cell has seen the full grid, a whole shard group
    VANISHING no longer blocks the gate — but only when the departed
    group's grid-file seq already covers this cell's current ``step``
    (groups may finish their final outer step one iteration apart, and a
    faster group that completed and left must not strand the lagging
    group; its terminal shard is already published). A group that CRASHED
    mid-run has stale seq entries, so the gate keeps holding for a
    replacement column instead of sailing into wait_all's timeout. During
    bootstrap (never yet full) the strict rectangularity condition
    stands."""
    deadline = time.time() + timeout
    while True:
        if comm.are_peers_pending():
            comm.update_topology()
        if comm.global_world_size == num_shards * comm.largest_peer_group:
            return
        if ever_full and comm.num_peer_groups < num_shards and (
                grid is None or bool(np.all(grid.seq >= step))):
            return  # a group finished its run and left — don't wait for it
        if time.time() > deadline:
            raise TimeoutError("grid never filled (a column is incomplete)")
        time.sleep(0.05)


def sync_with_retry(comm, state) -> None:
    """sync_shared_state with the reference's churn-retry loop around it
    (sync_diloco_fsdp.py retries the sync until the survivor group elects)."""
    from pccl_tpu.comm import PcclError

    while True:
        try:
            comm.sync_shared_state(state)
            return
        except PcclError:
            time.sleep(0.1)
            if comm.are_peers_pending():
                comm.update_topology()


def ring_average_shard(comm, shard: np.ndarray) -> None:
    """In-place AVG of `shard` across the cell's peer group, retrying over
    the survivor world on churn (reference all_reduce_multiple_with_retry
    pattern). Alone in the group → own value is the average."""
    from pccl_tpu.comm import PcclError, ReduceOp, TooFewPeersError

    try:
        comm.all_reduce(shard, op=ReduceOp.AVG)
        return
    except TooFewPeersError:
        return
    except PcclError:
        pass
    while True:
        try:
            comm.update_topology()
            comm.all_reduce_multiple_with_retry([shard], op=ReduceOp.AVG)
            return
        except TooFewPeersError:
            return
        except PcclError:
            time.sleep(0.1)


def main() -> int:
    ap = argparse.ArgumentParser()
    common.add_comm_args(ap)
    ap.add_argument("--num-shards", type=int, default=2,
                    help="outer-state shards = peer groups = grid rows; "
                         "--peer-group selects this cell's shard")
    ap.add_argument("--min-replicas", type=int, default=1,
                    help="wait until this cell's group has this many peers")
    ap.add_argument("--grid-file", default=None,
                    help="per-host mapped shard-exchange file "
                         "(default /dev/shm keyed by master port)")
    ap.add_argument("--outer-steps", type=int, default=8,
                    help="terminal shared-state revision (joiners resume "
                         "from the synced revision and run the remainder)")
    ap.add_argument("--inner-steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--block", type=int, default=64)
    ap.add_argument("--inner-lr", type=float, default=1e-3)
    ap.add_argument("--outer-lr", type=float, default=0.7)
    ap.add_argument("--outer-momentum", type=float, default=0.9)
    ap.add_argument("--seed", type=int, default=0)
    common.add_data_args(ap)
    common.add_model_args(ap)
    args = ap.parse_args()
    if args.solo:
        raise SystemExit("the grid example needs a comm (no --solo)")
    g = args.peer_group
    assert 0 <= g < args.num_shards, "--peer-group must be < --num-shards"

    common.force_cpu_if_requested()
    import jax

    from pccl_tpu.comm import SharedState, TensorInfo
    from pccl_tpu.parallel import codec as codec_lib
    from pccl_tpu.parallel import mesh as mesh_lib, train as train_lib

    # intra-cell sharding: the model is tensor-parallel over the local
    # mesh. Built BEFORE connect(): once admitted, this cell owes topology
    # votes to the group, and a half-minute of XLA compilation between
    # admission and the first vote would stall everyone's update_topology.
    mesh = mesh_lib.make_mesh(jax.devices(), ("dp", "tp"))
    cfg = common.model_config(args, char_level=args.data == "text")
    params, tx, opt_state = train_lib.make_train_state(
        jax.random.PRNGKey(args.seed), cfg, mesh, lr=args.inner_lr)
    step_fn = train_lib.build_train_step(cfg, tx, mesh)
    data_sharding = mesh_lib.batch_sharding(mesh)
    shardings = codec_lib.leaf_shardings(params)
    codec = codec_lib.build_codec(params)

    # min-world gates the cell's OWN group; the grid gate below handles
    # the cross-group (column-completeness) condition
    args.min_world = max(args.min_world, args.min_replicas)
    comm = common.connect(args)

    path = args.grid_file or f"/dev/shm/pcclt_grid_{args.master_port}.bin"
    grid = GridFile(path, args.num_shards, codec.count)
    lo, hi = grid.bounds[g], grid.bounds[g + 1]

    # this cell's slice of the outer state: its shard of the flat params
    # (identical across cells at init — same seed) + the shard's momentum
    outer_full = np.asarray(jax.device_get(codec.flat(params)),
                            dtype=np.float32)
    own_shard = np.array(outer_full[lo:hi])
    momentum = np.zeros(hi - lo, dtype=np.float32)
    step_arr = np.zeros(1, dtype=np.uint64)
    lr, mu = args.outer_lr, args.outer_momentum

    next_batch = common.make_batch_fn(args, cfg.vocab_size)
    first_loss = last_loss = None
    step = 0
    ever_full = False
    while step < args.outer_steps:
        wait_grid_full(comm, args.num_shards, ever_full, grid=grid, step=step)
        ever_full = True

        # shard-g shared state: joiners adopt the group's shard + revision
        step_arr[0] = step
        st = SharedState([
            TensorInfo.from_numpy("grid.outer_shard", own_shard),
            TensorInfo.from_numpy("grid.outer_momentum", momentum),
            TensorInfo.from_numpy("grid.step", step_arr),
        ], revision=step)
        sync_with_retry(comm, st)
        step = int(step_arr[0])
        if step >= args.outer_steps:
            grid.publish(g, step, own_shard)  # column-mates may still wait
            break

        # column exchange: publish shard g, assemble the full outer vector
        grid.publish(g, step, own_shard)
        grid.wait_all(step)
        outer_full = grid.read_full()
        params = codec_lib.restore_shardings(
            codec.unflat(jax.device_put(outer_full)), shardings)

        # inner phase: H jitted SPMD steps on the local tensor-parallel mesh
        import jax.numpy as jnp
        for _ in range(args.inner_steps):
            tok, tgt = next_batch()
            tok = jax.device_put(jnp.asarray(tok), data_sharding)
            tgt = jax.device_put(jnp.asarray(tgt), data_sharding)
            params, opt_state, loss = step_fn(params, opt_state, tok, tgt)

        # outer step, shard g only: ring-average the pseudo-gradient across
        # the group, then deterministic Nesterov SGD on the shard
        inner_flat = np.asarray(jax.device_get(codec.flat(params)),
                                dtype=np.float32)
        delta = outer_full[lo:hi] - inner_flat[lo:hi]
        ring_average_shard(comm, delta)
        momentum = mu * momentum + delta
        own_shard = outer_full[lo:hi] - lr * (delta + mu * momentum)
        step += 1
        grid.publish(g, step, own_shard)

        loss = float(loss)
        first_loss = first_loss if first_loss is not None else loss
        last_loss = loss
        print(f"outer {step} loss {loss:.4f} "
              f"grid {args.num_shards}x{comm.largest_peer_group} "
              f"global {comm.global_world_size} shard {g} "
              f"[{lo}:{hi}]", flush=True)

    code = common.report_final(first_loss, last_loss, comm)
    grid.remove()  # file is scoped to this run
    return code


if __name__ == "__main__":
    sys.exit(main())
